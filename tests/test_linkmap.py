"""Link observatory (stencil_tpu/observatory/linkmap.py): the modeled
traffic matrix against the existing byte counters, link/direction
classification, the measured topology fingerprint and its tuner
consumption, the per-link attribution gauges, the placement-quality
QAP gate, and the observatory CLI surfaces."""

import json

import numpy as np
import pytest

from stencil_tpu.analysis.costmodel import (LinkCoefficients,
                                            migration_wire_bytes_per_shard)
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.observatory.linkmap import (REGISTERED_MESHES,
                                             TrafficMatrix,
                                             allgather_traffic, classify,
                                             link_attribution_for,
                                             link_class_of,
                                             load_topology,
                                             measure_topology,
                                             mesh_distance_matrix,
                                             method_traffic,
                                             migration_traffic,
                                             pic_traffic,
                                             placement_quality,
                                             placement_report,
                                             render_heatmap,
                                             render_summary,
                                             save_topology, shard_slice,
                                             sweep_traffic,
                                             topology_coefficients,
                                             topology_fingerprint,
                                             topology_fingerprint_inputs,
                                             validate_topology)
from stencil_tpu.observatory.__main__ import main as observatory_cli
from stencil_tpu.parallel.exchange import exchanged_bytes_per_sweep
from stencil_tpu.tuning import FakeTimer, TuneGeometry, run_autotune
from stencil_tpu.tuning.plan import fingerprint_inputs


def _sweep_total(padded, radius, counts, elem):
    return sum(exchanged_bytes_per_sweep(padded, radius, counts,
                                         elem).values())


# ----------------------------------------------------------------------
# the modeled traffic matrix vs the existing byte counters
# ----------------------------------------------------------------------
class TestTrafficMatrix:
    @pytest.mark.parametrize("r", [1, 2, 3])
    def test_sweep_rows_match_exchange_counter(self, r):
        radius = Radius.constant(r)
        counts = Dim3(2, 2, 2)
        padded = (8 + 2 * r, 8 + 2 * r, 8 + 2 * r)
        tm = sweep_traffic(padded, radius, counts, (4,))
        assert tm.uniform_per_shard() == _sweep_total(padded, radius,
                                                      counts, 4)
        # whole-matrix total = n_shards x per-shard
        assert tm.total() == 8 * tm.uniform_per_shard()
        w = tm.matrix()
        assert np.all(np.diag(w) == 0)

    def test_asymmetric_radius_and_flat_axis(self):
        radius = Radius.constant(0)
        radius.set_dir((1, 0, 0), 2)
        radius.set_dir((-1, 0, 0), 1)
        radius.set_dir((0, 1, 0), 1)
        counts = Dim3(2, 2, 1)  # z flat: no z traffic ever
        padded = (8, 11, 11)
        tm = sweep_traffic(padded, radius, counts, (4,))
        assert tm.uniform_per_shard() == _sweep_total(padded, radius,
                                                      counts, 4)
        assert tm.axis_bytes()["z"] == 0

    def test_multi_quantity_elem_sizes(self):
        radius = Radius.constant(1)
        counts = Dim3(2, 2, 2)
        padded = (10, 10, 10)
        tm = sweep_traffic(padded, radius, counts, (4, 2))
        want = (_sweep_total(padded, radius, counts, 4)
                + _sweep_total(padded, radius, counts, 2))
        assert tm.uniform_per_shard() == want

    def test_direction_class_decomposition_sums_exactly(self):
        radius = Radius.constant(2)
        counts = Dim3(2, 2, 2)
        tm = sweep_traffic((12, 12, 12), radius, counts, (4,))
        cls = tm.direction_class_bytes()
        assert sum(cls.values()) == tm.total()
        assert cls["corner"] > 0 and cls["edge"] > 0

    def test_face_only_slabs_have_no_edge_corner_share(self):
        tm = allgather_traffic((8, 8, 8), Radius.constant(1),
                               Dim3(2, 2, 2), (4,))
        cls = tm.direction_class_bytes()
        assert cls["edge"] == 0 and cls["corner"] == 0
        assert tm.uniform_per_shard() == _sweep_total(
            (8, 8, 8), Radius.constant(1), Dim3(2, 2, 2), 4)

    def test_migration_matches_costmodel(self):
        counts = Dim3(2, 2, 1)
        tm = migration_traffic(counts, 5, 8, 4)
        assert tm.uniform_per_shard() == migration_wire_bytes_per_shard(
            5, 8, counts, 4)
        assert tm.axis_bytes()["z"] == 0  # flat axis: local copy

    def test_method_traffic_deepens_like_the_cost_model(self):
        from stencil_tpu.analysis.costmodel import exchange_round_model

        geom = ((8, 8, 8), Radius.constant(1), Dim3(2, 2, 2))
        for s in (1, 2, 4):
            tm = method_traffic("PpermuteSlab", geom[0], geom[1],
                                geom[2], (4,), steps=s)
            _, nbytes = exchange_round_model("PpermuteSlab", geom[0],
                                             geom[1], geom[2], (4,), s)
            assert tm.uniform_per_shard() == nbytes

    def test_pic_traffic_is_adjoint_plus_exchange_plus_migration(self):
        counts = Dim3(2, 2, 2)
        radius = Radius.constant(2)
        tm = pic_traffic((8, 8, 8), radius, counts, 4, 7, 8)
        sweep = _sweep_total((12, 12, 12), radius, counts, 4)
        mig = migration_wire_bytes_per_shard(7, 8, counts, 4)
        assert tm.uniform_per_shard() == 2 * sweep + mig

    def test_merge_accumulates(self):
        counts = Dim3(2, 1, 1)
        a = migration_traffic(counts, 1, 1, 4)
        b = migration_traffic(counts, 1, 1, 4)
        assert a.merge(b).total() == 2 * a.total()

    def test_renderers_smoke(self):
        tm = sweep_traffic((10, 10, 10), Radius.constant(1),
                           Dim3(2, 2, 1), (4,))
        art = render_heatmap(tm)
        assert "traffic matrix" in art and "|" in art
        txt = render_summary(classify(tm))
        assert "link classes" in txt and "direction classes" in txt


# ----------------------------------------------------------------------
# link classification
# ----------------------------------------------------------------------
class TestClassification:
    def test_neighbors_are_one_hop_including_the_wrap_link(self):
        counts = Dim3(4, 1, 1)
        tm = sweep_traffic((10, 10, 10), Radius.constant(1), counts,
                           (4,))
        summary = classify(tm)
        # every edge (the 3->0 wrap included) is one torus hop
        assert set(summary.link_bytes) == {("x", "ici-hop1")}

    def test_dcn_axis_classifies_slice_crossing_edges(self):
        counts = Dim3(2, 2, 2)
        tm = sweep_traffic((10, 10, 10), Radius.constant(1), counts,
                           (4,))
        summary = classify(tm, dcn_axis=2, n_slices=2)
        # the z axis crosses slices (2 shards over 2 slices): ALL its
        # traffic is dcn; x/y stay on the intra-slice ici
        assert ("z", "dcn") in summary.link_bytes
        assert ("z", "ici-hop1") not in summary.link_bytes
        assert ("x", "ici-hop1") in summary.link_bytes
        ici = sum(b for (a, c), b in summary.link_bytes.items()
                  if c != "dcn")
        assert ici + summary.link_bytes[("z", "dcn")] \
            == summary.total_bytes

    def test_shard_slice_blocks_along_axis(self):
        counts = Dim3(1, 1, 4)
        assert [shard_slice(i, counts, 2, 2) for i in range(4)] \
            == [0, 0, 1, 1]

    def test_link_class_of_self(self):
        counts = Dim3(2, 1, 1)
        dist = mesh_distance_matrix(counts)
        assert link_class_of(0, 0, dist, counts) == "self"
        assert link_class_of(0, 1, dist, counts) == "ici-hop1"

    def test_rounds_per_step_scales_bytes(self):
        tm = sweep_traffic((12, 12, 12), Radius.constant(2),
                           Dim3(2, 1, 1), (4,))
        s2 = classify(tm, rounds_per_step=0.5)
        s1 = classify(tm)
        for k in s1.link_bytes:
            assert s2.link_bytes_per_step()[k] \
                == s1.link_bytes_per_step()[k] / 2

    def test_summary_record_shares_sum_to_one(self):
        tm = sweep_traffic((10, 10, 10), Radius.constant(1),
                           Dim3(2, 2, 2), (4,))
        rec = classify(tm).to_record()
        assert sum(v["share"] for v in rec["links"].values()) \
            == pytest.approx(1.0)
        assert sum(v["share"]
                   for v in rec["direction_classes"].values()) \
            == pytest.approx(1.0)


# ----------------------------------------------------------------------
# the measured topology fingerprint
# ----------------------------------------------------------------------
class TestTopologyFingerprint:
    def _timer(self):
        return FakeTimer(axis_coeffs={
            "x": LinkCoefficients(alpha_s=1e-5, beta_bytes_per_s=4e10),
            "y": LinkCoefficients(alpha_s=2e-5, beta_bytes_per_s=2e10),
            "z": LinkCoefficients(alpha_s=8e-5, beta_bytes_per_s=5e9),
        })

    def _inputs(self):
        return topology_fingerprint_inputs("cpu", 8, (2, 2, 2), 1)

    def test_measure_recovers_per_axis_coefficients_exactly(self):
        rec = measure_topology(self._timer(), (2, 2, 2),
                               self._inputs(), dcn_axis=2)
        assert validate_topology(rec) == []
        links = topology_coefficients(rec)
        # the linear alpha-beta fit recovers the fake fabric exactly
        assert links["x"].alpha_s == pytest.approx(1e-5)
        assert links["y"].beta_bytes_per_s == pytest.approx(2e10)
        assert links["z"].alpha_s == pytest.approx(8e-5)
        # the slice-blocked axis doubles as the dcn link class
        assert links["dcn"].alpha_s == links["z"].alpha_s
        # raw samples ride the record for hardware-free refits
        assert len(rec["links"]["x"]["samples"]) == 3

    def test_flat_axes_are_not_fingerprinted(self):
        rec = measure_topology(self._timer(), (1, 2, 1),
                               topology_fingerprint_inputs(
                                   "cpu", 2, (1, 2, 1), 1))
        assert set(rec["links"]) == {"y"}

    def test_save_load_roundtrip_fingerprint_keyed(self, tmp_path):
        path = tmp_path / "topology.json"
        rec = measure_topology(self._timer(), (2, 2, 2), self._inputs())
        save_topology(rec, path)
        back = load_topology(rec["fingerprint"], path)
        assert back == rec
        # a different fabric's fingerprint misses
        other = topology_fingerprint(
            topology_fingerprint_inputs("tpu", 16, (4, 2, 2), 2))
        assert load_topology(other, path) is None
        # two fabrics coexist in one artifact
        rec2 = measure_topology(
            self._timer(), (4, 2, 1),
            topology_fingerprint_inputs("cpu", 8, (4, 2, 1), 1))
        save_topology(rec2, path)
        assert load_topology(rec["fingerprint"], path) == rec
        assert load_topology(rec2["fingerprint"], path) == rec2

    def test_corrupt_artifact_is_a_miss_not_fatal(self, tmp_path):
        path = tmp_path / "topology.json"
        path.write_text("{torn")
        assert load_topology("ab" * 16, path) is None
        # and save_topology rewrites over the corpse
        rec = measure_topology(self._timer(), (2, 2, 2), self._inputs())
        save_topology(rec, path)
        assert load_topology(rec["fingerprint"], path) == rec

    def test_concurrent_writers_drop_no_fingerprints(self, tmp_path):
        """Two tenants fingerprinting different fabrics concurrently:
        both records must land (the read-merge-write runs under the
        plan cache's writer lock — an unlocked publish would let the
        last rename win and silently drop the other measurement)."""
        import threading

        path = tmp_path / "topology.json"
        recs = [measure_topology(
            self._timer(), (2, 2, 2),
            topology_fingerprint_inputs("cpu", 8, (2, 2, 2), i + 1))
            for i in range(6)]
        threads = [threading.Thread(target=save_topology,
                                    args=(r, path)) for r in recs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for r in recs:
            assert load_topology(r["fingerprint"], path) == r

    def test_save_rejects_invalid_record(self, tmp_path):
        with pytest.raises(ValueError, match="invalid topology"):
            save_topology({"schema": 99}, tmp_path / "t.json")

    def test_tuner_consumes_fingerprint_instead_of_pingpong(
            self, tmp_path):
        """run_autotune(topology=...) performs ZERO pingpong
        calibrations — the artifact's per-axis links replace the two
        global alpha-betas, and the plan records them."""
        calls = {"pingpong": 0, "axis": 0}

        class SpyTimer(FakeTimer):
            def pingpong(self, nbytes):
                calls["pingpong"] += 1
                return super().pingpong(nbytes)

            def pingpong_axis(self, name, nbytes):
                calls["axis"] += 1
                return super().pingpong_axis(name, nbytes)

        rec = measure_topology(self._timer(), (2, 2, 2), self._inputs())
        geom = TuneGeometry(shard_interior_zyx=(8, 8, 8),
                            min_interior_zyx=(8, 8, 8),
                            radius=Radius.constant(1),
                            counts=Dim3(2, 2, 2), elem_sizes=(4,))
        inputs = fingerprint_inputs("cpu", 8, (2, 2, 2), (16, 16, 16),
                                    Radius.constant(1), {"q": "float32"},
                                    "PERIODIC")
        plan = run_autotune(geom, inputs, SpyTimer(),
                            read_cache=False, write_cache=False,
                            topology=rec)
        assert calls["pingpong"] == 0 and calls["axis"] == 0
        assert set(plan.coefficients) == {"x", "y", "z"}
        assert plan.coefficients["z"]["alpha_s"] \
            == pytest.approx(8e-5)
        # ranking priced at the bottleneck link (z: slowest)
        slab1 = plan.costs["PpermuteSlab[s=1]"]["predicted_s"]
        from stencil_tpu.analysis.costmodel import \
            configured_step_seconds
        want = configured_step_seconds(
            "PpermuteSlab", (8, 8, 8), Radius.constant(1),
            Dim3(2, 2, 2), (4,), 1,
            LinkCoefficients(alpha_s=8e-5, beta_bytes_per_s=5e9))
        assert slab1 == pytest.approx(want)

    def test_autotune_domain_measures_then_reuses(self, tmp_path,
                                                  monkeypatch):
        """autotune_domain(topology_path=...): the first tune measures
        the per-axis sweeps and persists the artifact; a fingerprint-
        identical second tune consumes it with zero axis pingpongs."""
        import numpy as np

        from stencil_tpu.distributed import DistributedDomain
        from stencil_tpu.tuning import autotune_domain

        calls = {"axis": 0}

        class SpyTimer(FakeTimer):
            def pingpong_axis(self, name, nbytes):
                calls["axis"] += 1
                return super().pingpong_axis(name, nbytes)

        topo = tmp_path / "topology.json"
        cache = tmp_path / "plans.json"

        def domain():
            dd = DistributedDomain(16, 16, 16)
            dd.set_mesh_shape((2, 2, 2))
            dd.set_radius(1)
            dd.add_data("q", np.float32)
            return dd

        plan1 = autotune_domain(domain(), timer=SpyTimer(),
                                cache_path=cache, topology_path=topo)
        assert calls["axis"] == 3 * 3  # 3 sizes x 3 active axes
        assert topo.exists()
        assert set(plan1.coefficients) == {"x", "y", "z"}
        # second process: plan-cache hit aside (force re-tune), the
        # topology artifact supplies the links — no more axis sweeps
        plan2 = autotune_domain(domain(), timer=SpyTimer(),
                                cache_path=cache, topology_path=topo,
                                force=True)
        assert calls["axis"] == 3 * 3
        assert plan2.coefficients == plan1.coefficients


# ----------------------------------------------------------------------
# per-link attribution (gauges + domain adapter)
# ----------------------------------------------------------------------
class TestLinkAttribution:
    def test_attributor_exports_link_gauges(self):
        from stencil_tpu.observatory import (
            METRIC_LINK_BYTES_PER_STEP, METRIC_LINK_UTILIZATION,
            PerfAttributor)
        from stencil_tpu.telemetry import MetricsRegistry

        reg = MetricsRegistry()
        att = PerfAttributor(
            "test", "PpermuteSlab", 1, model_step_seconds=1e-3,
            model_bytes_per_step=3000.0, registry=reg,
            link_bytes_per_step={("x", "ici-hop1"): 2000.0,
                                 ("z", "dcn"): 1000.0},
            link_peak_bytes_per_s={"x": 4e6, "z": 1e6})
        att.observe(1, 1e-3)  # measured == modeled
        b = reg.get(METRIC_LINK_BYTES_PER_STEP)
        u = reg.get(METRIC_LINK_UTILIZATION)
        assert b.value(axis="x", link_class="ici-hop1") == 2000.0
        assert b.value(axis="z", link_class="dcn") == 1000.0
        # 2000 B / 1e-3 s = 2e6 B/s over a 4e6 peak = 0.5
        assert u.value(axis="x", link_class="ici-hop1") \
            == pytest.approx(0.5)
        assert u.value(axis="z", link_class="dcn") \
            == pytest.approx(1.0)
        att.reset()
        assert b.value(axis="x", link_class="ici-hop1") == 0.0
        assert u.value(axis="z", link_class="dcn") == 0.0

    def test_link_attribution_for_realized_domain(self):
        import numpy as np

        from stencil_tpu.distributed import DistributedDomain

        dd = DistributedDomain(16, 16, 16)
        dd.set_mesh_shape((2, 2, 2))
        dd.set_radius(1)
        dd.add_data("q", np.float32)
        dd.realize()
        link = link_attribution_for(dd)
        assert link is not None
        total = sum(link["bytes_per_step"].values())
        # whole-mesh B/step — the same scope as the attributor's
        # model_bytes_per_step (exchange_bytes_amortized_per_step)
        assert total == pytest.approx(
            dd.exchange_bytes_amortized_per_step())
        assert set(link["peak_bytes_per_s"]) == {"x", "y", "z"}
        assert link["summary"]["links"]

    def test_link_attribution_unsharded_domain_is_none(self):
        import jax
        import numpy as np

        from stencil_tpu.distributed import DistributedDomain

        dd = DistributedDomain(8, 8, 8, devices=jax.devices()[:1])
        dd.set_mesh_shape((1, 1, 1))
        dd.set_radius(1)
        dd.add_data("q", np.float32)
        dd.realize()
        assert link_attribution_for(dd) is None

    def test_resilient_driver_exports_link_gauges(self, tmp_path):
        """The driver wiring end-to-end: a resilient run on a sharded
        domain exports nonzero per-link bytes and utilization through
        the process registry, and clears nothing it did not own."""
        import numpy as np

        from stencil_tpu.models.jacobi import Jacobi3D
        from stencil_tpu.observatory import (
            METRIC_LINK_BYTES_PER_STEP, METRIC_LINK_UTILIZATION)
        from stencil_tpu.resilience import ResiliencePolicy
        from stencil_tpu.telemetry import get_registry

        j = Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2),
                     dtype=np.float32, kernel="xla")
        j.init()
        j.run_resilient(4, policy=ResiliencePolicy(check_every=2),
                        ckpt_dir=str(tmp_path / "ckpt"))
        reg = get_registry()
        b = reg.get(METRIC_LINK_BYTES_PER_STEP)
        u = reg.get(METRIC_LINK_UTILIZATION)
        got = b.value(axis="x", link_class="ici-hop1")
        assert got > 0
        assert u.value(axis="x", link_class="ici-hop1") > 0
        # the modeled per-link total matches the domain's whole-mesh
        # amortized byte model — one byte source, three surfaces
        total = sum(b.value(axis=a, link_class="ici-hop1")
                    for a in ("x", "y", "z"))
        assert total == pytest.approx(
            j.dd.exchange_bytes_amortized_per_step())

    def test_flight_recorder_carries_linkmap(self, tmp_path):
        from stencil_tpu.observatory import FlightRecorder, validate_dump

        tm = sweep_traffic((10, 10, 10), Radius.constant(1),
                           Dim3(2, 2, 2), (4,))
        rec = FlightRecorder(run_id="lmtest")
        rec.set_linkmap(classify(tm).to_record())
        path = rec.dump(tmp_path, "unit_test")
        payload = json.loads(open(path).read())
        assert validate_dump(payload) == []
        assert payload["linkmap"]["links"]
        # a bogus linkmap payload is flagged by the validator
        payload["linkmap"] = {"nope": 1}
        assert any("linkmap" in p for p in validate_dump(payload))


# ----------------------------------------------------------------------
# placement-quality scoring
# ----------------------------------------------------------------------
class TestPlacementQuality:
    def test_registered_meshes_all_gate(self):
        report = placement_report()
        assert report["ok"] is True
        assert len(report["meshes"]) == len(REGISTERED_MESHES)
        for row in report["meshes"]:
            assert row["qap_cost"] <= row["trivial_cost"] * (1 + 1e-12)
            assert sorted(row["assignment"]) \
                == list(range(row["subdomains"]))

    def test_qap_beats_trivial_on_a_scrambled_fabric(self):
        """On a fabric whose fast links do NOT follow the lattice
        order, the QAP must strictly beat trivial placement — the
        signal the reference's NodeAware strategy exists for."""

        class Dev:
            def __init__(self, coords):
                self.coords = coords

        counts = Dim3(2, 2, 1)
        # devices enumerated in an order that scrambles the torus
        devs = [Dev((0, 0, 0)), Dev((1, 1, 0)), Dev((1, 0, 0)),
                Dev((0, 1, 0))]
        row = placement_quality(counts, Radius.constant(1), (4,),
                                devices=devs)
        assert row["ok"]
        assert row["qap_cost"] < row["trivial_cost"]

    def test_dcn_mesh_distance_adds_slice_penalty(self):
        counts = Dim3(1, 1, 4)
        flat = mesh_distance_matrix(counts)
        tiered = mesh_distance_matrix(counts, dcn_axis=2, n_slices=2)
        # shards 1-2 straddle the slice boundary: penalized
        assert tiered[1, 2] > flat[1, 2]
        assert tiered[0, 1] == flat[0, 1]

    def test_deployed_placement_scored_and_gated(self):
        """Every row scores the assignment make_placement(mode="auto")
        actually DEPLOYS, next to the hill-climb upper bound; the gate
        holds BOTH to the trivial cost — a deployment regression (the
        orchestrator shipping a worse order than it scored) fails the
        report, not just the solver."""
        for row in placement_report()["meshes"]:
            assert row["placement_mode"] == "auto"
            assert sorted(row["deployed_assignment"]) \
                == list(range(row["subdomains"]))
            assert row["deployed_cost"] <= \
                row["trivial_cost"] * (1 + 1e-12)
        # a forced-trivial scoring run reports identity deployment
        row = placement_quality(Dim3(2, 2, 2), Radius.constant(1),
                                (4,), dcn_axis=2, n_slices=2,
                                mode="trivial")
        assert row["placement_mode"] == "trivial"
        assert row["deployed_assignment"] == list(range(8))

    def test_placement_payload_repricing(self):
        """LinkmapSpec.placement: a target's claimed assignment is
        re-priced under the QAP objective on its own declared fabric —
        identity passes, a seam-crossing transpose is flagged (the
        bad_placement fixture's failure mode, unit-level)."""
        from stencil_tpu.observatory.linkmap import (
            _check_placement_payload)

        payload = {"counts": (2, 2, 2), "grid": (16, 16, 32),
                   "assignment": list(range(8)),
                   "radius": Radius.constant(1), "elem_sizes": (4,),
                   "dcn_axis": 2, "n_slices": 2}
        metrics = {}
        assert _check_placement_payload("t", payload, metrics) == []
        assert metrics["placement_claimed_cost"] == \
            metrics["placement_trivial_cost"]
        perm = [0] * 8
        for z in range(2):
            for y in range(2):
                for x in range(2):
                    # transpose x/z: the fat x faces cross the DCN seam
                    perm[x + 2 * y + 4 * z] = z + 2 * y + 4 * x
        bad, m2 = dict(payload, assignment=perm), {}
        findings = _check_placement_payload("t", bad, m2)
        assert len(findings) == 1
        assert "never lose to the identity assignment" \
            in findings[0].message
        assert m2["placement_claimed_cost"] > \
            m2["placement_trivial_cost"]
        # a non-permutation "assignment" is flagged outright
        junk, m3 = dict(payload, assignment=[0] * 8), {}
        assert _check_placement_payload("t", junk, m3)


# ----------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------
class TestCli:
    def test_linkmap_renders_and_writes_json(self, tmp_path, capsys):
        out = tmp_path / "linkmap.json"
        rc = observatory_cli(["linkmap", "--mesh", "2,2,2",
                              "--json", str(out)])
        assert rc == 0
        text = capsys.readouterr().out
        assert "traffic matrix" in text and "link classes" in text
        data = json.loads(out.read_text())
        assert data["kind"] == "linkmap"
        assert np.asarray(data["matrix"]).shape == (8, 8)

    def test_linkmap_placement_report_gates(self, tmp_path, capsys):
        out = tmp_path / "linkmap.json"
        rc = observatory_cli(["linkmap", "--placement-report",
                              "--json", str(out)])
        assert rc == 0
        assert "placement gate OK" in capsys.readouterr().out
        data = json.loads(out.read_text())
        assert data["placement_report"]["ok"] is True

    def test_linkmap_placement_report_fails_loudly(self, monkeypatch,
                                                   capsys):
        """A (hypothetical) QAP solver that returns a WORSE placement
        than trivial must fail the gate with nonzero exit."""
        import stencil_tpu.observatory.linkmap as lm

        real = lm.placement_quality

        def sabotaged(*a, **kw):
            row = real(*a, **kw)
            row["qap_cost"] = row["trivial_cost"] * 2 + 1
            row["ok"] = False
            return row

        monkeypatch.setattr(lm, "placement_quality", sabotaged)
        rc = observatory_cli(["linkmap", "--placement-report"])
        assert rc == 1
        assert "placement gate FAILED" in capsys.readouterr().out

    def test_gate_empty_ledger_notes_no_trajectory(self, tmp_path,
                                                   capsys):
        led = tmp_path / "empty.jsonl"
        led.write_text("")
        out = tmp_path / "gate.json"
        rc = observatory_cli(["gate", str(led), "--json", str(out)])
        assert rc == 0
        assert "no measured trajectory" in capsys.readouterr().out
        data = json.loads(out.read_text())
        assert data["groups_checked"] == 0 and data["records"] == 0

    def test_gate_min_groups_floor_fails_vacuous_pass(self, tmp_path):
        led = tmp_path / "empty.jsonl"
        led.write_text("")
        assert observatory_cli(["gate", str(led),
                                "--min-groups", "1"]) == 1
        # a healthy ledger with one comparable group satisfies floor 1
        from stencil_tpu.observatory.ledger import (append_record,
                                                    make_record)
        led2 = tmp_path / "ok.jsonl"
        for sps in (10.0, 11.0):
            append_record(led2, make_record(
                "bench", {"k": 1}, {"steps_per_s": sps}))
        out = tmp_path / "gate.json"
        assert observatory_cli(["gate", str(led2), "--min-groups", "1",
                                "--json", str(out)]) == 0
        assert json.loads(out.read_text())["groups_checked"] == 1

    def test_diff_groupless_ledger_notes_no_trajectory(self, tmp_path,
                                                       capsys):
        from stencil_tpu.observatory.ledger import (append_record,
                                                    make_record)
        led = tmp_path / "single.jsonl"
        append_record(led, make_record("bench", {"k": 1},
                                       {"steps_per_s": 10.0}))
        rc = observatory_cli(["diff", str(led)])
        assert rc == 0
        assert "no measured trajectory" in capsys.readouterr().out
