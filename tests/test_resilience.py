"""Resilient run loop: sentinels, fault injection, rollback recovery.

Every injected fault class (NaN step, corrupted halo, corrupt
checkpoint, transient save IOError, SIGTERM preemption) has a test
proving the driver recovers and the final state matches the fault-free
run — the ISSUE 5 acceptance contract.
"""

import os
import signal
import subprocess
import sys
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

from stencil_tpu.models.jacobi import Jacobi3D
from stencil_tpu.resilience import (CheckpointCorruption, FaultPlan,
                                    HaloCorruption, HealthSentinel,
                                    NaNInjection, Preemption,
                                    ResilienceError, ResiliencePolicy,
                                    StepConfig, TransientSaveFailure,
                                    degradation_ladder)

N = 16
STEPS = 12


def make_jacobi(**kw):
    j = Jacobi3D(N, N, N, mesh_shape=(2, 2, 2), dtype=np.float32, **kw)
    j.init()
    return j


def fast_policy(**kw):
    kw.setdefault("check_every", 1)
    kw.setdefault("ckpt_every", 4)
    kw.setdefault("base_delay", 0.0)
    kw.setdefault("sleep", lambda s: None)
    return ResiliencePolicy(**kw)


@pytest.fixture(scope="module")
def clean_final():
    j = make_jacobi()
    j.run(STEPS)
    return j.temperature()


# ----------------------------------------------------------------------
# health sentinel units
# ----------------------------------------------------------------------
def test_sentinel_clean_state_never_trips():
    j = make_jacobi()
    s = HealthSentinel(j.dd)
    for step in (1, 2, 3):
        s.probe(j.dd.curr, step)
    results = s.poll(block=True)
    assert len(results) == 3
    assert not any(r.tripped for r in results)
    assert s.tripped is None
    # stats are real: jacobi init is the 0.5 mean field
    assert results[0].max_abs["temp"] == pytest.approx(0.5)
    assert results[0].nonfinite["temp"] == 0


def test_sentinel_detects_nonfinite():
    j = make_jacobi()
    j.dd.curr["temp"] = j.dd.curr["temp"].at[3, 3, 3].set(float("nan"))
    s = HealthSentinel(j.dd)
    s.probe(j.dd.curr, 5)
    (r,) = s.poll(block=True)
    assert r.tripped and "non-finite" in r.reason
    assert r.nonfinite["temp"] >= 1
    assert s.tripped is r
    s.reset()
    assert s.tripped is None


def test_sentinel_detects_halo_corruption():
    """The probe reads PADDED fields: a poisoned halo cell trips it
    even though the next exchange would overwrite it."""
    j = make_jacobi()
    s = HealthSentinel(j.dd)
    # (0,0,0) is a pad cell of shard 0 (alloc radius 1 on all sides)
    j.dd.curr["temp"] = j.dd.curr["temp"].at[0, 0, 0].set(float("inf"))
    s.probe(j.dd.curr, 1)
    (r,) = s.poll(block=True)
    assert r.tripped and r.nonfinite["temp"] >= 1


def test_sentinel_growth_window_trips():
    j = make_jacobi()
    s = HealthSentinel(j.dd, window=4, growth_factor=10.0)
    base = j.dd.curr["temp"]
    s.probe({"temp": base}, 1)          # max_abs 0.5 -> history
    assert not any(r.tripped for r in s.poll(block=True))
    s.probe({"temp": base * 100.0}, 2)  # x100 > factor 10 -> trip
    (r,) = s.poll(block=True)
    assert r.tripped and "grew" in r.reason


def test_sentinel_async_poll_then_drain():
    j = make_jacobi()
    s = HealthSentinel(j.dd)
    s.probe(j.dd.curr, 1)
    s.probe(j.dd.curr, 2)
    got = s.poll()              # non-blocking: harvest whatever is done
    got += s.poll(block=True)   # drain the rest
    assert [r.step for r in got] == [1, 2]


# ----------------------------------------------------------------------
# fault class -> recover -> fault-free equivalence
# ----------------------------------------------------------------------
def test_nan_injection_rollback_equivalence(tmp_path, clean_final):
    j = make_jacobi()
    plan = FaultPlan(nans=[NaNInjection(step=7)])
    rep = j.run_resilient(STEPS, policy=fast_policy(),
                          ckpt_dir=str(tmp_path), faults=plan)
    assert rep.steps == STEPS
    assert rep.rollbacks == 1
    assert not rep.preempted
    kinds = [e["event"] for e in rep.events]
    assert "fault_nan" in kinds and "sentinel_tripped" in kinds \
        and "restored" in kinds
    np.testing.assert_array_equal(j.temperature(), clean_final)


def test_halo_corruption_rollback_equivalence(tmp_path, clean_final):
    j = make_jacobi()
    plan = FaultPlan(halos=[HaloCorruption(step=6, shard=(1, 0, 1))])
    rep = j.run_resilient(STEPS, policy=fast_policy(),
                          ckpt_dir=str(tmp_path), faults=plan)
    assert rep.steps == STEPS and rep.rollbacks == 1
    np.testing.assert_array_equal(j.temperature(), clean_final)


def test_transient_save_failure_retried(tmp_path, clean_final):
    j = make_jacobi()
    plan = FaultPlan(save_failures=[TransientSaveFailure(step=4,
                                                         failures=2)])
    rep = j.run_resilient(STEPS, policy=fast_policy(),
                          ckpt_dir=str(tmp_path), faults=plan)
    assert rep.steps == STEPS
    assert rep.save_retries == 2
    assert rep.rollbacks == 0
    np.testing.assert_array_equal(j.temperature(), clean_final)


def test_persistent_save_failure_raises(tmp_path):
    j = make_jacobi()
    plan = FaultPlan(save_failures=[TransientSaveFailure(step=4,
                                                         failures=99)])
    with pytest.raises(OSError, match="injected"):
        j.run_resilient(STEPS, policy=fast_policy(save_attempts=3),
                        ckpt_dir=str(tmp_path), faults=plan)


def test_corrupt_checkpoint_falls_back_during_recovery(tmp_path,
                                                       clean_final):
    """Checkpoint 4 is corrupted on disk after it lands; the NaN at
    step 6 forces a rollback, which must skip the corrupt step and
    restore the older anchor instead of dying."""
    j = make_jacobi()
    plan = FaultPlan(
        nans=[NaNInjection(step=6)],
        ckpt_corruptions=[CheckpointCorruption(step=4,
                                               mode="truncate")])
    rep = j.run_resilient(STEPS, policy=fast_policy(),
                          ckpt_dir=str(tmp_path), faults=plan)
    assert rep.steps == STEPS and rep.rollbacks == 1
    restored = [e for e in rep.events if e["event"] == "restored"]
    assert restored[0]["step"] == 0  # NOT the corrupt step 4
    np.testing.assert_array_equal(j.temperature(), clean_final)


def test_watchdog_mode_without_ckpt_dir_raises():
    j = make_jacobi()
    plan = FaultPlan(nans=[NaNInjection(step=3)])
    with pytest.raises(ResilienceError, match="nothing to roll back"):
        j.run_resilient(STEPS, policy=fast_policy(), ckpt_dir=None,
                        faults=plan)


def test_faults_target_live_interior_resident_fields():
    """On the interior-resident fast paths the live state is NOT
    dd.curr: state faults must hit the field dict the driver passes
    (the one the sentinel probes), and halo corruption — which has no
    resident pads to poison — must no-op instead of corrupting the
    stale padded buffer."""
    from stencil_tpu.local_domain import zyx_shape

    j = make_jacobi()
    inner = {"temp": jnp.zeros(zyx_shape(j.dd.size), jnp.float32)}
    FaultPlan(nans=[NaNInjection(step=1)]).on_step(j.dd, 1, inner)
    assert int(np.isnan(np.asarray(inner["temp"])).sum()) == 1
    assert not np.isnan(np.asarray(j.dd.curr["temp"])).any()

    inner2 = {"temp": jnp.zeros(zyx_shape(j.dd.size), jnp.float32)}
    FaultPlan(halos=[HaloCorruption(step=1)]).on_step(j.dd, 1, inner2)
    assert not np.isnan(np.asarray(inner2["temp"])).any()  # no-op
    assert not np.isnan(np.asarray(j.dd.curr["temp"])).any()


# ----------------------------------------------------------------------
# degradation ladder
# ----------------------------------------------------------------------
def test_degradation_ladder_order():
    from stencil_tpu.parallel.methods import Method

    ladder = degradation_ladder(Method.PpermutePacked, 4,
                                runnable=lambda m: m != Method.PallasDMA)
    assert ladder == [
        StepConfig(Method.PpermutePacked, 2),
        StepConfig(Method.PpermutePacked, 1),
        StepConfig(Method.PpermuteSlab, 1),
        StepConfig(Method.AllGather, 1),
    ]
    # depth-1 slab start: straight down the method list
    ladder = degradation_ladder(Method.PpermuteSlab, 1,
                                runnable=lambda m: m != Method.PallasDMA)
    assert ladder == [StepConfig(Method.AllGather, 1)]


def test_repeat_failure_degrades_config(tmp_path, clean_final):
    """A fault that keeps firing past the retry budget walks the
    degradation ladder (exchange_every 4 -> 2); the rebuilt engine is
    numerically identical, so the run still matches fault-free."""
    j = make_jacobi(exchange_every=4)
    plan = FaultPlan(nans=[NaNInjection(step=3, repeat=2)])
    pol = fast_policy(max_retries=1)
    rep = j.run_resilient(STEPS, policy=pol, ckpt_dir=str(tmp_path),
                          faults=plan)
    assert rep.steps == STEPS
    assert rep.rollbacks == 2
    assert rep.degradations == ["PpermuteSlab[s=2]"]
    assert rep.final_config == "PpermuteSlab[s=2]"
    assert j.dd.exchange_every == 2  # the handle was rebuilt in place
    np.testing.assert_array_equal(j.temperature(), clean_final)


def test_independent_incidents_get_fresh_retry_budgets(tmp_path,
                                                       clean_final):
    """Two unrelated transient faults separated by a successful
    checkpoint must NOT accumulate toward degradation: a checkpoint
    resets the attempt counter (retries are bounded per incident)."""
    j = make_jacobi()
    plan = FaultPlan(nans=[NaNInjection(step=3),
                           NaNInjection(step=9)])
    pol = fast_policy(max_retries=1)
    rep = j.run_resilient(STEPS, policy=pol, ckpt_dir=str(tmp_path),
                          faults=plan)
    assert rep.steps == STEPS
    assert rep.rollbacks == 2
    assert rep.degradations == []  # neither incident exhausted alone
    np.testing.assert_array_equal(j.temperature(), clean_final)


def test_one_probe_per_step_at_checkpoint_boundaries(tmp_path,
                                                     monkeypatch):
    """Stepwise dispatch loop (fusion off): check_every=1 with
    ckpt_every=2 — boundary steps are probed by the blocking drain
    ONLY, never a duplicate async reduction."""
    from stencil_tpu.resilience import driver as drv

    calls = []

    class Counting(drv.HealthSentinel):
        def probe(self, fields, step):
            calls.append(step)
            super().probe(fields, step)

    monkeypatch.setattr(drv, "HealthSentinel", Counting)
    j = make_jacobi()
    j.run_resilient(4, policy=fast_policy(ckpt_every=2,
                                          fuse_segments=False),
                    ckpt_dir=str(tmp_path))
    assert calls == [1, 2, 3, 4]


def test_fused_loop_probes_ride_the_segment_trace(tmp_path,
                                                  monkeypatch):
    """Megastep mode (the default): every step's health arrives as a
    row of the fused segment's in-graph trace — zero standalone probe
    dispatches on the fault-free path, one observe per segment."""
    from stencil_tpu.resilience import driver as drv

    probes, traces = [], []

    class Counting(drv.HealthSentinel):
        def probe(self, fields, step):
            probes.append(step)
            super().probe(fields, step)

        def observe_segment(self, trace, steps):
            traces.append(tuple(steps))
            super().observe_segment(trace, steps)

    monkeypatch.setattr(drv, "HealthSentinel", Counting)
    j = make_jacobi()
    rep = j.run_resilient(4, policy=fast_policy(ckpt_every=2,
                                                check_every=2),
                          ckpt_dir=str(tmp_path))
    assert rep.steps == 4
    assert probes == []               # no per-step probe dispatches
    assert traces == [(1, 2), (3, 4)]  # per-step rows, 2 megasteps


def test_retries_and_ladder_exhausted_raises(tmp_path):
    j = make_jacobi()
    plan = FaultPlan(nans=[NaNInjection(step=3, repeat=99)])
    pol = fast_policy(max_retries=1, degrade=False)
    with pytest.raises(ResilienceError, match="retries exhausted"):
        j.run_resilient(STEPS, policy=pol, ckpt_dir=str(tmp_path),
                        faults=plan)


def test_infeasible_ladder_rung_skipped_not_fatal(tmp_path):
    """An uneven (+-1) partition supports only the ppermute methods:
    the AllGather rung's constructor rejection must be absorbed as
    'rung infeasible', ending in ResilienceError — never a raw
    NotImplementedError escaping mid-recovery."""
    j = Jacobi3D(17, 17, 17, mesh_shape=(2, 2, 2), dtype=np.float32)
    j.init()
    plan = FaultPlan(nans=[NaNInjection(step=2, repeat=99)])
    pol = fast_policy(max_retries=0)
    with pytest.raises(ResilienceError, match="no degradation"):
        j.run_resilient(STEPS, policy=pol, ckpt_dir=str(tmp_path),
                        faults=plan)


def test_degrade_preserves_dcn_tier(tmp_path):
    """A degradation rebuild must carry the DCN slice tiering (and
    placement strategy) into the new engine, not silently fall back to
    raw device order."""
    import jax

    devs = jax.devices()[:8]
    groups = [devs[:4], devs[4:]]
    j = Jacobi3D(N, N, N, mesh_shape=(2, 2, 2), dtype=np.float32,
                 dcn_axis="z", dcn_groups=groups, exchange_every=4)
    j.init()
    assert j.dd.dcn_axis == 2 and j.dd.n_slices == 2
    plan = FaultPlan(nans=[NaNInjection(step=3, repeat=2)])
    rep = j.run_resilient(STEPS, policy=fast_policy(max_retries=1),
                          ckpt_dir=str(tmp_path), faults=plan)
    assert rep.degradations == ["PpermuteSlab[s=2]"]
    assert j.dd.exchange_every == 2
    assert j.dd.dcn_axis == 2 and j.dd.n_slices == 2  # tier survived


def test_astaroth_resilient_with_accumulators(tmp_path):
    """The Astaroth entry point: RK accumulators ride the checkpoint
    as extras, and recovery from a mid-campaign NaN is bitwise-equal
    to the fault-free run."""
    from stencil_tpu.models.astaroth import Astaroth, MhdParams

    prm = MhdParams()
    steps = 4
    a = Astaroth(8, 8, 8, params=prm, mesh_shape=(2, 2, 2),
                 dtype=np.float64)
    a.init()
    for _ in range(steps):
        a.step()
    want = {q: a.field(q) for q in ("lnrho", "uux", "ss")}

    b = Astaroth(8, 8, 8, params=prm, mesh_shape=(2, 2, 2),
                 dtype=np.float64)
    b.init()
    plan = FaultPlan(nans=[NaNInjection(step=3, quantity="uux")])
    rep = b.run_resilient(steps, policy=fast_policy(ckpt_every=2),
                          ckpt_dir=str(tmp_path), faults=plan)
    assert rep.steps == steps and rep.rollbacks == 1
    for q in want:
        np.testing.assert_array_equal(b.field(q), want[q])


# ----------------------------------------------------------------------
# preemption (SIGTERM) and resume
# ----------------------------------------------------------------------
def test_preemption_writes_tagged_checkpoint_and_resumes(tmp_path,
                                                         clean_final):
    from stencil_tpu.utils.checkpoint import checkpoint_meta

    j = make_jacobi()
    plan = FaultPlan(preemptions=[Preemption(step=6)])
    rep = j.run_resilient(STEPS, policy=fast_policy(check_every=2),
                          ckpt_dir=str(tmp_path), faults=plan)
    assert rep.preempted and rep.steps == 6
    meta = checkpoint_meta(str(tmp_path))
    assert meta["preempted"] is True
    assert meta["completed_steps"] == 6
    # the driver restored the previous SIGTERM disposition on exit
    assert signal.getsignal(signal.SIGTERM) == signal.SIG_DFL

    k = make_jacobi()
    rep2 = k.run_resilient(STEPS, policy=fast_policy(check_every=2),
                           ckpt_dir=str(tmp_path))
    assert rep2.resumed_from == 6
    assert rep2.steps == STEPS and not rep2.preempted
    np.testing.assert_array_equal(k.temperature(), clean_final)


def test_preemption_never_persists_poisoned_state(tmp_path, clean_final):
    """SIGTERM landing right after a fault, before any probe was
    harvested: the preemption path must drain health first and SKIP
    the final checkpoint, leaving the older good step as the resume
    anchor — never a NaN-laden 'latest'."""
    j = make_jacobi()
    plan = FaultPlan(nans=[NaNInjection(step=5)],
                     preemptions=[Preemption(step=5)])
    # check_every huge: no probe would have caught the NaN before the
    # preempt branch runs — only its own blocking drain can
    rep = j.run_resilient(STEPS, policy=fast_policy(check_every=100),
                          ckpt_dir=str(tmp_path), faults=plan)
    assert rep.preempted and rep.steps == 5
    kinds = [e["event"] for e in rep.events]
    assert "preempt_checkpoint_skipped" in kinds
    from stencil_tpu.utils.checkpoint import all_steps
    assert max(all_steps(str(tmp_path))) == 4  # poisoned step 5 absent

    k = make_jacobi()
    rep2 = k.run_resilient(STEPS, policy=fast_policy(), ckpt_dir=str(tmp_path))
    assert rep2.resumed_from == 4 and rep2.steps == STEPS
    np.testing.assert_array_equal(k.temperature(), clean_final)


CHILD = Path(__file__).parent / "fixtures" / "resilience_child.py"


def test_preemption_subprocess_e2e(tmp_path, clean_final):
    """The full fleet contract in real processes: a run SIGTERMed
    mid-loop exits 0 having written the preempted checkpoint; a fresh
    process resumes from it and the final field is bitwise-equal to an
    uninterrupted run."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the child sets its own 8-device mesh
    ckpt = tmp_path / "ckpt"
    out = tmp_path / "final.npy"

    first = subprocess.run(
        [sys.executable, str(CHILD), "--ckpt-dir", str(ckpt),
         "--steps", str(STEPS), "--preempt-at", "6"],
        capture_output=True, text=True, env=env, timeout=300)
    assert first.returncode == 0, first.stderr
    assert "PREEMPTED steps=6" in first.stdout, first.stdout

    second = subprocess.run(
        [sys.executable, str(CHILD), "--ckpt-dir", str(ckpt),
         "--steps", str(STEPS), "--out", str(out)],
        capture_output=True, text=True, env=env, timeout=300)
    assert second.returncode == 0, second.stderr
    assert f"DONE steps={STEPS} resumed_from=6" in second.stdout, \
        second.stdout
    np.testing.assert_array_equal(np.load(out), clean_final)


# ----------------------------------------------------------------------
# checkpoint hardening (integrity + fallback + manager cache)
# ----------------------------------------------------------------------
def test_restore_domain_falls_back_past_corrupt_latest(tmp_path):
    from stencil_tpu.utils.checkpoint import restore_domain, save_domain

    j = make_jacobi()
    j.step()
    save_domain(j.dd, str(tmp_path), step=1)
    want = j.temperature()
    j.step()
    save_domain(j.dd, str(tmp_path), step=2)
    # corrupt the LATEST step on disk
    CheckpointCorruption(step=2, mode="truncate").fire(
        str(tmp_path), 2, np.random.default_rng(0), lambda *a, **k: None)
    k = make_jacobi()
    step, _ = restore_domain(k.dd, str(tmp_path))
    assert step == 1
    np.testing.assert_array_equal(k.temperature(), want)


def test_restore_domain_raises_when_no_step_restorable(tmp_path):
    from stencil_tpu.utils.checkpoint import (CorruptCheckpointError,
                                              restore_domain,
                                              save_domain)

    j = make_jacobi()
    save_domain(j.dd, str(tmp_path), step=1)
    CheckpointCorruption(step=1, mode="truncate").fire(
        str(tmp_path), 1, np.random.default_rng(0), lambda *a, **k: None)
    k = make_jacobi()
    with pytest.raises(CorruptCheckpointError, match="no restorable"):
        restore_domain(k.dd, str(tmp_path))


def test_array_digest_detects_tampering():
    from stencil_tpu.utils.checkpoint import array_digest, verify_digests

    a = jnp.arange(16.0, dtype=jnp.float32).reshape(4, 4)
    digests = {"a": array_digest(a)}
    assert verify_digests({"a": a}, digests) == []
    assert verify_digests({"a": a.at[2, 2].set(7.0)}, digests) == ["a"]
    # arrays without a recorded digest are skipped, not flagged
    assert verify_digests({"b": a}, digests) == []


def test_save_meta_records_integrity_digests(tmp_path):
    from stencil_tpu.utils.checkpoint import checkpoint_meta, save_domain

    j = make_jacobi()
    save_domain(j.dd, str(tmp_path), step=0)
    meta = checkpoint_meta(str(tmp_path), 0)
    assert set(meta["integrity"]) == {"temp"}
    assert len(meta["integrity"]["temp"]) == 64  # sha256 hex


def test_integrity_skipped_on_multihost(tmp_path, monkeypatch):
    """Digests need host-addressable arrays; on a multi-host run the
    save must skip them (with a warning) instead of dying on
    np.asarray of non-addressable shards — and restore must not flag
    their absence."""
    from stencil_tpu.utils import checkpoint as ckpt
    from stencil_tpu.utils.checkpoint import (checkpoint_meta,
                                              restore_domain,
                                              save_domain)

    j = make_jacobi()
    j.step()
    monkeypatch.setattr(ckpt, "_single_host", lambda: False)
    save_domain(j.dd, str(tmp_path), step=1)
    assert "integrity" not in checkpoint_meta(str(tmp_path), 1)
    k = make_jacobi()
    step, _ = restore_domain(k.dd, str(tmp_path))
    assert step == 1
    np.testing.assert_array_equal(k.temperature(), j.temperature())


def test_step_listing_sees_external_writes(tmp_path):
    """latest_step/restore must see steps written by ANOTHER process
    after this process's manager was cached (a monitor polling a
    campaign's directory) — the step list is read fresh, not from the
    manager's construction-time snapshot."""
    import shutil

    from stencil_tpu.utils import checkpoint as ckpt

    src, dst = tmp_path / "src", tmp_path / "dst"
    j = make_jacobi()
    j.step()
    ckpt.save_domain(j.dd, str(src), step=7)
    assert ckpt.latest_step(str(dst)) is None  # manager cached, empty
    shutil.copytree(src / "7", dst / "7")      # "another process" saves
    assert ckpt.latest_step(str(dst)) == 7
    k = make_jacobi()
    step, _ = ckpt.restore_domain(k.dd, str(dst))
    assert step == 7
    np.testing.assert_array_equal(k.temperature(), j.temperature())


def test_checkpoint_manager_cached_per_directory(tmp_path):
    from stencil_tpu.utils import checkpoint as ckpt

    d = str(tmp_path / "mgrs")
    m1 = ckpt._manager(d)
    m2 = ckpt._manager(d)
    assert m1 is m2
    ckpt.close_checkpoints(d)
    m3 = ckpt._manager(d)
    assert m3 is not m1
    ckpt.close_checkpoints(d)


def test_manager_retention_none_means_keep_all(tmp_path):
    """max_to_keep=None must rebuild a keep-all manager, not silently
    inherit a prior caller's pruning retention; read-only callers
    (no max_to_keep argument) reuse whatever is cached."""
    from stencil_tpu.utils import checkpoint as ckpt

    d = str(tmp_path / "ret")
    key = str(Path(d).absolute())
    m3 = ckpt._manager(d, 3)
    assert ckpt._MANAGERS[key][1] == 3
    assert ckpt._manager(d) is m3          # reader: don't care, reuse
    mall = ckpt._manager(d, None)          # writer: keep-all, rebuild
    assert mall is not m3
    assert ckpt._MANAGERS[key][1] is None
    assert ckpt._manager(d, None) is mall  # stable once rebuilt
    ckpt.close_checkpoints(d)


def test_restore_meta_probe_retries_transient_oserror(tmp_path):
    """A one-off OSError on the meta probe is backoff-retried, not
    misclassified as corruption (which would silently discard a good
    checkpoint or kill the run when it is the only step)."""
    from stencil_tpu.utils import checkpoint as ckpt

    j = make_jacobi()
    j.step()
    ckpt.save_domain(j.dd, str(tmp_path), step=1)
    want = j.temperature()
    real = ckpt._manager(str(tmp_path))

    class FlakyMgr:
        def __init__(self, inner):
            self._inner = inner
            self.failures = 1

        def restore(self, *a, **kw):
            if self.failures:
                self.failures -= 1
                raise OSError("injected transient meta-read blip")
            return self._inner.restore(*a, **kw)

        def __getattr__(self, name):
            return getattr(self._inner, name)

    k = make_jacobi()
    arrays, meta = ckpt._restore_step_arrays(k.dd, FlakyMgr(real), 1)
    assert meta["integrity"]
    np.testing.assert_array_equal(np.asarray(arrays["temp"]), want)


def test_save_state_single_retry_layer(tmp_path, monkeypatch):
    """attempts=1 (the resilience driver's setting) must make exactly
    one save attempt — the policy-driven retry outside is the only
    loop; the default still retries with backoff."""
    from stencil_tpu.utils import checkpoint as ckpt

    class FakeMgr:
        def __init__(self):
            self.calls = 0

        def all_steps(self, read=False):
            return []

        def save(self, *a, **kw):
            self.calls += 1
            raise OSError("disk on fire")

    fake = FakeMgr()
    monkeypatch.setattr(ckpt, "_manager", lambda *a, **kw: fake)
    with pytest.raises(OSError):
        ckpt.save_state(str(tmp_path), 0, {}, attempts=1)
    assert fake.calls == 1
    delays = []
    with pytest.raises(OSError):
        ckpt.save_state(str(tmp_path), 0, {}, attempts=3,
                        base_delay=0.25, sleep=delays.append)
    assert fake.calls == 4 and delays == [0.25, 0.5]


def test_domain_close_checkpoints_releases_managers(tmp_path):
    from stencil_tpu.utils import checkpoint as ckpt
    from stencil_tpu.utils.checkpoint import save_domain

    j = make_jacobi()
    d = str(tmp_path / "dom")
    save_domain(j.dd, d, step=0)
    key = str(Path(d).absolute())
    assert key in ckpt._MANAGERS
    j.dd.close_checkpoints()
    assert key not in ckpt._MANAGERS


# ----------------------------------------------------------------------
# the sentinel's communication contract (registry targets)
# ----------------------------------------------------------------------
def test_health_probe_registry_targets_prove_single_all_reduce():
    from stencil_tpu.analysis import run_targets
    from stencil_tpu.analysis.hlo import lowering_supported
    from stencil_tpu.analysis.registry import default_targets

    if not lowering_supported():
        pytest.skip("StableHLO lowering unavailable in this JAX")
    targets = [t for t in default_targets()
               if t.name.startswith("resilience.health.")]
    # probe[hlo] + step+probe[hlo] + the step+probe transfer audit
    assert len(targets) == 3
    report = run_targets(targets)
    assert report.findings == []
    probe = report.metrics["hlo:resilience.health.probe[hlo]"]
    assert probe["collectives"] == {
        "all_reduce": {"count": 1, "bytes_per_shard": 16}}
    fused = report.metrics["hlo:resilience.health.step+probe[hlo]"]
    assert fused["collectives"]["all_reduce"]["count"] == 1
    assert set(fused["collectives"]) == {"collective_permute",
                                         "all_reduce"}


def test_unstacked_probe_fixture_flagged():
    from stencil_tpu.analysis import run_targets
    from stencil_tpu.analysis.hlo import lowering_supported
    from stencil_tpu.analysis.registry import load_targets

    if not lowering_supported():
        pytest.skip("StableHLO lowering unavailable in this JAX")
    fixture = Path(__file__).parent / "fixtures" / "lint" / "bad_probe.py"
    report = run_targets(load_targets(fixture))
    assert len(report.errors) == 1
    assert "exactly 1" in report.errors[0].message
