"""Unit tests for geometry value types (mirrors reference
test/test_cpu_{numeric,radius,mat2d}.cpp coverage)."""

import pytest

from stencil_tpu.geometry import (Dim3, Rect3, Radius, all_directions,
                                  direction_kind)
from stencil_tpu.numerics import (Statistics, div_ceil, next_align_of,
                                  next_power_of_two, prime_factors, trimean)


class TestNumerics:
    def test_prime_factors(self):
        assert prime_factors(12) == [3, 2, 2]
        assert prime_factors(1) == [1]
        assert prime_factors(0) == []
        assert prime_factors(13) == [13]
        assert prime_factors(8) == [2, 2, 2]
        assert prime_factors(30) == [5, 3, 2]

    def test_div_ceil(self):
        assert div_ceil(10, 3) == 4
        assert div_ceil(9, 3) == 3
        assert div_ceil(1, 3) == 1

    def test_next_power_of_two(self):
        assert next_power_of_two(1) == 1
        assert next_power_of_two(3) == 4
        assert next_power_of_two(8) == 8
        assert next_power_of_two(9) == 16

    def test_next_align_of(self):
        # reference: include/stencil/align.cuh:7-9
        assert next_align_of(0, 8) == 0
        assert next_align_of(1, 8) == 8
        assert next_align_of(8, 8) == 8
        assert next_align_of(9, 4) == 12

    def test_trimean(self):
        assert trimean([1.0, 2.0, 3.0, 4.0, 5.0]) == pytest.approx(3.0)
        # asymmetric sample: q1=0.0, q2=0.5, q3=25.75 (type-7 quantiles)
        assert trimean([0.0, 0.0, 1.0, 100.0]) == pytest.approx(
            (0.0 + 2 * 0.5 + 25.75) / 4.0)

    def test_statistics(self):
        s = Statistics()
        for v in [3.0, 1.0, 2.0]:
            s.insert(v)
        assert s.min() == 1.0
        assert s.max() == 3.0
        assert s.avg() == pytest.approx(2.0)
        assert s.median() == pytest.approx(2.0)


class TestDim3:
    def test_arithmetic(self):
        a = Dim3(1, 2, 3)
        b = Dim3(4, 5, 6)
        assert a + b == Dim3(5, 7, 9)
        assert b - a == Dim3(3, 3, 3)
        assert a * 2 == Dim3(2, 4, 6)
        assert a * b == Dim3(4, 10, 18)
        assert -a == Dim3(-1, -2, -3)
        assert Dim3(7, 8, 9) % Dim3(2, 3, 4) == Dim3(1, 2, 1)

    def test_flatten(self):
        assert Dim3(2, 3, 4).flatten() == 24

    def test_wrap(self):
        # periodic modulo (reference: dim3.hpp:208-230)
        assert Dim3(-1, 5, 3).wrap((4, 4, 4)) == Dim3(3, 1, 3)
        assert Dim3(4, -2, 0).wrap((4, 4, 4)) == Dim3(0, 2, 0)

    def test_neq_intended_semantics(self):
        # the reference operator!= has a latent bug (dim3.hpp:195);
        # we implement intended semantics
        assert Dim3(1, 1, 1) != Dim3(1, 1, 2)
        assert Dim3(1, 1, 1) == Dim3(1, 1, 1)


class TestRect3:
    def test_extent_contains(self):
        r = Rect3.of((1, 1, 1), (4, 5, 6))
        assert r.extent() == Dim3(3, 4, 5)
        assert r.contains((1, 1, 1))
        assert not r.contains((4, 1, 1))
        assert not r.empty()
        assert Rect3.of((2, 2, 2), (2, 5, 5)).empty()


class TestRadius:
    def test_constant(self):
        r = Radius.constant(2)
        for d in all_directions():
            assert r.dir(d) == 2

    def test_face_edge_corner(self):
        # mirrors reference test_cpu_radius.cpp coverage
        r = Radius.face_edge_corner(3, 2, 1)
        assert r.dir((1, 0, 0)) == 3
        assert r.dir((0, -1, 0)) == 3
        assert r.dir((1, 1, 0)) == 2
        assert r.dir((0, -1, 1)) == 2
        assert r.dir((1, 1, 1)) == 1
        assert r.dir((-1, -1, -1)) == 1
        assert r.dir((0, 0, 0)) == 0
        assert r.x(1) == 3 and r.y(-1) == 3 and r.z(0) == 0

    def test_direction_kinds(self):
        kinds = [direction_kind(d) for d in all_directions()]
        assert kinds.count("face") == 6
        assert kinds.count("edge") == 12
        assert kinds.count("corner") == 8

    def test_asymmetric(self):
        r = Radius.constant(0)
        r.set_dir((1, 0, 0), 3)   # uncentered kernel: +x only
        assert r.pad_hi() == Dim3(3, 0, 0)
        assert r.pad_lo() == Dim3(0, 0, 0)
        assert r.max_side(0, 1) == 3
        assert r.max_side(0, -1) == 0

    def test_max_side_includes_diagonals(self):
        r = Radius.face_edge_corner(1, 2, 3)
        # corner radius 3 dominates every side
        for axis in range(3):
            for side in (-1, 1):
                assert r.max_side(axis, side) == 3


class TestConstructorValidation:
    """Hardened constructors: bad values fail LOUDLY instead of
    truncating into slab-width math."""

    def test_dim3_rejects_floats(self):
        with pytest.raises(ValueError, match="not an integer"):
            Dim3(2.5, 1, 1)
        with pytest.raises(ValueError, match="use // for integer"):
            Dim3(1, 4.0, 1)   # even integral floats: / vs // bugs
        with pytest.raises(ValueError):
            Dim3.of((1, 2, 3.5))
        with pytest.raises(ValueError):
            Dim3.filled(1.0)

    def test_dim3_accepts_numpy_integers(self):
        import numpy as np
        d = Dim3(np.int32(2), np.int64(3), np.uint8(4))
        assert d == Dim3(2, 3, 4)
        assert all(isinstance(c, int) for c in d)

    def test_dim3_negative_components_stay_legal(self):
        # direction vectors and differences NEED negatives
        assert -Dim3(1, 2, 3) == Dim3(-1, -2, -3)
        assert Dim3(0, 0, 0) - Dim3(1, 1, 1) == Dim3(-1, -1, -1)

    def test_dim3_arithmetic_still_validated(self):
        d = Dim3(4, 4, 4) + (1, 1, 1)
        assert d == Dim3(5, 5, 5)
        with pytest.raises(ValueError):
            Dim3(4, 4, 4) + (0.5, 0, 0)

    def test_radius_rejects_negative(self):
        with pytest.raises(ValueError, match=">= 0"):
            Radius.constant(-1)
        r = Radius.constant(1)
        with pytest.raises(ValueError, match=">= 0"):
            r.set_dir((1, 0, 0), -2)
        with pytest.raises(ValueError):
            Radius.face_edge_corner(3, -1, 0)
        with pytest.raises(ValueError):
            r.set_face(-3)

    def test_radius_rejects_floats(self):
        with pytest.raises(ValueError, match="not an integer"):
            Radius.constant(1.5)
        r = Radius.constant(0)
        with pytest.raises(ValueError):
            r.set_edge(2.0)

    def test_radius_valid_values_unchanged(self):
        import numpy as np
        r = Radius.constant(np.int64(3))
        assert r.dir((1, 1, 1)) == 3
        r.set_dir((0, 0, 1), np.int32(5))
        assert r.z(1) == 5
