"""stencil-lint: each checker proven positive AND negative.

Positive: the shipped registry is clean (the same property CI's lint
stage gates on). Negative: every fixture under tests/fixtures/lint/
is flagged by its checker — the pass is not vacuously green. Plus CLI
exit codes and the JSON artifact schema. Everything here is pure
tracing: no kernel executes, so this runs identically with or without
a TPU/interpreter.
"""

import json
import pathlib

import pytest

from stencil_tpu.analysis import Finding, Report, run_targets
from stencil_tpu.analysis.footprint import required_radius
from stencil_tpu.analysis.registry import default_targets, load_targets

FIXTURES = pathlib.Path(__file__).parent / "fixtures" / "lint"


# ---------------------------------------------------------------------------
# positive: shipped code is clean


def test_shipped_registry_is_clean():
    """The acceptance property: every registered op, DMA kernel and
    exchange path upholds its contract — zero errors, zero warnings
    (a warning would mean a shipped path went statically unverifiable
    without anyone deciding that)."""
    report = run_targets(default_targets())
    assert report.findings == [], [str(f) for f in report.findings]
    assert len(report.targets_checked) >= 20
    assert report.ok


def test_checker_filter():
    report = run_targets(default_targets(), checkers=["collectives"])
    assert report.ok
    assert all(t.startswith("parallel.exchange")
               for t in report.targets_checked)
    with pytest.raises(ValueError):
        run_targets([], checkers=["nope"])


# ---------------------------------------------------------------------------
# negative controls: one per checker, with the finding shape pinned


def test_footprint_fixture_flagged():
    report = run_targets(load_targets(FIXTURES / "bad_footprint.py"))
    assert not report.ok
    msgs = {f.target: f.message for f in report.errors}
    # the understated 5-point z stencil: both z faces under-declared
    assert any("(0, 0, 1)" in m and "declared radius 1 < required 2" in m
               for t, m in msgs.items()
               if t == "fixture.wide5_z_radius_understated"), msgs
    # diagonal access with zero edge radius: flagged in (1,1,0) ONLY
    edge = [f for f in report.errors
            if f.target == "fixture.cross_with_zero_edge_radius"]
    assert len(edge) == 1 and "(1, 1, 0)" in edge[0].message, edge
    # asymmetric: the -x side specifically
    assert any("(-1, 0, 0)" in f.message for f in report.errors
               if f.target == "fixture.asymmetric_minus_x_understated")
    # alias propagation: the access slices `padded * 0.5`, not padded
    assert any("(0, 1, 0)" in f.message and "required 2" in f.message
               for f in report.errors
               if f.target == "fixture.laundered_through_elementwise")


def test_dma_fixture_flagged():
    report = run_targets(load_targets(FIXTURES / "bad_dma.py"))
    assert not report.ok
    by_target = {}
    for f in report.errors:
        by_target.setdefault(f.target.split(":")[0], []).append(f.message)
    assert any("never awaited" in m
               for m in by_target["fixture.remote_dma_missing_wait"])
    assert any("before any neighbor barrier" in m
               for m in by_target["fixture.remote_dma_missing_barrier"])
    assert any("re-armed while" in m
               for m in by_target["fixture.semaphore_reused_in_flight"])
    assert any("barrier wait value 2 != 1" in m
               for m in by_target["fixture.barrier_signal_wait_mismatch"])


def test_collectives_fixture_flagged():
    report = run_targets(load_targets(FIXTURES / "bad_collective.py"))
    assert not report.ok
    msgs = {f.target: f.message for f in report.errors}
    assert "duplicated destination" in \
        msgs["fixture.ppermute_duplicate_destination"]
    assert "outside [0, 2)" in msgs["fixture.ppermute_index_out_of_range"]
    assert "not a full bijection" in \
        msgs["fixture.ppermute_partial_ring"]


# ---------------------------------------------------------------------------
# unit: the 26-direction requirement formula


def test_required_radius_formula():
    # an access reaching (+3 x, +3 y): edge (1,1,0) needs 3, faces too,
    # and any direction involving z needs nothing
    access = {(0, -1): 0, (0, 1): 3, (1, -1): 0, (1, 1): 3,
              (2, -1): 0, (2, 1): 0}
    req = required_radius([access])
    assert req[(1, 0, 0)] == 3
    assert req[(0, 1, 0)] == 3
    assert req[(1, 1, 0)] == 3
    assert req[(1, 1, 1)] == 0
    assert req[(0, 0, 1)] == 0
    assert req[(-1, 0, 0)] == 0


# ---------------------------------------------------------------------------
# CLI + JSON artifact


def test_cli_exit_codes_and_json(tmp_path):
    from stencil_tpu.analysis.__main__ import main

    out = tmp_path / "report.json"
    # fixtures -> nonzero, and the artifact records the errors
    rc = main(["-q", "--json", str(out),
               str(FIXTURES / "bad_collective.py")])
    assert rc == 1
    data = json.loads(out.read_text())
    assert data["schema_version"] == 1
    assert data["tool"] == "stencil-lint"
    assert data["counts"]["errors"] >= 3
    assert data["counts"]["errors_by_checker"] == {
        "collectives": data["counts"]["errors"]}
    assert {f["severity"] for f in data["findings"]} == {"error"}
    assert all(set(f) == {"checker", "target", "message", "severity"}
               for f in data["findings"])


@pytest.mark.parametrize("fixture", ["bad_footprint.py", "bad_dma.py",
                                     "bad_collective.py"])
def test_cli_nonzero_on_every_fixture(fixture):
    """The acceptance criterion verbatim: the CLI exits nonzero on
    EVERY negative-control fixture."""
    from stencil_tpu.analysis.__main__ import main

    assert main(["-q", str(FIXTURES / fixture)]) == 1


def test_cli_usage_error_on_missing_fixture(tmp_path):
    from stencil_tpu.analysis.__main__ import main

    assert main(["-q", str(tmp_path / "nope.py")]) == 2


def test_report_json_roundtrip():
    r = Report()
    r.targets_checked.append("t")
    r.findings.append(Finding("dma", "t", "boom"))
    d = json.loads(r.to_json())
    assert d["counts"] == {"targets": 1, "errors": 1, "warnings": 0,
                           "errors_by_checker": {"dma": 1}}
    assert not r.ok
