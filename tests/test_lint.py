"""stencil-lint: each checker proven positive AND negative.

Positive: the shipped registry is clean (the same property CI's lint
stage gates on). Negative: every fixture under tests/fixtures/lint/
is flagged by its checker — the pass is not vacuously green. Plus CLI
exit codes and the JSON artifact schema. Everything here is pure
tracing: no kernel executes, so this runs identically with or without
a TPU/interpreter.
"""

import json
import pathlib

import pytest

from stencil_tpu.analysis import Finding, Report, run_targets
from stencil_tpu.analysis.footprint import required_radius
from stencil_tpu.analysis.registry import default_targets, load_targets

FIXTURES = pathlib.Path(__file__).parent / "fixtures" / "lint"


# ---------------------------------------------------------------------------
# positive: shipped code is clean


@pytest.fixture(scope="module")
def full_report():
    """One run of all ten checkers over the shipped registry, shared
    by every test that asserts on it (the donation block compiles all
    its entry points — paying that once per module, not per test)."""
    return run_targets(default_targets())


def test_shipped_registry_is_clean(full_report):
    """The acceptance property: every registered op, DMA kernel and
    exchange path upholds its contract — zero errors, zero warnings
    (a warning would mean a shipped path went statically unverifiable
    without anyone deciding that)."""
    report = full_report
    assert report.findings == [], [str(f) for f in report.findings]
    # the committed coverage floor — read from the SAME file CI stage 1
    # ratchets against, so the two gates cannot drift
    floor_file = pathlib.Path(__file__).parent.parent / "ci" / \
        "registry_floor.txt"
    floor = int(floor_file.read_text().split()[0])
    assert floor >= 105  # the PR 9 acceptance criterion itself
    assert len(report.targets_checked) >= floor
    assert report.ok
    # all thirteen checkers actually ran (and were timed)
    assert set(report.checker_seconds) == {
        "footprint", "dma", "collectives", "hlo", "costmodel", "vmem",
        "donation", "transfer", "recompile", "tiling", "linkmap",
        "schedule", "precision"}


def test_checker_filter():
    report = run_targets(default_targets(), checkers=["collectives"])
    assert report.ok
    assert all(t.startswith(("parallel.exchange", "parallel.temporal",
                             "parallel.migrate", "serving.ensemble"))
               for t in report.targets_checked)
    with pytest.raises(ValueError):
        run_targets([], checkers=["nope"])


def test_costmodel_cross_check_not_vacuous():
    """The analytic-vs-HLO byte cross-check must actually compare
    nonzero numbers on every ppermute exchange method (a lowering
    regression detector that observes zero bytes detects nothing).
    Skips only where this JAX cannot produce StableHLO at all."""
    from stencil_tpu.analysis.hlo import lowering_supported

    if not lowering_supported():
        pytest.skip("no StableHLO lowering in this JAX/backend")
    report = run_targets(default_targets(), checkers=["costmodel"])
    assert report.ok
    compared = [m for m in report.metrics.values()
                if "observed_bytes_per_shard" in m]
    assert len(compared) >= 6
    for m in compared:
        assert m["observed_bytes_per_shard"] > 0
        assert (m["observed_bytes_per_shard"]
                == m["expected_bytes_per_shard"])


def test_hlo_registry_collective_permute_only():
    """The acceptance criterion: every registered ppermute exchange
    method lowers to collective-permute ONLY (the all-gather control
    path is pinned to all_gather; the Pallas method is capability-
    gated off-TPU, recorded as a skip, never silently green)."""
    from stencil_tpu.analysis.hlo import lowering_supported

    if not lowering_supported():
        pytest.skip("no StableHLO lowering in this JAX/backend")
    report = run_targets(default_targets(), checkers=["hlo"])
    assert report.ok
    kinds_by_target = {}
    for key, m in report.metrics.items():
        if "collectives" in m:
            kinds_by_target[key] = set(m["collectives"])
    for key, kinds in kinds_by_target.items():
        if "allgather" in key.lower():
            assert kinds == {"all_gather"}, (key, kinds)
        elif ("resilience.health" in key
              or "serving.ensemble.probe" in key
              or "models.pic.probe" in key
              or "telemetry." in key
              or "parallel.megastep" in key
              or ".segment[" in key
              or "observatory.attribution" in key):
            # (the observatory's attributed segment IS the megastep
            # program — identical HLO is the whole point — so it
            # carries the same one-reduce-per-probe-row contract)
            # the health sentinels' contract is different by design:
            # exactly ONE small all-reduce (pinned via exact_counts on
            # their HloSpecs; the ensemble probe batches per-member
            # stats through the same single reduce, the telemetry
            # step-metrics columns ride that same reduce — never a
            # second one — and the fused megastep carries one such
            # reduce per declared probe row)
            assert kinds <= {"collective_permute", "all_reduce"}, \
                (key, kinds)
        else:
            assert kinds <= {"collective_permute"}, (key, kinds)
    assert any("collective_permute" in k
               for k in kinds_by_target.values())


# ---------------------------------------------------------------------------
# negative controls: one per checker, with the finding shape pinned


def test_footprint_fixture_flagged():
    report = run_targets(load_targets(FIXTURES / "bad_footprint.py"))
    assert not report.ok
    msgs = {f.target: f.message for f in report.errors}
    # the understated 5-point z stencil: both z faces under-declared
    assert any("(0, 0, 1)" in m and "declared radius 1 < required 2" in m
               for t, m in msgs.items()
               if t == "fixture.wide5_z_radius_understated"), msgs
    # diagonal access with zero edge radius: flagged in (1,1,0) ONLY
    edge = [f for f in report.errors
            if f.target == "fixture.cross_with_zero_edge_radius"]
    assert len(edge) == 1 and "(1, 1, 0)" in edge[0].message, edge
    # asymmetric: the -x side specifically
    assert any("(-1, 0, 0)" in f.message for f in report.errors
               if f.target == "fixture.asymmetric_minus_x_understated")
    # alias propagation: the access slices `padded * 0.5`, not padded
    assert any("(0, 1, 0)" in f.message and "required 2" in f.message
               for f in report.errors
               if f.target == "fixture.laundered_through_elementwise")


def test_temporal_fixture_flagged():
    """A blocked kernel whose sub-step window forgot to shrink reads
    depth 3 against a deepened depth-2 halo contract — the footprint
    checker must catch the fused program's total reach."""
    report = run_targets(load_targets(FIXTURES / "bad_temporal.py"))
    assert not report.ok
    errs = [f for f in report.errors
            if f.target == "fixture.temporal_substep_reads_past_deep_halo"]
    assert any("(0, 0, 1)" in f.message
               and "declared radius 2 < required 3" in f.message
               for f in errs), [str(f) for f in errs]
    assert any("(0, 0, -1)" in f.message for f in errs)


def test_dma_fixture_flagged():
    report = run_targets(load_targets(FIXTURES / "bad_dma.py"))
    assert not report.ok
    by_target = {}
    for f in report.errors:
        by_target.setdefault(f.target.split(":")[0], []).append(f.message)
    assert any("never awaited" in m
               for m in by_target["fixture.remote_dma_missing_wait"])
    assert any("before any neighbor barrier" in m
               for m in by_target["fixture.remote_dma_missing_barrier"])
    assert any("re-armed while" in m
               for m in by_target["fixture.semaphore_reused_in_flight"])
    assert any("barrier wait value 2 != 1" in m
               for m in by_target["fixture.barrier_signal_wait_mismatch"])


def test_schedule_fixture_flagged():
    """The two replay-soundness negative controls, each named by its
    violated condition: in-flight aliasing across sub-steps vs the
    cross-shard wait-cycle deadlock."""
    report = run_targets(load_targets(FIXTURES / "bad_schedule.py"))
    assert not report.ok
    by_target = {}
    for f in report.errors:
        by_target.setdefault(f.target.split(":")[0], []).append(f.message)
    assert any("in-flight aliasing across sub-steps" in m
               for m in by_target["fixture.schedule_slot_reuse_under_replay"])
    assert any("deadlock cycle" in m
               for m in by_target["fixture.schedule_wait_cycle_deadlock"])
    # the certificates say WHY in the metrics artifact too
    slot = report.metrics[
        "schedule:fixture.schedule_slot_reuse_under_replay"]
    assert slot["replay_safe"] is False
    assert any(not k["replay_safe"] for k in slot["kernels"].values())


def test_schedule_registry_certifies_fused_kernels(full_report):
    """The proof megastep consumes: every schedule target the segment
    compiler fuses through (``fused_by_megastep``) holds a
    ``replay_safe`` certificate with the pinned in-flight peak — and
    at least one production RDMA kernel earns it."""
    fused = {name: m for name, m in full_report.metrics.items()
             if name.startswith("schedule:") and m.get("fused_by_megastep")}
    assert any("jacobi7_overlap_pallas" in name for name in fused), \
        list(full_report.metrics)
    for name, m in fused.items():
        assert m["replay_safe"] is True, (name, m)
    overlap = full_report.metrics[
        "schedule:analysis.schedule.ops.pallas_overlap."
        "jacobi7_overlap_pallas[k=4]"]
    assert overlap["max_in_flight"] == 4
    assert overlap["replay"] == 4


def test_precision_fixture_flagged():
    """The three dtype-flow negative controls, each named by its
    violated condition: the bf16 psum sold as f32 (condition (a)),
    the silent in-step narrowing, and the double-quantized wire hop
    (condition (c))."""
    report = run_targets(load_targets(FIXTURES / "bad_precision.py"))
    assert not report.ok
    by_target = {}
    for f in report.errors:
        by_target.setdefault(f.target.split(":")[0], []).append(f.message)
    assert any("(a) accumulation below the compute floor" in m
               for m in by_target["fixture.precision_bf16_psum_sold_as_f32"])
    assert any("silent convert" in m
               for m in by_target["fixture.precision_silent_step_narrowing"])
    assert any("(c) double quantization" in m
               for m in by_target[
                   "fixture.precision_double_quantized_wire_hop"])
    # the certificates say WHY in the metrics artifact too
    psum = report.metrics["precision:fixture.precision_bf16_psum_sold_as_f32"]
    assert psum["safe"] is False
    assert psum["narrowest_accum"] == "bfloat16"
    silent = report.metrics[
        "precision:fixture.precision_silent_step_narrowing"]
    assert silent["silent_converts"] == [
        {"from": "float32", "to": "bfloat16", "count": 1}]


def test_precision_registry_certifies_shipped_paths(full_report):
    """The proof the wire-format gate consumes: EVERY registered entry
    point holds a ``safe`` certificate with zero silent converts, the
    declared-bf16 exchange targets carry exactly the bf16 wire dtype on
    every narrowing axis with the analytic 2^-8 bound, and the f32
    paths certify bitwise-identity wire (bound 0.0)."""
    certs = {name: m for name, m in full_report.metrics.items()
             if name.startswith("precision:")}
    assert len(certs) >= 13, list(certs)
    for name, m in certs.items():
        assert m["safe"] is True, (name, m)
        assert m["silent_converts"] == [], (name, m)
    bf16 = full_report.metrics[
        "precision:analysis.precision.parallel.exchange."
        "make_exchange[PpermuteSlab,wire=bf16]"]
    assert bf16["max_rel_error_bound"] == 2.0 ** -8
    for ax, rec in bf16["wire_dtypes"].items():
        if rec["declared"] == "bf16":
            assert rec["dtypes"] == ["bfloat16"], (ax, rec)
    f32 = full_report.metrics[
        "precision:analysis.precision.parallel.exchange."
        "make_exchange[PpermuteSlab]"]
    assert f32["max_rel_error_bound"] == 0.0
    # accumulation floor held everywhere it was observed
    for name, m in certs.items():
        if m["narrowest_accum"] is not None:
            assert m["narrowest_accum"] in ("float32", "float64"), \
                (name, m)


def test_collectives_fixture_flagged():
    report = run_targets(load_targets(FIXTURES / "bad_collective.py"))
    assert not report.ok
    msgs = {f.target: f.message for f in report.errors}
    assert "duplicated destination" in \
        msgs["fixture.ppermute_duplicate_destination"]
    assert "outside [0, 2)" in msgs["fixture.ppermute_index_out_of_range"]
    assert "not a full bijection" in \
        msgs["fixture.ppermute_partial_ring"]


def test_hlo_fixture_flagged():
    from stencil_tpu.analysis.hlo import lowering_supported

    if not lowering_supported():
        pytest.skip("no StableHLO lowering in this JAX/backend")
    report = run_targets(load_targets(FIXTURES / "bad_hlo.py"))
    assert not report.ok
    msgs = {f.target: f.message for f in report.errors}
    # the accidental all-gather from "fixing" mismatched out_specs
    assert "stablehlo.all_gather" in \
        msgs["fixture.allgather_via_mismatched_out_specs"]
    # a psum left in the hot step lowers to all-reduce
    assert "stablehlo.all_reduce" in msgs["fixture.psum_in_step"]
    # the costmodel catches a radius-2 exchange sold as radius-1
    m = msgs["fixture.exchange_moves_more_than_model"]
    assert "2304 B/shard" in m and "1152 B/shard" in m and "+100.0%" in m


def test_plan_fixture_flagged():
    """A tampered/buggy tuned plan that silently enables the AllGather
    strategy must trip the registry's ppermute-only HLO gate — the
    negative control proving tuned-plan coverage is not vacuous."""
    from stencil_tpu.analysis.hlo import lowering_supported

    if not lowering_supported():
        pytest.skip("no StableHLO lowering in this JAX/backend")
    report = run_targets(load_targets(FIXTURES / "bad_plan.py"))
    assert not report.ok
    msgs = {f.target: f.message for f in report.errors}
    assert "stablehlo.all_gather" in \
        msgs["fixture.plan_silently_enables_allgather"]


def test_tuner_emittable_configs_are_registered():
    """Every (method, depth) configuration the autotuner's candidate
    space can emit on a capability-complete backend has a tuning.plan
    HLO target in the shipped registry (the Auto manifest entry's
    substance)."""
    from stencil_tpu.tuning.plan import DEFAULT_DEPTHS, PLAN_METHODS

    names = _registry_names()
    for method in PLAN_METHODS:
        depths = DEFAULT_DEPTHS if method in (
            "PpermuteSlab", "PpermutePacked") else (1,)
        for s in depths:
            assert f"tuning.plan[{method},s={s},hlo]" in names, \
                f"emittable plan config {method} s={s} unregistered"


def test_donation_fixture_flagged():
    """Both donation-death modes are caught: a jit that lost its
    donate_argnums, and a donated buffer XLA silently copies because
    the output dtype narrowed."""
    from stencil_tpu.analysis.hlo import lowering_supported

    if not lowering_supported():
        pytest.skip("no StableHLO lowering in this JAX/backend")
    report = run_targets(load_targets(FIXTURES / "bad_donation.py"))
    assert not report.ok
    msgs = {f.target: f.message for f in report.errors}
    assert "missing from the compiled input_output_alias" in \
        msgs["fixture.donation_never_declared"]
    assert "missing from the compiled input_output_alias" in \
        msgs["fixture.donated_but_copied"]
    # donated-bytes metrics computed even for flagged targets
    m = report.metrics["donation:fixture.donation_never_declared"]
    assert m["donated_bytes"] == 8 * 8 * 8 * 4
    assert m["donated_leaves"] == 1 and m["aliased_params"] == []


def test_transfer_fixture_flagged():
    report = run_targets(load_targets(FIXTURES / "bad_transfer.py"))
    assert not report.ok
    msgs = {f.target: f.message for f in report.errors}
    assert "debug_callback" in msgs["fixture.debug_print_in_step"]
    assert "pure_callback" in msgs["fixture.pure_callback_in_step"]
    m = report.metrics["transfer:fixture.debug_print_in_step"]
    assert m["host_escapes"] == {"debug_callback": 1}


def test_recompile_fixture_flagged():
    """All three fingerprint-drift modes are caught: curr/next dtype
    drift, weak-type promotion of the carried state, and a Python
    scalar passed where the warm path feeds a committed array."""
    report = run_targets(load_targets(FIXTURES / "bad_recompile.py"))
    assert not report.ok
    msgs = {f.target: f.message for f in report.errors}
    assert "dtype drift float32 -> bfloat16" in \
        msgs["fixture.carry_dtype_drift"]
    assert "weak-type promotion" in msgs["fixture.weak_type_promotion"]
    assert "Python scalar" in msgs["fixture.python_scalar_arg"]
    # the abstract-fingerprint manifest is still recorded
    m = report.metrics["recompile:fixture.carry_dtype_drift"]
    assert len(m["fingerprint"]) == 64 and m["carry_leaves"] == 1


def test_dataflow_entry_points_all_pass(full_report):
    """The acceptance criterion: every registered production entry
    point — the model step loops, every runnable make_exchange method,
    the fused megastep segments, and the ensemble step/segment/lane
    programs — is donation-clean, transfer-clean, and single-compile
    (its abstract fingerprint is dispatch-stable). Asserted on the
    shared nine-checker report (one registry run per module)."""
    from stencil_tpu.analysis.hlo import lowering_supported

    if not lowering_supported():
        pytest.skip("no StableHLO lowering in this JAX/backend")
    report = full_report
    dataflow = [f for f in report.findings
                if f.checker in ("donation", "transfer", "recompile")]
    assert dataflow == [], [str(f) for f in dataflow]
    names = set(report.targets_checked)
    # every runnable exchange method's orchestrator donates
    for method in ("PpermuteSlab", "PpermutePacked", "AllGather"):
        assert (f"parallel.exchange.make_exchange[{method},donation]"
                in names), names
    # the megastep + ensemble entry points carry all three audits
    for suffix in ("donation", "transfer", "recompile"):
        assert f"parallel.megastep.segment[k=4,{suffix}]" in names
        assert f"serving.ensemble.step[N=4,{suffix}]" in names
        assert f"serving.ensemble.segment[N=4,k=2,{suffix}]" in names
        assert f"models.jacobi.step_n[xla,{suffix}]" in names
        assert f"models.astaroth.iter_n[{suffix}]" in names
    # donated-bytes metrics are live for the model steps
    m = report.metrics["donation:models.jacobi.step_n[xla,donation]"]
    assert m["donated_bytes"] > 0
    assert m["aliased_params"] and 0 in m["aliased_params"]


def test_tiling_fixture_flagged():
    """The SNIPPETS.md 512^3 failure as a negative control: the Jacobi
    halo kernel pinned to the old default (16, 128) block shape is
    flagged at the PHYSICAL budget (its raised vmem_limit_bytes hid it
    from the plain vmem checker) and the finding carries the planner's
    concrete prescription — the (8, 128) shape the registry's legal
    512^3 target proves clean."""
    report = run_targets(load_targets(FIXTURES / "bad_tiling.py"))
    assert not report.ok
    (f,) = report.errors
    assert f.checker == "tiling"
    assert f.target.startswith(
        "fixture.jacobi_halo_old_default_shape_at_512")
    assert "20971520 B" in f.message and "exceeds" in f.message
    assert "suggestion: block shape (8, 128)" in f.message


def test_tiling_registry_production_sizes(full_report):
    """The acceptance criterion: every registered Pallas kernel is
    gated at 256^3- AND 512^3-per-device shapes, the Jacobi production
    family (plane/wrap/wrapn/halo/halon) proves LEGAL planner-derived
    shapes at 512^3, and the pinned-infeasible kernels are verdicts,
    not silences (refused or flagged-as-expected, never unaudited)."""
    report = full_report
    tiling = [n for n in report.targets_checked
              if n.startswith("analysis.tiling.")]
    assert len(tiling) >= 28
    for side in (256, 512):
        assert sum(1 for n in tiling if n.endswith(f"[{side}]")) >= 14
    for kernel in ("ops.pallas_stencil.jacobi7_pallas",
                   "ops.pallas_stencil.jacobi7_wrap_pallas",
                   "ops.pallas_stencil.jacobi7_wrapn_pallas[n=2]",
                   "ops.pallas_halo.jacobi7_halo_pallas",
                   "ops.pallas_halo.jacobi7_halon_pallas[n=2]"):
        m = report.metrics[f"tiling:analysis.tiling.{kernel}[512]"]
        assert m["verdict"] == "legal", (kernel, m)
    # the pinned-infeasible kernels record WHY (binding constraint or
    # expected findings), proving the audit has teeth at these sizes
    for kernel in ("ops.pallas_halo.mhd_substep_halo_pallas",
                   "ops.pallas_mhd.mhd_substep_wrap_pallas"):
        m = report.metrics[f"tiling:analysis.tiling.{kernel}[512]"]
        assert m["verdict"] in ("refused-at-build", "refused-at-trace",
                                "flagged-as-expected"), (kernel, m)


def test_linkmap_fixture_flagged():
    """The 6-neighbor-only traffic matrix (corner messages dropped)
    must under-sum against the HLO-extracted bytes and be flagged
    with the zero-corner-share hint."""
    from stencil_tpu.analysis.hlo import lowering_supported

    if not lowering_supported():
        pytest.skip("no StableHLO lowering in this JAX/backend")
    report = run_targets(load_targets(FIXTURES / "bad_linkmap.py"))
    assert not report.ok
    (f,) = report.errors
    assert f.checker == "linkmap"
    assert f.target == "fixture.linkmap_drops_corner_messages"
    assert "B unattributed" in f.message
    assert "6-neighbor-only" in f.message


def test_placement_fixture_flagged():
    """A linkmap target that SHIPS a QAP-refined placement costing
    more than the identity order on its own declared fabric
    (tests/fixtures/lint/bad_placement.py: an x/z transpose that drags
    the fat x faces across the DCN seam) must be flagged by the
    placement-payload re-pricing inside the linkmap checker."""
    from stencil_tpu.analysis.hlo import lowering_supported

    if not lowering_supported():
        pytest.skip("no StableHLO lowering in this JAX/backend")
    report = run_targets(load_targets(FIXTURES / "bad_placement.py"))
    assert not report.ok
    errs = [f for f in report.errors if "placement" in f.message]
    assert errs, [str(f) for f in report.errors]
    (f,) = errs
    assert f.checker == "linkmap"
    assert f.target.startswith("fixture.placement_ships_qap_loser")
    assert "never lose to the identity assignment" in f.message


def test_segment_carry_fixture_flagged():
    """A PIC fused segment whose carry contract DROPS the overflow
    probe column (tests/fixtures/lint/bad_segment_carry.py): every
    trace row's all-reduce shrinks from the contract's (2, 9) to
    (2, 8) f32, so the byte pin must flag the missing column."""
    from stencil_tpu.analysis.hlo import lowering_supported

    if not lowering_supported():
        pytest.skip("no StableHLO lowering in this JAX/backend")
    report = run_targets(load_targets(FIXTURES / "bad_segment_carry.py"))
    assert not report.ok
    (f,) = report.errors
    assert f.checker == "costmodel"
    assert "128 B/shard" in f.message
    assert "144 B/shard" in f.message


def test_linkmap_registry_pins_exact_hlo_bytes(full_report):
    """The acceptance criterion: every observatory.linkmap.* target's
    modeled traffic matrix sums EXACTLY to the HLO-extracted wire
    bytes — slab/packed x s, the all-gather control, migration, and
    the PIC step (accumulate adjoint included)."""
    from stencil_tpu.analysis.hlo import lowering_supported

    if not lowering_supported():
        pytest.skip("no StableHLO lowering in this JAX/backend")
    report = full_report
    keys = [k for k in report.metrics if k.startswith("linkmap:")]
    assert len(keys) >= 9
    for key in keys:
        m = report.metrics[key]
        assert m["matrix_bytes_per_shard"] > 0, key
        assert (m["observed_bytes_per_shard"]
                == m["matrix_bytes_per_shard"]), (key, m)
    for name in ("observatory.linkmap.exchange[r1]",
                 "observatory.linkmap.plan[PpermuteSlab,s=2]",
                 "observatory.linkmap.plan[PpermutePacked,s=4]",
                 "observatory.linkmap.allgather",
                 "observatory.linkmap.migrate",
                 "observatory.linkmap.pic_step"):
        assert f"linkmap:{name}" in report.metrics, name


def test_vmem_fixture_flagged():
    report = run_targets(load_targets(FIXTURES / "bad_vmem.py"))
    assert not report.ok
    by_target = {}
    for f in report.errors:
        by_target.setdefault(f.target.split(":")[0], []).append(f.message)
    assert any("exceeds the 16777216 B budget" in m
               for m in by_target["fixture.block_over_vmem_budget"])
    assert any("lane (last) dim 96 is neither a multiple of 128" in m
               for m in by_target["fixture.misaligned_trailing_tile"])
    assert any("block 8 does not divide the array extent 20" in m
               for m in by_target["fixture.ragged_grid_tiling"])
    # footprint metrics computed even for flagged kernels
    key = "vmem:fixture.block_over_vmem_budget"
    kernels = report.metrics[key]["kernels"]
    (m,) = kernels.values()
    assert m["vmem_estimate_bytes"] == 2 * 2 * 128 * 128 * 128 * 4
    assert m["pipeline_buffers"] == 2


# ---------------------------------------------------------------------------
# unit: the 26-direction requirement formula


def test_required_radius_formula():
    # an access reaching (+3 x, +3 y): edge (1,1,0) needs 3, faces too,
    # and any direction involving z needs nothing
    access = {(0, -1): 0, (0, 1): 3, (1, -1): 0, (1, 1): 3,
              (2, -1): 0, (2, 1): 0}
    req = required_radius([access])
    assert req[(1, 0, 0)] == 3
    assert req[(0, 1, 0)] == 3
    assert req[(1, 1, 0)] == 3
    assert req[(1, 1, 1)] == 0
    assert req[(0, 0, 1)] == 0
    assert req[(-1, 0, 0)] == 0


# ---------------------------------------------------------------------------
# CLI + JSON artifact


def test_cli_exit_codes_and_json(tmp_path):
    from stencil_tpu.analysis.__main__ import main

    out = tmp_path / "report.json"
    # fixtures -> nonzero, and the artifact records the errors
    rc = main(["-q", "--json", str(out),
               str(FIXTURES / "bad_collective.py")])
    assert rc == 1
    data = json.loads(out.read_text())
    assert data["schema_version"] == 2
    assert data["tool"] == "stencil-lint"
    assert data["tool_version"]
    assert data["counts"]["errors"] >= 3
    assert data["counts"]["errors_by_checker"] == {
        "collectives": data["counts"]["errors"]}
    # schema v2: per-checker wall time
    assert set(data["checker_seconds"]) == {"collectives"}
    assert data["checker_seconds"]["collectives"] >= 0
    assert {f["severity"] for f in data["findings"]} == {"error"}
    assert all(set(f) == {"checker", "target", "message", "severity"}
               for f in data["findings"])


def test_cli_list_and_only(capsys, tmp_path):
    from stencil_tpu.analysis import CHECKERS
    from stencil_tpu.analysis.__main__ import main

    assert main(["--list"]) == 0
    out = capsys.readouterr().out
    for name in CHECKERS:
        assert name in out
    # --list also prints the registry target counts per group
    assert "registry targets by group" in out
    for group in ("ops", "parallel", "tuning", "serving", "telemetry",
                  "resilience", "models"):
        assert group in out
    assert "donation=" in out and "recompile=" in out

    # --only restricts the run AND the artifact to one checker
    report = tmp_path / "r.json"
    rc = main(["-q", "--only", "vmem", "--json", str(report),
               str(FIXTURES / "bad_vmem.py")])
    assert rc == 1
    data = json.loads(report.read_text())
    assert set(data["checker_seconds"]) == {"vmem"}
    assert {f["checker"] for f in data["findings"]} == {"vmem"}
    # vmem metrics land keyed by checker:target
    assert any(k.startswith("vmem:fixture.") for k in data["metrics"])


def test_cli_only_accepts_target_globs(tmp_path):
    """--only values that are not checker names filter TARGET names by
    glob: '--only fixture.ppermute_*' runs only the matching targets,
    and composes with a checker-name filter."""
    from stencil_tpu.analysis.__main__ import main

    report = tmp_path / "r.json"
    rc = main(["-q", "--only", "fixture.ppermute_*", "--json",
               str(report), str(FIXTURES / "bad_collective.py")])
    assert rc == 1
    data = json.loads(report.read_text())
    assert data["counts"]["targets"] == 3
    assert all(t.startswith("fixture.ppermute_")
               for t in data["targets_checked"])

    # composed to NOTHING: the glob matches only collectives targets,
    # the checker filter says vmem — a vacuously green run is refused
    # the same way an unmatched glob is
    rc = main(["-q", "--only", "fixture.ppermute_*", "--only", "vmem",
               str(FIXTURES / "bad_collective.py")])
    assert rc == 2
    # composed to SOMETHING: same glob with the matching checker
    rc = main(["-q", "--only", "fixture.ppermute_*", "--only",
               "collectives", "--json", str(report),
               str(FIXTURES / "bad_collective.py")])
    assert rc == 1
    assert json.loads(report.read_text())["counts"]["targets"] == 3

    # literal brackets in target names: fnmatch treats [..] as a
    # character class, so '--only' escapes them — the bracketed
    # schedule fixtureless registry names match as spelled. The
    # fixture's targets carry no brackets, so exercise the escape
    # against the shipped registry spelling instead
    report2 = tmp_path / "r2.json"
    rc = main(["-q", "--only", "analysis.schedule.*[k=4]",
               "--json", str(report2)])
    assert rc == 0
    data2 = json.loads(report2.read_text())
    assert data2["counts"]["targets"] >= 4
    assert all("k=4]" in t for t in data2["targets_checked"])

    # a glob matching nothing is a usage error — even when OTHER
    # patterns matched (a typo'd glob must not silently drop its
    # coverage from a green run)
    rc = main(["-q", "--only", "no.such.target.*",
               str(FIXTURES / "bad_collective.py")])
    assert rc == 2
    rc = main(["-q", "--only", "fixture.ppermute_*",
               "--only", "no.such.target.*",
               str(FIXTURES / "bad_collective.py")])
    assert rc == 2


@pytest.mark.parametrize("fixture", ["bad_footprint.py", "bad_dma.py",
                                     "bad_collective.py", "bad_hlo.py",
                                     "bad_vmem.py", "bad_temporal.py",
                                     "bad_plan.py", "bad_probe.py",
                                     "bad_probe_metrics.py",
                                     "bad_megastep.py",
                                     "bad_donation.py",
                                     "bad_transfer.py",
                                     "bad_recompile.py",
                                     "bad_migration.py",
                                     "bad_attribution.py",
                                     "bad_tiling.py",
                                     "bad_linkmap.py",
                                     "bad_placement.py",
                                     "bad_segment_carry.py",
                                     "bad_schedule.py",
                                     "bad_precision.py",
                                     "bad_packing.py",
                                     "bad_bucketing.py"])
def test_cli_nonzero_on_every_fixture(fixture):
    """The acceptance criterion verbatim: the CLI exits nonzero on
    EVERY negative-control fixture."""
    from stencil_tpu.analysis.__main__ import main

    if fixture in ("bad_hlo.py", "bad_plan.py", "bad_probe.py",
                   "bad_probe_metrics.py", "bad_megastep.py",
                   "bad_donation.py", "bad_migration.py",
                   "bad_linkmap.py", "bad_placement.py",
                   "bad_segment_carry.py", "bad_packing.py"):
        from stencil_tpu.analysis.hlo import lowering_supported

        if not lowering_supported():
            pytest.skip("no StableHLO lowering in this JAX/backend")
    assert main(["-q", str(FIXTURES / fixture)]) == 1


def test_cli_usage_error_on_missing_fixture(tmp_path):
    from stencil_tpu.analysis.__main__ import main

    assert main(["-q", str(tmp_path / "nope.py")]) == 2


def test_report_json_roundtrip():
    r = Report()
    r.targets_checked.append("t")
    r.findings.append(Finding("dma", "t", "boom"))
    d = json.loads(r.to_json())
    assert d["counts"] == {"targets": 1, "errors": 1, "warnings": 0,
                           "errors_by_checker": {"dma": 1}}
    assert not r.ok


def test_vmem_handles_squeezed_block_dims():
    """The standard Pallas squeezed-dim pattern (``None`` in a
    BlockSpec) must audit cleanly — a None dim occupies one array
    slice per grid step, it must not crash the checker (regression:
    the Mapped sentinel is not int()-able)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from stencil_tpu.analysis import VmemSpec, VmemTarget, check_vmem

    def kern(x, o):
        o[...] = x[...]

    def fn(x):
        return pl.pallas_call(
            kern,
            grid=(4,),
            in_specs=[pl.BlockSpec((None, 8, 128), lambda i: (i, 0, 0))],
            out_specs=pl.BlockSpec((None, 8, 128), lambda i: (i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((4, 8, 128), jnp.float32),
            interpret=False,
        )(x)

    target = VmemTarget(
        "unit.squeezed", lambda: VmemSpec(
            fn=fn, args=(jax.ShapeDtypeStruct((4, 8, 128),
                                              jnp.float32),)))
    findings, metrics = check_vmem(target)
    assert findings == [], [str(f) for f in findings]
    (m,) = metrics["kernels"].values()
    # squeezed z dim counts as 1 slice: 8*128 f32 x 2 blocks x 2 buffers
    assert m["vmem_block_bytes"] == 2 * 8 * 128 * 4
    assert m["pipeline_buffers"] == 2


# ---------------------------------------------------------------------------
# registry-drift guard: new public ops / exchange methods cannot
# silently escape the lint gate


def _registry_names():
    return [t.name for t in default_targets()]


def test_every_exchange_method_is_registered():
    """Every ``Method`` strategy flag maps (via the parallel package's
    coverage manifest) to a registered analysis target."""
    from stencil_tpu.parallel import exchange_method_targets

    names = _registry_names()
    manifest = exchange_method_targets()
    assert set(manifest) == {"PpermuteSlab", "PpermutePacked",
                             "PallasDMA", "AllGather", "Auto"}
    for method, prefix in manifest.items():
        assert any(n.startswith(prefix) for n in names), \
            f"exchange method {method} ({prefix}) has no analysis target"


def test_every_public_op_is_registered():
    """Every entry of the ops package's coverage manifest points at a
    live registry target, and the manifest itself covers every public
    kernel entry point defined in ops/ (every module-level *_pallas
    function plus the XLA core ops) — code cannot be added to ops/
    without either registering it or failing here."""
    import importlib
    import inspect
    import pkgutil

    import stencil_tpu.ops as ops_pkg
    from stencil_tpu.ops import PUBLIC_OPS

    names = _registry_names()
    for op, prefix in PUBLIC_OPS.items():
        assert any(n.startswith(prefix) for n in names), \
            f"public op {op} maps to unregistered target prefix {prefix}"

    core_ops = {"jacobi7", "laplacian27", "der1", "der2", "der_cross"}
    expected = set()
    for info in pkgutil.iter_modules(ops_pkg.__path__):
        mod = importlib.import_module(f"stencil_tpu.ops.{info.name}")
        for fname, obj in vars(mod).items():
            if fname.startswith("_") or not inspect.isfunction(obj):
                continue
            if inspect.getmodule(obj) is not mod:
                continue  # re-exports
            if fname.endswith("_pallas") or fname in core_ops:
                expected.add(f"ops.{info.name}.{fname}")
    missing = expected - set(PUBLIC_OPS)
    assert not missing, \
        f"public ops missing from the lint-coverage manifest: {sorted(missing)}"


# ---------------------------------------------------------------------------
# the analytic byte model (geometry/partition) the costmodel checker
# cross-checks against


def test_sweep_wire_bytes_matches_exchange_counter():
    """partition.sweep_wire_bytes (derived from the partition) must
    equal n_shards x parallel.exchange.exchanged_bytes_per_sweep
    (derived from one shard's padded shape) — two independent routes
    to the same model, uneven remainders included."""
    from stencil_tpu.geometry import Dim3, Radius
    from stencil_tpu.parallel.exchange import exchanged_bytes_per_sweep
    from stencil_tpu.partition import RankPartition, sweep_wire_bytes

    radius = Radius.constant(0)
    radius.set_dir((1, 0, 0), 2)
    radius.set_dir((-1, 0, 0), 1)
    radius.set_dir((0, 1, 0), 1)
    radius.set_dir((0, 0, 1), 3)
    radius.set_dir((0, 0, -1), 3)
    # 21 is not divisible by 2: x and y get +-1 remainder subdomains
    part = RankPartition.from_dim((21, 21, 16), (2, 2, 2))
    model = sweep_wire_bytes(part, radius, 4)

    dim = part.dim()
    cap = part.subdomain_size(Dim3(0, 0, 0))  # the capacity shard
    padded = cap + radius.pad_lo() + radius.pad_hi()
    per_shard = exchanged_bytes_per_sweep(
        (padded.z, padded.y, padded.x), radius, dim, 4)
    for ax in ("x", "y", "z"):
        assert model[ax] == per_shard[ax] * dim.flatten(), ax
    assert model["total"] == sum(per_shard.values()) * dim.flatten()
    # uneven capacity: ceil(21/2) = 11, and the filler rows DO ride
    # the wire (static-shape slabs), so the model must price them
    assert cap.x == 11 and cap.y == 11


# ---------------------------------------------------------------------------
# the runtime twins of the dataflow checkers: the trace-count guard
# (recompile) and the hot-loop transfer guard (transfer)


def test_assert_single_compile_guard():
    import jax
    import jax.numpy as jnp

    from stencil_tpu.analysis.recompile import (RecompileGuardError,
                                                assert_single_compile)

    fn = jax.jit(lambda x: x + 1.0)
    # one fingerprint, many dispatches: fine
    with assert_single_compile(fn, "unit"):
        fn(jnp.zeros((4,), jnp.float32))
        fn(jnp.ones((4,), jnp.float32))
    # a second fingerprint inside the block: the recompile loop
    with pytest.raises(RecompileGuardError, match="re-traced"):
        with assert_single_compile(fn, "unit"):
            fn(jnp.zeros((8,), jnp.float32))
            fn(jnp.zeros((16,), jnp.float32))


def test_single_compile_guard_cross_dispatch():
    import jax
    import jax.numpy as jnp

    from stencil_tpu.analysis.recompile import (RecompileGuardError,
                                                SingleCompileGuard)

    fn = jax.jit(lambda x: x * 2.0)
    guard = SingleCompileGuard()
    fn(jnp.zeros((4,), jnp.float32))
    guard.observe(fn, "unit")
    fn(jnp.ones((4,), jnp.float32))
    guard.observe(fn, "unit")  # same fingerprint: cache flat, fine
    fn(jnp.zeros((8,), jnp.float32))  # fingerprint drift
    with pytest.raises(RecompileGuardError, match="recompiling"):
        guard.observe(fn, "unit")


def test_hot_loop_transfer_guard_blocks_implicit(monkeypatch):
    import contextlib

    import jax.numpy as jnp
    import numpy as np

    from stencil_tpu.analysis.transfer import (ALLOW_TRANSFERS_ENV,
                                               hot_loop_transfer_guard)

    monkeypatch.delenv(ALLOW_TRANSFERS_ENV, raising=False)
    with pytest.raises(Exception, match="[Dd]isallow"):
        with hot_loop_transfer_guard():
            _ = jnp.asarray(np.ones((4,), np.float32)) + 1.0
    # the escape hatch turns the guard into a no-op
    monkeypatch.setenv(ALLOW_TRANSFERS_ENV, "1")
    guard = hot_loop_transfer_guard()
    assert isinstance(guard, contextlib.nullcontext)
    with guard:
        _ = jnp.asarray(np.ones((4,), np.float32)) + 1.0


def test_fused_driver_single_compile_under_guard(monkeypatch, tmp_path):
    """The driver wiring: a fused resilient run under
    STENCIL_ASSERT_SINGLE_COMPILE=1 (and the always-on transfer guard)
    completes — the megastep programs never re-trace mid-campaign."""
    import numpy as np

    from stencil_tpu.analysis.recompile import ASSERT_SINGLE_COMPILE_ENV
    from stencil_tpu.models.jacobi import Jacobi3D
    from stencil_tpu.resilience import ResiliencePolicy

    monkeypatch.setenv(ASSERT_SINGLE_COMPILE_ENV, "1")
    j = Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float32,
                 kernel="xla")
    j.init()
    policy = ResiliencePolicy(check_every=2, ckpt_every=4,
                              fuse_segments=True)
    report = j.run_resilient(8, policy=policy,
                             ckpt_dir=str(tmp_path / "ckpt"))
    assert report.steps == 8 and report.rollbacks == 0


def test_halo_byte_model_counts_face_edge_corner():
    from stencil_tpu.geometry import Radius
    from stencil_tpu.partition import RankPartition, halo_byte_model

    part = RankPartition.from_dim((8, 8, 8), (2, 2, 2))
    model = halo_byte_model(part, Radius.constant(1), 4)
    # 8 subdomains of 4^3: per subdomain 6 faces x 16 cells,
    # 12 edges x 4 cells, 8 corners x 1 cell, 4 B elements
    assert model["face"] == 8 * 6 * 16 * 4
    assert model["edge"] == 8 * 12 * 4 * 4
    assert model["corner"] == 8 * 8 * 1 * 4
    assert model["total"] == sum(
        model[k] for k in ("face", "edge", "corner"))
    # zero edge/corner radius -> only faces priced (the reference's
    # "edge radius gates diagonal exchanges" rule)
    fo = halo_byte_model(part, Radius.face_edge_corner(1, 0, 0), 4)
    assert fo["edge"] == fo["corner"] == 0 and fo["face"] == model["face"]
    # a 1-subdomain axis is an in-core wrap: no wire bytes for any
    # direction that uses it
    flat = RankPartition.from_dim((8, 8, 8), (1, 2, 2))
    m2 = halo_byte_model(flat, Radius.constant(1), 4)
    assert m2["corner"] == 0  # corners all need the x axis
    # 4 subdomains of (8,4,4): 4 y/z faces x 8*4 cells each
    assert m2["face"] == 4 * 4 * 32 * 4
