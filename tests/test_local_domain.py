"""Halo-geometry tests mirroring reference test/test_cuda_local_domain.cu
pinned cases (symmetric and asymmetric radius, face/edge/corner)."""

import numpy as np

from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.local_domain import (LocalDomain, get_exterior, get_interior,
                                      halo_extent, halo_pos, raw_size)


class TestHaloGeometry:
    def test_raw_size_symmetric(self):
        r = Radius.constant(2)
        assert raw_size((10, 10, 10), r) == Dim3(14, 14, 14)

    def test_raw_size_asymmetric(self):
        # uncentered kernel: +x radius 3 only
        r = Radius.constant(0)
        r.set_dir((1, 0, 0), 3)
        assert raw_size((10, 10, 10), r) == Dim3(13, 10, 10)

    def test_halo_pos_symmetric(self):
        # reference: src/local_domain.cu:86-125 halo_pos
        sz = Dim3(10, 10, 10)
        r = Radius.constant(2)
        # +x halo begins past lo pad + interior
        assert halo_pos((1, 0, 0), sz, r, halo=True) == Dim3(12, 2, 2)
        # +x interior-edge region (exterior compute) begins at sz.x offset
        assert halo_pos((1, 0, 0), sz, r, halo=False) == Dim3(10, 2, 2)
        assert halo_pos((-1, 0, 0), sz, r, halo=True) == Dim3(0, 2, 2)
        assert halo_pos((-1, 0, 0), sz, r, halo=False) == Dim3(2, 2, 2)
        assert halo_pos((0, 0, 0), sz, r, halo=True) == Dim3(2, 2, 2)

    def test_halo_extent_uses_face_radii(self):
        # reference: local_domain.cuh:212-222 — edge/corner extents are
        # built from face radii, not the edge/corner radius values
        sz = Dim3(10, 12, 14)
        r = Radius.face_edge_corner(2, 1, 1)
        assert halo_extent((1, 0, 0), sz, r) == Dim3(2, 12, 14)
        assert halo_extent((1, 1, 0), sz, r) == Dim3(2, 2, 14)
        assert halo_extent((1, 1, 1), sz, r) == Dim3(2, 2, 2)
        assert halo_extent((0, 0, 0), sz, r) == sz

    def test_halo_extent_asymmetric(self):
        sz = Dim3(10, 10, 10)
        r = Radius.constant(0)
        r.set_dir((1, 0, 0), 3)
        assert halo_extent((1, 0, 0), sz, r) == Dim3(3, 10, 10)
        assert halo_extent((-1, 0, 0), sz, r) == Dim3(0, 10, 10)


class TestLocalDomain:
    def _make(self, sz=(8, 8, 8), r=None):
        dom = LocalDomain(sz, (0, 0, 0), r or Radius.constant(1))
        dom.add_data("q0", np.float32)
        dom.add_data("q1", np.float64)
        dom.realize()
        return dom

    def test_realize_shapes(self):
        dom = self._make()
        assert dom.curr["q0"].shape == (10, 10, 10)
        assert dom.curr["q1"].dtype == np.float64
        assert dom.num_data() == 2
        assert dom.elem_size("q0") == 4
        assert dom.elem_size("q1") == 8

    def test_swap(self):
        dom = self._make()
        dom.curr["q0"] = dom.curr["q0"] + 1.0
        dom.swap()
        assert float(dom.curr["q0"][0, 0, 0]) == 0.0
        assert float(dom.next_["q0"][0, 0, 0]) == 1.0

    def test_halo_bytes(self):
        dom = self._make()
        # radius-1 +x face: 1*8*8 points
        assert dom.halo_bytes((1, 0, 0), "q0") == 4 * 1 * 8 * 8
        assert dom.halo_bytes((1, 0, 0), "q1") == 8 * 1 * 8 * 8

    def test_accessor_global_coords(self):
        dom = LocalDomain((4, 4, 4), (10, 20, 30), Radius.constant(1))
        dom.add_data("q", np.float32)
        dom.realize()
        dom.curr["q"] = dom.curr["q"].at[1 + 2, 1 + 1, 1 + 3].set(7.0)
        acc = dom.get_curr_accessor("q")
        # global coord = origin + local interior offset (x=3,y=1,z=2)
        assert float(acc[(13, 21, 32)]) == 7.0
        # halo cells are addressable (origin shifted by pad_lo)
        assert float(acc[(9, 19, 29)]) == 0.0

    def test_halo_coords(self):
        dom = LocalDomain((4, 4, 4), (10, 20, 30), Radius.constant(1))
        rect = dom.halo_coords((1, 0, 0), halo=True)
        assert rect.lo == Dim3(14, 20, 30)
        assert rect.extent() == Dim3(1, 4, 4)
        rect = dom.halo_coords((-1, 0, 0), halo=False)
        assert rect.lo == Dim3(10, 20, 30)
        assert rect.extent() == Dim3(1, 4, 4)

    def test_halo_coords_asymmetric_send_region(self):
        # send region width must be the receiver's opposite halo
        # (reference pairing: src/packer.cu:116-118)
        r = Radius.constant(0)
        r.set_dir((1, 0, 0), 2)   # +x halo is 2 wide
        r.set_dir((-1, 0, 0), 1)  # -x halo is 1 wide
        dom = LocalDomain((10, 10, 10), (0, 0, 0), r)
        # sending in +x fills the neighbor's -x halo (width 1): last
        # interior plane only, and stays inside the compute region
        rect = dom.halo_coords((1, 0, 0), halo=False)
        assert rect.lo == Dim3(9, 0, 0)
        assert rect.hi == Dim3(10, 10, 10)
        # the +x halo region itself is width 2
        rect = dom.halo_coords((1, 0, 0), halo=True)
        assert rect.lo == Dim3(10, 0, 0)
        assert rect.hi == Dim3(12, 10, 10)


class TestInteriorExterior:
    def test_interior_symmetric(self):
        # reference: src/stencil.cu:874-921
        dom = LocalDomain((10, 10, 10), (0, 0, 0), Radius.constant(2))
        inter = get_interior(dom)
        assert inter.lo == Dim3(2, 2, 2)
        assert inter.hi == Dim3(8, 8, 8)

    def test_interior_respects_diagonal_radii(self):
        r = Radius.face_edge_corner(1, 1, 3)
        dom = LocalDomain((10, 10, 10), (0, 0, 0), r)
        inter = get_interior(dom)
        # corner radius 3 dominates
        assert inter.lo == Dim3(3, 3, 3)
        assert inter.hi == Dim3(7, 7, 7)

    def test_exterior_tiles_complement(self):
        dom = LocalDomain((10, 10, 10), (5, 5, 5), Radius.constant(2))
        inter = get_interior(dom)
        exts = get_exterior(dom)
        # exterior slabs + interior must tile the compute region exactly
        vol = sum(r.extent().flatten() for r in exts)
        assert vol + inter.extent().flatten() == 1000
        # non-overlap: pairwise disjoint
        boxes = exts + [inter]
        for i in range(len(boxes)):
            for j in range(i + 1, len(boxes)):
                a, b = boxes[i], boxes[j]
                lo = a.lo.elementwise_max(b.lo)
                hi = a.hi.elementwise_min(b.hi)
                assert (hi - lo).any_lt(1)

    def test_exterior_asymmetric(self):
        r = Radius.constant(0)
        r.set_dir((1, 0, 0), 2)
        dom = LocalDomain((10, 10, 10), (0, 0, 0), r)
        inter = get_interior(dom)
        assert inter.lo == Dim3(0, 0, 0)
        assert inter.hi == Dim3(8, 10, 10)
        exts = get_exterior(dom)
        assert len(exts) == 1
        assert exts[0].lo == Dim3(8, 0, 0)
        assert exts[0].hi == Dim3(10, 10, 10)
