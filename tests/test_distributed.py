"""DistributedDomain orchestrator tests: the end-to-end ripple oracle
through the public API (mirrors reference
test/test_cuda_mpi_distributed_domain.cu and test_exchange.cu)."""

import numpy as np
import pytest

import jax

from stencil_tpu.distributed import DistributedDomain
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel.methods import Method
from stencil_tpu.placement import PlacementStrategy

RIPPLE = [1.0, 0.25, 0.5, 0.75]


def ripple_grid(size: Dim3) -> np.ndarray:
    z, y, x = np.meshgrid(np.arange(size.z), np.arange(size.y),
                          np.arange(size.x), indexing="ij")
    r = np.array(RIPPLE)
    return ((x + r[x % 4]) + (y + r[y % 4]) + (z + r[z % 4])).astype(np.float64)


def check_dd_halos(dd: DistributedDomain, name: str, oracle: np.ndarray):
    """Every halo cell of every shard must equal oracle[wrap(global)]."""
    from stencil_tpu.local_domain import raw_size
    dim = dd.placement.dim()
    local = dd.local_size
    pr = raw_size(local, dd.radius)
    lo = dd.radius.pad_lo()
    host = np.asarray(dd.curr[name])
    gs = dd.size
    for bz in range(dim.z):
        for by in range(dim.y):
            for bx in range(dim.x):
                blk = host[bz * pr.z:(bz + 1) * pr.z,
                           by * pr.y:(by + 1) * pr.y,
                           bx * pr.x:(bx + 1) * pr.x]
                for lz in range(pr.z):
                    for ly in range(pr.y):
                        for lx in range(pr.x):
                            gx = (bx * local.x + lx - lo.x) % gs.x
                            gy = (by * local.y + ly - lo.y) % gs.y
                            gz = (bz * local.z + lz - lo.z) % gs.z
                            assert blk[lz, ly, lx] == pytest.approx(
                                oracle[gz, gy, gx]), (
                                f"block ({bx},{by},{bz}) local ({lx},{ly},{lz})")


@pytest.mark.parametrize("strategy", [PlacementStrategy.Trivial,
                                      PlacementStrategy.NodeAware,
                                      PlacementStrategy.IntraNodeRandom])
def test_exchange_oracle_8dev(strategy):
    dd = DistributedDomain(8, 8, 8)
    dd.set_radius(1)
    dd.add_data("q0", np.float64)
    dd.set_placement(strategy)
    dd.realize()
    oracle = ripple_grid(dd.size)
    dd.set_interior("q0", oracle)
    dd.exchange()
    check_dd_halos(dd, "q0", oracle)


def test_exchange_multi_quantity_methods():
    for method in (Method.PpermuteSlab, Method.PpermutePacked):
        dd = DistributedDomain(8, 8, 8)
        dd.set_radius(2)
        dd.set_methods(method)
        dd.add_data("a", np.float32)
        dd.add_data("b", np.float64)
        dd.realize()
        oracle = ripple_grid(dd.size)
        dd.set_interior("a", oracle.astype(np.float32))
        dd.set_interior("b", oracle * 3.0)
        dd.exchange()
        check_dd_halos(dd, "b", oracle * 3.0)


def test_roundtrip_interior():
    dd = DistributedDomain(8, 8, 8)
    dd.set_radius(1)
    dd.add_data("q", np.float64)
    dd.realize()
    oracle = ripple_grid(dd.size)
    dd.set_interior("q", oracle)
    np.testing.assert_array_equal(dd.interior_to_host("q"), oracle)


def test_swap_double_buffer():
    dd = DistributedDomain(8, 8, 8)
    dd.set_radius(1)
    dd.add_data("q", np.float64)
    dd.realize()
    oracle = ripple_grid(dd.size)
    dd.set_interior("q", oracle)
    dd.swap()
    assert float(dd.interior_to_host("q").max()) == 0.0
    dd.swap()
    np.testing.assert_array_equal(dd.interior_to_host("q"), oracle)


def test_interior_exterior_queries():
    dd = DistributedDomain(8, 8, 8)
    dd.set_radius(2)
    dd.add_data("q", np.float32)
    dd.realize()
    inters = dd.get_interior()
    exts = dd.get_exterior()
    assert len(inters) == 8 and len(exts) == 8
    local_vol = dd.local_size.flatten()
    for i in range(8):
        vol = inters[i].extent().flatten() + sum(
            r.extent().flatten() for r in exts[i])
        assert vol == local_vol


def test_plan_files(tmp_path):
    dd = DistributedDomain(8, 8, 8)
    dd.set_radius(1)
    dd.add_data("q", np.float32)
    dd.set_output_prefix(str(tmp_path) + "/")
    dd.realize()
    plan = (tmp_path / "plan.txt").read_text()
    assert "mesh" in plan and "bytes per shard" in plan
    mat = np.loadtxt(tmp_path / "comm_matrix.txt")
    assert mat.shape == (8, 8)
    # radius-1 f32, 4^3 local: each face message is 4*4*1*4 bytes = 64
    assert mat[0, 1] > 0
    # per-message detail (reference: src/stencil.cu:523-637): one line
    # per planned cross-shard message, consistent with the matrix
    msgs = [l for l in plan.splitlines() if l.startswith("message ")]
    assert any(l.startswith("message 0 -> 1 ") and l.endswith("B")
               for l in msgs), msgs[:3]
    m01 = sum(int(l.split(":")[1].split()[0]) for l in msgs
              if l.startswith("message 0 -> 1 "))
    assert m01 == mat[0, 1], (m01, mat[0, 1])
    assert np.all(mat.diagonal() == 0)


def test_exchange_bytes_counters():
    dd = DistributedDomain(8, 8, 8)
    dd.set_radius(1)
    dd.add_data("q", np.float32)
    dd.realize()
    b = dd.exchange_bytes_per_axis()
    # 2x2x2 mesh, local 4^3 padded to 6^3: x axis moves 2*6*6*4 bytes
    assert b["x"] == 2 * 6 * 6 * 4
    assert dd.exchange_bytes_total() == sum(b.values()) * 8


def test_paraview_dump(tmp_path):
    dd = DistributedDomain(4, 4, 4)
    dd.set_radius(1)
    dd.set_mesh_shape((2, 2, 2))
    dd.add_data("q", np.float64)
    dd.realize()
    oracle = ripple_grid(dd.size)
    dd.set_interior("q", oracle)
    dd.write_paraview(str(tmp_path) + "/out")
    files = sorted(tmp_path.glob("out*.txt"))
    assert len(files) == 8
    header = files[0].read_text().splitlines()[0]
    assert header == "Z,Y,X,q"


def test_placement_order_survives_mesh():
    # regression: make_mesh must not re-sort an explicit device order,
    # else QAP/random placements silently never take effect
    dd = DistributedDomain(8, 8, 8)
    dd.set_radius(1)
    dd.add_data("q", np.float32)
    dd.set_placement(PlacementStrategy.IntraNodeRandom)
    dd.realize()
    part = dd.placement.part
    for i in range(8):
        idx = part.dimensionize(i)
        want = dd.placement.get_device(idx)
        got = dd.mesh.devices[idx.x, idx.y, idx.z]
        assert want == got, (i, want, got)


def test_rejects_bad_configs():
    dd = DistributedDomain(7, 7, 7)
    dd.set_radius(1)
    dd.add_data("q", np.float32)
    dd.realize()  # 7^3 over 8 devices: uneven (+-1) subdomains
    assert dd.rem != (0, 0, 0)

    dd = DistributedDomain(4, 4, 4)
    dd.set_radius(1)
    dd.add_data("q", np.float32)
    dd.set_mesh_shape((2, 2, 1))  # 4 != 8 devices
    with pytest.raises(ValueError):
        dd.realize()

    dd = DistributedDomain(8, 8, 8)
    dd.set_radius(8)  # radius larger than 4^3 subdomain
    dd.add_data("q", np.float32)
    with pytest.raises(ValueError):
        dd.realize()


def test_fast_path_exchange_stats():
    """The models' per-iteration exchange accounting must (a) agree
    between the pair and sequential MHD halo paths (same wire bytes,
    the pair's whole point), (b) match interior_slab_bytes exactly,
    and (c) produce a positive standalone timing — the honest fast-path
    stats the orchestrator counters cannot provide (reference:
    src/stencil.cu:1005-1008,1174-1181)."""
    import os

    import jax

    from stencil_tpu.models.astaroth import FIELDS, Astaroth
    from stencil_tpu.models.jacobi import Jacobi3D
    from stencil_tpu.parallel.exchange import interior_slab_bytes
    from stencil_tpu.parallel.mesh import mesh_dim

    prior = os.environ.get("STENCIL_MHD_PAIR")
    os.environ["STENCIL_MHD_PAIR"] = "1"
    try:
        a = Astaroth(16, 8, 16, mesh_shape=(1, 1, 2), dtype=np.float64,
                     devices=jax.devices()[:2], kernel="halo")
    finally:
        if prior is None:
            os.environ.pop("STENCIL_MHD_PAIR", None)
        else:
            os.environ["STENCIL_MHD_PAIR"] = prior
    b = Astaroth(16, 8, 16, mesh_shape=(1, 1, 2), dtype=np.float64,
                 devices=jax.devices()[:2], kernel="halo")
    sa, sb = a.exchange_stats(), b.exchange_stats()
    assert (sa["rounds_per_iteration"], sb["rounds_per_iteration"]) == (2.0, 3.0)
    assert sa["bytes_per_iteration"] == sb["bytes_per_iteration"]
    counts = mesh_dim(b.dd.mesh)
    local = b.dd.local_size
    per = interior_slab_bytes((local.z, local.y, local.x), counts, 3, 8,
                              y_z_extended=True)
    assert sb["bytes_per_iteration"] == 3 * per * 2 * len(FIELDS)
    assert b.measure_exchange_seconds(reps=2) > 0

    j = Jacobi3D(16, 16, 16, mesh_shape=(1, 2, 2), dtype=np.float32,
                 devices=jax.devices()[:4], kernel="halo")
    js = j.exchange_stats()
    assert js["path"] == "halo"
    assert js["rounds_per_iteration"] == 0.5     # 2-step groups
    assert j.measure_exchange_seconds(reps=2) > 0
    w = Jacobi3D(16, 16, 16, mesh_shape=(1, 1, 1),
                 devices=jax.devices()[:1], kernel="wrap",
                 dtype=np.float32)
    assert w.exchange_stats()["bytes_per_iteration"] == 0
    assert w.measure_exchange_seconds() == 0.0
