"""Communication-avoiding temporal blocking (parallel/temporal.py).

The load-bearing property: ``s``-blocked stepping (one depth-``s*r``
exchange per ``s`` steps, sub-steps on shrinking windows) is
numerically identical to step-by-step stepping — on uneven (+-1
remainder) partitions, for periodic AND non-periodic (zero-Dirichlet
exterior) boundaries, including tail steps that don't fill a group.
Jacobi is pinned BITWISE (pure add/mul arithmetic is shape-invariant);
MHD is pinned to ~1 ULP (the rate expressions contain ``exp``, whose
CPU vectorization may differ by 1 ULP between the window-shaped and
full-shard programs — measured max 1.3e-18 absolute on O(1) fields).
"""

import numpy as np
import pytest

from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.models.jacobi import Jacobi3D
from stencil_tpu.parallel.methods import Method
from stencil_tpu.topology import Boundary

BOUNDARIES = [Boundary.PERIODIC, Boundary.NONE]


# ---------------------------------------------------------------------------
# fuser geometry units


def test_deepened_radius():
    r = Radius.constant(0)
    r.set_dir((1, 0, 0), 2)
    r.set_dir((0, -1, 0), 1)
    r.set_dir((1, 1, 0), 1)
    d = r.deepened(3)
    assert d.dir((1, 0, 0)) == 6
    assert d.dir((0, -1, 0)) == 3
    assert d.dir((1, 1, 0)) == 3       # edge radii deepen too
    assert d.dir((0, 0, 1)) == 0       # zero stays zero
    assert r.deepened(1) == r
    with pytest.raises(ValueError):
        r.deepened(0)


def test_sub_step_windows_shrink_to_interior():
    from stencil_tpu.parallel.temporal import sub_step_windows

    r = Radius.constant(1)
    cap = Dim3(8, 6, 4)
    w = sub_step_windows(r, cap, 3)
    assert w[0] == (Dim3(-2, -2, -2), Dim3(12, 10, 8))
    assert w[1] == (Dim3(-1, -1, -1), Dim3(10, 8, 6))
    assert w[2] == (Dim3(0, 0, 0), cap)
    # asymmetric: only padded sides expand
    ra = Radius.constant(0)
    ra.set_dir((1, 0, 0), 2)
    ra.set_dir((0, -1, 0), 1)
    wa = sub_step_windows(ra, cap, 2)
    assert wa[0] == (Dim3(0, -1, 0), Dim3(10, 7, 4))


def test_validate_temporal_rejects_thin_shards():
    from stencil_tpu.parallel.temporal import validate_temporal

    r = Radius.constant(1)
    validate_temporal(r, Dim3(4, 4, 4), 4)
    with pytest.raises(ValueError):
        validate_temporal(r, Dim3(4, 4, 4), 5)
    # uneven: the SHORT shard must host the deep slab
    with pytest.raises(ValueError):
        validate_temporal(r, Dim3(4, 4, 4), 4, rem=Dim3(1, 0, 0))


# ---------------------------------------------------------------------------
# Jacobi: bitwise equivalence on uneven partitions, both boundaries


@pytest.mark.parametrize("boundary", BOUNDARIES)
def test_jacobi_blocked_bitwise_uneven(boundary):
    """s-blocked == step-by-step BITWISE across s in {1, 2, 4} on a
    17-point x axis over a 2x2x2 mesh (9/8-point uneven shards); 5
    iterations so s=2 and s=4 both exercise a partial tail group."""
    base = Jacobi3D(17, 8, 8, mesh_shape=(2, 2, 2), dtype=np.float64,
                    kernel="xla", boundary=boundary)
    assert base.dd.rem == Dim3(1, 0, 0)
    base.init()
    base.run(5)
    ref = base.temperature()
    for s in (1, 2, 4):
        j = Jacobi3D(17, 8, 8, mesh_shape=(2, 2, 2), dtype=np.float64,
                     kernel="xla", boundary=boundary, exchange_every=s)
        j.init()
        j.run(5)
        np.testing.assert_array_equal(j.temperature(), ref)
        if s > 1:
            assert j.kernel_path == f"xla-temporal[s={s}]"
            stats = j.exchange_stats()
            assert stats["rounds_per_iteration"] == pytest.approx(1.0 / s)
            assert j.dd.exchange_bytes_amortized_per_step() == \
                j.dd.exchange_bytes_total() / s


def test_jacobi_blocked_packed_method():
    """The deep exchange through the PpermutePacked data path (uneven
    shards): one packed buffer per direction carries the s*r slabs."""
    base = Jacobi3D(17, 8, 8, mesh_shape=(2, 2, 2), dtype=np.float64,
                    kernel="xla", methods=Method.PpermutePacked)
    base.init()
    base.run(4)
    j = Jacobi3D(17, 8, 8, mesh_shape=(2, 2, 2), dtype=np.float64,
                 kernel="xla", methods=Method.PpermutePacked,
                 exchange_every=2)
    j.init()
    j.run(4)
    np.testing.assert_array_equal(j.temperature(), base.temperature())


def test_jacobi_blocked_overlap_even():
    """Overlap composition: the deep exchange hides behind sub-step 0's
    interior block; values stay bitwise identical (even shards)."""
    base = Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float64,
                    kernel="xla")
    base.init()
    base.run(4)
    j = Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float64,
                 kernel="xla", exchange_every=2, overlap=True)
    assert j.kernel_path == "xla-temporal[s=2]-overlap"
    j.init()
    j.run(4)
    np.testing.assert_array_equal(j.temperature(), base.temperature())


def test_jacobi_blocked_single_chip_wrap():
    """1-device mesh: the deep 'exchange' degenerates to local periodic
    wraps of depth s*r — blocking must still match step-by-step."""
    import jax

    dev = jax.devices()[:1]
    base = Jacobi3D(8, 8, 8, mesh_shape=(1, 1, 1), devices=dev,
                    dtype=np.float64, kernel="xla")
    base.init()
    base.run(3)
    j = Jacobi3D(8, 8, 8, mesh_shape=(1, 1, 1), devices=dev,
                 dtype=np.float64, kernel="xla", exchange_every=2)
    j.init()
    j.run(3)
    np.testing.assert_array_equal(j.temperature(), base.temperature())


def test_jacobi_blocked_rejects_infeasible_depth():
    with pytest.raises(ValueError):
        Jacobi3D(8, 8, 8, mesh_shape=(2, 2, 2), dtype=np.float64,
                 kernel="xla", exchange_every=5)  # 5 > 4-point shards


# ---------------------------------------------------------------------------
# MHD: RK3 substep blocking (w rides the deep exchange when a group
# starts at an alpha != 0 substep)


def _mhd_pair(s, boundary, size, iters):
    import jax

    from stencil_tpu.models.astaroth import Astaroth, FIELDS

    devs = jax.devices()[:2]
    base = Astaroth(*size, mesh_shape=(1, 1, 2), dtype=np.float64,
                    devices=devs, kernel="xla",
                    methods=Method.PpermuteSlab, boundary=boundary)
    base.init()
    base.run(iters)
    refs = {q: base.field(q) for q in FIELDS}
    b = Astaroth(*size, mesh_shape=(1, 1, 2), dtype=np.float64,
                 devices=devs, kernel="xla", methods=Method.PpermuteSlab,
                 boundary=boundary, exchange_every=s)
    assert b.kernel_path == f"xla-temporal[s={s}]"
    b.init()
    b.run(iters)
    for q in FIELDS:
        # exp() in the rates may differ by 1 ULP between window shapes;
        # measured max 1.3e-18 absolute on O(1) fields (see module doc)
        np.testing.assert_allclose(b.field(q), refs[q], rtol=1e-12,
                                   atol=1e-16, err_msg=q)
    return b


@pytest.mark.parametrize("boundary", BOUNDARIES)
def test_mhd_blocked_matches_stepwise_uneven(boundary):
    """s=2 substep blocking on an uneven 7/6-point z split: groups
    straddle iterations (period lcm(3,2)=6 substeps), so two of three
    groups start at alpha != 0 and ship w in the deep exchange."""
    b = _mhd_pair(2, boundary, (8, 8, 13), iters=3)
    assert b.dd.rem == Dim3(0, 0, 1)
    stats = b.exchange_stats()
    # 3 groups per 2 iterations; groups starting at substeps 2 and 1
    # carry w (2x bytes), the substep-0 group carries fields only
    assert stats["rounds_per_iteration"] == pytest.approx(1.5)
    per_ex = b.dd.exchange_bytes_total()
    assert stats["bytes_per_iteration"] == pytest.approx(
        (per_ex + 2 * per_ex + 2 * per_ex) / 2)


@pytest.mark.slow
def test_mhd_blocked_s4_matches_stepwise():
    """s=4 (deep radius 12): period lcm(3,4)=12 substeps = 4
    iterations; 5 iterations exercise a full period + a tail."""
    _mhd_pair(4, Boundary.PERIODIC, (12, 12, 26), iters=5)


def test_checkpoint_roundtrip_with_deep_allocation(tmp_path):
    """save/restore must extract/insert at the ALLOC pads (s*r), not
    the stencil radius — a blocked domain's checkpoint restores bitwise
    onto blocked AND unblocked domains (regression: _interior_fns used
    dd.radius and sliced shifted, halo-contaminated interiors)."""
    from stencil_tpu.utils.checkpoint import restore_domain, save_domain

    j = Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float64,
                 kernel="xla", exchange_every=2)
    j.init()
    j.run(3)
    want = j.temperature()
    save_domain(j.dd, str(tmp_path), step=3)
    k = Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float64,
                 kernel="xla", exchange_every=2)
    step, _ = restore_domain(k.dd, str(tmp_path))
    assert step == 3
    np.testing.assert_array_equal(k.temperature(), want)
    # cross-depth: blocked save -> plain per-step domain
    m = Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float64,
                 kernel="xla")
    restore_domain(m.dd, str(tmp_path))
    np.testing.assert_array_equal(m.temperature(), want)


def test_set_exchange_every_after_realize_raises():
    from stencil_tpu.distributed import DistributedDomain

    dd = DistributedDomain(8, 8, 8)
    dd.set_mesh_shape((2, 2, 2))
    dd.set_radius(1)
    dd.add_data("q", np.float64)
    dd.realize()
    with pytest.raises(RuntimeError):
        dd.set_exchange_every(2)


def test_jacobi_asym_depths_bitwise_even_and_uneven():
    """Per-axis {z: 4, y: 1, x: 1}: z rides a depth-4r slab refreshed
    once per 4 steps while x/y refresh every sub-step — bitwise equal
    to stepwise on even 16^3 AND uneven 17^3 (rem (1,1,1)), periodic
    and zero-Dirichlet, 5 iterations so the tail group is partial."""
    for size in ((16, 16, 16), (17, 17, 17)):
        for boundary in BOUNDARIES:
            base = Jacobi3D(*size, mesh_shape=(2, 2, 2),
                            dtype=np.float64, kernel="xla",
                            boundary=boundary)
            base.init()
            base.run(5)
            ref = base.temperature()
            j = Jacobi3D(*size, mesh_shape=(2, 2, 2), dtype=np.float64,
                         kernel="xla", boundary=boundary,
                         exchange_every={"z": 4, "y": 1, "x": 1})
            assert j.kernel_path == "xla-temporal[s=1.1.4]"
            j.init()
            j.run(5)
            np.testing.assert_array_equal(j.temperature(), ref)
            stats = j.exchange_stats()
            # x's cadence-1 refresh rides every sub-step, so dispatches
            # stay at one round per iteration — the win is the deep z
            # slab shipping (and paying its DCN alpha) only once per 4
            assert stats["rounds_per_iteration"] == pytest.approx(1.0)


def test_jacobi_asym_depths_packed_method_bitwise():
    """Asymmetric depths through the PpermutePacked data path (uneven
    shards): the mid-group x/y refreshes ride the packed buffers."""
    base = Jacobi3D(17, 8, 8, mesh_shape=(2, 2, 2), dtype=np.float64,
                    kernel="xla", methods=Method.PpermutePacked)
    base.init()
    base.run(4)
    j = Jacobi3D(17, 8, 8, mesh_shape=(2, 2, 2), dtype=np.float64,
                 kernel="xla", methods=Method.PpermutePacked,
                 exchange_every={"x": 2})
    assert j.kernel_path == "xla-temporal[s=2.1.1]"
    j.init()
    j.run(4)
    np.testing.assert_array_equal(j.temperature(), base.temperature())


def test_asym_depths_decline_loudly():
    """The unsupported compositions must raise NotImplementedError at
    construction/realize — never a silent fall back to symmetric
    blocking or stepwise exchange."""
    asym = {"z": 2, "y": 1, "x": 1}
    # the Pallas in-kernel multi-step paths have ONE step count
    for kernel in ("wrap", "halo", "pallas"):
        with pytest.raises(NotImplementedError,
                           match="asymmetric temporal depths"):
            Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2),
                     dtype=np.float64, kernel=kernel,
                     exchange_every=asym)
    # the overlap composition assumes one group-wide deep exchange
    with pytest.raises(NotImplementedError, match="overlap"):
        Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float64,
                 kernel="xla", exchange_every=asym, overlap=True)
    # the irredundant dedup plan assumes one group-wide exchange whose
    # slabs carry the halo-of-halo rows mid-group refreshes rely on
    with pytest.raises(NotImplementedError, match="wire_layout"):
        Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float64,
                 kernel="xla", exchange_every=asym,
                 wire_layout="irredundant")
    # each axis's cadence must divide the group length
    with pytest.raises(ValueError):
        Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float64,
                 kernel="xla", exchange_every={"z": 4, "y": 3, "x": 1})


def test_mhd_exchange_every_one_is_stepwise():
    import jax

    from stencil_tpu.models.astaroth import Astaroth

    b = Astaroth(8, 8, 8, mesh_shape=(1, 1, 2), devices=jax.devices()[:2],
                 dtype=np.float64, kernel="xla",
                 methods=Method.PpermuteSlab, exchange_every=1)
    assert b.kernel_path == "xla"
