"""The campaign service: admission, caching, tenancy, resilience.

Covers the serving acceptance contract: concurrent campaigns from
distinct tenants complete with isolated checkpoint namespaces,
fingerprint-identical requests compile and measure nothing, and a
per-member fault rolls back only the affected campaign.
"""

import numpy as np
import pytest

from stencil_tpu.serving import (CampaignRequest, CampaignService,
                                 RequestQueue)
from stencil_tpu.serving.queue import request_fingerprint
from stencil_tpu.serving.service import CampaignFailed
from stencil_tpu.tuning import FakeTimer

MESH = (2, 2, 2)
GRID = (8, 8, 8)


def req(tenant="t0", campaign="c0", **kw):
    kw.setdefault("grid", GRID)
    kw.setdefault("n_steps", 4)
    kw.setdefault("ckpt_every", 2)
    return CampaignRequest(tenant=tenant, campaign=campaign, **kw)


def service(tmp_path, **kw):
    kw.setdefault("width", 4)
    kw.setdefault("tuner_timer", FakeTimer())
    kw.setdefault("plan_cache_path", str(tmp_path / "plans.json"))
    return CampaignService(str(tmp_path / "root"), **kw)


# ---------------------------------------------------------------------------
# requests, fingerprints, admission


def test_request_validation_rejects_traversal_ids():
    for bad in ("", "..", ".", "a/b", "a\\b", "x\x00y", "a\nb"):
        with pytest.raises(ValueError):
            req(tenant=bad).validate()
        with pytest.raises(ValueError):
            req(campaign=bad).validate()
    req(tenant="tenant-1.prod_a", campaign="run..01").validate()


def test_fingerprint_groups_compatible_requests():
    fp0 = request_fingerprint(req(tenant="a"))
    fp1 = request_fingerprint(req(tenant="b", n_steps=99,
                                  params={"hot_temp": 2.0}))
    assert fp0 == fp1  # tenant/steps/params don't change the program
    assert fp0 != request_fingerprint(req(grid=(16, 8, 8)))
    assert fp0 != request_fingerprint(req(dtype="float64"))
    assert fp0 != request_fingerprint(req(model="astaroth"))
    assert fp0 != request_fingerprint(req(boundary="NONE"))


def test_queue_pop_batch_groups_by_fingerprint():
    q = RequestQueue()
    a0 = q.submit(req(tenant="a"))
    q.submit(req(tenant="big", grid=(16, 8, 8)))
    a1 = q.submit(req(tenant="b"))
    batch = q.pop_batch(width=4)
    assert [e.handle for e in batch] == [a0, a1]
    assert len(q) == 1  # the other fingerprint kept its place
    assert q.pop_batch(width=4)[0].request.tenant == "big"


def test_queue_pop_batch_respects_width():
    q = RequestQueue()
    for i in range(5):
        q.submit(req(tenant=f"t{i}"))
    assert len(q.pop_batch(width=3)) == 3
    assert len(q.pop_batch(width=3)) == 2


# ---------------------------------------------------------------------------
# the service


def test_concurrent_tenants_complete_with_isolated_namespaces(tmp_path):
    svc = service(tmp_path)
    handles = [svc.submit(req(tenant=f"t{i}", campaign="c",
                              init_seed=50 + i, snapshot_every=2,
                              n_steps=4))
               for i in range(3)]
    svc.drain()
    for i, h in enumerate(handles):
        r = h.result(timeout=120)
        assert r.steps == 4 and r.rollbacks == 0
        assert [s for s, _ in r.snapshots] == [2]
        assert not np.isnan(r.final["temp"]).any()
        # isolated checkpoint namespace per tenant
        assert (tmp_path / "root" / f"t{i}" / "c").is_dir()
    assert svc.stats.completed == 3 and svc.stats.failed == 0
    assert svc.stats.batches == 1  # one fingerprint -> one batch


def test_warm_path_zero_recompiles_zero_measurements(tmp_path):
    """The warm-path invariants asserted from the EXPORTED metrics
    surface (``metrics_text()`` — what a Prometheus scraper sees), not
    internal fields: the acceptance contract of the telemetry PR."""
    from stencil_tpu.telemetry import metric_value, parse_prometheus_text

    svc = service(tmp_path)
    svc.submit(req(tenant="t0"))
    svc.drain()
    text = svc.metrics_text()
    meas_after_first = metric_value(
        text, "stencil_service_tuner_measurements_total")
    assert metric_value(text, "stencil_service_compiles_total") == 1
    assert meas_after_first > 0
    h = svc.submit(req(tenant="t1", init_seed=9))
    svc.drain()
    assert h.result(timeout=120).steps == 4
    text = svc.metrics_text()
    # the zero-valued gate tests a series that EXISTS in the scrape
    # (counters are seeded to 0 at registration) — absent-series 0.0
    # would make this assertion vacuous
    parsed = parse_prometheus_text(text)
    assert parsed["stencil_service_recompiles_total"] == {(): 0.0}
    # engine cache: the warm request compiled nothing and measured
    # nothing — and no fingerprint was ever rebuilt
    assert metric_value(text, "stencil_service_compiles_total") == 1
    assert metric_value(text, "stencil_service_recompiles_total") == 0
    assert metric_value(
        text, "stencil_service_engine_cache_hits_total") == 1
    assert metric_value(
        text,
        "stencil_service_tuner_measurements_total") == meas_after_first
    assert metric_value(text, "stencil_service_requests_total",
                        tenant="t1") == 1
    batches = [e for e in svc.events if e["event"] == "batch_started"]
    assert batches[-1]["compiled"] is False
    assert batches[-1]["measurements"] == 0


def test_plan_cache_shared_across_services(tmp_path):
    """A second service process (fresh engine cache, same plan cache)
    re-compiles but measures NOTHING — the plan-cache hit, asserted
    from each service's exported metrics."""
    from stencil_tpu.telemetry import metric_value, parse_prometheus_text

    svc1 = service(tmp_path)
    svc1.submit(req(tenant="t0"))
    svc1.drain()
    assert metric_value(svc1.metrics_text(),
                        "stencil_service_tuner_measurements_total") > 0
    svc2 = service(tmp_path)
    svc2.submit(req(tenant="t1"))
    svc2.drain()
    text = svc2.metrics_text()
    # zero-valued gates test series seeded into the scrape at birth
    parsed = parse_prometheus_text(text)
    assert parsed["stencil_service_tuner_measurements_total"] == {(): 0.0}
    assert parsed["stencil_service_recompiles_total"] == {(): 0.0}
    assert metric_value(
        text, "stencil_service_plan_cache_hits_total") == 1
    assert metric_value(
        text, "stencil_service_tuner_measurements_total") == 0
    assert metric_value(text, "stencil_service_recompiles_total") == 0
    assert svc2._engines and next(
        iter(svc2._engines.values())).dd.plan_provenance == "cached"


def test_member_fault_rolls_back_only_affected_campaign(tmp_path):
    """Acceptance: a per-member NaN rolls back only that campaign; an
    untouched batch-mate finishes bitwise-identical to a fault-free
    service run."""
    chaos = service(tmp_path / "chaos")
    calm = service(tmp_path / "calm")
    kwargs = dict(campaign="c", n_steps=6, ckpt_every=2, init_seed=5)
    h0 = chaos.submit(req(tenant="tA", chaos_nan_step=3, **kwargs))
    h1 = chaos.submit(req(tenant="tB", **kwargs))
    chaos.drain()
    r0, r1 = h0.result(timeout=120), h1.result(timeout=120)
    assert r0.rollbacks >= 1 and r0.steps == 6
    assert r1.rollbacks == 0 and r1.steps == 6
    assert not np.isnan(r0.final["temp"]).any()

    g0 = calm.submit(req(tenant="tA", **kwargs))
    g1 = calm.submit(req(tenant="tB", **kwargs))
    calm.drain()
    np.testing.assert_array_equal(g1.result().final["temp"],
                                  r1.final["temp"])
    # the faulted campaign recovered to the fault-free trajectory too
    np.testing.assert_array_equal(g0.result().final["temp"],
                                  r0.final["temp"])
    trips = [e for e in chaos.events
             if e["event"] == "sentinel_tripped"]
    assert trips and all(e["tenant"] == "tA" for e in trips)


def test_retries_exhausted_fails_only_that_campaign(tmp_path):
    svc = service(tmp_path)
    # no checkpoints between injection points: rollback restores to
    # step 0, the (once-only) chaos won't refire — so use max_retries=0
    # to exhaust immediately on the first trip
    h0 = svc.submit(req(tenant="bad", chaos_nan_step=2, n_steps=4,
                        ckpt_every=0, max_retries=0))
    h1 = svc.submit(req(tenant="good", n_steps=4))
    svc.drain()
    with pytest.raises(CampaignFailed):
        h0.result(timeout=120)
    assert h1.result(timeout=120).steps == 4
    assert svc.stats.failed == 1 and svc.stats.completed == 1


def test_preempt_then_resume(tmp_path):
    svc = service(tmp_path)
    h = svc.submit(req(tenant="t0", campaign="long", n_steps=6))
    svc._preempt = True  # deterministic: reclaim before the first seg
    svc.drain()
    r = h.result(timeout=120)
    assert r.preempted and r.steps == 0

    svc2 = service(tmp_path)
    h2 = svc2.submit(req(tenant="t0", campaign="long", n_steps=6))
    svc2.drain()
    r2 = h2.result(timeout=120)
    assert not r2.preempted and r2.steps == 6
    assert r2.resumed_from == 0


def test_completed_campaign_extends_on_resubmit(tmp_path):
    """Resubmitting a finished campaign with a larger step budget
    resumes from its final checkpoint instead of restarting — and the
    two-leg trajectory matches one uninterrupted run bitwise."""
    svc = service(tmp_path)
    h0 = svc.submit(req(tenant="t0", campaign="c", n_steps=3,
                        init_seed=4))
    svc.drain()
    assert h0.result(timeout=120).steps == 3
    h = svc.submit(req(tenant="t0", campaign="c", n_steps=7,
                       init_seed=4))
    svc.drain()
    r = h.result(timeout=120)
    assert r.resumed_from == 3 and r.steps == 7
    # resubmitting with the budget already met completes immediately,
    # never stepping past the request
    h2 = svc.submit(req(tenant="t0", campaign="c", n_steps=7,
                        init_seed=4))
    svc.drain()
    r2 = h2.result(timeout=120)
    assert r2.steps == 7
    np.testing.assert_array_equal(r2.final["temp"], r.final["temp"])

    one = service(tmp_path / "oneshot")
    g = one.submit(req(tenant="t0", campaign="c", n_steps=7,
                       init_seed=4))
    one.drain()
    np.testing.assert_array_equal(g.result().final["temp"],
                                  r.final["temp"])


def test_background_worker_serves(tmp_path):
    svc = service(tmp_path)
    svc.start()
    try:
        h = svc.submit(req(tenant="t0"))
        assert h.result(timeout=120).steps == 4
    finally:
        svc.stop()


def test_namespace_rejects_traversal(tmp_path):
    svc = service(tmp_path)
    with pytest.raises(ValueError):
        svc.namespace("../escape", "c")
    with pytest.raises(ValueError):
        svc.namespace("t", "a/b")


def test_astaroth_campaign(tmp_path):
    svc = service(tmp_path, width=2)
    h = svc.submit(req(tenant="t0", model="astaroth", n_steps=2,
                       dtype="float64", ckpt_every=1,
                       params={"nu_visc": 6e-3}))
    svc.drain()
    r = h.result(timeout=300)
    assert r.steps == 2
    assert set(r.final) == {"lnrho", "uux", "uuy", "uuz",
                            "ax", "ay", "az", "ss"}
    assert all(np.isfinite(v).all() for v in r.final.values())
