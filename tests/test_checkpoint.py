"""Checkpoint/resume roundtrips (SURVEY.md section 5.4 modernization)."""

import numpy as np
import pytest

from stencil_tpu.utils.checkpoint import restore_domain, save_domain


def test_jacobi_checkpoint_resume(tmp_path):
    from stencil_tpu.models.jacobi import Jacobi3D

    n = 16
    a = Jacobi3D(n, n, n, mesh_shape=(2, 2, 2), dtype=np.float32)
    a.init()
    a.step()
    a.step()
    save_domain(a.dd, str(tmp_path / "ckpt"), step=2)
    a.step()
    want = a.temperature()

    b = Jacobi3D(n, n, n, mesh_shape=(2, 2, 2), dtype=np.float32)
    step, extra = restore_domain(b.dd, str(tmp_path / "ckpt"))
    assert step == 2
    assert extra == {}
    b.step()
    np.testing.assert_array_equal(b.temperature(), want)


def test_checkpoint_reshard_onto_different_mesh(tmp_path):
    """Restore onto a different mesh decomposition: the elastic-resume
    capability the reference lacks entirely (SURVEY.md section 5.3/5.4)."""
    from stencil_tpu.models.jacobi import Jacobi3D

    n = 16
    a = Jacobi3D(n, n, n, mesh_shape=(2, 2, 2), dtype=np.float32)
    a.init()
    a.step()
    save_domain(a.dd, str(tmp_path / "ckpt"), step=1)
    a.step()
    want = a.temperature()

    b = Jacobi3D(n, n, n, mesh_shape=(8, 1, 1), dtype=np.float32)
    step, _ = restore_domain(b.dd, str(tmp_path / "ckpt"))
    assert step == 1
    b.step()
    np.testing.assert_allclose(b.temperature(), want, atol=1e-6)


def test_checkpoint_rejects_mismatched_domain(tmp_path):
    from stencil_tpu.models.jacobi import Jacobi3D

    a = Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float32)
    a.init()
    save_domain(a.dd, str(tmp_path / "ckpt"), step=0)

    b = Jacobi3D(32, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float32)
    with pytest.raises(Exception):
        restore_domain(b.dd, str(tmp_path / "ckpt"))


def test_checkpoint_rejects_mismatched_dtype(tmp_path):
    """Restoring a float32 checkpoint into a float64 domain must fail
    with a clear error, not silently reinterpret the data."""
    from stencil_tpu.models.jacobi import Jacobi3D

    a = Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float32)
    a.init()
    save_domain(a.dd, str(tmp_path / "ckpt"), step=0)

    b = Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float64)
    with pytest.raises(Exception, match="dtype"):
        restore_domain(b.dd, str(tmp_path / "ckpt"))


@pytest.mark.slow
def test_astaroth_checkpoint_with_accumulators(tmp_path):
    from stencil_tpu.models.astaroth import Astaroth, MhdParams

    prm = MhdParams()
    a = Astaroth(16, 16, 16, params=prm, mesh_shape=(2, 2, 2),
                 dtype=np.float64)
    a.init()
    a.step()
    save_domain(a.dd, str(tmp_path / "ckpt"), step=1, extra=a._w)
    a.step()
    want = {q: a.field(q) for q in ("lnrho", "uux", "ss")}

    b = Astaroth(16, 16, 16, params=prm, mesh_shape=(2, 2, 2),
                 dtype=np.float64)
    step, extra = restore_domain(b.dd, str(tmp_path / "ckpt"))
    assert step == 1
    assert set(extra) == set(a._w)
    b._w = extra
    b.step()
    for q in want:
        np.testing.assert_allclose(b.field(q), want[q], rtol=1e-12,
                                   atol=1e-14)


def test_checkpoint_bf16_cross_mesh_roundtrip(tmp_path):
    """bfloat16 fields survive save/restore bit-exactly, including
    onto a different mesh (orbax stores the raw bf16 interior; the
    restore path re-shards it like any other dtype)."""
    import jax.numpy as jnp

    from stencil_tpu.models.jacobi import Jacobi3D

    a = Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=jnp.bfloat16)
    a.init()
    a.step()
    save_domain(a.dd, str(tmp_path / "ck"), step=1)
    a.step()
    want = np.asarray(a.temperature(), np.float32)

    b = Jacobi3D(16, 16, 16, mesh_shape=(1, 2, 4), dtype=jnp.bfloat16)
    step, _ = restore_domain(b.dd, str(tmp_path / "ck"))
    assert step == 1
    b.step()
    got = np.asarray(b.temperature(), np.float32)
    np.testing.assert_array_equal(got, want)


def test_validate_checkpoint_component():
    """Tenant/campaign ids become checkpoint directory components — an
    id like ``../other-tenant`` must be rejected before it touches the
    filesystem (multi-tenant serving, stencil_tpu/serving)."""
    from stencil_tpu.utils.checkpoint import validate_checkpoint_component

    for ok in ("tenant0", "a-b_c.d", "run..01", "UPPER", "0"):
        assert validate_checkpoint_component(ok) == ok
    for bad in ("", ".", "..", "a/b", "/abs", "a\\b", "..\\up",
                "x\x00y", "a\nb", "tab\tid", None, 7):
        with pytest.raises(ValueError):
            validate_checkpoint_component(bad)


def test_validate_checkpoint_component_names_the_kind():
    from stencil_tpu.utils.checkpoint import validate_checkpoint_component

    with pytest.raises(ValueError, match="tenant id"):
        validate_checkpoint_component("../up", kind="tenant id")
