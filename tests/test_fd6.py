"""6th-order derivative operators vs an independent numpy oracle and
analytic convergence checks (reference coefficients:
astaroth/user_kernels.h:36-76)."""

import numpy as np
import pytest

import jax.numpy as jnp

from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.ops.fd6 import FieldData, der1, der2, der_cross

R = 3


def pad_periodic(a: np.ndarray) -> np.ndarray:
    """Periodic halo padding of a (z,y,x) interior array."""
    return np.pad(a, R, mode="wrap")


def np_der1(a: np.ndarray, axis_grid: int, inv_ds: float) -> np.ndarray:
    """Independent oracle via np.roll on the interior (periodic)."""
    ax = {0: 2, 1: 1, 2: 0}[axis_grid]
    c = [3.0 / 4.0, -3.0 / 20.0, 1.0 / 60.0]
    out = np.zeros_like(a)
    for i, ci in enumerate(c, start=1):
        out += ci * (np.roll(a, -i, axis=ax) - np.roll(a, i, axis=ax))
    return out * inv_ds


def np_der2(a: np.ndarray, axis_grid: int, inv_ds: float) -> np.ndarray:
    ax = {0: 2, 1: 1, 2: 0}[axis_grid]
    c0 = -49.0 / 18.0
    c = [3.0 / 2.0, -3.0 / 20.0, 1.0 / 90.0]
    out = c0 * a.copy()
    for i, ci in enumerate(c, start=1):
        out += ci * (np.roll(a, -i, axis=ax) + np.roll(a, i, axis=ax))
    return out * inv_ds * inv_ds


def np_cross(a: np.ndarray, ga: int, gb: int, inv_a: float, inv_b: float
             ) -> np.ndarray:
    axa = {0: 2, 1: 1, 2: 0}[ga]
    axb = {0: 2, 1: 1, 2: 0}[gb]
    fac = 1.0 / 720.0
    c = [270.0 * fac, -27.0 * fac, 2.0 * fac]
    out = np.zeros_like(a)
    for i, ci in enumerate(c, start=1):
        pp = np.roll(np.roll(a, -i, axis=axa), -i, axis=axb)
        mm = np.roll(np.roll(a, i, axis=axa), i, axis=axb)
        pm = np.roll(np.roll(a, -i, axis=axa), i, axis=axb)
        mp = np.roll(np.roll(a, i, axis=axa), -i, axis=axb)
        out += ci * (pp + mm - pm - mp)
    return out * inv_a * inv_b


@pytest.fixture
def rand_field():
    rng = np.random.default_rng(42)
    return rng.standard_normal((10, 12, 14))


class TestOperatorsVsOracle:
    def test_der1_all_axes(self, rand_field):
        a = rand_field
        p = jnp.asarray(pad_periodic(a))
        lo = Dim3(R, R, R)
        n = Dim3(a.shape[2], a.shape[1], a.shape[0])
        for axis in range(3):
            got = np.asarray(der1(p, axis, 2.5, lo, n))
            want = np_der1(a, axis, 2.5)
            np.testing.assert_allclose(got, want, atol=1e-12)

    def test_der2_all_axes(self, rand_field):
        a = rand_field
        p = jnp.asarray(pad_periodic(a))
        lo = Dim3(R, R, R)
        n = Dim3(a.shape[2], a.shape[1], a.shape[0])
        for axis in range(3):
            got = np.asarray(der2(p, axis, 1.5, lo, n))
            want = np_der2(a, axis, 1.5)
            np.testing.assert_allclose(got, want, atol=1e-12)

    def test_cross_all_pairs(self, rand_field):
        a = rand_field
        p = jnp.asarray(pad_periodic(a))
        lo = Dim3(R, R, R)
        n = Dim3(a.shape[2], a.shape[1], a.shape[0])
        for ga, gb in ((0, 1), (0, 2), (1, 2)):
            got = np.asarray(der_cross(p, ga, gb, 2.0, 3.0, lo, n))
            want = np_cross(a, ga, gb, 2.0, 3.0)
            np.testing.assert_allclose(got, want, atol=1e-12)
            # symmetry d2/dadb == d2/dbda
            got_t = np.asarray(der_cross(p, gb, ga, 3.0, 2.0, lo, n))
            np.testing.assert_allclose(got, got_t, atol=1e-12)


class TestAnalyticAccuracy:
    def test_sine_wave_derivatives(self):
        # f = sin(kx): f' = k cos(kx), f'' = -k^2 sin(kx); 6th order
        # should be accurate to ~(k dx)^6
        n = 32
        ds = 2 * np.pi / n
        x = np.arange(n) * ds
        f = np.sin(x)[None, None, :] * np.ones((4, 4, 1))
        p = jnp.asarray(pad_periodic(f))
        lo = Dim3(R, R, R)
        ni = Dim3(n, 4, 4)
        d1 = np.asarray(der1(p, 0, 1.0 / ds, lo, ni))
        np.testing.assert_allclose(d1[0, 0], np.cos(x), atol=1e-6)
        d2v = np.asarray(der2(p, 0, 1.0 / ds, lo, ni))
        np.testing.assert_allclose(d2v[0, 0], -np.sin(x), atol=1e-5)

    def test_cross_of_product(self):
        # f = sin(x) sin(y): dxy f = cos(x) cos(y)
        n = 32
        ds = 2 * np.pi / n
        x = np.arange(n) * ds
        f = np.sin(x)[None, :, None] * np.sin(x)[None, None, :]
        f = np.broadcast_to(f, (4, n, n)).copy()
        p = jnp.asarray(pad_periodic(f))
        lo = Dim3(R, R, R)
        ni = Dim3(n, n, 4)
        got = np.asarray(der_cross(p, 0, 1, 1.0 / ds, 1.0 / ds, lo, ni))
        want = np.cos(x)[None, :, None] * np.cos(x)[None, None, :]
        # 6th-order truncation at this resolution is ~6e-6
        np.testing.assert_allclose(got[0], want[0], atol=2e-5)


class TestFieldData:
    def test_caching_and_shapes(self, rand_field):
        a = rand_field
        p = jnp.asarray(pad_periodic(a))
        fd = FieldData(p, (1.0, 1.0, 1.0), Dim3(R, R, R),
                       Dim3(a.shape[2], a.shape[1], a.shape[0]))
        assert fd.value.shape == a.shape
        assert fd.grad(0) is fd.grad(0)  # cached
        assert fd.hess(1, 0) is fd.hess(0, 1)  # symmetric alias
        lap = np.asarray(fd.laplace)
        want = np_der2(a, 0, 1) + np_der2(a, 1, 1) + np_der2(a, 2, 1)
        np.testing.assert_allclose(lap, want, atol=1e-12)
