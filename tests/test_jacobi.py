"""Jacobi3D distributed solver vs dense single-device oracle
(the numerical-parity strategy from SURVEY.md section 4)."""

import numpy as np
import pytest

from stencil_tpu.geometry import Dim3
from stencil_tpu.models.jacobi import (Jacobi3D, dense_reference_step,
                                       HOT_TEMP, COLD_TEMP)
from stencil_tpu.parallel.methods import Method


def run_dense(size: Dim3, iters: int) -> np.ndarray:
    temp = np.full((size.z, size.y, size.x), (HOT_TEMP + COLD_TEMP) / 2,
                   dtype=np.float64)
    hot_c = (size.x // 3, size.y // 2, size.z // 2)
    cold_c = (size.x * 2 // 3, size.y // 2, size.z // 2)
    sph_r = size.x // 10
    for _ in range(iters):
        temp = dense_reference_step(temp, hot_c, cold_c, sph_r)
    return temp


@pytest.mark.parametrize("mesh_shape", [(2, 2, 2), (8, 1, 1), (1, 2, 4)])
def test_jacobi_matches_dense(mesh_shape):
    size = Dim3(16, 16, 16)
    j = Jacobi3D(size.x, size.y, size.z, mesh_shape=mesh_shape,
                 dtype=np.float64)
    j.init()
    for _ in range(5):
        j.step()
    want = run_dense(size, 5)
    got = j.temperature()
    np.testing.assert_allclose(got, want, rtol=0, atol=1e-13)


def test_jacobi_run_fused_loop():
    size = Dim3(16, 16, 16)
    j = Jacobi3D(size.x, size.y, size.z, mesh_shape=(2, 2, 2),
                 dtype=np.float64)
    j.init()
    j.run(5)
    want = run_dense(size, 5)
    np.testing.assert_allclose(j.temperature(), want, rtol=0, atol=1e-13)


def test_jacobi_packed_method():
    size = Dim3(16, 16, 16)
    j = Jacobi3D(size.x, size.y, size.z, mesh_shape=(2, 2, 2),
                 dtype=np.float64, methods=Method.PpermutePacked)
    j.init()
    for _ in range(3):
        j.step()
    np.testing.assert_allclose(j.temperature(), run_dense(size, 3),
                               rtol=0, atol=1e-13)


def test_jacobi_single_device():
    size = Dim3(12, 12, 12)
    import jax
    j = Jacobi3D(size.x, size.y, size.z, mesh_shape=(1, 1, 1),
                 dtype=np.float64, devices=jax.devices()[:1])
    j.init()
    j.run(4)
    np.testing.assert_allclose(j.temperature(), run_dense(size, 4),
                               rtol=0, atol=1e-13)


# ---------------------------------------------------------------------------
# low-precision halo wire formats (parallel/exchange.py wire_format=,
# certified by analysis/precision.py)


def _wire_pair(size, boundary, wire, steps=5, method=Method.PpermuteSlab):
    """Run the same campaign twice — full-precision wire vs ``wire`` —
    and return (reference, narrowed, certificate)."""
    from stencil_tpu.topology import Boundary

    kw = dict(mesh_shape=(2, 2, 2), dtype=np.float32, kernel="xla",
              methods=method,
              boundary=Boundary[boundary] if boundary else None)
    ref = Jacobi3D(size.x, size.y, size.z, **kw)
    ref.init()
    ref.run(steps)
    jw = Jacobi3D(size.x, size.y, size.z, wire_format=wire, **kw)
    jw.init()
    jw.run(steps)
    return ref.temperature(), jw.temperature(), jw.dd.precision_certificate


@pytest.mark.parametrize("boundary", ["PERIODIC", "NONE"])
@pytest.mark.parametrize("n", [16, 17])
def test_jacobi_bf16_wire_error_bound(boundary, n):
    """The certificate's analytic bound is LIVE: a bf16 wire injects at
    most one 2^-8 relative rounding per halo cell per hop, and the
    7-point average is a contraction, so ``steps`` steps stay within
    ``steps * max_rel_error_bound`` of the f32-wire run — on even 16^3
    and uneven (+-1 remainder) 17^3 grids, periodic and zero-Dirichlet
    exterior alike. The halo MATH runs at f32: only the wire narrows."""
    steps = 5
    size = Dim3(n, n, n)
    want, got, cert = _wire_pair(size, boundary, "bf16", steps=steps)
    assert cert is not None and cert.safe
    assert cert.max_rel_error_bound == 2.0 ** -8  # bf16: 2^-(7+1)
    assert got.dtype == np.float32  # storage dtype untouched
    scale = np.abs(want).max()
    err = np.abs(got - want).max()
    assert err <= steps * cert.max_rel_error_bound * scale, (err, scale)
    # non-vacuous: the narrowed wire actually perturbed the halos
    assert err > 0.0


def test_jacobi_bf16_wire_fused_equals_stepwise():
    """The fused n-step loop and n single steps build the same shard
    program, so the bf16-wire results are bitwise identical — the wire
    rounding is deterministic, not noise."""
    size = Dim3(16, 16, 16)
    kw = dict(mesh_shape=(2, 2, 2), dtype=np.float32, kernel="xla",
              methods=Method.PpermutePacked, wire_format="bf16")
    a = Jacobi3D(size.x, size.y, size.z, **kw)
    a.init()
    a.run(4)
    b = Jacobi3D(size.x, size.y, size.z, **kw)
    b.init()
    for _ in range(4):
        b.step()
    np.testing.assert_array_equal(a.temperature(), b.temperature())


def test_jacobi_f32_wire_is_identity():
    """``wire_format="f32"`` is the do-nothing declaration: bitwise
    identical to the undeclared path, no gate, no certificate."""
    size = Dim3(16, 16, 16)
    want, got, cert = _wire_pair(size, "PERIODIC", "f32", steps=4)
    assert cert is None  # identity wire never runs the gate
    np.testing.assert_array_equal(got, want)
