"""Jacobi3D distributed solver vs dense single-device oracle
(the numerical-parity strategy from SURVEY.md section 4)."""

import numpy as np
import pytest

from stencil_tpu.geometry import Dim3
from stencil_tpu.models.jacobi import (Jacobi3D, dense_reference_step,
                                       HOT_TEMP, COLD_TEMP)
from stencil_tpu.parallel.methods import Method


def run_dense(size: Dim3, iters: int) -> np.ndarray:
    temp = np.full((size.z, size.y, size.x), (HOT_TEMP + COLD_TEMP) / 2,
                   dtype=np.float64)
    hot_c = (size.x // 3, size.y // 2, size.z // 2)
    cold_c = (size.x * 2 // 3, size.y // 2, size.z // 2)
    sph_r = size.x // 10
    for _ in range(iters):
        temp = dense_reference_step(temp, hot_c, cold_c, sph_r)
    return temp


@pytest.mark.parametrize("mesh_shape", [(2, 2, 2), (8, 1, 1), (1, 2, 4)])
def test_jacobi_matches_dense(mesh_shape):
    size = Dim3(16, 16, 16)
    j = Jacobi3D(size.x, size.y, size.z, mesh_shape=mesh_shape,
                 dtype=np.float64)
    j.init()
    for _ in range(5):
        j.step()
    want = run_dense(size, 5)
    got = j.temperature()
    np.testing.assert_allclose(got, want, rtol=0, atol=1e-13)


def test_jacobi_run_fused_loop():
    size = Dim3(16, 16, 16)
    j = Jacobi3D(size.x, size.y, size.z, mesh_shape=(2, 2, 2),
                 dtype=np.float64)
    j.init()
    j.run(5)
    want = run_dense(size, 5)
    np.testing.assert_allclose(j.temperature(), want, rtol=0, atol=1e-13)


def test_jacobi_packed_method():
    size = Dim3(16, 16, 16)
    j = Jacobi3D(size.x, size.y, size.z, mesh_shape=(2, 2, 2),
                 dtype=np.float64, methods=Method.PpermutePacked)
    j.init()
    for _ in range(3):
        j.step()
    np.testing.assert_allclose(j.temperature(), run_dense(size, 3),
                               rtol=0, atol=1e-13)


def test_jacobi_single_device():
    size = Dim3(12, 12, 12)
    import jax
    j = Jacobi3D(size.x, size.y, size.z, mesh_shape=(1, 1, 1),
                 dtype=np.float64, devices=jax.devices()[:1])
    j.init()
    j.run(4)
    np.testing.assert_allclose(j.temperature(), run_dense(size, 4),
                               rtol=0, atol=1e-13)
