"""The prescriptive VMEM tiling planner (analysis/tiling.py).

Three properties anchor the module:

* plan -> audit round trip: every planner-EMITTED block shape, traced
  through the real kernel it was planned for, passes ``check_vmem``
  at the PHYSICAL budget with zero findings — across kernel families,
  sizes (8^3 smoke, 17^3 uneven, 256^3/512^3 production) and dtypes
  (f32, bf16). Where the planner refuses, the refusal IS the contract
  (TilingInfeasibleError naming the binding constraint), never a
  silently shrunken shape.
* prescription correctness: the SNIPPETS.md 512^3 failure shape
  (16, 128) is flagged and the planner's (8, 128) replacement is
  clean — and block shapes never change numerics (bitwise equality
  across shapes at a small size).
* the tuner integration: planner-legal shapes rank by the modeled
  HBM price and ride ``Plan.tiling`` records through the cache.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from stencil_tpu.analysis.tiling import (TILE_SELECT_BUDGET_BYTES,
                                         TilingInfeasibleError,
                                         plan_blocks, reset_warnings,
                                         snap_blocks)
from stencil_tpu.analysis.vmem import VMEM_BUDGET_BYTES


def _f(shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


# ---------------------------------------------------------------------------
# planner unit properties


def _unit_elems(bz, by):
    return (2 * bz * by, bz * by, 0)


def test_plan_blocks_candidates_are_aligned_divisible():
    plan = plan_blocks("unit", 64, 64, 128, 4, _unit_elems)
    assert plan.options
    for o in plan.options:
        assert 64 % o.block_z == 0 and 64 % o.block_y == 0
        assert o.block_y % 8 == 0            # f32 sublane tile
        assert o.footprint_bytes <= plan.budget_bytes
    # cheapest traffic first; ties prefer fatter block_y then block_z
    amps = [o.amplification for o in plan.options]
    assert amps == sorted(amps)
    assert plan.best.block_z == 64 and plan.best.block_y == 64


def test_plan_blocks_caps_and_sublanes():
    plan = plan_blocks("unit", 64, 64, 128, 4, _unit_elems,
                       cap_z=16, cap_y=32, sublane_z=4)
    assert plan.best.block_z == 16 and plan.best.block_y == 32
    for o in plan.options:
        assert o.block_z <= 16 and o.block_y <= 32
        assert o.block_z % 4 == 0
    # bf16 doubles the sublane tile; a cap below the floor means "the
    # smallest legal shape", not infeasible (the old fitters' clamp-up)
    plan16 = plan_blocks("unit", 64, 64, 128, 2, _unit_elems, cap_y=8)
    assert plan16.best.block_y == 16


def test_plan_blocks_budget_binds_and_names_constraint():
    # 3 full-array streams of (bz, by, 128) f32: force the budget down
    # until only small blocks survive, then to nothing
    elems = lambda bz, by: (2 * bz * by, bz * by, 0)  # noqa: E731
    tight = plan_blocks("unit", 256, 256, 512, 4, elems,
                        budget=4 * 2**20)
    assert tight.options and tight.over_budget > 0
    for o in tight.options:
        assert o.footprint_bytes <= 4 * 2**20
    nothing = plan_blocks("unit", 256, 256, 512, 4, elems, budget=1024)
    assert not nothing.options
    assert "VMEM footprint is the binding constraint" in nothing.infeasible
    with pytest.raises(TilingInfeasibleError, match="binding constraint"):
        nothing.blocks()


def test_plan_blocks_alignment_infeasible_named():
    # Y=17 with an 8-row sublane requirement: no aligned block_y at all
    plan = plan_blocks("unit", 16, 17, 128, 4, _unit_elems, sublane_y=8)
    assert not plan.options
    assert "sublane tile 8" in plan.infeasible
    with pytest.raises(TilingInfeasibleError):
        plan.blocks()


def test_snap_blocks_warns_once_per_replacement(capsys):
    reset_warnings()
    bz, by = snap_blocks("unit_kernel", 16, 16, 16, 128, sublane_y=8)
    assert (bz, by) == (16, 16)
    err = capsys.readouterr().err
    assert "unit_kernel" in err and "(16, 128)" in err \
        and "(16, 16)" in err
    # the same replacement again: silent (once per kernel+shape+request)
    snap_blocks("unit_kernel", 16, 16, 16, 128, sublane_y=8)
    assert "unit_kernel" not in capsys.readouterr().err
    # a legal explicit request passes through silently
    reset_warnings()
    assert snap_blocks("unit_kernel", 16, 16, 8, 8) == (8, 8)
    assert "unit_kernel" not in capsys.readouterr().err
    with pytest.raises(TilingInfeasibleError):
        snap_blocks("unit_kernel", 17, 16, 16, 16, sublane_z=8, min_z=8)


# ---------------------------------------------------------------------------
# plan -> audit round trip: the planner's shapes pass the PHYSICAL-
# budget VMEM audit through the real kernels, or the planner refuses
# with the constraint named — across families x sizes x dtypes


def _wrap_fn(side, dtype, steps):
    from stencil_tpu.ops.pallas_stencil import (jacobi7_wrap_pallas,
                                                jacobi7_wrapn_pallas)

    hot = (side // 4, side // 2, side // 2)
    cold = (3 * side // 4, side // 2, side // 2)

    def fn(q):
        if steps == 1:
            return jacobi7_wrap_pallas(q, hot, cold, max(side // 8, 1),
                                       interpret=False)
        return jacobi7_wrapn_pallas(q, hot, cold, max(side // 8, 1),
                                    steps=steps, interpret=False)

    return fn, (_f((side, side, side), dtype),)


def _halo_fn(side, dtype):
    from stencil_tpu.ops.pallas_stencil import sublane_tile
    from stencil_tpu.ops.pallas_halo import jacobi7_halo_pallas

    esub = sublane_tile(dtype)
    if side % esub:
        esub = 1
    slabs = {"zlo": _f((1, side, side), dtype),
             "zhi": _f((1, side, side), dtype),
             "ylo": _f((side, esub, side), dtype),
             "yhi": _f((side, esub, side), dtype)}
    org = jax.ShapeDtypeStruct((3,), jnp.int32)

    def fn(interior, zlo, zhi, ylo, yhi, o):
        return jacobi7_halo_pallas(
            interior, {"zlo": zlo, "zhi": zhi, "ylo": ylo, "yhi": yhi},
            o, (2, 4, 4), (5, 4, 4), 1, interpret=False)

    return fn, (_f((side, side, side), dtype), slabs["zlo"],
                slabs["zhi"], slabs["ylo"], slabs["yhi"], org)


def _mhd_wrap_fn(side, dtype):
    from stencil_tpu.models.astaroth import FIELDS, MhdParams
    from stencil_tpu.ops.pallas_mhd import mhd_substep_wrap_pallas

    prm = MhdParams()

    def fn(*fs):
        f, w = mhd_substep_wrap_pallas(dict(zip(FIELDS, fs)), None, 0,
                                       prm, prm.dt, interpret=False)
        return tuple(f[q] for q in FIELDS)

    return fn, tuple(_f((side, side, side), dtype) for _ in FIELDS)


_FAMILIES = {
    "wrap": lambda side, dtype: _wrap_fn(side, dtype, 1),
    "wrapn2": lambda side, dtype: _wrap_fn(side, dtype, 2),
    "halo": _halo_fn,
    "mhd_wrap": _mhd_wrap_fn,
}


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
@pytest.mark.parametrize("side", [8, 17, 256, 512])
@pytest.mark.parametrize("family", sorted(_FAMILIES))
def test_planner_shapes_round_trip_through_vmem_audit(family, side,
                                                      dtype):
    """Every planner-emitted default shape passes check_vmem at the
    PHYSICAL budget (declared vmem_limit raises ignored); where the
    planner refuses, the refusal names its binding constraint — never
    a silent shrink, never an audit failure."""
    if family in ("wrapn2", "mhd_wrap") and side == 17:
        pytest.skip("kernel requires sublane-divisible Y (model gates)")
    if family == "mhd_wrap" and side == 8:
        side = 16  # 8^3 leaves no room for the radius-3 window ring
    from stencil_tpu.ops.pallas_stencil import sublane_tile

    # arrays whose Y is not a multiple of the dtype's sublane tile run
    # in the kernels' documented degraded-alignment mode (single-row
    # edge slabs): Mosaic pads those fetches, and the audit reports
    # exactly that — the ONLY findings allowed there
    degraded = side % sublane_tile(dtype) != 0
    try:
        fn, args = _FAMILIES[family](side, dtype)
        # trace once; audit against the physical budget ourselves
        from stencil_tpu.analysis.jaxprs import iter_eqns, trace
        from stencil_tpu.analysis.vmem import audit_pallas_call

        name = f"roundtrip.{family}[{side}]"

        closed = trace(fn, *args)
        findings = []
        n_kernels = 0
        for eqn in iter_eqns(closed.jaxpr):
            if eqn.primitive.name != "pallas_call":
                continue
            n_kernels += 1
            f, _m = audit_pallas_call(eqn, VMEM_BUDGET_BYTES, "k",
                                      name,
                                      honor_kernel_limit=False)
            findings.extend(f)
        assert n_kernels >= 1
        if degraded:
            assert all("sublane dim 1 is neither" in str(f)
                       for f in findings), [str(f) for f in findings]
        else:
            assert findings == [], [str(f) for f in findings]
    except TilingInfeasibleError as e:
        assert "no legal block shape" in str(e)
    except ValueError as e:
        # the N-step kernels refuse non-sublane-divisible Y outright
        assert degraded and "== 0" in str(e), e


def test_snippets_512_failure_flagged_and_prescription_clean():
    """The motivating failure end-to-end: the old default (16, 128)
    halo blocking at 512^3 exceeds the physical budget (check_vmem
    honoring the kernel's raised limit MISSES it — which is exactly
    why the tiling checker exists), the planner prescribes (8, 128),
    and the prescribed shape audits clean."""
    from stencil_tpu.analysis.jaxprs import iter_eqns, trace
    from stencil_tpu.analysis.vmem import audit_pallas_call
    from stencil_tpu.ops.pallas_halo import (_jacobi_block_bytes,
                                             fit_jacobi_halo_blocks)

    assert _jacobi_block_bytes(16, 128, 512, 8, 4) > VMEM_BUDGET_BYTES
    assert fit_jacobi_halo_blocks(512, 512, 512, 8, 4, 16, 128) \
        == (8, 128)

    def audit(block_z, block_y):
        from stencil_tpu.ops.pallas_halo import jacobi7_halo_pallas

        S = 512
        slabs = {"zlo": _f((1, S, S)), "zhi": _f((1, S, S)),
                 "ylo": _f((S, 8, S)), "yhi": _f((S, 8, S))}
        org = jax.ShapeDtypeStruct((3,), jnp.int32)

        def fn(interior, zlo, zhi, ylo, yhi, o):
            return jacobi7_halo_pallas(
                interior,
                {"zlo": zlo, "zhi": zhi, "ylo": ylo, "yhi": yhi},
                o, (2, 4, 4), (5, 4, 4), 1, block_z=block_z,
                block_y=block_y, interpret=False)

        closed = trace(fn, _f((S, S, S)), slabs["zlo"], slabs["zhi"],
                       slabs["ylo"], slabs["yhi"], org)
        out = []
        for eqn in iter_eqns(closed.jaxpr):
            if eqn.primitive.name == "pallas_call":
                f, _ = audit_pallas_call(eqn, VMEM_BUDGET_BYTES, "k",
                                         "t", honor_kernel_limit=False)
                out.extend(f)
        return out

    reset_warnings()
    assert audit(16, 128), "the SNIPPETS shape must be flagged"
    assert audit(None, None) == [], "the prescribed shape must be clean"


def test_block_shape_never_changes_numerics():
    """Bitwise equality across block shapes at a small size: the
    planner choosing a different legal shape can never change results
    (same per-point op order by kernel construction)."""
    from stencil_tpu.ops.pallas_stencil import jacobi7_wrap_pallas

    n = 16
    rng = np.random.default_rng(11)
    t = jnp.asarray(rng.random((n, n, n)).astype(np.float32))
    hot, cold, r = (4, 8, 8), (12, 8, 8), 2
    default = np.asarray(jacobi7_wrap_pallas(t, hot, cold, r,
                                             interpret=True))
    for bz, by in ((4, 8), (16, 16), (2, 8)):
        got = np.asarray(jacobi7_wrap_pallas(t, hot, cold, r,
                                             block_z=bz, block_y=by,
                                             interpret=True))
        np.testing.assert_array_equal(got, default, err_msg=(bz, by))


# ---------------------------------------------------------------------------
# tuner integration: planner-legal shapes rank and ride Plan records


def _geom(side=512, itemsize=4):
    from stencil_tpu.geometry import Dim3, Radius
    from stencil_tpu.tuning import TuneGeometry

    return TuneGeometry(
        shard_interior_zyx=(side, side, side),
        min_interior_zyx=(side, side, side),
        radius=Radius.constant(1), counts=Dim3(1, 2, 2),
        elem_sizes=(itemsize,), dtype_strs=("float32",))


def test_tiling_candidate_space_is_planner_legal():
    from stencil_tpu.tuning import (rank_tiling_candidates,
                                    tiling_candidate_space)

    cands = tiling_candidate_space(_geom())
    assert cands and all(c.footprint_bytes <= TILE_SELECT_BUDGET_BYTES
                         for c in cands)
    ranked = rank_tiling_candidates(_geom(), cands)
    costs = [s for s, _c in ranked]
    assert costs == sorted(costs)
    # the winner is the judge-measured 512^3 fast point
    assert (ranked[0][1].block_z, ranked[0][1].block_y) == (8, 128)


def test_plan_record_carries_tiling_and_roundtrips(tmp_path):
    from stencil_tpu.tuning import (FakeTimer, fingerprint_inputs,
                                    load_plan, run_autotune,
                                    tiling_record)
    from stencil_tpu.geometry import Radius

    geom = _geom(side=64)
    inputs = fingerprint_inputs(
        platform="cpu", device_count=4, mesh_shape=[1, 2, 2],
        grid=[64, 128, 128], radius=Radius.constant(1),
        quantities={"q": "float32"}, boundary="PERIODIC")
    cache = tmp_path / "plans.json"
    plan = run_autotune(geom, inputs, FakeTimer(), cache_path=cache)
    assert plan.tiling == tiling_record(geom)
    rec = plan.tiling["jacobi7_halo_pallas"]
    assert rec["block"] and rec["footprint_bytes"] > 0
    # the cached record round-trips the tiling payload bit-for-bit
    cached = load_plan(plan.fingerprint, cache)
    assert cached is not None and cached.tiling == plan.tiling


def test_infeasible_geometry_records_constraint():
    from stencil_tpu.tuning import tiling_candidate_space, tiling_record

    # Y=17: no sublane-aligned block_y exists for the halo kernel
    geom = _geom()
    geom = type(geom)(shard_interior_zyx=(16, 17, 16),
                     min_interior_zyx=(16, 17, 16),
                     radius=geom.radius, counts=geom.counts,
                     elem_sizes=(4,), dtype_strs=("float32",))
    assert tiling_candidate_space(geom)  # esub falls back to 1: legal
    rec = tiling_record(geom)
    assert "jacobi7_halo_pallas" in rec and rec["jacobi7_halo_pallas"]


# ---------------------------------------------------------------------------
# CLI --plan-tiling


def test_cli_plan_tiling(tmp_path, capsys):
    import json

    from stencil_tpu.analysis.__main__ import main

    out = tmp_path / "plans.json"
    rc = main(["--plan-tiling", "*jacobi7_halo_pallas?512?",
               "--json", str(out)])
    assert rc == 0
    text = capsys.readouterr().out
    assert "best (8, 128)" in text
    data = json.loads(out.read_text())
    assert data["mode"] == "plan-tiling"
    (name,) = [k for k in data["plans"]
               if k.endswith("jacobi7_halo_pallas[512]")]
    entry = data["plans"][name]
    assert entry["expect"] == "legal" and entry["findings"] == []
    (kern,) = entry["kernels"].values()
    best = kern["plan"]["options"][0]
    assert (best["block_z"], best["block_y"]) == (8, 128)
    # an unmatched glob is a usage error, same contract as --only
    assert main(["--plan-tiling", "no.such.kernel.*"]) == 2
