"""Particle-in-cell workload: fixed-capacity migration + the PIC step.

The dynamic-communication test base (ROADMAP item 5): the migration
ring's routing/overflow semantics, the deposition adjoint against a
dense oracle, bitwise charge conservation across migrations AND shard
counts (uneven +-1 partitions included), ParticleLoss recovery proven
bitwise through the resilience driver, particle checkpoint lanes
through the hardened checkpoint layer (corrupt walk-back included),
the in-graph overflow column on the sentinel's one all-reduce, the
migration registry gates, and the capacity/budget tuner ranking.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from stencil_tpu.geometry import Dim3
from stencil_tpu.models.pic import (PARTICLE_FIELDS, Pic,
                                    dense_reference_rho)
from stencil_tpu.parallel.migrate import (migrate_shard,
                                          migration_messages,
                                          migration_record_rows)
from stencil_tpu.parallel.mesh import make_mesh


MESH222 = (2, 2, 2)


def _pic(gx=8, gy=8, gz=8, n=40, **kw):
    kw.setdefault("mesh_shape", MESH222)
    kw.setdefault("dtype", np.float64)
    kw.setdefault("dt", 0.25)
    return Pic(gx, gy, gz, n, **kw)


def _uniform_ics(rng, g, n, charges=None):
    return {
        "x": rng.uniform(0, g[0], n), "y": rng.uniform(0, g[1], n),
        "z": rng.uniform(0, g[2], n),
        "vx": np.zeros(n), "vy": np.zeros(n), "vz": np.zeros(n),
        "q": np.ones(n) if charges is None else charges,
    }


def _sorted_particles(p):
    h = p.particles_to_host()
    order = np.lexsort((h["z"], h["y"], h["x"], h["q"]))
    return {k: h[k][order] for k in PARTICLE_FIELDS}


# ----------------------------------------------------------------------
# the migration ring
# ----------------------------------------------------------------------
def _run_migrate(q_vals, valid, offs, cap=8, budget=4):
    mesh = make_mesh(MESH222, jax.devices()[:8])
    counts = Dim3(*MESH222)
    spec = P(("z", "y", "x"))
    psh = NamedSharding(mesh, spec)

    def shard(fields, v, ox, oy, oz):
        f, vv, ovf = migrate_shard(fields, v, (ox, oy, oz), counts,
                                   budget)
        return f, vv, ovf.reshape(1)

    sm = jax.jit(jax.shard_map(
        shard, mesh=mesh, in_specs=({"q": spec}, spec, spec, spec, spec),
        out_specs=({"q": spec}, spec, spec), check_vma=False))
    dev = lambda a: jax.device_put(a, psh)  # noqa: E731
    f, vv, ovf = sm({"q": dev(q_vals)}, dev(valid),
                    *(dev(o) for o in offs))
    return np.asarray(f["q"]), np.asarray(vv), np.asarray(ovf), cap


def _blocks(q, valid, cap):
    out = {}
    for b in range(8):
        sel = valid[b * cap:(b + 1) * cap]
        vals = q[b * cap:(b + 1) * cap][sel]
        if len(vals):
            out[b] = sorted(vals.tolist())
    return out


def test_migrate_face_edge_corner_routing():
    """A stayer, a +x face hop, and a (+x,+y,+z) corner hop (three
    sequential ring hops) all land on the owning shard, payload
    bitwise-intact, zero overflow."""
    cap = 8
    q = np.zeros(8 * cap)
    valid = np.zeros(8 * cap, bool)
    ox = np.zeros(8 * cap, np.int32)
    oy = np.zeros(8 * cap, np.int32)
    oz = np.zeros(8 * cap, np.int32)
    valid[0:3] = True
    q[0:3] = [10.0, 11.0, 12.0]
    ox[1] = 1
    ox[2] = oy[2] = oz[2] = 1
    qq, vv, ovf, cap = _run_migrate(q, valid, (ox, oy, oz), cap=cap)
    assert ovf.sum() == 0
    # P(('z','y','x')) block order: shard (x=1,y=0,z=0) -> block 1,
    # shard (1,1,1) -> block 7
    assert _blocks(qq, vv, cap) == {0: [10.0], 1: [11.0], 7: [12.0]}


def test_migrate_periodic_wrap():
    """-x from shard 0 wraps the ring onto the last x shard."""
    cap = 8
    q = np.zeros(8 * cap)
    valid = np.zeros(8 * cap, bool)
    valid[0] = True
    q[0] = 5.0
    ox = np.zeros(8 * cap, np.int32)
    ox[0] = -1
    zero = np.zeros(8 * cap, np.int32)
    qq, vv, ovf, cap = _run_migrate(q, valid, (ox, zero, zero), cap=cap)
    assert ovf.sum() == 0
    assert _blocks(qq, vv, cap) == {1: [5.0]}


def test_migrate_send_budget_overflow_counts_and_drops():
    """Leavers beyond the per-direction budget are dropped and counted
    — never silently retained on the wrong shard."""
    cap = 8
    q = np.zeros(8 * cap)
    valid = np.zeros(8 * cap, bool)
    valid[0:6] = True
    q[0:6] = np.arange(1.0, 7.0)
    ox = np.zeros(8 * cap, np.int32)
    ox[0:6] = 1
    zero = np.zeros(8 * cap, np.int32)
    qq, vv, ovf, cap = _run_migrate(q, valid, (ox, zero, zero),
                                    cap=cap, budget=4)
    assert ovf.sum() == 2
    assert vv[:cap].sum() == 0          # every leaver left block 0
    assert vv[cap:2 * cap].sum() == 4   # only budget-many arrived


def test_migrate_receive_capacity_overflow():
    """Arrivals beyond the receiver's free slots are dropped and
    counted."""
    cap = 4
    q = np.zeros(8 * cap)
    valid = np.zeros(8 * cap, bool)
    # block 1 (shard x=1) is FULL; block 0 sends it 2 particles
    valid[cap:2 * cap] = True
    q[cap:2 * cap] = 100.0
    valid[0:2] = True
    q[0:2] = [1.0, 2.0]
    ox = np.zeros(8 * cap, np.int32)
    ox[0:2] = 1
    zero = np.zeros(8 * cap, np.int32)
    qq, vv, ovf, _ = _run_migrate(q, valid, (ox, zero, zero),
                                  cap=cap, budget=4)
    assert ovf.sum() == 2               # both arrivals dropped
    assert vv[cap:2 * cap].sum() == cap  # receiver unchanged


def test_migration_messages_and_record_rows():
    assert migration_messages(Dim3(2, 2, 2)) == 6
    assert migration_messages(Dim3(1, 2, 1)) == 2
    assert migration_messages(Dim3(1, 1, 1)) == 0
    assert migration_record_rows(7) == 8  # 7 fields + 1 packed control row


# ----------------------------------------------------------------------
# deposition + reverse halo-accumulate
# ----------------------------------------------------------------------
@pytest.mark.parametrize("dep", ["ngp", "cic"])
@pytest.mark.parametrize("grid", [(8, 8, 8), (9, 9, 9)])
def test_deposit_accumulate_matches_dense_oracle(dep, grid):
    """deposit + reverse accumulate + exchange over the sharded
    (even AND uneven +-1) mesh reproduces the dense periodic oracle —
    NGP bitwise, CIC to roundoff (scatter order differs)."""
    rng = np.random.default_rng(1)
    n = 40
    ics = _uniform_ics(rng, grid, n)
    p = _pic(*grid, n=n, deposition=dep)
    p.set_particles(ics)
    p.step()
    oracle = dense_reference_rho(ics["x"], ics["y"], ics["z"], ics["q"],
                                 grid, deposition=dep)
    if dep == "ngp":
        assert np.array_equal(p.rho(), oracle)
    else:
        np.testing.assert_allclose(p.rho(), oracle, rtol=0, atol=1e-12)
    assert p.overflow_total() == 0


# ----------------------------------------------------------------------
# charge conservation (the satellite property test)
# ----------------------------------------------------------------------
@pytest.mark.parametrize("grid", [(8, 8, 8), (9, 9, 9)])
def test_total_charge_bitwise_across_migrations_and_meshes(grid):
    """Total deposited charge is BITWISE-preserved across migrations
    and shard counts, including uneven +-1 partitions: NGP deposits of
    unit charges are exact integer sums in f64, so every step's grid
    total equals the particle count exactly on ANY mesh."""
    rng = np.random.default_rng(3)
    n = 48
    ics = _uniform_ics(rng, grid, n)
    totals = {}
    for ms, nd in (((1, 1, 1), 1), (MESH222, 8)):
        p = _pic(*grid, n=n, mesh_shape=ms, deposition="ngp",
                 devices=jax.devices()[:nd])
        p.set_particles(ics)
        seq = []
        for _ in range(5):
            p.step()
            seq.append(p.total_charge())
        assert p.overflow_total() == 0
        totals[ms] = seq
    assert totals[(1, 1, 1)] == totals[MESH222]
    assert all(t == float(n) for t in totals[MESH222])


def test_trajectory_and_rho_bitwise_across_meshes_cic():
    """With dyadic ICs (1/8-lattice positions, integer charges, dyadic
    dt) the CIC arithmetic is exact, so particles AND the deposited
    rho are bitwise-identical between the 1-device and 8-device runs
    after multiple push+migrate steps."""
    rng = np.random.default_rng(5)
    n = 16
    lat = rng.integers(0, 64, size=(3, n)) / 8.0
    ics = {"x": lat[0], "y": lat[1], "z": lat[2],
           "vx": np.zeros(n), "vy": np.zeros(n), "vz": np.zeros(n),
           "q": np.arange(1.0, n + 1.0)}
    res = {}
    for ms, nd in (((1, 1, 1), 1), (MESH222, 8)):
        p = _pic(8, 8, 8, n=n, mesh_shape=ms, deposition="cic",
                 devices=jax.devices()[:nd])
        p.set_particles(ics)
        p.run(2)
        res[ms] = (_sorted_particles(p), p.rho())
    solo, rho_solo = res[(1, 1, 1)]
    dist, rho_dist = res[MESH222]
    for k in PARTICLE_FIELDS:
        assert np.array_equal(solo[k], dist[k]), k
    assert np.array_equal(rho_solo, rho_dist)


# ----------------------------------------------------------------------
# megastep: the PIC carry contract (fused == stepwise, bitwise)
# ----------------------------------------------------------------------
@pytest.mark.parametrize("grid", [(8, 8, 8), (9, 9, 9)])
def test_pic_segment_bitwise_even_and_uneven(grid):
    """A fused PIC segment == the stepwise dispatch loop BITWISE on
    the full carried state: rho, every particle SoA lane, the validity
    mask, AND the cumulative overflow column — even 8^3 and uneven
    (+-1) 9^3 partitions. The trace rows carry the contract's 9 probe
    columns with the overflow column riding the single all-reduce."""
    from stencil_tpu.models.pic import PARTICLE_STATE_KEYS

    a = _pic(*grid, n=40, deposition="cic", seed=5)
    b = _pic(*grid, n=40, deposition="cic", seed=5)
    for _ in range(4):
        a.step()
    seg = b.make_segment(4)
    assert seg and seg.steps == 4
    tr = seg.run(0)
    host = np.asarray(tr.array)
    assert host.shape == (4, 2, 9)  # rows x stats x (rho+7 lanes+ovf)
    # the overflow column reports the live counter (zero here) and the
    # health columns are real reductions over the carried state
    np.testing.assert_array_equal(host[:, 0, 8], 0.0)
    assert (host[:, 1, :8] > 0).any()
    for k in PARTICLE_STATE_KEYS + ("rho",):
        np.testing.assert_array_equal(np.asarray(a.state[k]),
                                      np.asarray(b.state[k]),
                                      err_msg=k)


def test_pic_segment_trace_reports_overflow_column():
    """A budget=1 migration burst drops particles mid-segment: the
    trace rows' overflow column (the probe's max-reduction over the
    per-shard cumulative counters) goes nonzero IN-GRAPH, without any
    separate probe dispatch."""
    rng = np.random.default_rng(3)
    n = 24
    p = _pic(8, 8, 8, n=n, deposition="ngp", budget=1, seed=1)
    # a burst crossing the same +x boundary: several leavers, budget 1
    # (the test_sentinel_reports_nonzero_overflow setup, fused)
    ics = _uniform_ics(rng, (8, 8, 8), n)
    ics["x"] = np.full(n, 3.9)   # just inside shard x=0
    ics["vx"] = np.full(n, 1.0)  # all cross next step
    p.set_particles(ics)
    tr = p.make_segment(3).run(0)
    host = np.asarray(tr.array)
    # the column is the probe's per-shard MAX of the cumulative
    # counter; the exported total is the all-shard SUM — zero iff no
    # shard dropped anything, and never above the sum
    assert host[-1, 0, 8] > 0
    assert host[-1, 0, 8] <= p.overflow_total()


# ----------------------------------------------------------------------
# ParticleLoss + resilience (bitwise recovery, fused AND stepwise)
# ----------------------------------------------------------------------
@pytest.mark.parametrize("fused", [True, False])
def test_particle_loss_recovery_bitwise(tmp_path, fused):
    """A ParticleLoss fault trips the sentinel (the NaN'd charge lane
    is probed non-finite), rolls back to the checkpoint whose extras
    carry the particle lanes, and the recovered run ends BITWISE-equal
    to the fault-free run — fields and particles both, under the fused
    megastep driver (default) and the stepwise loop, with the trip at
    the EXACT injected step in both modes."""
    from stencil_tpu.resilience import (FaultPlan, ParticleLoss,
                                        ResiliencePolicy)

    def mk():
        return _pic(8, 8, 8, n=40, deposition="cic", seed=7)

    ref = mk()
    for _ in range(8):
        ref.step()
    ref_parts = _sorted_particles(ref)
    ref_rho = ref.rho()

    p = mk()
    plan = FaultPlan()
    plan.particle_losses.append(
        ParticleLoss(step=5, count=2, shard=(0, 0, 0)))
    pol = ResiliencePolicy(check_every=1, ckpt_every=4, base_delay=0.0,
                           sleep=lambda s: None, fuse_segments=fused)
    rep = p.run_resilient(8, policy=pol, ckpt_dir=str(tmp_path),
                          faults=plan)
    assert rep.steps == 8
    assert rep.rollbacks >= 1
    assert rep.fused is fused
    kinds = [e["event"] for e in rep.events]
    assert "fault_particle_loss" in kinds and "restored" in kinds
    trip = [e for e in rep.events if e["event"] == "sentinel_tripped"][0]
    assert trip["step"] == 5
    assert "'q'" in trip["reason"]
    assert np.array_equal(p.rho(), ref_rho)
    got = _sorted_particles(p)
    for k in PARTICLE_FIELDS:
        assert np.array_equal(ref_parts[k], got[k]), k


def test_particle_loss_counter_exported():
    """run_resilient exports stencil_run_particles_total through the
    process metrics registry (README metric-table contract)."""
    from stencil_tpu.telemetry import get_registry

    reg = get_registry()
    c = reg.counter("stencil_run_particles_total", "")
    before = c.value()
    p = _pic(8, 8, 8, n=24, deposition="ngp")
    from stencil_tpu.resilience import ResiliencePolicy
    pol = ResiliencePolicy(check_every=2, base_delay=0.0,
                           sleep=lambda s: None)
    p.run_resilient(4, policy=pol)
    assert c.value() - before == 4 * 24
    o = reg.counter("stencil_run_migration_overflow_total", "")
    assert o.value() >= 0.0


def test_particle_loss_noop_without_particle_state():
    """On a domain without particle lanes the fault warns and no-ops
    instead of corrupting unrelated state."""
    from stencil_tpu.models.jacobi import Jacobi3D
    from stencil_tpu.resilience import ParticleLoss

    j = Jacobi3D(8, 8, 8, mesh_shape=MESH222, dtype=np.float64,
                 kernel="xla")
    j.init()
    ev = ParticleLoss(step=1)
    logged = []
    ev.fire(j.dd, lambda kind, **kw: logged.append(kind),
            fields=j.dd.curr)
    assert not logged
    assert not np.isnan(j.temperature()).any()


# ----------------------------------------------------------------------
# checkpoint roundtrip for particle lanes as extras
# ----------------------------------------------------------------------
def test_particle_checkpoint_roundtrip_and_corrupt_walkback(tmp_path):
    """Particle lanes ride checkpoints as extras through the hardened
    utils/checkpoint.py layer: save/restore is bitwise, and a
    corrupted newest step walks back to the older one."""
    from stencil_tpu.resilience.faults import CheckpointCorruption
    from stencil_tpu.utils.checkpoint import restore_domain, save_domain

    p = _pic(8, 8, 8, n=32, deposition="cic", seed=11)
    p.run(2)
    snap0 = _sorted_particles(p)
    save_domain(p.dd, str(tmp_path), 0, extra=p._particle_extras())
    p.run(2)
    save_domain(p.dd, str(tmp_path), 4, extra=p._particle_extras())
    snap4 = _sorted_particles(p)

    # clean restore of the newest step is bitwise
    p.run(1)
    step, extras = restore_domain(p.dd, str(tmp_path))
    assert step == 4
    p.state["rho"] = p.dd.curr["rho"]
    p._install_particles(extras)
    got = _sorted_particles(p)
    for k in PARTICLE_FIELDS:
        assert np.array_equal(snap4[k], got[k]), k

    # corrupt the newest step: restore must walk back to step 0 with
    # the step-0 particle lanes intact
    corr = CheckpointCorruption(step=4, mode="truncate")
    corr.fire(str(tmp_path), 4, np.random.default_rng(0),
              lambda *a, **k: None)
    step, extras = restore_domain(p.dd, str(tmp_path))
    assert step == 0
    p.state["rho"] = p.dd.curr["rho"]
    p._install_particles(extras)
    got = _sorted_particles(p)
    for k in PARTICLE_FIELDS:
        assert np.array_equal(snap0[k], got[k]), k


# ----------------------------------------------------------------------
# sentinel: the in-graph overflow column
# ----------------------------------------------------------------------
def test_sentinel_decodes_overflow_column_and_trips_on_nan():
    """The migration-overflow counter rides the probe's ONE all-reduce
    as an extra column and decodes into HealthStats.metrics; a NaN'd
    particle lane trips the same probe."""
    p = _pic(8, 8, 8, n=24, deposition="ngp")
    s = p.make_sentinel()
    s.probe(p.state, 3)
    stats = s.poll(block=True)[0]
    assert stats.step == 3
    assert not stats.tripped
    assert stats.metrics == {"migration_overflow": 0.0}
    # poison one charge record: the q lane is probed non-finite
    p.state["q"] = p.state["q"].at[0].set(float("nan"))
    s.probe(p.state, 4)
    stats = s.poll(block=True)[-1]
    assert stats.tripped and "q" in stats.reason


def test_cfl_violation_dropped_and_counted():
    """A particle faster than one shard per step cannot be routed by
    the +-1 ring: it must be DROPPED and COUNTED as overflow — never
    shipped one hop short, where its deposits would silently vanish
    and total charge would drift with no operator signal."""
    n = 4
    p = _pic(8, 8, 8, n=n, deposition="ngp", capacity=8, seed=0)
    ics = {"x": np.array([1.0, 2.0, 3.0, 3.5]),
           "y": np.full(n, 2.0), "z": np.full(n, 2.0),
           # particle 0 jumps 10 cells = 2+ shards of the 4-cell
           # x-extent (a 1-shard hop would still be ring-routable)
           "vx": np.array([40.0, 0.0, 0.0, 0.0]),
           "vy": np.zeros(n), "vz": np.zeros(n), "q": np.ones(n)}
    p.set_particles(ics)
    p.step()
    assert p.overflow_total() == 1.0
    h = p.particles_to_host()
    assert len(h["q"]) == n - 1
    # the survivors' charge is all that deposits from here on
    p.step()
    assert p.total_charge() == float(n - 1)


def test_sentinel_reports_nonzero_overflow():
    """Drive a real overflow (budget 1, clustered burst) and read the
    counter back through the sentinel metrics column."""
    rng = np.random.default_rng(2)
    n = 24
    p = _pic(8, 8, 8, n=n, deposition="ngp", budget=1, seed=2)
    # a burst crossing the same +x boundary: several leavers, budget 1
    ics = _uniform_ics(rng, (8, 8, 8), n)
    ics["x"] = np.full(n, 3.9)   # just inside shard x=0
    ics["vx"] = np.full(n, 1.0)  # all cross next step
    p.set_particles(ics)
    p.step()
    assert p.overflow_total() > 0
    s = p.make_sentinel()
    s.probe(p.state, 1)
    stats = s.poll(block=True)[0]
    assert stats.metrics["migration_overflow"] > 0


# ----------------------------------------------------------------------
# registry gates
# ----------------------------------------------------------------------
def test_pic_registry_targets_pin_the_collective_bill():
    """models.pic.step[hlo] pins 18 collective-permutes (accumulate +
    exchange + migrate, 6 each) and nothing else; the cost target's
    modeled bytes match the lowered HLO exactly; the probe target pins
    one all-reduce."""
    from stencil_tpu.analysis.hlo import check_hlo
    from stencil_tpu.analysis.costmodel import check_costmodel
    from stencil_tpu.analysis.registry import default_targets

    targets = {t.name: t for t in default_targets()}
    for name in ("models.pic.step[hlo]", "models.pic.probe[hlo]",
                 "parallel.migrate.migrate_shard[hlo]"):
        findings, metrics = check_hlo(targets[name])
        assert findings == [], (name, findings)
    f, metrics = check_costmodel(targets["models.pic.step[cost]"])
    assert f == []
    assert (metrics["observed_bytes_per_shard"]
            == metrics["expected_bytes_per_shard"])
    f, metrics = check_costmodel(
        targets["parallel.migrate.migrate_shard[cost]"])
    assert f == []
    assert (metrics["observed_bytes_per_shard"]
            == metrics["expected_bytes_per_shard"])


def test_bad_migration_fixture_is_flagged():
    """The all-gather 'migration' negative control must be flagged by
    the hlo checker — the ppermute-only gate is not vacuous for the
    dynamic pattern."""
    import pathlib

    from stencil_tpu.analysis import run_targets
    from stencil_tpu.analysis.registry import load_targets

    fx = (pathlib.Path(__file__).parent / "fixtures" / "lint"
          / "bad_migration.py")
    report = run_targets(load_targets(fx))
    assert report.errors
    assert any("all_gather" in f.message for f in report.findings)


def test_migration_bytes_model_identity():
    """The model the registry cross-checks: 2 messages per active axis
    x record rows x budget x itemsize."""
    from stencil_tpu.analysis.costmodel import (
        migration_wire_bytes_per_shard)

    assert migration_wire_bytes_per_shard(7, 8, Dim3(2, 2, 2), 4) \
        == 6 * 8 * 8 * 4
    assert migration_wire_bytes_per_shard(7, 8, Dim3(1, 1, 2), 4) \
        == 2 * 8 * 8 * 4


# ----------------------------------------------------------------------
# tuning: capacity/budget ranking
# ----------------------------------------------------------------------
def test_migration_tuner_ranks_smallest_safe_budget():
    from stencil_tpu.tuning import rank_migration_candidates

    ranked = rank_migration_candidates(
        particles_per_shard=256, n_fields=7, counts=Dim3(2, 2, 2),
        elem_size=4, max_crossing_fraction=0.1)
    costs = [c for c, _ in ranked]
    assert costs == sorted(costs)
    best = ranked[0][1]
    # the winner's budget clears the safety floor but is the smallest
    # that does (wire bytes scale with budget)
    need = int(256 * 0.1 * 1.5) + 1
    assert best.budget >= need
    assert all(cand.budget >= best.budget for _, cand in ranked)


def test_migration_tuner_scales_budget_with_flux():
    from stencil_tpu.tuning import rank_migration_candidates

    lo = rank_migration_candidates(256, 7, Dim3(2, 2, 2), 4,
                                   max_crossing_fraction=0.05)[0][1]
    hi = rank_migration_candidates(256, 7, Dim3(2, 2, 2), 4,
                                   max_crossing_fraction=0.5)[0][1]
    assert hi.budget > lo.budget


def test_migration_tuner_rejects_unsafe_everything():
    from stencil_tpu.tuning import (MigrationCandidate,
                                    rank_migration_candidates)

    with pytest.raises(ValueError, match="no feasible"):
        rank_migration_candidates(
            256, 7, Dim3(2, 2, 2), 4, max_crossing_fraction=1.0,
            candidates=[MigrationCandidate(512, 4)])


# ----------------------------------------------------------------------
# model ergonomics
# ----------------------------------------------------------------------
def test_capacity_and_budget_validation():
    with pytest.raises(ValueError, match="capacity"):
        _pic(8, 8, 8, n=64, capacity=4)
    with pytest.raises(ValueError, match="budget"):
        _pic(8, 8, 8, n=8, capacity=16, budget=0)
    with pytest.raises(ValueError, match="deposition"):
        _pic(8, 8, 8, n=8, deposition="tsc")
    with pytest.raises(ValueError, match="outside"):
        p = _pic(8, 8, 8, n=4)
        p.set_particles({"x": np.array([9.5, 1, 1, 1]),
                         "y": np.ones(4), "z": np.ones(4)})


def test_migration_stats_surface():
    p = _pic(8, 8, 8, n=24, capacity=16, budget=4)
    st = p.migration_stats()
    assert st["capacity"] == 16 and st["budget"] == 4
    assert st["record_bytes"] == (len(PARTICLE_FIELDS) + 1) * 8
    assert st["migration_bytes_per_shard"] == 6 * 8 * 4 * 8
