"""Interior/exterior overlap decomposition correctness.

The overlapped step must produce the same state as the fused step
(the reference validates its overlap choreography the same way: the
jacobi/astaroth results don't depend on the interior/exterior split,
bin/jacobi3d.cu:296-377)."""

import numpy as np
import pytest

from stencil_tpu._compat import remote_dma_runnable
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel.overlap import split_regions


class TestSplitRegions:
    def test_covers_interior(self):
        local = Dim3(8, 6, 5)
        r = Radius.constant(2)
        inner, ext = split_regions(r, local)
        seen = np.zeros((local.z, local.y, local.x), dtype=int)
        for off, dims in inner + ext:
            seen[off.z:off.z + dims.z, off.y:off.y + dims.y,
                 off.x:off.x + dims.x] += 1
        assert (seen >= 1).all(), "every interior point computed"
        # inner region covered exactly once
        assert seen[2:-2, 2:-2, 2:-2].max() == 1

    def test_inner_reads_stay_owned(self):
        local = Dim3(8, 8, 8)
        r = Radius.constant(3)
        inner, _ = split_regions(r, local)
        (off, dims), = inner
        for a, (o, d) in enumerate(((off.x, dims.x), (off.y, dims.y),
                                    (off.z, dims.z))):
            assert o - r.face(a, -1) >= 0
            assert o + d + r.face(a, 1) <= local[a]

    def test_thin_shard_no_inner(self):
        local = Dim3(4, 4, 4)
        r = Radius.constant(2)
        inner, ext = split_regions(r, local)
        assert inner == []
        assert len(ext) == 1  # whole interior as one region

    def test_asymmetric_radius_slabs(self):
        local = Dim3(8, 8, 8)
        r = Radius.constant(0)
        r.set_dir((1, 0, 0), 2)
        r.set_dir((-1, 0, 0), 1)
        inner, ext = split_regions(r, local)
        (off, dims), = inner
        assert (off.x, dims.x) == (1, 5)  # [1, 8-2)
        assert (off.y, dims.y) == (0, 8)
        assert len(ext) == 2  # only +-x slabs


def test_jacobi_overlap_matches_fused():
    from stencil_tpu.models.jacobi import Jacobi3D

    n = 16
    a = Jacobi3D(n, n, n, mesh_shape=(2, 2, 2), dtype=np.float32)
    b = Jacobi3D(n, n, n, mesh_shape=(2, 2, 2), dtype=np.float32,
                 overlap=True)
    a.init()
    b.init()
    for _ in range(4):
        a.step()
        b.step()
    np.testing.assert_allclose(b.temperature(), a.temperature(), atol=1e-6)


@pytest.mark.skipif(
    not remote_dma_runnable(),
    reason="Pallas remote DMA needs a TPU backend or the distributed "
           "(mosaic) TPU interpreter")
def test_jacobi_overlap_kernel_in_kernel_rdma():
    """overlap=True on an x-unsharded even mesh routes to the in-kernel
    RDMA overlap kernel (ops/pallas_overlap.py) — interior computed
    while slabs fly, faces fixed after. Must match the dense oracle
    over several steps, odd and even counts (ripple analog of
    reference src/stencil.cu:1081-1118 overlap choreography)."""
    import jax

    from stencil_tpu.models.jacobi import Jacobi3D, dense_reference_step

    n = 32
    for mesh_shape in [(1, 2, 4), (1, 4, 2)]:
        # kernel="halo" + overlap opts into the RDMA overlap kernel
        # even off-TPU (auto only takes it on hardware)
        j = Jacobi3D(n, n, n, mesh_shape=mesh_shape, dtype=np.float32,
                     overlap=True, kernel="halo")
        # confirm the overlap kernel path was selected (not the XLA
        # interior/exterior split)
        assert j.kernel_path == "overlap", j.kernel_path
        j.init()
        temp = j.temperature()
        hot = (n // 3, n // 2, n // 2)
        cold = (2 * n // 3, n // 2, n // 2)
        for _ in range(3):
            temp = dense_reference_step(temp, hot, cold, n // 10)
            j.step()
        np.testing.assert_allclose(j.temperature(), temp, atol=2e-6,
                                   err_msg=str(mesh_shape))
        j.run(2)
        for _ in range(2):
            temp = dense_reference_step(temp, hot, cold, n // 10)
        np.testing.assert_allclose(j.temperature(), temp, atol=2e-6)


@pytest.mark.slow
@pytest.mark.skipif(
    not remote_dma_runnable(),
    reason="Pallas remote DMA needs a TPU backend or the distributed "
           "(mosaic) TPU interpreter")
@pytest.mark.parametrize("mesh_shape,size,thinz,pair", [
    # (1,2,2) on (16,16,48): local (16,8,24) -> nzg=3, exercising BOTH
    # fix-up strips (z edges + the middle y strip); (1,1,2) on
    # (16,16,32): local z=16 -> nzg=2, z strips cover everything and
    # the y axis is a local wrap; the thinz=0 case runs the slabless
    # interior plan AND the fix-up plan in tiled-z mode
    ((1, 2, 2), (16, 16, 48), "1", "0"),
    ((1, 1, 2), (16, 16, 32), "1", "0"),
    ((1, 1, 2), (16, 16, 32), "0", "0"),
    # tiled-z through BOTH fix-up strips (nzg=3 -> the y strip's
    # tiled z-segment remap is exercised too)
    ((1, 2, 2), (16, 16, 48), "0", "0"),
    # fused substep-0+1 pair composed with the overlap path: one
    # radius-2R overlapped exchange per pair, both fix-up strips —
    # under both window plans (tiled-z slices rr=6 differently)
    ((1, 2, 2), (16, 16, 48), "1", "1"),
    ((1, 1, 2), (16, 16, 32), "0", "1")])
def test_astaroth_rdma_overlap_matches_xla(mesh_shape, size, thinz,
                                           pair, monkeypatch):
    """The in-kernel RDMA overlap path (ops/pallas_mhd_overlap.py):
    slab RDMA behind the fused interior compute + strip fix-ups must
    match the XLA oracle exactly like the sequential halo path does
    (reference choreography: astaroth/astaroth.cu:552-646)."""
    import jax

    from stencil_tpu.models.astaroth import FIELDS, Astaroth

    monkeypatch.setenv("STENCIL_MHD_THINZ", thinz)
    monkeypatch.setenv("STENCIL_MHD_PAIR", pair)

    ndev = mesh_shape[0] * mesh_shape[1] * mesh_shape[2]
    a = Astaroth(*size, mesh_shape=(1, 1, 1), dtype=np.float64,
                 devices=jax.devices()[:1], kernel="xla")
    b = Astaroth(*size, mesh_shape=mesh_shape, dtype=np.float64,
                 devices=jax.devices()[:ndev], kernel="halo",
                 overlap=True)
    assert b.kernel_path == "halo-overlap", b.kernel_path
    # the pair cases must actually engage pair mode (guard against the
    # gate silently falling back to the already-covered non-pair path)
    assert b._slab_exchange_cfg["pair"] == (pair == "1")
    for m in (a, b):
        m.init()
        m.step()
        m.step()
    for q in FIELDS:
        np.testing.assert_allclose(b.field(q), a.field(q), rtol=1e-11,
                                   atol=1e-13, err_msg=q)


@pytest.mark.slow
def test_astaroth_overlap_matches_fused():
    from stencil_tpu.models.astaroth import Astaroth, MhdParams

    prm = MhdParams()
    a = Astaroth(16, 16, 16, params=prm, mesh_shape=(2, 2, 2),
                 dtype=np.float64)
    b = Astaroth(16, 16, 16, params=prm, mesh_shape=(2, 2, 2),
                 dtype=np.float64, overlap=True)
    a.init()
    b.init()
    a.step()
    b.step()
    for q in ("lnrho", "uux", "ss", "ax"):
        np.testing.assert_allclose(b.field(q), a.field(q),
                                   rtol=1e-10, atol=1e-12)
