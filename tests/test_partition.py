"""Partition / topology / placement-solver tests mirroring the
reference's test/test_cpu_partition.cpp and test_cpu_qap.cpp pinned
arithmetic."""

import numpy as np
import pytest

from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.partition import (NodePartition, RankPartition,
                                   partition_dims_even)
from stencil_tpu.qap import cost, make_reciprocal, solve, solve_catch
from stencil_tpu.topology import Boundary, Topology


class TestRankPartition:
    """Pinned cases from reference test/test_cpu_partition.cpp:22-41."""

    def test_10x5x5_into_2(self):
        p = RankPartition((10, 5, 5), 2)
        assert p.dim() == Dim3(2, 1, 1)
        assert p.subdomain_size((0, 0, 0)) == Dim3(5, 5, 5)
        assert p.subdomain_size((1, 0, 0)) == Dim3(5, 5, 5)

    def test_10x3x1_into_4(self):
        p = RankPartition((10, 3, 1), 4)
        assert p.subdomain_size((0, 0, 0)) == Dim3(3, 3, 1)
        assert p.subdomain_size((1, 0, 0)) == Dim3(3, 3, 1)
        assert p.subdomain_size((2, 0, 0)) == Dim3(2, 3, 1)
        assert p.subdomain_size((3, 0, 0)) == Dim3(2, 3, 1)
        assert p.subdomain_origin((0, 0, 0)) == Dim3(0, 0, 0)
        assert p.subdomain_origin((1, 0, 0)) == Dim3(3, 0, 0)
        assert p.subdomain_origin((2, 0, 0)) == Dim3(6, 0, 0)
        assert p.subdomain_origin((3, 0, 0)) == Dim3(8, 0, 0)

    def test_10x5x5_into_3(self):
        p = RankPartition((10, 5, 5), 3)
        assert p.subdomain_size((0, 0, 0)) == Dim3(4, 5, 5)
        assert p.subdomain_size((1, 0, 0)) == Dim3(3, 5, 5)
        assert p.subdomain_size((2, 0, 0)) == Dim3(3, 5, 5)

    def test_13x7x7_into_4(self):
        p = RankPartition((13, 7, 7), 4)
        assert p.subdomain_size((0, 0, 0)) == Dim3(4, 7, 7)
        assert p.subdomain_size((1, 0, 0)) == Dim3(3, 7, 7)
        assert p.subdomain_size((2, 0, 0)) == Dim3(3, 7, 7)
        assert p.subdomain_size((3, 0, 0)) == Dim3(3, 7, 7)

    def test_10x14x2_into_9(self):
        p = RankPartition((10, 14, 2), 9)
        assert p.subdomain_origin((0, 0, 0)) == Dim3(0, 0, 0)
        assert p.subdomain_origin((1, 1, 0)) == Dim3(4, 5, 0)
        assert p.subdomain_origin((2, 2, 0)) == Dim3(7, 10, 0)

    def test_sizes_tile_exactly(self):
        # subdomain sizes and origins must tile the global grid
        p = RankPartition((13, 7, 7), 6)
        d = p.dim()
        total = 0
        for z in range(d.z):
            for y in range(d.y):
                for x in range(d.x):
                    total += p.subdomain_size((x, y, z)).flatten()
        assert total == 13 * 7 * 7

    def test_linearize_roundtrip(self):
        p = RankPartition((16, 16, 16), 8)
        d = p.dim()
        for i in range(d.flatten()):
            assert p.linearize(p.dimensionize(i)) == i


class TestNodePartition:
    def test_min_interface_split(self):
        # radius only in z -> cutting z is expensive; x/y preferred
        r = Radius.constant(0)
        r.set_dir((0, 0, 1), 2)
        r.set_dir((0, 0, -1), 2)
        p = NodePartition((8, 8, 8), r, 2, 2)
        assert p.dim().z == 1
        assert p.dim().flatten() == 4

    def test_two_level_dims(self):
        r = Radius.constant(1)
        p = NodePartition((64, 64, 64), r, 2, 4)
        assert (p.sys_dim() * p.node_dim()).flatten() == 8
        assert p.dim() == p.sys_dim() * p.node_dim()

    def test_sizes_tile_exactly(self):
        r = Radius.constant(1)
        p = NodePartition((13, 7, 7), r, 2, 2)
        d = p.dim()
        total = 0
        for z in range(d.z):
            for y in range(d.y):
                for x in range(d.x):
                    total += p.subdomain_size((x, y, z)).flatten()
        assert total == 13 * 7 * 7


class TestPartitionDimsEven:
    def test_exact_when_divisible(self):
        d = partition_dims_even((64, 64, 64), 8)
        assert d.flatten() == 8
        assert Dim3(64, 64, 64) % d == Dim3(0, 0, 0)

    def test_finds_divisor_shape(self):
        d = partition_dims_even((12, 10, 1), 4)
        assert d.flatten() == 4
        assert Dim3(12, 10, 1) % d == Dim3(0, 0, 0)

    def test_raises_when_impossible(self):
        with pytest.raises(ValueError):
            partition_dims_even((7, 7, 7), 2)


class TestTopology:
    def test_periodic_wrap(self):
        # reference: src/topology.cpp:5-17 (PERIODIC only)
        t = Topology((2, 2, 2))
        n = t.get_neighbor((0, 0, 0), (-1, 0, 0))
        assert n.exists and n.index == Dim3(1, 0, 0)
        n = t.get_neighbor((1, 1, 1), (1, 1, 1))
        assert n.exists and n.index == Dim3(0, 0, 0)

    def test_none_boundary(self):
        t = Topology((2, 2, 2), Boundary.NONE)
        assert not t.get_neighbor((0, 0, 0), (-1, 0, 0)).exists
        assert t.get_neighbor((0, 0, 0), (1, 0, 0)).exists


class TestQap:
    """Pinned cases from reference test/test_cpu_qap.cpp:30-60."""

    def test_unbalanced_triangle(self):
        inf = np.inf
        bw = np.array([[inf, 1, 10], [1, inf, 1], [10, 1, inf]])
        comm = np.array([[0, 10, 1], [10, 0, 1], [1, 1, 0.0]])
        f, c = solve(comm, make_reciprocal(bw))
        assert f == [0, 2, 1]

    def test_p9(self):
        bw = np.array([[900, 75, 64, 64],
                       [75, 900, 64, 64],
                       [64, 64, 900, 75],
                       [64, 64, 75, 900.0]])
        comm = np.array([[7, 5, 10, 1],
                         [5, 7, 1, 10],
                         [10, 1, 7, 5],
                         [1, 10, 5, 7.0]])
        f, c = solve(comm, make_reciprocal(bw))
        assert f == [0, 2, 1, 3]

    def test_p9_catch(self):
        bw = np.array([[900, 75, 64, 64],
                       [75, 900, 64, 64],
                       [64, 64, 900, 75],
                       [64, 64, 75, 900.0]])
        comm = np.array([[7, 5, 10, 1],
                         [5, 7, 1, 10],
                         [10, 1, 7, 5],
                         [1, 10, 5, 7.0]])
        dist = make_reciprocal(bw)
        f_exact, c_exact = solve(comm, dist)
        f_catch, c_catch = solve_catch(comm, dist)
        # hill climb must be no worse than identity and match cost()
        assert c_catch <= cost(comm, dist, list(range(4)))
        assert c_catch == pytest.approx(cost(comm, dist, f_catch))

    def test_solver_agreement_random(self):
        rng = np.random.default_rng(0)
        for _ in range(3):
            w = rng.uniform(0, 10, (5, 5))
            np.fill_diagonal(w, 0)
            d = rng.uniform(0.1, 1, (5, 5))
            f, c = solve(w, d)
            assert c == pytest.approx(cost(w, d, f))


class TestExactPartitionCandidates:
    def test_enumerates_exact_factorizations_only(self):
        from stencil_tpu.partition import exact_partition_candidates

        cands = exact_partition_candidates((32, 16, 16), 8)
        assert Dim3(8, 1, 1) in cands
        assert Dim3(2, 2, 2) in cands
        for dim in cands:
            assert dim.flatten() == 8
            assert Dim3(32, 16, 16) % dim == Dim3(0, 0, 0)
        # a prime axis with no exact split yields no candidate there
        assert exact_partition_candidates((7, 7, 7), 8) == []


class TestHierarchicalDcnPlanner:
    """The hierarchical partition planner (_plan_dcn_partition): on a
    DCN-blocked domain the deployed grid must be the candidate the
    per-link alpha-beta model prices cheapest — the slice seam lands
    on the axis with the smallest halo cross-section, and deep
    temporal blocking on that axis must beat the uniform-depth
    trivial baseline in modeled step seconds (ISSUE 19 acceptance)."""

    def _domain(self, depths=None):
        import jax

        from stencil_tpu.distributed import DistributedDomain

        devs = jax.devices()[:8]
        dd = DistributedDomain(32, 16, 16, devices=devs)
        dd.set_radius(1)
        dd.add_data("q", np.float32)
        if depths is not None:
            dd.set_exchange_every(depths)
        dd.set_dcn_axis(groups=[devs[:4], devs[4:]])
        dd.realize()
        return dd

    def test_planner_minimizes_dcn_cross_section(self):
        from stencil_tpu.parallel.mesh import mesh_dim

        dd = self._domain()
        # 32x16x16 over 8 devices, 2 slices: (8,1,1) puts the seam on
        # x where the cross-section (16*16) is smallest per face pair
        # and leaves y/z unsharded (zero ICI halo traffic)
        assert tuple(mesh_dim(dd.mesh)) == (8, 1, 1)
        assert dd.dcn_axis == 0
        assert dd.n_slices == 2

    def test_asym_depth_on_dcn_axis_beats_uniform_trivial(self):
        """The acceptance criterion: modeled step seconds of the
        planned grid + deep blocking on the DCN axis beat the
        uniform-depth baseline (the expensive DCN alpha/beta bill is
        paid once per 4 steps instead of every step)."""
        from stencil_tpu.analysis.costmodel import (
            asymmetric_step_seconds)
        from stencil_tpu.parallel.mesh import mesh_dim

        base = self._domain()
        deep = self._domain(depths={"x": 4})

        def seconds(dd):
            local = dd.local_size
            return asymmetric_step_seconds(
                "PpermuteSlab", (local.z, local.y, local.x),
                dd.radius, mesh_dim(dd.mesh), (4,),
                dd.exchange_depths, dcn_axis=dd.dcn_axis)

        assert seconds(deep) < seconds(base)
        # and the planned grid itself beats the naive cube-like split
        # (2,2,2) under the same model and depths
        naive = asymmetric_step_seconds(
            "PpermuteSlab", (8, 8, 16), Radius.constant(1),
            Dim3(2, 2, 2), (4,), deep.exchange_depths, dcn_axis=0)
        assert seconds(deep) < naive
