"""Parity tests for the halo-aware fused Pallas kernels
(ops/pallas_halo.py + parallel/exchange.exchange_interior_slabs):
the multi-device analog of the single-chip wrap kernels, checked
against the dense single-device oracles on the 8-device CPU mesh
(the reference's method-sweep oracle pattern,
test/test_cuda_mpi_exchange.cu:193-234)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from stencil_tpu.geometry import Dim3
from stencil_tpu.models.jacobi import dense_reference_step
from stencil_tpu.ops.pallas_halo import jacobi7_halo_pallas
from stencil_tpu.parallel.exchange import (exchange_interior_slabs,
                                           shard_origin)
from stencil_tpu.parallel.mesh import make_mesh, mesh_dim


def _run_halo_jacobi(global_zyx: np.ndarray, mesh_shape, iters: int = 2):
    """Drive the interior-resident halo step under shard_map."""
    gz, gy, gx = global_zyx.shape
    gsize = Dim3(gx, gy, gz)
    mesh = make_mesh(mesh_shape, jax.devices()[:Dim3.of(mesh_shape).flatten()])
    counts = mesh_dim(mesh)
    assert counts.x == 1, "halo kernels require x unsharded"
    local = Dim3(gx // counts.x, gy // counts.y, gz // counts.z)
    hot = (gsize.x // 3, gsize.y // 2, gsize.z // 2)
    cold = (gsize.x * 2 // 3, gsize.y // 2, gsize.z // 2)
    sph_r = gsize.x // 10
    esub = 8 if local.y % 8 == 0 else 1

    def shard_steps(p, n):
        ox, oy, oz = shard_origin(local, Dim3(0, 0, 0))
        org = jnp.stack([oz, oy, ox]).astype(jnp.int32)

        def body(_, q):
            slabs = exchange_interior_slabs(q, counts, rz=1, ry=esub)
            return jacobi7_halo_pallas(q, slabs, org, hot, cold, sph_r)

        return lax.fori_loop(0, n, body, p)

    spec = P("z", "y", "x")
    sm = jax.shard_map(shard_steps, mesh=mesh, in_specs=(spec, P()),
                       out_specs=spec, check_vma=False)
    fn = jax.jit(sm, donate_argnums=0)
    arr = jax.device_put(jnp.asarray(global_zyx),
                         NamedSharding(mesh, spec))
    return np.asarray(fn(arr, jnp.asarray(iters, jnp.int32)))


@pytest.mark.parametrize("mesh_shape", [(1, 1, 1), (1, 2, 4), (1, 4, 2),
                                        (1, 1, 8), (1, 8, 1)])
def test_jacobi_halo_matches_dense(mesh_shape):
    """(x, y, z) mesh shapes with x unsharded; 2 steps vs dense oracle."""
    gz, gy, gx = 16, 16, 30
    rng = np.random.default_rng(7)
    init = rng.uniform(0.0, 1.0, size=(gz, gy, gx)).astype(np.float32)
    hot = (gx // 3, gy // 2, gz // 2)
    cold = (gx * 2 // 3, gy // 2, gz // 2)
    sph_r = gx // 10
    want = init
    for _ in range(2):
        want = dense_reference_step(want, hot, cold, sph_r)
    got = _run_halo_jacobi(init, mesh_shape, iters=2)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("gzyx,mesh_shape,blocks", [
    ((9, 16, 16), (1, 1, 2), (1, 8)),    # bz=1: row Lz-1 in block nzb-2
    ((9, 17, 16), (1, 2, 2), (1, 8)),    # + uneven y
    ((10, 15, 16), (1, 2, 2), (2, 8)),   # uneven y only, small blocks
])
def test_jacobi_halo_uneven_small_blocks(gzyx, mesh_shape, blocks):
    """Uneven (+-1) shards with explicit small blockings: the zhi slab
    must be fetched with the true y-block wherever row Lz-1 falls
    (regression: the revisit-cache pin to y-block 0 leaked into the
    short shard's last interior row when bz == 1 and nyb > 1)."""
    from stencil_tpu.parallel.exchange import shard_interior_len

    gz, gy, gx = gzyx
    mesh = make_mesh(mesh_shape,
                     jax.devices()[:Dim3.of(mesh_shape).flatten()])
    counts = mesh_dim(mesh)
    from stencil_tpu.numerics import div_ceil
    local = Dim3(gx, div_ceil(gy, counts.y), div_ceil(gz, counts.z))
    rem = Dim3(0, gy % counts.y, gz % counts.z)
    hot = (gx // 3, gy // 2, gz // 2)
    cold = (gx * 2 // 3, gy // 2, gz // 2)
    sph_r = gx // 10
    esub = 8 if local.y % 8 == 0 else 1
    bz, by = blocks

    def shard_step(p):
        ox, oy, oz = shard_origin(local, rem)
        org = jnp.stack([oz, oy, ox]).astype(jnp.int32)
        lens = jnp.stack([
            jnp.asarray(shard_interior_len(2, local.z, rem)),
            jnp.asarray(shard_interior_len(1, local.y, rem)),
        ]).astype(jnp.int32)
        slabs = exchange_interior_slabs(p, counts, rz=1, ry=esub,
                                        rem=rem)
        return jacobi7_halo_pallas(p, slabs, org, hot, cold, sph_r,
                                   block_z=bz, block_y=by,
                                   interior_len_zy=lens)

    spec = P("z", "y", "x")
    sm = jax.jit(jax.shard_map(shard_step, mesh=mesh, in_specs=spec,
                               out_specs=spec, check_vma=False))
    rng = np.random.default_rng(13)
    # capacity-padded global: valid data in the per-shard interiors
    capz = local.z * counts.z
    capy = local.y * counts.y
    init = rng.uniform(0.0, 1.0, (gz, gy, gx)).astype(np.float64)
    want = dense_reference_step(init, hot, cold, sph_r)
    # scatter into capacity layout
    cap = np.zeros((capz, capy, gx))
    for iz in range(counts.z):
        for iy in range(counts.y):
            Lz = local.z - (1 if rem.z and iz >= rem.z else 0)
            Ly = local.y - (1 if rem.y and iy >= rem.y else 0)
            oz = iz * local.z - max(iz - rem.z, 0) if rem.z else iz * local.z
            oy = iy * local.y - max(iy - rem.y, 0) if rem.y else iy * local.y
            cap[iz * local.z:iz * local.z + Lz,
                iy * local.y:iy * local.y + Ly] = \
                init[oz:oz + Lz, oy:oy + Ly]
    got_cap = np.asarray(sm(jax.device_put(
        jnp.asarray(cap), NamedSharding(mesh, spec))))
    # gather back from capacity layout
    got = np.zeros_like(want)
    for iz in range(counts.z):
        for iy in range(counts.y):
            Lz = local.z - (1 if rem.z and iz >= rem.z else 0)
            Ly = local.y - (1 if rem.y and iy >= rem.y else 0)
            oz = iz * local.z - max(iz - rem.z, 0) if rem.z else iz * local.z
            oy = iy * local.y - max(iy - rem.y, 0) if rem.y else iy * local.y
            got[oz:oz + Lz, oy:oy + Ly] = \
                got_cap[iz * local.z:iz * local.z + Lz,
                        iy * local.y:iy * local.y + Ly]
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize("mesh_shape,blocks,steps", [
    ((1, 1, 1), (4, 8), 2),   # nzg=4, nyg=2 on one shard (wrapped slabs)
    ((1, 2, 2), (4, 8), 2),   # sharded + interior blocks both axes
    ((1, 2, 2), (2, 8), 2),   # bz=2 == steps: thinnest legal z block
    ((1, 2, 2), (4, 8), 3),   # depth 3: radius-3 exchange, deeper rings
    ((1, 1, 2), (8, 8), 4),   # depth 4 on a z-split mesh
])
def test_jacobi_halo_pair_multiblock(mesh_shape, blocks, steps):
    """The N-step halo kernel with MULTI-BLOCK grids (nzg > 1 and/or
    nyg > 1): exercises the in-shard ring singles, clamped corner maps,
    and revisit-cache slab pinning that the model-level tests (whose
    small shards collapse to one block) never select."""
    from stencil_tpu.ops.pallas_halo import jacobi7_halon_pallas

    gz, gy, gx = 16, 16, 30
    rng = np.random.default_rng(11)
    init = rng.uniform(0.0, 1.0, size=(gz, gy, gx)).astype(np.float32)
    hot = (gx // 3, gy // 2, gz // 2)
    cold = (gx * 2 // 3, gy // 2, gz // 2)
    sph_r = gx // 10
    bz, by = blocks

    mesh = make_mesh(mesh_shape,
                     jax.devices()[:Dim3.of(mesh_shape).flatten()])
    counts = mesh_dim(mesh)
    local = Dim3(gx, gy // counts.y, gz // counts.z)

    def shard_pair(p):
        ox, oy, oz = shard_origin(local, Dim3(0, 0, 0))
        org = jnp.stack([oz, oy, ox]).astype(jnp.int32)
        slabs = exchange_interior_slabs(p, counts, rz=bz, ry=8,
                                        radius_rows=steps,
                                        y_z_extended=True)
        return jacobi7_halon_pallas(p, slabs, org, (gz, gy, gx), hot,
                                    cold, sph_r, steps=steps,
                                    block_z=bz, block_y=by)

    spec = P("z", "y", "x")
    sm = jax.shard_map(shard_pair, mesh=mesh, in_specs=spec,
                       out_specs=spec, check_vma=False)
    arr = jax.device_put(jnp.asarray(init), NamedSharding(mesh, spec))
    got = np.asarray(jax.jit(sm)(arr))

    want = init
    for _ in range(steps):
        want = dense_reference_step(want, hot, cold, sph_r)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("mesh_shape", [(1, 2, 4), (1, 1, 1)])
def test_jacobi3d_model_halo_kernel(mesh_shape):
    """Jacobi3D(kernel='halo') end-to-end through the orchestrator."""
    from stencil_tpu.models.jacobi import Jacobi3D

    gx, gy, gz = 30, 16, 16
    ndev = mesh_shape[0] * mesh_shape[1] * mesh_shape[2]
    j = Jacobi3D(gx, gy, gz, mesh_shape=mesh_shape, kernel="halo",
                 devices=jax.devices()[:ndev])
    j.init()
    j.run(3)

    hot = (gx // 3, gy // 2, gz // 2)
    cold = (gx * 2 // 3, gy // 2, gz // 2)
    want = np.full((gz, gy, gx), 0.5, dtype=np.float32)
    for _ in range(3):
        want = dense_reference_step(want, hot, cold, gx // 10)
    np.testing.assert_allclose(j.temperature(), want, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
class TestAstarothHalo:
    """MHD halo megakernel (mhd_substep_halo_pallas) parity and the
    interior-resident state protocol."""

    @pytest.mark.parametrize("mesh_shape,thinz,pair", [
        ((1, 2, 4), "1", "0"), ((1, 1, 1), "1", "0"),
        # tiled-z control: the (1,1,1) case has nzg=4, exercising the
        # tiled IN-SHARD z segments that edge-only shards never select
        ((1, 2, 4), "0", "0"), ((1, 1, 1), "0", "0"),
        # fused substep-0+1 pair (STENCIL_MHD_PAIR=1): the (1,2,4) case
        # has nzg=nyg=1 (every block slab-fed on all four sides at the
        # rr=2R window), the (1,1,1) case exercises in-shard rr=6 rows
        # under the tiled-z plan
        ((1, 2, 4), "1", "1"), ((1, 1, 1), "0", "1")])
    def test_halo_matches_xla(self, mesh_shape, thinz, pair, monkeypatch):
        from stencil_tpu.models.astaroth import FIELDS, Astaroth

        monkeypatch.setenv("STENCIL_MHD_THINZ", thinz)
        monkeypatch.setenv("STENCIL_MHD_PAIR", pair)
        size = (16, 16, 32)   # (nx, ny, nz): local z/y stay multiples of 8
        ndev = mesh_shape[0] * mesh_shape[1] * mesh_shape[2]
        a = Astaroth(*size, mesh_shape=(1, 1, 1), dtype=np.float64,
                     devices=jax.devices()[:1], kernel="xla")
        b = Astaroth(*size, mesh_shape=mesh_shape, dtype=np.float64,
                     devices=jax.devices()[:ndev], kernel="halo")
        for m in (a, b):
            m.init()
            m.step()
            m.step()
        for q in FIELDS:
            np.testing.assert_allclose(b.field(q), a.field(q),
                                       rtol=1e-11, atol=1e-13, err_msg=q)

    def test_reinit_resets_state(self):
        """Regression (round-1 advisor): re-init() after stepping must
        not be silently discarded by the interior-resident cache."""
        from stencil_tpu.models.astaroth import Astaroth

        m = Astaroth(16, 16, 16, mesh_shape=(1, 2, 2), dtype=np.float64,
                     devices=jax.devices()[:4], kernel="halo")
        m.init()
        m.step()
        after_one = m.field("uux").copy()
        m.init()   # must flush + reset the interior cache
        m.step()
        np.testing.assert_array_equal(m.field("uux"), after_one)

    def test_set_interior_after_step_is_honored(self):
        """dd.set_interior between steps must reach the fast path."""
        from stencil_tpu.models.astaroth import Astaroth

        m = Astaroth(16, 16, 16, mesh_shape=(1, 2, 2), dtype=np.float64,
                     devices=jax.devices()[:4], kernel="halo")
        m.init()
        m.step()
        new_ss = np.zeros((16, 16, 16), dtype=np.float64)
        m.dd.set_interior("ss", new_ss)
        got = m.field("ss")
        np.testing.assert_array_equal(got, new_ss)


def test_jacobi_halo_uneven_y_blocks():
    """Shard sizes that are not multiples of 8 exercise the esub=1 slab
    fallback and block shrinking."""
    gz, gy, gx = 12, 12, 20
    rng = np.random.default_rng(3)
    init = rng.uniform(0.0, 1.0, size=(gz, gy, gx)).astype(np.float32)
    hot = (gx // 3, gy // 2, gz // 2)
    cold = (gx * 2 // 3, gy // 2, gz // 2)
    want = dense_reference_step(init, hot, cold, gx // 10)
    got = _run_halo_jacobi(init, (1, 2, 2), iters=1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
