"""Halo-exchange correctness: the ripple oracle.

Reproduces the single most important reference test pattern
(test/test_exchange.cu:12-33,126-191): initialize every point of the
global grid with an analytic coordinate function, run one exchange, copy
the full padded region (including halos) of every shard to host, then
verify every halo point equals the oracle at the periodically-wrapped
global coordinate.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from stencil_tpu._compat import remote_dma_runnable
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.local_domain import raw_size, zyx_shape
from stencil_tpu.parallel.exchange import (make_exchange,
                                           exchanged_bytes_per_sweep)
from stencil_tpu.parallel.mesh import make_mesh, mesh_dim
from stencil_tpu.parallel.methods import Method

RIPPLE = [1.0, 0.25, 0.5, 0.75]


def ripple(x, y, z):
    """f(p) = x + r[x%4] + y + r[y%4] + z + r[z%4]
    (reference: test/test_exchange.cu:12-33)."""
    return (x + RIPPLE[x % 4]) + (y + RIPPLE[y % 4]) + (z + RIPPLE[z % 4])


def make_padded_global(gsize: Dim3, mesh, radius: Radius) -> jnp.ndarray:
    """Build the global padded (z,y,x) array: each shard's interior holds
    the oracle values; halos start at a sentinel."""
    md = mesh_dim(mesh)
    local = gsize // md
    pr = raw_size(local, radius)
    full = np.full(zyx_shape(pr * md), -1000.0, dtype=np.float64)
    lo = radius.pad_lo()
    for bz in range(md.z):
        for by in range(md.y):
            for bx in range(md.x):
                block = np.zeros(zyx_shape(local))
                for lz in range(local.z):
                    for ly in range(local.y):
                        for lx in range(local.x):
                            gx = bx * local.x + lx
                            gy = by * local.y + ly
                            gz = bz * local.z + lz
                            block[lz, ly, lx] = ripple(gx, gy, gz)
                z0 = bz * pr.z + lo.z
                y0 = by * pr.y + lo.y
                x0 = bx * pr.x + lo.x
                full[z0:z0 + local.z, y0:y0 + local.y, x0:x0 + local.x] = block
    arr = jnp.asarray(full)
    return jax.device_put(arr, NamedSharding(mesh, P("z", "y", "x")))


def check_halos(host: np.ndarray, gsize: Dim3, mesh, radius: Radius,
                check_diagonals: bool = True):
    """Verify every halo point of every shard equals ripple(wrap(p))."""
    md = mesh_dim(mesh)
    local = gsize // md
    pr = raw_size(local, radius)
    lo = radius.pad_lo()
    bad = 0
    for bz in range(md.z):
        for by in range(md.y):
            for bx in range(md.x):
                z0, y0, x0 = bz * pr.z, by * pr.y, bx * pr.x
                blk = host[z0:z0 + pr.z, y0:y0 + pr.y, x0:x0 + pr.x]
                for lz in range(pr.z):
                    for ly in range(pr.y):
                        for lx in range(pr.x):
                            # global coordinate of this padded cell
                            gx = bx * local.x + lx - lo.x
                            gy = by * local.y + ly - lo.y
                            gz = bz * local.z + lz - lo.z
                            want = ripple(gx % gsize.x, gy % gsize.y,
                                          gz % gsize.z)
                            got = blk[lz, ly, lx]
                            if abs(got - want) > 1e-12:
                                bad += 1
                                assert bad < 5, (
                                    f"halo mismatch at block ({bx},{by},{bz}) "
                                    f"local ({lx},{ly},{lz}) global "
                                    f"({gx},{gy},{gz}): got {got}, want {want}")
    assert bad == 0


@pytest.fixture(scope="module")
def mesh222():
    return make_mesh((2, 2, 2))


# executing (not just tracing) explicit remote DMA needs a TPU or the
# distributed mosaic interpreter; the static analysis pass (stencil-lint)
# still checks these paths on every image
needs_rdma = pytest.mark.skipif(
    not remote_dma_runnable(),
    reason="Pallas remote DMA needs a TPU backend or the distributed "
           "(mosaic) TPU interpreter")


class TestExchangeOracle:
    @pytest.mark.parametrize("method", [Method.PpermuteSlab,
                                        Method.PpermutePacked,
                                        Method.AllGather,
                                        pytest.param(Method.PallasDMA,
                                                     marks=needs_rdma)])
    def test_radius1_2x2x2(self, mesh222, method):
        gsize = Dim3(8, 8, 8)
        radius = Radius.constant(1)
        arr = make_padded_global(gsize, mesh222, radius)
        ex = make_exchange(mesh222, radius, method)
        out = ex({"q": arr})["q"]
        check_halos(np.asarray(out), gsize, mesh222, radius)

    def test_radius2_2x2x2(self, mesh222):
        gsize = Dim3(8, 8, 8)
        radius = Radius.constant(2)
        arr = make_padded_global(gsize, mesh222, radius)
        ex = make_exchange(mesh222, radius, Method.Default)
        out = ex({"q": arr})["q"]
        check_halos(np.asarray(out), gsize, mesh222, radius)

    def test_asymmetric_radius(self, mesh222):
        # uncentered kernel: +x 2, -x 1, +y 1, -y 0, z 0
        gsize = Dim3(8, 8, 8)
        radius = Radius.constant(0)
        radius.set_dir((1, 0, 0), 2)
        radius.set_dir((-1, 0, 0), 1)
        radius.set_dir((0, 1, 0), 1)
        arr = make_padded_global(gsize, mesh222, radius)
        ex = make_exchange(mesh222, radius, Method.Default)
        out = ex({"q": arr})["q"]
        # only face halos on padded sides exist; check full padded region
        check_halos(np.asarray(out), gsize, mesh222, radius)

    @needs_rdma
    def test_pallas_dma_radius2(self, mesh222):
        gsize = Dim3(8, 8, 8)
        radius = Radius.constant(2)
        arr = make_padded_global(gsize, mesh222, radius)
        ex = make_exchange(mesh222, radius, Method.PallasDMA)
        out = ex({"q": arr})["q"]
        check_halos(np.asarray(out), gsize, mesh222, radius)

    @needs_rdma
    def test_pallas_dma_asymmetric_1d(self):
        # uncentered kernel over a deep 1D ring: +x 2, -x 1
        mesh = make_mesh((8, 1, 1))
        gsize = Dim3(16, 4, 4)
        radius = Radius.constant(0)
        radius.set_dir((1, 0, 0), 2)
        radius.set_dir((-1, 0, 0), 1)
        arr = make_padded_global(gsize, mesh, radius)
        ex = make_exchange(mesh, radius, Method.PallasDMA)
        out = ex({"q": arr})["q"]
        check_halos(np.asarray(out), gsize, mesh, radius)

    def test_anisotropic_mesh_1d(self):
        mesh = make_mesh((8, 1, 1))
        gsize = Dim3(16, 4, 4)
        radius = Radius.constant(1)
        arr = make_padded_global(gsize, mesh, radius)
        ex = make_exchange(mesh, radius, Method.Default)
        out = ex({"q": arr})["q"]
        check_halos(np.asarray(out), gsize, mesh, radius)

    def test_multi_quantity(self, mesh222):
        gsize = Dim3(8, 8, 8)
        radius = Radius.constant(1)
        a = make_padded_global(gsize, mesh222, radius)
        b = (make_padded_global(gsize, mesh222, radius) * 2.0)
        ex = make_exchange(mesh222, radius, Method.PpermutePacked)
        out = ex({"a": a, "b": b})
        check_halos(np.asarray(out["a"]), gsize, mesh222, radius)
        md = mesh_dim(mesh222)
        local = gsize // md
        pr = raw_size(local, radius)
        host_b = np.asarray(out["b"])
        # b = 2*a everywhere in interiors, so halos must be 2*oracle
        lo = radius.pad_lo()
        assert host_b[0, lo.y, lo.x] == pytest.approx(
            2 * ripple(0, 0, (0 - lo.z) % gsize.z))


class TestSingleDeviceWrap:
    """mesh_counts == 1 on every axis: the periodic neighbor is the
    shard itself (the reference's same-GPU PeerAccessSender analog)."""

    def test_local_wrap(self):
        gsize = Dim3(6, 6, 6)
        radius = Radius.constant(2)
        mesh = make_mesh((1, 1, 1), devices=jax.devices()[:1])
        arr = make_padded_global(gsize, mesh, radius)
        ex = make_exchange(mesh, radius, Method.Default)
        out = ex({"q": arr})["q"]
        check_halos(np.asarray(out), gsize, mesh, radius)


class TestByteCounters:
    def test_counts(self):
        radius = Radius.constant(2)
        shape = (12, 12, 12)  # padded shard
        counts = Dim3(2, 2, 1)
        b = exchanged_bytes_per_sweep(shape, radius, counts, elem_size=4)
        assert b["x"] == 4 * (2 + 2) * 12 * 12
        assert b["y"] == 4 * (2 + 2) * 12 * 12
        assert b["z"] == 0  # single shard along z: local wrap
