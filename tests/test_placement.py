"""Seed placement-module tests the link observatory activates:
``placement.comm_bytes_matrix`` against the ``partition.
halo_byte_model`` oracle (even + uneven partitions),
``torus_distance_matrix`` invariants, and ``qap.solve_catch``'s clean
fallback when the native solver library is unavailable."""

import numpy as np
import pytest

import stencil_tpu.qap as qap
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.partition import RankPartition, halo_byte_model
from stencil_tpu.placement import (Placement, PlacementStrategy,
                                   comm_bytes_matrix, iter_messages,
                                   make_placement,
                                   torus_distance_matrix)
from stencil_tpu.topology import Boundary, Topology


class _Dev:
    def __init__(self, coords):
        self.coords = coords


class TestCommBytesMatrix:
    """The QAP's ``w`` matrix vs the reference's per-message byte
    model — two independent routes to the same 26-direction halo
    arithmetic."""

    def _oracle_total(self, part, radius, elem_sizes):
        return sum(halo_byte_model(part, radius, es)["total"]
                   for es in elem_sizes)

    def test_even_partition_matches_halo_byte_model(self):
        part = RankPartition.from_dim((16, 16, 16), (2, 2, 2))
        radius = Radius.constant(1)
        w = comm_bytes_matrix(part, radius, (4,))
        assert w.shape == (8, 8)
        assert np.all(np.diag(w) == 0)
        assert w.sum() == self._oracle_total(part, radius, (4,))

    def test_uneven_partition_matches_halo_byte_model(self):
        # 21 is not divisible by 2: +-1-remainder subdomains, so the
        # matrix rows are NOT uniform — but the total must still equal
        # the oracle's sum over the ACTUAL subdomain sizes
        part = RankPartition.from_dim((21, 21, 16), (2, 2, 2))
        radius = Radius.constant(2)
        w = comm_bytes_matrix(part, radius, (4, 8))
        assert w.sum() == self._oracle_total(part, radius, (4, 8))
        # remainder subdomains send different byte counts
        assert len(set(w.sum(axis=1).tolist())) > 1

    def test_asymmetric_radius_directionality(self):
        # radius only toward +x: subdomains send only to their -x
        # neighbor (the message toward d fills the neighbor's -d halo)
        part = RankPartition.from_dim((8, 8, 8), (2, 1, 1))
        radius = Radius.constant(0)
        radius.set_dir((1, 0, 0), 1)
        msgs = list(iter_messages(part, radius, (4,)))
        assert msgs, "one face pair must exchange"
        assert all(d == Dim3(-1, 0, 0) for _, _, d, _ in msgs)

    def test_nonperiodic_topology_drops_boundary_messages(self):
        part = RankPartition.from_dim((16, 16, 16), (2, 2, 2))
        radius = Radius.constant(1)
        periodic = comm_bytes_matrix(part, radius, (4,))
        walls = comm_bytes_matrix(
            part, radius, (4,),
            topo=Topology(part.dim(), Boundary.NONE))
        assert walls.sum() < periodic.sum()
        assert np.all(walls <= periodic)


class TestTorusDistanceMatrix:
    def test_symmetry_and_zero_diagonal(self):
        devs = [_Dev((x, y, z)) for z in range(2) for y in range(2)
                for x in range(2)]
        d = torus_distance_matrix(devs)
        assert d.shape == (8, 8)
        assert np.all(np.diag(d) == 0)
        assert np.array_equal(d, d.T)
        # L1 hop counts over coords
        assert d[0, 1] == 1 and d[0, 7] == 3

    def test_uniform_fallback_without_coords(self):
        d = torus_distance_matrix([object() for _ in range(4)])
        assert np.all(np.diag(d) == 0)
        assert np.all(d[~np.eye(4, dtype=bool)] == 1)


class TestQapFallback:
    def _wd(self):
        part = RankPartition.from_dim((16, 16, 16), (2, 2, 2))
        w = comm_bytes_matrix(part, Radius.constant(1), (4,))
        devs = [_Dev((x, y, z)) for z in range(2) for y in range(2)
                for x in range(2)]
        return w, torus_distance_matrix(devs)

    def test_solve_catch_pure_python_when_native_unavailable(
            self, monkeypatch):
        """The native library being unbuildable must degrade to the
        pure-Python hill climb, not fail — same bijection contract,
        cost no worse than identity."""
        monkeypatch.setattr(qap, "_get_lib", lambda: None)
        w, d = self._wd()
        f, c = qap.solve_catch(w, d)
        assert sorted(f) == list(range(8))  # a true bijection
        assert c == pytest.approx(qap.cost(w, d, f))
        assert c <= qap.cost(w, d, list(range(8))) + 1e-9

    def test_native_available_reports_false_after_failed_build(
            self, monkeypatch):
        monkeypatch.setattr(qap, "_get_lib", lambda: None)
        assert qap.native_available() is False

    def test_fallback_matches_native_on_pinned_case(self, monkeypatch):
        """The reference's P9 case: the pure-Python climb must find a
        placement at least as good as identity and agree with cost()
        whether or not the native solver exists."""
        bw = np.array([[900, 75, 64, 64], [75, 900, 64, 64],
                       [64, 64, 900, 75], [64, 64, 75, 900.0]])
        comm = np.array([[7, 5, 10, 1], [5, 7, 1, 10],
                         [10, 1, 7, 5], [1, 10, 5, 7.0]])
        dist = qap.make_reciprocal(bw)
        native = qap.solve_catch(comm, dist)
        monkeypatch.setattr(qap, "_get_lib", lambda: None)
        pure = qap.solve_catch(comm, dist)
        assert pure[1] == pytest.approx(
            qap.cost(comm, dist, list(pure[0])))
        assert pure[1] <= qap.cost(comm, dist, [0, 1, 2, 3]) + 1e-9
        # both solvers land on equally-good assignments here
        assert pure[1] == pytest.approx(native[1])


class TestMakePlacement:
    class _IdDev:
        def __init__(self, i):
            self.id = i

    def test_node_aware_on_uniform_fabric_is_torus_sort(self):
        part = RankPartition.from_dim((16, 16, 16), (2, 2, 2))
        devs = [self._IdDev(i) for i in range(8)]  # no coords: uniform
        p = make_placement(PlacementStrategy.NodeAware, part, devs,
                           Radius.constant(1), (4,))
        assert isinstance(p, Placement)
        assert sorted(p.assignment) == list(range(8))

    def test_random_placement_is_seeded_permutation(self):
        part = RankPartition.from_dim((16, 16, 16), (2, 2, 2))
        devs = [object() for _ in range(8)]
        p1 = make_placement(PlacementStrategy.IntraNodeRandom, part,
                            devs, Radius.constant(1), (4,), seed=7)
        p2 = make_placement(PlacementStrategy.IntraNodeRandom, part,
                            devs, Radius.constant(1), (4,), seed=7)
        assert p1.assignment == p2.assignment
        assert sorted(p1.assignment) == list(range(8))


class TestPlacementModes:
    """The deployment flip: ``make_placement(mode=...)`` — the QAP
    assignment ships by default on non-uniform fabrics (measured hop
    spread or a DCN-blocked axis), the trivial order is retained on
    uniform fabrics, and "trivial"/"qap" force either side. Every
    deployed assignment is clamped to never cost more than identity
    under the QAP objective (the observatory placement-report gate,
    held structurally)."""

    class _IdDev:
        def __init__(self, i):
            self.id = i

    def _args(self, grid=(16, 16, 32), counts=(2, 2, 2)):
        part = RankPartition.from_dim(grid, counts)
        n = Dim3.of(counts).flatten()
        return part, [self._IdDev(i) for i in range(n)]

    def test_mode_validation(self):
        from stencil_tpu.placement import normalize_placement_mode

        assert normalize_placement_mode(None) == "auto"
        assert normalize_placement_mode("qap") == "qap"
        with pytest.raises(ValueError):
            normalize_placement_mode("fastest")

    def test_auto_on_uniform_fabric_keeps_trivial_order(self):
        part, devs = self._args()
        p = make_placement(PlacementStrategy.NodeAware, part, devs,
                           Radius.constant(1), (4,), mode="auto")
        assert p.assignment == list(range(8))

    def test_auto_deploys_qap_on_dcn_blocked_fabric(self):
        """A DCN seam across z makes the coordless fabric non-uniform
        (synthetic lattice-torus + DCN-penalty distances): auto must
        QAP-refine, and the deployed permutation must never cost more
        than identity on that same fabric."""
        from stencil_tpu.observatory.linkmap import mesh_distance_matrix

        part, devs = self._args()
        radius = Radius.constant(1)
        p = make_placement(PlacementStrategy.NodeAware, part, devs,
                           radius, (4,), mode="auto", dcn_axis=2,
                           n_slices=2)
        assert sorted(p.assignment) == list(range(8))
        w = comm_bytes_matrix(part, radius, (4,))
        dist = mesh_distance_matrix(Dim3(2, 2, 2), dcn_axis=2,
                                    n_slices=2)
        assert qap.cost(w, dist, p.assignment) <= \
            qap.cost(w, dist, list(range(8))) + 1e-9

    def test_trivial_mode_skips_refinement_on_dcn_fabric(self):
        part, devs = self._args()
        p = make_placement(PlacementStrategy.NodeAware, part, devs,
                           Radius.constant(1), (4,), mode="trivial",
                           dcn_axis=2, n_slices=2)
        assert p.assignment == list(range(8))

    def test_qap_mode_forces_refinement_on_uniform_fabric(self):
        """mode="qap" on a coordless fabric synthesizes the lattice
        distances and refines anyway — still clamped to identity."""
        from stencil_tpu.observatory.linkmap import mesh_distance_matrix

        part, devs = self._args()
        radius = Radius.constant(1)
        p = make_placement(PlacementStrategy.NodeAware, part, devs,
                           radius, (4,), mode="qap")
        assert sorted(p.assignment) == list(range(8))
        w = comm_bytes_matrix(part, radius, (4,))
        dist = mesh_distance_matrix(Dim3(2, 2, 2))
        assert qap.cost(w, dist, p.assignment) <= \
            qap.cost(w, dist, list(range(8))) + 1e-9

    def test_domain_placement_mode_escape_hatch(self):
        """DistributedDomain.set_placement("qap"|"trivial"|"auto")
        sets the NodeAware mode; junk is rejected loudly."""
        from stencil_tpu.distributed import DistributedDomain

        dd = DistributedDomain(16, 16, 16)
        dd.set_placement("qap")
        assert dd.placement_mode == "qap"
        dd.set_placement(PlacementStrategy.Trivial)  # strategy form
        assert dd.strategy == PlacementStrategy.Trivial
        with pytest.raises(ValueError):
            dd.set_placement("fastest")
