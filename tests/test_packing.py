"""Irredundant halo wire layout (parallel/packing.py).

The planner's telescoping property (every wire-halo cell rides exactly
one message), the byte model against the slab twin, and the data-plane
guarantee the layout ships under: bitwise equality with the slab
exchange on the whole live window — periodic and zero-Dirichlet
boundaries, even and uneven (+-1 remainder) shards, radius 1 and 3,
full-precision and bf16 wire — plus the blocked (temporal) path and
the PIC packed migration records that ride the same PR.
"""

from collections import Counter

import numpy as np
import pytest

from stencil_tpu.distributed import DistributedDomain
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.local_domain import raw_size
from stencil_tpu.models.jacobi import Jacobi3D
from stencil_tpu.parallel.packing import (WIRE_LAYOUTS,
                                          irredundant_bytes_per_sweep,
                                          normalize_wire_layout,
                                          pack_layout_report, plan_sweep)
from stencil_tpu.topology import Boundary

MESH222 = (2, 2, 2)


# ---------------------------------------------------------------------------
# planner: the telescoping tiling property


def _dst_cells(plan, interiors):
    """The receiver cells one direction's box writes, with the two
    traced ``plus_L`` placements resolved at the even-shard length."""
    rngs = []
    for j, s in enumerate(plan.dst):
        start = s.base + (interiors[j] if s.plus_L else 0)
        rngs.append(range(start, start + s.size))
    return [(x, y, z) for x in rngs[0] for y in rngs[1] for z in rngs[2]]


def _shell(radius, interiors):
    """Every cell of the wire-radius halo shell: the padded window
    minus the interior box (alloc pad == wire radius here)."""
    win, inner = [], []
    for a in range(3):
        lo, hi = radius.face(a, -1), radius.face(a, 1)
        win.append(range(0, lo + interiors[a] + hi))
        inner.append(range(lo, lo + interiors[a]))
    inner_set = {(x, y, z) for x in inner[0] for y in inner[1]
                 for z in inner[2]}
    return {(x, y, z) for x in win[0] for y in win[1] for z in win[2]
            if (x, y, z) not in inner_set}


@pytest.mark.parametrize("radius", [
    Radius.constant(1), Radius.constant(2),
    Radius.face_edge_corner(2, 1, 1),
], ids=["r1", "r2", "fec211"])
def test_dst_boxes_tile_halo_shell_exactly_once(radius):
    """The layout's defining invariant: the six direction boxes tile
    the wire-radius halo shell — every shell cell written by exactly
    one message, no interior cell written, nothing missed."""
    interiors = (6, 5, 4)
    plans = plan_sweep(radius, None, interiors)
    counts = Counter()
    for plan in plans.values():
        counts.update(_dst_cells(plan, interiors))
    assert set(counts) == _shell(radius, interiors)
    assert set(counts.values()) == {1}


def test_asymmetric_radius_drops_zero_directions():
    """Zero-radius directions ship no message; the surviving boxes
    still tile exactly the (asymmetric) shell once."""
    r = Radius.constant(0)
    r.set_dir((1, 0, 0), 2)
    r.set_dir((-1, 0, 0), 1)
    r.set_dir((0, 1, 0), 1)
    interiors = (5, 5, 5)
    plans = plan_sweep(r, None, interiors)
    assert set(plans) == {(0, 1), (0, -1), (1, 1)}
    counts = Counter()
    for plan in plans.values():
        counts.update(_dst_cells(plan, interiors))
    assert set(counts) == _shell(r, interiors)
    assert set(counts.values()) == {1}


def test_normalize_wire_layout():
    assert normalize_wire_layout(None) == "slab"
    for lay in WIRE_LAYOUTS:
        assert normalize_wire_layout(lay) == lay
    with pytest.raises(ValueError):
        normalize_wire_layout("fat-slab")


# ---------------------------------------------------------------------------
# byte model: strictly below the slab twin wherever a diagonal carries


def test_bytes_strictly_below_slab_with_diagonals():
    from stencil_tpu.parallel.exchange import exchanged_bytes_per_sweep

    counts = Dim3(*MESH222)
    for padded, r in (((16, 16, 16), Radius.constant(1)),
                      ((20, 20, 20), Radius.constant(3))):
        slab = sum(exchanged_bytes_per_sweep(padded, r, counts, 4)
                   .values())
        irr = sum(irredundant_bytes_per_sweep(padded, r, counts, 4)
                  .values())
        assert 0 < irr < slab, (padded, irr, slab)


def test_pack_layout_report_is_the_ci_artifact():
    """Every canonical config saves bytes, and the report's figures
    are exactly the model's (the registry pins the model against HLO,
    so the artifact chain is report == model == wire)."""
    rep = pack_layout_report()
    assert rep
    for name, row in rep.items():
        assert row["irredundant_bytes"] < row["slab_bytes"], name
        assert 0.0 < row["saved_fraction"] < 1.0, name
    assert rep["exchange[r1]"]["irredundant_bytes"] == 5408
    assert rep["exchange[r1]"]["slab_bytes"] == 6144


def test_costmodel_sweep_matches_packing_model():
    """analysis/costmodel.py's layout="irredundant" branch IS this
    planner's model — one source of truth for the checker and tuner."""
    from stencil_tpu.analysis.costmodel import sweep_wire_bytes

    got = sweep_wire_bytes((16, 16, 16), Radius.constant(1),
                           Dim3(*MESH222), 4, layout="irredundant")
    want = irredundant_bytes_per_sweep((16, 16, 16), Radius.constant(1),
                                       Dim3(*MESH222), 4)
    assert got == want


# ---------------------------------------------------------------------------
# data plane: slab == irredundant BITWISE on the whole live window


def _ripple_grid(n):
    g = np.arange(n)
    r = g + np.asarray([3.0, 7.0, 1.0, 5.0])[g % 4]
    return (r[:, None, None] * 100.0 + r[None, :, None] * 10.0
            + r[None, None, :]).astype(np.float32)


def _exchanged_block(n, radius, boundary, wire, layout):
    dd = DistributedDomain(n, n, n)
    dd.set_mesh_shape(MESH222)
    dd.set_radius(radius)
    dd.set_boundary(boundary)
    if wire is not None:
        dd.set_wire_format(wire)
    dd.set_wire_layout(layout)
    dd.add_data("q", np.float32)
    dd.realize()
    dd.set_interior("q", _ripple_grid(n))
    dd.exchange()
    return np.asarray(dd.curr["q"]), dd


@pytest.mark.parametrize("wire", [None, "bf16"], ids=["f32", "bf16"])
@pytest.mark.parametrize("radius", [1, 3], ids=["r1", "r3"])
@pytest.mark.parametrize("n", [16, 17], ids=["even16", "uneven17"])
@pytest.mark.parametrize("boundary",
                         [Boundary.PERIODIC, Boundary.NONE],
                         ids=["periodic", "none"])
def test_exchange_bitwise_matrix(boundary, n, radius, wire):
    """The full guarantee matrix: after one exchange the two layouts
    agree BITWISE on every shard's live window (interior plus the
    wire-radius shell; beyond it lies a short shard's dead slack,
    which no consumer reads). bf16 rides the same certificate-gated
    narrowing either way, so even the rounded halos match exactly."""
    slab, dd = _exchanged_block(n, radius, boundary, wire, "slab")
    irr, _ = _exchanged_block(n, radius, boundary, wire, "irredundant")
    pr = raw_size(dd.local_size, dd.radius)
    lo, hi = dd.radius.pad_lo(), dd.radius.pad_hi()
    dim = dd.placement.dim()
    for bz in range(dim.z):
        for by in range(dim.y):
            for bx in range(dim.x):
                sz = dd.placement.subdomain_size(Dim3(bx, by, bz))
                live = np.s_[bz * pr.z:bz * pr.z + lo.z + sz.z + hi.z,
                             by * pr.y:by * pr.y + lo.y + sz.y + hi.y,
                             bx * pr.x:bx * pr.x + lo.x + sz.x + hi.x]
                np.testing.assert_array_equal(slab[live], irr[live])


def test_irredundant_rejected_after_realize():
    dd = DistributedDomain(16, 16, 16)
    dd.set_mesh_shape(MESH222)
    dd.set_radius(1)
    dd.add_data("q", np.float32)
    dd.realize()
    with pytest.raises(AssertionError):
        dd.set_wire_layout("irredundant")


# ---------------------------------------------------------------------------
# blocked (temporal) path: fused == stepwise under the new layout


def test_jacobi_irredundant_matches_slab_bitwise_uneven():
    """End-to-end consumption: 6 Jacobi steps on uneven 17^3 shards
    read every halo cell the exchange delivered; the two layouts'
    temperatures are bitwise identical."""
    out = {}
    for layout in WIRE_LAYOUTS:
        j = Jacobi3D(17, 8, 8, mesh_shape=MESH222, dtype=np.float64,
                     kernel="xla", wire_layout=layout)
        assert j.dd.rem == Dim3(1, 0, 0)
        j.init()
        j.run(6)
        out[layout] = j.temperature()
    np.testing.assert_array_equal(out["slab"], out["irredundant"])


def test_jacobi_blocked_bitwise_irredundant_uneven():
    """s-blocked == step-by-step BITWISE under the irredundant layout
    (the deep exchange ships packed boxes at the deepened radius); 5
    iterations so s=2 exercises a partial tail group."""
    base = Jacobi3D(17, 8, 8, mesh_shape=MESH222, dtype=np.float64,
                    kernel="xla", wire_layout="irredundant")
    base.init()
    base.run(5)
    ref = base.temperature()
    for s in (2, 4):
        j = Jacobi3D(17, 8, 8, mesh_shape=MESH222, dtype=np.float64,
                     kernel="xla", wire_layout="irredundant",
                     exchange_every=s)
        j.init()
        j.run(5)
        assert j.kernel_path == f"xla-temporal[s={s}]"
        np.testing.assert_array_equal(j.temperature(), ref)


def test_jacobi_irredundant_disables_pallas_fast_paths():
    """The halo/overlap Pallas kernels run their own slab exchange, so
    an EXPLICIT kernel='halo' request with the irredundant layout must
    fail loudly instead of silently shipping slab bytes — and the auto
    pick must route around the fast path."""
    with pytest.raises(ValueError):
        Jacobi3D(16, 16, 16, mesh_shape=MESH222, dtype=np.float32,
                 kernel="halo", wire_layout="irredundant")
    j = Jacobi3D(16, 16, 16, mesh_shape=MESH222, dtype=np.float32,
                 kernel="auto", wire_layout="irredundant")
    assert j.kernel_path.startswith("xla")


# ---------------------------------------------------------------------------
# PIC: packed migration records (one offset+validity row) on uneven mesh


def test_pic_charge_conservation_packed_records_uneven():
    """Total deposited charge is BITWISE-preserved across migrations on
    an uneven 9^3 / 2x2x2 partition with the PACKED record layout: the
    three per-axis offset rows and the validity flag ride ONE base-3
    coded row, so record rows are n_fields + 1 and the migration's
    collective bill (2 per crossing mesh axis) is unchanged."""
    import jax

    from stencil_tpu.models.pic import PARTICLE_FIELDS, Pic
    from stencil_tpu.parallel.migrate import (RECORD_EXTRA_ROWS,
                                              migration_messages,
                                              migration_record_rows)

    assert RECORD_EXTRA_ROWS == 1
    nf = len(PARTICLE_FIELDS)
    assert migration_record_rows(nf) == nf + 1
    assert migration_messages(Dim3(*MESH222)) == 6

    rng = np.random.default_rng(11)
    n = 48
    p = Pic(9, 9, 9, n, mesh_shape=MESH222, dtype=np.float64, dt=0.25,
            deposition="ngp", capacity=24, devices=jax.devices()[:8])
    assert p.dd.rem == Dim3(1, 1, 1)
    p.set_particles({
        "x": rng.uniform(0, 9, n), "y": rng.uniform(0, 9, n),
        "z": rng.uniform(0, 9, n),
        "vx": rng.uniform(-1, 1, n), "vy": rng.uniform(-1, 1, n),
        "vz": rng.uniform(-1, 1, n), "q": np.ones(n),
    })
    for _ in range(5):
        p.step()
        assert p.total_charge() == float(n)
    assert p.overflow_total() == 0
