"""Race detection over the manual-DMA data planes — the memcheck /
racecheck analog of the reference's CUDA-sanitizer CI step (reference:
ci/build.sh runs tests under cuda-memcheck; SURVEY.md section 5.2).

The Pallas TPU interpreter's vector-clock race detector
(``InterpretParams(detect_races=True)``) checks every DMA, semaphore,
and buffer access the RDMA exchange and the in-kernel overlap kernel
make; a detected race prints ``RACE DETECTED`` — these tests fail on
any such report while also pinning the numerics.
"""

import contextlib
import io

import jax
import pytest
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.experimental.pallas import tpu as pltpu

from stencil_tpu._compat import has_race_detector
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel.mesh import make_mesh, mesh_dim

# The vector-clock race detector is the distributed (mosaic) TPU
# interpreter's; on images whose JAX predates it these tests cannot run
# at all (no interpreted inter-device DMA either). The static analysis
# pass (python -m stencil_tpu.analysis) covers the same kernels'
# DMA/semaphore discipline on every image.
pytestmark = pytest.mark.skipif(
    not has_race_detector(),
    reason="needs pltpu.InterpretParams(detect_races=True) — the "
           "distributed TPU interpreter's vector-clock race detector")


def _capture_races(fn):
    """Run ``fn`` with stdout captured; return (result, race_report)."""
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        out = fn()
    text = buf.getvalue()
    return out, ("RACE DETECTED" in text, text)


def test_detector_fires_on_deliberate_race():
    """Negative control: an unsynchronized remote write racing a local
    write MUST be reported — proves the detector wiring is not
    vacuously quiet for the race-free tests below."""
    from jax import lax
    from jax.experimental import pallas as pl

    mesh = make_mesh((1, 1, 2), jax.devices()[:2])

    def kern(in_ref, out_ref, vbuf, send, recv):
        me = lax.axis_index("z")
        other = lax.rem(me + 1, jnp.int32(2))
        # remote-write into the neighbor's out[0:1] while the neighbor
        # writes the same rows locally — no barrier, no ordering
        rc = pltpu.make_async_remote_copy(
            src_ref=in_ref.at[0:1], dst_ref=out_ref.at[0:1],
            send_sem=send.at[0], recv_sem=recv.at[0],
            device_id={"z": other})
        rc.start()
        vbuf[...] = jnp.zeros_like(vbuf)
        pltpu.make_async_copy(vbuf, out_ref.at[0:1], send.at[1]).start()
        pltpu.make_async_copy(vbuf, out_ref.at[0:1], send.at[1]).wait()
        rc.wait()

    def shard(p):
        return pl.pallas_call(
            kern,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            out_shape=jax.ShapeDtypeStruct(p.shape, p.dtype),
            scratch_shapes=[pltpu.VMEM((1,) + p.shape[1:], p.dtype),
                            pltpu.SemaphoreType.DMA((2,)),
                            pltpu.SemaphoreType.DMA((2,))],
            compiler_params=pltpu.CompilerParams(
                collective_id=7, has_side_effects=True),
            interpret=pltpu.InterpretParams(detect_races=True),
        )(p)

    sm = jax.jit(jax.shard_map(shard, mesh=mesh,
                               in_specs=P("z", "y", "x"),
                               out_specs=P("z", "y", "x"),
                               check_vma=False))
    a = jnp.asarray(np.random.default_rng(0)
                    .random((8, 8, 128)).astype(np.float32))
    arr = jax.device_put(a, NamedSharding(mesh, P("z", "y", "x")))
    _, (raced, _) = _capture_races(lambda: np.asarray(sm(arr)))
    assert raced, "race detector failed to flag a deliberate race"


def test_rdma_exchange_race_free():
    """The explicit inter-chip RDMA exchange (barrier + remote DMA
    choreography) under the race detector on a 2x2x2 mesh."""
    from stencil_tpu.parallel.pallas_exchange import exchange_shard_pallas

    mesh = make_mesh((2, 2, 2), jax.devices()[:8])
    counts = mesh_dim(mesh)
    radius = Radius.constant(1)
    params = pltpu.InterpretParams(detect_races=True)

    def shard(p):
        return exchange_shard_pallas(p, radius, counts,
                                     interpret=params)

    sm = jax.jit(jax.shard_map(shard, mesh=mesh, in_specs=P("z", "y", "x"),
                               out_specs=P("z", "y", "x"),
                               check_vma=False))
    rng = np.random.default_rng(3)
    a = rng.random((8, 8, 8)).astype(np.float32)
    arr = jax.device_put(jnp.asarray(a),
                         NamedSharding(mesh, P("z", "y", "x")))

    def run():
        out = np.asarray(sm(arr))
        return out

    out, (raced, text) = _capture_races(run)
    assert not raced, text[:2000]
    # interiors untouched by the exchange
    np.testing.assert_array_equal(out[1:3, 1:3, 1:3], a[1:3, 1:3, 1:3])


@pytest.mark.parametrize("dtype", ["f32", "bf16"])
def test_mhd_overlap_kernel_race_free(dtype):
    """The MHD in-kernel RDMA overlap substep (barrier + two-phase slab
    DMA concurrent with the fused mhd_rates block pipeline + aliased
    strip fix-ups) under the race detector on a (1,2,2) mesh — in f32
    (8-row slab tiles) and bf16 (16-row tiles, different DMA offsets)."""
    from stencil_tpu.models.astaroth import FIELDS, MhdParams
    from stencil_tpu.ops.pallas_mhd_overlap import mhd_substep_overlap

    mesh = make_mesh((1, 2, 2), jax.devices()[:4])
    counts = Dim3(1, 2, 2)
    prm = MhdParams()
    params = pltpu.InterpretParams(detect_races=True)
    dt = np.float32 if dtype == "f32" else jnp.bfloat16
    # one block/shard: local (8,8,8) f32, (16,16,8) bf16 (tile-16 z/y)
    gz, gy, gx = (16, 16, 8) if dtype == "f32" else (32, 32, 8)

    def shard(fields, w):
        f, wk = mhd_substep_overlap(fields, w, 0, prm, prm.dt, counts,
                                    interpret=params)
        return f, wk

    spec = P("z", "y", "x")
    fspec = {q: spec for q in FIELDS}
    sm = jax.jit(jax.shard_map(shard, mesh=mesh, in_specs=(fspec, fspec),
                               out_specs=(fspec, fspec), check_vma=False))
    rng = np.random.default_rng(11)
    sh = NamedSharding(mesh, spec)
    fields = {q: jax.device_put(
        jnp.asarray(rng.random((gz, gy, gx)).astype(np.float32) * 0.1,
                    dtype=dt), sh) for q in FIELDS}
    w = {q: jax.device_put(jnp.zeros((gz, gy, gx), dt), sh)
         for q in FIELDS}

    out, (raced, text) = _capture_races(
        lambda: jax.tree.map(np.asarray, sm(fields, w)))
    assert not raced, text[:2000]
    f_out, _ = out
    for q in FIELDS:
        assert np.all(np.isfinite(np.asarray(f_out[q], np.float32))), q


@pytest.mark.parametrize("dtype", ["f32", "bf16"])
def test_mhd_overlap_pair_kernel_race_free(dtype):
    """The PACKED (fused substep-0+1, pair=True) MHD overlap kernel
    under the race detector: radius-2R slab RDMA concurrent with the
    fused pair update + aliased strip fix-ups. The 2R transfers use
    different slab offsets than the radius-R substep path, so this is
    a distinct DMA choreography from test_mhd_overlap_kernel_race_free."""
    from stencil_tpu.models.astaroth import FIELDS, MhdParams
    from stencil_tpu.ops.pallas_mhd_overlap import mhd_substep_overlap

    mesh = make_mesh((1, 2, 2), jax.devices()[:4])
    counts = Dim3(1, 2, 2)
    prm = MhdParams()
    params = pltpu.InterpretParams(detect_races=True)
    dt = np.float32 if dtype == "f32" else jnp.bfloat16
    # pair mode needs 2R=6 <= min(bz, esub): 8-row f32 tiles, 16 bf16
    gz, gy, gx = (16, 16, 8) if dtype == "f32" else (32, 32, 8)

    def shard(fields):
        f, wk = mhd_substep_overlap(fields, None, 0, prm, prm.dt, counts,
                                    pair=True, interpret=params)
        return f, wk

    spec = P("z", "y", "x")
    fspec = {q: spec for q in FIELDS}
    sm = jax.jit(jax.shard_map(shard, mesh=mesh, in_specs=(fspec,),
                               out_specs=(fspec, fspec), check_vma=False))
    rng = np.random.default_rng(17)
    sh = NamedSharding(mesh, spec)
    fields = {q: jax.device_put(
        jnp.asarray(rng.random((gz, gy, gx)).astype(np.float32) * 0.1,
                    dtype=dt), sh) for q in FIELDS}

    out, (raced, text) = _capture_races(
        lambda: jax.tree.map(np.asarray, sm(fields)))
    assert not raced, text[:2000]
    f_out, _ = out
    for q in FIELDS:
        assert np.all(np.isfinite(np.asarray(f_out[q], np.float32))), q


def test_pair_overlap_negative_control_missing_barrier():
    """Negative control for the packed-overlap choreography: the same
    shape of bug the pair kernel's rendezvous prevents — a remote slab
    write issued WITHOUT the neighbor barrier, racing the neighbor's
    local initialization of that slab buffer. MUST be reported."""
    from jax import lax
    from jax.experimental import pallas as pl

    mesh = make_mesh((1, 1, 2), jax.devices()[:2])
    R2 = 6  # pair-mode halo rows (2R)

    def kern(in_ref, out_ref, slab, send, recv):
        me = lax.axis_index("z")
        other = lax.rem(me + 1, jnp.int32(2))
        # the neighbor is still zero-filling its slab buffer when the
        # remote write lands: no rendezvous, unsynchronized
        slab[...] = jnp.zeros_like(slab)
        rc = pltpu.make_async_remote_copy(
            src_ref=in_ref.at[0:R2], dst_ref=slab.at[0:R2],
            send_sem=send.at[0], recv_sem=recv.at[0],
            device_id={"z": other})
        rc.start()
        rc.wait()
        out_ref[...] = in_ref[...]

    def shard(p):
        return pl.pallas_call(
            kern,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            out_shape=jax.ShapeDtypeStruct(p.shape, p.dtype),
            scratch_shapes=[pltpu.VMEM((R2 + 2,) + p.shape[1:], p.dtype),
                            pltpu.SemaphoreType.DMA((1,)),
                            pltpu.SemaphoreType.DMA((1,))],
            compiler_params=pltpu.CompilerParams(
                collective_id=9, has_side_effects=True),
            interpret=pltpu.InterpretParams(detect_races=True),
        )(p)

    sm = jax.jit(jax.shard_map(shard, mesh=mesh,
                               in_specs=P("z", "y", "x"),
                               out_specs=P("z", "y", "x"),
                               check_vma=False))
    a = jnp.asarray(np.random.default_rng(5)
                    .random((16, 8, 128)).astype(np.float32))
    arr = jax.device_put(a, NamedSharding(mesh, P("z", "y", "x")))
    _, (raced, _) = _capture_races(lambda: np.asarray(sm(arr)))
    assert raced, "race detector failed to flag an unbarriered slab write"


def _uneven_rdma_exchange(off_by_one: bool):
    """One z-axis uneven (+-1 remainder) RDMA halo fill on a 2-shard
    ring: capacity-sized allocations, shard 1 one row short (rem=1).
    Each shard locally fills its ACTUAL interior [r, r+L) while remote
    writes land in the halos — correct dynamic placement puts the hi
    halo at [r+L, r+L+r) (disjoint); ``off_by_one=True`` plants the
    remainder-rule bug (destination at r+L-1, overlapping the last
    interior row the neighbor is writing) which MUST race."""
    from jax import lax
    from jax.experimental import pallas as pl

    mesh = make_mesh((1, 1, 2), jax.devices()[:2])
    r = 1
    cap = 8                    # interior capacity; shard 1 holds cap-1
    rem = 1                    # first `rem` shards are full-length
    alloc = cap + 2 * r

    def kern(in_ref, out_ref, send, recv):
        me = lax.axis_index("z")
        n = jnp.int32(2)
        up = lax.rem(me + 1, n)
        dn = lax.rem(me + n - 1, n)
        # rendezvous: destination halos quiescent before remote writes
        bsem = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(bsem, inc=1, device_id={"z": up})
        pltpu.semaphore_signal(bsem, inc=1, device_id={"z": dn})
        pltpu.semaphore_wait(bsem, 2)

        def actual_len(i):
            return jnp.int32(cap) - (i >= jnp.int32(rem)).astype(jnp.int32)

        L_me = actual_len(me)
        L_up = actual_len(up)
        # my top interior rows -> up neighbor's LO halo [0, r) (static)
        top = pltpu.make_async_remote_copy(
            src_ref=in_ref.at[pl.ds(r + L_me - r, r)],
            dst_ref=out_ref.at[pl.ds(0, r)],
            send_sem=send.at[0], recv_sem=recv.at[0],
            device_id={"z": up})
        # my bottom interior rows -> up neighbor's HI halo at its
        # actual interior end r+L (the partition.hpp:55-69 rule);
        # the negative control lands one row low, inside the
        # neighbor's interior
        dst_off = r + L_up - (1 if off_by_one else 0)
        bot = pltpu.make_async_remote_copy(
            src_ref=in_ref.at[pl.ds(r, r)],
            dst_ref=out_ref.at[pl.ds(dst_off, r)],
            send_sem=send.at[1], recv_sem=recv.at[1],
            device_id={"z": up})
        top.start()
        bot.start()
        # concurrent local fill of my ACTUAL interior rows [r, r+L)
        # (the halo regions are remote-write-only: disjoint when the
        # placement is correct)
        i = jnp.arange(alloc)[:, None, None]
        interior = jnp.logical_and(i >= r, i < r + L_me)
        vals = jnp.where(interior, in_ref[...], jnp.zeros_like(in_ref))
        out_ref[pl.ds(r, 1)] = vals[r:r + 1]
        idx = jnp.minimum(r + L_me - 1, jnp.int32(alloc - 1))
        out_ref[pl.ds(idx, 1)] = jnp.take(vals, idx[None], axis=0)
        top.wait()
        bot.wait()

    def shard(p):
        return pl.pallas_call(
            kern,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            out_shape=jax.ShapeDtypeStruct(p.shape, p.dtype),
            scratch_shapes=[pltpu.SemaphoreType.DMA((2,)),
                            pltpu.SemaphoreType.DMA((2,))],
            compiler_params=pltpu.CompilerParams(
                collective_id=8, has_side_effects=True),
            interpret=pltpu.InterpretParams(detect_races=True),
        )(p)

    sm = jax.jit(jax.shard_map(shard, mesh=mesh,
                               in_specs=P("z", "y", "x"),
                               out_specs=P("z", "y", "x"),
                               check_vma=False))
    a = jnp.asarray(np.random.default_rng(21)
                    .random((2 * alloc, 8, 128)).astype(np.float32))
    arr = jax.device_put(a, NamedSharding(mesh, P("z", "y", "x")))
    _, (raced, text) = _capture_races(lambda: np.asarray(sm(arr)))
    return raced, text


def test_uneven_rdma_exchange_race_free():
    """Uneven (+-1 remainder) RDMA halo placement: dynamic hi-halo
    destinations at each shard's ACTUAL interior end must not overlap
    the neighbor's concurrent interior writes."""
    raced, text = _uneven_rdma_exchange(off_by_one=False)
    assert not raced, text[:2000]


def test_uneven_rdma_exchange_negative_control():
    """Negative control: the classic remainder-rule off-by-one (halo
    landed at r+L-1, inside the short neighbor's interior) MUST be
    reported as a race."""
    raced, _ = _uneven_rdma_exchange(off_by_one=True)
    assert raced, ("race detector failed to flag an off-by-one uneven "
                   "halo placement")


def test_overlap_kernel_race_free():
    """The in-kernel RDMA overlap step (remote slab DMA concurrent with
    the interior compute pipeline) under the race detector."""
    from functools import partial

    from stencil_tpu.models.jacobi import dense_reference_step
    from stencil_tpu.ops.pallas_overlap import jacobi7_overlap_pallas

    mesh = make_mesh((1, 2, 2), jax.devices()[:4])
    counts = Dim3(1, 2, 2)
    N = 16
    params = pltpu.InterpretParams(detect_races=True)
    hot = (N // 3, N // 2, N // 2)
    cold = (2 * N // 3, N // 2, N // 2)

    def shard(q):
        iz = jax.lax.axis_index("z")
        iy = jax.lax.axis_index("y")
        org = jnp.stack([iz * (N // 2), iy * (N // 2),
                         jnp.int32(0)]).astype(jnp.int32)
        return jacobi7_overlap_pallas(q, org, hot, cold, N // 10,
                                      counts, block_z=4,
                                      interpret=params)

    sm = jax.jit(jax.shard_map(shard, mesh=mesh, in_specs=P("z", "y", "x"),
                               out_specs=P("z", "y", "x"),
                               check_vma=False))
    rng = np.random.default_rng(9)
    a = rng.random((N, N, N)).astype(np.float32)
    arr = jax.device_put(jnp.asarray(a),
                         NamedSharding(mesh, P("z", "y", "x")))

    out, (raced, text) = _capture_races(lambda: np.asarray(sm(arr)))
    assert not raced, text[:2000]
    want = dense_reference_step(a, hot, cold, N // 10)
    np.testing.assert_allclose(out, want, rtol=2e-6, atol=2e-6)
