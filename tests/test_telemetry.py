"""Unified telemetry: events, metrics, spans, in-graph step metrics.

Covers the ISSUE 7 acceptance contract: one versioned event schema
across the resilience driver and the campaign service, warm-path
invariants readable from the EXPORTED metrics surface, spans that
export as Perfetto-loadable Chrome trace JSON, and in-graph step
metrics that ride the health probe's one all-reduce (zero extra
collectives / zero extra wire bytes — proven by registry targets, with
a negative control).
"""

import json
import threading
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from stencil_tpu.telemetry import (EVENT_SCHEMA_VERSION, EventLog,
                                   JsonlSink, ListSink, MetricsRegistry,
                                   MetricsServer, RingSink, StepMetrics,
                                   Tracer, metric_value,
                                   parse_prometheus_text,
                                   render_snapshot_text, snapshot_value,
                                   validate_chrome_trace,
                                   validate_events)

FIXTURES = Path(__file__).parent / "fixtures" / "lint"


# ---------------------------------------------------------------------------
# the versioned event schema + sinks


def test_event_log_stamps_schema_run_and_monotonic_seq():
    got = []
    log = EventLog(sinks=(ListSink(got),), clock=lambda: 123.0)
    log.emit("a", step=1)
    log.emit("b", span="r/0", nested={"k": "v"})
    assert [e["seq"] for e in got] == [0, 1]
    assert all(e["run"] == log.run_id for e in got)
    assert all(e["schema"] == EVENT_SCHEMA_VERSION for e in got)
    assert got[0] == {"event": "a", "time": 123.0, "run": log.run_id,
                      "seq": 0, "schema": EVENT_SCHEMA_VERSION,
                      "step": 1}
    assert got[1]["span"] == "r/0"
    assert validate_events(got) == []


def test_event_attrs_may_not_shadow_schema_keys():
    """The stamped identity (run/seq/time/schema/event) is what fleet
    scrapers merge on — a colliding attr must raise, not silently
    corrupt it."""
    elog = EventLog(run_id="r")
    # ("span" binds to emit()'s named parameter, the supported way to
    # set it — it can never arrive through **attrs)
    for key in ("run", "seq", "time", "schema", "event"):
        with pytest.raises(ValueError, match="schema keys"):
            elog.emit("tick", **{key: "boom"})
    # nothing was emitted and seq did not advance
    assert elog.emit("tick")["seq"] == 0


def test_validate_events_flags_bad_records():
    assert validate_events([{"event": "x"}])  # missing run/seq/...
    ok = {"event": "x", "time": 1.0, "run": "r", "seq": 1, "schema": 1}
    assert validate_events([ok]) == []
    # non-monotonic seq within one run
    again = dict(ok)
    problems = validate_events([ok, again])
    assert problems and "not increasing" in problems[0]
    # float seqs (an external serializer may write 1.0) get the same
    # monotonicity check as ints
    f1 = dict(ok, seq=1.0)
    f2 = dict(ok, seq=3.0)
    f3 = dict(ok, seq=2.0)
    assert validate_events([f1, f2]) == []
    problems = validate_events([f1, f2, f3])
    assert problems and "not increasing" in problems[0]


def test_event_log_survives_a_failing_sink():
    # a dead sink (disk full, closed stream) must neither kill the
    # instrumented loop nor starve later sinks of the record
    class Boom:
        def emit(self, record):
            raise OSError("disk full")

        def close(self):
            pass

    ring = RingSink(capacity=8)
    log = EventLog(sinks=(Boom(), ring))
    rec = log.emit("tick", i=1)
    assert rec["event"] == "tick"
    assert [r["i"] for r in ring.records()] == [1]


def test_ring_sink_bounds_memory_and_counts_drops():
    ring = RingSink(capacity=3)
    log = EventLog(sinks=(ring,))
    for i in range(10):
        log.emit("tick", i=i)
    records = ring.records()
    assert len(records) == 3
    assert [r["i"] for r in records] == [7, 8, 9]
    assert ring.dropped == 7


def test_jsonl_sink_writes_one_record_per_line(tmp_path):
    path = tmp_path / "events.jsonl"
    sink = JsonlSink(str(path))
    log = EventLog(sinks=(sink,))
    log.emit("a")
    log.emit("b", x=2)
    sink.close()
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert [r["event"] for r in lines] == ["a", "b"]
    assert validate_events(lines) == []


# ---------------------------------------------------------------------------
# the metrics registry + exposition


def test_counter_gauge_histogram_exposition_and_parse():
    reg = MetricsRegistry()
    c = reg.counter("req_total", "requests")
    c.inc(tenant="a")
    c.inc(2, tenant="b")
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("depth")
    g.set(5)
    g.dec(2)
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
    h.observe(0.05, op="admit")
    h.observe(3.0, op="admit")
    text = reg.to_prometheus_text()
    assert "# TYPE req_total counter" in text
    assert metric_value(text, "req_total", tenant="b") == 2
    assert metric_value(text, "depth") == 3
    parsed = parse_prometheus_text(text)
    assert metric_value(parsed, "lat_seconds_bucket", op="admit",
                        le="0.1") == 1
    assert metric_value(parsed, "lat_seconds_bucket", op="admit",
                        le="+Inf") == 2
    assert metric_value(parsed, "lat_seconds_count", op="admit") == 2
    # absent series read as 0 (the Prometheus convention)
    assert metric_value(text, "req_total", tenant="nobody") == 0.0


def test_label_values_escape_and_round_trip():
    """Tenant-controlled label values with quotes/commas/backslashes
    must not corrupt the exposition surface: values are escaped per
    format 0.0.4 and the parser round-trips them exactly."""
    reg = MetricsRegistry()
    c = reg.counter("req_total")
    hostile = ('acme"corp', "acme,corp", "a\\b", "two\nlines")
    for t in hostile:
        c.inc(tenant=t)
    text = reg.to_prometheus_text()
    # no raw quote/newline inside a label value on the wire
    for line in text.splitlines():
        assert "\n" not in line
        assert 'tenant="acme"corp"' not in line
    for t in hostile:
        assert metric_value(text, "req_total", tenant=t) == 1, t
    # and every series is still individually addressable
    assert len(parse_prometheus_text(text)["req_total"]) == len(hostile)


def test_counter_seeded_to_zero_exports_explicit_sample():
    # inc(0) births the unlabeled series: the exposition carries an
    # explicit `name 0` line, so "== 0" gates (CI warm path) assert a
    # sample that exists rather than the absent-series 0.0 default
    reg = MetricsRegistry()
    c = reg.counter("seeded_total", "seeded at registration")
    c.inc(0)
    text = reg.to_prometheus_text()
    assert "seeded_total 0" in text
    assert metric_value(text, "seeded_total") == 0
    assert snapshot_value(reg.snapshot(), "seeded_total") == 0
    assert reg.snapshot()["metrics"]["seeded_total"]["samples"]


def test_registry_idempotent_and_kind_checked():
    reg = MetricsRegistry()
    c1 = reg.counter("foo_total")
    assert reg.counter("foo_total") is c1
    with pytest.raises(ValueError):
        reg.gauge("foo_total")
    # histogram re-registration: same buckets fine, different raise
    # (silently keeping the first bounds would misbin observations)
    h1 = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
    assert reg.histogram("lat_seconds", buckets=(1.0, 0.1)) is h1
    with pytest.raises(ValueError, match="buckets"):
        reg.histogram("lat_seconds", buckets=(0.5,))
    # no-preference re-declaration (buckets omitted) stays idempotent
    # even though the first registration chose custom bounds — only an
    # explicit conflicting choice raises
    assert reg.histogram("lat_seconds") is h1
    assert h1.buckets == (0.1, 1.0)
    # histograms have no single value — count()/sum() are the readers
    with pytest.raises(TypeError, match="count"):
        h1.value()
    # HELP text is escaped per format 0.0.4
    reg.counter("esc_total", "two\nlines \\ slash").inc()
    text = reg.to_prometheus_text()
    assert r"# HELP esc_total two\nlines \\ slash" in text
    assert all(line.startswith(("#", "esc_total", "foo_total",
                                "lat_seconds"))
               for line in text.splitlines())


def test_snapshot_round_trips_through_render():
    reg = MetricsRegistry()
    reg.counter("a_total", "help a").inc(3, k="v")
    reg.histogram("h_seconds", buckets=(1.0,)).observe(0.5)
    snap = reg.snapshot()
    assert snap["schema"] == 1
    assert snapshot_value(snap, "a_total", k="v") == 3
    text = render_snapshot_text(snap)
    assert metric_value(text, "a_total", k="v") == 3
    assert metric_value(text, "h_seconds_count") == 1
    # one renderer serves both surfaces: the re-rendered snapshot IS
    # the live scrape, byte for byte
    assert text == reg.to_prometheus_text()


def test_metrics_http_endpoint():
    reg = MetricsRegistry()
    reg.counter("hits_total").inc(7)
    with MetricsServer(reg, port=0) as srv:
        base = f"http://127.0.0.1:{srv.port}"
        text = urllib.request.urlopen(f"{base}/metrics").read().decode()
        assert metric_value(text, "hits_total") == 7
        snap = json.loads(
            urllib.request.urlopen(f"{base}/metrics.json").read())
        assert snapshot_value(snap, "hits_total") == 7
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/nope")


# ---------------------------------------------------------------------------
# spans + Chrome trace export


def test_tracer_span_tree_and_chrome_export(tmp_path):
    t = Tracer(run_id="testrun")
    with t.span("campaign", tenant="a"):
        with t.span("segment", steps=4):
            pass
        with t.span("checkpoint"):
            pass
    spans = t.finished()
    by_name = {s.name: s for s in spans}
    assert by_name["segment"].parent_id == by_name["campaign"].span_id
    assert by_name["checkpoint"].parent_id == by_name["campaign"].span_id
    assert by_name["campaign"].parent_id is None
    assert all(s.span_id.startswith("testrun/") for s in spans)
    assert by_name["segment"].attrs == {"steps": 4}

    path = tmp_path / "trace.json"
    t.export_chrome_trace(str(path))
    assert validate_chrome_trace(str(path)) == []
    data = json.loads(path.read_text())
    ev = {e["name"]: e for e in data["traceEvents"]}
    assert ev["segment"]["ph"] == "X"
    assert ev["segment"]["args"]["parent_id"] == \
        ev["campaign"]["args"]["span_id"]
    assert data["otherData"]["dropped_spans"] == 0


def test_tracer_rejects_identity_key_attrs():
    # same contract as EventLog.RESERVED: an attr named span_id or
    # parent_id would clobber the exported trace's parent links
    t = Tracer()
    for key in ("span_id", "parent_id"):
        with pytest.raises(ValueError, match="identity keys"):
            with t.span("seg", **{key: "forged"}):
                pass
    assert t.finished() == []


def test_tracer_ring_counts_dropped_spans():
    t = Tracer(capacity=3)
    for i in range(5):
        with t.span(f"s{i}"):
            pass
    assert [s.name for s in t.finished()] == ["s2", "s3", "s4"]
    assert t.dropped == 2
    assert t.chrome_trace()["otherData"]["dropped_spans"] == 2
    t.clear()
    assert t.dropped == 0


def test_tracer_threads_keep_independent_stacks():
    t = Tracer()
    seen = {}

    def worker():
        with t.span("worker-root") as sp:
            seen["worker_parent"] = sp.parent_id

    with t.span("main-root"):
        th = threading.Thread(target=worker)
        th.start()
        th.join()
    # the worker thread's span is NOT parented under main's stack
    assert seen["worker_parent"] is None


def test_validate_chrome_trace_flags_garbage(tmp_path):
    assert validate_chrome_trace({"nope": 1})
    assert validate_chrome_trace(
        {"traceEvents": [{"name": 3, "ph": "X"}]})
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert validate_chrome_trace(str(bad))


def test_span_named_scope_reaches_traced_ops():
    """A telemetry span wraps utils.profiling.scope: ops traced inside
    it carry the span name on their name stack (-> XLA op metadata)."""
    import jax
    import jax.numpy as jnp

    t = Tracer()

    def fn(x):
        with t.span("telemetry-span-label"):
            return x * 2.0

    closed = jax.make_jaxpr(fn)(jnp.ones(4))
    stacks = [str(eqn.source_info.name_stack)
              for eqn in closed.jaxpr.eqns]
    assert any("telemetry-span-label" in s for s in stacks), stacks


# ---------------------------------------------------------------------------
# in-graph step metrics: ride the probe's one all-reduce


def make_jacobi():
    from stencil_tpu.models.jacobi import Jacobi3D

    j = Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float32)
    j.init()
    return j


def test_step_metrics_ride_the_health_probe():
    from stencil_tpu.resilience import HealthSentinel

    j = make_jacobi()
    sm = StepMetrics(j.dd)
    assert sm.bytes_per_step == pytest.approx(
        j.dd.exchange_bytes_amortized_per_step())
    s = HealthSentinel(j.dd, metrics=sm)
    s.probe(j.dd.curr, 3)
    (r,) = s.poll(block=True)
    assert not r.tripped
    # health stats untouched by the extra columns
    assert r.max_abs["temp"] == pytest.approx(0.5)
    # the counters decode from the SAME harvested vector
    assert r.metrics["substeps"] == 3
    assert r.metrics["wire_bytes"] == pytest.approx(
        3 * sm.bytes_per_step)
    decoded = sm.decode(r.metrics)
    assert decoded["bytes_per_step_probe"] == pytest.approx(
        sm.bytes_per_step)
    assert decoded["bytes_per_step_model"] == sm.bytes_per_step
    assert r.to_record()["metrics"]["substeps"] == 3


def test_step_metrics_rebase_prices_only_future_steps():
    """A mid-run reconfiguration (degradation ladder) must not
    retroactively reprice traffic already sent: the rebased counter
    carries the old price for steps up to the rebase point and applies
    the new domain's price only beyond it."""
    j = make_jacobi()
    sm = StepMetrics(j.dd)
    old_price = sm.bytes_per_step
    # reconfigure: temporal depth 2 changes the amortized B/step
    from stencil_tpu.models.jacobi import Jacobi3D

    k = Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float32,
                 exchange_every=2)
    k.init()
    sm2 = sm.rebased(k.dd, 6)
    new_price = sm2.bytes_per_step
    assert new_price != pytest.approx(old_price)
    assert sm2.cumulative_bytes(6) == pytest.approx(6 * old_price)
    assert sm2.cumulative_bytes(10) == pytest.approx(
        6 * old_price + 4 * new_price)
    # a rollback below the rebase point never goes negative
    assert sm2.cumulative_bytes(4) == pytest.approx(6 * old_price)
    vals = np.asarray(sm2.values(10))
    assert vals[0] == 10
    assert vals[1] == pytest.approx(6 * old_price + 4 * new_price,
                                    rel=1e-6)


def test_telemetry_registry_targets_prove_zero_added_collectives():
    """Acceptance verbatim: the instrumented production Jacobi step
    passes exact_counts (6 collective_permutes + exactly 1 all_reduce)
    and the exchange byte cross-check stays exact."""
    from stencil_tpu.analysis import run_targets
    from stencil_tpu.analysis.hlo import lowering_supported
    from stencil_tpu.analysis.registry import default_targets

    if not lowering_supported():
        pytest.skip("StableHLO lowering unavailable in this JAX")
    targets = [t for t in default_targets()
               if t.name.startswith("telemetry.")]
    assert len(targets) == 3
    report = run_targets(targets)
    assert report.findings == []
    fused = report.metrics["hlo:telemetry.step+probe+metrics[hlo]"]
    assert fused["collectives"]["all_reduce"]["count"] == 1
    assert fused["collectives"]["collective_permute"]["count"] == 6
    cost = report.metrics["costmodel:telemetry.step+probe+metrics[cost]"]
    assert cost["observed_bytes_per_shard"] == \
        cost["expected_bytes_per_shard"]


def test_separate_metrics_reduce_fixture_flagged():
    from stencil_tpu.analysis import run_targets
    from stencil_tpu.analysis.hlo import lowering_supported
    from stencil_tpu.analysis.registry import load_targets

    if not lowering_supported():
        pytest.skip("StableHLO lowering unavailable in this JAX")
    report = run_targets(load_targets(FIXTURES / "bad_probe_metrics.py"))
    assert len(report.errors) == 1
    assert "exactly 1" in report.errors[0].message


# ---------------------------------------------------------------------------
# one schema across subsystems


def test_resilience_report_events_speak_the_unified_schema(tmp_path):
    from stencil_tpu.resilience import ResiliencePolicy
    from stencil_tpu.resilience.driver import run_resilient

    j = make_jacobi()
    rep = run_resilient(
        j.dd, j.step, 3,
        policy=ResiliencePolicy(check_every=1, ckpt_every=2,
                                sleep=lambda s: None),
        ckpt_dir=str(tmp_path / "ckpt"))
    assert rep.run_id
    assert rep.events and validate_events(rep.events) == []
    assert all(e["run"] == rep.run_id for e in rep.events)
    # events emitted inside the run-loop spans are span-correlated
    # (same shape as the service's event log — one scraper joins the
    # event stream and the chrome trace)
    spans = [e["span"] for e in rep.events if "span" in e]
    assert spans, rep.events
    from stencil_tpu.telemetry import get_tracer
    trace_ids = {s.span_id for s in get_tracer().finished()}
    assert set(spans) <= trace_ids
    # the serialized record keeps the schema-stamped events
    rec = rep.to_record()
    assert rec["run_id"] == rep.run_id
    assert validate_events(rec["events"]) == []


def test_service_events_metrics_and_trace(tmp_path):
    from stencil_tpu.serving import CampaignRequest, CampaignService
    from stencil_tpu.tuning import FakeTimer

    svc = CampaignService(str(tmp_path / "root"), width=4,
                          tuner_timer=FakeTimer(),
                          plan_cache_path=str(tmp_path / "plans.json"),
                          events_capacity=512)
    h = svc.submit(CampaignRequest(tenant="t0", campaign="c0",
                                   grid=(8, 8, 8), n_steps=4,
                                   ckpt_every=2))
    svc.drain()
    assert h.result(timeout=120).steps == 4

    # events: unified schema, one run id, span correlation
    events = svc.events
    assert events and validate_events(events) == []
    assert {e["run"] for e in events} == {svc.run_id}
    in_batch = [e for e in events if e.get("span")]
    assert in_batch, "batch-scoped events must carry span ids"

    # metrics: text and snapshot expose the same numbers
    text = svc.metrics_text()
    snap = svc.metrics_snapshot()
    assert metric_value(text, "stencil_service_batches_total") == 1
    assert snapshot_value(snap, "stencil_service_batches_total") == 1
    assert metric_value(text, "stencil_service_member_steps_total") == 4
    assert metric_value(text, "stencil_service_campaigns_total",
                        tenant="t0", outcome="completed") == 1
    assert metric_value(text, "stencil_service_queue_depth") == 0
    parsed = parse_prometheus_text(text)
    assert metric_value(
        parsed, "stencil_service_admission_latency_seconds_count") == 1

    # spans export as a valid Chrome trace with the expected tree
    trace = tmp_path / "trace.json"
    svc.export_trace(str(trace))
    assert validate_chrome_trace(str(trace)) == []
    names = {s.name for s in svc.tracer.finished()}
    assert {"campaign.batch", "segment", "compile",
            "tune"} <= names

    # the event payload carries schema/run/dropped
    out = tmp_path / "events.json"
    svc.write_events(str(out))
    payload = json.loads(out.read_text())
    assert payload["schema"] == EVENT_SCHEMA_VERSION
    assert payload["run"] == svc.run_id
    assert payload["dropped_events"] == 0


def test_service_event_ring_is_bounded(tmp_path):
    from stencil_tpu.serving import CampaignRequest, CampaignService
    from stencil_tpu.tuning import FakeTimer

    svc = CampaignService(str(tmp_path / "root"), width=2,
                          tuner_timer=FakeTimer(),
                          plan_cache_path=str(tmp_path / "plans.json"),
                          events_capacity=5)
    h = svc.submit(CampaignRequest(tenant="t0", campaign="c0",
                                   grid=(8, 8, 8), n_steps=4,
                                   ckpt_every=1))
    svc.drain()
    assert h.result(timeout=120).steps == 4
    assert len(svc.events) == 5          # flat memory, newest kept
    assert svc._ring.dropped > 0
    svc.write_events(str(tmp_path / "ev.json"))
    payload = json.loads((tmp_path / "ev.json").read_text())
    assert payload["dropped_events"] == svc._ring.dropped
    assert len(payload["events"]) == 5


# ---------------------------------------------------------------------------
# structured-JSON log mode (STENCIL_LOG_FORMAT=json)


def test_log_json_mode_routes_through_event_schema(capsys):
    from stencil_tpu.utils import logging as slog

    slog.set_format("json")
    try:
        slog.LOG_INFO("hello fleet")
        slog.LOG_WARN("watch out")
    finally:
        slog.set_format("text")
    lines = [json.loads(ln)
             for ln in capsys.readouterr().err.splitlines() if ln]
    assert [r["level"] for r in lines] == ["info", "warn"]
    assert all(r["event"] == "log" for r in lines)
    assert all(r["schema"] == EVENT_SCHEMA_VERSION for r in lines)
    assert lines[0]["message"] == "hello fleet"
    assert lines[0]["rank"] == 0
    assert validate_events(lines) == []
    # plain-text default unchanged
    slog.LOG_INFO("plain again")
    err = capsys.readouterr().err
    assert "INFO: plain again" in err


def test_log_set_format_rejects_unknown():
    from stencil_tpu.utils import logging as slog

    with pytest.raises(ValueError):
        slog.set_format("xml")


# ---------------------------------------------------------------------------
# the snapshot / validator CLI


def test_telemetry_cli(tmp_path, capsys):
    from stencil_tpu.telemetry.__main__ import main

    reg = MetricsRegistry()
    reg.counter("x_total").inc(4)
    snap_path = tmp_path / "snap.json"
    reg.write_snapshot(str(snap_path))
    assert main(["snapshot", str(snap_path)]) == 0
    out = capsys.readouterr().out
    assert metric_value(out, "x_total") == 4

    t = Tracer()
    with t.span("a"):
        pass
    trace_path = tmp_path / "trace.json"
    t.export_chrome_trace(str(trace_path))
    assert main(["validate-trace", str(trace_path)]) == 0
    bad = tmp_path / "bad_trace.json"
    bad.write_text(json.dumps({"traceEvents": [{"ph": "X"}]}))
    assert main(["validate-trace", str(bad)]) == 1

    evs = []
    log = EventLog(sinks=(ListSink(evs),))
    log.emit("a")
    log.emit("b")
    ev_path = tmp_path / "events.json"
    ev_path.write_text(json.dumps({"events": evs}))
    assert main(["validate-events", str(ev_path)]) == 0
    ev_path.write_text(json.dumps({"events": [{"event": "x"}]}))
    assert main(["validate-events", str(ev_path)]) == 1
    # JSONL input works too
    jsonl = tmp_path / "events.jsonl"
    jsonl.write_text("\n".join(json.dumps(e) for e in evs))
    assert main(["validate-events", str(jsonl)]) == 0
    # a ONE-line JSONL file is valid JSON on its own — it must parse
    # as a single record, not be rejected as a payload without events
    jsonl.write_text(json.dumps(evs[0]))
    assert main(["validate-events", str(jsonl)]) == 0
