"""utils/profiling: scopes, phase timers, and report renderers.

Previously untested (ISSUE 7 satellite): ``scope`` must nest
``named_scope`` without breaking tracing (it is the substrate every
telemetry span stands on), ``PhaseTimer`` must accumulate repeated
phases, and the report renderers must produce their documented lines
against golden inputs.
"""

import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stencil_tpu.utils.profiling import (PhaseTimer, autotune_report,
                                         exchange_stats_report, scope,
                                         setup_stats_report)


# ---------------------------------------------------------------------------
# scope


def test_scope_nests_without_breaking_tracing():
    def fn(x):
        with scope("outer"):
            y = x + 1.0
            with scope("inner"):
                y = y * 2.0
        return y

    out = jax.jit(fn)(jnp.asarray([1.0, 2.0]))
    np.testing.assert_allclose(np.asarray(out), [4.0, 6.0])


def test_scope_labels_reach_traced_ops():
    """The named_scope half of ``scope``: traced ops inside the block
    carry the label on their name stack (what XLA turns into op
    metadata in the profile)."""
    def fn(x):
        with scope("golden-scope-name"):
            return jnp.sin(x)

    closed = jax.make_jaxpr(fn)(jnp.ones(4))
    stacks = [str(eqn.source_info.name_stack)
              for eqn in closed.jaxpr.eqns]
    assert any("golden-scope-name" in s for s in stacks), stacks


def test_scope_works_outside_tracing():
    with scope("host-only"):
        assert 1 + 1 == 2


# ---------------------------------------------------------------------------
# PhaseTimer


def test_phase_timer_accumulates_repeated_phases(monkeypatch):
    import stencil_tpu.utils.profiling as prof

    ticks = iter([0.0, 1.0, 10.0, 12.5, 20.0, 20.25])
    monkeypatch.setattr(prof.time, "perf_counter", lambda: next(ticks))
    t = PhaseTimer()
    with t.phase("exchange"):
        pass  # 1.0s
    with t.phase("exchange"):
        pass  # +2.5s
    with t.phase("compute"):
        pass  # 0.25s
    assert t.seconds["exchange"] == pytest.approx(3.5)
    assert t.seconds["compute"] == pytest.approx(0.25)


def test_phase_timer_accumulates_across_exceptions(monkeypatch):
    import stencil_tpu.utils.profiling as prof

    ticks = iter([0.0, 2.0])
    monkeypatch.setattr(prof.time, "perf_counter", lambda: next(ticks))
    t = PhaseTimer()
    with pytest.raises(RuntimeError):
        with t.phase("doomed"):
            raise RuntimeError("boom")
    assert t.seconds["doomed"] == pytest.approx(2.0)


def test_phase_timer_reduced_single_process_identity():
    t = PhaseTimer()
    t.seconds = {"a": 1.5, "b": 0.25}
    assert t.reduced() == {"a": 1.5, "b": 0.25}


# ---------------------------------------------------------------------------
# report renderers (golden inputs)


def _fake_dd(**kw):
    dd = types.SimpleNamespace(
        setup_seconds={"partition": 0.5, "realize": 1.25},
        exchange_seconds=[], exchange_every=1,
        plan_provenance="default")
    for k, v in kw.items():
        setattr(dd, k, v)
    return dd


def test_setup_stats_report_golden():
    line = setup_stats_report(_fake_dd())
    assert line == "setup: partition=0.500000s realize=1.250000s"


def test_exchange_stats_report_no_samples():
    assert exchange_stats_report(_fake_dd()) == \
        "exchange: no samples (enable_timing first)"


def test_exchange_stats_report_golden():
    dd = _fake_dd(exchange_seconds=[2e-3, 2e-3, 2e-3, 2e-3],
                  exchange_bytes_total=lambda: 4_000_000)
    line = exchange_stats_report(dd)
    assert "n=4" in line
    assert "trimean=2.000000e-03s" in line
    assert "expected=4000000B/exchange (analytic)" in line
    assert "eff=2.00GB/s" in line
    assert "amortized" not in line   # s=1: no temporal line
    assert "plan=" not in line       # default provenance: silent


def test_exchange_stats_report_temporal_and_provenance():
    dd = _fake_dd(exchange_seconds=[4e-3] * 4, exchange_every=4,
                  exchange_bytes_total=lambda: 8_000_000,
                  exchange_bytes_amortized_per_step=lambda: 2_000_000.0,
                  plan_provenance="cached")
    line = exchange_stats_report(dd)
    assert "exchange_every=4" in line
    assert "amortized=2000000B/step" in line
    assert "(1.000000e-03s/step exchange cost)" in line
    assert line.endswith("plan=cached")


def test_autotune_report_golden():
    cfg = types.SimpleNamespace(key=lambda: "PpermuteSlab[s=8]")
    plan = types.SimpleNamespace(
        config=cfg, provenance="tuned", measurements=7,
        fingerprint="abcdef0123456789",
        coefficients={"ici": {"alpha_s": 1e-6,
                              "beta_bytes_per_s": 1e11}},
        costs={
            "PpermuteSlab[s=8]": {"predicted_s": 1e-4,
                                  "measured_s": 9e-5},
            "AllGather[s=1]": {"predicted_s": 5e-3},
        })
    text = autotune_report(plan)
    lines = text.splitlines()
    assert lines[0] == ("autotune: PpermuteSlab[s=8] provenance=tuned"
                        " measurements=7 fingerprint=abcdef012345...")
    assert "  link ici: alpha=1.000e-06s beta=1.000e+11B/s (measured)" \
        in lines
    # ranked by measured-else-predicted: the winner first
    assert lines[2].startswith("  PpermuteSlab[s=8]: ")
    assert "measured=9.000e-05s/step" in lines[2]
    assert lines[3].startswith("  AllGather[s=1]: ")
    assert "(pruned by model)" in lines[3]
