"""The serving fleet: sharded/SLO admission, shedding, replica
recovery, rebalancing.

Covers the PR's acceptance contract end to end, asserting from the
EXPORTED surfaces (Prometheus text, v1-schema events), not internal
fields: a replica killed mid-fleet loses zero campaigns and every
recovered campaign finishes bitwise-equal to a fault-free fleet run
with zero recompiles and zero tuner measurements on survivors; floods
are shed loudly below the protected priority while protected tenants
finish unaffected; rebalance migrations resume bitwise on a
destination that recompiles nothing; bucketing bounds the engine
cache under 20 distinct user grids.
"""

import time

import numpy as np
import pytest

from stencil_tpu.resilience.faults import (AdmissionFlood, ReplicaCrash,
                                           SlowReplica)
from stencil_tpu.serving import (BucketError, CampaignRequest,
                                 DeadlineExpired, Fleet, GridBucketer,
                                 RequestQueue, RequestShed, SloPolicy,
                                 TransientDispatchError,
                                 rendezvous_replica)
from stencil_tpu.serving.queue import request_fingerprint
from stencil_tpu.telemetry import (metric_value, parse_prometheus_text,
                                   validate_events)
from stencil_tpu.tuning import FakeTimer

MESH = (2, 2, 2)
GRID = (8, 8, 8)


def req(tenant="t0", campaign="c0", **kw):
    kw.setdefault("grid", GRID)
    kw.setdefault("n_steps", 4)
    kw.setdefault("ckpt_every", 2)
    kw.setdefault("mesh_shape", MESH)
    return CampaignRequest(tenant=tenant, campaign=campaign, **kw)


def fleet(tmp_path, tag, **kw):
    kw.setdefault("n_replicas", 3)
    kw.setdefault("width", 4)
    kw.setdefault("tuner_timer", FakeTimer())
    kw.setdefault("plan_cache_path", str(tmp_path / f"plans-{tag}.json"))
    return Fleet(str(tmp_path / f"root-{tag}"), **kw)


def owner_of(tenant, n_replicas=3, request=None):
    """The rendezvous owner the fleet will route this tenant to."""
    fp = request_fingerprint(request if request is not None
                             else req(tenant=tenant))
    names = [f"replica-{i}" for i in range(n_replicas)]
    return rendezvous_replica(f"{fp}|{tenant}", names)


# ---------------------------------------------------------------------------
# queue: priority + deadline ordering


def test_queue_priority_order_stable_fifo_within_class():
    q = RequestQueue()
    a = q.submit(req(tenant="a", priority=1))
    b = q.submit(req(tenant="b", priority=2))
    c = q.submit(req(tenant="c", priority=2))
    d = q.submit(req(tenant="d", priority=1))
    batch = q.pop_batch(width=4)
    # highest class first, submit order within a class
    assert [e.handle for e in batch] == [b, c, a, d]


def test_queue_priority_back_compat_default_is_fifo():
    q = RequestQueue()
    handles = [q.submit(req(tenant=f"t{i}")) for i in range(4)]
    batch = q.pop_batch(width=4)
    assert [e.handle for e in batch] == handles


def test_queue_priority_head_other_fingerprints_keep_place():
    q = RequestQueue()
    q.submit(req(tenant="low", priority=0))
    q.submit(req(tenant="big", grid=(16, 8, 8), priority=5))
    batch = q.pop_batch(width=4)
    # the high-priority head picks ITS fingerprint's batch
    assert [e.request.tenant for e in batch] == ["big"]
    assert q.pop_batch(width=4)[0].request.tenant == "low"


def test_queue_deadline_expired_rejected_at_pop():
    expired_cb = []
    q = RequestQueue(on_expired=expired_cb.append)
    dead = q.submit(req(tenant="dead", deadline_seconds=0.01))
    live = q.submit(req(tenant="live"))
    time.sleep(0.05)
    batch = q.pop_batch(width=4)
    assert [e.handle for e in batch] == [live]
    assert dead.done()
    with pytest.raises(DeadlineExpired):
        dead.result(timeout=0)
    assert [e.request.tenant for e in expired_cb] == ["dead"]


def test_queue_deadline_validation():
    with pytest.raises(ValueError):
        req(deadline_seconds=0).validate()
    with pytest.raises(ValueError):
        req(deadline_seconds=-1.0).validate()
    req(deadline_seconds=30.0).validate()


# ---------------------------------------------------------------------------
# bucketing + rendezvous policy units


def test_bucketer_picks_smallest_fit_and_rejects_oversize():
    b = GridBucketer(((16, 16, 16), (8, 8, 8)))
    assert b.bucket_for((5, 6, 7)) == (8, 8, 8)
    assert b.bucket_for((8, 8, 8)) == (8, 8, 8)
    assert b.bucket_for((9, 2, 2)) == (16, 16, 16)
    with pytest.raises(BucketError):
        b.bucket_for((17, 1, 1))
    padded, was_padded = b.apply(req(grid=(5, 6, 7)))
    assert was_padded and padded.grid == (8, 8, 8)
    same, untouched = b.apply(req(grid=(8, 8, 8)))
    assert not untouched and same.grid == (8, 8, 8)


def test_bucketed_request_shares_native_fingerprint():
    b = GridBucketer(((8, 8, 8),))
    padded, _ = b.apply(req(tenant="pad", grid=(5, 6, 7)))
    assert request_fingerprint(padded) == \
        request_fingerprint(req(tenant="nat", grid=(8, 8, 8)))


def test_rendezvous_death_remaps_only_the_dead_replicas_keys():
    names = ["replica-0", "replica-1", "replica-2"]
    keys = [f"fp|tenant-{i}" for i in range(40)]
    before = {k: rendezvous_replica(k, names) for k in keys}
    assert len(set(before.values())) == 3  # all replicas own something
    survivors = [n for n in names if n != "replica-1"]
    for k in keys:
        after = rendezvous_replica(k, survivors)
        if before[k] != "replica-1":
            assert after == before[k]  # survivors keep their keys
        else:
            assert after in survivors


# ---------------------------------------------------------------------------
# the zero-loss gate: replica crash -> recovery, bitwise


def test_replica_crash_recovers_all_campaigns_bitwise(tmp_path):
    tenants = [f"t{i}" for i in range(4)]
    reqs = [req(tenant=t, n_steps=6, ckpt_every=2) for t in tenants]

    # one plan cache across both fleets: the calm run tunes once, the
    # chaos run's replicas all resolve their exchange plans from cache
    plans = str(tmp_path / "plans-shared.json")
    calm = fleet(tmp_path, "calm", plan_cache_path=plans)
    calm_handles = [calm.submit(r) for r in reqs]
    calm.serve()
    calm_final = {t: h.result(timeout=0).final["temp"]
                  for t, h in zip(tenants, calm_handles)}

    # kill the replica that owns t0 (computed, not guessed), mid-batch
    victim = int(owner_of("t0").rsplit("-", 1)[1])
    chaos = fleet(tmp_path, "chaos", plan_cache_path=plans, chaos=[
        ReplicaCrash(step=0, replica=victim, at_member_step=2)])
    handles = [chaos.submit(r) for r in reqs]
    chaos.serve()

    # zero campaigns lost, every one bitwise-equal to the calm fleet
    for t, h in zip(tenants, handles):
        np.testing.assert_array_equal(calm_final[t],
                                      h.result(timeout=0).final["temp"])

    # the gate reads the EXPORTED surfaces
    text = chaos.metrics_text()
    assert metric_value(text, "stencil_fleet_replicas",
                        state="dead") == 1.0
    assert metric_value(text, "stencil_fleet_replicas",
                        state="active") == 2.0
    assert metric_value(
        text, "stencil_fleet_recovered_campaigns_total") >= 1.0
    for rep in chaos.replicas:
        if rep.state != "active":
            continue
        rtext = rep.service.metrics_text()
        parsed = parse_prometheus_text(rtext)
        # the series exists (seeded 0) AND is 0: no recompiles, and no
        # tuner measurements for plan-cache-held fingerprints
        assert parsed["stencil_service_recompiles_total"] == {(): 0.0}
        assert parsed["stencil_service_tuner_measurements_total"] \
            == {(): 0.0}
    kinds = [e["event"] for e in chaos.events]
    assert "fault_replica_crash" in kinds
    assert "replica_dead" in kinds
    assert "campaign_recovered" in kinds
    assert validate_events(chaos.events) == []


# ---------------------------------------------------------------------------
# SLO shedding under flood


def test_flood_is_shed_loudly_and_protected_tenants_unaffected(tmp_path):
    protected = [req(tenant="alice", n_steps=4, ckpt_every=2),
                 req(tenant="bob", n_steps=4, ckpt_every=2)]

    calm = fleet(tmp_path, "calm", n_replicas=2)
    calm_final = {}
    for r in protected:
        calm_final[r.tenant] = calm.submit(r)
    calm.serve()
    calm_final = {t: h.result(timeout=0).final["temp"]
                  for t, h in calm_final.items()}

    flooded = fleet(
        tmp_path, "flood", n_replicas=2,
        policy=SloPolicy(max_queue_depth=3),
        chaos=[AdmissionFlood(step=0, tenant="flood", count=6,
                              priority=0, n_steps=1)])
    handles = {r.tenant: flooded.submit(r) for r in protected}
    flooded.serve()

    # protected campaigns complete bitwise-identical to the calm fleet
    for t, h in handles.items():
        np.testing.assert_array_equal(calm_final[t],
                                      h.result(timeout=0).final["temp"])

    text = flooded.metrics_text()
    shed = metric_value(text, "stencil_fleet_shed_total",
                        tenant="flood", reason="queue_depth")
    assert shed >= 1.0
    # protected tenants shed nothing (series exist, seeded 0)
    for t in ("alice", "bob"):
        for reason in ("queue_depth", "admission_latency"):
            parsed = parse_prometheus_text(text)
            assert parsed["stencil_fleet_shed_total"][
                (("reason", reason), ("tenant", t))] == 0.0
    sheds = [e for e in flooded.events if e["event"] == "request_shed"]
    assert len(sheds) == int(shed)
    assert all(e["reason"] == "queue_depth" and e["tenant"] == "flood"
               for e in sheds)
    assert validate_events(flooded.events) == []


def test_shed_reason_thresholds():
    p = SloPolicy(max_queue_depth=4,
                  max_admission_latency_seconds=1.0,
                  protected_priority=1)
    assert p.shed_reason(1, 100, 100.0) is None     # protected
    assert p.shed_reason(0, 4, None) == "queue_depth"
    assert p.shed_reason(0, 3, 2.0) == "admission_latency"
    assert p.shed_reason(0, 3, 0.5) is None


# ---------------------------------------------------------------------------
# rebalance: preempt-on-src -> resume-on-dst, zero dst recompiles


def test_rebalance_migration_bitwise_zero_destination_recompiles(
        tmp_path):
    mig_req = req(tenant="mig", n_steps=6, ckpt_every=2)

    # one SHARED plan cache across both fleets: the calm run tunes
    # once, so NO replica of the migration fleet measures anything
    plans = str(tmp_path / "plans-shared.json")
    calm = fleet(tmp_path, "calm", n_replicas=2, plan_cache_path=plans)
    h = calm.submit(mig_req)
    calm.serve()
    calm_final = h.result(timeout=0).final["temp"]

    fl = fleet(tmp_path, "mig", n_replicas=2, plan_cache_path=plans)
    src = owner_of("mig", n_replicas=2, request=mig_req)
    dst = next(r.name for r in fl.replicas if r.name != src)
    # warm the destination with a fingerprint-identical campaign from
    # a tenant the rendezvous hash routes there
    warm_tenant = next(
        f"w{i}" for i in range(64)
        if owner_of(f"w{i}", n_replicas=2,
                    request=req(tenant=f"w{i}")) == dst)
    warm = fl.submit(req(tenant=warm_tenant, n_steps=2, ckpt_every=2))
    handle = fl.submit(mig_req)
    # preempt-on-src mid-campaign, then pin the resume to dst
    fl.replica(src).service.arm_preempt_at(2)
    fl.pump()
    assert warm.done() and not handle.done()
    fl.migrate("mig", "c0", dst)
    fl.serve()

    np.testing.assert_array_equal(calm_final,
                                  handle.result(timeout=0).final["temp"])
    res = handle.result(timeout=0)
    assert res.resumed_from == 2   # continued, not restarted

    dtext = fl.replica(dst).service.metrics_text()
    # destination recompiled nothing and re-tuned nothing: the warm
    # campaign built the engine (1 compile), the migrated campaign
    # reused it
    assert metric_value(dtext, "stencil_service_recompiles_total") == 0.0
    assert metric_value(dtext,
                        "stencil_service_tuner_measurements_total") == 0.0
    assert metric_value(dtext, "stencil_service_compiles_total") == 1.0
    ftext = fl.metrics_text()
    assert metric_value(ftext, "stencil_fleet_migrations_total") == 1.0
    migs = [e for e in fl.events if e["event"] == "migration"]
    assert len(migs) == 1 and migs[0]["to_replica"] == dst


def test_rebalance_picks_migrations_from_load(tmp_path):
    fl = fleet(tmp_path, "bal", n_replicas=2)
    # pin 4 campaigns onto one replica via pinned routing, then let
    # rebalance spread them
    for i in range(4):
        fl.submit(req(tenant=f"t{i}", n_steps=2))
        fl._campaigns[(f"t{i}", "c0")].pinned = "replica-0"
    moved = fl.rebalance()
    # 4/0 -> 3/1 -> 2/2: two moves reach balance
    assert len(moved) == 2
    assert all(m["from"] == "replica-0" and m["to"] == "replica-1"
               for m in moved)
    load = fl.loads()
    assert abs(load["replica-0"] - load["replica-1"]) < 2
    fl.serve()
    for c in fl._campaigns.values():
        assert c.handle.result(timeout=0).steps == 2


# ---------------------------------------------------------------------------
# bucketing bounds the engine cache


def test_bucketing_caps_engine_cache_under_20_distinct_grids(tmp_path):
    fl = fleet(tmp_path, "buckets", n_replicas=1)
    grids = [(2 + a, 2 + b, 8) for a in range(4) for b in range(5)]
    assert len(set(grids)) == 20
    handles = [fl.submit(req(tenant=f"g{i}", grid=g, n_steps=1,
                             ckpt_every=0))
               for i, g in enumerate(grids)]
    fl.serve()
    for h in handles:
        assert h.result(timeout=0).steps == 1
        assert h.request.grid == (8, 8, 8)  # admitted AT the bucket
    rtext = fl.replicas[0].service.metrics_text()
    # 20 distinct user grids -> ONE bucket-shaped engine
    assert metric_value(rtext, "stencil_service_engine_cache_size") == 1.0
    assert metric_value(rtext, "stencil_service_compiles_total") == 1.0
    assert metric_value(rtext, "stencil_service_recompiles_total") == 0.0
    bucketed = [e for e in fl.events if e["event"] == "request_bucketed"]
    assert len(bucketed) == 20


def test_unbucketable_grid_rejected_loudly(tmp_path):
    fl = fleet(tmp_path, "reject", n_replicas=1)
    h = fl.submit(req(tenant="huge", grid=(64, 64, 64)))
    assert h.done()
    with pytest.raises(BucketError):
        h.result(timeout=0)
    assert any(e["event"] == "request_rejected"
               and e["reason"] == "bucket" for e in fl.events)


# ---------------------------------------------------------------------------
# slow-replica degradation ladder


def test_slow_replica_drains_resards_and_readmits(tmp_path):
    victim_name = owner_of("t0")
    victim = int(victim_name.rsplit("-", 1)[1])
    fl = fleet(tmp_path, "slow", chaos=[
        SlowReplica(step=0, replica=victim, recover_step=1)])
    handles = [fl.submit(req(tenant=f"t{i}", n_steps=2))
               for i in range(3)]
    fl.serve()
    for h in handles:
        assert h.result(timeout=0).steps == 2
    # nothing ran on the degraded replica while it was out
    vtext = fl.replica(victim_name).service.metrics_text()
    assert metric_value(vtext, "stencil_service_batches_total") == 0.0
    kinds = [e["event"] for e in fl.events]
    assert "replica_degraded" in kinds and "replica_recovered" in kinds
    # after readmission it serves its tenants again
    text = fl.metrics_text()
    assert metric_value(text, "stencil_fleet_replicas",
                        state="active") == 3.0
    assert metric_value(text, "stencil_fleet_replicas",
                        state="degraded") == 0.0
    h2 = fl.submit(req(tenant="t0", campaign="c1", n_steps=2))
    fl.serve()
    assert h2.result(timeout=0).steps == 2
    assert metric_value(fl.replica(victim_name).service.metrics_text(),
                        "stencil_service_batches_total") == 1.0


# ---------------------------------------------------------------------------
# dispatch retry/backoff


def test_transient_dispatch_failure_retries_with_backoff(tmp_path):
    delays = []
    fl = fleet(tmp_path, "retry", n_replicas=1,
               retry_base_delay=0.05, retry_sleep=delays.append)
    fl.inject_dispatch_error(TransientDispatchError("blip"),
                             TransientDispatchError("blip"))
    h = fl.submit(req(tenant="t0", n_steps=2))
    fl.serve()
    assert h.result(timeout=0).steps == 2
    assert delays == [0.05, 0.1]   # base_delay * 2**k
    retries = [e for e in fl.events if e["event"] == "dispatch_retry"]
    assert [r["attempt"] for r in retries] == [1, 2]


def test_dispatch_retry_budget_exhaustion_fails_the_campaign(tmp_path):
    fl = fleet(tmp_path, "retryx", n_replicas=1,
               retry_attempts=2, retry_sleep=lambda _d: None)
    fl.inject_dispatch_error(TransientDispatchError("down"),
                             TransientDispatchError("down"),
                             TransientDispatchError("down"))
    h = fl.submit(req(tenant="t0", n_steps=2))
    fl.serve()
    with pytest.raises(TransientDispatchError):
        h.result(timeout=0)
    assert any(e["event"] == "dispatch_failed" for e in fl.events)


def test_non_retriable_dispatch_error_propagates_immediately(tmp_path):
    delays = []
    fl = fleet(tmp_path, "retrynr", n_replicas=1,
               retry_sleep=delays.append)
    fl.inject_dispatch_error(ValueError("not transient"))
    h = fl.submit(req(tenant="t0", n_steps=2))
    fl.serve()
    with pytest.raises(ValueError):
        h.result(timeout=0)
    assert delays == []   # no backoff burned on a non-transient error
