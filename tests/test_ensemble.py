"""Ensemble numerics: N batched members == N independent runs.

The serving contract (stencil_tpu/serving/ensemble.py): the vmapped
member axis changes THROUGHPUT, never results — every member of a
batched dispatch must match the standalone solver bitwise (Jacobi) or
at pinned tolerance (Astaroth), including when another member is
faulted mid-run.
"""

import dataclasses

import numpy as np
import pytest

from stencil_tpu.models.astaroth import FIELDS, Astaroth, \
    _radial_explosion
from stencil_tpu.models.jacobi import Jacobi3D
from stencil_tpu.serving.ensemble import (EnsembleAstaroth,
                                          EnsembleJacobi,
                                          EnsembleSentinel)

MESH = (2, 2, 2)
GRID = (8, 8, 8)


def _jacobi_ics(n, seed=0):
    rng = np.random.default_rng(seed)
    return [0.5 + 0.01 * rng.standard_normal(GRID[::-1])
            .astype(np.float32) for _ in range(n)]


def _poison(eng, k):
    host = eng.member_interior(eng.names[0], k)
    host[0, 0, 0] = np.nan
    eng.set_member_interior(eng.names[0], k, host)


# ---------------------------------------------------------------------------
# Jacobi: bitwise


def test_ensemble_jacobi_bitwise_vs_independent_runs():
    """An N=8 batched dispatch (one compiled executable) is bitwise-
    equal, member by member, to 8 independent Jacobi3D runs with the
    same distinct initial conditions."""
    n = 8
    ics = _jacobi_ics(n)
    eng = EnsembleJacobi(n, *GRID, mesh_shape=MESH)
    eng.init()
    for k in range(n):
        eng.set_member_interior("temp", k, ics[k])
    eng.run(4)

    ref = Jacobi3D(*GRID, mesh_shape=MESH, kernel="xla")
    for k in range(n):
        ref.init()
        ref.dd.set_interior("temp", ics[k])
        ref.run(4)
        np.testing.assert_array_equal(
            ref.temperature(), eng.member_interior("temp", k),
            err_msg=f"member {k}")


def test_ensemble_jacobi_per_member_params():
    """Per-member hot/cold Dirichlet temperatures: each member of a
    mixed batch is bitwise-equal to a single-member ensemble run with
    that member's parameters (one executable, many parameter points)."""
    n = 4
    temps = [(1.0, 0.0), (2.0, -1.0), (0.75, 0.25), (1.5, 0.5)]
    eng = EnsembleJacobi(n, *GRID, mesh_shape=MESH)
    for k, (hot, cold) in enumerate(temps):
        eng.set_member_params(k, {"hot_temp": hot, "cold_temp": cold})
    eng.init()
    eng.run(3)
    for k, (hot, cold) in enumerate(temps):
        solo = EnsembleJacobi(1, *GRID, mesh_shape=MESH)
        solo.set_member_params(0, {"hot_temp": hot, "cold_temp": cold})
        solo.init()
        solo.run(3)
        np.testing.assert_array_equal(
            solo.member_interior("temp", 0),
            eng.member_interior("temp", k), err_msg=f"member {k}")


def test_ensemble_jacobi_fault_isolated():
    """A NaN injected into one member mid-run corrupts ONLY that lane:
    every other member stays bitwise-equal to the fault-free batch, and
    the per-member sentinel trips only the faulted member."""
    n = 8
    ics = _jacobi_ics(n, seed=3)

    def build():
        eng = EnsembleJacobi(n, *GRID, mesh_shape=MESH)
        eng.init()
        for k in range(n):
            eng.set_member_interior("temp", k, ics[k])
        return eng

    faulted, clean = build(), build()
    faulted.run(2)
    clean.run(2)
    _poison(faulted, 5)
    faulted.run(2)
    clean.run(2)

    sentinel = EnsembleSentinel(faulted)
    sentinel.probe(4)
    health = sentinel.poll(block=True)[0]
    assert health.tripped_members == [5]
    assert "member 5" in health.members[5].reason

    assert np.isnan(faulted.member_interior("temp", 5)).any()
    for k in range(n):
        if k == 5:
            continue
        np.testing.assert_array_equal(
            clean.member_interior("temp", k),
            faulted.member_interior("temp", k), err_msg=f"member {k}")


def test_ensemble_sentinel_reset_member():
    eng = EnsembleJacobi(2, *GRID, mesh_shape=MESH)
    eng.init()
    _poison(eng, 1)
    s = EnsembleSentinel(eng)
    s.probe(0)
    assert s.poll(block=True)[0].tripped_members == [1]
    eng.reset_member(1)
    s.reset_member(1)
    s.probe(1)
    assert s.poll(block=True)[0].tripped_members == []


# ---------------------------------------------------------------------------
# Jacobi: per-member checkpoints


def test_member_checkpoint_roundtrip(tmp_path):
    eng = EnsembleJacobi(3, *GRID, mesh_shape=MESH)
    eng.init()
    for k, ic in enumerate(_jacobi_ics(3, seed=7)):
        eng.set_member_interior("temp", k, ic)
    eng.run(2)
    want = eng.member_interior("temp", 1)
    eng.save_member(str(tmp_path), 2, 1)

    eng.run(3)  # diverge
    assert not np.array_equal(want, eng.member_interior("temp", 1))
    other = eng.member_interior("temp", 2)
    step = eng.restore_member(str(tmp_path), 1)
    assert step == 2
    np.testing.assert_array_equal(want, eng.member_interior("temp", 1))
    # restoring member 1 never touches member 2's lane
    np.testing.assert_array_equal(other, eng.member_interior("temp", 2))


def test_member_checkpoint_corrupt_falls_back(tmp_path):
    import glob
    import os

    eng = EnsembleJacobi(2, *GRID, mesh_shape=MESH)
    eng.init()
    eng.run(1)
    eng.save_member(str(tmp_path), 1, 0)
    want = eng.member_interior("temp", 0)
    eng.run(1)
    eng.save_member(str(tmp_path), 2, 0)
    # truncate the newest step's array blobs on disk
    for f in glob.glob(str(tmp_path / "2" / "state" / "**"),
                       recursive=True):
        if os.path.isfile(f) and os.path.getsize(f) > 8:
            with open(f, "r+b") as fh:
                fh.truncate(4)
    from stencil_tpu.utils.checkpoint import close_checkpoints
    close_checkpoints(str(tmp_path))
    step = eng.restore_member(str(tmp_path), 0)
    assert step == 1
    np.testing.assert_array_equal(want, eng.member_interior("temp", 0))


# ---------------------------------------------------------------------------
# Astaroth: pinned tolerance, including per-member physics


ASTAROTH_RTOL = 1e-12
ASTAROTH_ATOL = 1e-15


def _astaroth_ref(seed, iters, overrides=None):
    ref = Astaroth(*GRID, mesh_shape=MESH, kernel="xla",
                   dtype=np.float64)
    if overrides:
        ref.prm = dataclasses.replace(ref.prm, **overrides)
        ref._build_step()
    rng = np.random.default_rng(seed)
    for q in ("ax", "ay", "az", "ss"):
        ref.dd.set_interior(q, rng.uniform(-1.0, 1.0, size=GRID[::-1]))
    ref.dd.set_interior("lnrho", np.full(GRID[::-1], 0.5))
    ux, uy, uz = _radial_explosion(ref.dd.size, ref.prm)
    ref.dd.set_interior("uux", ux)
    ref.dd.set_interior("uuy", uy)
    ref.dd.set_interior("uuz", uz)
    ref.run(iters)
    return ref


def test_ensemble_astaroth_matches_independent_runs():
    """A batched MHD dispatch with distinct initial conditions AND one
    member running different physics (viscosity/resistivity) matches
    the standalone solver at pinned float64 tolerance."""
    n = 4
    overrides = {"nu_visc": 7e-3, "eta": 6e-3}
    eng = EnsembleAstaroth(n, *GRID, mesh_shape=MESH, dtype=np.float64)
    eng.init(seeds=[20, 21, 22, 23])
    eng.set_member_params(2, overrides)
    eng.run(2)
    for k in (0, 2):
        ref = _astaroth_ref(20 + k, 2,
                            overrides if k == 2 else None)
        for q in FIELDS:
            np.testing.assert_allclose(
                ref.field(q), eng.member_interior(q, k),
                rtol=ASTAROTH_RTOL, atol=ASTAROTH_ATOL,
                err_msg=f"member {k} field {q}")


def test_ensemble_astaroth_fault_isolated():
    n = 3
    eng = EnsembleAstaroth(n, *GRID, mesh_shape=MESH, dtype=np.float64)
    eng.init(seeds=[30, 31, 32])
    eng.run(1)
    _poison(eng, 0)
    eng.run(1)
    sentinel = EnsembleSentinel(eng)
    sentinel.probe(2)
    health = sentinel.poll(block=True)[0]
    assert health.tripped_members == [0]
    # untouched members still match the standalone solver
    ref = _astaroth_ref(31, 2)
    for q in FIELDS:
        np.testing.assert_allclose(
            ref.field(q), eng.member_interior(q, 1),
            rtol=ASTAROTH_RTOL, atol=ASTAROTH_ATOL, err_msg=q)


def test_member_checkpoint_restores_rk_accumulator(tmp_path):
    """An Astaroth lane rollback must restore the RK accumulator with
    the fields — resuming with a zeroed w would silently change the
    trajectory."""
    eng = EnsembleAstaroth(2, *GRID, mesh_shape=MESH, dtype=np.float64)
    eng.init(seeds=[40, 41])
    eng.run(1)
    eng.save_member(str(tmp_path), 1, 0)
    want = {q: eng.member_interior(q, 0) for q in FIELDS}
    eng.run(2)
    eng.restore_member(str(tmp_path), 0)
    for q in FIELDS:
        np.testing.assert_array_equal(want[q],
                                      eng.member_interior(q, 0))
    eng.run(1)
    # the restored trajectory continues exactly like an uninterrupted
    # one: fields AND accumulator must have come back
    ref = _astaroth_ref(40, 2)
    for q in FIELDS:
        np.testing.assert_allclose(
            ref.field(q), eng.member_interior(q, 0),
            rtol=ASTAROTH_RTOL, atol=ASTAROTH_ATOL, err_msg=q)


# ---------------------------------------------------------------------------
# engine hygiene


def test_ensemble_rejects_bad_member_count():
    with pytest.raises(ValueError):
        EnsembleJacobi(0, *GRID, mesh_shape=MESH)


def test_unknown_param_rejected():
    eng = EnsembleJacobi(2, *GRID, mesh_shape=MESH)
    with pytest.raises(KeyError):
        eng.set_member_params(0, {"viscosity": 1.0})


def test_snapshot_async_roundtrip():
    eng = EnsembleJacobi(2, *GRID, mesh_shape=MESH)
    eng.init()
    eng.run(1)
    snap = eng.member_snapshot_async(1, step=1)
    data = snap.get()  # blocks if needed
    assert snap.ready()
    np.testing.assert_array_equal(data["temp"],
                                  eng.member_interior("temp", 1))
