"""Multi-slice (DCN-tier) mesh layout + profiling scope tests."""

import jax
import numpy as np
import pytest

from stencil_tpu.geometry import Dim3
from stencil_tpu.parallel.mesh import mesh_dim
from stencil_tpu.parallel.multihost import (dcn_bytes_per_exchange,
                                            make_multihost_mesh,
                                            slice_groups)


def test_slice_groups_single_process():
    groups = slice_groups()
    assert sum(len(g) for g in groups) == len(jax.devices())


def test_multihost_mesh_blocks_dcn_axis():
    """With 2 fake slices of 4 devices, the z (DCN) axis must be blocked:
    all subdomains with z-index 0 on slice 0, z-index 1 on slice 1."""
    devs = jax.devices()[:8]
    groups = [devs[:4], devs[4:]]
    mesh = make_multihost_mesh((2, 2, 2), dcn_axis=2, groups=groups)
    assert mesh_dim(mesh) == Dim3(2, 2, 2)
    arr = mesh.devices  # indexed [x, y, z]
    g0 = {d.id for d in devs[:4]}
    for ix in range(2):
        for iy in range(2):
            assert arr[ix, iy, 0].id in g0
            assert arr[ix, iy, 1].id not in g0


def test_multihost_mesh_dcn_axis_x():
    devs = jax.devices()[:8]
    groups = [devs[:2], devs[2:4], devs[4:6], devs[6:]]
    mesh = make_multihost_mesh((4, 2, 1), dcn_axis=0, groups=groups)
    arr = mesh.devices
    for ix in range(4):
        grp = {d.id for d in groups[ix]}
        for iy in range(2):
            assert arr[ix, iy, 0].id in grp


def test_multihost_mesh_validates():
    devs = jax.devices()[:8]
    groups = [devs[:4], devs[4:]]
    with pytest.raises(ValueError):
        make_multihost_mesh((1, 1, 8), dcn_axis=0, groups=groups)  # 1 % 2
    with pytest.raises(ValueError):
        make_multihost_mesh((2, 2, 2), dcn_axis=2,
                            groups=[devs[:3], devs[3:]])


def test_exchange_on_multihost_mesh_and_dcn_bytes():
    """The ripple oracle still holds on a slice-blocked mesh, and the
    DCN byte counter reports the designated axis."""
    from stencil_tpu.distributed import DistributedDomain

    devs = jax.devices()[:8]
    groups = [devs[:4], devs[4:]]
    mesh = make_multihost_mesh((2, 2, 2), dcn_axis=2, groups=groups)
    order = [mesh.devices[ix, iy, iz]
             for iz in range(2) for iy in range(2) for ix in range(2)]
    dd = DistributedDomain(8, 8, 8, devices=order)
    dd.set_mesh_shape((2, 2, 2))
    dd.set_radius(1)
    dd.add_data("q", np.float32)
    dd.realize()
    dd.exchange()
    assert dcn_bytes_per_exchange(dd, dcn_axis=2) > 0


def test_profiling_scopes_and_reports():
    from stencil_tpu.models.jacobi import Jacobi3D
    from stencil_tpu.utils.profiling import (PhaseTimer, scope,
                                             exchange_stats_report,
                                             setup_stats_report)

    pt = PhaseTimer()
    with pt.phase("build"):
        j = Jacobi3D(8, 8, 8, mesh_shape=(2, 2, 2), dtype=np.float32)
    j.init()
    with scope("jacobi-step"):
        j.step()
    assert pt.reduced()["build"] > 0
    assert "partition" in setup_stats_report(j.dd)
    j.dd.enable_timing(True)
    j.dd.exchange()
    assert "trimean" in exchange_stats_report(j.dd)
