"""Multi-slice (DCN-tier) mesh layout + profiling scope tests."""

import jax
import numpy as np
import pytest

from stencil_tpu.geometry import Dim3
from stencil_tpu.parallel.mesh import mesh_dim
from stencil_tpu.parallel.multihost import (dcn_bytes_per_exchange,
                                            make_multihost_mesh,
                                            slice_groups)


def test_slice_groups_single_process():
    groups = slice_groups()
    assert sum(len(g) for g in groups) == len(jax.devices())


def test_multihost_mesh_blocks_dcn_axis():
    """With 2 fake slices of 4 devices, the z (DCN) axis must be blocked:
    all subdomains with z-index 0 on slice 0, z-index 1 on slice 1."""
    devs = jax.devices()[:8]
    groups = [devs[:4], devs[4:]]
    mesh = make_multihost_mesh((2, 2, 2), dcn_axis=2, groups=groups)
    assert mesh_dim(mesh) == Dim3(2, 2, 2)
    arr = mesh.devices  # indexed [x, y, z]
    g0 = {d.id for d in devs[:4]}
    for ix in range(2):
        for iy in range(2):
            assert arr[ix, iy, 0].id in g0
            assert arr[ix, iy, 1].id not in g0


def test_multihost_mesh_dcn_axis_x():
    devs = jax.devices()[:8]
    groups = [devs[:2], devs[2:4], devs[4:6], devs[6:]]
    mesh = make_multihost_mesh((4, 2, 1), dcn_axis=0, groups=groups)
    arr = mesh.devices
    for ix in range(4):
        grp = {d.id for d in groups[ix]}
        for iy in range(2):
            assert arr[ix, iy, 0].id in grp


def test_multihost_mesh_validates():
    devs = jax.devices()[:8]
    groups = [devs[:4], devs[4:]]
    with pytest.raises(ValueError):
        make_multihost_mesh((1, 1, 8), dcn_axis=0, groups=groups)  # 1 % 2
    with pytest.raises(ValueError):
        make_multihost_mesh((2, 2, 2), dcn_axis=2,
                            groups=[devs[:3], devs[3:]])


def test_exchange_on_multihost_mesh_and_dcn_bytes():
    """The ripple oracle still holds on a slice-blocked mesh, and the
    DCN byte counter reports the designated axis."""
    from stencil_tpu.distributed import DistributedDomain

    devs = jax.devices()[:8]
    groups = [devs[:4], devs[4:]]
    mesh = make_multihost_mesh((2, 2, 2), dcn_axis=2, groups=groups)
    order = [mesh.devices[ix, iy, iz]
             for iz in range(2) for iy in range(2) for ix in range(2)]
    dd = DistributedDomain(8, 8, 8, devices=order)
    dd.set_mesh_shape((2, 2, 2))
    dd.set_radius(1)
    dd.add_data("q", np.float32)
    dd.realize()
    dd.exchange()
    assert dcn_bytes_per_exchange(dd, dcn_axis=2) > 0


def test_orchestrator_dcn_tier_end_to_end(tmp_path):
    """The product path VERDICT r3 asked for: DistributedDomain itself
    consumes the slice grouping (set_dcn_axis) — the model runs through
    the orchestrator on 2 fake slices of 4 devices, matches the dense
    oracle, blocks the DCN axis onto slices, and splits ICI vs DCN bytes
    in the plan file (reference: partition.hpp:120-256 NodePartition
    being load-bearing in every placement)."""
    from stencil_tpu.models.jacobi import Jacobi3D, dense_reference_step

    devs = jax.devices()[:8]
    groups = [devs[:4], devs[4:]]
    n = 16
    j = Jacobi3D(n, n, n, dtype=np.float32, dcn_axis="z",
                 dcn_groups=groups, mesh_shape=(2, 2, 2),
                 output_prefix=str(tmp_path) + "/")
    dd = j.dd
    assert dd.dcn_axis == 2 and dd.n_slices == 2
    # the z (DCN) axis is blocked: z-index 0 subdomains on slice 0
    arr = dd.mesh.devices
    g0 = {d.id for d in groups[0]}
    for ix in range(2):
        for iy in range(2):
            assert arr[ix, iy, 0].id in g0
            assert arr[ix, iy, 1].id not in g0
    # byte split: z is 1 of 3 sharded axes; all its boundaries are
    # inter-slice here (counts.z == n_slices)
    total = dd.exchange_bytes_total()
    dcn = dd.exchange_bytes_dcn()
    assert 0 < dcn < total
    assert dd.exchange_bytes_ici() == total - dcn
    plan = (tmp_path / "plan.txt").read_text()
    assert "dcn axis: z (2 slices)" in plan
    assert f"bytes per exchange over DCN (whole mesh): {dcn}" in plan
    # numerics through the orchestrator still match the dense oracle
    j.init()
    temp = j.temperature()
    hot = (n // 3, n // 2, n // 2)
    cold = (2 * n // 3, n // 2, n // 2)
    for _ in range(2):
        temp = dense_reference_step(temp, hot, cold, n // 10)
    j.run(2)
    np.testing.assert_allclose(j.temperature(), temp, atol=2e-6)


def test_orchestrator_dcn_auto_axis_and_shape():
    """Without an explicit mesh shape, realize() derives the grid from
    NodePartition's interface-minimizing split and picks a divisible
    DCN axis automatically."""
    from stencil_tpu.distributed import DistributedDomain

    devs = jax.devices()[:8]
    groups = [devs[:4], devs[4:]]
    dd = DistributedDomain(32, 16, 16, devices=devs)
    dd.set_radius(1)
    dd.set_dcn_axis(groups=groups)
    dd.add_data("q", np.float32)
    dd.realize()
    assert dd.n_slices == 2
    assert dd.dcn_axis in (0, 1, 2)
    dim = dd.placement.dim()
    assert dim.flatten() == 8
    assert dim[dd.dcn_axis] % 2 == 0
    dd.exchange()  # program compiles and runs on the blocked mesh


def test_profiling_scopes_and_reports():
    from stencil_tpu.models.jacobi import Jacobi3D
    from stencil_tpu.utils.profiling import (PhaseTimer, scope,
                                             exchange_stats_report,
                                             setup_stats_report)

    pt = PhaseTimer()
    with pt.phase("build"):
        j = Jacobi3D(8, 8, 8, mesh_shape=(2, 2, 2), dtype=np.float32)
    j.init()
    with scope("jacobi-step"):
        j.step()
    assert pt.reduced()["build"] > 0
    assert "partition" in setup_stats_report(j.dd)
    j.dd.enable_timing(True)
    j.dd.exchange()
    assert "trimean" in exchange_stats_report(j.dd)


def test_dcn_tier_halo_kernel_matches_dense_oracle():
    """DCN tier x the fused halo fast path: with no explicit mesh the
    model derives an x-free slice-compatible shape (NodePartition's
    split may shard x, which the slab kernels cannot use), and the
    temporally-blocked slab exchange runs across the inter-slice
    boundary unchanged."""
    import numpy as np

    from stencil_tpu.models.jacobi import Jacobi3D, dense_reference_step

    devs = jax.devices()[:8]
    groups = [devs[:4], devs[4:]]
    n = 16
    j = Jacobi3D(n, n, n, dtype=np.float32, devices=devs,
                 kernel="halo", dcn_axis="z", dcn_groups=groups)
    assert j.kernel_path == "halo"
    assert j.dd.n_slices == 2
    dim = j.dd.placement.dim()
    assert dim.x == 1 and dim.z % 2 == 0, tuple(dim)
    assert j.dd.exchange_bytes_dcn() > 0
    j.init()
    temp = j.temperature()
    hot = (n // 3, n // 2, n // 2)
    cold = (2 * n // 3, n // 2, n // 2)
    for _ in range(3):
        temp = dense_reference_step(temp, hot, cold, n // 10)
    j.run(3)
    np.testing.assert_allclose(j.temperature(), temp, rtol=1e-5,
                               atol=1e-5)


def test_dcn_tier_astaroth_halo_mesh_derivation():
    """Astaroth mirrors the Jacobi rule: DCN tier + kernel='halo'
    derives an x-free slice-compatible mesh (radius-3 slab kernels)."""
    import numpy as np

    from stencil_tpu.models.astaroth import Astaroth

    devs = jax.devices()[:8]
    groups = [devs[:4], devs[4:]]
    m = Astaroth(16, 16, 32, dtype=np.float64, devices=devs,
                 kernel="halo", dcn_axis="z", dcn_groups=groups)
    assert m.kernel_path == "halo"
    assert m.dd.n_slices == 2
    dim = m.dd.placement.dim()
    assert dim.x == 1 and dim.z % 2 == 0, tuple(dim)
