"""Performance observatory: attribution, drift, ledger, flight recorder.

The ISSUE 11 acceptance contracts:

* the drift detector's state machine (fake-injected error ratios: no
  event inside tolerance, one ``perf_drift`` event + plan-cache
  invalidation after K consecutive misses, a re-tuned plan clears the
  gauge);
* the ledger schema, the append-only trajectory, the regression gate
  (a synthetic same-fingerprint steps/s drop = nonzero CLI exit, the
  honest ledger passes), and the legacy BENCH_*.json backfill;
* the flight recorder (a chaos NaN trip produces a schema-valid dump
  whose timeline contains the trip step and the rollback; the SIGTERM
  path dumps BEFORE the preemption checkpoint);
* the attribution honesty contract (the attributed program IS the
  uninstrumented one — the registry targets pin the HLO identity, and
  the host-callback timer fixture is the proven-flagged negative
  control).
"""

import glob
import json
import os
import pathlib

import numpy as np
import pytest

from stencil_tpu.models.jacobi import Jacobi3D
from stencil_tpu.observatory import (FlightRecorder, PerfAttributor,
                                     METRIC_MODEL_ERROR_RATIO,
                                     append_record, backfill_records,
                                     diff_records, gate_regressions,
                                     make_record, model_step_seconds_for,
                                     payload_records, read_ledger,
                                     render_timeline, validate_dump,
                                     validate_record)
from stencil_tpu.observatory.__main__ import main as observatory_cli
from stencil_tpu.resilience import (FaultPlan, NaNInjection, Preemption,
                                    ResiliencePolicy)
from stencil_tpu.telemetry import MetricsRegistry, metric_value
from stencil_tpu.tuning import (Candidate, Plan, invalidate_plan,
                                load_plan, store_plan)

REPO = pathlib.Path(__file__).parent.parent

N = 16
STEPS = 12


def make_jacobi(**kw):
    j = Jacobi3D(N, N, N, mesh_shape=(2, 2, 2), dtype=np.float32, **kw)
    j.init()
    return j


def fast_policy(**kw):
    kw.setdefault("check_every", 1)
    kw.setdefault("ckpt_every", 4)
    kw.setdefault("base_delay", 0.0)
    kw.setdefault("sleep", lambda s: None)
    return ResiliencePolicy(**kw)


def make_attributor(events, reg, on_drift=None, **kw):
    kw.setdefault("model_step_seconds", 1.0)
    kw.setdefault("model_bytes_per_step", 1000.0)
    kw.setdefault("tolerance", 0.25)
    kw.setdefault("window", 3)
    return PerfAttributor("test", "PpermuteSlab", 2,
                          emit=lambda k, **a: events.append((k, a)),
                          on_drift=on_drift, registry=reg,
                          fingerprint="f" * 32, **kw)


# ----------------------------------------------------------------------
# attribution + drift detector
# ----------------------------------------------------------------------
def test_in_tolerance_ratios_never_drift():
    events, reg = [], MetricsRegistry()
    att = make_attributor(events, reg)
    # calibration + jitter inside the 25% band
    for seconds in (4.0, 4.3, 3.8, 4.1, 4.4):
        assert att.observe(4, seconds) is None
    assert not events
    # gauges exported with the {entry,method,s} labels
    txt = reg.to_prometheus_text()
    got = metric_value(txt, METRIC_MODEL_ERROR_RATIO, entry="test",
                       method="PpermuteSlab", s="2")
    assert got == pytest.approx(4.4 / 4)
    achieved = metric_value(txt, "stencil_perf_achieved_bytes_per_s",
                            entry="test", method="PpermuteSlab", s="2")
    assert achieved == pytest.approx(1000.0 / 1.1)


def test_drift_fires_once_after_k_consecutive_misses():
    events, reg = [], MetricsRegistry()
    att = make_attributor(events, reg)
    att.observe(1, 1.0)           # calibrate: ratio 1.0
    att.observe(1, 2.0)           # miss 1
    att.observe(1, 2.0)           # miss 2
    assert not events
    verdict = att.observe(1, 2.0, step=30)  # miss 3 = K -> drift
    assert verdict is not None
    assert events and events[0][0] == "perf_drift"
    attrs = events[0][1]
    assert attrs["consecutive"] == 3 and attrs["step"] == 30
    assert attrs["fingerprint"] == "f" * 32
    # latched: further misses do not refire
    att.observe(1, 2.0)
    assert len(events) == 1


def test_recovery_inside_tolerance_rearms_the_detector():
    events, reg = [], MetricsRegistry()
    att = make_attributor(events, reg)
    att.observe(1, 1.0)
    for _ in range(3):
        att.observe(1, 2.0)
    assert len(events) == 1
    # back in tolerance: streak clears, latch re-arms
    for _ in range(4):
        att.observe(1, 1.05)
    for _ in range(3):
        att.observe(1, 2.2)
    assert len(events) == 2


def test_gradual_slowdown_still_drifts():
    """The boiling frog: the calibrated reference stays FIXED, so a
    4%-per-observation compounding slowdown must eventually register
    as drift (a moving/EWMA reference would chase it forever)."""
    events, reg = [], MetricsRegistry()
    att = make_attributor(events, reg)
    seconds = 1.0
    att.observe(1, seconds)
    for _ in range(60):
        seconds *= 1.04
        att.observe(1, seconds)
        if events:
            break
    assert events and events[0][0] == "perf_drift"


def test_zero_duration_observation_cannot_poison_calibration():
    """A degenerate zero-seconds observation (fake clocks) must not
    anchor the reference at 0 and divide by it later."""
    events, reg = [], MetricsRegistry()
    att = make_attributor(events, reg)
    att.observe(1, 0.0)          # cannot calibrate a relative band
    att.observe(1, 0.5)          # calibrates HERE instead of crashing
    att.observe(1, 0.6)
    assert att.last_ratio == pytest.approx(0.6)
    assert not events


def test_miss_streak_must_be_consecutive():
    events, reg = [], MetricsRegistry()
    att = make_attributor(events, reg)
    att.observe(1, 1.0)
    att.observe(1, 2.0)
    att.observe(1, 2.0)
    att.observe(1, 1.0)           # clean observation breaks the streak
    att.observe(1, 2.0)
    att.observe(1, 2.0)
    assert not events


def test_reset_clears_gauge_and_recalibrates():
    """The re-tuned-plan contract: reset() zeroes the exported ratio
    gauge and drops the calibrated reference."""
    events, reg = [], MetricsRegistry()
    att = make_attributor(events, reg)
    att.observe(1, 1.7)
    assert metric_value(reg.to_prometheus_text(),
                        METRIC_MODEL_ERROR_RATIO, entry="test",
                        method="PpermuteSlab", s="2") == 1.7
    att.reset(model_step_seconds=0.5, fingerprint="a" * 32)
    assert metric_value(reg.to_prometheus_text(),
                        METRIC_MODEL_ERROR_RATIO, entry="test",
                        method="PpermuteSlab", s="2") == 0.0
    assert att.last_ratio is None
    # the next observation calibrates against the NEW model price
    att.observe(1, 1.0)
    assert att.last_ratio == pytest.approx(2.0)


def test_drift_invalidates_plan_cache(tmp_path):
    """K consecutive misses + on_drift wired to the cache: the stale
    plan's record is dropped so the next tune re-measures."""
    cache = tmp_path / "plans.json"
    plan = Plan(config=Candidate("PpermuteSlab", 1),
                fingerprint="f" * 32, coefficients={}, costs={})
    store_plan(plan, cache)
    assert load_plan("f" * 32, cache) is not None

    events, reg = [], MetricsRegistry()
    att = make_attributor(
        events, reg,
        on_drift=lambda a: invalidate_plan(a["fingerprint"], cache))
    att.observe(1, 1.0)
    for _ in range(3):
        att.observe(1, 3.0)
    assert load_plan("f" * 32, cache) is None
    # a second invalidation is a clean miss, not an error
    assert invalidate_plan("f" * 32, cache) is False


def test_driver_wires_retune_on_drift(tmp_path):
    """The resilience driver's drift hook: with retune_on_drift the
    attributor's on_drift drops the domain plan's cache record and
    logs plan_invalidated through the report's event log."""
    from stencil_tpu.resilience.driver import _ResilientRun

    cache = tmp_path / "plans.json"
    j = make_jacobi()
    fp = "c" * 32
    plan = Plan(config=Candidate("PpermuteSlab", 1), fingerprint=fp,
                coefficients={"ici": {"alpha_s": 1e-5,
                                      "beta_bytes_per_s": 1e10}},
                costs={})
    store_plan(plan, cache)
    j.dd.plan = plan
    run = _ResilientRun(j.dd, j.step, 2,
                        fast_policy(retune_on_drift=True,
                                    plan_cache_path=str(cache)),
                        None, None, None, None, None, None, None)
    assert run.attributor is not None and run.attributor.enabled
    assert run.attributor.fingerprint == fp
    run.attributor._on_drift({"fingerprint": fp})
    assert load_plan(fp, cache) is None
    kinds = [e["event"] for e in run.report.events]
    assert "plan_invalidated" in kinds


def test_model_step_seconds_for_domains():
    j = make_jacobi()
    model = model_step_seconds_for(j.dd)
    assert model is not None and model > 0
    # a single-device mesh has nothing on the wire to attribute
    import jax
    j1 = Jacobi3D(8, 8, 8, mesh_shape=(1, 1, 1),
                  devices=jax.devices()[:1], dtype=np.float32)
    j1.init()
    assert model_step_seconds_for(j1.dd) is None


def test_disabled_attributor_is_a_passthrough():
    events, reg = [], MetricsRegistry()
    att = make_attributor(events, reg, model_step_seconds=None)
    assert not att.enabled
    with att.dispatch(4, block=lambda: (_ for _ in ()).throw(
            AssertionError("disabled attribution must not block"))):
        pass
    assert att.observe(4, 10.0) is None and not events


def test_attributed_program_is_the_uninstrumented_one():
    """The honesty contract the observatory.attribution.* registry
    targets pin: attribution never edits the dispatched program."""
    def fn(x):
        return x
    assert PerfAttributor.attributed(fn) is fn


def test_host_callback_timer_fixture_flagged(tmp_path):
    """Negative control: a timer that sneaks a host callback into the
    step must fail the transfer checker (nonzero CLI exit)."""
    from stencil_tpu.analysis import run_targets
    from stencil_tpu.analysis.registry import load_targets
    fixtures = pathlib.Path(__file__).parent / "fixtures" / "lint"
    report = run_targets(load_targets(fixtures / "bad_attribution.py"))
    assert len(report.errors) >= 2
    assert all(f.checker == "transfer" for f in report.findings)
    assert any("pure_callback" in f.message for f in report.errors)
    assert any("io_callback" in f.message for f in report.errors)


# ----------------------------------------------------------------------
# ledger
# ----------------------------------------------------------------------
def _record(sps=100.0, bench="b", fp="a" * 32, prov="measured",
            created=1.0):
    return make_record(bench, {"grid": [8, 8, 8]},
                       {"steps_per_s": sps}, provenance=prov,
                       fingerprint=fp, created=created)


def test_record_schema_validates():
    rec = _record()
    assert validate_record(rec) == []
    bad = dict(rec)
    bad["provenance"] = "guessed"
    assert any("provenance" in p for p in validate_record(bad))
    bad = dict(rec)
    bad["metrics"] = {"steps_per_s": -1.0}
    assert any("steps_per_s" in p for p in validate_record(bad))
    with pytest.raises(ValueError):
        make_record("b", {}, {"steps_per_s": float("nan")})


def test_append_read_roundtrip_and_torn_line(tmp_path):
    path = tmp_path / "ledger.jsonl"
    append_record(path, _record(100.0))
    append_record(path, _record(120.0, created=2.0))
    recs = read_ledger(path)
    assert [r["metrics"]["steps_per_s"] for r in recs] == [100.0, 120.0]
    with open(path, "a") as f:
        f.write("{torn\n")
    with pytest.raises(ValueError):
        read_ledger(path)


def test_gate_passes_improvement_and_catches_regression():
    honest = [_record(100.0), _record(110.0, created=2.0)]
    assert gate_regressions(honest, threshold=0.2) == []
    regressed = honest + [_record(50.0, created=3.0)]
    fails = gate_regressions(regressed, threshold=0.2)
    assert len(fails) == 1 and "regressed" in fails[0]
    # different fingerprint = different trajectory: never compared
    other = honest + [_record(50.0, fp="b" * 32, created=3.0)]
    assert gate_regressions(other, threshold=0.2) == []
    # legacy provenance does not gate by default, but can opt in
    legacy = [_record(100.0, prov="legacy"),
              _record(10.0, prov="legacy", created=2.0)]
    assert gate_regressions(legacy) == []
    assert len(gate_regressions(legacy,
                                provenances=("measured", "legacy"))) == 1


def test_diff_records_ratio_and_comparability():
    d = diff_records(_record(100.0), _record(150.0, created=2.0))
    assert d["comparable"]
    assert d["metrics"]["steps_per_s"]["ratio"] == pytest.approx(1.5)
    d = diff_records(_record(100.0), _record(150.0, fp="b" * 32))
    assert not d["comparable"]


def test_backfill_committed_legacy_history():
    """The five committed BENCH_*.json shapes all convert; failed and
    suspect legacy runs are skipped, never invented."""
    from stencil_tpu.observatory.ledger import backfill_files
    files = [REPO / f for f in
             ("BENCH_pr3.json", "BENCH_pr4.json", "BENCH_pr8.json",
              "BENCH_pr10.json", "BENCH_r01.json", "BENCH_r02.json",
              "BENCH_r03.json", "BENCH_r04.json", "BENCH_r05.json")]
    records, skipped = backfill_files(files)
    assert len(records) == 10
    assert all(r["provenance"] == "legacy" for r in records)
    assert all(validate_record(r) == [] for r in records)
    benches = {r["bench"] for r in records}
    assert {"bench_exchange", "bench_exchange.megastep",
            "bench_exchange.autotune", "pic"} <= benches
    # r02 failed, r04/r05 are suspect: skipped with a reason each
    assert len(skipped) == 3
    # legacy history seeds trajectories but never trips the gate
    assert gate_regressions(records) == []


def test_payload_records_carry_contract_race_legs():
    """The segment compiler's race legs convert into their OWN
    trajectory groups: bench_exchange payloads with pic /
    astaroth_temporal fused legs and pic payloads with a fused block
    each land one extra megastep record (the one shared converter —
    live emission and backfill can never fork these groups)."""
    from stencil_tpu.observatory.ledger import payload_records

    leg = {"check_every": 8, "steps": 16,
           "stepwise_steps_per_s": 100.0, "fused_steps_per_s": 180.0,
           "fused_over_stepwise": 1.8}
    be = {"bench": "bench_exchange", "mesh": [1, 1, 1],
          "per_device_size": [8, 8, 8], "radius": [1, 1, 1],
          "fields": 1,
          "configs": [{"exchange_every": 1, "steps_per_s": 50.0}],
          "fused": {**leg, "pic": dict(leg),
                    "astaroth_temporal": {**leg,
                                          "exchange_every": 2}}}
    records, skipped = payload_records(be, "t", provenance="measured",
                                       created=1.0)
    assert not skipped
    by_bench = {r["bench"]: r for r in records}
    assert {"bench_exchange", "bench_exchange.megastep",
            "bench_exchange.megastep.pic",
            "bench_exchange.megastep.astaroth_temporal"} \
        <= set(by_bench)
    ast = by_bench["bench_exchange.megastep.astaroth_temporal"]
    assert ast["config"]["exchange_every"] == 2
    assert ast["metrics"]["steps_per_s"] == 180.0
    assert ast["metrics"]["fused_over_stepwise"] == 1.8

    pic = {"bench": "pic", "seconds_per_step": 0.01,
           "particle_steps_per_s": 1000.0,
           "migration_bytes_per_shard": 64, "overflow": 0,
           "config": {"grid": [8, 8, 8]}, "fused": dict(leg)}
    records, skipped = payload_records(pic, "t", provenance="measured",
                                       created=1.0)
    assert not skipped
    by_bench = {r["bench"]: r for r in records}
    assert set(by_bench) == {"pic", "pic.megastep"}
    assert by_bench["pic.megastep"]["metrics"]["steps_per_s"] == 180.0
    assert by_bench["pic.megastep"]["config"]["check_every"] == 8


def test_payload_records_stamp_depths_post_fingerprint():
    """Asymmetric-depth bench configs carry a structured ``depths``
    vector in the ledger record, stamped AFTER the fingerprint is
    taken: a payload with and without the vector lands in the same
    (fingerprint, bench) trajectory group (the ``exchange_every``
    label string already keys it)."""
    from stencil_tpu.observatory.ledger import payload_records

    base = {"bench": "bench_exchange", "mesh": [2, 2, 2],
            "per_device_size": [8, 8, 8], "radius": [1, 1, 1],
            "fields": 1}
    with_depths = {**base,
                   "configs": [{"exchange_every": "1.1.4",
                                "depths": [1, 1, 4],
                                "steps_per_s": 80.0}]}
    without = {**base,
               "configs": [{"exchange_every": "1.1.4",
                            "steps_per_s": 80.0}]}
    stamped, _ = payload_records(with_depths, "t",
                                 provenance="measured", created=1.0)
    plain, _ = payload_records(without, "t",
                               provenance="measured", created=1.0)
    assert stamped[0]["config"]["depths"] == [1, 1, 4]
    assert "depths" not in plain[0]["config"]
    assert stamped[0]["fingerprint"] == plain[0]["fingerprint"]
    assert stamped[0]["config"]["exchange_every"] == "1.1.4"


def test_gate_and_groups_accept_bench_globs_and_brackets():
    """The ledger CLIs' ``--bench`` filter is a glob with
    literal-bracket tolerance: ``bench_exchange*`` restricts the gate,
    and a bench id carrying ``[...]`` (the candidate-key spelling)
    matches both its exact string and a ``*[s=...]`` pattern that raw
    fnmatch would misread as a character class."""
    from stencil_tpu.observatory.ledger import gate_groups_checked

    regressed = [_record(100.0), _record(50.0, created=2.0),
                 _record(100.0, bench="pic", fp="b" * 32),
                 _record(90.0, bench="pic", fp="b" * 32, created=2.0)]
    assert len(gate_regressions(regressed, threshold=0.2)) == 1
    assert len(gate_regressions(regressed, threshold=0.2,
                                bench="b*")) == 1
    assert gate_regressions(regressed, threshold=0.2,
                            bench="pic") == []
    assert gate_groups_checked(regressed, bench="b*") == 1
    assert gate_groups_checked(regressed) == 2

    bracketed = [_record(100.0, bench="bench_exchange[s=1.1.4]"),
                 _record(40.0, bench="bench_exchange[s=1.1.4]",
                         created=2.0)]
    for pat in ("bench_exchange[s=1.1.4]", "*[s=1.1.4]",
                "bench_exchange*"):
        assert len(gate_regressions(bracketed, threshold=0.2,
                                    bench=pat)) == 1, pat
        assert gate_groups_checked(bracketed, bench=pat) == 1, pat
    assert gate_regressions(bracketed, threshold=0.2,
                            bench="*[s=2]") == []


def test_committed_seed_ledger_matches_backfill():
    """bench/ledger.jsonl: the first ten records are exactly the
    backfill of the committed legacy snapshots; everything after is a
    measured record (PR 15 landed the megastep carry-contract race
    trajectories — bench_exchange.megastep.pic / .astaroth_temporal /
    pic.megastep — as measured history), all schema-valid and the
    whole file gate-clean."""
    from stencil_tpu.observatory.ledger import (gate_regressions,
                                                validate_ledger)
    recs = read_ledger(REPO / "bench" / "ledger.jsonl")
    assert validate_ledger(recs) == []
    assert len(recs) >= 22
    assert all(r["provenance"] == "legacy" for r in recs[:10])
    assert all(r["provenance"] == "measured" for r in recs[10:])
    benches = {r["bench"] for r in recs[10:]}
    assert {"bench_exchange.megastep", "bench_exchange.megastep.pic",
            "bench_exchange.megastep.astaroth_temporal",
            "pic.megastep"} <= benches
    # the measured trajectories gate clean at the committed threshold
    assert gate_regressions(recs, threshold=0.8) == []


def test_live_and_backfilled_records_share_groups(tmp_path):
    """One converter serves live emission and backfill, so a live
    bench_exchange record lands in the same (fingerprint, bench)
    trajectory group as its legacy ancestor."""
    payload = json.load(open(REPO / "BENCH_pr3.json"))
    legacy, _ = backfill_records(payload, "BENCH_pr3.json", created=1.0)
    live, _ = payload_records(payload, "smoke", provenance="measured",
                              created=2.0)
    assert [r["fingerprint"] for r in legacy] == \
        [r["fingerprint"] for r in live]
    assert [r["bench"] for r in legacy] == [r["bench"] for r in live]


def test_cli_validate_backfill_diff_gate(tmp_path, capsys):
    ledger = tmp_path / "ledger.jsonl"
    # backfill the committed history through the CLI
    rc = observatory_cli(["backfill", "--out", str(ledger),
                          str(REPO / "BENCH_pr3.json"),
                          str(REPO / "BENCH_pr4.json")])
    assert rc == 0
    assert observatory_cli(["validate", str(ledger)]) == 0
    assert observatory_cli(["gate", str(ledger)]) == 0
    # pr3 and pr4 measured the same fingerprints: diffable trajectory
    assert observatory_cli(["diff", str(ledger),
                            "--bench", "bench_exchange"]) == 0
    out = capsys.readouterr().out
    assert "steps_per_s" in out
    # legacy-inclusive gate sees the pr3 -> pr4 slowdown (different
    # machines — exactly why legacy is excluded by default)
    assert observatory_cli(["gate", str(ledger),
                            "--include-legacy"]) == 1
    # synthetic same-fingerprint regression: nonzero exit
    recs = read_ledger(ledger)
    bad = dict(recs[-1])
    bad["metrics"] = dict(bad["metrics"],
                          steps_per_s=bad["metrics"]["steps_per_s"] / 10)
    bad["provenance"] = "measured"
    good = dict(recs[-1])
    good["provenance"] = "measured"
    for r in (good, bad):
        r = dict(r)
        append_record(ledger, r)
    assert observatory_cli(["gate", str(ledger)]) == 1
    # bad input paths exit 2
    assert observatory_cli(["validate",
                            str(tmp_path / "missing.jsonl")]) == 2


def test_empty_ledger_env_var_disables(monkeypatch, tmp_path):
    """STENCIL_BENCH_LEDGER='' must disable the ledger exactly like
    --ledger '' — never fall through to the committed checkout file."""
    import sys
    sys.path.insert(0, str(REPO / "apps"))
    try:
        import _common
    finally:
        sys.path.pop(0)

    class Args:
        ledger = None
    monkeypatch.setenv("STENCIL_BENCH_LEDGER", "")
    assert _common.resolve_ledger_path(Args()) is None
    monkeypatch.setenv("STENCIL_BENCH_LEDGER", str(tmp_path / "l.jsonl"))
    assert _common.resolve_ledger_path(Args()) == \
        str(tmp_path / "l.jsonl")
    monkeypatch.delenv("STENCIL_BENCH_LEDGER")
    assert _common.resolve_ledger_path(Args()).endswith(
        os.path.join("bench", "ledger.jsonl"))
    Args.ledger = ""
    assert _common.resolve_ledger_path(Args()) is None


def test_cli_validate_rejects_malformed_ledger(tmp_path):
    path = tmp_path / "ledger.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({"schema": 99, "bench": "x"}) + "\n")
    assert observatory_cli(["validate", str(path)]) == 1


# ----------------------------------------------------------------------
# flight recorder
# ----------------------------------------------------------------------
def test_recorder_dump_schema_and_bounds(tmp_path):
    from stencil_tpu.telemetry import EventLog, Tracer
    reg = MetricsRegistry()
    reg.counter("c_total", "help").inc(3)
    tracer = Tracer(run_id="runx")
    fr = FlightRecorder(run_id="runx", events_capacity=4,
                        registry=reg, tracer=tracer)
    elog = EventLog(run_id="runx", sinks=(fr,))
    with tracer.span("segment.dispatch", k=4):
        pass
    for i in range(6):
        elog.emit("tick", n=i)
    fr.record_probe({"step": 5, "tripped": True, "reason": "nan"})
    path = fr.dump(tmp_path, "sentinel_trip", trip_step=5)
    payload = json.load(open(path))
    assert validate_dump(payload) == []
    # bounded ring: only the newest 4 events, truncation visible
    assert len(payload["events"]) == 4
    assert payload["dropped_events"] == 2
    assert payload["spans"][0]["name"] == "segment.dispatch"
    assert payload["metrics"]["metrics"]["c_total"]
    tl = render_timeline(payload)
    assert "TRIPPED" in tl and "segment.dispatch" in tl
    # corrupted dumps are caught
    bad = dict(payload, kind="blackbox")
    assert validate_dump(bad)


def test_chaos_trip_produces_valid_dump_with_trip_and_rollback(tmp_path):
    """ISSUE acceptance: the chaos NaN trip's dump is schema-valid and
    its timeline contains the trip step and the rollback."""
    fdir = tmp_path / "flight"
    j = make_jacobi()
    plan = FaultPlan(nans=[NaNInjection(step=6)])
    rep = j.run_resilient(
        STEPS, policy=fast_policy(flight_recorder_dir=str(fdir)),
        ckpt_dir=str(tmp_path / "ckpt"), faults=plan)
    assert rep.steps == STEPS and rep.rollbacks >= 1
    dumps = sorted(glob.glob(str(fdir / "flight_*sentinel_trip*.json")))
    assert dumps
    assert validate_dump(dumps[0]) == []
    payload = json.load(open(dumps[0]))
    kinds = [e["event"] for e in payload["events"]]
    assert "sentinel_tripped" in kinds and "restored" in kinds
    trip = next(e for e in payload["events"]
                if e["event"] == "sentinel_tripped")
    assert trip["step"] == 6
    tl = render_timeline(dumps[0])
    assert "sentinel_tripped" in tl and "restored" in tl
    # probe history rode along
    assert any(p.get("tripped") for p in payload["probes"])


def test_sigterm_dumps_before_the_preemption_checkpoint(tmp_path):
    """ISSUE acceptance: the SIGTERM path dumps BEFORE the preemption
    checkpoint — the black box must not contain the final save."""
    fdir = tmp_path / "flight"
    j = make_jacobi()
    plan = FaultPlan(preemptions=[Preemption(step=6)])
    rep = j.run_resilient(
        STEPS, policy=fast_policy(check_every=2,
                                  flight_recorder_dir=str(fdir)),
        ckpt_dir=str(tmp_path / "ckpt"), faults=plan)
    assert rep.preempted
    dumps = sorted(glob.glob(str(fdir / "flight_*preempt*.json")))
    assert dumps
    payload = json.load(open(dumps[0]))
    assert validate_dump(payload) == []
    # dumped before the tagged save: no preempted checkpoint event yet
    assert not any(e["event"] == "checkpoint" and e.get("preempted")
                   for e in payload["events"])
    # ...but the preempted checkpoint DID happen afterwards
    assert any(e["event"] == "checkpoint" and e.get("preempted")
               for e in rep.events)


def test_unhandled_error_dumps_black_box(tmp_path):
    from stencil_tpu.resilience import ResilienceError
    fdir = tmp_path / "flight"
    j = make_jacobi()
    plan = FaultPlan(nans=[NaNInjection(step=3)])
    # watchdog mode (no ckpt_dir): the trip raises — and dumps
    with pytest.raises(ResilienceError):
        j.run_resilient(
            STEPS, policy=fast_policy(flight_recorder_dir=str(fdir)),
            faults=plan)
    dumps = glob.glob(str(fdir / "flight_*unhandled_error*.json"))
    assert dumps and validate_dump(dumps[0]) == []


def test_recorder_disarmed_without_directory(tmp_path):
    j = make_jacobi()
    rep = j.run_resilient(4, policy=fast_policy(),
                          ckpt_dir=str(tmp_path / "ckpt"))
    assert rep.steps == 4  # no recorder, no dumps, loop unchanged


# ----------------------------------------------------------------------
# driver integration: attribution is on by default and harmless
# ----------------------------------------------------------------------
def test_resilient_run_attributes_by_default(tmp_path):
    from stencil_tpu.telemetry import get_registry
    j = make_jacobi()
    rep = j.run_resilient(4, policy=fast_policy(),
                          ckpt_dir=str(tmp_path / "ckpt"))
    assert rep.steps == 4
    reg = get_registry()
    ratio = reg.get(METRIC_MODEL_ERROR_RATIO)
    assert ratio is not None
    assert ratio.value(entry="jacobi", method="PpermuteSlab",
                       s="1") > 0
