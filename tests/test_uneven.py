"""Uneven (+-1 remainder) subdomain support.

The reference supports non-divisible grids via +-1-sized subdomains
(reference: partition.hpp:55-86; pinned by test_cpu_partition.cpp).
XLA SPMD shards are equal-capacity, so short shards place their halo at
a dynamic offset right after the actual interior; these tests pin the
data-plane behavior against the dense oracle and a direct halo check.
"""

import numpy as np
import pytest

from stencil_tpu.distributed import DistributedDomain
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.local_domain import raw_size
from stencil_tpu.parallel.methods import Method


def ripple(x, y, z):
    r = (3.0, 7.0, 1.0, 5.0)
    return (x + r[x % 4]) + 10.0 * (y + r[y % 4]) + 100.0 * (z + r[z % 4])


def _ripple_grid(size: Dim3) -> np.ndarray:
    gx = np.arange(size.x)
    gy = np.arange(size.y)
    gz = np.arange(size.z)
    rx = gx + np.asarray([3.0, 7.0, 1.0, 5.0])[gx % 4]
    ry = gy + np.asarray([3.0, 7.0, 1.0, 5.0])[gy % 4]
    rz = gz + np.asarray([3.0, 7.0, 1.0, 5.0])[gz % 4]
    return (rz[:, None, None] * 100.0 + ry[None, :, None] * 10.0
            + rx[None, None, :])


def test_uneven_exchange_halos_match_wrap():
    """9-point axis over 2 shards -> sizes 5 and 4; halos must hold the
    periodic-wrap neighbor values at the dynamic positions."""
    size = Dim3(9, 8, 8)
    dd = DistributedDomain(size.x, size.y, size.z)
    dd.set_mesh_shape((2, 2, 2))
    dd.set_radius(1)
    dd.add_data("q", np.float64)
    dd.realize()
    assert dd.rem == Dim3(1, 0, 0)
    vals = _ripple_grid(size)
    dd.set_interior("q", vals)
    dd.exchange()

    host = np.asarray(dd.curr["q"])
    pr = raw_size(dd.local_size, dd.radius)
    lo = dd.radius.pad_lo()
    dim = dd.placement.dim()
    bad = 0
    for bz in range(dim.z):
        for by in range(dim.y):
            for bx in range(dim.x):
                idx = Dim3(bx, by, bz)
                sz = dd.placement.subdomain_size(idx)
                org = dd.placement.subdomain_origin(idx)
                blk = host[bz * pr.z:(bz + 1) * pr.z,
                           by * pr.y:(by + 1) * pr.y,
                           bx * pr.x:(bx + 1) * pr.x]
                # x-axis lo halo [0, 1) and hi halo [lo.x+sz.x, +1)
                for lz in range(sz.z):
                    for ly in range(sz.y):
                        gy, gz = org.y + ly, org.z + lz
                        want_lo = ripple((org.x - 1) % size.x, gy, gz)
                        got_lo = blk[lo.z + lz, lo.y + ly, 0]
                        want_hi = ripple((org.x + sz.x) % size.x, gy, gz)
                        got_hi = blk[lo.z + lz, lo.y + ly, lo.x + sz.x]
                        bad += (got_lo != want_lo) + (got_hi != want_hi)
    assert bad == 0


@pytest.mark.parametrize("n", [17, 18])
def test_uneven_jacobi_matches_dense_oracle(n):
    """17^3 over a 2x2x2 mesh -> 9/8-point shards every axis; the
    distributed solver must track the dense single-array reference
    through steps (the strongest uneven-path test)."""
    from stencil_tpu.models.jacobi import Jacobi3D, dense_reference_step

    j = Jacobi3D(n, n, n, mesh_shape=(2, 2, 2), dtype=np.float64)
    if n % 2:
        assert j.dd.rem == Dim3(1, 1, 1)
    j.init()
    temp = j.temperature()
    hot = (n // 3, n // 2, n // 2)
    cold = (2 * n // 3, n // 2, n // 2)
    for _ in range(3):
        temp = dense_reference_step(temp, hot, cold, n // 10)
        j.step()
    np.testing.assert_allclose(j.temperature(), temp, rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize("size,mesh", [
    ((16, 17, 18), (1, 2, 4)),   # uneven y (9/8 shards)
    ((16, 16, 17), (1, 2, 4)),   # uneven z (5/4 shards)
    ((16, 15, 13), (1, 4, 2)),   # uneven y and z
])
def test_uneven_halo_kernel_matches_dense_oracle(size, mesh):
    """The fused halo-kernel fast path on uneven (+-1) shards: the
    kernel's interior-length overlay reads the neighbor slab at the
    shard's ACTUAL last row/column (reference: partition.hpp:55-86
    supports +-1 everywhere; VERDICT r3 missing #5)."""
    from stencil_tpu.models.jacobi import Jacobi3D, dense_reference_step

    x, y, z = size
    j = Jacobi3D(x, y, z, mesh_shape=mesh, dtype=np.float64,
                 kernel="halo")
    assert j.dd.rem != Dim3(0, 0, 0)
    assert j.kernel_path == "halo"
    j.init()
    temp = j.temperature()
    hot = (x // 3, y // 2, z // 2)
    cold = (2 * x // 3, y // 2, z // 2)
    for _ in range(3):
        temp = dense_reference_step(temp, hot, cold, x // 10)
    j.run(3)
    np.testing.assert_allclose(j.temperature(), temp, rtol=1e-12,
                               atol=1e-12)


def test_uneven_rejects_unsupported_methods():
    dd = DistributedDomain(9, 8, 8)
    dd.set_mesh_shape((2, 2, 2))
    dd.set_radius(1)
    dd.set_methods(Method.AllGather)
    dd.add_data("q", np.float32)
    with pytest.raises(NotImplementedError):
        dd.realize()


@pytest.mark.parametrize("n", [17])
def test_uneven_packed_matches_dense_oracle(n):
    """The packed multi-quantity exchange on uneven (+-1) shards: the
    hi-edge sends slice at the traced interior length and the hi halo
    lands after the actual interior (the partition.hpp:55-69 placement
    rule), so packed and slab methods agree with the dense oracle."""
    from stencil_tpu.models.jacobi import Jacobi3D, dense_reference_step

    j = Jacobi3D(n, n, n, mesh_shape=(2, 2, 2), dtype=np.float64,
                 methods=Method.PpermutePacked)
    assert j.dd.rem == Dim3(1, 1, 1)
    j.init()
    temp = j.temperature()
    hot = (n // 3, n // 2, n // 2)
    cold = (2 * n // 3, n // 2, n // 2)
    for _ in range(3):
        temp = dense_reference_step(temp, hot, cold, n // 10)
        j.step()
    np.testing.assert_allclose(j.temperature(), temp, rtol=1e-12,
                               atol=1e-12)


def test_auto_partition_falls_back_to_uneven():
    """A prime grid over 8 devices has no exact factorization; realize
    must fall back to the greedy +-1 split instead of failing."""
    dd = DistributedDomain(17, 17, 17)
    dd.set_radius(1)
    dd.add_data("q", np.float32)
    dd.realize()
    assert dd.placement.dim().flatten() == 8
    assert dd.rem != Dim3(0, 0, 0)
    dd.exchange()


@pytest.mark.slow
def test_uneven_astaroth_matches_single_device():
    """MHD on an uneven grid must match the 1-device run (regression:
    substeps once dropped dd.rem, silently corrupting wrap halos)."""
    import jax

    from stencil_tpu.models.astaroth import Astaroth, MhdParams

    prm = MhdParams()
    multi = Astaroth(9, 8, 8, params=prm, mesh_shape=(2, 2, 2),
                     dtype=np.float64, methods=Method.PpermuteSlab)
    single = Astaroth(9, 8, 8, params=prm, mesh_shape=(1, 1, 1),
                      dtype=np.float64, methods=Method.PpermuteSlab,
                      devices=jax.devices()[:1])
    multi.init()
    single.init()
    for _ in range(2):
        multi.step()
        single.step()
    for q in ("lnrho", "uux", "ss", "ax"):
        np.testing.assert_allclose(multi.field(q), single.field(q),
                                   rtol=1e-12, atol=1e-13)


def test_uneven_checkpoint_roundtrip(tmp_path):
    """Checkpoints of uneven domains store the true dd.size interior
    (regression: capacity-shaped extraction wrote unrestorable files)."""
    from stencil_tpu.models.jacobi import Jacobi3D
    from stencil_tpu.utils.checkpoint import restore_domain, save_domain

    a = Jacobi3D(9, 8, 8, mesh_shape=(2, 2, 2), dtype=np.float32)
    a.init()
    a.step()
    save_domain(a.dd, str(tmp_path / "ck"), step=1)
    a.step()
    want = a.temperature()

    b = Jacobi3D(9, 8, 8, mesh_shape=(2, 2, 2), dtype=np.float32)
    step, _ = restore_domain(b.dd, str(tmp_path / "ck"))
    assert step == 1
    b.step()
    np.testing.assert_allclose(b.temperature(), want, atol=1e-6)


def test_uneven_set_get_roundtrip():
    size = Dim3(10, 9, 11)
    dd = DistributedDomain(size.x, size.y, size.z)
    dd.set_mesh_shape((2, 2, 2))
    dd.set_radius(1)
    dd.add_data("q", np.float64)
    dd.realize()
    vals = _ripple_grid(size)
    dd.set_interior("q", vals)
    np.testing.assert_array_equal(dd.interior_to_host("q"), vals)


@pytest.mark.slow
def test_uneven_mhd_radius3_matches_oracle():
    """Radius-3, 8-field MHD on +-1 shards (18 over 4 -> 5,5,4,4):
    the uneven exchange at a 3-deep halo with multiple quantities —
    a combination the radius-1 Jacobi uneven tests never reach
    (reference: the partitioner serves the astaroth app the same +-1
    subdomains it serves jacobi3d, partition.hpp:55-86)."""
    import jax

    from stencil_tpu.models.astaroth import FIELDS, Astaroth

    a = Astaroth(18, 18, 18, mesh_shape=(1, 1, 1), dtype=np.float64,
                 devices=jax.devices()[:1], kernel="xla")
    b = Astaroth(18, 18, 18, mesh_shape=(1, 4, 1), dtype=np.float64,
                 devices=jax.devices()[:4], kernel="xla")
    for m in (a, b):
        m.init()
        m.step()
    for q in FIELDS:
        np.testing.assert_allclose(b.field(q), a.field(q),
                                   rtol=0, atol=1e-12, err_msg=q)
