"""Pallas kernel parity tests (interpreter-backed off-TPU).

Mirrors the reference's kernel unit tests (test/test_cuda_pack.cu,
test_derivative.cu): each Pallas kernel is checked against the XLA
slicing implementation it accelerates, and the pallas-kernel Jacobi
model is checked against the dense single-device oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stencil_tpu._compat import remote_dma_runnable
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.local_domain import raw_size, zyx_shape
from stencil_tpu.ops.fd6 import FieldData
from stencil_tpu.ops.pallas_stencil import jacobi7_pallas, laplace6_pallas
from stencil_tpu.ops.stencil_kernels import jacobi7


@pytest.mark.parametrize("interior", [Dim3(8, 8, 8), Dim3(12, 10, 6)])
def test_jacobi7_pallas_matches_xla(interior):
    rng = np.random.default_rng(7)
    r = Radius.constant(1)
    p = jnp.asarray(rng.standard_normal(zyx_shape(raw_size(interior, r))),
                    dtype=jnp.float32)
    want = jacobi7(p, r, interior)
    got = jacobi7_pallas(p, r, interior, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_jacobi7_pallas_asymmetric_radius():
    # pad offsets differ per side; kernel must honor pad_lo
    rng = np.random.default_rng(8)
    r = Radius.constant(1)
    r.set_dir((1, 0, 0), 2)   # x hi face radius 2
    r.set_dir((0, 0, -1), 3)  # z lo face radius 3
    interior = Dim3(6, 7, 8)
    p = jnp.asarray(rng.standard_normal(zyx_shape(raw_size(interior, r))),
                    dtype=jnp.float32)
    want = jacobi7(p, r, interior)
    got = jacobi7_pallas(p, r, interior, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_laplace6_pallas_matches_fd6():
    rng = np.random.default_rng(9)
    r = Radius.constant(3)
    interior = Dim3(10, 8, 6)
    inv_ds = (1.0, 0.5, 2.0)
    p = jnp.asarray(rng.standard_normal(zyx_shape(raw_size(interior, r))),
                    dtype=jnp.float64)
    fd = FieldData(p, inv_ds, r.pad_lo(), interior)
    want = fd.laplace
    got = laplace6_pallas(p, r, interior, inv_ds=inv_ds, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize("bz,by", [(4, 8), (8, 128), (16, 16)])
def test_jacobi7_wrap_pallas_matches_oracle(bz, by):
    """The fused periodic single-chip kernel (wrap inside the kernel,
    no halo storage) against the dense reference step."""
    from stencil_tpu.models.jacobi import dense_reference_step
    from stencil_tpu.ops.pallas_stencil import jacobi7_wrap_pallas

    n = 16
    rng = np.random.default_rng(3)
    t = rng.random((n, n, n)).astype(np.float32)
    hot = (n // 3, n // 2, n // 2)
    cold = (2 * n // 3, n // 2, n // 2)
    want = dense_reference_step(t, hot, cold, n // 10)
    got = np.asarray(jacobi7_wrap_pallas(jnp.asarray(t), hot, cold, n // 10,
                                         block_z=bz, block_y=by,
                                         interpret=True))
    np.testing.assert_allclose(got, want, atol=1e-6)


@pytest.mark.parametrize("bz,by", [(4, 8), (16, 128), (8, 16)])
def test_jacobi7_wrap2_pallas_matches_two_steps(bz, by):
    """The temporally-blocked pair kernel (two fused iterations per
    HBM pass) against two dense reference steps — including sphere
    sources re-imposed between the fused steps and periodic-wrap
    coordinates for the step-1 edge ring."""
    from stencil_tpu.models.jacobi import dense_reference_step
    from stencil_tpu.ops.pallas_stencil import jacobi7_wrap2_pallas

    n = 16
    rng = np.random.default_rng(5)
    t = rng.random((n, n, n)).astype(np.float32)
    hot = (n // 3, n // 2, n // 2)
    cold = (2 * n // 3, n // 2, n // 2)
    want = dense_reference_step(
        dense_reference_step(t, hot, cold, n // 10), hot, cold, n // 10)
    got = np.asarray(jacobi7_wrap2_pallas(jnp.asarray(t), hot, cold,
                                          n // 10, block_z=bz, block_y=by,
                                          interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-6)


def test_jacobi_model_wrap_pair_and_tail_matches_oracle():
    """run(3) through the wrap path = one fused pair + one single-step
    tail; must match three sequential dense steps."""
    import jax

    from stencil_tpu.models.jacobi import Jacobi3D, dense_reference_step

    n = 16
    j = Jacobi3D(n, n, n, mesh_shape=(1, 1, 1), dtype=np.float32,
                 kernel="wrap", devices=jax.devices()[:1])
    j.init()
    temp = j.temperature()
    hot = (n // 3, n // 2, n // 2)
    cold = (2 * n // 3, n // 2, n // 2)
    for _ in range(3):
        temp = dense_reference_step(temp, hot, cold, n // 10)
    j.run(3)
    np.testing.assert_allclose(j.temperature(), temp, atol=2e-6)


def test_jacobi_model_wrap_kernel_matches_oracle():
    import jax

    from stencil_tpu.models.jacobi import Jacobi3D, dense_reference_step

    n = 16
    j = Jacobi3D(n, n, n, mesh_shape=(1, 1, 1), dtype=np.float32,
                 kernel="wrap", devices=jax.devices()[:1])
    j.init()
    temp = j.temperature()
    hot = (n // 3, n // 2, n // 2)
    cold = (2 * n // 3, n // 2, n // 2)
    for _ in range(2):
        temp = dense_reference_step(temp, hot, cold, n // 10)
        j.step()
    np.testing.assert_allclose(j.temperature(), temp, atol=1e-6)


@pytest.mark.skipif(
    not remote_dma_runnable(),
    reason="Pallas remote DMA needs a TPU backend or the distributed "
           "(mosaic) TPU interpreter")
def test_jacobi_model_full_pallas_path_matches_oracle():
    """Pallas compute kernel + Pallas RDMA exchange — the all-manual
    path (the reference's Colo*Kernel method analog)."""
    from stencil_tpu.models.jacobi import Jacobi3D, dense_reference_step
    from stencil_tpu.parallel.methods import Method

    n = 16
    j = Jacobi3D(n, n, n, mesh_shape=(2, 2, 2), dtype=np.float32,
                 kernel="pallas", methods=Method.PallasDMA)
    j.init()
    temp = j.temperature()
    hot = (n // 3, n // 2, n // 2)
    cold = (2 * n // 3, n // 2, n // 2)
    for _ in range(3):
        temp = dense_reference_step(temp, hot, cold, n // 10)
        j.step()
    np.testing.assert_allclose(j.temperature(), temp, atol=1e-5)


def test_jacobi_model_pallas_kernel_matches_oracle():
    from stencil_tpu.models.jacobi import Jacobi3D, dense_reference_step

    n = 16
    j = Jacobi3D(n, n, n, mesh_shape=(2, 2, 2), dtype=np.float32,
                 kernel="pallas")
    j.init()
    temp = j.temperature()
    hot = (n // 3, n // 2, n // 2)
    cold = (2 * n // 3, n // 2, n // 2)
    for _ in range(3):
        temp = dense_reference_step(temp, hot, cold, n // 10)
        j.step()
    np.testing.assert_allclose(j.temperature(), temp, atol=1e-5)


@pytest.mark.parametrize("kernel,mesh_shape", [
    ("wrap", (1, 1, 1)),     # pair kernel, 16-row bf16 edge slabs
    ("halo", (1, 2, 2)),     # slab-layout pair kernel, bf16 tiles
])
def test_jacobi_model_bf16(kernel, mesh_shape):
    """bfloat16 fields through the fused fast paths (the TPU-native
    analog of the reference's float/double templating,
    bin/jacobi3d.cu:40-85): the dtype's 16-row sublane tile changes
    every edge-slab block shape, so run the full model vs a float64
    dense oracle at bf16 tolerance."""
    import jax.numpy as jnp

    from stencil_tpu.models.jacobi import Jacobi3D, dense_reference_step

    n = 32
    ndev = mesh_shape[0] * mesh_shape[1] * mesh_shape[2]
    j = Jacobi3D(n, n, n, mesh_shape=mesh_shape, dtype=jnp.bfloat16,
                 kernel=kernel, devices=jax.devices()[:ndev])
    assert j.kernel_path == kernel
    j.init()
    j.run(2)
    hot = (n // 3, n // 2, n // 2)
    cold = (2 * n // 3, n // 2, n // 2)
    want = np.full((n, n, n), 0.5, dtype=np.float64)
    for _ in range(2):
        want = dense_reference_step(want, hot, cold, n // 10)
    got = np.asarray(j.temperature(), dtype=np.float64)
    # two bf16 steps: ~8 bits of mantissa -> absolute error ~1e-2
    np.testing.assert_allclose(got, want, atol=2e-2)


@pytest.mark.parametrize("steps,bz,by", [(1, 4, 8), (3, 4, 8),
                                         (3, 16, 128), (4, 2, 8),
                                         (4, 8, 8),   # slabbed N-row segs
                                         (5, 4, 16)])
def test_jacobi7_wrapn_pallas_matches_n_steps(steps, bz, by):
    """The generalized temporal-blocking kernel at depth N against N
    dense reference steps — the ring recompute, per-step sources, and
    wrapped single-row z fetches must hold at every depth (wrap2 is
    the N=2 special case, tested above)."""
    from stencil_tpu.models.jacobi import dense_reference_step
    from stencil_tpu.ops.pallas_stencil import jacobi7_wrapn_pallas

    n = 16
    rng = np.random.default_rng(6)
    t = rng.random((n, n, n)).astype(np.float32)
    hot = (n // 3, n // 2, n // 2)
    cold = (2 * n // 3, n // 2, n // 2)
    want = t
    for _ in range(steps):
        want = dense_reference_step(want, hot, cold, n // 10)
    got = np.asarray(jacobi7_wrapn_pallas(jnp.asarray(t), hot, cold,
                                          n // 10, steps=steps,
                                          block_z=bz, block_y=by,
                                          interpret=True))
    np.testing.assert_allclose(got, want, atol=3e-6)


def test_jacobi_model_wrap_steps_env(monkeypatch):
    """STENCIL_WRAP_STEPS=3 drives the wrap path in triples (+ tail)."""
    from stencil_tpu.models.jacobi import Jacobi3D, dense_reference_step

    monkeypatch.setenv("STENCIL_WRAP_STEPS", "3")
    n = 16
    j = Jacobi3D(n, n, n, mesh_shape=(1, 1, 1), dtype=np.float32,
                 kernel="wrap", devices=jax.devices()[:1])
    j.init()
    j.run(4)   # one triple + one tail step
    hot = (n // 3, n // 2, n // 2)
    cold = (2 * n // 3, n // 2, n // 2)
    want = np.full((n, n, n), 0.5, dtype=np.float32)
    for _ in range(4):
        want = dense_reference_step(want, hot, cold, n // 10)
    np.testing.assert_allclose(j.temperature(), want, rtol=1e-5,
                               atol=1e-6)
