"""Astaroth MHD integrator tests.

Strategy (SURVEY.md section 4): distributed-vs-single-device numerical
parity (the same XLA program on a 1-device mesh is the dense oracle),
finiteness/stability over iterations, conf-file loading, and
initial-condition pinning against the reference's formulas.
"""

import numpy as np
import pytest

import jax

from stencil_tpu._compat import remote_dma_runnable
from stencil_tpu.geometry import Dim3
from stencil_tpu.models.astaroth import (FIELDS, Astaroth, MhdParams,
                                         _hash_field, _radial_explosion)
from stencil_tpu.parallel.methods import Method


def make_pair(size=(16, 16, 16), iters=2, dtype=np.float64):
    """Run the same problem on a 1-device mesh and a 2x2x2 mesh."""
    single = Astaroth(*size, mesh_shape=(1, 1, 1), dtype=dtype,
                      devices=jax.devices()[:1])
    multi = Astaroth(*size, mesh_shape=(2, 2, 2), dtype=dtype)
    for m in (single, multi):
        m.init()
        for _ in range(iters):
            m.step()
    return single, multi


class TestDistributedParity:
    @pytest.mark.slow
    def test_multi_matches_single_device(self):
        single, multi = make_pair()
        for q in FIELDS:
            a = single.field(q)
            b = multi.field(q)
            np.testing.assert_allclose(a, b, rtol=0, atol=1e-12, err_msg=q)

    @pytest.mark.slow
    def test_slab_method_matches(self):
        size = (16, 16, 16)
        a = Astaroth(*size, mesh_shape=(2, 2, 2), dtype=np.float64,
                     methods=Method.PpermutePacked)
        b = Astaroth(*size, mesh_shape=(2, 2, 2), dtype=np.float64,
                     methods=Method.PpermuteSlab)
        for m in (a, b):
            m.init()
            m.step()
        for q in FIELDS:
            np.testing.assert_array_equal(a.field(q), b.field(q), err_msg=q)


class TestStability:
    @pytest.mark.slow
    @pytest.mark.parametrize("thinz,pair", [
        ("1", "0"), ("0", "0"),
        # fused substep-0+1 kernel (STENCIL_MHD_PAIR=1 opt-in), under
        # both window plans (tiled-z at rr=6 slices the ESUB tile
        # differently than the rr=3 single-substep path)
        ("1", "1"), ("0", "1")])
    def test_wrap_megakernel_matches_xla(self, thinz, pair, monkeypatch):
        """The fused Pallas substep megakernel (ops/pallas_mhd.py,
        single-chip fast path) against the slicing formulation — under
        BOTH window plans (exact-radius thin-z default and the
        STENCIL_MHD_THINZ=0 tiled-z A/B control) and with the fused
        substep-0+1 pair kernel opted in."""
        monkeypatch.setenv("STENCIL_MHD_THINZ", thinz)
        monkeypatch.setenv("STENCIL_MHD_PAIR", pair)
        size = (16, 16, 16)
        a = Astaroth(*size, mesh_shape=(1, 1, 1), dtype=np.float64,
                     devices=jax.devices()[:1], kernel="xla")
        b = Astaroth(*size, mesh_shape=(1, 1, 1), dtype=np.float64,
                     devices=jax.devices()[:1], kernel="wrap")
        for m in (a, b):
            m.init()
            m.step()
            m.step()
        for q in FIELDS:
            np.testing.assert_allclose(b.field(q), a.field(q),
                                       rtol=1e-11, atol=1e-13, err_msg=q)

    def test_fields_stay_finite(self):
        m = Astaroth(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float64)
        m.init()
        m.run(10)
        for q in FIELDS:
            v = m.field(q)
            assert np.all(np.isfinite(v)), q

    def test_fields_actually_evolve(self):
        m = Astaroth(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float64)
        m.init()
        before = {q: m.field(q).copy() for q in ("lnrho", "uux", "ss")}
        # dt is 1e-8 (reference loads AC_dt=1e-8) so changes are small
        # but must be nonzero
        m.step()
        changed = sum(not np.array_equal(before[q], m.field(q))
                      for q in before)
        assert changed == len(before)


class TestDeadWElision:
    """alpha_0 == 0 makes the incoming w dead at substep 0 and the
    outgoing w dead at substep 2 (the next iteration restarts the
    recurrence); the kernels elide those HBM sweeps on request
    (w=None / write_w=False). Dropping the 0*w term changes how the
    compiler fuses the update (FMA contraction), so fields match to
    ~1 ulp rather than bit-for-bit; write_w elision IS bit-exact."""

    @staticmethod
    def _mk_state(seed=7, size=(16, 16, 16)):
        rng = np.random.default_rng(seed)
        f = {q: np.asarray(rng.normal(0.0, 0.1, size), np.float64)
             for q in FIELDS}
        wz = {q: np.zeros(size, np.float64) for q in FIELDS}
        return f, wz

    @pytest.mark.slow
    def test_wrap_kernel_elision_bit_identical(self):
        from stencil_tpu.ops.pallas_mhd import mhd_substep_wrap_pallas

        prm = MhdParams()
        f, wz = self._mk_state()
        fa, wa = mhd_substep_wrap_pallas(f, wz, 0, prm, prm.dt)
        fb, wb = mhd_substep_wrap_pallas(f, None, 0, prm, prm.dt)
        for q in FIELDS:
            np.testing.assert_allclose(np.asarray(fa[q]),
                                       np.asarray(fb[q]),
                                       rtol=1e-14, atol=1e-18,
                                       err_msg=q)
            np.testing.assert_array_equal(np.asarray(wa[q]),
                                          np.asarray(wb[q]), err_msg=q)
        fc, wc = mhd_substep_wrap_pallas(fb, wb, 2, prm, prm.dt)
        fd, wd = mhd_substep_wrap_pallas(fb, wb, 2, prm, prm.dt,
                                         write_w=False)
        assert wd is None
        assert wc is not None
        for q in FIELDS:
            np.testing.assert_array_equal(np.asarray(fc[q]),
                                          np.asarray(fd[q]), err_msg=q)

    def test_wrap_kernel_w_none_rejected_midstep(self):
        from stencil_tpu.ops.pallas_mhd import mhd_substep_wrap_pallas

        prm = MhdParams()
        f, _ = self._mk_state()
        with pytest.raises(AssertionError):
            mhd_substep_wrap_pallas(f, None, 1, prm, prm.dt)


class TestParams:
    def test_defaults_match_reference_conf(self):
        p = MhdParams()
        assert p.nu_visc == 5e-3
        assert p.mu0 == 1.4
        assert p.gamma == 0.5
        assert p.cs2_sound == 1.0

    def test_from_conf_roundtrip(self, tmp_path):
        conf = tmp_path / "a.conf"
        conf.write_text("""
// comment
AC_nu_visc = 1e-2
AC_mu0 = 2.0   // inline comment
/* block
comment */
AC_gamma = 0.6
AC_dsx = 0.1
""")
        p = MhdParams.from_conf(str(conf))
        assert p.nu_visc == 1e-2
        assert p.mu0 == 2.0
        assert p.gamma == 0.6
        assert p.dsx == 0.1
        assert p.dsy == 0.04908738521  # untouched default


class TestInitialConditions:
    def test_hash_field_range_and_determinism(self):
        a = _hash_field((8, 8, 8))
        b = _hash_field((8, 8, 8))
        np.testing.assert_array_equal(a, b)
        assert a.min() >= -1.0 and a.max() <= 1.0
        assert a.std() > 0.1  # actually random-ish

    def test_radial_explosion_shell(self):
        prm = MhdParams()
        ux, uy, uz = _radial_explosion(Dim3(64, 64, 64), prm)
        speed = np.sqrt(ux ** 2 + uy ** 2 + uz ** 2)
        # gaussian shell: peak speed ~ampl at radius 0.8 from origin
        assert speed.max() == pytest.approx(1.0, abs=0.05)
        # velocity points radially away from origin (0.01, 32dy, 50dz)
        oz, oy, ox = 50 * prm.dsz, 32 * prm.dsy, 0.01
        z, y, x = 40, 40, 20
        r = np.array([x * prm.dsx - ox, y * prm.dsy - oy, z * prm.dsz - oz])
        u = np.array([ux[z, y, x], uy[z, y, x], uz[z, y, x]])
        if np.linalg.norm(u) > 1e-12:
            cos = np.dot(r, u) / np.linalg.norm(r) / np.linalg.norm(u)
            assert cos == pytest.approx(1.0, abs=1e-9)


class TestBfloat16:
    """bfloat16 MHD: fields stored half-width, RHS computed in float32
    (ops/pallas_mhd.compute_dtype) — the TPU bf16-in-memory /
    f32-accumulate idiom. Parity is against the float32 XLA oracle at
    bf16 storage tolerance (~2^-8 per-step rounding), since the Pallas
    path computes on exactly the f32 promotions of the stored values.
    Reference analog: the float/double templating the reference builds
    with (e.g. astaroth typed on AcReal); bf16 is the TPU-native
    half-traffic point on that axis."""

    @staticmethod
    def _f32_oracle(size, iters=2):
        a = Astaroth(*size, mesh_shape=(1, 1, 1), dtype=np.float32,
                     devices=jax.devices()[:1], kernel="xla")
        a.init()
        for _ in range(iters):
            a.step()
        return {q: np.asarray(a.field(q), np.float32) for q in FIELDS}

    @staticmethod
    def _assert_close(got_model, ref, label, tol=3e-2):
        import jax.numpy as jnp
        for q in FIELDS:
            raw = got_model.field(q)
            assert raw.dtype == jnp.bfloat16, (label, q, raw.dtype)
            got = np.asarray(raw, np.float32)
            scale = max(np.abs(ref[q]).max(), 1e-30)
            err = np.abs(got - ref[q]).max() / scale
            assert err < tol, (label, q, err)

    @pytest.mark.slow
    @pytest.mark.parametrize("thinz,pair", [
        ("1", "0"), ("0", "0"), ("1", "1")])
    def test_wrap_bf16_matches_f32_oracle(self, thinz, pair, monkeypatch):
        import jax.numpy as jnp
        monkeypatch.setenv("STENCIL_MHD_THINZ", thinz)
        monkeypatch.setenv("STENCIL_MHD_PAIR", pair)
        size = (32, 32, 32)
        ref = self._f32_oracle(size)
        b = Astaroth(*size, mesh_shape=(1, 1, 1), dtype=jnp.bfloat16,
                     devices=jax.devices()[:1], kernel="wrap")
        assert b.kernel_path == "wrap"
        b.init()
        b.step()
        b.step()
        self._assert_close(b, ref, f"wrap thinz={thinz} pair={pair}")

    @pytest.mark.slow
    @pytest.mark.parametrize("pair", ["0", "1"])
    def test_halo_bf16_matches_f32_oracle(self, pair, monkeypatch):
        """Multi-device slab layout: 16-row (bf16-tile) slab exchange +
        the halo megakernel, on an x-unsharded (1,2,2) mesh."""
        import jax.numpy as jnp
        monkeypatch.setenv("STENCIL_MHD_PAIR", pair)
        size = (32, 32, 32)
        ref = self._f32_oracle(size)
        c = Astaroth(*size, mesh_shape=(1, 2, 2), dtype=jnp.bfloat16,
                     devices=jax.devices()[:4], kernel="halo")
        assert c.kernel_path == "halo"
        c.init()
        c.step()
        c.step()
        self._assert_close(c, ref, f"halo pair={pair}")

    def test_xla_bf16_matches_f32_oracle(self):
        """The XLA fallback path must apply the same storage/compute
        split (bf16 in HBM, f32 RHS evaluation) as the Pallas paths —
        a bf16-evaluated 6th-order RHS would drift far beyond storage
        tolerance."""
        import jax.numpy as jnp
        size = (32, 32, 32)
        ref = self._f32_oracle(size)
        b = Astaroth(*size, mesh_shape=(2, 2, 2), dtype=jnp.bfloat16,
                     kernel="xla")
        b.init()
        b.step()
        b.step()
        self._assert_close(b, ref, "xla bf16")

    def test_bf16_overlap_selects_rdma_path(self):
        """bf16 + overlap takes the in-kernel RDMA path like f32 (the
        16-row slab tiling now runs through ops/pallas_mhd_overlap)."""
        import jax.numpy as jnp
        m = Astaroth(32, 32, 32, mesh_shape=(1, 2, 2),
                     dtype=jnp.bfloat16, devices=jax.devices()[:4],
                     kernel="halo", overlap=True)
        assert m.kernel_path == "halo-overlap"

    @pytest.mark.slow
    @pytest.mark.skipif(
        not remote_dma_runnable(),
        reason="Pallas remote DMA needs a TPU backend or the "
               "distributed (mosaic) TPU interpreter")
    @pytest.mark.parametrize("pair", ["0", "1"])
    def test_overlap_bf16_matches_f32_oracle(self, pair, monkeypatch):
        """The overlapped (in-kernel RDMA) path in bf16, alone and
        composed with the substep-0+1 pair."""
        import jax.numpy as jnp
        monkeypatch.setenv("STENCIL_MHD_PAIR", pair)
        size = (32, 32, 32)
        ref = self._f32_oracle(size)
        c = Astaroth(*size, mesh_shape=(1, 2, 2), dtype=jnp.bfloat16,
                     devices=jax.devices()[:4], kernel="halo",
                     overlap=True)
        assert c.kernel_path == "halo-overlap"
        c.init()
        c.step()
        c.step()
        self._assert_close(c, ref, f"halo-overlap pair={pair}")
