"""Tests for the exchange autotuner (stencil_tpu/tuning).

Everything runs off-TPU: the injectable FakeTimer evaluates the same
analytic alpha-beta model the calibrated cost model uses, so the full
measure -> fit -> plan -> cache pipeline is deterministic on the
8-device virtual CPU mesh — search, pruning, fit recovery, cache
round-trip/invalidation, and plan application through realize().
"""

import json

import numpy as np
import pytest

from stencil_tpu.analysis.costmodel import (LinkCoefficients,
                                            configured_step_seconds)
from stencil_tpu.distributed import DistributedDomain
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel.methods import Method, pick_method
from stencil_tpu.tuning import (Candidate, FakeTimer, Plan,
                                TuneGeometry, calibrate_link,
                                candidate_space, fingerprint,
                                fingerprint_inputs, fit_alpha_beta,
                                load_plan, run_autotune, store_plan)
from stencil_tpu.tuning.cache import load_cache
from stencil_tpu.tuning.plan import SCHEMA_VERSION, candidate_feasible


def _domain(radius=1, dtype=np.float32, mesh=(2, 2, 2), nfields=2,
            grid=(16, 16, 16)):
    dd = DistributedDomain(*grid)
    dd.set_mesh_shape(mesh)
    dd.set_radius(radius)
    for i in range(nfields):
        dd.add_data(f"q{i}", dtype)
    return dd


def _geom(radius=1, shard=(8, 8, 8), counts=(2, 2, 2),
          elem_sizes=(4, 4), **kw) -> TuneGeometry:
    r = Radius.constant(radius) if isinstance(radius, int) else radius
    return TuneGeometry(shard_interior_zyx=shard,
                        min_interior_zyx=kw.pop("min_interior", shard),
                        radius=r, counts=Dim3(*counts),
                        elem_sizes=tuple(elem_sizes), **kw)


# ---------------------------------------------------------------------------
# fit


def test_fit_recovers_alpha_beta_exactly():
    truth = LinkCoefficients(alpha_s=37e-6, beta_bytes_per_s=2.5e10)
    fit = fit_alpha_beta([(b, truth.seconds(1, b))
                          for b in (1 << 12, 1 << 17, 1 << 21)])
    assert fit.alpha_s == pytest.approx(truth.alpha_s, rel=1e-9)
    assert fit.beta_bytes_per_s == pytest.approx(truth.beta_bytes_per_s,
                                                 rel=1e-9)


def test_calibrate_link_from_fake_timer():
    timer = FakeTimer(LinkCoefficients(50e-6, 1e10))
    fit = calibrate_link(timer.pingpong)
    assert fit.alpha_s == pytest.approx(50e-6, rel=1e-9)
    assert fit.beta_bytes_per_s == pytest.approx(1e10, rel=1e-9)


def test_fit_degenerate_single_sample():
    fit = fit_alpha_beta([(4096, 1e-4)])
    assert fit.alpha_s == pytest.approx(1e-4)
    assert fit.beta_bytes_per_s > 1e20  # bandwidth term inert


# ---------------------------------------------------------------------------
# candidate space / feasibility


def test_candidate_space_depths_and_methods():
    cands = candidate_space(_geom(), runnable=lambda m: True)
    keys = {c.key() for c in cands}
    # ppermute methods sweep every depth that fits an 8^3 r=1 shard
    for m in ("PpermuteSlab", "PpermutePacked"):
        for s in (1, 2, 4, 8):
            assert f"{m}[s={s}]" in keys
    # non-ppermute strategies are depth-1 only
    assert "AllGather[s=1]" in keys
    assert "PallasDMA[s=1]" in keys
    assert not any(k.startswith("AllGather[s=2")
                   or k.startswith("PallasDMA[s=2") for k in keys)
    # the overlap dimension (opt-in): ppermute methods only
    ovl = candidate_space(_geom(), overlap_options=(False, True),
                          runnable=lambda m: True)
    assert Candidate("PpermuteSlab", 4, True) in ovl
    assert not any(c.overlap for c in ovl
                   if c.method in ("AllGather", "PallasDMA"))


def test_candidate_space_respects_geometry_and_capability():
    # radius 2 on an 8^3 shard: depth 8 needs 16 rows -> infeasible
    cands = candidate_space(_geom(radius=2), runnable=lambda m: True)
    depths = {c.exchange_every for c in cands
              if c.method == "PpermuteSlab"}
    assert depths == {1, 2, 4}
    # capability probe filters whole strategies
    cands = candidate_space(
        _geom(), runnable=lambda m: m != Method.PallasDMA)
    assert not any(c.method == "PallasDMA" for c in cands)


def test_candidate_feasibility_uneven_and_nonperiodic():
    geom = _geom(uneven=True)
    assert not candidate_feasible(Candidate("AllGather", 1), geom)
    assert not candidate_feasible(Candidate("PallasDMA", 1), geom)
    assert candidate_feasible(Candidate("PpermutePacked", 2), geom)
    geom = _geom(nonperiodic=True)
    assert not candidate_feasible(Candidate("AllGather", 1), geom)
    assert candidate_feasible(Candidate("PpermuteSlab", 1), geom)
    # the SMALLEST shard bounds the depth (realize()'s rule)
    geom = _geom(min_interior=(7, 7, 7))
    assert not candidate_feasible(Candidate("PpermuteSlab", 8), geom)
    assert candidate_feasible(Candidate("PpermuteSlab", 4), geom)


def test_packed_model_groups_by_dtype_not_size():
    """The packed engine concatenates per DTYPE (f32 and i32 pack
    separately despite equal itemsize — parallel/exchange.py groups by
    .dtype); the cost model must count launches the same way."""
    from stencil_tpu.analysis.costmodel import exchange_round_model

    geom = _geom()  # two 4-byte quantities
    msgs_one_dtype, _ = exchange_round_model(
        "PpermutePacked", geom.shard_interior_zyx, geom.radius,
        geom.counts, geom.elem_sizes, 1, dtype_groups=1)
    msgs_two_dtypes, _ = exchange_round_model(
        "PpermutePacked", geom.shard_interior_zyx, geom.radius,
        geom.counts, geom.elem_sizes, 1, dtype_groups=2)
    assert msgs_two_dtypes == 2 * msgs_one_dtype
    # the domain adapter carries real dtype names: f32 + i32 (same
    # itemsize) must rank packed at TWO launch groups, not one
    from stencil_tpu.tuning import geometry_from_domain

    dd = DistributedDomain(16, 16, 16)
    dd.set_mesh_shape((2, 2, 2))
    dd.set_radius(1)
    dd.add_data("a", np.float32)
    dd.add_data("b", np.int32)
    g = geometry_from_domain(dd, Dim3(2, 2, 2))
    assert g.dtype_groups == 2
    assert g.elem_sizes == (4, 4)


# ---------------------------------------------------------------------------
# plan cache


def test_plan_cache_round_trip(tmp_path):
    cache = tmp_path / "plans.json"
    plan = Plan(config=Candidate("PpermutePacked", 4),
                fingerprint="abc123", coefficients={
                    "ici": {"alpha_s": 1e-5, "beta_bytes_per_s": 1e10}},
                costs={"PpermutePacked[s=4]": {"predicted_s": 1e-4,
                                               "measured_s": 9e-5}},
                provenance="tuned", measurements=7, created=123.0,
                library_version="0.1.0")
    store_plan(plan, cache)
    back = load_plan("abc123", cache)
    assert back is not None
    assert back.config == plan.config
    assert back.coefficients == plan.coefficients
    assert back.costs == plan.costs
    assert back.measurements == 7
    assert back.library_version == "0.1.0"
    # unknown fingerprint is a miss, not an error
    assert load_plan("zzz", cache) is None


def test_plan_cache_rejects_corrupt_file(tmp_path):
    cache = tmp_path / "plans.json"
    cache.write_text("{ not json !!!")
    assert load_plan("abc", cache) is None
    # a rewrite recovers the file
    plan = Plan(config=Candidate("PpermuteSlab", 1), fingerprint="f1",
                coefficients={}, costs={})
    store_plan(plan, cache)
    assert load_plan("f1", cache) is not None


def test_plan_cache_rejects_old_schema(tmp_path):
    cache = tmp_path / "plans.json"
    plan = Plan(config=Candidate("PpermuteSlab", 1), fingerprint="f1",
                coefficients={}, costs={})
    store_plan(plan, cache)
    data = json.loads(cache.read_text())
    assert data["schema"] == SCHEMA_VERSION
    data["schema"] = SCHEMA_VERSION + 999
    cache.write_text(json.dumps(data))
    assert load_cache(cache) == {}
    assert load_plan("f1", cache) is None


def test_plan_cache_rejects_unparsable_record(tmp_path):
    cache = tmp_path / "plans.json"
    cache.write_text(json.dumps(
        {"schema": SCHEMA_VERSION, "plans": {"f1": {"bogus": 1}}}))
    assert load_plan("f1", cache) is None


def test_cache_env_override(tmp_path, monkeypatch):
    target = tmp_path / "fleet" / "plans.json"
    monkeypatch.setenv("STENCIL_TUNE_CACHE", str(target))
    plan = Plan(config=Candidate("PpermuteSlab", 1), fingerprint="f1",
                coefficients={}, costs={})
    store_plan(plan)  # no explicit path: env decides
    assert target.exists()
    assert load_plan("f1") is not None


# ---------------------------------------------------------------------------
# fingerprint semantics


def test_fingerprint_invalidation_radius_dtype_mesh():
    base = dict(platform="cpu", device_count=8, mesh_shape=[2, 2, 2],
                grid=[16, 16, 16], radius=Radius.constant(1),
                quantities={"q0": "float32"}, boundary="PERIODIC")
    fp = fingerprint(fingerprint_inputs(**base))
    assert fp == fingerprint(fingerprint_inputs(**base))  # stable
    changed = dict(base, radius=Radius.constant(2))
    assert fingerprint(fingerprint_inputs(**changed)) != fp
    changed = dict(base, quantities={"q0": "float64"})
    assert fingerprint(fingerprint_inputs(**changed)) != fp
    changed = dict(base, mesh_shape=[4, 2, 1])
    assert fingerprint(fingerprint_inputs(**changed)) != fp
    changed = dict(base)
    assert fingerprint(fingerprint_inputs(
        library_version="99.0", **changed)) != fp


def test_fingerprint_invalidation_wire_format():
    """A cached plan tuned for the f32 wire must NOT be served to a
    bf16-wire campaign (its measured seconds priced twice the wire
    bytes) — the wire format is part of the fingerprint key."""
    base = dict(platform="cpu", device_count=8, mesh_shape=[2, 2, 2],
                grid=[16, 16, 16], radius=Radius.constant(1),
                quantities={"q0": "float32"}, boundary="PERIODIC")
    fp = fingerprint(fingerprint_inputs(**base))
    # the default IS f32 — spelling it out must not re-key the cache
    assert fingerprint(fingerprint_inputs(wire_format="f32",
                                          **base)) == fp
    assert fingerprint(fingerprint_inputs(wire_format="bf16",
                                          **base)) != fp


def test_candidate_wire_format_space_and_feasibility():
    """Opting wire formats into the sweep doubles the ppermute
    candidates only (narrow wire is a slab/packed capability), the
    bf16 variants rank strictly cheaper than their f32 twins under the
    calibrated model (half the wire bytes), and the key round-trips."""
    geom = TuneGeometry(shard_interior_zyx=(8, 8, 8),
                        min_interior_zyx=(8, 8, 8),
                        radius=Radius.constant(1), counts=Dim3(2, 2, 2),
                        elem_sizes=(4,))
    base = candidate_space(geom, depths=(1,))
    wired = candidate_space(geom, depths=(1,),
                            wire_formats=("f32", "bf16"))
    ppermute = [c for c in base
                if c.method in ("PpermuteSlab", "PpermutePacked")]
    assert len(wired) == len(base) + len(ppermute)
    assert all(c.method in ("PpermuteSlab", "PpermutePacked")
               for c in wired if c.wire_format == "bf16")
    coeffs = LinkCoefficients(alpha_s=0.0, beta_bytes_per_s=1e10)
    for c in wired:
        if c.wire_format != "bf16":
            continue
        twin = next(t for t in wired
                    if t.method == c.method and t.wire_format == "f32"
                    and t.exchange_every == c.exchange_every
                    and t.overlap == c.overlap)

        def price(cand):
            return configured_step_seconds(
                cand.method, geom.shard_interior_zyx, geom.radius,
                geom.counts, geom.elem_sizes, cand.exchange_every,
                coeffs, wire_format=cand.wire_format)

        assert price(c) < price(twin)
        assert "wire=bf16" in c.key()
        assert Candidate.from_key(c.key()) == c


# ---------------------------------------------------------------------------
# per-axis depths + placement: keys, candidate space, cache compat


def test_asym_candidate_key_roundtrip_and_feasibility():
    """Asymmetric depths serialize as a dot-separated (x, y, z) depth
    (``PpermuteSlab[s=1.1.4]``), round-trip through from_key, and obey
    the realize()-equivalent feasibility rules per axis."""
    c = Candidate("PpermuteSlab", 4, depths=(1, 1, 4))
    assert c.key() == "PpermuteSlab[s=1.1.4]"
    assert Candidate.from_key(c.key()) == c
    # a uniform depths tuple collapses to the symmetric spelling
    assert Candidate("PpermuteSlab", 2, depths=(2, 2, 2)).key() == \
        "PpermuteSlab[s=2]"
    geom = _geom()
    assert candidate_feasible(Candidate("PpermuteSlab", 4,
                                        depths=(1, 1, 4)), geom)
    # the deep axis is bounded by the SMALLEST shard (min_interior is
    # zyx: 7 rows on z reject depth 8 there, depth 4 fits)
    short = _geom(min_interior=(7, 8, 8))
    assert not candidate_feasible(Candidate("PpermuteSlab", 8,
                                            depths=(1, 1, 8)), short)
    assert candidate_feasible(Candidate("PpermuteSlab", 4,
                                        depths=(1, 1, 4)), short)
    # asym declines: non-ppermute engines, overlap, non-slab layout,
    # and cadences that do not divide the group length
    assert not candidate_feasible(Candidate("AllGather", 4,
                                            depths=(1, 1, 4)), geom)
    assert not candidate_feasible(Candidate("PpermuteSlab", 4, True,
                                            depths=(1, 1, 4)), geom)
    assert not candidate_feasible(
        Candidate("PpermuteSlab", 4, wire_layout="irredundant",
                  depths=(1, 1, 4)), geom)
    assert not candidate_feasible(Candidate("PpermuteSlab", 4,
                                            depths=(1, 3, 4)), geom)


def test_candidate_space_asymmetric_depth_specs():
    """Depth entries may be per-axis dicts/tuples: they become
    asymmetric candidates on the ppermute engines only, and uniform
    spellings collapse into the symmetric set (no duplicate keys)."""
    cands = candidate_space(_geom(), depths=(1, 4, {"z": 4}, (4, 4, 4)),
                            runnable=lambda m: True)
    keys = [c.key() for c in cands]
    assert len(keys) == len(set(keys))
    assert "PpermuteSlab[s=1.1.4]" in keys
    assert "PpermutePacked[s=1.1.4]" in keys
    assert "PpermuteSlab[s=4]" in keys
    assert not any(k.startswith(("AllGather[s=1.1.4",
                                 "PallasDMA[s=1.1.4")) for k in keys)


def test_plan_cache_loads_pre_deployment_records(tmp_path):
    """Cache records written before the per-axis depth / placement
    axes existed carry neither ``config.depths`` nor ``placement`` —
    they must load cleanly as symmetric-depth auto-placement plans
    (the same old-record contract as ``Plan.tiling``), and a new
    asymmetric/qap plan round-trips its keys."""
    cache = tmp_path / "plans.json"
    store_plan(Plan(config=Candidate("PpermutePacked", 4),
                    fingerprint="old1", coefficients={}, costs={}),
               cache)
    data = json.loads(cache.read_text())
    rec = data["plans"]["old1"]
    del rec["config"]["depths"]
    del rec["placement"]
    cache.write_text(json.dumps(data))
    back = load_plan("old1", cache)
    assert back is not None
    assert back.config.depths is None
    assert back.config.depths_xyz() == (4, 4, 4)
    assert back.placement == "auto"
    store_plan(Plan(config=Candidate("PpermuteSlab", 4,
                                     depths=(1, 1, 4)),
                    fingerprint="new1", coefficients={}, costs={},
                    placement="qap"), cache)
    b2 = load_plan("new1", cache)
    assert b2.config.depths == (1, 1, 4)
    assert b2.config.key() == "PpermuteSlab[s=1.1.4]"
    assert b2.placement == "qap"


def test_fingerprint_depths_and_placement_only_when_nondefault():
    """Symmetric depths and auto placement are the identity: spelling
    them out must not re-key plans cached before these axes existed;
    non-uniform depths and forced placement modes must."""
    base = dict(platform="cpu", device_count=8, mesh_shape=[2, 2, 2],
                grid=[16, 16, 16], radius=Radius.constant(1),
                quantities={"q0": "float32"}, boundary="PERIODIC")
    fp = fingerprint(fingerprint_inputs(**base))
    assert fingerprint(fingerprint_inputs(
        exchange_depths=(4, 4, 4), placement="auto", **base)) == fp
    assert fingerprint(fingerprint_inputs(
        exchange_depths=(1, 1, 4), **base)) != fp
    assert fingerprint(fingerprint_inputs(placement="qap", **base)) != fp
    assert fingerprint(fingerprint_inputs(placement="trivial",
                                          **base)) != fp


# ---------------------------------------------------------------------------
# the end-to-end search (fake timer; deterministic)


def test_autotune_selects_model_cheapest_plan(tmp_path):
    """The acceptance criterion: with the fake timer (which evaluates
    the same analytic model), autotune() selects exactly the plan the
    CALIBRATED cost model ranks cheapest, prunes the sweep before
    timing, and a second run is a pure cache hit."""
    cache = tmp_path / "plans.json"
    dd = _domain()  # 16^3 over 2x2x2: 8^3 shards, r=1, two f32 fields
    plan = dd.autotune(timer=FakeTimer(), cache_path=cache)

    assert plan.provenance == "tuned"
    # pruning: 9 feasible candidates, only 4 measured (+3 pingpongs)
    n_cands = len(plan.costs)
    n_measured = sum(1 for rec in plan.costs.values()
                     if "measured_s" in rec)
    assert n_cands == 9 and n_measured == 4
    assert plan.measurements == n_measured + 3

    # the calibrated model's argmin IS the winner (fake measurements
    # realize the model exactly)
    coeffs = LinkCoefficients(**plan.coefficients["ici"])
    geom = _geom()
    best = min(
        (Candidate.from_key(k) for k in plan.costs),
        key=lambda c: configured_step_seconds(
            c.method, geom.shard_interior_zyx, geom.radius, geom.counts,
            geom.elem_sizes, c.exchange_every, coeffs))
    assert plan.config == best
    # ...and concretely: two fields + tiny latency-bound shards ->
    # per-direction packing at the deepest feasible blocking
    assert plan.config == Candidate("PpermutePacked", 8)

    # the plan applied: realize() runs the tuned configuration
    dd.realize()
    assert dd.methods == Method.PpermutePacked
    assert dd.exchange_every == 8
    assert dd.plan_provenance == "tuned"
    dd.exchange()  # the tuned program actually runs

    # second run, same fingerprint: cache hit, ZERO measurements
    dd2 = _domain()
    plan2 = dd2.autotune(timer=FakeTimer(), cache_path=cache)
    assert plan2.provenance == "cached"
    assert plan2.measurements == 0
    assert plan2.config == plan.config
    assert dd2.plan_provenance == "cached"


def test_autotune_retunes_on_fingerprint_mismatch(tmp_path):
    cache = tmp_path / "plans.json"
    _domain().autotune(timer=FakeTimer(), cache_path=cache)
    # radius change -> new fingerprint -> forced re-tune
    dd = _domain(radius=2)
    plan = dd.autotune(timer=FakeTimer(), cache_path=cache)
    assert plan.provenance == "tuned" and plan.measurements > 0
    # dtype change likewise
    dd = _domain(dtype=np.float64)
    plan = dd.autotune(timer=FakeTimer(), cache_path=cache)
    assert plan.provenance == "tuned" and plan.measurements > 0
    # mesh change likewise
    dd = _domain(mesh=(4, 2, 1))
    plan = dd.autotune(timer=FakeTimer(), cache_path=cache)
    assert plan.provenance == "tuned" and plan.measurements > 0
    # all four plans coexist in one cache file
    assert len(load_cache(cache)) == 4


def test_autotune_force_remeasures(tmp_path):
    cache = tmp_path / "plans.json"
    _domain().autotune(timer=FakeTimer(), cache_path=cache)
    plan = _domain().autotune(timer=FakeTimer(), cache_path=cache,
                              force=True)
    assert plan.provenance == "tuned" and plan.measurements > 0


def test_measurements_decide_among_survivors(tmp_path):
    """The tuner trusts measurements over the model within the pruned
    set: a fake timer that (only) slows PpermutePacked 10x flips the
    winner to the next-best measured survivor."""
    cache = tmp_path / "plans.json"
    dd = _domain()
    plan = dd.autotune(timer=FakeTimer(scale={"PpermutePacked": 10.0}),
                       cache_path=cache)
    assert plan.config == Candidate("PpermuteSlab", 8)


def test_autotune_fits_dcn_link_class(tmp_path):
    """A timer exposing a (slower) DCN link gets a second per-link
    alpha-beta fit; ranking uses the bottleneck combine (sequential
    axis sweeps must cross the slow fabric), recorded in the plan."""
    cache = tmp_path / "plans.json"
    ici = LinkCoefficients(50e-6, 1e10)
    dcn = LinkCoefficients(500e-6, 1e9)
    dd = _domain()
    plan = dd.autotune(timer=FakeTimer(ici, dcn_coeffs=dcn),
                       cache_path=cache)
    assert set(plan.coefficients) == {"ici", "dcn"}
    assert plan.coefficients["dcn"]["alpha_s"] == \
        pytest.approx(500e-6, rel=1e-9)
    assert plan.coefficients["ici"]["alpha_s"] == \
        pytest.approx(50e-6, rel=1e-9)
    # 3 ici + 3 dcn pingpongs + 4 exchange timings
    assert plan.measurements == 10
    # predicted costs were priced at the bottleneck (dcn) coefficients
    geom = _geom()
    bottleneck = LinkCoefficients(500e-6, 1e9)
    c = plan.config
    assert plan.costs[c.key()]["predicted_s"] == pytest.approx(
        configured_step_seconds(c.method, geom.shard_interior_zyx,
                                geom.radius, geom.counts,
                                geom.elem_sizes, c.exchange_every,
                                bottleneck), rel=1e-9)


def test_method_auto_resolves_at_realize(tmp_path, monkeypatch):
    """Method.Auto is the standing autotune request: realize() runs
    the tuner (here with the fake timer substituted for the real
    MeshTimer) and deploys the winner."""
    import stencil_tpu.tuning as tuning

    monkeypatch.setenv("STENCIL_TUNE_CACHE",
                       str(tmp_path / "plans.json"))
    monkeypatch.setattr(tuning, "MeshTimer",
                        lambda *a, **kw: FakeTimer())
    dd = _domain()
    dd.set_methods(Method.Auto)
    dd.realize()
    assert Method.Auto not in dd.methods
    assert dd.methods == Method.PpermutePacked
    assert dd.exchange_every == 8
    assert dd.plan_provenance == "tuned"
    dd.exchange()


def test_plan_file_records_provenance(tmp_path):
    dd = _domain()
    dd.autotune(timer=FakeTimer(), cache_path=tmp_path / "plans.json")
    dd.set_output_prefix(str(tmp_path) + "/")
    dd.realize()
    text = (tmp_path / "plan.txt").read_text()
    assert "plan provenance: tuned" in text
    assert "plan config: PpermutePacked[s=8]" in text
    # an untuned domain records the static-default provenance
    dd = _domain()
    dd.set_output_prefix(str(tmp_path) + "/untuned_")
    dd.realize()
    text = (tmp_path / "untuned_plan.txt").read_text()
    assert "plan provenance: default" in text


def test_run_autotune_rejects_impossible_geometry(tmp_path):
    geom = _geom(radius=16)  # radius exceeds the 8^3 shard everywhere
    inputs = fingerprint_inputs(
        platform="cpu", device_count=8, mesh_shape=[2, 2, 2],
        grid=[16, 16, 16], radius=Radius.constant(16),
        quantities={"q0": "float32"}, boundary="PERIODIC")
    with pytest.raises(ValueError, match="no feasible"):
        run_autotune(geom, inputs, FakeTimer(),
                     cache_path=tmp_path / "plans.json")


# ---------------------------------------------------------------------------
# capability-aware pick_method (both branches, capability injected)


def test_pick_method_keeps_runnable_request():
    assert pick_method(Method.PallasDMA,
                       runnable=lambda m: True) == Method.PallasDMA
    assert pick_method(Method.Default) == Method.PpermuteSlab


def test_pick_method_falls_back_when_unrunnable(capsys):
    from stencil_tpu.parallel import methods as methods_mod

    methods_mod._warned.clear()
    no_dma = lambda m: m != Method.PallasDMA  # noqa: E731
    # next requested strategy wins...
    got = pick_method(Method.PallasDMA | Method.PpermutePacked,
                      runnable=no_dma)
    assert got == Method.PpermutePacked
    # ...or Default when nothing requested is runnable
    methods_mod._warned.clear()
    assert pick_method(Method.PallasDMA,
                       runnable=no_dma) == Method.PpermuteSlab
    err = capsys.readouterr().err
    assert "PallasDMA" in err and "falling back" in err


def test_pick_method_warns_once_per_fact(capsys):
    from stencil_tpu.parallel import methods as methods_mod

    methods_mod._warned.clear()
    no_dma = lambda m: m != Method.PallasDMA  # noqa: E731
    for _ in range(3):
        pick_method(Method.PallasDMA, runnable=no_dma)
    err = capsys.readouterr().err
    assert err.count("falling back") == 1


def test_pick_method_rejects_bare_auto():
    with pytest.raises(ValueError, match="Auto"):
        pick_method(Method.Auto)
    with pytest.raises(ValueError):
        pick_method(Method.NONE)


def test_cache_concurrent_writers_drop_no_records(tmp_path):
    """Two service workers storing plans for DIFFERENT fingerprints
    concurrently must both land: store_plan is a read-merge-write
    under the cache's writer lock, not a blind whole-file overwrite."""
    import threading

    from stencil_tpu.tuning.cache import load_cache, store_plan
    from stencil_tpu.tuning.plan import Candidate, Plan

    path = tmp_path / "plans.json"
    n = 16

    def mkplan(i):
        return Plan(config=Candidate("PpermuteSlab", 1, False),
                    fingerprint=f"{i:02d}" * 16, coefficients={},
                    costs={}, provenance="tuned", measurements=1)

    start = threading.Barrier(n)
    errors = []

    def worker(i):
        try:
            start.wait()
            store_plan(mkplan(i), path)
        except BaseException as e:  # noqa: BLE001 - surface in main
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    plans = load_cache(path)
    assert sorted(plans) == sorted(f"{i:02d}" * 16 for i in range(n))


def test_cache_lock_released_after_store(tmp_path):
    """The writer lock is released even when the publish raises — a
    poisoned lock would deadlock every later tune."""
    from stencil_tpu.tuning import cache as cache_mod
    from stencil_tpu.tuning.plan import Candidate, Plan

    plan = Plan(config=Candidate("PpermuteSlab", 1, False),
                fingerprint="a" * 32, coefficients={}, costs={},
                provenance="tuned", measurements=1)
    path = tmp_path / "nested" / "plans.json"
    cache_mod.store_plan(plan, path)
    # immediately storable again (no held flock / thread mutex)
    cache_mod.store_plan(plan, path)
    assert cache_mod.load_plan("a" * 32, path) is not None
