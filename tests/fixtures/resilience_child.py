"""Subprocess runner for the preemption e2e test.

Runs a small Jacobi campaign under ``run_resilient``. With
``--preempt-at N`` a seeded :class:`Preemption` delivers a real
SIGTERM to this process mid-loop; the driver writes a final
"preempted" checkpoint and the process exits 0 (the clean-preemption
contract a fleet scheduler relies on). Invoked again on the same
``--ckpt-dir`` without the fault, it resumes from that checkpoint and
writes the final temperature field to ``--out`` — the parent test
asserts bitwise equality with an uninterrupted run.
"""

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--preempt-at", type=int, default=0)
    ap.add_argument("--out", default="")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import numpy as np

    from stencil_tpu.models.jacobi import Jacobi3D
    from stencil_tpu.resilience import (FaultPlan, Preemption,
                                        ResiliencePolicy)

    j = Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float32)
    j.init()
    faults = None
    if args.preempt_at:
        faults = FaultPlan(preemptions=[Preemption(step=args.preempt_at)])
    policy = ResiliencePolicy(check_every=2, ckpt_every=4,
                              base_delay=0.0)
    report = j.run_resilient(args.steps, policy=policy,
                             ckpt_dir=args.ckpt_dir, faults=faults)
    if report.preempted:
        print(f"PREEMPTED steps={report.steps}")
        return
    if args.out:
        np.save(args.out, j.temperature())
    print(f"DONE steps={report.steps} "
          f"resumed_from={report.resumed_from}")


if __name__ == "__main__":
    main()
