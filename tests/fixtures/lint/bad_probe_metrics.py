"""Negative control for the telemetry step-metrics contract.

Telemetry's license to instrument the production step loop is that its
counters PIGGYBACK on the health probe's one existing all-reduce
(``stencil_tpu/telemetry/probe.py``): extra columns in the stacked
stats vector, one pmax, zero additional collectives — pinned by
``exact_counts`` on the ``telemetry.*`` registry targets. This fixture
is the tempting shortcut that breaks the contract without changing any
*result*: reducing the metrics vector with its OWN ``pmax`` instead of
stacking it into the health vector first — numerically identical
metrics, but every instrumented probe step now pays a second
all-reduce launch on the fabric the telemetry is supposed to be
observing, not taxing. ``python -m stencil_tpu.analysis
tests/fixtures/lint/bad_probe_metrics.py`` MUST exit nonzero.
"""

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from stencil_tpu.analysis import HloSpec, HloTarget
from stencil_tpu.resilience.health import probe_shard


def _f32(shape):
    return jax.ShapeDtypeStruct(shape, jnp.float32)


def _separate_metrics_reduce_spec() -> HloSpec:
    """Health stats reduced once, metrics reduced AGAIN separately: 2
    all-reduces where the shipped instrumentation does 1. Sold under
    the shipped contract (exactly one all_reduce) — the checker must
    flag it."""
    import numpy as np

    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("z", "y", "x"))
    axes = ("z", "y", "x")

    def shard(a, b, metrics_vec):
        # the health stats still reduce correctly in one pmax...
        stats = probe_shard({"a": a, "b": b})
        # ...but the bug pays a SECOND all-reduce for the metrics
        # instead of stacking them into the probe vector first
        reduced_metrics = jax.lax.pmax(metrics_vec, axes)
        return stats, reduced_metrics

    spec = P("z", "y", "x")
    sm = jax.shard_map(shard, mesh=mesh, in_specs=(spec, spec, P()),
                       out_specs=(P(), P()), check_vma=False)
    return HloSpec(fn=sm,
                   args=(_f32((16, 16, 16)), _f32((16, 16, 16)),
                         _f32((2,))),
                   allow=("all_reduce",),
                   exact_counts={"all_reduce": 1})


TARGETS = [
    HloTarget("bad_probe_metrics.separate_reduce[hlo]",
              _separate_metrics_reduce_spec),
]
