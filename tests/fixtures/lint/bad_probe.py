"""Negative control for the health-sentinel probe contract.

The sentinel's license to ride the production step loop is its
communication bill: exactly ONE small all-reduce (the stacked-stats
pmax in ``resilience/health.py``), pinned by ``exact_counts`` on its
registry targets. This fixture is the tempting refactor that breaks
the contract without changing any *result*: reducing each statistic
with its own ``pmax`` (one per quantity per row) instead of stacking
first — numerically identical, but every probe step now pays N
all-reduce launches on the fabric the sentinel is supposed to be
guarding. ``python -m stencil_tpu.analysis tests/fixtures/lint/
bad_probe.py`` MUST exit nonzero.
"""

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from stencil_tpu.analysis import HloSpec, HloTarget


def _f32(shape):
    return jax.ShapeDtypeStruct(shape, jnp.float32)


def _unstacked_probe_spec() -> HloSpec:
    """Per-quantity, per-row pmax: 4 all-reduces where the shipped
    probe does 1. Sold under the shipped contract (exactly one
    all_reduce) — the checker must flag it."""
    import numpy as np

    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("z", "y", "x"))
    axes = ("z", "y", "x")

    def shard(a, b):
        stats = []
        for p in (a, b):
            finite = jnp.isfinite(p)
            nf = jnp.sum(~finite).astype(jnp.float32)
            ma = jnp.max(jnp.where(finite, jnp.abs(p),
                                   jnp.zeros_like(p))).astype(jnp.float32)
            # the bug: reduce each scalar separately instead of
            # stacking into one vector and reducing once
            stats.append(jnp.stack([jax.lax.pmax(nf, axes),
                                    jax.lax.pmax(ma, axes)]))
        return jnp.stack(stats, axis=1)

    spec = P("z", "y", "x")
    sm = jax.shard_map(shard, mesh=mesh, in_specs=(spec, spec),
                       out_specs=P(), check_vma=False)
    return HloSpec(fn=sm, args=(_f32((16, 16, 16)), _f32((16, 16, 16))),
                   allow=("all_reduce",),
                   exact_counts={"all_reduce": 1})


TARGETS = [
    HloTarget("bad_probe.unstacked_pmax[hlo]", _unstacked_probe_spec),
]
