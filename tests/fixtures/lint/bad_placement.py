"""Negative control for the placement gate: a QAP-costlier-than-
trivial assignment shipped as "optimized".

The two-tier fabric blocks z across 2 slices, and the 16x16x32 grid
gives z the SMALLEST halo cross-sections — so trivial device order
(z-neighbors across the DCN) is already the cheap side. The claimed
"tuned" assignment transposes the x and z mesh indices, which marches
the fat x faces over the slow DCN links instead. The linkmap checker
re-prices the claimed permutation under the NodeAware objective and
must flag it with a nonzero CLI exit: a placement shipped as
optimized must never lose to the identity assignment.
"""

import jax
from jax.sharding import PartitionSpec as P

from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.observatory.linkmap import (LinkmapSpec, LinkmapTarget,
                                             sweep_traffic)

_MESH = (2, 2, 2)
_GRID = (16, 16, 32)  # (x, y, z): z has the smallest cross-sections


def _overpriced_placement_spec() -> LinkmapSpec:
    from stencil_tpu.parallel.exchange import exchange_shard
    from stencil_tpu.parallel.mesh import make_mesh, mesh_dim

    n = _MESH[0] * _MESH[1] * _MESH[2]
    mesh = make_mesh(_MESH, jax.devices()[:n])
    counts = mesh_dim(mesh)
    radius = Radius.constant(1)

    def shard(p):
        return exchange_shard(p, radius, counts)

    sm = jax.shard_map(shard, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    # padded shard (z,y,x) = (18, 10, 10); the traffic matrix itself is
    # exact — only the shipped placement is wrong
    global_zyx = tuple((_GRID[2 - d] // _MESH[2 - d] + 2)
                       * _MESH[2 - d] for d in range(3))
    arg = jax.ShapeDtypeStruct(global_zyx, jax.numpy.float32)
    traffic = sweep_traffic((18, 10, 10), radius, Dim3(*_MESH), (4,))
    # the bug: an "optimized" assignment that transposes the x and z
    # mesh indices, shipping the LARGE x faces across the DCN tier
    perm = [0] * n
    for z in range(2):
        for y in range(2):
            for x in range(2):
                perm[x + 2 * y + 4 * z] = z + 2 * y + 4 * x
    placement = {
        "counts": _MESH,
        "grid": _GRID,
        "assignment": perm,
        "dcn_axis": 2,
        "n_slices": 2,
    }
    return LinkmapSpec(fn=sm, args=(arg,), traffic=traffic,
                       placement=placement)


TARGETS = [
    LinkmapTarget("fixture.placement_ships_qap_loser",
                  _overpriced_placement_spec),
]
