"""Negative controls for the COLLECTIVES checker.

Each target traces a ``lax.ppermute`` whose permutation violates the
full-bijection contract — all of these trace cleanly (JAX defers
validation to compile time, and un-sourced destinations silently keep
zeros), which is precisely why the static pass exists.
``python -m stencil_tpu.analysis tests/fixtures/lint/bad_collective.py``
MUST exit nonzero.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from stencil_tpu.analysis import CollectiveSpec, CollectiveTarget
from stencil_tpu.parallel.mesh import make_mesh


def _spec(perm, axis="z") -> CollectiveSpec:
    mesh = make_mesh((1, 1, 2), jax.devices()[:2])

    def shard(x):
        return lax.ppermute(x, axis, perm)

    sm = jax.shard_map(shard, mesh=mesh, in_specs=P("z", None, None),
                       out_specs=P("z", None, None), check_vma=False)
    return CollectiveSpec(
        fn=sm, args=(jax.ShapeDtypeStruct((4, 4, 4), jnp.float32),),
        axis_sizes=dict(mesh.shape), expect_ppermute=True)


def _duplicate_dest() -> CollectiveSpec:
    # both shards send to shard 1: shard 0's halo is never filled and
    # shard 1 receives conflicting writes
    return _spec([(0, 1), (1, 1)])


def _out_of_range() -> CollectiveSpec:
    # a 4-device ring permutation issued on a 2-device axis
    return _spec([(i, (i + 1) % 4) for i in range(4)])


def _partial_perm() -> CollectiveSpec:
    # half the ring: shard 0 never receives — its halo keeps zeros
    return _spec([(0, 1)])


TARGETS = [
    CollectiveTarget("fixture.ppermute_duplicate_destination",
                     _duplicate_dest),
    CollectiveTarget("fixture.ppermute_index_out_of_range",
                     _out_of_range),
    CollectiveTarget("fixture.ppermute_partial_ring", _partial_perm),
]
