"""Negative controls for the VMEM checker.

Each target's ``pallas_call`` traces cleanly (the generic interpreter
would even run it), but its BlockSpec geometry is hostile to the TPU
memory system: a working set over the VMEM budget, a lane-misaligned
trailing tile, or a ragged grid tiling. These fail (or crawl) only
when Mosaic meets real hardware — the static audit turns them into
red CI instead.
``python -m stencil_tpu.analysis tests/fixtures/lint/bad_vmem.py``
MUST exit nonzero.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from stencil_tpu.analysis import VmemSpec, VmemTarget


def _copy_kernel(x, o):
    o[...] = x[...]


def _over_budget() -> VmemSpec:
    """(128, 128, 128) f32 blocks: 8 MiB per block, in + out doubled
    by the pipeline = 32 MiB against the 16 MiB budget."""
    def fn(x):
        return pl.pallas_call(
            _copy_kernel,
            grid=(8,),
            in_specs=[pl.BlockSpec((128, 128, 128),
                                   lambda i: (i, 0, 0))],
            out_specs=pl.BlockSpec((128, 128, 128),
                                   lambda i: (i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((1024, 128, 128),
                                           jnp.float32),
            interpret=False,
        )(x)

    return VmemSpec(
        fn=fn, args=(jax.ShapeDtypeStruct((1024, 128, 128),
                                          jnp.float32),))


def _misaligned_lane() -> VmemSpec:
    """Trailing (lane) block dim 96: neither a multiple of 128 nor the
    full array extent 192 — every grid step pays a partial-lane tile."""
    def fn(x):
        return pl.pallas_call(
            _copy_kernel,
            grid=(2,),
            in_specs=[pl.BlockSpec((8, 8, 96), lambda i: (0, 0, i))],
            out_specs=pl.BlockSpec((8, 8, 96), lambda i: (0, 0, i)),
            out_shape=jax.ShapeDtypeStruct((8, 8, 192), jnp.float32),
            interpret=False,
        )(x)

    return VmemSpec(
        fn=fn, args=(jax.ShapeDtypeStruct((8, 8, 192), jnp.float32),))


def _ragged_grid() -> VmemSpec:
    """Sublane block dim 8 against array extent 20: 20 % 8 != 0, so
    the last tile is ragged (masked partial blocks on the hot path)."""
    def fn(x):
        return pl.pallas_call(
            _copy_kernel,
            grid=(3,),
            in_specs=[pl.BlockSpec((8, 8, 128), lambda i: (0, i, 0))],
            out_specs=pl.BlockSpec((8, 8, 128), lambda i: (0, i, 0)),
            out_shape=jax.ShapeDtypeStruct((8, 20, 128), jnp.float32),
            interpret=False,
        )(x)

    return VmemSpec(
        fn=fn, args=(jax.ShapeDtypeStruct((8, 20, 128), jnp.float32),))


TARGETS = [
    VmemTarget("fixture.block_over_vmem_budget", _over_budget),
    VmemTarget("fixture.misaligned_trailing_tile", _misaligned_lane),
    VmemTarget("fixture.ragged_grid_tiling", _ragged_grid),
]
