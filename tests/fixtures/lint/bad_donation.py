"""Negative control for the donation checker: entry points whose
declared donation contract is dead in the compiled program.

``fixture.donation_never_declared`` models the classic refactor
regression — a step loop re-wrapped in a fresh ``jax.jit`` WITHOUT
``donate_argnums`` (the spec still declares the contract; the compiled
alias map is empty). ``fixture.donated_but_copied`` models the subtler
one: ``donate_argnums`` is still declared on the jit, but an
``astype`` changed the output's byte width, so XLA silently drops the
alias and copies — donation checked at the Python level looks fine,
the compiled program says otherwise.
"""

import jax
import jax.numpy as jnp

from stencil_tpu.analysis.donation import DonationSpec, DonationTarget


def _arg():
    return jax.ShapeDtypeStruct((8, 8, 8), jnp.float32)


def _never_declared() -> DonationSpec:
    # the jit lost its donate_argnums; the contract says arg 0 aliases
    fn = jax.jit(lambda x: x + 1.0)
    return DonationSpec(fn=fn, args=(_arg(),), donate_argnums=(0,))


def _donated_but_copied() -> DonationSpec:
    # donated on the jit, but the f32 -> bf16 narrowing makes the
    # buffer unaliasable: XLA warns and copies
    fn = jax.jit(lambda x: x.astype(jnp.bfloat16), donate_argnums=0)
    return DonationSpec(fn=fn, args=(_arg(),), donate_argnums=(0,))


TARGETS = [
    DonationTarget("fixture.donation_never_declared", _never_declared),
    DonationTarget("fixture.donated_but_copied", _donated_but_copied),
]
