"""Negative control for the megastep fusion contract: a fused segment
that RE-REDUCES the health probe on every sub-step.

The megastep's license to ride the production loop is its collective
bill: a ``check_every=k`` segment lowers to exactly ``k`` x the
per-step collective-permutes plus ONE small all-reduce per *declared*
probe row and nothing else. The broken builder here ignores its
``probe_every=2`` contract and pays a probe reduction after EVERY
sub-step — the classic fusion regression where instrumentation
quietly multiplies the all-reduce traffic the fleet's health cadence
was budgeted for. The hlo checker's ``exact_counts`` pin (2 probe
rows for k=4, probe_every=2) must flag the 4 emitted all-reduces.
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from stencil_tpu.analysis.hlo import HloSpec, HloTarget
from stencil_tpu.geometry import Dim3
from stencil_tpu.models.jacobi import jacobi_shard_step
from stencil_tpu.parallel.exchange import shard_origin
from stencil_tpu.parallel.megastep import fused_segment_shard, health_probe
from stencil_tpu.parallel.mesh import make_mesh
from stencil_tpu.parallel.methods import Method
from stencil_tpu.resilience.health import probe_shard

K = 4
PROBE_EVERY = 2  # the declared cadence the broken fusion ignores


def _mesh():
    return make_mesh((2, 2, 2), jax.devices()[:8])


def _bad_segment_spec() -> HloSpec:
    mesh = _mesh()
    counts = Dim3(2, 2, 2)
    radius_local = Dim3(12, 12, 12)
    gsize = Dim3(24, 24, 24)
    from stencil_tpu.geometry import Radius
    radius = Radius.constant(1)

    def shard(p, vec):
        origin = shard_origin(radius_local, Dim3(0, 0, 0))

        def advance(q, c, i):
            return jacobi_shard_step(q, radius, counts, radius_local,
                                     gsize, origin, Method.PpermuteSlab)

        # the bug: probe_every=1 hardwired — each of the k sub-steps
        # pays its own all-reduce, 2x the declared probe bill
        probe = health_probe(lambda q: {"temp": q}, base_vec=vec)
        return fused_segment_shard(p, advance, probe, [1] * K,
                                   probe_every=1)

    spec = P("z", "y", "x")
    sm = jax.shard_map(shard, mesh=mesh, in_specs=(spec, P()),
                       out_specs=(spec, P()), check_vma=False)
    vec = jax.ShapeDtypeStruct((2,), jnp.float32)
    arg = jax.ShapeDtypeStruct((28, 28, 28), jnp.float32)
    return HloSpec(fn=sm, args=(arg, vec),
                   allow=("collective_permute", "all_reduce"),
                   exact_counts={"collective_permute": 6 * K,
                                 "all_reduce": -(-K // PROBE_EVERY)})


TARGETS = [
    HloTarget("fixture.megastep.reprobed_per_substep[hlo]",
              _bad_segment_spec),
]

# silence unused-import style checkers; probe_shard documents what the
# broken probe ultimately reduces with
_ = probe_shard
