"""Negative control for the tuned-plan registry gate.

The autotuner's whole trust story is that every configuration a plan
can apply is already under the registry's HLO ppermute-only gate
(``tuning.plan[*]`` targets). This fixture is the attack that gate
exists for: a plan record tampered with (or a buggy plan-application
path) that silently enables the O(domain) ``AllGather`` strategy while
the registered contract still claims collective-permute-only halo
traffic. The lowered StableHLO betrays it — ``python -m
stencil_tpu.analysis tests/fixtures/lint/bad_plan.py`` MUST exit
nonzero.
"""

import jax

from stencil_tpu.analysis import HloSpec, HloTarget
from stencil_tpu.geometry import Radius
from stencil_tpu.parallel.exchange import make_exchange
from stencil_tpu.parallel.mesh import make_mesh
from stencil_tpu.parallel.methods import Method
from stencil_tpu.tuning import Candidate, Plan


def _tampered_plan() -> Plan:
    """A plan-cache record whose method field was flipped to AllGather
    — fingerprint and provenance look perfectly healthy."""
    return Plan.from_record({
        "config": {"method": "AllGather", "exchange_every": 1,
                   "overlap": False},
        "fingerprint": "deadbeef" * 4,
        "coefficients": {"ici": {"alpha_s": 2e-5,
                                 "beta_bytes_per_s": 4.5e10}},
        "costs": {}, "provenance": "cached", "measurements": 0,
        "created": 0.0, "library_version": "0.1.0",
    })


def _plan_applied_exchange() -> HloSpec:
    """Apply the tampered plan the way a deployment would and register
    the result under the tuned-plan contract (collective-permute
    only): the hlo checker must flag the smuggled all-gather."""
    plan = _tampered_plan()
    mesh = make_mesh((2, 2, 2), jax.devices()[:8])
    radius = Radius.constant(1).deepened(plan.config.exchange_every)
    ex = make_exchange(mesh, radius, Method[plan.config.method])
    arg = {"q": jax.ShapeDtypeStruct((20, 20, 20), jax.numpy.float32)}
    return HloSpec(fn=ex, args=(arg,), allow=("collective_permute",))


TARGETS = [
    HloTarget("fixture.plan_silently_enables_allgather",
              _plan_applied_exchange),
]
