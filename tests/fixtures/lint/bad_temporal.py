"""Negative control for the footprint checker: temporal blocking gone
wrong.

A 2-step blocked jacobi group declares the deepened contract — the
exchange ships a depth-2 halo (``Radius.constant(1).deepened(2)``) —
but sub-step 0's window forgot to shrink: it computes the FULL
depth-2-valid region instead of the one-ring-smaller window, so its
stencil reads reach depth 3 into halo data that the deep exchange
never delivered. The footprint checker must prove the fused program's
total static reach exceeds the deepened declaration (the exact bug
class ``parallel/temporal.py``'s shrinking-window schedule exists to
prevent).
"""

import jax

from stencil_tpu.analysis.footprint import StencilOpSpec, StencilOpTarget
from stencil_tpu.geometry import Dim3, Radius


def _f32(shape):
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(shape, jnp.float32)


def _temporal_overreach_spec() -> StencilOpSpec:
    from stencil_tpu.ops.stencil_kernels import jacobi7

    interior = Dim3(8, 8, 8)
    declared = Radius.constant(1).deepened(2)   # the deep halo contract
    pad = Dim3(3, 3, 3)                         # buffer padded deeper
    r1 = Radius.constant(1)

    def fused(p):
        # sub-step 0 BUG: window [1, 13) (all depth-2-valid cells)
        # instead of [2, 12) — the 7-point reads span [0, 14), depth 3
        w0 = jacobi7(p, r1, Dim3(12, 12, 12))
        # sub-step 1: correct shrink to the interior window
        w1 = jacobi7(w0, r1, Dim3(10, 10, 10))
        return w1[1:9, 1:9, 1:9]

    return StencilOpSpec(fn=fused, args=(_f32((14, 14, 14)),),
                         radius=declared, interior=interior,
                         pad_lo=pad, pad_hi=pad)


TARGETS = [
    StencilOpTarget("fixture.temporal_substep_reads_past_deep_halo",
                    _temporal_overreach_spec),
]
