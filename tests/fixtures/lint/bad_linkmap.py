"""Negative control for the link observatory: a traffic matrix that
drops corner messages — the classic 6-neighbor-only bug.

The sequential-sweep exchange forwards edge/corner halos inside its
fat axis slabs (each axis message's cross-section spans the OTHER
axes' pads), so a per-link traffic model that prices only the
face-interior cross-sections — the naive "6 neighbors, 6 face slabs"
picture — under-counts exactly the edge+corner bytes. The linkmap
checker must flag the mismatch against the HLO-extracted bytes with a
nonzero CLI exit, naming the zero-corner-share smell.
"""

import jax
from jax.sharding import PartitionSpec as P

from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.observatory.linkmap import (LinkmapSpec, LinkmapTarget,
                                             sweep_traffic)

_MESH = (2, 2, 2)
_GLOBAL = (28, 28, 28)


def _six_neighbor_only_spec() -> LinkmapSpec:
    from stencil_tpu.parallel.exchange import exchange_shard
    from stencil_tpu.parallel.mesh import make_mesh, mesh_dim

    n = _MESH[0] * _MESH[1] * _MESH[2]
    mesh = make_mesh(_MESH, jax.devices()[:n])
    counts = mesh_dim(mesh)
    radius = Radius.constant(1)

    def shard(p):
        return exchange_shard(p, radius, counts)

    sm = jax.shard_map(shard, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    arg = jax.ShapeDtypeStruct(_GLOBAL, jax.numpy.float32)
    # the bug: cross-sections priced on the INTERIOR dims only — the
    # "6 neighbors, 6 bare face slabs" picture, which forgets that the
    # real slabs are PADDED and forward the edge/corner halos of the
    # other axes. Every edge/corner byte the HLO moves goes missing.
    interior = tuple(g // m - 2 * radius.face(0, 1)
                     for g, m in zip(_GLOBAL, _MESH))
    traffic = sweep_traffic(interior, radius, Dim3(*_MESH), (4,),
                            pads_included=False)
    return LinkmapSpec(fn=sm, args=(arg,), traffic=traffic)


TARGETS = [
    LinkmapTarget("fixture.linkmap_drops_corner_messages",
                  _six_neighbor_only_spec),
]
