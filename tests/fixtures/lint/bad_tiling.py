"""Negative control for the prescriptive-tiling checker.

The SNIPPETS.md motivating failure, reproduced verbatim as a fixture:
the Jacobi halo kernel pinned to its OLD default block shape (16, 128)
at the 512^3-per-device size where the judge measured Mosaic's VMEM
allocation failing on real TPU — 20 MiB of double-buffered blocks
against the 16 MiB physical budget (the kernel's raised
``vmem_limit_bytes`` hid it from the plain VMEM checker, which honors
declared limits; the tiling checker deliberately does not). The
planner's prescription for this size is (8, 128) at 11 MiB — the
registered ``analysis.tiling...jacobi7_halo_pallas[512]`` target
proves that shape clean; THIS target proves the checker flags the bad
one, with the suggestion attached.
``python -m stencil_tpu.analysis tests/fixtures/lint/bad_tiling.py``
MUST exit nonzero.
"""

import jax
import jax.numpy as jnp

from stencil_tpu.analysis import TilingSpec, TilingTarget


def _f32(shape):
    return jax.ShapeDtypeStruct(shape, jnp.float32)


def _old_default_at_512() -> TilingSpec:
    from stencil_tpu.ops.pallas_halo import jacobi7_halo_pallas

    S = 512
    slabs = {"zlo": _f32((1, S, S)), "zhi": _f32((1, S, S)),
             "ylo": _f32((S, 8, S)), "yhi": _f32((S, 8, S))}
    org = jax.ShapeDtypeStruct((3,), jnp.int32)

    def fn(interior, zlo, zhi, ylo, yhi, o):
        return jacobi7_halo_pallas(
            interior, {"zlo": zlo, "zhi": zhi, "ylo": ylo, "yhi": yhi},
            o, (128, 256, 256), (384, 256, 256), 64,
            block_z=16, block_y=128,   # the pre-planner default shape
            interpret=False)

    return TilingSpec(fn=fn, args=(_f32((S, S, S)), slabs["zlo"],
                                   slabs["zhi"], slabs["ylo"],
                                   slabs["yhi"], org))


TARGETS = [
    TilingTarget("fixture.jacobi_halo_old_default_shape_at_512",
                 _old_default_at_512),
]
