"""Negative control for the irredundant wire-layout byte contract.

The redundancy regression the costmodel checker must catch: an
exchange program that still ships the fat SLAB cross-sections (every
edge/corner cell transiting the wire up to three times) while its
declared byte model claims the irredundant packed layout. The HLO
moves more bytes than the irredundant contract — exactly what a
half-reverted packing plan or a silently dropped ``wire_layout=``
plumb would look like. ``python -m stencil_tpu.analysis
tests/fixtures/lint/bad_packing.py`` MUST exit nonzero.
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from stencil_tpu.analysis import CostModelSpec, CostModelTarget
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel.exchange import exchange_shard
from stencil_tpu.parallel.mesh import make_mesh
from stencil_tpu.parallel.packing import irredundant_bytes_per_sweep


def _slab_sold_as_irredundant() -> CostModelSpec:
    """The program runs the default slab exchange; the declared model
    prices the irredundant layout. Corner and edge cells of the r=1
    halo shell ride the wire three/two times in the lowered HLO, so
    the measured bytes exceed the irredundant contract and the
    analytic cross-check must flag the mismatch."""
    mesh = make_mesh((2, 2, 2), jax.devices()[:8])
    counts = Dim3(2, 2, 2)
    radius = Radius.constant(1)

    def step(x):
        # wire_layout defaults to "slab" — the redundant fat slabs
        return exchange_shard(x, radius, counts)

    sm = jax.shard_map(step, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    expected = sum(irredundant_bytes_per_sweep(
        (10, 10, 10), radius, counts, 4).values())
    return CostModelSpec(
        fn=sm, args=(jax.ShapeDtypeStruct((20, 20, 20), jnp.float32),),
        expected_bytes_per_shard=expected)


TARGETS = [
    CostModelTarget("fixture.slab_bytes_sold_as_irredundant",
                    _slab_sold_as_irredundant),
]
