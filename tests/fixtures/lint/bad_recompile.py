"""Negative control for the recompile checker: entry points whose
abstract fingerprint drifts between dispatches.

``fixture.carry_dtype_drift`` returns its carried state at a different
dtype than it accepts — the second dispatch sees a new input aval and
re-traces, every step (and the donation dies with it).
``fixture.weak_type_promotion`` rebuilds part of the state from a
Python scalar (``jnp.full`` with no dtype), so the carried output is
weak-typed while the input is strong — same retrace loop, harder to
see. ``fixture.python_scalar_arg`` passes the step count as a bare
Python ``int``: it traces weak-typed, forking the jit cache from the
array-typed calls the warm path makes.
"""

import jax
import jax.numpy as jnp

from stencil_tpu.analysis.recompile import RecompileSpec, RecompileTarget


def _arg():
    return jax.ShapeDtypeStruct((8, 8), jnp.float32)


def _dtype_drift() -> RecompileSpec:
    fn = jax.jit(lambda x: (x * 0.5).astype(jnp.bfloat16))
    return RecompileSpec(fn=fn, args=(_arg(),), carry=((0, None),))


def _weak_promotion() -> RecompileSpec:
    # jnp.full with a Python scalar and no dtype produces a WEAK-typed
    # default float — same dtype as the strong input (the arg uses the
    # default float so this holds with and without jax_enable_x64),
    # but feeding the weak result back re-traces next dispatch
    fn = jax.jit(lambda x: jnp.full(x.shape, 2.0))
    arg = jax.ShapeDtypeStruct((8, 8), jnp.result_type(float))
    return RecompileSpec(fn=fn, args=(arg,), carry=((0, None),))


def _python_scalar_arg() -> RecompileSpec:
    fn = jax.jit(lambda x, n: x * n)
    return RecompileSpec(fn=fn, args=(_arg(), 3), carry=((0, None),))


TARGETS = [
    RecompileTarget("fixture.carry_dtype_drift", _dtype_drift),
    RecompileTarget("fixture.weak_type_promotion", _weak_promotion),
    RecompileTarget("fixture.python_scalar_arg", _python_scalar_arg),
]
