"""Negative controls for the PRECISION-CERTIFICATION checker.

Each target is a step body whose dtype flow violates one of the three
proof conditions (or narrows silently) — exactly the programs a
low-precision wire format must never be licensed for. ``python -m
stencil_tpu.analysis tests/fixtures/lint/bad_precision.py`` MUST exit
nonzero, naming the violated condition:

* a bf16 ``psum`` accumulation SOLD as f32 (the result is cast back
  up, but the reduction itself ran below the compute floor) —
  condition (a);
* a silent f32 -> bf16 narrowing inside a step body, declared by no
  wire or compute dtype — a silent convert;
* a double-quantized wire hop (bf16 -> f32 -> arithmetic -> bf16
  before ONE ``ppermute``): each quantization compounds error, so
  narrowing is licensed at most once per hop — condition (c).

Everything here is TRACED, never executed.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from stencil_tpu.analysis import PrecisionSpec, PrecisionTarget
from stencil_tpu.geometry import Dim3
from stencil_tpu.parallel.mesh import make_mesh


def _mesh2():
    return make_mesh((1, 1, 2), jax.devices()[:2])


def _sharded(shard, wire=None):
    mesh = _mesh2()
    sm = jax.shard_map(shard, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    return PrecisionSpec(
        fn=sm, args=(jax.ShapeDtypeStruct((8, 8, 8), jnp.float32),),
        wire=wire, counts=Dim3(1, 1, 2))


def _bf16_psum_sold_as_f32() -> PrecisionSpec:
    """The classic mixed-precision lie: the reduction runs at bf16 and
    the result is cast back to f32 — every digit the accumulation lost
    is still lost, but the output dtype claims full precision."""

    def shard(x):
        acc = lax.psum(x.astype(jnp.bfloat16), "z")
        return acc.astype(jnp.float32)

    return _sharded(shard)


def _silent_step_narrowing() -> PrecisionSpec:
    """A step body that quietly round-trips through bf16 (a stray
    mixed-precision cast, no wire or compute declaration anywhere):
    the checker must flag the narrowing as a silent convert."""

    def shard(x):
        y = (x.astype(jnp.bfloat16) * 2).astype(jnp.float32)
        return y + 1.0

    return _sharded(shard)


def _double_quantized_wire_hop() -> PrecisionSpec:
    """A declared bf16 wire hop whose operand was ALREADY quantized
    once: bf16 -> f32 -> new arithmetic -> bf16 -> ppermute compounds
    two independent roundings into one hop's error budget."""

    def shard(x):
        y = x.astype(jnp.bfloat16).astype(jnp.float32)
        y = y * 1.5
        w = y.astype(jnp.bfloat16)
        n = 2
        w = lax.ppermute(w, "z", [(i, (i + 1) % n) for i in range(n)])
        return w.astype(jnp.float32)

    return _sharded(shard, wire={"z": "bf16"})


TARGETS = [
    PrecisionTarget("fixture.precision_bf16_psum_sold_as_f32",
                    _bf16_psum_sold_as_f32),
    PrecisionTarget("fixture.precision_silent_step_narrowing",
                    _silent_step_narrowing),
    PrecisionTarget("fixture.precision_double_quantized_wire_hop",
                    _double_quantized_wire_hop),
]
