"""Negative controls for the HLO and COSTMODEL checkers.

Each target is a step/exchange program that traces cleanly and passes
the jaxpr-level checkers, but whose LOWERED form betrays it: the halo
exchange has fallen off the collective-permute fast path (an
accidental all-gather "fix" for mismatched out_specs, a psum smuggled
into the hot step), or it moves more bytes than its declared halo
geometry. All of these run happily on hardware — just at O(domain)
wire cost instead of O(halo) — which is precisely why the static pass
exists. ``python -m stencil_tpu.analysis tests/fixtures/lint/bad_hlo.py``
MUST exit nonzero.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from stencil_tpu.analysis import (CostModelSpec, CostModelTarget,
                                  HloSpec, HloTarget)
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel.exchange import (exchange_shard,
                                           exchanged_bytes_per_sweep)
from stencil_tpu.parallel.mesh import make_mesh


def _mismatched_out_specs() -> HloSpec:
    """The classic accident: the author wants the step's output
    replicated (out_specs drops the 'z' axis), "fixes" the shape
    mismatch by gathering the whole sharded field, and the halo
    exchange silently becomes an O(domain) all-gather."""
    mesh = make_mesh((1, 1, 2), jax.devices()[:2])

    def step(x):
        gathered = lax.all_gather(x, "z", axis=0, tiled=True)
        return gathered * 0.5

    sm = jax.shard_map(step, mesh=mesh, in_specs=P("z", None, None),
                       out_specs=P(None, None, None), check_vma=False)
    return HloSpec(fn=sm,
                   args=(jax.ShapeDtypeStruct((8, 8, 8), jnp.float32),))


def _psum_in_step() -> HloSpec:
    """A convergence check (global residual psum) left inside the hot
    step function: lowers to an all-reduce every iteration."""
    mesh = make_mesh((1, 1, 2), jax.devices()[:2])
    counts = Dim3(1, 1, 2)
    radius = Radius.constant(1)

    def step(x):
        x = exchange_shard(x, radius, counts)
        resid = lax.psum(jnp.sum(x * x), "z")
        return x * (1.0 / (1.0 + resid))

    sm = jax.shard_map(step, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    return HloSpec(fn=sm,
                   args=(jax.ShapeDtypeStruct((10, 10, 10),
                                              jnp.float32),))


def _moves_more_than_model() -> CostModelSpec:
    """A lowering/geometry drift: the program exchanges radius-2 slabs
    while the declared halo model says radius 1 — double the wire
    bytes of the contract. The analytic cross-check must flag it."""
    mesh = make_mesh((1, 1, 2), jax.devices()[:2])
    counts = Dim3(1, 1, 2)
    declared = Radius.constant(1)
    actually = Radius.constant(2)

    def step(x):
        return exchange_shard(x, actually, counts)

    sm = jax.shard_map(step, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    expected = sum(exchanged_bytes_per_sweep(
        (12, 12, 12), declared, counts, 4).values())
    return CostModelSpec(
        fn=sm, args=(jax.ShapeDtypeStruct((24, 12, 12), jnp.float32),),
        expected_bytes_per_shard=expected)


TARGETS = [
    HloTarget("fixture.allgather_via_mismatched_out_specs",
              _mismatched_out_specs),
    HloTarget("fixture.psum_in_step", _psum_in_step),
    CostModelTarget("fixture.exchange_moves_more_than_model",
                    _moves_more_than_model),
]
