"""Negative controls for the DMA-DISCIPLINE checker.

Each target is a Pallas kernel violating one remote-DMA invariant the
shipped kernels uphold. ``python -m stencil_tpu.analysis
tests/fixtures/lint/bad_dma.py`` MUST exit nonzero.

These kernels are TRACED, never executed, so they lint identically on
images without the distributed interpreter.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from stencil_tpu.analysis import PallasKernelSpec, PallasKernelTarget
from stencil_tpu.parallel.mesh import make_mesh

from jax.sharding import PartitionSpec as P


def _mesh2():
    return make_mesh((1, 1, 2), jax.devices()[:2])


def _spec(kern, n_sems: int = 2) -> PallasKernelSpec:
    def shard(p):
        return pl.pallas_call(
            kern,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            out_shape=jax.ShapeDtypeStruct(p.shape, p.dtype),
            scratch_shapes=[pltpu.SemaphoreType.DMA((n_sems,)),
                            pltpu.SemaphoreType.DMA((n_sems,))],
            compiler_params=pltpu.CompilerParams(
                collective_id=13, has_side_effects=True),
            interpret=False,
        )(p)

    mesh = _mesh2()
    sm = jax.shard_map(shard, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    return PallasKernelSpec(
        fn=sm, args=(jax.ShapeDtypeStruct((8, 8, 8), jnp.float32),),
        axis_names=("x", "y", "z"), expect_remote_dma=True)


def _other(n=2):
    me = lax.axis_index("z")
    return {"z": lax.rem(me + 1, jnp.int32(n))}


def _missing_wait() -> PallasKernelSpec:
    """Remote copy started, barrier correct, NEVER awaited: the kernel
    can retire (and its buffers be reused) with the DMA in flight."""

    def kern(in_ref, out_ref, send, recv):
        bsem = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(bsem, inc=1, device_id=_other())
        pltpu.semaphore_wait(bsem, 1)
        pltpu.make_async_remote_copy(
            src_ref=in_ref.at[0:1], dst_ref=out_ref.at[0:1],
            send_sem=send.at[0], recv_sem=recv.at[0],
            device_id=_other()).start()
        # BUG: no .wait()

    return _spec(kern)


def _missing_barrier() -> PallasKernelSpec:
    """Remote write with start/wait paired but NO neighbor rendezvous:
    the destination buffer is not known quiescent (unordered write —
    the race the sanitizer's negative control exhibits dynamically)."""

    def kern(in_ref, out_ref, send, recv):
        rc = pltpu.make_async_remote_copy(
            src_ref=in_ref.at[0:1], dst_ref=out_ref.at[0:1],
            send_sem=send.at[0], recv_sem=recv.at[0],
            device_id=_other())
        rc.start()
        rc.wait()

    return _spec(kern)


def _reused_in_flight() -> PallasKernelSpec:
    """The same semaphore cells re-armed by a second remote copy while
    the first is still in flight."""

    def kern(in_ref, out_ref, send, recv):
        bsem = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(bsem, inc=1, device_id=_other())
        pltpu.semaphore_wait(bsem, 1)

        def copy(rows):
            return pltpu.make_async_remote_copy(
                src_ref=in_ref.at[rows], dst_ref=out_ref.at[rows],
                send_sem=send.at[0], recv_sem=recv.at[0],
                device_id=_other())

        a = copy(slice(0, 1))
        b = copy(slice(1, 2))   # BUG: same sems, first still flying
        a.start()
        b.start()
        a.wait()
        b.wait()

    return _spec(kern)


def _barrier_miscounted() -> PallasKernelSpec:
    """Rendezvous waits for 2 signals but only 1 is sent: the barrier
    can deadlock (or, reordered, pass before the neighbor arrived)."""

    def kern(in_ref, out_ref, send, recv):
        bsem = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(bsem, inc=1, device_id=_other())
        pltpu.semaphore_wait(bsem, 2)   # BUG: one signal, waits two
        rc = pltpu.make_async_remote_copy(
            src_ref=in_ref.at[0:1], dst_ref=out_ref.at[0:1],
            send_sem=send.at[0], recv_sem=recv.at[0],
            device_id=_other())
        rc.start()
        rc.wait()

    return _spec(kern)


TARGETS = [
    PallasKernelTarget("fixture.remote_dma_missing_wait", _missing_wait),
    PallasKernelTarget("fixture.remote_dma_missing_barrier",
                       _missing_barrier),
    PallasKernelTarget("fixture.semaphore_reused_in_flight",
                       _reused_in_flight),
    PallasKernelTarget("fixture.barrier_signal_wait_mismatch",
                       _barrier_miscounted),
]
