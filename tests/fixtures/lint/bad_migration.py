"""Negative control for the particle-migration communication contract.

The fixed-capacity migration's license to ride the hot loop is its
collective bill: one ``ppermute`` per direction per active axis,
moving exactly ``record_rows x budget`` elements — pinned by the
``parallel.migrate.migrate_shard[hlo]`` registry target. This fixture
is the tempting shortcut that breaks it: instead of ring-shifting each
direction's outbox to its one receiver, every shard ``all_gather``s
every outbox and picks its neighbor's rows locally — functionally
identical results, but the wire now carries every shard's outbox to
every device (the reference library's bench_alltoallv anti-pattern).
Sold under the shipped ppermute-only contract, the hlo checker must
flag it: ``python -m stencil_tpu.analysis
tests/fixtures/lint/bad_migration.py`` MUST exit nonzero.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from stencil_tpu.analysis import HloSpec, HloTarget

_BUDGET = 4
_CAP = 16


def _f32(shape):
    return jax.ShapeDtypeStruct(shape, jnp.float32)


def _allgather_migrate_spec() -> HloSpec:
    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("z", "y", "x"))

    def shard(q, valid, offx):
        # the bug: gather EVERY shard's +x outbox onto every device and
        # slice out the -1 neighbor's, instead of one ring ppermute
        name = "x"
        n = 2  # mesh axis size (static, like the shipped engine's)
        leave = valid & (offx == 1)
        order = jnp.argsort(jnp.where(leave, 0, 1))
        idx = order[:_BUDGET]
        buf = jnp.stack([q[idx], leave[idx].astype(q.dtype)])
        gath = lax.all_gather(buf, name, axis=0)  # (n, rows, budget)
        i = lax.axis_index(name)
        recv = gath[(i - 1) % n]
        inc_q = recv[0]
        inc_valid = recv[1] > 0.5
        valid = valid & ~leave
        free = jnp.argsort(valid)
        rank = jnp.cumsum(inc_valid) - 1
        ok = inc_valid & (rank < (_CAP - jnp.sum(valid)))
        slot = jnp.where(ok, free[jnp.clip(rank, 0, _CAP - 1)], _CAP)
        q = q.at[slot].set(inc_q, mode="drop")
        valid = valid.at[slot].set(True, mode="drop")
        return q, valid

    spec = P(("z", "y", "x"))
    sm = jax.shard_map(shard, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=(spec, spec), check_vma=False)
    n = 8 * _CAP
    valid = jax.ShapeDtypeStruct((n,), jnp.bool_)
    off = jax.ShapeDtypeStruct((n,), jnp.int32)
    # the shipped contract: migration lowers to collective-permute only
    return HloSpec(fn=sm, args=(_f32((n,)), valid, off),
                   allow=("collective_permute",))


TARGETS = [
    HloTarget("bad_migration.allgather_outbox[hlo]",
              _allgather_migrate_spec),
]
