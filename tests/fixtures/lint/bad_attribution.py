"""Negative control for the observatory attribution contract: a timer
that sneaks a host callback INTO the step program.

Attribution must be a host wall clock AROUND the dispatch
(``PerfAttributor.attributed`` returns the program unchanged — the
``observatory.attribution.*`` registry targets pin the HLO identity).
The tempting wrong implementation is to read the clock *inside* the
compiled step via a callback, which serializes every dispatch on a
host round-trip and changes the lowered program. Both spellings here —
a ``pure_callback`` timestamp folded into the output and an
``io_callback`` side-channel timer — must be flagged by the transfer
checker (nonzero CLI exit).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from stencil_tpu.analysis.transfer import TransferSpec, TransferTarget


def _arg():
    return jax.ShapeDtypeStruct((8, 8), jnp.float32)


def _pure_callback_timer_step() -> TransferSpec:
    def host_clock():
        return np.float32(time.perf_counter())

    def step(x):
        t = jax.pure_callback(
            host_clock, jax.ShapeDtypeStruct((), jnp.float32))
        y = x * 0.5
        # "attribute" the step by folding the timestamp into the
        # output so XLA cannot elide the callback
        return y + 0.0 * t

    return TransferSpec(fn=step, args=(_arg(),))


def _io_callback_timer_step() -> TransferSpec:
    samples = []

    def record(t):
        samples.append(float(t))

    def step(x):
        y = x * 0.5
        jax.experimental.io_callback(record, None, y[0, 0],
                                     ordered=True)
        return y

    return TransferSpec(fn=step, args=(_arg(),))


TARGETS = [
    TransferTarget("fixture.pure_callback_timer_in_step",
                   _pure_callback_timer_step),
    TransferTarget("fixture.io_callback_timer_in_step",
                   _io_callback_timer_step),
]
