"""Negative controls for the FOOTPRINT checker.

Each target is a deliberately broken stencil op whose true access
footprint exceeds its declared ``Radius`` — the "kernel silently reads
stale halo data" bug class. ``python -m stencil_tpu.analysis
tests/fixtures/lint/bad_footprint.py`` MUST exit nonzero, and
tests/test_lint.py asserts the specific findings.

The allocations are padded BEYOND the declaration (``pad_lo``/
``pad_hi`` overrides) so the broken reads trace cleanly — exactly the
production shape of the bug, where the buffer comes from a wider
allocator while the exchange plan ships only the declared radius.
"""

import jax
import jax.numpy as jnp
from jax import lax

from stencil_tpu.analysis import StencilOpSpec, StencilOpTarget
from stencil_tpu.geometry import Dim3, Radius


def _wide5_z_understated() -> StencilOpSpec:
    """5-point z stencil reaching +-2, declared ``Radius.constant(1)``:
    the exchange would fill one halo plane, the second plane is stale."""
    interior = Dim3(8, 8, 8)
    radius = Radius.constant(1)
    pad = Dim3(2, 2, 2)

    def fn(p):
        c = lax.slice(p, (2, 2, 2), (10, 10, 10))
        zm2 = lax.slice(p, (0, 2, 2), (8, 10, 10))
        zp2 = lax.slice(p, (4, 2, 2), (12, 10, 10))
        return (c + zm2 + zp2) * (1.0 / 3.0)

    return StencilOpSpec(
        fn=fn, args=(jax.ShapeDtypeStruct((12, 12, 12), jnp.float32),),
        radius=radius, interior=interior, pad_lo=pad, pad_hi=pad)


def _cross_zero_edge() -> StencilOpSpec:
    """Cross-derivative-style diagonal access (+x, +y) with face radius
    1 but edge radius 0: the per-axis slabs are delivered, the xy edge
    exchange is skipped, the corner cell is stale."""
    interior = Dim3(8, 8, 8)
    radius = Radius.face_edge_corner(1, 0, 0)

    def fn(p):
        c = lax.slice(p, (1, 1, 1), (9, 9, 9))
        diag = lax.slice(p, (1, 2, 2), (9, 10, 10))
        return c - diag

    return StencilOpSpec(
        fn=fn, args=(jax.ShapeDtypeStruct((10, 10, 10), jnp.float32),),
        radius=radius, interior=interior,
        pad_lo=Dim3(1, 1, 1), pad_hi=Dim3(1, 1, 1))


def _asymmetric_understated() -> StencilOpSpec:
    """Uncentered op reading 2 deep on -x but declaring only 1 there
    (asymmetric radii must be honored per side)."""
    interior = Dim3(8, 8, 8)
    radius = Radius.constant(0)
    radius.set_dir((1, 0, 0), 1)
    radius.set_dir((-1, 0, 0), 1)   # true reach is 2

    def fn(p):
        c = lax.slice(p, (0, 0, 2), (8, 8, 10))
        xm2 = lax.slice(p, (0, 0, 0), (8, 8, 8))
        return c + xm2

    return StencilOpSpec(
        fn=fn, args=(jax.ShapeDtypeStruct((8, 8, 12), jnp.float32),),
        radius=radius, interior=interior,
        pad_lo=Dim3(2, 0, 0), pad_hi=Dim3(2, 0, 0))


def _laundered_through_mul() -> StencilOpSpec:
    """The deep access happens on ``padded * 0.5``, not on the input
    directly — the alias must propagate through elementwise ops or
    this understated radius slips through."""
    interior = Dim3(8, 8, 8)
    radius = Radius.constant(1)
    pad = Dim3(2, 2, 2)

    def fn(p):
        q = p * 0.5
        c = lax.slice(q, (2, 2, 2), (10, 10, 10))
        yp2 = lax.slice(q, (2, 4, 2), (10, 12, 10))
        return c + yp2

    return StencilOpSpec(
        fn=fn, args=(jax.ShapeDtypeStruct((12, 12, 12), jnp.float32),),
        radius=radius, interior=interior, pad_lo=pad, pad_hi=pad)


TARGETS = [
    StencilOpTarget("fixture.wide5_z_radius_understated",
                    _wide5_z_understated),
    StencilOpTarget("fixture.cross_with_zero_edge_radius",
                    _cross_zero_edge),
    StencilOpTarget("fixture.asymmetric_minus_x_understated",
                    _asymmetric_understated),
    StencilOpTarget("fixture.laundered_through_elementwise",
                    _laundered_through_mul),
]
