"""Negative control for the transfer checker: step programs that
escape to the host every dispatch.

``fixture.debug_print_in_step`` is the one everybody ships at least
once — a ``jax.debug.print`` left in the hot loop (a host callback per
dispatch). ``fixture.pure_callback_in_step`` routes part of the step
through a Python callback, serializing the pipeline on the host.
"""

import jax
import jax.numpy as jnp
import numpy as np

from stencil_tpu.analysis.transfer import TransferSpec, TransferTarget


def _arg():
    return jax.ShapeDtypeStruct((8, 8), jnp.float32)


def _debug_print_step() -> TransferSpec:
    def step(x):
        jax.debug.print("step max {m}", m=x.max())
        return x * 0.5

    return TransferSpec(fn=step, args=(_arg(),))


def _pure_callback_step() -> TransferSpec:
    def host_filter(a):
        return np.asarray(a) * 2.0

    def step(x):
        y = jax.pure_callback(
            host_filter, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y + 1.0

    return TransferSpec(fn=step, args=(_arg(),))


TARGETS = [
    TransferTarget("fixture.debug_print_in_step", _debug_print_step),
    TransferTarget("fixture.pure_callback_in_step",
                   _pure_callback_step),
]
