"""Negative controls for the SCHEDULE-CERTIFICATION checker.

Each target is a Pallas kernel whose semaphore schedule is unsound
under k-fold replay — exactly the programs megastep fusion must never
be licensed for. ``python -m stencil_tpu.analysis
tests/fixtures/lint/bad_schedule.py`` MUST exit nonzero, naming the
violated condition (in-flight aliasing vs deadlock cycle).

These kernels are TRACED, never executed, so they lint identically on
images without the distributed interpreter.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from stencil_tpu.analysis import ScheduleSpec, ScheduleTarget
from stencil_tpu.parallel.mesh import make_mesh

from jax.sharding import PartitionSpec as P


def _mesh2():
    return make_mesh((1, 1, 2), jax.devices()[:2])


def _spec(kern, n_sems: int = 2) -> ScheduleSpec:
    def shard(p):
        return pl.pallas_call(
            kern,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            out_shape=jax.ShapeDtypeStruct(p.shape, p.dtype),
            scratch_shapes=[pltpu.SemaphoreType.DMA((n_sems,)),
                            pltpu.SemaphoreType.DMA((n_sems,))],
            compiler_params=pltpu.CompilerParams(
                collective_id=13, has_side_effects=True),
            interpret=False,
        )(p)

    mesh = _mesh2()
    sm = jax.shard_map(shard, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    return ScheduleSpec(
        fn=sm, args=(jax.ShapeDtypeStruct((8, 8, 8), jnp.float32),),
        axis_names=("x", "y", "z"), expect_remote_dma=True)


def _other(n=2):
    me = lax.axis_index("z")
    return {"z": lax.rem(me + 1, jnp.int32(n))}


def _slot_reuse_under_replay() -> ScheduleSpec:
    """One launch looks almost disciplined (the recv side is waited),
    but the SEND semaphore is still in flight at kernel end — replay
    i+1 re-arms the same slot while replay i's copy flies: the
    in-flight aliasing a fused multi-launch segment would hit."""

    def kern(in_ref, out_ref, send, recv):
        bsem = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(bsem, inc=1, device_id=_other())
        pltpu.semaphore_wait(bsem, 1)
        rc = pltpu.make_async_remote_copy(
            src_ref=in_ref.at[0:1], dst_ref=out_ref.at[0:1],
            send_sem=send.at[0], recv_sem=recv.at[0],
            device_id=_other())
        rc.start()
        rc.wait_recv()
        # BUG: no wait_send — the send slot is armed across the
        # sub-step boundary

    return _spec(kern)


def _wait_cycle_deadlock() -> ScheduleSpec:
    """Two-shard rendezvous wait-cycle: every shard WAITS for its
    neighbor's signal BEFORE signaling — under SPMD symmetry both
    block forever (the circular cross-shard wait the certifier must
    refuse to license)."""

    def kern(in_ref, out_ref, send, recv):
        bsem = pltpu.get_barrier_semaphore()
        # BUG: wait precedes the only signal that could satisfy it
        pltpu.semaphore_wait(bsem, 1)
        pltpu.semaphore_signal(bsem, inc=1, device_id=_other())
        rc = pltpu.make_async_remote_copy(
            src_ref=in_ref.at[0:1], dst_ref=out_ref.at[0:1],
            send_sem=send.at[0], recv_sem=recv.at[0],
            device_id=_other())
        rc.start()
        rc.wait()

    return _spec(kern)


TARGETS = [
    ScheduleTarget("fixture.schedule_slot_reuse_under_replay",
                   _slot_reuse_under_replay),
    ScheduleTarget("fixture.schedule_wait_cycle_deadlock",
                   _wait_cycle_deadlock),
]
