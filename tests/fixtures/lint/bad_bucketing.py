"""Negative control for the fleet bucketing contract: admission paths
that leak the per-request grid into the jit signature, so every
distinct user grid forks the compile cache — the unbounded-engine-
cache hazard grid bucketing exists to prevent.

``fixture.bucketing.shape_drift`` "buckets" by padding INSIDE the
jitted step instead of before admission: the carried output is
bucket-shaped while the input is the raw user grid, so the abstract
fingerprint drifts and the second dispatch re-traces (and the real
engine cache would hold one executable per user grid).
``fixture.bucketing.grid_scalar_arg`` threads the grid extent through
as a bare Python scalar — every distinct grid value forks the jit
cache exactly like an unbucketed shape would.
"""

import jax
import jax.numpy as jnp

from stencil_tpu.analysis.recompile import RecompileSpec, RecompileTarget

#: the declared bucket edge and a user grid strictly inside it
_BUCKET = 8
_USER = 5


def _shape_drift() -> RecompileSpec:
    # pad-to-bucket INSIDE the compiled step: input is user-shaped,
    # carried output is bucket-shaped — aval drift, retrace per step
    fn = jax.jit(lambda x: jnp.pad(
        x * 0.5, ((0, _BUCKET - _USER),) * 2))
    arg = jax.ShapeDtypeStruct((_USER, _USER), jnp.float32)
    return RecompileSpec(fn=fn, args=(arg,), carry=((0, None),))


def _grid_scalar_arg() -> RecompileSpec:
    # the grid extent as a Python int in the signature: weak-typed
    # trace, one cache entry per distinct user grid
    fn = jax.jit(lambda x, n: x * (1.0 / n))
    arg = jax.ShapeDtypeStruct((_BUCKET, _BUCKET), jnp.float32)
    return RecompileSpec(fn=fn, args=(arg, _USER), carry=((0, None),))


TARGETS = [
    RecompileTarget("fixture.bucketing.shape_drift", _shape_drift),
    RecompileTarget("fixture.bucketing.grid_scalar_arg",
                    _grid_scalar_arg),
]
