"""Negative control for the segment compiler's carry contract: a PIC
fused segment whose contract DROPS the migration-overflow column.

The carry contract (``parallel/megastep.CarryContract``) is the whole
point of the segment compiler: the model declares what the fused
probe rows carry, and the sentinel decodes exactly those columns.
The broken contract here probes rho + the particle lanes but forgets
``probe_extra`` — migration overflow silently VANISHES from the
in-graph trace, so a fleet fusing this segment would never see
capacity-exceeded particle drops (the overflow counter still
accumulates in the carry, but no probe row reports it). The
``models.pic.segment[k=4,probe]``-style byte pin must flag it: each
trace row's single all-reduce now moves (2, 8) f32 instead of the
contract's (2, 9) — 128 B/row against the declared 144 B/row bill.
"""

import dataclasses

from stencil_tpu.analysis.costmodel import CostModelSpec, CostModelTarget
from stencil_tpu.models.pic import Pic
from stencil_tpu.parallel.megastep import (SegmentCompiler,
                                           metric_base_vec)

K = 4
PROBE_EVERY = 2
#: the SHIPPED contract's probe bill: rho + 7 particle lanes + the
#: overflow column = (2, 9) f32 per row, 2 rows for k=4/probe_every=2
ROWS = -(-K // PROBE_EVERY)
CONTRACT_COLS = 9


def _bad_segment_spec() -> CostModelSpec:
    eng = Pic(16, 16, 16, 64, mesh_shape=(2, 2, 2), capacity=32,
              budget=8)
    # the bug: the carry contract loses its probe_extra — the overflow
    # column is dropped from every trace row
    contract = dataclasses.replace(eng.segment_contract(),
                                   probe_extra=None)
    builder = SegmentCompiler(
        eng.dd.mesh, contract, lambda st, c, i: eng._shard_step(st),
        lambda: dict(eng.state), eng._adopt, use_metrics=False)
    seg = builder(K, probe_every=PROBE_EVERY)
    return CostModelSpec(
        fn=seg.fn,
        args=(dict(eng.state),
              metric_base_vec(None, 0, mesh=eng.dd.mesh)),
        expected_bytes_per_shard=ROWS * 2 * CONTRACT_COLS * 4,
        count_kinds=("all_reduce",))


TARGETS = [
    CostModelTarget("fixture.pic.segment_carry_drops_overflow[probe]",
                    _bad_segment_spec),
]
