"""utils.naming.glob_match: glob matching with literal-bracket
tolerance.

Candidate keys and bench ids carry ``[...]`` (``PpermuteSlab[s=1.1.4]``,
``observatory.linkmap.hierarchical[dcn]``), which raw fnmatch reads as
a character class — so ``bench_exchange --targets`` and the ledger
``--bench`` filter route through glob_match, which retries with the
bracket escaped. These tests pin both readings."""

import pytest

from stencil_tpu.utils.naming import glob_match


def test_exact_match_always_passes():
    assert glob_match("PpermuteSlab[s=1.1.4]", "PpermuteSlab[s=1.1.4]")
    assert glob_match("plain", "plain")
    assert not glob_match("plain", "other")


def test_raw_fnmatch_still_works():
    # patterns without brackets behave exactly like fnmatch
    assert glob_match("bench_exchange.megastep", "bench_exchange*")
    assert glob_match("observatory.linkmap.hierarchical",
                      "observatory.linkmap.*")
    assert not glob_match("pic", "bench_*")
    # a pattern whose character class genuinely matches keeps working
    assert glob_match("a1", "a[0-9]")


def test_bracketed_names_match_bracketed_patterns():
    # raw fnmatch would read [s=1.1.4] as a character class and fail;
    # glob_match retries with the bracket escaped
    assert glob_match("PpermuteSlab[s=2]", "*[s=2]")
    assert glob_match("observatory.linkmap.hierarchical[dcn]",
                      "observatory.linkmap.hierarchical[dcn]")
    assert glob_match("observatory.linkmap.hierarchical[dcn]",
                      "*hierarchical[dcn]")
    assert glob_match("PpermuteSlab[s=1.1.4]", "PpermuteSlab[s=*]")
    assert not glob_match("PpermuteSlab[s=2]", "*[s=4]")
    assert not glob_match("PpermuteSlab[s=2]", "AllGather[s=2]")


@pytest.mark.parametrize("name,pattern,expected", [
    ("bench_exchange[s=1.1.4]", "bench_exchange[s=1.1.4]", True),
    ("bench_exchange[s=1.1.4]", "*[s=1.1.4]", True),
    ("bench_exchange", "bench_exchange[s=*]", False),
    ("x[a]y", "x[a]*", True),
])
def test_bracket_tolerance_table(name, pattern, expected):
    assert glob_match(name, pattern) is expected
