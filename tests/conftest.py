"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's strategy of simulating multi-GPU / multi-node
without a cluster (SURVEY.md section 4): the reference oversubscribes one
GPU (test/test_exchange.cu:52 `dd.set_gpus({0,0})`); we fake an 8-device
mesh on CPU via XLA_FLAGS. Must run before jax is imported — a
sitecustomize in this image forces JAX_PLATFORMS=axon, so we override it
here rather than in the shell environment.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
