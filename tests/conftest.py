"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's strategy of simulating multi-GPU / multi-node
without a cluster (SURVEY.md section 4): the reference oversubscribes one
GPU (test/test_exchange.cu:52 `dd.set_gpus({0,0})`); we fake an 8-device
mesh on CPU via XLA_FLAGS.

Note: a sitecustomize in this image imports jax at interpreter startup
with JAX_PLATFORMS=axon, so env vars are too late here — but the XLA
backend initializes lazily, so `jax.config.update` still takes effect as
long as no test module touched a device yet.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy interpret-mode Pallas parity tests (minutes each). "
        "The smoke tier (ci/run_ci.sh default) runs -m 'not slow'; the "
        "full tier and a bare pytest run everything.")
