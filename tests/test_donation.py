"""Buffer-donation proof: the jitted step loops and the exchange
orchestrator must alias their curr/next buffers in the compiled HLO
(``input_output_alias``), so the double-buffer swap costs no HBM copy.

Donation silently disappears when a refactor re-wraps a jitted
function without ``donate_argnums`` — these tests pin the aliasing at
the compiled-HLO level on the CPU backend (the alias map is a
lowering-level property; the CPU runtime may still copy, but the
contract XLA:TPU consumes is exactly this annotation).

The alias-map parser is library code now —
:func:`stencil_tpu.analysis.donation.alias_param_ids` — shared with
the donation checker (``python -m stencil_tpu.analysis --only
donation``), which audits every registered entry point in CI; these
tests keep the direct, readable proofs and exercise the same parser.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from stencil_tpu.analysis.donation import (alias_param_ids,
                                           compiled_alias_ids)
from stencil_tpu.models.jacobi import Jacobi3D


def _alias_param_ids(compiled) -> set:
    """Aliased entry-parameter numbers of a compiled program, via the
    analysis library's single parser."""
    ids = alias_param_ids(compiled.as_text())
    assert ids, "no input_output_alias in compiled HLO"
    return ids


def test_jacobi_step_loop_donates_field_buffer():
    j = Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float32,
                 kernel="xla")
    arr = j.dd.curr["temp"]
    compiled = j._step_n.lower(arr, jnp.asarray(2, jnp.int32)).compile()
    ids = _alias_param_ids(compiled)
    assert 0 in ids, "temp field buffer (arg 0) lost its donation"


def test_jacobi_temporal_step_loop_donates_field_buffer():
    """The temporal-blocking loop must keep the donation."""
    j = Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float32,
                 kernel="xla", exchange_every=2)
    assert j.kernel_path == "xla-temporal[s=2]"
    arr = j.dd.curr["temp"]
    ids = compiled_alias_ids(j._step_n, (arr, jnp.asarray(2, jnp.int32)))
    assert 0 in ids


def test_exchange_orchestrator_donates_every_field():
    """make_exchange donates its whole field dict: each quantity's
    halo fill aliases in place instead of copying the padded global."""
    from stencil_tpu.distributed import DistributedDomain

    dd = DistributedDomain(16, 16, 16)
    dd.set_mesh_shape((2, 2, 2))
    dd.set_radius(1)
    dd.add_data("a", np.float32)
    dd.add_data("b", np.float32)
    dd.realize()
    ids = compiled_alias_ids(dd._exchange_fn, (dd.curr,))
    assert ids == {0, 1}, f"expected both fields donated, got {ids}"


def test_astaroth_iteration_donates_fields_and_w():
    import jax

    from stencil_tpu.models.astaroth import Astaroth
    from stencil_tpu.parallel.methods import Method

    a = Astaroth(8, 8, 8, mesh_shape=(1, 1, 2),
                 devices=jax.devices()[:2], dtype=np.float32,
                 kernel="xla", methods=Method.PpermuteSlab)
    a._ensure_w()
    ids = compiled_alias_ids(a._iter_n,
                             (a.dd.curr, a._w, jnp.asarray(1, jnp.int32)))
    # 8 fields + 8 w accumulators donated; the iteration count is not
    assert ids == set(range(16)), ids


def test_megastep_segment_donates_field_buffer():
    """The fused campaign segment (parallel/megastep.py) must alias
    its field state end-to-end: a k-step megastep costs no more HBM
    than one step."""
    from stencil_tpu.parallel.megastep import metric_base_vec
    from stencil_tpu.telemetry.probe import StepMetrics

    j = Jacobi3D(16, 16, 16, mesh_shape=(2, 2, 2), dtype=np.float32,
                 kernel="xla")
    j.init()
    m = StepMetrics(j.dd)
    seg = j.make_segment(4, probe_every=2, metrics=m)
    assert seg is not None and seg.fn is not None
    vec = metric_base_vec(m, 0, mesh=j.dd.mesh)
    ids = compiled_alias_ids(seg.fn, (j.dd.curr["temp"], vec))
    assert 0 in ids, "megastep lost its field-buffer donation"


def test_domain_megastep_donates_every_field():
    """The generic DistributedDomain.make_segment donates the WHOLE
    field dict — every quantity's buffer aliases in place."""
    from stencil_tpu.distributed import DistributedDomain
    from stencil_tpu.geometry import Radius
    from stencil_tpu.parallel.exchange import exchange_shard
    from stencil_tpu.parallel.megastep import metric_base_vec
    from stencil_tpu.parallel.mesh import mesh_dim

    dd = DistributedDomain(16, 16, 16)
    dd.set_mesh_shape((2, 2, 2))
    dd.set_radius(1)
    dd.add_data("a", np.float32)
    dd.add_data("b", np.float32)
    dd.realize()
    counts = mesh_dim(dd.mesh)
    radius = Radius.constant(1)

    def shard_step(fields):
        return {q: exchange_shard(p, radius, counts)
                for q, p in fields.items()}

    seg = dd.make_segment(shard_step, check_every=2)
    fn = seg.fn
    vec = metric_base_vec(None, 0, mesh=dd.mesh)
    ids = compiled_alias_ids(fn, (dict(dd.curr), vec))
    assert {0, 1} <= ids, f"expected both fields donated, got {ids}"


def test_alias_parser_handles_nested_braces():
    """The alias map body nests braces ({0} output indices, {} param
    index lists); the parser walks them balanced — a non-greedy regex
    would stop at the first '}' and report an empty map. Also holds
    without the usual ', entry' suffix after the attribute."""
    text = ("HloModule m, "
            "input_output_alias={ {0}: (0, {}, may-alias), "
            "{1}: (2, {}, must-alias) }\nENTRY ...")
    assert alias_param_ids(text) == {0, 2}


def test_donation_checker_maps_through_dropped_params():
    """jit's default keep_unused=False drops unused inputs from the
    executable and renumbers the alias map; the checker must map its
    flat-leaf expectations through the kept-parameter order, so a
    correctly-donated arg AFTER an unused one audits clean — and a
    donated arg the program never consumes is its own finding."""
    import jax

    from stencil_tpu.analysis import DonationSpec, DonationTarget
    from stencil_tpu.analysis.donation import check_donation

    fn = jax.jit(lambda unused, x: x + 1.0, donate_argnums=(1,))
    args = (jnp.zeros((3,), jnp.float32), jnp.zeros((4,), jnp.float32))
    t = DonationTarget("unit.dropped_param",
                       lambda: DonationSpec(fn=fn, args=args,
                                            donate_argnums=(1,)))
    findings, metrics = check_donation(t)
    assert findings == [], [str(f) for f in findings]
    # the dropped-parameter case: declaring the UNUSED arg donated is
    # a dead contract, reported as such
    t2 = DonationTarget("unit.donated_unused",
                        lambda: DonationSpec(fn=fn, args=args,
                                             donate_argnums=(0,)))
    findings, _ = check_donation(t2)
    assert findings and "UNUSED by the compiled program" in \
        findings[0].message


def test_alias_parser_empty_on_alias_free_program():
    """The promoted parser returns the empty set (never raises) on a
    compiled program with no alias map — the donation checker turns
    that into its donated-but-copied ERROR."""
    import jax

    fn = jax.jit(lambda x: x + 1.0)
    compiled = fn.lower(jnp.zeros((4,), jnp.float32)).compile()
    assert alias_param_ids(compiled.as_text()) == set()


def test_donated_exchange_invalidates_input():
    """The donation is real: reusing the donated input raises."""
    from stencil_tpu.distributed import DistributedDomain

    dd = DistributedDomain(16, 16, 16)
    dd.set_mesh_shape((2, 2, 2))
    dd.set_radius(1)
    dd.add_data("q", np.float32)
    dd.realize()
    old = dd.curr["q"]
    dd.exchange()
    if old.is_deleted():
        with pytest.raises(RuntimeError):
            np.asarray(old)
    else:
        # backends without donation support (plain CPU) keep the buffer
        # alive — the aliasing contract is still pinned above
        np.asarray(old)
