"""utils/retry: bounded retry + exponential backoff, fake-clocked."""

import pytest

from stencil_tpu.utils.retry import retry


class FakeClock:
    def __init__(self):
        self.delays = []

    def sleep(self, s):
        self.delays.append(s)


def flaky(failures, exc=OSError):
    """A callable that raises ``exc`` for its first ``failures`` calls."""
    state = {"calls": 0}

    def fn():
        state["calls"] += 1
        if state["calls"] <= failures:
            raise exc(f"boom {state['calls']}")
        return state["calls"]

    fn.state = state
    return fn


def test_success_first_try_never_sleeps():
    clock = FakeClock()
    assert retry(lambda: 42, attempts=3, sleep=clock.sleep) == 42
    assert clock.delays == []


def test_exponential_backoff_delays():
    clock = FakeClock()
    fn = flaky(2)
    assert retry(fn, attempts=3, base_delay=0.5, sleep=clock.sleep) == 3
    assert clock.delays == [0.5, 1.0]  # base * 2**k


def test_exhausted_attempts_raise_last_error():
    clock = FakeClock()
    fn = flaky(5)
    with pytest.raises(OSError, match="boom 3"):
        retry(fn, attempts=3, base_delay=0.1, sleep=clock.sleep)
    assert fn.state["calls"] == 3
    assert clock.delays == [0.1, 0.2]  # no sleep after the final failure


def test_non_retriable_propagates_immediately():
    clock = FakeClock()
    fn = flaky(1, exc=ValueError)
    with pytest.raises(ValueError):
        retry(fn, attempts=5, sleep=clock.sleep)
    assert fn.state["calls"] == 1
    assert clock.delays == []


def test_on_retry_callback_sees_each_failure():
    seen = []
    fn = flaky(2)
    retry(fn, attempts=3, base_delay=1.0, sleep=lambda s: None,
          on_retry=lambda k, e, d: seen.append((k, str(e), d)))
    assert [(k, d) for k, _, d in seen] == [(1, 1.0), (2, 2.0)]
    assert "boom 1" in seen[0][1]


def test_attempts_must_be_positive():
    with pytest.raises(ValueError):
        retry(lambda: 1, attempts=0)


def test_tuning_cache_store_retries_transient_replace(tmp_path,
                                                     monkeypatch):
    """A transient os.replace failure must not lose a measured plan."""
    import os as os_mod

    from stencil_tpu.tuning import cache as cache_mod
    from stencil_tpu.tuning.plan import Candidate, Plan

    monkeypatch.setattr(cache_mod, "_RETRY_SLEEP", lambda s: None)
    real_replace = os_mod.replace
    state = {"calls": 0}

    def flaky_replace(src, dst):
        state["calls"] += 1
        if state["calls"] == 1:
            raise OSError("injected transient rename failure")
        return real_replace(src, dst)

    monkeypatch.setattr(cache_mod.os, "replace", flaky_replace)
    plan = Plan(config=Candidate("PpermuteSlab", 1, False),
                fingerprint="f" * 64, coefficients={}, costs={},
                provenance="tuned", measurements=1)
    p = cache_mod.store_plan(plan, tmp_path / "plans.json")
    assert state["calls"] == 2
    got = cache_mod.load_plan("f" * 64, p)
    assert got is not None and got.config.method == "PpermuteSlab"
