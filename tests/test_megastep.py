"""Megastep: whole-campaign fused segments (parallel/megastep.py).

The ISSUE 8 acceptance contract: a ``check_every=k`` segment compiles
to ONE program that is numerically indistinguishable from the stepwise
loop (bitwise for Jacobi — periodic AND zero-Dirichlet, even AND
uneven partitions; accumulator-carrying ~1-ULP for Astaroth), carries
the per-step health probe in-graph so the driver can locate the exact
tripped step, donates its state end-to-end, and passes the same
registry gates as the stepwise path (exact collective counts, exact
bytes, negative control flagged).
"""

import subprocess
import sys
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

from stencil_tpu._compat import remote_dma_runnable
from stencil_tpu.models.jacobi import Jacobi3D
from stencil_tpu.parallel.megastep import (MAX_UNROLL, probe_rel_steps,
                                           segment_chunks)

N = 16
BAD_FIXTURE = Path(__file__).parent / "fixtures" / "lint" / \
    "bad_megastep.py"


def make_jacobi(**kw):
    kw.setdefault("mesh_shape", (2, 2, 2))
    kw.setdefault("dtype", np.float32)
    kw.setdefault("kernel", "xla")
    j = Jacobi3D(kw.pop("x", N), kw.pop("y", N), kw.pop("z", N), **kw)
    j.init()
    return j


# ----------------------------------------------------------------------
# segmentation helpers
# ----------------------------------------------------------------------
def test_segment_chunks_and_probe_points():
    assert segment_chunks(5) == [1] * 5
    assert segment_chunks(7, stride=3) == [3, 3, 1]
    assert probe_rel_steps([1] * 6, 2) == (2, 4, 6)
    # the final step is ALWAYS probed, cadence or not
    assert probe_rel_steps([1] * 5, 2) == (2, 4, 5)
    assert probe_rel_steps([3, 3, 1], 1) == (3, 6, 7)
    assert MAX_UNROLL >= 16


# ----------------------------------------------------------------------
# fused == stepwise, bitwise (jacobi)
# ----------------------------------------------------------------------
def _compare_jacobi(steps=8, seg=None, **kw):
    a = make_jacobi(**kw)
    b = make_jacobi(**kw)
    for _ in range(steps):
        a.step()
    done = 0
    while done < steps:
        k = min(seg or steps, steps - done)
        s = b.make_segment(k)
        assert s is not None and s.steps == k
        s.run(done)
        done += k
    np.testing.assert_array_equal(a.temperature(), b.temperature())


def test_jacobi_segment_bitwise_periodic():
    _compare_jacobi(steps=8, seg=4)


def test_jacobi_segment_bitwise_uneven_partitions():
    _compare_jacobi(steps=6, seg=3, x=17, y=17, z=17)


def test_jacobi_segment_bitwise_boundary_none():
    from stencil_tpu.topology import Boundary
    _compare_jacobi(steps=6, seg=3, boundary=Boundary.NONE)


def test_jacobi_segment_bitwise_uneven_none():
    from stencil_tpu.topology import Boundary
    _compare_jacobi(steps=5, seg=2, x=17, y=17, z=17,
                    boundary=Boundary.NONE)


def test_jacobi_temporal_segment_bitwise():
    """exchange_every=2: the fused segment advances whole temporal
    groups plus depth-1 tails, bitwise-equal to the blocked loop."""
    a = make_jacobi(exchange_every=2)
    assert a.kernel_path == "xla-temporal[s=2]"
    b = make_jacobi(exchange_every=2)
    a.run(7)
    s = b.make_segment(7)
    # 3 groups of 2 + 1 tail step, probed per chunk
    assert s.probe_steps == (2, 4, 6, 7)
    s.run(0)
    np.testing.assert_array_equal(a.temperature(), b.temperature())


def test_wrap_path_segment_bitwise():
    """The single-chip Pallas wrap path fuses: segment chunks mirror
    run(n)'s N-step in-kernel groups + single-step tail, bitwise."""
    import jax

    def mk():
        j = Jacobi3D(16, 16, 16, mesh_shape=(1, 1, 1),
                     devices=jax.devices()[:1], dtype=np.float32,
                     kernel="wrap")
        j.init()
        return j

    a, b = mk(), mk()
    a.run(5)
    seg = b.make_segment(5)
    assert seg and seg.steps == 5
    # N=2 in-kernel groups + a single-step tail, probed per chunk
    assert seg.probe_steps == (2, 4, 5)
    seg.run(0)
    np.testing.assert_array_equal(a.temperature(), b.temperature())


def test_halo_path_segment_bitwise():
    """The multi-device Pallas halo path fuses: each segment chunk is
    one temporally-blocked kernel launch (slab exchange inside),
    bitwise-equal to the fused run loop."""
    import jax

    def mk():
        j = Jacobi3D(16, 16, 16, mesh_shape=(1, 2, 2),
                     devices=jax.devices()[:4], dtype=np.float32,
                     kernel="halo")
        j.init()
        return j

    a, b = mk(), mk()
    assert a.kernel_path == "halo"
    a.run(5)
    seg = b.make_segment(5)
    assert seg and seg.probe_steps == (2, 4, 5)
    seg.run(0)
    np.testing.assert_array_equal(a.temperature(), b.temperature())


def _make_overlap_jacobi():
    import jax

    j = Jacobi3D(16, 16, 16, mesh_shape=(1, 2, 2),
                 devices=jax.devices()[:4], dtype=np.float32,
                 kernel="halo", overlap=True)
    j.init()
    assert j.kernel_path == "overlap"
    return j


def test_overlap_path_fuses_under_certificate():
    """The in-kernel RDMA overlap path FUSES: the schedule certifier
    (analysis/schedule.py) proves the kernel's semaphore schedule
    replay-safe — four face slabs, every slot drained per launch —
    and make_segment consumes the certificate into a real Segment.
    Traced only here; execution is covered by the capability-gated
    bitwise test below."""
    j = _make_overlap_jacobi()
    seg = j.make_segment(4)
    assert seg and seg.steps == 4
    cert = j._schedule_certificate
    assert cert is not None and cert.replay_safe is True
    assert cert.max_in_flight == 4 and not cert.reasons


def test_overlap_path_declines_on_unsafe_certificate(monkeypatch):
    """replay_safe=False gates fusion OFF: make_segment returns a
    falsy SegmentDecline quoting the certificate's reasons[] under
    the uncertified-rdma-schedule code — never a silent None. (The
    certificate memo keys on the certifier's identity, so the
    monkeypatched verdict is never shadowed by a cached one.)"""
    from stencil_tpu.analysis import schedule as schedule_checker
    from stencil_tpu.parallel.megastep import (
        DECLINE_UNCERTIFIED_SCHEDULE, SegmentDecline)

    def unsafe(fn, args, axis_names=(), replay=4):
        return schedule_checker.ScheduleCertificate(
            kernel="jacobi7_overlap", replay=replay, max_in_flight=9,
            replay_safe=False,
            reasons=["in-flight aliasing across sub-steps"])

    monkeypatch.setattr(schedule_checker, "certify_traceable", unsafe)
    j = _make_overlap_jacobi()
    d = j.make_segment(4)
    assert not d and isinstance(d, SegmentDecline)
    assert d.model == "jacobi" and d.path == "overlap"
    assert d.code == DECLINE_UNCERTIFIED_SCHEDULE
    assert "uncertified RDMA schedule" in d.reason
    assert "in-flight aliasing across sub-steps" in d.reason


@pytest.mark.skipif(
    not remote_dma_runnable(),
    reason="Pallas remote DMA needs a TPU backend or the distributed "
           "(mosaic) TPU interpreter")
def test_overlap_segment_bitwise():
    """Certificate-gated fused RDMA segment == stepwise, bitwise: the
    k launches fused into one program carry exactly the per-launch
    semaphore drain the certificate proved."""
    a, b = _make_overlap_jacobi(), _make_overlap_jacobi()
    a.run(4)
    seg = b.make_segment(4)
    assert seg and seg.steps == 4
    seg.run(0)
    np.testing.assert_array_equal(a.temperature(), b.temperature())


def test_decline_reason_vocabulary():
    """The decline_reason vocabulary is pinned: fused:false events and
    the flight-recorder timeline are greppable by CAUSE, and decline()
    refuses codes outside the set."""
    from stencil_tpu.parallel import megastep as ms

    assert ms.DECLINE_REASONS == frozenset({
        "no-fused-builder", "uncertified-rdma-schedule",
        "interior-resident-state", "policy-disabled",
        "no-segment-factory", "rebuild-no-segment-factory",
    })
    d = ms.decline("jacobi", "xla", "free-form prose")
    assert d.code == ms.DECLINE_NO_BUILDER  # the default
    d = ms.decline("jacobi", "overlap", "gate said no",
                   code=ms.DECLINE_UNCERTIFIED_SCHEDULE)
    assert not d and d.code == "uncertified-rdma-schedule"
    with pytest.raises(ValueError, match="unknown decline code"):
        ms.decline("jacobi", "xla", "typo", code="not-a-real-code")


def test_astaroth_fast_path_declines_loudly():
    """The interior-resident MHD fast paths decline with the
    extract/loop/insert reason (their state lives outside dd.curr)."""
    import jax

    from stencil_tpu.models.astaroth import Astaroth
    from stencil_tpu.parallel.megastep import SegmentDecline

    a = Astaroth(16, 16, 16, mesh_shape=(1, 1, 1),
                 devices=jax.devices()[:1], dtype=np.float32,
                 kernel="wrap")
    d = a.make_segment(2)
    assert not d and isinstance(d, SegmentDecline)
    assert d.model == "astaroth" and d.path == "wrap"
    assert "extract/loop/insert" in d.reason


# ----------------------------------------------------------------------
# the in-graph probe trace
# ----------------------------------------------------------------------
def test_segment_trace_rows_and_metrics():
    from stencil_tpu.telemetry.probe import StepMetrics

    j = make_jacobi()
    m = StepMetrics(j.dd)
    seg = j.make_segment(6, probe_every=2, metrics=m)
    tr = seg.run(10)
    assert tr.steps == (2, 4, 6)
    assert tr.abs_steps == [12, 14, 16]
    host = np.asarray(tr.array)
    # columns: temp, substeps, wire_bytes; rows replicated f32
    assert host.shape == (3, 2, 3)
    np.testing.assert_array_equal(host[:, 0, 1], [12.0, 14.0, 16.0])
    np.testing.assert_allclose(
        host[:, 0, 2],
        [m.cumulative_bytes(s) for s in (12, 14, 16)], rtol=1e-6)
    # health columns are real: nonfinite 0, max-abs 1 (hot sphere)
    assert host[0, 0, 0] == 0.0
    assert host[0, 1, 0] == pytest.approx(1.0)


def test_sentinel_locates_exact_tripped_step_in_trace():
    """A NaN planted mid-segment: the trace row of ITS step trips, with
    earlier rows clean — the driver learns the exact step without
    replaying the segment."""
    from stencil_tpu.resilience.health import HealthSentinel

    j = make_jacobi()
    s = HealthSentinel(j.dd)
    clean = j.dd.curr["temp"]
    rows = []
    for i in range(4):
        p = clean if i < 2 else clean.at[3, 3, 3].set(float("nan"))
        rows.append(jnp.stack([
            jnp.stack([jnp.sum(~jnp.isfinite(p)).astype(jnp.float32)]),
            jnp.stack([jnp.max(jnp.abs(jnp.nan_to_num(p)))]),
        ]))
    s.observe_segment(jnp.stack(rows), steps=[5, 6, 7, 8])
    results = s.poll(block=True)
    assert [r.step for r in results] == [5, 6, 7, 8]
    assert [r.tripped for r in results] == [False, False, True, True]
    assert s.tripped.step == 7


def test_driver_fused_equals_stepwise(tmp_path):
    """run_resilient fused (default) vs fuse_segments=False: identical
    final state, identical checkpoint trail."""
    from stencil_tpu.resilience import ResiliencePolicy

    def pol(fused):
        return ResiliencePolicy(check_every=3, ckpt_every=4,
                                base_delay=0.0, sleep=lambda s: None,
                                fuse_segments=fused)

    a = make_jacobi()
    ra = a.run_resilient(10, policy=pol(True),
                         ckpt_dir=str(tmp_path / "fused"))
    b = make_jacobi()
    rb = b.run_resilient(10, policy=pol(False),
                         ckpt_dir=str(tmp_path / "stepwise"))
    assert ra.steps == rb.steps == 10
    np.testing.assert_array_equal(a.temperature(), b.temperature())
    from stencil_tpu.utils.checkpoint import all_steps
    assert sorted(all_steps(str(tmp_path / "fused"))) == \
        sorted(all_steps(str(tmp_path / "stepwise")))


def test_driver_fused_rollback_bitwise(tmp_path):
    """A NaN inside a fused segment: rollback restores and the final
    state is bitwise-equal to the fault-free run — with the trip
    located at the exact injected step in the event log."""
    from stencil_tpu.resilience import (FaultPlan, NaNInjection,
                                        ResiliencePolicy)

    clean = make_jacobi()
    clean.run(12)

    j = make_jacobi()
    plan = FaultPlan(nans=[NaNInjection(step=7)])
    rep = j.run_resilient(
        12, policy=ResiliencePolicy(check_every=4, ckpt_every=4,
                                    base_delay=0.0,
                                    sleep=lambda s: None),
        ckpt_dir=str(tmp_path), faults=plan)
    assert rep.steps == 12 and rep.rollbacks == 1
    trips = [e for e in rep.events if e["event"] == "sentinel_tripped"]
    assert trips and trips[0]["step"] == 7
    np.testing.assert_array_equal(j.temperature(), clean.temperature())


# ----------------------------------------------------------------------
# DistributedDomain.make_segment (the generic entry)
# ----------------------------------------------------------------------
def test_domain_make_segment_generic():
    from stencil_tpu.distributed import DistributedDomain
    from stencil_tpu.geometry import Radius
    from stencil_tpu.parallel.exchange import exchange_shard
    from stencil_tpu.parallel.mesh import mesh_dim

    dd = DistributedDomain(16, 16, 16)
    dd.set_mesh_shape((2, 2, 2))
    dd.set_radius(1)
    dd.add_data("a", np.float32)
    dd.add_data("b", np.float32)
    dd.realize()
    counts = mesh_dim(dd.mesh)
    radius = Radius.constant(1)

    def shard_step(fields):
        out = {}
        for q, p in fields.items():
            p = exchange_shard(p, radius, counts)
            out[q] = p * 0.5
        return out

    dd.curr["a"] = dd.curr["a"] + 1.0
    dd.curr["b"] = dd.curr["b"] + 2.0
    seg = dd.make_segment(shard_step, check_every=3)
    tr = seg.run(0)
    assert tr.steps == (1, 2, 3)
    host = np.asarray(tr.array)
    assert host.shape == (3, 2, 2)  # rows x (nonfinite,max) x {a,b}
    np.testing.assert_allclose(host[:, 1, 0], [0.5, 0.25, 0.125])
    np.testing.assert_allclose(host[:, 1, 1], [1.0, 0.5, 0.25])
    np.testing.assert_allclose(np.asarray(dd.curr["a"]),
                               np.full_like(host[0, 0, 0], 0.125),
                               rtol=0)


# ----------------------------------------------------------------------
# astaroth: accumulator carry
# ----------------------------------------------------------------------
def test_astaroth_segment_accumulator_carry():
    """Fused RK3 segments vs stepwise: <= 1 ULP on the fields AND the
    carried w accumulators (float64 on CPU pins the comparison)."""
    from stencil_tpu.models.astaroth import Astaroth, MhdParams

    prm = MhdParams()
    a = Astaroth(8, 8, 8, params=prm, mesh_shape=(2, 2, 2),
                 dtype=np.float64)
    a.init()
    b = Astaroth(8, 8, 8, params=prm, mesh_shape=(2, 2, 2),
                 dtype=np.float64)
    b.init()
    for _ in range(2):
        a.step()
    seg = b.make_segment(2)
    tr = seg.run(0)
    assert tr.steps == (1, 2)
    assert np.asarray(tr.array).shape == (2, 2, 8)
    for q in ("lnrho", "uux", "ax", "ss"):
        np.testing.assert_allclose(b.field(q), a.field(q),
                                   rtol=1e-12, atol=1e-15)
        np.testing.assert_allclose(np.asarray(b._w[q]),
                                   np.asarray(a._w[q]),
                                   rtol=1e-12, atol=1e-15)


def _astaroth_temporal_pair(s, size, iters, check_every):
    """(stepwise_fields, fused_engine) for the temporal path at depth
    ``s``: the reference runs the blocked loop, the other runs ONE
    fused segment — the same lcm(3, s)-period group sequence."""
    import jax

    from stencil_tpu.models.astaroth import Astaroth
    from stencil_tpu.parallel.methods import Method

    devs = jax.devices()[:2]

    def mk():
        a = Astaroth(*size, mesh_shape=(1, 1, 2), devices=devs,
                     dtype=np.float64, kernel="xla",
                     methods=Method.PpermuteSlab, exchange_every=s)
        a.init()
        return a

    a, b = mk(), mk()
    assert a.kernel_path == f"xla-temporal[s={s}]"
    a.run(iters)
    seg = b.make_segment(check_every)
    assert seg and seg.steps == check_every
    done = 0
    while done < iters:
        k = min(check_every, iters - done)
        s2 = b.make_segment(k) if k != check_every else seg
        s2.run(done)
        done += k
    return a, b


def test_astaroth_temporal_segment_s2_group_straddle():
    """s=2 fused segments vs the blocked loop, <= 1 ULP (f64): the
    lcm(3,2)=6-substep period straddles iteration boundaries, so two
    of three groups start at alpha != 0 and ship the w carry in the
    deep exchange — the group-straddle case, INSIDE one fused
    program."""
    from stencil_tpu.models.astaroth import FIELDS

    a, b = _astaroth_temporal_pair(2, (8, 8, 16), iters=6,
                                   check_every=4)
    for q in FIELDS:
        np.testing.assert_allclose(b.field(q), a.field(q), rtol=1e-12,
                                   atol=1e-16, err_msg=q)
        np.testing.assert_allclose(np.asarray(b._w[q]),
                                   np.asarray(a._w[q]),
                                   rtol=1e-12, atol=1e-16, err_msg=q)


@pytest.mark.slow
def test_astaroth_temporal_segment_s3():
    """s=3 (period == 3: every group starts at alpha_0 == 0, w never
    rides the wire): fused segments match the blocked loop <= 1 ULP,
    with an uneven check_every exercising the tail-iteration chunks."""
    from stencil_tpu.models.astaroth import FIELDS

    # every per-shard axis (unsharded ones included — the local
    # periodic wrap ships s*r rows too) must be >= the deepened
    # radius 9, hence 9x9 cross-sections
    a, b = _astaroth_temporal_pair(3, (9, 9, 20), iters=3,
                                   check_every=2)
    for q in FIELDS:
        np.testing.assert_allclose(b.field(q), a.field(q), rtol=1e-12,
                                   atol=1e-16, err_msg=q)


# ----------------------------------------------------------------------
# decline visibility: fused: false is a reported fact, not a silence
# ----------------------------------------------------------------------
def test_driver_reports_fused_decline(tmp_path):
    """A declining path under the fused-by-default driver: the report
    says fused: false with the decline reason, the event log carries
    fused_decline, and the stencil_run_fused_dispatch_total{fused}
    counter accumulates the stepwise dispatches. (Certificate-gated
    overlap declines are pinned by
    test_overlap_path_declines_on_unsafe_certificate; here a declining
    factory drives the DRIVER's visibility contract without needing
    interpreted remote DMA to execute steps.)"""
    from stencil_tpu.parallel.megastep import decline
    from stencil_tpu.resilience import ResiliencePolicy
    from stencil_tpu.resilience.driver import run_resilient
    from stencil_tpu.telemetry import get_registry

    c = get_registry().counter("stencil_run_fused_dispatch_total", "")
    before_f = c.value(fused="false")
    before_t = c.value(fused="true")
    j = make_jacobi()
    rep = run_resilient(
        j.dd, j.step, 4,
        policy=ResiliencePolicy(check_every=2, base_delay=0.0,
                                sleep=lambda s: None),
        make_segment=lambda k, pe, m: decline(
            "jacobi", "overlap",
            "uncertified RDMA schedule: replay_safe=false (test stub)",
            code="uncertified-rdma-schedule"))
    assert rep.steps == 4
    assert rep.fused is False
    assert "RDMA" in rep.fused_decline_reason
    assert rep.fused_decline_code == "uncertified-rdma-schedule"
    declines = [e for e in rep.events if e["event"] == "fused_decline"]
    assert declines and declines[0]["model"] == "jacobi"
    assert declines[0]["path"] == "overlap"
    assert c.value(fused="false") - before_f == 4
    assert c.value(fused="true") == before_t
    # the record round-trips the verdict (chaos-smoke CI artifact)
    assert rep.to_record()["fused"] is False


def test_driver_reports_fused_true():
    from stencil_tpu.resilience import ResiliencePolicy
    from stencil_tpu.telemetry import get_registry

    c = get_registry().counter("stencil_run_fused_dispatch_total", "")
    before_t = c.value(fused="true")
    j = make_jacobi()
    rep = j.run_resilient(
        4, policy=ResiliencePolicy(check_every=2, base_delay=0.0,
                                   sleep=lambda s: None))
    assert rep.fused is True and rep.fused_decline_reason == ""
    assert not [e for e in rep.events
                if e["event"] == "fused_decline"]
    assert c.value(fused="true") - before_t >= 2


# ----------------------------------------------------------------------
# ensemble: batched segments
# ----------------------------------------------------------------------
def test_ensemble_segment_matches_stepwise_run():
    from stencil_tpu.serving.ensemble import EnsembleJacobi

    a = EnsembleJacobi(4, 16, 16, 16, mesh_shape=(2, 2, 2))
    a.init()
    a.set_member_params(2, {"hot_temp": 1.25})
    b = EnsembleJacobi(4, 16, 16, 16, mesh_shape=(2, 2, 2))
    b.init()
    b.set_member_params(2, {"hot_temp": 1.25})
    a.run(5)
    tr = b.run_segment(5)
    assert tr.steps == (1, 2, 3, 4, 5)
    host = np.asarray(tr.array)
    assert host.shape == (5, 4, 2, 1)  # rows x members x stats x temp
    assert not host[:, :, 0, :].any()  # all members finite throughout
    for k in range(4):
        np.testing.assert_array_equal(a.member_interior("temp", k),
                                      b.member_interior("temp", k))


def test_ensemble_segment_trace_isolates_tripped_member():
    from stencil_tpu.serving.ensemble import (EnsembleJacobi,
                                              EnsembleSentinel)

    eng = EnsembleJacobi(4, 16, 16, 16, mesh_shape=(2, 2, 2))
    eng.init()
    host = eng.member_interior("temp", 1)
    host[0, 0, 0] = np.nan
    eng.set_member_interior("temp", 1, host)
    sentinel = EnsembleSentinel(eng)
    tr = eng.run_segment(3)
    sentinel.observe_segment(tr.array, [r for r in tr.steps])
    healths = sentinel.poll(block=True)
    assert [h.step for h in healths] == [1, 2, 3]
    for h in healths:
        assert h.tripped_members == [1]


# ----------------------------------------------------------------------
# registry gates
# ----------------------------------------------------------------------
def test_megastep_registry_targets_prove_exact_counts():
    """The shipped megastep targets pass: k x per-step ppermutes + one
    all-reduce per probe row, bytes exactly k x the per-step model."""
    from stencil_tpu.analysis import run_targets
    from stencil_tpu.analysis.hlo import lowering_supported
    from stencil_tpu.analysis.registry import default_targets

    if not lowering_supported():
        pytest.skip("StableHLO lowering unavailable")
    targets = [t for t in default_targets() if "megastep" in t.name]
    assert {t.name for t in targets} == {
        "parallel.megastep.segment[k=4,hlo]",
        "parallel.megastep.segment[k=4,cost]",
        # the dataflow audits of the same fused program (PR 9)
        "parallel.megastep.segment[k=4,donation]",
        "parallel.megastep.segment[k=4,transfer]",
        "parallel.megastep.segment[k=4,recompile]",
        # the fused RDMA segment's schedule certificate (PR 16);
        # pinned by test_lint's schedule tests, excluded from the
        # collective-count audit below (it is traced, not lowered)
        "analysis.schedule.parallel.megastep.segment[overlap,k=4]",
        # the fused segment's dtype-flow certificate (PR 17); pinned
        # by test_lint's precision tests, likewise traced not lowered
        "analysis.precision.parallel.megastep.segment"}
    targets = [t for t in targets
               if t.checker not in ("schedule", "precision")]
    report = run_targets(targets)
    assert not report.findings, report.findings
    hlo = report.metrics["hlo:parallel.megastep.segment[k=4,hlo]"]
    assert hlo["collectives"]["collective_permute"]["count"] == 24
    assert hlo["collectives"]["all_reduce"]["count"] == 2
    cost = report.metrics[
        "costmodel:parallel.megastep.segment[k=4,cost]"]
    # exact-byte cross-check: observed == expected == k x per-step
    assert cost["observed_bytes_per_shard"] == \
        cost["expected_bytes_per_shard"]


def test_carry_contract_registry_targets_prove_exact_counts():
    """The segment compiler's per-model carry contracts, pinned: a
    fused PIC segment lowers to exactly k x 18 collective-permutes +
    one probe all-reduce per trace row with HLO-exact bytes AND the
    full (2, 9) probe column set; the astaroth temporal segment pays
    exactly its lcm(3, s)-period grouped deep exchanges (w riding only
    where a group starts at alpha != 0) — k x the amortized
    deep-exchange model, byte-exact."""
    from stencil_tpu.analysis import run_targets
    from stencil_tpu.analysis.hlo import lowering_supported
    from stencil_tpu.analysis.registry import default_targets

    if not lowering_supported():
        pytest.skip("StableHLO lowering unavailable")
    targets = [t for t in default_targets()
               if "models.pic.segment" in t.name
               or "models.astaroth.segment" in t.name]
    assert {t.name for t in targets} == {
        "models.pic.segment[k=4,hlo]",
        "models.pic.segment[k=4,cost]",
        "models.pic.segment[k=4,probe]",
        "models.pic.segment[k=4,donation]",
        "models.astaroth.segment[temporal,s=2,k=4,hlo]",
        "models.astaroth.segment[temporal,s=2,k=4,cost]",
        # the segments' dtype-flow certificates (PR 17) — pinned by
        # test_lint's precision tests, not re-certified here
        "analysis.precision.models.pic.segment",
        "analysis.precision.models.astaroth.segment"}
    targets = [t for t in targets if t.checker != "precision"]
    report = run_targets(targets)
    assert not report.findings, [str(f) for f in report.findings]
    pic = report.metrics["hlo:models.pic.segment[k=4,hlo]"]
    assert pic["collectives"]["collective_permute"]["count"] == 72
    assert pic["collectives"]["all_reduce"]["count"] == 2
    # the probe bill: 2 rows x (2, 9) f32 — overflow column included
    assert pic["collectives"]["all_reduce"]["bytes_per_shard"] == 144
    cost = report.metrics["costmodel:models.pic.segment[k=4,cost]"]
    assert cost["observed_bytes_per_shard"] == \
        cost["expected_bytes_per_shard"]
    ast = report.metrics[
        "hlo:models.astaroth.segment[temporal,s=2,k=4,hlo]"]
    # 2 period chunks x (8 + 16 + 16 quantities) x 2 ppermutes on the
    # one active axis — the w-carrying groups double their quantities
    assert ast["collectives"]["collective_permute"]["count"] == 160
    acost = report.metrics[
        "costmodel:models.astaroth.segment[temporal,s=2,k=4,cost]"]
    assert acost["observed_bytes_per_shard"] == \
        acost["expected_bytes_per_shard"]


def test_reprobed_megastep_fixture_flagged():
    """The negative control — a fused segment re-reducing the probe on
    every sub-step — is flagged with a nonzero CLI exit."""
    from stencil_tpu.analysis.hlo import lowering_supported

    if not lowering_supported():
        pytest.skip("StableHLO lowering unavailable")
    proc = subprocess.run(
        [sys.executable, "-m", "stencil_tpu.analysis",
         str(BAD_FIXTURE)],
        capture_output=True, text=True,
        cwd=str(Path(__file__).parent.parent), timeout=600)
    assert proc.returncode != 0, proc.stdout + proc.stderr
    assert "all_reduce" in proc.stdout
    assert "requires exactly 2" in proc.stdout
