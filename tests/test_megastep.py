"""Megastep: whole-campaign fused segments (parallel/megastep.py).

The ISSUE 8 acceptance contract: a ``check_every=k`` segment compiles
to ONE program that is numerically indistinguishable from the stepwise
loop (bitwise for Jacobi — periodic AND zero-Dirichlet, even AND
uneven partitions; accumulator-carrying ~1-ULP for Astaroth), carries
the per-step health probe in-graph so the driver can locate the exact
tripped step, donates its state end-to-end, and passes the same
registry gates as the stepwise path (exact collective counts, exact
bytes, negative control flagged).
"""

import subprocess
import sys
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

from stencil_tpu.models.jacobi import Jacobi3D
from stencil_tpu.parallel.megastep import (MAX_UNROLL, probe_rel_steps,
                                           segment_chunks)

N = 16
BAD_FIXTURE = Path(__file__).parent / "fixtures" / "lint" / \
    "bad_megastep.py"


def make_jacobi(**kw):
    kw.setdefault("mesh_shape", (2, 2, 2))
    kw.setdefault("dtype", np.float32)
    kw.setdefault("kernel", "xla")
    j = Jacobi3D(kw.pop("x", N), kw.pop("y", N), kw.pop("z", N), **kw)
    j.init()
    return j


# ----------------------------------------------------------------------
# segmentation helpers
# ----------------------------------------------------------------------
def test_segment_chunks_and_probe_points():
    assert segment_chunks(5) == [1] * 5
    assert segment_chunks(7, stride=3) == [3, 3, 1]
    assert probe_rel_steps([1] * 6, 2) == (2, 4, 6)
    # the final step is ALWAYS probed, cadence or not
    assert probe_rel_steps([1] * 5, 2) == (2, 4, 5)
    assert probe_rel_steps([3, 3, 1], 1) == (3, 6, 7)
    assert MAX_UNROLL >= 16


# ----------------------------------------------------------------------
# fused == stepwise, bitwise (jacobi)
# ----------------------------------------------------------------------
def _compare_jacobi(steps=8, seg=None, **kw):
    a = make_jacobi(**kw)
    b = make_jacobi(**kw)
    for _ in range(steps):
        a.step()
    done = 0
    while done < steps:
        k = min(seg or steps, steps - done)
        s = b.make_segment(k)
        assert s is not None and s.steps == k
        s.run(done)
        done += k
    np.testing.assert_array_equal(a.temperature(), b.temperature())


def test_jacobi_segment_bitwise_periodic():
    _compare_jacobi(steps=8, seg=4)


def test_jacobi_segment_bitwise_uneven_partitions():
    _compare_jacobi(steps=6, seg=3, x=17, y=17, z=17)


def test_jacobi_segment_bitwise_boundary_none():
    from stencil_tpu.topology import Boundary
    _compare_jacobi(steps=6, seg=3, boundary=Boundary.NONE)


def test_jacobi_segment_bitwise_uneven_none():
    from stencil_tpu.topology import Boundary
    _compare_jacobi(steps=5, seg=2, x=17, y=17, z=17,
                    boundary=Boundary.NONE)


def test_jacobi_temporal_segment_bitwise():
    """exchange_every=2: the fused segment advances whole temporal
    groups plus depth-1 tails, bitwise-equal to the blocked loop."""
    a = make_jacobi(exchange_every=2)
    assert a.kernel_path == "xla-temporal[s=2]"
    b = make_jacobi(exchange_every=2)
    a.run(7)
    s = b.make_segment(7)
    # 3 groups of 2 + 1 tail step, probed per chunk
    assert s.probe_steps == (2, 4, 6, 7)
    s.run(0)
    np.testing.assert_array_equal(a.temperature(), b.temperature())


def test_fast_paths_decline_segments():
    """Interior-resident Pallas paths keep their own fused loops: the
    factory returns None and the driver falls back to stepwise."""
    import jax

    j = Jacobi3D(16, 16, 16, mesh_shape=(1, 1, 1),
                 devices=jax.devices()[:1], dtype=np.float32,
                 kernel="wrap")
    j.init()
    assert j.make_segment(4) is None


# ----------------------------------------------------------------------
# the in-graph probe trace
# ----------------------------------------------------------------------
def test_segment_trace_rows_and_metrics():
    from stencil_tpu.telemetry.probe import StepMetrics

    j = make_jacobi()
    m = StepMetrics(j.dd)
    seg = j.make_segment(6, probe_every=2, metrics=m)
    tr = seg.run(10)
    assert tr.steps == (2, 4, 6)
    assert tr.abs_steps == [12, 14, 16]
    host = np.asarray(tr.array)
    # columns: temp, substeps, wire_bytes; rows replicated f32
    assert host.shape == (3, 2, 3)
    np.testing.assert_array_equal(host[:, 0, 1], [12.0, 14.0, 16.0])
    np.testing.assert_allclose(
        host[:, 0, 2],
        [m.cumulative_bytes(s) for s in (12, 14, 16)], rtol=1e-6)
    # health columns are real: nonfinite 0, max-abs 1 (hot sphere)
    assert host[0, 0, 0] == 0.0
    assert host[0, 1, 0] == pytest.approx(1.0)


def test_sentinel_locates_exact_tripped_step_in_trace():
    """A NaN planted mid-segment: the trace row of ITS step trips, with
    earlier rows clean — the driver learns the exact step without
    replaying the segment."""
    from stencil_tpu.resilience.health import HealthSentinel

    j = make_jacobi()
    s = HealthSentinel(j.dd)
    clean = j.dd.curr["temp"]
    rows = []
    for i in range(4):
        p = clean if i < 2 else clean.at[3, 3, 3].set(float("nan"))
        rows.append(jnp.stack([
            jnp.stack([jnp.sum(~jnp.isfinite(p)).astype(jnp.float32)]),
            jnp.stack([jnp.max(jnp.abs(jnp.nan_to_num(p)))]),
        ]))
    s.observe_segment(jnp.stack(rows), steps=[5, 6, 7, 8])
    results = s.poll(block=True)
    assert [r.step for r in results] == [5, 6, 7, 8]
    assert [r.tripped for r in results] == [False, False, True, True]
    assert s.tripped.step == 7


def test_driver_fused_equals_stepwise(tmp_path):
    """run_resilient fused (default) vs fuse_segments=False: identical
    final state, identical checkpoint trail."""
    from stencil_tpu.resilience import ResiliencePolicy

    def pol(fused):
        return ResiliencePolicy(check_every=3, ckpt_every=4,
                                base_delay=0.0, sleep=lambda s: None,
                                fuse_segments=fused)

    a = make_jacobi()
    ra = a.run_resilient(10, policy=pol(True),
                         ckpt_dir=str(tmp_path / "fused"))
    b = make_jacobi()
    rb = b.run_resilient(10, policy=pol(False),
                         ckpt_dir=str(tmp_path / "stepwise"))
    assert ra.steps == rb.steps == 10
    np.testing.assert_array_equal(a.temperature(), b.temperature())
    from stencil_tpu.utils.checkpoint import all_steps
    assert sorted(all_steps(str(tmp_path / "fused"))) == \
        sorted(all_steps(str(tmp_path / "stepwise")))


def test_driver_fused_rollback_bitwise(tmp_path):
    """A NaN inside a fused segment: rollback restores and the final
    state is bitwise-equal to the fault-free run — with the trip
    located at the exact injected step in the event log."""
    from stencil_tpu.resilience import (FaultPlan, NaNInjection,
                                        ResiliencePolicy)

    clean = make_jacobi()
    clean.run(12)

    j = make_jacobi()
    plan = FaultPlan(nans=[NaNInjection(step=7)])
    rep = j.run_resilient(
        12, policy=ResiliencePolicy(check_every=4, ckpt_every=4,
                                    base_delay=0.0,
                                    sleep=lambda s: None),
        ckpt_dir=str(tmp_path), faults=plan)
    assert rep.steps == 12 and rep.rollbacks == 1
    trips = [e for e in rep.events if e["event"] == "sentinel_tripped"]
    assert trips and trips[0]["step"] == 7
    np.testing.assert_array_equal(j.temperature(), clean.temperature())


# ----------------------------------------------------------------------
# DistributedDomain.make_segment (the generic entry)
# ----------------------------------------------------------------------
def test_domain_make_segment_generic():
    from stencil_tpu.distributed import DistributedDomain
    from stencil_tpu.geometry import Radius
    from stencil_tpu.parallel.exchange import exchange_shard
    from stencil_tpu.parallel.mesh import mesh_dim

    dd = DistributedDomain(16, 16, 16)
    dd.set_mesh_shape((2, 2, 2))
    dd.set_radius(1)
    dd.add_data("a", np.float32)
    dd.add_data("b", np.float32)
    dd.realize()
    counts = mesh_dim(dd.mesh)
    radius = Radius.constant(1)

    def shard_step(fields):
        out = {}
        for q, p in fields.items():
            p = exchange_shard(p, radius, counts)
            out[q] = p * 0.5
        return out

    dd.curr["a"] = dd.curr["a"] + 1.0
    dd.curr["b"] = dd.curr["b"] + 2.0
    seg = dd.make_segment(shard_step, check_every=3)
    tr = seg.run(0)
    assert tr.steps == (1, 2, 3)
    host = np.asarray(tr.array)
    assert host.shape == (3, 2, 2)  # rows x (nonfinite,max) x {a,b}
    np.testing.assert_allclose(host[:, 1, 0], [0.5, 0.25, 0.125])
    np.testing.assert_allclose(host[:, 1, 1], [1.0, 0.5, 0.25])
    np.testing.assert_allclose(np.asarray(dd.curr["a"]),
                               np.full_like(host[0, 0, 0], 0.125),
                               rtol=0)


# ----------------------------------------------------------------------
# astaroth: accumulator carry
# ----------------------------------------------------------------------
def test_astaroth_segment_accumulator_carry():
    """Fused RK3 segments vs stepwise: <= 1 ULP on the fields AND the
    carried w accumulators (float64 on CPU pins the comparison)."""
    from stencil_tpu.models.astaroth import Astaroth, MhdParams

    prm = MhdParams()
    a = Astaroth(8, 8, 8, params=prm, mesh_shape=(2, 2, 2),
                 dtype=np.float64)
    a.init()
    b = Astaroth(8, 8, 8, params=prm, mesh_shape=(2, 2, 2),
                 dtype=np.float64)
    b.init()
    for _ in range(2):
        a.step()
    seg = b.make_segment(2)
    tr = seg.run(0)
    assert tr.steps == (1, 2)
    assert np.asarray(tr.array).shape == (2, 2, 8)
    for q in ("lnrho", "uux", "ax", "ss"):
        np.testing.assert_allclose(b.field(q), a.field(q),
                                   rtol=1e-12, atol=1e-15)
        np.testing.assert_allclose(np.asarray(b._w[q]),
                                   np.asarray(a._w[q]),
                                   rtol=1e-12, atol=1e-15)


# ----------------------------------------------------------------------
# ensemble: batched segments
# ----------------------------------------------------------------------
def test_ensemble_segment_matches_stepwise_run():
    from stencil_tpu.serving.ensemble import EnsembleJacobi

    a = EnsembleJacobi(4, 16, 16, 16, mesh_shape=(2, 2, 2))
    a.init()
    a.set_member_params(2, {"hot_temp": 1.25})
    b = EnsembleJacobi(4, 16, 16, 16, mesh_shape=(2, 2, 2))
    b.init()
    b.set_member_params(2, {"hot_temp": 1.25})
    a.run(5)
    tr = b.run_segment(5)
    assert tr.steps == (1, 2, 3, 4, 5)
    host = np.asarray(tr.array)
    assert host.shape == (5, 4, 2, 1)  # rows x members x stats x temp
    assert not host[:, :, 0, :].any()  # all members finite throughout
    for k in range(4):
        np.testing.assert_array_equal(a.member_interior("temp", k),
                                      b.member_interior("temp", k))


def test_ensemble_segment_trace_isolates_tripped_member():
    from stencil_tpu.serving.ensemble import (EnsembleJacobi,
                                              EnsembleSentinel)

    eng = EnsembleJacobi(4, 16, 16, 16, mesh_shape=(2, 2, 2))
    eng.init()
    host = eng.member_interior("temp", 1)
    host[0, 0, 0] = np.nan
    eng.set_member_interior("temp", 1, host)
    sentinel = EnsembleSentinel(eng)
    tr = eng.run_segment(3)
    sentinel.observe_segment(tr.array, [r for r in tr.steps])
    healths = sentinel.poll(block=True)
    assert [h.step for h in healths] == [1, 2, 3]
    for h in healths:
        assert h.tripped_members == [1]


# ----------------------------------------------------------------------
# registry gates
# ----------------------------------------------------------------------
def test_megastep_registry_targets_prove_exact_counts():
    """The shipped megastep targets pass: k x per-step ppermutes + one
    all-reduce per probe row, bytes exactly k x the per-step model."""
    from stencil_tpu.analysis import run_targets
    from stencil_tpu.analysis.hlo import lowering_supported
    from stencil_tpu.analysis.registry import default_targets

    if not lowering_supported():
        pytest.skip("StableHLO lowering unavailable")
    targets = [t for t in default_targets() if "megastep" in t.name]
    assert {t.name for t in targets} == {
        "parallel.megastep.segment[k=4,hlo]",
        "parallel.megastep.segment[k=4,cost]",
        # the dataflow audits of the same fused program (PR 9)
        "parallel.megastep.segment[k=4,donation]",
        "parallel.megastep.segment[k=4,transfer]",
        "parallel.megastep.segment[k=4,recompile]"}
    report = run_targets(targets)
    assert not report.findings, report.findings
    hlo = report.metrics["hlo:parallel.megastep.segment[k=4,hlo]"]
    assert hlo["collectives"]["collective_permute"]["count"] == 24
    assert hlo["collectives"]["all_reduce"]["count"] == 2
    cost = report.metrics[
        "costmodel:parallel.megastep.segment[k=4,cost]"]
    # exact-byte cross-check: observed == expected == k x per-step
    assert cost["observed_bytes_per_shard"] == \
        cost["expected_bytes_per_shard"]


def test_reprobed_megastep_fixture_flagged():
    """The negative control — a fused segment re-reducing the probe on
    every sub-step — is flagged with a nonzero CLI exit."""
    from stencil_tpu.analysis.hlo import lowering_supported

    if not lowering_supported():
        pytest.skip("StableHLO lowering unavailable")
    proc = subprocess.run(
        [sys.executable, "-m", "stencil_tpu.analysis",
         str(BAD_FIXTURE)],
        capture_output=True, text=True,
        cwd=str(Path(__file__).parent.parent), timeout=600)
    assert proc.returncode != 0, proc.stdout + proc.stderr
    assert "all_reduce" in proc.stdout
    assert "requires exactly 2" in proc.stdout
