#!/usr/bin/env python
"""Headline benchmark: Jacobi-3D iteration rate + halo-exchange bandwidth.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}

North-star metric (BASELINE.md): jacobi3d iters/sec at 512^3, radius 1,
measured with the reference's statistics (trimean over sample windows,
bin/statistics.hpp analog). The reference publishes no numbers
(BASELINE.md), so vs_baseline compares against the previous round's
recorded result in BENCH_r*.json when present, else 1.0.

Timing note: on the axon TPU tunnel, block_until_ready does not drain
execution; we fence with a device->host fetch (stencil_tpu.utils.timers).
"""

import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    import jax
    import numpy as np

    on_tpu = any("tpu" in str(d).lower() for d in jax.devices())
    if on_tpu:
        size, iters, warmup = 512, 200, 10
    else:  # CPU smoke-test path
        size, iters, warmup = 64, 20, 2

    from stencil_tpu.models.jacobi import Jacobi3D
    from stencil_tpu.numerics import trimean
    from stencil_tpu.geometry import Radius
    from stencil_tpu.local_domain import halo_bytes

    ndev = len(jax.devices())
    from stencil_tpu.parallel.mesh import default_mesh_shape
    mesh_shape = default_mesh_shape(ndev)
    j = Jacobi3D(size, size, size, mesh_shape=mesh_shape, dtype=np.float32)
    j.init()
    j.run(warmup)
    j.block()

    # iteration rate: several timed windows, trimean (reference
    # statistics schema, bin/statistics.hpp:6-19)
    window = max(iters // 4, 1)
    rates = []
    for _ in range(4):
        t0 = time.perf_counter()
        j.run(window)
        j.block()
        dt = time.perf_counter() - t0
        rates.append(window / dt)
    iters_per_sec = trimean(rates)

    # exchange-only bandwidth: all 26-direction halo bytes accounted the
    # reference way (halo_extent per direction, local_domain.cuh:212-239)
    dd = j.dd
    radius = dd.radius
    from stencil_tpu.geometry import all_directions
    per_dir = sum(halo_bytes(d, dd.local_size, radius, 4)
                  for d in all_directions())
    total_halo_bytes = per_dir * dd.placement.dim().flatten()
    ex = dd._exchange_fn
    out = ex(dd.curr)  # compile
    from stencil_tpu.utils.timers import device_sync
    device_sync(out)
    n_ex = 50 if on_tpu else 5
    t0 = time.perf_counter()
    for _ in range(n_ex):
        out = ex(out)
    device_sync(out)
    ex_s = (time.perf_counter() - t0) / n_ex
    exchange_gbs = total_halo_bytes / ex_s / 1e9

    value = round(iters_per_sec, 2)
    baseline = _previous_round_value()
    vs = round(value / baseline, 3) if baseline else 1.0
    print(json.dumps({
        "metric": f"jacobi3d_{size}c_iters_per_sec",
        "value": value,
        "unit": "iters/s",
        "vs_baseline": vs,
        "extra": {
            "devices": ndev,
            "mesh": tuple(mesh_shape),
            "platform": str(jax.devices()[0].platform),
            "exchange_GBps": round(exchange_gbs, 2),
            "exchange_s": round(ex_s, 6),
            "halo_bytes_per_exchange": total_halo_bytes,
        },
    }))


def _previous_round_value():
    best = None
    for path in sorted(glob.glob("BENCH_r*.json")):
        try:
            with open(path) as f:
                rec = json.load(f)
            v = rec.get("value")
            if isinstance(v, (int, float)) and v > 0:
                best = v
        except Exception:
            pass
    return best


if __name__ == "__main__":
    main()
