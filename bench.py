#!/usr/bin/env python
"""Headline benchmark: Jacobi-3D iteration rate + halo-exchange bandwidth.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}

North-star metric (BASELINE.md): jacobi3d iters/sec at 512^3, radius 1,
measured with the reference's statistics (trimean over sample windows,
bin/statistics.hpp analog). The reference publishes no numbers
(BASELINE.md), so vs_baseline compares against the BEST non-suspect
result across all prior rounds' BENCH_r*.json when present, else 1.0.

Timing note: on the axon TPU tunnel, block_until_ready does not drain
execution; we fence with a device->host fetch (stencil_tpu.utils.timers).

Robustness: the measurement runs in a SUBPROCESS with a timeout; if the
default (temporally-blocked wrap2) compute path hangs or fails on the
current backend, the run retries once with STENCIL_DISABLE_WRAP2=1 (the
hardware-proven single-step kernel), and a total failure still emits a
parseable suspect record instead of hanging the driver.
"""

import glob
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# generous first attempt (a fresh 512^3 Mosaic compile can take
# minutes); the fallback path is known to compile in under a minute
_TIMEOUTS_S = (1500, 600)


def _backend_alive(timeout_s: int = 180) -> bool:
    """Probe backend init in a throwaway subprocess: a wedged
    accelerator tunnel hangs inside the C runtime (no Python signal
    delivery), so an in-process guard cannot catch it. A dead probe
    short-circuits the whole measurement to a fast suspect record
    instead of burning both attempt timeouts (~35 min)."""
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False
    # a FAST probe failure (rc != 0) is not a hang: let the real
    # measurement attempts run and capture the actual error in last_err
    return True


def main() -> None:
    if "--measure" in sys.argv:
        measure()
        return
    env = dict(os.environ)
    last_err = ""
    if not _backend_alive():
        # value/vs_baseline are null, not 0.0: nothing was measured, and
        # a numeric zero invites downstream tooling to ingest it as data
        print(json.dumps({
            "metric": "jacobi3d_512c_iters_per_sec", "value": None,
            "unit": "iters/s", "vs_baseline": None, "suspect": True,
            "extra": {"suspect_reason":
                      "XLA backend init hung >180s (accelerator tunnel "
                      "down); measurement skipped"},
        }))
        return
    for attempt, note in ((0, None), (1, "wrap2 disabled")):
        if attempt:
            env["STENCIL_DISABLE_WRAP2"] = "1"
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--measure"],
                capture_output=True, text=True,
                timeout=_TIMEOUTS_S[attempt], env=env)
        except subprocess.TimeoutExpired:
            last_err = f"attempt {attempt}: timeout"
            continue
        if out.returncode != 0:
            last_err = (f"attempt {attempt}: rc={out.returncode}: "
                        + out.stderr[-400:])
        for line in reversed(out.stdout.splitlines()):
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if note:
                rec.setdefault("extra", {})["fallback"] = note
            print(json.dumps(rec))
            return
    print(json.dumps({
        "metric": "jacobi3d_512c_iters_per_sec", "value": None,
        "unit": "iters/s", "vs_baseline": None, "suspect": True,
        "extra": {"suspect_reason":
                  "measurement subprocess hung or died on both the "
                  "wrap2 and single-step paths; last error: "
                  + (last_err or "none captured")},
    }))


def measure() -> None:
    import jax
    import numpy as np

    from stencil_tpu.utils.config import enable_compile_cache
    enable_compile_cache()
    on_tpu = any("tpu" in str(d).lower() for d in jax.devices())
    if on_tpu:
        size, iters, warmup = 512, 200, 10
    else:  # CPU smoke-test path
        size, iters, warmup = 64, 20, 2

    from stencil_tpu.models.jacobi import Jacobi3D
    from stencil_tpu.numerics import trimean

    ndev = len(jax.devices())
    from stencil_tpu.parallel.mesh import default_mesh_shape
    mesh_shape = default_mesh_shape(ndev)
    j = Jacobi3D(size, size, size, mesh_shape=mesh_shape, dtype=np.float32)
    j.init()
    j.run(warmup)
    j.block()

    # iteration rate: several timed windows, trimean (reference
    # statistics schema, bin/statistics.hpp:6-19)
    window = max(iters // 4, 1)
    rates = []
    for _ in range(4):
        t0 = time.perf_counter()
        j.run(window)
        j.block()
        dt = time.perf_counter() - t0
        rates.append(window / dt)
    iters_per_sec = trimean(rates)

    # exchange-only bandwidth: cross-device bytes only (axes with mesh
    # count 1 are local wraps, not wire traffic) — same accounting as
    # DistributedDomain's byte counters
    dd = j.dd
    total_halo_bytes = dd.exchange_bytes_total()
    ex = dd._exchange_fn
    out = ex(dd.curr)  # compile
    from stencil_tpu.utils.timers import device_sync
    device_sync(out)
    n_ex = 50 if on_tpu else 5
    t0 = time.perf_counter()
    for _ in range(n_ex):
        out = ex(out)
    device_sync(out)
    ex_s = (time.perf_counter() - t0) / n_ex
    exchange_gbs = total_halo_bytes / ex_s / 1e9

    value = round(iters_per_sec, 2)
    metric = f"jacobi3d_{size}c_iters_per_sec"
    baseline = _previous_round_value(metric, ndev)
    vs = round(value / baseline, 3) if baseline else 1.0
    rec = {
        "metric": metric,
        "value": value,
        "unit": "iters/s",
        "vs_baseline": vs,
        "extra": {
            "devices": ndev,
            "mesh": tuple(mesh_shape),
            "platform": str(jax.devices()[0].platform),
            # On one chip there is no wire traffic — report null, not a
            # misleading 0.0 bandwidth.
            "exchange_GBps": (round(exchange_gbs, 2)
                              if total_halo_bytes else None),
            "exchange_s": round(ex_s, 6),
            "halo_bytes_per_exchange": total_halo_bytes,
        },
    }
    # A run >2x SLOWER than the best prior round is almost certainly an
    # environment glitch (BENCH_r03 recorded 25.95 vs 195.5 with no
    # flag) — mark it so downstream tooling doesn't ingest it silently.
    # Improvements are never flagged: they must be able to raise the
    # baseline bar for subsequent rounds.
    if baseline and value < 0.5 * baseline:
        rec["suspect"] = True
        rec["extra"]["suspect_reason"] = (
            f">2x below best prior round ({baseline}); "
            "likely environment glitch")
    print(json.dumps(rec))


def _previous_round_value(metric, ndev):
    """Best value across prior rounds whose metric AND device count
    match (an 8-chip round must not become the bar for 1-chip runs).
    The driver wraps this script's JSON line as {"n": .., "tail": ..,
    "parsed": {...}} in BENCH_r*.json — unwrap that; also accept the
    bare schema for hand-saved records. "Best" (not "latest") so one
    glitched round (e.g. BENCH_r03's 25.95 vs 195.5) doesn't reset the
    comparison bar."""
    best = None
    for path in glob.glob("BENCH_r*.json"):
        try:
            with open(path) as f:
                rec = json.load(f)
            if isinstance(rec.get("parsed"), dict):
                rec = rec["parsed"]
            v = rec.get("value")
            rec_dev = rec.get("extra", {}).get("devices")
            if (rec.get("metric") == metric and rec_dev == ndev
                    and isinstance(v, (int, float)) and v > 0
                    and not rec.get("suspect")):
                best = v if best is None else max(best, v)
        except Exception:
            pass
    return best


if __name__ == "__main__":
    main()
