#!/usr/bin/env python
"""Single-chip kernel A/B bench: wrap vs halo vs xla compute paths.

Measures the fused-kernel iteration rate for Jacobi-3D (512^3 default)
and the Astaroth MHD integrator (256^3 default) on the current backend,
per kernel mode and block shape — the tuning harness behind the
BASELINE.md single-chip numbers (reference's bench ethos:
bin/jacobi3d.cu:383-392 CSV, trimean statistics).

Usage: python scripts/bench_kernels.py [--model jacobi|mhd|both]
       [--size N] [--iters N] [--kernels wrap,halo,xla] [--blocks ...]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_jacobi(size, iters, kernels, blocks):
    import jax
    import numpy as np
    from stencil_tpu.models.jacobi import Jacobi3D
    from stencil_tpu.numerics import trimean

    for kernel in kernels:
        try:
            j = Jacobi3D(size, size, size, mesh_shape=(1, 1, 1),
                         devices=jax.devices()[:1], kernel=kernel)
        except ValueError as e:
            print(f"jacobi,{kernel},SKIP,{e}")
            continue
        if kernel in ("wrap", "halo") and blocks:
            _patch_jacobi_blocks(j, kernel, blocks)
        j.init()
        j.run(5)
        j.block()
        window = max(iters // 4, 1)
        rates = []
        for _ in range(4):
            t0 = time.perf_counter()
            j.run(window)
            j.block()
            rates.append(window / (time.perf_counter() - t0))
        print(f"jacobi,{kernel},{size},{trimean(rates):.2f} iters/s,"
              f"min {min(rates):.2f},max {max(rates):.2f}")
        del j


def _patch_jacobi_blocks(j, kernel, blocks):
    """Rebuild the step with explicit (bz, by) via functools.partial on
    the kernel module entry (tuning hook, not a public knob)."""
    import functools
    from stencil_tpu.ops import pallas_halo, pallas_stencil

    bz, by = blocks
    if kernel == "wrap":
        orig = pallas_stencil.jacobi7_wrap_pallas
        pallas_stencil.jacobi7_wrap_pallas = functools.partial(
            orig, block_z=bz, block_y=by)
        j._build_wrap_step()
        pallas_stencil.jacobi7_wrap_pallas = orig
    else:
        orig = pallas_halo.jacobi7_halo_pallas
        pallas_halo.jacobi7_halo_pallas = functools.partial(
            orig, block_z=bz, block_y=by)
        j._build_halo_step()
        pallas_halo.jacobi7_halo_pallas = orig


def bench_mhd(size, iters, kernels, blocks):
    import jax
    import numpy as np
    from stencil_tpu.models.astaroth import Astaroth
    from stencil_tpu.numerics import trimean

    for kernel in kernels:
        try:
            m = Astaroth(size, size, size, mesh_shape=(1, 1, 1),
                         devices=jax.devices()[:1], kernel=kernel)
        except ValueError as e:
            print(f"mhd,{kernel},SKIP,{e}")
            continue
        if kernel in ("wrap", "halo") and blocks:
            _patch_mhd_blocks(m, kernel, blocks)
        m.init()
        m.run(2)
        m.block()
        window = max(iters // 4, 1)
        rates = []
        for _ in range(4):
            t0 = time.perf_counter()
            m.run(window)
            m.block()
            rates.append(window / (time.perf_counter() - t0))
        print(f"mhd,{kernel},{size},{trimean(rates):.2f} iters/s,"
              f"min {min(rates):.2f},max {max(rates):.2f}")
        del m


def _patch_mhd_blocks(m, kernel, blocks):
    import functools
    from stencil_tpu.ops import pallas_mhd

    bz, by = blocks
    if kernel == "wrap":
        orig = pallas_mhd.mhd_substep_wrap_pallas
        pallas_mhd.mhd_substep_wrap_pallas = functools.partial(
            orig, block_z=bz, block_y=by)
        m._build_wrap_step()
        pallas_mhd.mhd_substep_wrap_pallas = orig
    else:
        m._halo_blocks = (bz, by)
        m._build_halo_step()


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="both",
                    choices=("jacobi", "mhd", "both"))
    ap.add_argument("--size", type=int, default=0,
                    help="cube edge (default 512 jacobi / 256 mhd)")
    ap.add_argument("--iters", type=int, default=0)
    ap.add_argument("--kernels", default="wrap,halo,xla")
    ap.add_argument("--blocks", default="",
                    help="bz,by override for pallas kernels")
    args = ap.parse_args()
    kernels = args.kernels.split(",")
    blocks = (tuple(int(v) for v in args.blocks.split(","))
              if args.blocks else None)

    import jax
    on_tpu = jax.default_backend() == "tpu"
    if args.model in ("jacobi", "both"):
        size = args.size or (512 if on_tpu else 32)
        iters = args.iters or (200 if on_tpu else 4)
        bench_jacobi(size, iters, kernels, blocks)
    if args.model in ("mhd", "both"):
        size = args.size or (256 if on_tpu else 16)
        iters = args.iters or (20 if on_tpu else 2)
        bench_mhd(size, iters, kernels, blocks)


if __name__ == "__main__":
    main()
