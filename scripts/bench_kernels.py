#!/usr/bin/env python
"""Single-chip kernel A/B bench: wrap vs halo vs xla compute paths.

Measures the fused-kernel iteration rate for Jacobi-3D (512^3 default)
and the Astaroth MHD integrator (256^3 default) on the current backend,
per kernel mode and block shape — the tuning harness behind the
BASELINE.md single-chip numbers (reference's bench ethos:
bin/jacobi3d.cu:383-392 CSV, trimean statistics).

Usage: python scripts/bench_kernels.py [--model jacobi|mhd|both]
       [--size N] [--iters N] [--kernels wrap,halo,xla] [--blocks ...]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bench_model(label, ctor, size, iters, kernels, blocks, patch_fn,
                 warmup):
    """Shared sweep loop: construct, optionally patch block shapes, warm
    up, time 4 windows, print one CSV line per kernel. Any one kernel's
    build/compile failure (e.g. a Mosaic scoped-VMEM OOM at an
    aggressive block shape) prints a FAIL line and must not abort the
    rest of the sweep."""
    from stencil_tpu.numerics import trimean

    for kernel in kernels:
        try:
            m = ctor(kernel)
        except ValueError as e:  # unsupported config for this kernel
            print(f"{label},{kernel},SKIP,{_one_line(e)}")
            continue
        except Exception as e:  # kernel build/compile failure
            print(f"{label},{kernel},{size},FAIL,{_one_line(e)}")
            continue
        try:
            if kernel in ("wrap", "halo") and blocks:
                patch_fn(m, kernel, blocks)
            m.init()
            m.run(warmup)
            m.block()
            window = max(iters // 4, 1)
            rates = []
            for _ in range(4):
                t0 = time.perf_counter()
                m.run(window)
                m.block()
                rates.append(window / (time.perf_counter() - t0))
            print(f"{label},{kernel},{size},{trimean(rates):.2f} iters/s,"
                  f"min {min(rates):.2f},max {max(rates):.2f}")
        except Exception as e:
            print(f"{label},{kernel},{size},FAIL,{_one_line(e)}")
        del m


def bench_jacobi(size, iters, kernels, blocks, dtype="f32"):
    import jax
    import jax.numpy as jnp
    from stencil_tpu.models.jacobi import Jacobi3D

    dt = jnp.bfloat16 if dtype == "bf16" else jnp.float32

    def ctor(kernel):
        return Jacobi3D(size, size, size, mesh_shape=(1, 1, 1),
                        devices=jax.devices()[:1], kernel=kernel,
                        dtype=dt)

    _bench_model("jacobi", ctor, size, iters, kernels, blocks,
                 _patch_jacobi_blocks, warmup=5)


def _patch_jacobi_blocks(j, kernel, blocks):
    """Rebuild the step with explicit (bz, by) via functools.partial on
    the kernel module entry (tuning hook, not a public knob)."""
    import functools
    from stencil_tpu.ops import pallas_halo, pallas_stencil

    bz, by = blocks
    if kernel == "wrap":
        # the wrap step runs N-step groups through the wrapn kernel
        # with a single-step tail — patch BOTH so the sweep measures
        # what it reports
        orig1 = pallas_stencil.jacobi7_wrap_pallas
        orign = pallas_stencil.jacobi7_wrapn_pallas
        pallas_stencil.jacobi7_wrap_pallas = functools.partial(
            orig1, block_z=bz, block_y=by)
        pallas_stencil.jacobi7_wrapn_pallas = functools.partial(
            orign, block_z=bz, block_y=by)
        try:
            j._build_wrap_step()
        finally:
            pallas_stencil.jacobi7_wrap_pallas = orig1
            pallas_stencil.jacobi7_wrapn_pallas = orign
    else:
        # the halo path runs N-step groups (jacobi7_halon_pallas, blocks
        # from fit_pair_halo_blocks) with a single-step tail — ONE
        # resolved (bz, by) decision drives both, so a measurement is
        # never a hybrid of swept-group + default-tail shapes (or vice
        # versa). Swept shapes are honored as-given (the sweep's whole
        # point); only a shape whose byte model exceeds the kernel's
        # actual 64 MiB scoped-VMEM compile ceiling — certain to fail —
        # is replaced by the default fit, with a visible stderr note so
        # the CSV row is not silently mislabeled.
        orig = pallas_halo.jacobi7_halo_pallas
        orig_fit = pallas_halo.fit_pair_halo_blocks
        from stencil_tpu.ops.pallas_stencil import sublane_tile_bytes
        hard = 64 * 2**20   # pallas_halo kernels' vmem_limit_bytes
        resolved = {}

        def _fit_swept(Z, Y, X, item, steps=2):
            cand = (pallas_halo._shrink_block(Z, bz),
                    pallas_halo._shrink_block(Y, by,
                                              sublane_tile_bytes(item)))
            if (pallas_halo._pair_block_bytes(cand[0], cand[1], X, item,
                                              steps) > hard):
                fb = orig_fit(Z, Y, X, item, steps)
                print(f"swept blocks {cand} exceed the {hard >> 20} MiB "
                      f"scoped-VMEM ceiling; measuring fallback {fb}",
                      file=sys.stderr)
                cand = fb
            resolved["blocks"] = cand
            return cand

        def _tail(*a, **kw):
            blk = resolved.get("blocks", (bz, by))
            kw.setdefault("block_z", blk[0])
            kw.setdefault("block_y", blk[1])
            return orig(*a, **kw)

        pallas_halo.jacobi7_halo_pallas = _tail
        pallas_halo.fit_pair_halo_blocks = _fit_swept
        try:
            j._build_halo_step()
        finally:
            pallas_halo.jacobi7_halo_pallas = orig
            pallas_halo.fit_pair_halo_blocks = orig_fit


def bench_mhd(size, iters, kernels, blocks, dtype="f32"):
    import jax
    import jax.numpy as jnp
    from stencil_tpu.models.astaroth import Astaroth

    dt = jnp.bfloat16 if dtype == "bf16" else jnp.float32

    def ctor(kernel):
        return Astaroth(size, size, size, mesh_shape=(1, 1, 1),
                        devices=jax.devices()[:1], kernel=kernel,
                        dtype=dt)

    _bench_model("mhd", ctor, size, iters, kernels, blocks,
                 _patch_mhd_blocks, warmup=2)


def _patch_mhd_blocks(m, kernel, blocks):
    import functools
    import sys
    from stencil_tpu.ops import pallas_mhd

    bz, by = blocks
    # the kernels snap non-tile-multiple blocks down to the dtype's
    # sublane tile (16-row for bf16): say so, or the CSV row would be
    # labeled with a shape that was never measured (same stderr note
    # the jacobi sweep prints on a substituted blocking)
    local = m.dd.local_size
    tile = pallas_mhd.mhd_tile(m._dtype)
    actual = pallas_mhd._fit_blocks(local.z, local.y, bz, by, tile)
    if actual != (bz, by):
        print(f"note: blocks {bz},{by} snapped to "
              f"{actual[0]},{actual[1]} (dtype tile {tile}, local "
              f"{local.z}x{local.y})", file=sys.stderr)
    if kernel == "wrap":
        # patch the fused substep-0+1 kernel too (STENCIL_MHD_PAIR=1
        # runs it for two of the three substeps)
        orig = pallas_mhd.mhd_substep_wrap_pallas
        orig01 = pallas_mhd.mhd_substep01_wrap_pallas
        pallas_mhd.mhd_substep_wrap_pallas = functools.partial(
            orig, block_z=bz, block_y=by)
        pallas_mhd.mhd_substep01_wrap_pallas = functools.partial(
            orig01, block_z=bz, block_y=by)
        try:
            m._build_wrap_step()
        finally:
            pallas_mhd.mhd_substep_wrap_pallas = orig
            pallas_mhd.mhd_substep01_wrap_pallas = orig01
    else:
        m._halo_blocks = (bz, by)
        m._build_halo_step()


def _one_line(e: Exception) -> str:
    """First line of an exception message, CSV-safe."""
    msg = f"{type(e).__name__}: {e}".splitlines()[0]
    return msg.replace(",", ";")


def _watchdog_sweep(args, kernels) -> int:
    """Run each (model, kernel) combo as a SUBPROCESS of this script
    with a wall-clock cap: a wedged accelerator tunnel can hang a
    Mosaic compile inside the C runtime for tens of minutes, which no
    in-process try/except can interrupt — a hung combo must cost one
    timeout and a FAIL line, not the whole sweep. Child stderr is
    forwarded so campaign .err logs stay useful; returns nonzero if
    any combo timed out or died without a result line."""
    import subprocess

    models = (("jacobi", "mhd") if args.model == "both"
              else (args.model,))
    env = dict(os.environ, STENCIL_BENCH_SUBPROC="1")
    failures = 0
    for model in models:
        for kernel in kernels:
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--model", model, "--kernels", kernel,
                   "--dtype", args.dtype]
            for flag, val in (("--size", args.size),
                              ("--iters", args.iters),
                              ("--fake-cpu", args.fake_cpu)):
                if val:
                    cmd += [flag, str(val)]
            if args.blocks:
                cmd += ["--blocks", args.blocks]
            try:
                out = subprocess.run(cmd, capture_output=True, text=True,
                                     timeout=args.per_kernel_timeout,
                                     env=env)
            except subprocess.TimeoutExpired as e:
                # forward whatever the child said before the kill —
                # that partial log is the only record of the hang
                for chunk in (e.stdout, e.stderr):
                    if chunk:
                        sys.stderr.write(
                            chunk if isinstance(chunk, str)
                            else chunk.decode(errors="replace"))
                print(f"{model},{kernel},{args.size or '?'},TIMEOUT,"
                      f"wall-clock cap {args.per_kernel_timeout}s "
                      f"(compile hang?)")
                failures += 1
                continue
            if out.stderr:
                sys.stderr.write(out.stderr)
            got_line = False
            for line in out.stdout.splitlines():
                if line.startswith(f"{model},"):
                    print(line)
                    got_line = True
            if not got_line:
                tail = (out.stderr or out.stdout).strip().splitlines()
                msg = (tail[-1][:160] if tail else "no output")
                print(f"{model},{kernel},{args.size or '?'},FAIL,"
                      f"{msg.replace(',', ';')}")
                failures += 1
    return failures


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="both",
                    choices=("jacobi", "mhd", "both"))
    ap.add_argument("--size", type=int, default=0,
                    help="cube edge (default 512 jacobi / 256 mhd)")
    ap.add_argument("--iters", type=int, default=0)
    ap.add_argument("--kernels", default="wrap,halo,xla")
    ap.add_argument("--blocks", default="",
                    help="bz,by override for pallas kernels")
    ap.add_argument("--dtype", default="f32", choices=("f32", "bf16"),
                    help="field dtype (bf16 halves HBM traffic; MHD "
                         "bf16 stores half-width, computes f32)")
    ap.add_argument("--fake-cpu", type=int, default=0, metavar="N",
                    help="run on N virtual CPU devices (smoke mode)")
    ap.add_argument("--per-kernel-timeout", type=int, default=0,
                    metavar="S",
                    help="run each model/kernel combo in a subprocess "
                         "with this wall-clock cap (0 = in-process, no "
                         "cap); a hang then costs one TIMEOUT line, "
                         "not the sweep")
    args = ap.parse_args()
    kernels = args.kernels.split(",")
    if (args.per_kernel_timeout
            and not os.environ.get("STENCIL_BENCH_SUBPROC")):
        sys.exit(1 if _watchdog_sweep(args, kernels) else 0)
    from stencil_tpu.utils.config import apply_fake_cpu, enable_compile_cache
    apply_fake_cpu(args.fake_cpu)
    enable_compile_cache()
    blocks = (tuple(int(v) for v in args.blocks.split(","))
              if args.blocks else None)

    import jax
    on_tpu = jax.default_backend() == "tpu"
    if args.model in ("jacobi", "both"):
        size = args.size or (512 if on_tpu else 32)
        iters = args.iters or (200 if on_tpu else 4)
        bench_jacobi(size, iters, kernels, blocks, args.dtype)
    if args.model in ("mhd", "both"):
        size = args.size or (256 if on_tpu else 16)
        iters = args.iters or (20 if on_tpu else 2)
        bench_mhd(size, iters, kernels, blocks, args.dtype)


if __name__ == "__main__":
    main()
