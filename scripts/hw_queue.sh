#!/usr/bin/env bash
# Hardware measurement queue: the ordered single-chip runs that
# validate this round's kernels, sized so each item lands a number
# (or a watchdog TIMEOUT line) even over a slow tunnel. Run when a
# chip is reachable; results append to hw_queue_<ts>.log in CSV form.
# The persistent compile cache (utils/config.enable_compile_cache)
# makes reruns cheap once an item has compiled.
set -uo pipefail
cd "$(dirname "$0")/.."
OUT="hw_queue_$(date +%Y%m%d_%H%M%S).log"
echo "hw queue -> $OUT"
WD=(--per-kernel-timeout 2400)
run() { echo "== $*" | tee -a "$OUT"; "$@" 2>>"$OUT.err" | tee -a "$OUT"; }

# 1. headline + wrap depth ladder (validates jacobi7_wrapn on hardware)
run python scripts/bench_kernels.py --model jacobi --kernels wrap \
    "${WD[@]}"
for n in 3 4; do
  run env STENCIL_WRAP_STEPS=$n python scripts/bench_kernels.py \
      --model jacobi --kernels wrap "${WD[@]}"
done

# 1b. limiter evidence: stream ceiling + depth ladder + verdict line
#     (what binds at 298 vs the ~500 traffic bound — BASELINE.md)
run timeout 2400 python scripts/profile_wrap.py

# 2. halo path: single-step vs pair vs depth-3 (multi-chip compute path)
run env STENCIL_DISABLE_WRAP2=1 python scripts/bench_kernels.py \
    --model jacobi --kernels halo "${WD[@]}"
run python scripts/bench_kernels.py --model jacobi --kernels halo \
    "${WD[@]}"
run env STENCIL_WRAP_STEPS=3 python scripts/bench_kernels.py \
    --model jacobi --kernels halo "${WD[@]}"

# 3. bf16 wrap + halo (half-traffic ladder)
run python scripts/bench_kernels.py --model jacobi --kernels wrap,halo \
    --dtype bf16 "${WD[@]}"

# 4. MHD wrap (thin-z + x-roll scheme) at candidate blockings,
#    plus the round-3 tiled-z layout as the A/B control
for b in "8,64" "8,32" "16,64"; do
  run python scripts/bench_kernels.py --model mhd --kernels wrap \
      --blocks "$b" "${WD[@]}"
done
run env STENCIL_MHD_THINZ=0 python scripts/bench_kernels.py --model mhd \
    --kernels wrap --blocks "8,32" "${WD[@]}"
run env STENCIL_MHD_PAIR=1 python scripts/bench_kernels.py --model mhd \
    --kernels wrap --blocks "8,32" "${WD[@]}"

# 5. MHD halo (x-roll window), thin-z default + tiled-z control,
#    plus the fused substep-0+1 pair on the halo path
run python scripts/bench_kernels.py --model mhd --kernels halo \
    "${WD[@]}"
run env STENCIL_MHD_THINZ=0 python scripts/bench_kernels.py --model mhd \
    --kernels halo "${WD[@]}"
run env STENCIL_MHD_PAIR=1 python scripts/bench_kernels.py --model mhd \
    --kernels halo "${WD[@]}"
# pair x in-kernel-RDMA-overlap composition (single chip: local wrap
# copies; the overlap benefit needs multi-chip ICI, but the schedule
# must not cost throughput)
run timeout 2400 env STENCIL_MHD_PAIR=1 python apps/astaroth.py \
    --nx 256 --ny 256 --nz 256 --iters 10 --kernel halo --overlap

# 6. overlap structure, single-chip (serialized vs in-kernel-RDMA
#    schedule with local wrap copies; real overlap_efficiency needs
#    multi-chip ICI — VERDICT r4 weak #2). MHD is where overlap pays
#    3x per iteration.
run timeout 2400 python apps/measure_overlap.py --x 256 --y 256 --z 256
run timeout 2400 python apps/measure_overlap.py --model mhd \
    --x 256 --y 256 --z 256 --iters 10

# 7. headline JSON
run python bench.py
echo "hw queue complete -> $OUT"
