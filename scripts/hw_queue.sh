#!/usr/bin/env bash
# Hardware measurement queue: the ordered single-chip runs that
# validate this round's kernels, sized so each item lands a number
# (or a watchdog TIMEOUT line) even over a slow tunnel. Results
# append to hw_queue_<ts>.log in CSV form. The persistent compile
# cache (utils/config.enable_compile_cache) makes reruns cheap once
# an item has compiled.
#
# The axon tunnel FLAPS (up for a window, wedged for a while): a
# probe can succeed and the very next backend init hang for 25 min
# before dying UNAVAILABLE. So the queue treats chip access as a
# perishable resource: it probes in a throwaway 90 s subprocess
# before EVERY item, waits out downtime between items instead of
# burning it inside backend init, and retries an item once if its
# output shows the backend died mid-run. The highest-value
# measurements (headline JSON, wrap pairs, halo pairs, bf16) are
# ordered first so a short tunnel window still lands them.
set -uo pipefail
cd "$(dirname "$0")/.."
# Single-instance guard: two concurrent queues would contend for the one
# chip (the loser burns its per-item retries on backend-init failures).
# Exit 3 (not 0) on contention so hw_watch.sh can tell "skipped" from
# "completed"; children run with fd 9 closed so an orphaned hung
# benchmark process can't keep the lock held after this shell dies.
exec 9>.hw_queue.lock
if ! flock -n 9; then
  echo "another hw_queue.sh holds .hw_queue.lock; exiting" >&2
  exit 3
fi
OUT="hw_queue_$(date +%Y%m%d_%H%M%S).log"
echo "hw queue -> $OUT"

# The raw hw_queue_*.log files are gitignored, but measurements must
# survive into the repo even if the session ends (or the tunnel dies)
# mid-queue: on exit OR a fatal signal (HUP/INT/TERM — SIGKILL cannot
# be covered), append this run's full transcript (tunnel-wait noise
# stripped, capped at 200 KB per run to bound the tracked file) to
# HW_RESULTS.md, skipping runs that never got past probing. The
# driver's end-of-round commit picks it up.
persist_results() {
  [ -s "$OUT" ] || return 0
  grep -q "^== \[" "$OUT" || return 0   # no item ever started
  {
    echo ""
    echo "## hw_queue run $(date -u +%Y-%m-%dT%H:%M:%SZ) ($OUT)"
    echo '```'
    grep -v "tunnel down (wait" "$OUT" | head -c 200000
    echo '```'
  } >> HW_RESULTS.md
}
trap persist_results EXIT
trap 'persist_results; trap - EXIT; exit 129' HUP
trap 'persist_results; trap - EXIT; exit 130' INT
trap 'persist_results; trap - EXIT; exit 143' TERM
WD=(--per-kernel-timeout 2400)
MAX_WAITS="${MAX_WAITS:-240}"   # 240 x 150 s = 10 h of patience, total
waits=0
. scripts/probe_tunnel.sh   # cwd is the repo root after the cd above

await_tunnel() {
  while ! probe 9>&-; do
    waits=$((waits + 1))
    echo "$(date +%T) tunnel down (wait $waits/$MAX_WAITS)" >>"$OUT"
    if [ "$waits" -ge "$MAX_WAITS" ]; then
      echo "$(date +%T) giving up: tunnel never recovered" | tee -a "$OUT"
      exit 1
    fi
    sleep "$PROBE_INTERVAL_S" 9>&-
  done
}

run() {
  # Apps without their own error handling (profile_wrap, measure_overlap,
  # astaroth) only show a backend death in their stderr, so the retry
  # check must read the new tail of BOTH $OUT and $OUT.err.
  local attempt marker emarker
  for attempt in 1 2; do
    await_tunnel
    echo "== [$(date +%T) try $attempt] $*" | tee -a "$OUT"
    marker=$(wc -l <"$OUT")
    emarker=$({ wc -l <"$OUT.err"; } 2>/dev/null || echo 0)
    { "$@" 2>>"$OUT.err" | tee -a "$OUT"; } 9>&-
    # tail -n +N starts AT line N, so +1 to read only this attempt's lines.
    # Match init-time deaths, mid-run tunnel losses (the XlaRuntimeError
    # UNAVAILABLE traceback), and bench.py's suspect JSON records — all
    # mean "the chip went away", not "the kernel is broken", so all earn
    # the one retry. Bare "UNAVAILABLE" is NOT enough: the TPU runtime
    # logs benign recovered-gRPC warnings with that word on successful
    # runs over a flaky tunnel.
    if { tail -n +"$((marker + 1))" "$OUT";
         tail -n +"$((emarker + 1))" "$OUT.err" 2>/dev/null; } \
        | grep -qE 'Unable to initialize backend|XlaRuntimeError.*UNAVAILABLE|"suspect": true'; then
      if [ "$attempt" -eq 2 ]; then
        echo "-- backend death or suspect record on both attempts;" \
             "giving up on this item (if the result reproduced, read the" \
             "suspect_reason: it may be a real perf signal, not a tunnel" \
             "failure)" | tee -a "$OUT"
      else
        echo "-- backend death or suspect record; retrying after next" \
             "good probe" | tee -a "$OUT"
      fi
      continue
    fi
    return 0
  done
}

# 1. headline JSON first — the round artifact (fail-fast probe built in)
run python bench.py

# 2. wrap pairs (the 298 iters/s kernel) + depth ladder 3/4
run python scripts/bench_kernels.py --model jacobi --kernels wrap \
    "${WD[@]}"
for n in 3 4; do
  run env STENCIL_WRAP_STEPS=$n python scripts/bench_kernels.py \
      --model jacobi --kernels wrap "${WD[@]}"
done

# 3. halo path: single-step vs pair vs depth-3 (multi-chip compute path;
#    the halo-vs-wrap gap is VERDICT r4 weak #2)
run env STENCIL_DISABLE_WRAP2=1 python scripts/bench_kernels.py \
    --model jacobi --kernels halo "${WD[@]}"
run python scripts/bench_kernels.py --model jacobi --kernels halo \
    "${WD[@]}"
run env STENCIL_WRAP_STEPS=3 python scripts/bench_kernels.py \
    --model jacobi --kernels halo "${WD[@]}"

# 4. bf16 wrap + halo (half-traffic ladder), then bf16 x depth-3
#    (the two biggest traffic levers composed)
run python scripts/bench_kernels.py --model jacobi --kernels wrap,halo \
    --dtype bf16 "${WD[@]}"
run env STENCIL_WRAP_STEPS=3 python scripts/bench_kernels.py \
    --model jacobi --kernels wrap --dtype bf16 "${WD[@]}"

# 5. limiter evidence: stream ceiling + depth ladder + verdict line
#    (what binds at 298 vs the ~500 traffic bound — BASELINE.md)
run timeout 2400 python scripts/profile_wrap.py

# 6. MHD wrap (thin-z + x-roll scheme) at candidate blockings,
#    plus the round-3 tiled-z layout as the A/B control
for b in "8,64" "8,32" "16,64"; do
  run python scripts/bench_kernels.py --model mhd --kernels wrap \
      --blocks "$b" "${WD[@]}"
done
run env STENCIL_MHD_THINZ=0 python scripts/bench_kernels.py --model mhd \
    --kernels wrap --blocks "8,32" "${WD[@]}"
run env STENCIL_MHD_PAIR=1 python scripts/bench_kernels.py --model mhd \
    --kernels wrap --blocks "8,32" "${WD[@]}"

# 7. MHD halo (x-roll window), thin-z default + tiled-z control,
#    plus the fused substep-0+1 pair on the halo path
run python scripts/bench_kernels.py --model mhd --kernels halo \
    "${WD[@]}"
run env STENCIL_MHD_THINZ=0 python scripts/bench_kernels.py --model mhd \
    --kernels halo "${WD[@]}"
run env STENCIL_MHD_PAIR=1 python scripts/bench_kernels.py --model mhd \
    --kernels halo "${WD[@]}"
# pair x in-kernel-RDMA-overlap composition (single chip: local wrap
# copies; the overlap benefit needs multi-chip ICI, but the schedule
# must not cost throughput)
run timeout 2400 env STENCIL_MHD_PAIR=1 python apps/astaroth.py \
    --nx 256 --ny 256 --nz 256 --iters 10 --kernel halo --overlap

# 7b. MHD bf16 (storage bf16, compute f32 — ops/pallas_mhd
#     .compute_dtype): the half-traffic ladder for the MHD app;
#     wrap + halo, then the substep-pair composition
run python scripts/bench_kernels.py --model mhd --kernels wrap,halo \
    --dtype bf16 "${WD[@]}"
run env STENCIL_MHD_PAIR=1 python scripts/bench_kernels.py --model mhd \
    --kernels wrap --dtype bf16 "${WD[@]}"
run env STENCIL_MHD_PAIR=1 python scripts/bench_kernels.py --model mhd \
    --kernels halo --dtype bf16 "${WD[@]}"

# 7c. MHD limiter evidence: stream ceiling + {seq,pair} x {f32,bf16}
#     ladder + LIMITER verdict (the MHD analog of item 5)
run timeout 2400 python scripts/profile_wrap.py --model mhd

# 8. overlap structure, single-chip (serialized vs in-kernel-RDMA
#    schedule with local wrap copies; real overlap_efficiency needs
#    multi-chip ICI — VERDICT r4 weak #2). MHD is where overlap pays
#    3x per iteration.
run timeout 2400 python apps/measure_overlap.py --x 256 --y 256 --z 256
run timeout 2400 python apps/measure_overlap.py --model mhd \
    --x 256 --y 256 --z 256 --iters 10

# 9. headline JSON again at the end (fresh record after the campaign)
run python bench.py
echo "hw queue complete -> $OUT"
