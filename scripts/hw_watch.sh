#!/usr/bin/env bash
# Tunnel watcher: probe the axon TPU in a watchdogged subprocess every
# ~2.5 min; the moment a probe answers, run the staged hardware queue
# (scripts/hw_queue.sh) exactly once and exit. Keeps the chip free
# between probes (each probe is its own short-lived process).
set -u -o pipefail
cd "$(dirname "$0")/.."
. scripts/probe_tunnel.sh   # cwd is the repo root after the cd above
LOG="hw_watch.log"
MAX_PROBES="${1:-200}"
echo "$(date +%T) watcher start (max $MAX_PROBES probes)" | tee -a "$LOG"
for ((i = 1; i <= MAX_PROBES; i++)); do
  if probe; then
    echo "$(date +%T) tunnel UP on probe $i — running hw queue" | tee -a "$LOG"
    bash scripts/hw_queue.sh 2>&1 | tee -a "$LOG"
    rc=$?
    echo "$(date +%T) hw queue finished rc=$rc" | tee -a "$LOG"
    exit "$rc"
  fi
  echo "$(date +%T) probe $i: tunnel down" >>"$LOG"
  sleep "$PROBE_INTERVAL_S"
done
echo "$(date +%T) watcher gave up after $MAX_PROBES probes" | tee -a "$LOG"
exit 1
