#!/usr/bin/env bash
# One-command benchmark campaign: reproduces every BASELINE.md row on
# the current backend (intended for a real TPU chip). Results land in
# campaign_<timestamp>/ as raw CSV/JSON logs, one file per experiment
# (the scripts/summit/512node_jacobi3d.sh:15-37 ethos: a reproducible
# sweep, every number written down).
#
# CAMPAIGN_SMOKE=1 runs the same sweep structure on an 8-device virtual
# CPU mesh with tiny sizes — a plumbing check for CI, not a benchmark.
set -uo pipefail
cd "$(dirname "$0")/.."

SMOKE="${CAMPAIGN_SMOKE:-0}"
OUT="$(pwd)/campaign_$(date +%Y%m%d_%H%M%S)"
mkdir -p "$OUT"
echo "campaign output -> $OUT/ (smoke=$SMOKE)"

FAKE=()
# per-kernel watchdog (tunnel-hang insurance) only matters on real
# hardware; smoke mode keeps the cheap in-process sweep
WD=(--per-kernel-timeout 2400)
if [ "$SMOKE" = "1" ]; then
    FAKE=(--fake-cpu 8)
    WD=()
    JN=16; JI=4; MN=16; MI=2; EX=8; EI=2
else
    JN=256; JI=50; MN=128; MI=10; EX=256; EI=30
fi

run() {  # run <logfile> <cmd...>; failures are recorded, not fatal
    local log="$OUT/$1" rc; shift
    echo "== $* (-> $log)"
    "$@" > "$log" 2> "$log.err"
    rc=$?
    if [ "$rc" -ne 0 ]; then
        echo "FAILED rc=$rc (see $log.err)" | tee -a "$log"
    fi
}

# 1. headline: jacobi3d 512^3 iters/s + exchange stats (BENCH schema;
#    needs the real backend — skipped in smoke mode)
if [ "$SMOKE" != "1" ]; then
    run bench.json python bench.py
fi

# 2. single-chip kernel A/B: wrap vs halo vs xla, both models
# (per-kernel watchdog: a wedged tunnel compile costs one TIMEOUT
# line, not the sweep)
run kernels_default.csv python scripts/bench_kernels.py \
    --model both --kernels wrap,halo,xla ${WD[@]+"${WD[@]}"} \
    "${FAKE[@]}"

# 3. block-shape sweeps at the benchmark sizes
for b in "8,128" "16,128" "8,256" "16,64"; do
    run "kernels_jacobi_b${b/,/x}.csv" python scripts/bench_kernels.py \
        --model jacobi --kernels wrap,halo --blocks "$b" \
        ${WD[@]+"${WD[@]}"} \
        --iters "$([ "$SMOKE" = 1 ] && echo 4 || echo 100)" "${FAKE[@]}"
done
for b in "8,32" "8,64" "16,32"; do
    run "kernels_mhd_b${b/,/x}.csv" python scripts/bench_kernels.py \
        --model mhd --kernels wrap,halo --blocks "$b" \
        ${WD[@]+"${WD[@]}"} \
        --iters "$([ "$SMOKE" = 1 ] && echo 2 || echo 10)" "${FAKE[@]}"
done
# fused RK substep-0+1 pair, wrap + halo paths (A/B vs the rows above)
run kernels_mhd_pair.csv env STENCIL_MHD_PAIR=1 \
    python scripts/bench_kernels.py --model mhd --kernels wrap,halo \
    ${WD[@]+"${WD[@]}"} \
    --iters "$([ "$SMOKE" = 1 ] && echo 2 || echo 10)" "${FAKE[@]}"
# bfloat16 (half HBM traffic; MHD stores bf16 / computes f32) — same
# default iteration counts as kernels_default.csv for a like-for-like
# f32-vs-bf16 A/B
run kernels_bf16.csv python scripts/bench_kernels.py \
    --model both --kernels wrap,halo --dtype bf16 ${WD[@]+"${WD[@]}"} \
    "${FAKE[@]}"
# limiter evidence: stream ceiling + ladder + LIMITER verdict per
# model (timeout = the same wedged-tunnel-compile insurance as the
# --per-kernel-timeout on the bench_kernels runs; profile_wrap
# compiles several variants per run and has no per-kernel flag)
PROF=()
if [ "$SMOKE" = "1" ]; then PROF=(--size 16 --iters 2); fi
run profile_jacobi.csv timeout 2400 python scripts/profile_wrap.py \
    ${PROF[@]+"${PROF[@]}"} "${FAKE[@]}"
run profile_mhd.csv timeout 2400 python scripts/profile_wrap.py \
    --model mhd ${PROF[@]+"${PROF[@]}"} "${FAKE[@]}"

# 4. exchange microbenchmarks (BASELINE.md configs 2/4 analogs)
( cd apps
  run bench_exchange.csv python bench_exchange.py \
      --x "$EX" --y "$EX" --z "$EX" --fr 2 --er 2 --cr 2 \
      --iters "$EI" "${FAKE[@]}"
  run bench_pack.csv python bench_pack.py "${FAKE[@]}"
  run pingpong.csv python pingpong.py "${FAKE[@]}"
  run bench_methods.csv python bench_methods.py \
      --x "$EX" --y "$EX" --z "$EX" --iters "$EI" "${FAKE[@]}"
  run bench_qap.csv python bench_qap.py --sizes 4 6 8
  # the fused fast paths' transfer standalone (same byte accounting as
  # the models' exchange_stats)
  run exchange_slabs.csv python exchange_weak.py \
      --x "$EX" --y "$EX" --z "$EX" --radius 3 --iters "$EI" \
      --interior-slabs "${FAKE[@]}"
)

# 5. apps at reference configs (weak scaling on whatever devices exist)
( cd apps
  run jacobi3d.csv python jacobi3d.py \
      --x "$JN" --y "$JN" --z "$JN" --iters "$JI" --batch 2 "${FAKE[@]}"
  run astaroth.csv python astaroth.py \
      --nx "$MN" --ny "$MN" --nz "$MN" --iters "$MI" "${FAKE[@]}"
  run measure_overlap.csv python measure_overlap.py \
      --x "$MN" --y "$MN" --z "$MN" --iters "$MI" "${FAKE[@]}"
)

echo "campaign complete: $OUT/"
# bench.json is absent in smoke mode; the summary glob must not turn a
# fully-green run into a nonzero exit
grep -H "" "$OUT"/*.csv "$OUT"/*.json 2>/dev/null | tail -40 || true
