# Shared axon-tunnel probe: sourced by hw_queue.sh and hw_watch.sh so
# the two agree on what "tunnel up" means. A throwaway subprocess with
# a hard timeout — a wedged backend init hangs without ever raising
# (it waits on RPC delivery), so an in-process check cannot catch it.
PROBE_TIMEOUT_S="${PROBE_TIMEOUT_S:-90}"
PROBE_INTERVAL_S="${PROBE_INTERVAL_S:-150}"

probe() {
  timeout "$PROBE_TIMEOUT_S" python -c \
    "import jax; d = jax.devices(); assert d[0].platform != 'cpu', d" \
    >/dev/null 2>&1
}
