#!/usr/bin/env python
"""What binds the fused kernels? Limiter evidence on hardware.

``--model jacobi`` (default) answers the round-4 question (BASELINE.md):
the temporally blocked pair kernel hit 298 iters/s at 512^3 against a
~500 iters/s HBM-traffic bound, so something other than traffic now
binds. ``--model mhd`` asks the same question of the MHD megakernel
(21.3 iters/s at 256^3 vs a ~2x higher traffic bound). One run gathers:

1. streaming ceiling: an elementwise-copy pass over the same arrays
   (the chip's practical HBM GB/s for this shape);
2. a ladder: jacobi wrap at temporal depths 1/2/3/4, or MHD at
   {sequential, substep-0+1 pair} x {f32, bf16} — if rates saturate
   while per-iteration traffic keeps dropping, the limiter is
   compute/issue, not HBM;
3. per-pass model: effective GB/s of each rung vs the ceiling — a rung
   whose per-PASS bandwidth sits well under the ceiling names the
   in-core pipeline (compute, DMA descriptors, grid overhead) as the
   binder; one that tracks the ceiling names traffic;
4. optional --trace DIR: wraps one timed window in
   ``jax.profiler.trace`` for TensorBoard-level confirmation.

Prints one CSV row per experiment plus a LIMITER line with the
verdict. Reference ethos: measure, then optimize
(scripts/summit/512node_jacobi3d.sh).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _stream_ceiling(n: int, tag: str) -> float:
    """Practical HBM GB/s for this shape: out = in + 1 (read + write)."""
    import jax
    import jax.numpy as jnp

    from stencil_tpu.utils.timers import device_sync

    item = 4  # f32
    x = jnp.zeros((n, n, n), jnp.float32)
    copy = jax.jit(lambda a: a + 1.0)
    y = copy(x)
    device_sync(y)
    t0 = time.perf_counter()
    reps = 20
    for _ in range(reps):
        y = copy(y)
    device_sync(y)
    dt = (time.perf_counter() - t0) / reps
    ceiling = 2 * n * n * n * item / dt / 1e9
    print(f"{tag},stream,{n},{ceiling:.1f} GB/s,{dt * 1e3:.3f} ms/pass")
    return ceiling


def _verdict(tag: str, rows, ceiling: float, sat: bool,
             deeper: str) -> None:
    best = max(rows, key=lambda r: r[1])
    frac = best[2] / ceiling if ceiling else 0
    if sat and frac < 0.7:
        verdict = ("rate saturates across rungs at {:.0%} of the "
                   "stream ceiling: COMPUTE/ISSUE-bound — {} won't "
                   "help; spend on in-core work (VPU ops per point, "
                   "DMA descriptor count, grid shape)"
                   .format(frac, deeper))
    elif frac >= 0.7:
        verdict = ("best rung runs at {:.0%} of the stream ceiling: "
                   "HBM-TRAFFIC-bound — {} still pays"
                   .format(frac, deeper))
    else:
        verdict = ("rates still rising at {:.0%} of ceiling: mixed — "
                   "keep laddering".format(frac))
    print(f"{tag},LIMITER,{best[0]} best "
          f"({best[1]:.1f} iters/s),{verdict}")


def _mhd_ladder(args) -> None:
    """MHD rungs: {sequential, pair} x {f32, bf16}, elision-aware
    traffic model (BASELINE.md: 80 field-volumes/iter sequential, 48
    pair, halved for bf16 storage; ring refetch excluded, so the
    effective-GB/s figures are lower bounds)."""
    import jax
    import jax.numpy as jnp

    from stencil_tpu.models.astaroth import Astaroth
    from stencil_tpu.numerics import trimean

    on_tpu = jax.default_backend() == "tpu"
    n = args.size or (256 if on_tpu else 32)
    iters = args.iters or (40 if on_tpu else 4)
    ceiling = _stream_ceiling(n, "profile_mhd")
    rows = []
    for pair in (False, True):
        for dtype, item in ((jnp.float32, 4), (jnp.bfloat16, 2)):
            label = (f"{'pair' if pair else 'seq'}-"
                     f"{'bf16' if item == 2 else 'f32'}")
            os.environ["STENCIL_MHD_PAIR"] = "1" if pair else "0"
            m = Astaroth(n, n, n, mesh_shape=(1, 1, 1),
                         devices=jax.devices()[:1], kernel="wrap",
                         dtype=dtype)
            m.init()
            m.run(2)
            m.block()
            window = max(iters // 4, 1)
            rates = []
            for _ in range(4):
                t0 = time.perf_counter()
                m.run(window)
                m.block()
                rates.append(window / (time.perf_counter() - t0))
            if args.trace and pair and item == 4:
                with jax.profiler.trace(args.trace):
                    m.run(window)
                    m.block()
                print(f"profile_mhd,trace,{args.trace}")
            rate = trimean(rates)
            # dead-w-elided model, in single-field n^3 volumes per
            # iteration (BASELINE.md: 80 sequential, 48 pair)
            volumes = 48.0 if pair else 80.0
            gbs = rate * volumes * n * n * n * item / 1e9
            rows.append((label, rate, gbs))
            print(f"profile_mhd,wrap,{n},{label},"
                  f"{rate:.1f} iters/s,{gbs:.1f} GB/s-effective")
            del m
    # saturation: does the pair rung fail to beat sequential at the
    # same dtype (traffic dropped 80->48 but rate stayed put)?
    sat = all(abs(p[1] - s[1]) < 0.15 * s[1]
              for s, p in ((rows[0], rows[2]), (rows[1], rows[3])))
    _verdict("profile_mhd", rows, ceiling, sat,
             "more substep fusion / bf16")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", choices=("jacobi", "mhd"),
                    default="jacobi")
    ap.add_argument("--size", type=int, default=0,
                    help="cube edge (jacobi: 512 on TPU, 64 off; "
                         "mhd: 256 / 32)")
    ap.add_argument("--iters", type=int, default=0)
    ap.add_argument("--trace", default="",
                    help="capture a jax.profiler trace of one window "
                         "into this directory")
    ap.add_argument("--fake-cpu", type=int, default=0, metavar="N")
    args = ap.parse_args()
    from stencil_tpu.utils.config import apply_fake_cpu, enable_compile_cache
    apply_fake_cpu(args.fake_cpu)
    enable_compile_cache()

    if args.model == "mhd":
        _mhd_ladder(args)
        return

    import jax
    import jax.numpy as jnp

    from stencil_tpu.models.jacobi import Jacobi3D
    from stencil_tpu.numerics import trimean

    on_tpu = jax.default_backend() == "tpu"
    n = args.size or (512 if on_tpu else 64)
    iters = args.iters or (120 if on_tpu else 8)
    item = 4  # f32

    ceiling = _stream_ceiling(n, "profile_wrap")

    # --- 2./3. depth ladder ------------------------------------------
    rows = []
    for depth in (1, 2, 3, 4):
        os.environ["STENCIL_WRAP_STEPS"] = str(depth)
        if depth == 1:
            os.environ["STENCIL_DISABLE_WRAP2"] = "1"
        else:
            os.environ.pop("STENCIL_DISABLE_WRAP2", None)
        j = Jacobi3D(n, n, n, mesh_shape=(1, 1, 1),
                     devices=jax.devices()[:1], kernel="wrap",
                     dtype=jnp.float32)
        j.init()
        j.run(depth * 2)
        j.block()
        window = max(iters // 4, depth)
        window -= window % depth
        rates = []
        for wi in range(4):
            t0 = time.perf_counter()
            j.run(window)
            j.block()
            rates.append(window / (time.perf_counter() - t0))
        if args.trace and depth == 2:
            # traced window runs EXTRA and is excluded from the rate
            # stats: profiler overhead would skew the depth-2 row and
            # could flip the LIMITER verdict
            with jax.profiler.trace(args.trace):
                j.run(window)
                j.block()
            print(f"profile_wrap,trace,{args.trace}")
        rate = trimean(rates)
        # per-iteration HBM traffic of the depth-N kernel ~ (1 read +
        # 1 write pass + ring refetch) / N; ring refetch small at 512
        passes_per_iter = 2.0 / depth
        gbs = rate * passes_per_iter * n * n * n * item / 1e9
        rows.append((f"depth {depth}", rate, gbs))
        print(f"profile_wrap,wrap,{n},depth {depth},"
              f"{rate:.1f} iters/s,{gbs:.1f} GB/s-effective")
        del j

    sat = all(abs(rows[i][1] - rows[i - 1][1]) < 0.15 * rows[i - 1][1]
              for i in range(2, len(rows)))
    _verdict("profile_wrap", rows, ceiling, sat,
             "deeper temporal blocking or bf16")


if __name__ == "__main__":
    main()
