#!/usr/bin/env python
"""What binds the wrap kernel? Evidence for the 298-vs-500 gap.

The round-4 measurement left a question (BASELINE.md): the temporally
blocked pair kernel hit 298 iters/s at 512^3 against a ~500 iters/s
HBM-traffic bound, so something other than traffic now binds. This
script gathers the evidence on hardware in one run:

1. streaming ceiling: an elementwise-copy pass over the same arrays
   (the chip's practical HBM GB/s for this shape);
2. depth ladder: wrap kernel at temporal depths 1/2/3/4 — if rates
   saturate while per-iteration traffic keeps dropping, the limiter is
   compute/issue, not HBM;
3. per-pass model: effective GB/s of each depth vs the ceiling — a
   depth whose per-PASS bandwidth sits well under the ceiling names
   the in-core pipeline (compute, DMA descriptors, grid overhead) as
   the binder; one that tracks the ceiling names traffic;
4. optional --trace DIR: wraps one timed window in
   ``jax.profiler.trace`` for TensorBoard-level confirmation.

Prints one CSV row per experiment plus a LIMITER line with the
verdict. Reference ethos: measure, then optimize
(scripts/summit/512node_jacobi3d.sh).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--size", type=int, default=0,
                    help="cube edge (default 512 on TPU, 64 off)")
    ap.add_argument("--iters", type=int, default=0)
    ap.add_argument("--trace", default="",
                    help="capture a jax.profiler trace of one window "
                         "into this directory")
    ap.add_argument("--fake-cpu", type=int, default=0, metavar="N")
    args = ap.parse_args()
    from stencil_tpu.utils.config import apply_fake_cpu, enable_compile_cache
    apply_fake_cpu(args.fake_cpu)
    enable_compile_cache()

    import jax
    import jax.numpy as jnp

    from stencil_tpu.models.jacobi import Jacobi3D
    from stencil_tpu.numerics import trimean
    from stencil_tpu.utils.timers import device_sync

    on_tpu = jax.default_backend() == "tpu"
    n = args.size or (512 if on_tpu else 64)
    iters = args.iters or (120 if on_tpu else 8)
    item = 4  # f32

    # --- 1. streaming ceiling: out = in + 1 over the same footprint ---
    x = jnp.zeros((n, n, n), jnp.float32)
    copy = jax.jit(lambda a: a + 1.0)
    y = copy(x)
    device_sync(y)
    t0 = time.perf_counter()
    reps = 20
    for _ in range(reps):
        y = copy(y)
    device_sync(y)
    dt = (time.perf_counter() - t0) / reps
    ceiling = 2 * n * n * n * item / dt / 1e9     # read + write
    print(f"profile_wrap,stream,{n},{ceiling:.1f} GB/s,"
          f"{dt * 1e3:.3f} ms/pass")

    # --- 2./3. depth ladder ------------------------------------------
    rows = []
    for depth in (1, 2, 3, 4):
        os.environ["STENCIL_WRAP_STEPS"] = str(depth)
        if depth == 1:
            os.environ["STENCIL_DISABLE_WRAP2"] = "1"
        else:
            os.environ.pop("STENCIL_DISABLE_WRAP2", None)
        j = Jacobi3D(n, n, n, mesh_shape=(1, 1, 1),
                     devices=jax.devices()[:1], kernel="wrap",
                     dtype=jnp.float32)
        j.init()
        j.run(depth * 2)
        j.block()
        window = max(iters // 4, depth)
        window -= window % depth
        rates = []
        for wi in range(4):
            t0 = time.perf_counter()
            j.run(window)
            j.block()
            rates.append(window / (time.perf_counter() - t0))
        if args.trace and depth == 2:
            # traced window runs EXTRA and is excluded from the rate
            # stats: profiler overhead would skew the depth-2 row and
            # could flip the LIMITER verdict
            with jax.profiler.trace(args.trace):
                j.run(window)
                j.block()
            print(f"profile_wrap,trace,{args.trace}")
        rate = trimean(rates)
        # per-iteration HBM traffic of the depth-N kernel ~ (1 read +
        # 1 write pass + ring refetch) / N; ring refetch small at 512
        passes_per_iter = 2.0 / depth
        gbs = rate * passes_per_iter * n * n * n * item / 1e9
        rows.append((depth, rate, gbs))
        print(f"profile_wrap,wrap,{n},depth {depth},"
              f"{rate:.1f} iters/s,{gbs:.1f} GB/s-effective")
        del j

    # --- verdict ------------------------------------------------------
    best = max(rows, key=lambda r: r[1])
    sat = all(abs(rows[i][1] - rows[i - 1][1]) < 0.15 * rows[i - 1][1]
              for i in range(2, len(rows)))
    frac = best[2] / ceiling if ceiling else 0
    if sat and frac < 0.7:
        verdict = ("rate saturates across depths at {:.0%} of the "
                   "stream ceiling: COMPUTE/ISSUE-bound — deeper "
                   "blocking won't help; spend on in-core work (VPU "
                   "ops per point, DMA descriptor count, grid "
                   "shape)".format(frac))
    elif frac >= 0.7:
        verdict = ("best depth runs at {:.0%} of the stream ceiling: "
                   "HBM-TRAFFIC-bound — deeper temporal blocking or "
                   "bf16 still pays".format(frac))
    else:
        verdict = ("rates still rising with depth at {:.0%} of "
                   "ceiling: mixed — keep laddering".format(frac))
    print(f"profile_wrap,LIMITER,depth {best[0]} best "
          f"({best[1]:.1f} iters/s),{verdict}")


if __name__ == "__main__":
    main()
