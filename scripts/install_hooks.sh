#!/usr/bin/env bash
# Install the local git pre-push hook that runs the smoke-tier CI
# pipeline (ci/run_ci.sh) before every push — stencil-lint is its
# stage 1, so a broken invariant fails in seconds, before any build.
# The local analog of the reference's service-triggered CI
# (.travis.yml:1-20). One-time setup:
#   bash scripts/install_hooks.sh
set -euo pipefail
cd "$(dirname "$0")/.."
HOOK=.git/hooks/pre-push
mkdir -p .git/hooks
cat > "$HOOK" <<'EOF'
#!/usr/bin/env bash
# auto-installed by scripts/install_hooks.sh: smoke-tier CI gate
# (stage 1 = stencil-lint, fails fast before the build). Bypass with
# `git push --no-verify` (e.g. docs-only changes).
exec env CI_TIER=smoke bash ci/run_ci.sh
EOF
chmod +x "$HOOK"
echo "installed $HOOK (stencil-lint + smoke-tier CI gate;" \
     "bypass: git push --no-verify)"
