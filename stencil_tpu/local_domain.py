"""LocalDomain: one subdomain's halo-padded, double-buffered fields.

TPU-native re-implementation of the reference's LocalDomain
(reference: include/stencil/local_domain.cuh:34-276,
src/local_domain.cu:86-219). Geometry conventions are identical:

* The *compute region* of a subdomain has size ``sz`` and global origin
  ``origin``.
* Each quantity is allocated halo-padded: the allocation ("raw") size is
  ``sz + pad_lo + pad_hi`` where the padding on each face side equals
  the face radius on that side (reference: local_domain.cuh raw_size()).
* Fields are double-buffered (curr/next); ``swap()`` exchanges the
  buffer tables (reference: src/local_domain.cu:67-84).

Array layout: JAX arrays are indexed ``arr[z, y, x]`` — x contiguous,
matching the reference's pitched layout where x is the fastest-varying
dimension. ``Dim3``/geometry values remain (x, y, z) ordered; helpers
convert at the array boundary.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax.numpy as jnp
import numpy as np

from .geometry import Dim3, Dim3Like, Radius, Rect3


def zyx_shape(sz: Dim3Like) -> Tuple[int, int, int]:
    """Convert an (x,y,z) Dim3 into a (z,y,x) array shape."""
    sz = Dim3.of(sz)
    return (sz.z, sz.y, sz.x)


def halo_pos(dir: Dim3Like, sz: Dim3Like, radius: Radius, halo: bool) -> Dim3:
    """Offset (in allocation coordinates, x/y/z order) of the halo region
    on side ``dir``: the *halo* itself when ``halo`` is True, else the
    interior ("exterior compute") region adjacent to that side.
    ``dir == 0`` on an axis selects the whole interior span on that axis.
    (reference: src/local_domain.cu:86-129 halo_pos)
    """
    dir = Dim3.of(dir)
    sz = Dim3.of(sz)
    out: List[int] = []
    for axis in range(3):
        d = dir[axis]
        n = sz[axis]
        r_lo = radius.face(axis, -1)
        if d == 1:
            out.append(n + (r_lo if halo else 0))
        elif d == -1:
            out.append(0 if halo else r_lo)
        else:
            out.append(r_lo)
    return Dim3(*out)


def halo_extent(dir: Dim3Like, sz: Dim3Like, radius: Radius) -> Dim3:
    """Point-extent of the halo region on side ``dir``; components use
    the *face* radii (reference: local_domain.cuh:212-222 halo_extent).
    ``dir == (0,0,0)`` returns ``sz``.
    """
    dir = Dim3.of(dir)
    sz = Dim3.of(sz)
    out: List[int] = []
    for axis in range(3):
        d = dir[axis]
        out.append(sz[axis] if d == 0 else radius.face(axis, d))
    return Dim3(*out)


def halo_bytes(dir: Dim3Like, sz: Dim3Like, radius: Radius, elem_size: int) -> int:
    """Bytes of one quantity's halo region on side ``dir``
    (reference: local_domain.cuh halo_bytes)."""
    return elem_size * halo_extent(dir, sz, radius).flatten()


def raw_size(sz: Dim3Like, radius: Radius) -> Dim3:
    """Allocation size including halo padding
    (reference: local_domain.cuh raw_size())."""
    sz = Dim3.of(sz)
    return sz + radius.pad_lo() + radius.pad_hi()


class Accessor:
    """Global-coordinate indexing into a padded local array — the
    app-facing "friendly coordinates" feature
    (reference: include/stencil/accessor.hpp:14-49).

    ``acc[(x, y, z)]`` reads the element at *global* grid coordinate
    (x, y, z) from the padded (z,y,x)-ordered array. The stored origin
    is ``domain origin - pad_lo`` so halo cells are addressable too.
    """

    def __init__(self, arr, origin: Dim3Like, radius: Radius) -> None:
        self.arr = arr
        origin = Dim3.of(origin)
        self.origin = origin - radius.pad_lo()

    def __getitem__(self, p: Dim3Like):
        p = Dim3.of(p) - self.origin
        return self.arr[p.z, p.y, p.x]

    def set(self, p: Dim3Like, v):
        """Functional update; returns a new array."""
        p = Dim3.of(p) - self.origin
        return self.arr.at[p.z, p.y, p.x].set(v)


class LocalDomain:
    """One subdomain's quantities on one device: halo-padded,
    double-buffered arrays plus halo-geometry queries
    (reference: include/stencil/local_domain.cuh:34-276).

    In JAX the buffers are immutable; ``curr``/``next_`` hold the
    current bindings and ``swap()`` exchanges them (the analog of the
    reference's pointer-table swap, src/local_domain.cu:67-84).
    """

    def __init__(self, sz: Dim3Like, origin: Dim3Like, radius: Radius) -> None:
        self.sz = Dim3.of(sz)
        self.origin = Dim3.of(origin)
        self.radius = radius
        self._names: List[str] = []
        self._dtypes: Dict[str, np.dtype] = {}
        self.curr: Dict[str, jnp.ndarray] = {}
        self.next_: Dict[str, jnp.ndarray] = {}

    # -- data management (reference: local_domain.cuh add_data) -------
    def add_data(self, name: str, dtype=jnp.float32) -> None:
        assert name not in self._dtypes, f"duplicate quantity {name}"
        self._names.append(name)
        self._dtypes[name] = np.dtype(dtype)

    def num_data(self) -> int:
        return len(self._names)

    @property
    def names(self) -> List[str]:
        return list(self._names)

    def elem_size(self, name: str) -> int:
        return self._dtypes[name].itemsize

    def realize(self) -> None:
        """Allocate zeroed curr/next padded arrays for every quantity
        (reference: src/local_domain.cu:159-219)."""
        shape = zyx_shape(self.raw_size())
        for name in self._names:
            dt = self._dtypes[name]
            self.curr[name] = jnp.zeros(shape, dtype=dt)
            self.next_[name] = jnp.zeros(shape, dtype=dt)

    def swap(self) -> None:
        self.curr, self.next_ = self.next_, self.curr

    # -- geometry -----------------------------------------------------
    def raw_size(self) -> Dim3:
        return raw_size(self.sz, self.radius)

    def size(self) -> Dim3:
        return self.sz

    def halo_pos(self, dir: Dim3Like, halo: bool) -> Dim3:
        return halo_pos(dir, self.sz, self.radius, halo)

    def halo_extent(self, dir: Dim3Like) -> Dim3:
        return halo_extent(dir, self.sz, self.radius)

    def halo_bytes(self, dir: Dim3Like, name: str) -> int:
        return halo_bytes(dir, self.sz, self.radius, self.elem_size(name))

    def halo_coords(self, dir: Dim3Like, halo: bool) -> Rect3:
        """Global coordinates of the halo (halo=True) or the
        interior send region adjacent to side ``dir`` (halo=False).

        The send region's width is the *opposite* face radius — the
        receiver's halo on its ``-dir`` side — matching the pairing the
        reference's packer uses (reference: src/packer.cu:116-118:
        halo_pos(dir, false) with halo_extent(dir * -1); the reference's
        own halo_coords pairs halo_extent(dir) instead, which reads out
        of bounds for asymmetric radii — intended semantics kept here).
        """
        pos = self.halo_pos(dir, halo)
        ext = self.halo_extent(Dim3.of(dir) if halo else -Dim3.of(dir))
        pos = pos - self.radius.pad_lo() + self.origin
        return Rect3(pos, pos + ext)

    def get_compute_region(self) -> Rect3:
        return Rect3(self.origin, self.origin + self.sz)

    # -- accessors ----------------------------------------------------
    def get_curr_accessor(self, name: str) -> Accessor:
        return Accessor(self.curr[name], self.origin, self.radius)

    def get_next_accessor(self, name: str) -> Accessor:
        return Accessor(self.next_[name], self.origin, self.radius)

    # -- host/debug copies (reference: src/local_domain.cu:131-157) ---
    def interior_slices(self) -> Tuple[slice, slice, slice]:
        """(z, y, x) slices selecting the compute interior of a padded
        array."""
        lo = self.radius.pad_lo()
        return (slice(lo.z, lo.z + self.sz.z),
                slice(lo.y, lo.y + self.sz.y),
                slice(lo.x, lo.x + self.sz.x))

    def interior_to_host(self, name: str) -> np.ndarray:
        """Copy the compute region to host, (z,y,x) ordered."""
        return np.asarray(self.curr[name][self.interior_slices()])

    def quantity_to_host(self, name: str) -> np.ndarray:
        """Copy the full padded region (including halos) to host."""
        return np.asarray(self.curr[name])


def interior_shrink(radius: Radius) -> Tuple[Dim3, Dim3]:
    """How far the interior pulls in from the compute region on the
    (lo, hi) side of each axis: the max radius over every direction
    touching that side (reference: src/stencil.cu:874-921 get_interior).
    """
    lo = Dim3(radius.max_side(0, -1), radius.max_side(1, -1), radius.max_side(2, -1))
    hi = Dim3(radius.max_side(0, 1), radius.max_side(1, 1), radius.max_side(2, 1))
    return lo, hi


def get_interior(dom: LocalDomain) -> Rect3:
    """Interior region: points whose stencil reads never touch the halo
    (reference: src/stencil.cu:874-921)."""
    lo_s, hi_s = interior_shrink(dom.radius)
    com = dom.get_compute_region()
    lo = com.lo + lo_s
    hi = com.hi - hi_s
    return Rect3(lo.elementwise_min(hi), hi.elementwise_max(lo))


def get_exterior(dom: LocalDomain) -> List[Rect3]:
    """Non-overlapping face-slab decomposition of compute-region minus
    interior, by sliding faces in (+x,+y,+z,-x,-y,-z order — reference:
    src/stencil.cu:927-977)."""
    int_reg = get_interior(dom)
    com = dom.get_compute_region()
    out: List[Rect3] = []
    lo = [com.lo.x, com.lo.y, com.lo.z]
    hi = [com.hi.x, com.hi.y, com.hi.z]
    for axis in (0, 1, 2):  # +x, +y, +z
        if int_reg.hi[axis] != hi[axis]:
            r_lo = [lo[0], lo[1], lo[2]]
            r_hi = [hi[0], hi[1], hi[2]]
            r_lo[axis] = int_reg.hi[axis]
            out.append(Rect3.of(tuple(r_lo), tuple(r_hi)))
            hi[axis] = int_reg.hi[axis]
    for axis in (0, 1, 2):  # -x, -y, -z
        if int_reg.lo[axis] != lo[axis]:
            r_lo = [lo[0], lo[1], lo[2]]
            r_hi = [hi[0], hi[1], hi[2]]
            r_hi[axis] = int_reg.lo[axis]
            out.append(Rect3.of(tuple(r_lo), tuple(r_hi)))
            lo[axis] = int_reg.lo[axis]
    return out
