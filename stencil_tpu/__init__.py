"""stencil_tpu: a TPU-native distributed 3D stencil / halo-exchange framework.

A brand-new JAX/XLA/Pallas re-design with the capabilities of
cwpearson/stencil (an MPI/CUDA halo-exchange library): automatic
communication-minimizing partitioning of a global 3D grid of multiple
quantities, topology-aware placement, per-direction variable-radius
(face/edge/corner, possibly asymmetric) halo exchange with periodic
boundaries, double-buffered fields, interior/exterior overlap queries,
and reference applications (Jacobi-3D, Astaroth-style MHD).

Instead of MPI ranks + CUDA streams/IPC, the data plane is a 3D
``jax.sharding.Mesh`` over the TPU ICI torus with ``shard_map`` +
``lax.ppermute`` (or Pallas async remote DMA) halo shifts, and the
compute plane is XLA/Pallas kernels.
"""

from . import _compat

_compat.install()

from .geometry import (Dim3, Rect3, Radius, all_directions, deepened,
                       direction_kind)
from .numerics import Statistics, div_ceil, next_align_of, prime_factors, trimean
from .partition import NodePartition, RankPartition, partition_dims_even
from .topology import Boundary, Topology

__version__ = "0.1.0"

__all__ = [
    "Dim3", "Rect3", "Radius", "all_directions", "deepened",
    "direction_kind",
    "Statistics", "div_ceil", "next_align_of", "prime_factors", "trimean",
    "NodePartition", "RankPartition", "partition_dims_even",
    "Boundary", "Topology",
    "__version__",
]
