"""Quadratic-assignment solvers for topology-aware placement.

Python front-end over the native C++ solvers in ``csrc/qap.cpp``
(reference: include/stencil/qap.hpp:51-180), with a pure-Python fallback
when the native library cannot be built. Matrices are numpy float64
``(n, n)`` arrays: ``w`` = communication weight between subdomain pairs,
``d`` = distance (1/bandwidth) between device pairs. Solvers return a
bijection ``f`` (list of device slots) minimizing
``sum_{a,b} w[a,b] * d[f[a],f[b]]`` with ``0 * inf == 0``.
"""

from __future__ import annotations

import ctypes
import itertools
import subprocess
import time
from pathlib import Path
from typing import List, Optional, Tuple

import numpy as np

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "csrc" / "qap.cpp"
_BUILD_DIR = _HERE / "_build"
_LIB_PATH = _BUILD_DIR / "libstencil_qap.so"

_lib: Optional[ctypes.CDLL] = None
_native_failed = False


def _build_native() -> Optional[ctypes.CDLL]:
    """Compile csrc/qap.cpp to a shared library (cached by mtime)."""
    global _native_failed
    if _native_failed:
        return None
    try:
        _BUILD_DIR.mkdir(exist_ok=True)
        if (not _LIB_PATH.exists()
                or _LIB_PATH.stat().st_mtime < _SRC.stat().st_mtime):
            cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                   str(_SRC), "-o", str(_LIB_PATH)]
            subprocess.run(cmd, check=True, capture_output=True)
        lib = ctypes.CDLL(str(_LIB_PATH))
        dp = ctypes.POINTER(ctypes.c_double)
        ip = ctypes.POINTER(ctypes.c_int64)
        lib.qap_solve_exact.restype = ctypes.c_double
        lib.qap_solve_exact.argtypes = [ctypes.c_int64, dp, dp, ip, ctypes.c_double]
        lib.qap_solve_catch.restype = ctypes.c_double
        lib.qap_solve_catch.argtypes = [ctypes.c_int64, dp, dp, ip]
        lib.qap_cost.restype = ctypes.c_double
        lib.qap_cost.argtypes = [ctypes.c_int64, dp, dp, ip]
        return lib
    except Exception:
        _native_failed = True
        return None


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is None and not _native_failed:
        _lib = _build_native()
    return _lib


def _cost_product(we: float, de: float) -> float:
    # 0 * inf == 0 by convention (reference: qap.hpp:16-21)
    if we == 0 or de == 0:
        return 0.0
    return we * de


def cost(w: np.ndarray, d: np.ndarray, f: List[int]) -> float:
    """Assignment cost (reference: qap.hpp detail::cost)."""
    w = np.asarray(w, dtype=np.float64)
    d = np.asarray(d, dtype=np.float64)
    n = w.shape[0]
    ret = 0.0
    for a in range(n):
        for b in range(n):
            ret += _cost_product(w[a, b], d[f[a], f[b]])
    return ret


def _as_c(arr: np.ndarray):
    arr = np.ascontiguousarray(arr, dtype=np.float64)
    return arr, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def solve(w: np.ndarray, d: np.ndarray, timeout_s: float = 10.0
          ) -> Tuple[List[int], float]:
    """Exact brute-force QAP with timeout (reference: qap.hpp:51-85)."""
    w = np.asarray(w, dtype=np.float64)
    d = np.asarray(d, dtype=np.float64)
    n = w.shape[0]
    assert w.shape == d.shape == (n, n)
    lib = _get_lib()
    if lib is not None:
        wk, wp = _as_c(w)
        dk, dp = _as_c(d)
        out = np.zeros(n, dtype=np.int64)
        c = lib.qap_solve_exact(n, wp, dp,
                                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                                float(timeout_s))
        return out.tolist(), float(c)
    # pure-Python fallback
    stop = time.monotonic() + timeout_s
    best_f = list(range(n))
    best_c = cost(w, d, best_f)
    for i, perm in enumerate(itertools.permutations(range(n))):
        if (i & 0x3FF) == 0 and time.monotonic() > stop:
            break
        c = cost(w, d, list(perm))
        if c < best_c:
            best_c, best_f = c, list(perm)
    return best_f, best_c


def solve_catch(w: np.ndarray, d: np.ndarray) -> Tuple[List[int], float]:
    """Greedy pairwise-swap hill climb (reference: qap.hpp:87-180)."""
    w = np.asarray(w, dtype=np.float64)
    d = np.asarray(d, dtype=np.float64)
    n = w.shape[0]
    assert w.shape == d.shape == (n, n)
    lib = _get_lib()
    if lib is not None:
        wk, wp = _as_c(w)
        dk, dp = _as_c(d)
        out = np.zeros(n, dtype=np.int64)
        c = lib.qap_solve_catch(n, wp, dp,
                                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        return out.tolist(), float(c)
    best_f = list(range(n))
    best_c = cost(w, d, best_f)
    improved = True
    while improved:
        improved = False
        impr_f, impr_c = best_f, best_c
        for i in range(n):
            for j in range(i + 1, n):
                f = list(best_f)
                f[i], f[j] = f[j], f[i]
                c = cost(w, d, f)
                if c < impr_c:
                    impr_f, impr_c = f, c
                    improved = True
        if improved:
            best_f, best_c = impr_f, impr_c
    return best_f, best_c


def native_available() -> bool:
    return _get_lib() is not None


def make_reciprocal(m: np.ndarray) -> np.ndarray:
    """Elementwise 1/m with 0 -> inf (reference: mat2d.hpp:188-204)."""
    m = np.asarray(m, dtype=np.float64)
    with np.errstate(divide="ignore"):
        return np.where(m == 0, np.inf, 1.0 / np.where(m == 0, 1.0, m))
