"""Checker 1: static access footprint vs. declared ``geometry.Radius``.

A stencil op computes interior values from a halo-padded (z,y,x) shard;
the exchange plan ships exactly the halo the *declared* radius claims.
If the op's true footprint reaches deeper than the declaration in ANY
of the 26 directions, the exchange under-delivers and the kernel
silently reads stale halo cells — the bug class TEMPI-style static
layout validation moves from "flaky numerics on hardware" to "red CI".

Method: trace the op to a jaxpr and collect every ``lax.slice`` whose
operand is (an alias of) a padded input. For a slice with per-axis
start/limit, the *reach* past the interior on side ``s`` of axis ``a``
is how far the access extends into the halo there. One access can
reach on several axes at once (cross-derivative pencils): for each
direction ``d`` the access penetrates the direction-``d`` halo region
to depth ``min over axes a with d_a != 0 of reach(a, d_a)``, and the
declared per-direction radius must cover the max over all accesses:

    radius.dir(d)  >=  max_access  min_{a: d_a != 0}  reach(a, d_a)

For face directions this reduces to the per-axis max reach; for
edge/corner directions it is exactly the reference's "edge radius
gates whether diagonal-neighbor data is required" rule
(src/stencil.cu:344): an access touching the (1,1,0) region at depth 3
demands edge radius >= 3 even when both face radii already equal 3.

Asymmetric radii are handled per side; allocation padding (``pad_lo`` /
``pad_hi``) may be declared independently of the radius for targets
whose buffers are sized by other layers — reaches are measured against
the interior box, radii are judged against the declaration.

Aliasing: the footprint follows the padded inputs through dtype casts
and elementwise ops (positions preserved — a slice of ``padded * c``
reads the same cells as a slice of ``padded``). Out of scope (reported
as warnings, never silently passed): dynamic slices of a padded input
(traced offsets), padded data flowing into ``scan``/``while`` bodies,
and any other primitive consuming the padded array
(position-scrambling ops like ``concatenate``/``roll``/``transpose``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..geometry import Dim3, Radius, all_directions
from .jaxprs import ClosedJaxpr, Jaxpr, Var, trace
from .report import ERROR, WARNING, Finding

# grid axis (0=x, 1=y, 2=z) -> array dim of a (z,y,x) block
_AXIS_TO_DIM = {0: 2, 1: 1, 2: 0}

# primitives that forward their (single) operand unchanged for
# footprint purposes
_PASSTHROUGH = ("convert_element_type", "copy", "stop_gradient")

# elementwise primitives preserve index positions: a slice of
# ``padded * c`` reads exactly the cells a slice of ``padded`` would,
# so the alias (and the footprint) propagates through them — provided
# the output shape matches the aliased operand (no broadcasting of
# the padded array itself)
_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "rem", "max", "min", "pow",
    "integer_pow", "neg", "sign", "abs", "exp", "log", "expm1",
    "log1p", "sqrt", "rsqrt", "cbrt", "square", "sin", "cos", "tan",
    "tanh", "logistic", "atan2", "select_n", "and", "or", "xor",
    "not", "eq", "ne", "lt", "le", "gt", "ge", "is_finite",
    "clamp", "nextafter",
})


@dataclasses.dataclass
class StencilOpSpec:
    """One traceable stencil op plus its declared halo contract.

    ``fn(*args)`` is traced abstractly; ``padded_argnums`` selects the
    positional args that are halo-padded (z,y,x) inputs. ``interior``
    is the interior extent (x,y,z); ``pad_lo``/``pad_hi`` default to
    the radius' allocation pads (``Radius.pad_lo/pad_hi``) and may be
    overridden when the buffer is padded beyond the declaration.
    """

    fn: Callable
    args: Sequence[Any]
    radius: Radius
    interior: Dim3
    padded_argnums: Tuple[int, ...] = (0,)
    pad_lo: Optional[Dim3] = None
    pad_hi: Optional[Dim3] = None

    def resolved_pads(self) -> Tuple[Dim3, Dim3]:
        lo = self.pad_lo if self.pad_lo is not None else self.radius.pad_lo()
        hi = self.pad_hi if self.pad_hi is not None else self.radius.pad_hi()
        return lo, hi


@dataclasses.dataclass
class StencilOpTarget:
    """Registry entry: a named, lazily-built :class:`StencilOpSpec`."""

    name: str
    build: Callable[[], StencilOpSpec]

    checker = "footprint"


# one access = per-(axis, side) halo reach depths
_Reach = Dict[Tuple[int, int], int]


def _slice_reach(start: Sequence[int], limit: Sequence[int],
                 pad_lo: Dim3, interior: Dim3) -> _Reach:
    reach: _Reach = {}
    for a in range(3):
        d = _AXIS_TO_DIM[a]
        lo = max(0, pad_lo[a] - int(start[d]))
        hi = max(0, int(limit[d]) - (pad_lo[a] + interior[a]))
        reach[(a, -1)] = lo
        reach[(a, 1)] = hi
    return reach


def _collect_accesses(jaxpr: Jaxpr, roots: set,
                      pad_lo: Dim3, interior: Dim3,
                      accesses: List[_Reach],
                      issues: List[str]) -> None:
    """Walk one jaxpr scope: record slice reaches on root-aliased vars,
    follow pass-through ops, recurse into call-like sub-jaxprs with the
    alias set translated, and note unverifiable flows."""
    alias = set(roots)
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        in_alias = [v for v in eqn.invars
                    if isinstance(v, Var) and v in alias]
        if name == "slice" and eqn.invars[0] in alias:
            accesses.append(_slice_reach(eqn.params["start_indices"],
                                         eqn.params["limit_indices"],
                                         pad_lo, interior))
            continue
        if name in _PASSTHROUGH and in_alias:
            for ov in eqn.outvars:
                alias.add(ov)
            continue
        if name in _ELEMENTWISE and in_alias:
            # positions preserved: propagate the alias when no
            # broadcasting reshapes the aliased operand
            shapes = {getattr(v.aval, "shape", None) for v in in_alias}
            for ov in eqn.outvars:
                if getattr(ov.aval, "shape", None) in shapes:
                    alias.add(ov)
            continue
        if name in ("dynamic_slice", "gather") and eqn.invars[0] in alias:
            issues.append(f"{name} of a padded input has traced offsets; "
                          f"footprint not statically checkable")
            continue
        if name in ("scan", "while") and in_alias:
            issues.append(f"padded input flows into a {name} loop; "
                          f"footprint not statically checkable")
            continue
        # call-like eqns: map operands to sub-jaxpr invars and recurse
        sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
        if sub is not None and in_alias:
            sj = sub.jaxpr if isinstance(sub, ClosedJaxpr) else sub
            if isinstance(sj, Jaxpr):
                operands = eqn.invars[len(eqn.invars) - len(sj.invars):]
                sub_roots = {iv for iv, ov in zip(sj.invars, operands)
                             if isinstance(ov, Var) and ov in alias}
                _collect_accesses(sj, sub_roots, pad_lo, interior,
                                  accesses, issues)
            continue
        if name == "cond" and in_alias:
            branches = eqn.params.get("branches", ())
            operands = eqn.invars[1:]
            for br in branches:
                bj = br.jaxpr if isinstance(br, ClosedJaxpr) else br
                sub_roots = {iv for iv, ov in zip(bj.invars, operands)
                             if isinstance(ov, Var) and ov in alias}
                _collect_accesses(bj, sub_roots, pad_lo, interior,
                                  accesses, issues)
            continue
        if in_alias:
            # anything else consuming the (aliased) padded array hides
            # accesses from the checker — surface it rather than pass
            # silently (position-scrambling ops like concatenate /
            # roll / transpose land here by design)
            issues.append(f"padded input consumed by unanalyzed "
                          f"primitive '{name}'; accesses through its "
                          f"result are not tracked")


def required_radius(accesses: Sequence[_Reach]) -> Dict[Tuple[int, int, int], int]:
    """Per-direction minimum radius implied by the access set."""
    req: Dict[Tuple[int, int, int], int] = {}
    for d in all_directions():
        axes = [(a, d[a]) for a in range(3) if d[a] != 0]
        best = 0
        for reach in accesses:
            depth = min(reach[k] for k in axes)
            best = max(best, depth)
        req[tuple(d)] = best
    return req


def check_stencil_op(target: StencilOpTarget) -> List[Finding]:
    """Prove (or refute) that the target's declared Radius covers its
    static access footprint in all 26 directions."""
    try:
        spec = target.build()
    except Exception as e:  # noqa: BLE001 - any build error is a finding
        return [Finding("footprint", target.name,
                        f"target build failed: {type(e).__name__}: {e}")]
    pad_lo, _pad_hi = spec.resolved_pads()
    try:
        closed = trace(spec.fn, *spec.args)
    except Exception as e:  # noqa: BLE001 - OOB slices land here
        return [Finding("footprint", target.name,
                        f"trace failed (op reads outside its padded "
                        f"allocation?): {type(e).__name__}: {e}")]
    jaxpr = closed.jaxpr
    roots = {jaxpr.invars[i] for i in spec.padded_argnums}
    accesses: List[_Reach] = []
    issues: List[str] = []
    _collect_accesses(jaxpr, roots, pad_lo, spec.interior, accesses, issues)

    findings = [Finding("footprint", target.name, msg, WARNING)
                for msg in sorted(set(issues))]
    if not accesses:
        if not issues:
            findings.append(Finding(
                "footprint", target.name,
                "no static slice accesses of the padded input found; "
                "nothing to verify", WARNING))
        return findings

    req = required_radius(accesses)
    for d, need in sorted(req.items()):
        have = spec.radius.dir(d)
        if have < need:
            findings.append(Finding(
                "footprint", target.name,
                f"direction {d}: declared radius {have} < required "
                f"{need} — the exchange plan under-delivers halo data "
                f"the op reads", ERROR))
    return findings
