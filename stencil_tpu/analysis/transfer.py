"""Checker 8: host-transfer audit — no host escapes inside hot loops.

A step or segment program must stay on the device: the run loops' only
sanctioned readbacks are the async probe trace and the checkpoint
boundary copies, both of which live OUTSIDE the jitted step program
and poll ``is_ready`` instead of blocking. Anything host-shaped
*inside* the compiled hot path — a ``jax.debug.print`` left over from
debugging, a ``pure_callback``/``io_callback`` escape, an
infeed/outfeed, a ``device_put`` onto host memory — serializes the
step pipeline on a host round-trip every dispatch (the silent-fallback
failure mode TEMPI instruments against, arXiv:2012.14363). This
checker walks each registered entry point's jaxpr (tracing only,
nothing executes) and flags every such escape as an ERROR.

The static gate has a runtime twin: :func:`hot_loop_transfer_guard`
wraps the fused-segment dispatch in ``resilience/driver.py`` and
``serving/service.py`` with ``jax.transfer_guard("disallow")``, so an
*implicit* host↔device (or cross-device reshard) transfer that only
materializes at dispatch time fails loudly in CI's chaos/service
smokes instead of shipping as a latency cliff. Sanctioned movements
are explicit by construction — ``jax.device_put`` with the mesh
sharding (see ``parallel/megastep.metric_base_vec`` and the ensemble
parameter plumbing). ``STENCIL_ALLOW_TRANSFERS=1`` is the operator
escape hatch.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
from typing import Any, Callable, Dict, List, Sequence, Tuple

from .jaxprs import iter_eqns, trace
from .report import ERROR, Finding

#: jaxpr primitives that round-trip through the host per dispatch
HOST_ESCAPE_PRIMS: Dict[str, str] = {
    "pure_callback": "a Python callback runs on host every dispatch",
    "io_callback": "an I/O callback runs on host every dispatch",
    "debug_callback": "jax.debug.print/callback stalls on host I/O",
    "debug_print": "debug printing stalls on host I/O",
    "infeed": "infeed blocks the step on host-fed data",
    "outfeed": "outfeed pushes device data at the host mid-step",
}

#: the env var that disables the runtime transfer guard
ALLOW_TRANSFERS_ENV = "STENCIL_ALLOW_TRANSFERS"


def hot_loop_transfer_guard():
    """The runtime guard the fused-segment dispatch sites run under:
    ``jax.transfer_guard("disallow")`` — implicit transfers raise,
    explicit ``jax.device_put`` stays allowed — unless
    ``STENCIL_ALLOW_TRANSFERS=1`` opts out."""
    if os.environ.get(ALLOW_TRANSFERS_ENV, "") == "1":
        return contextlib.nullcontext()
    import jax

    return jax.transfer_guard("disallow")


@dataclasses.dataclass
class TransferSpec:
    """A hot-path program plus its (normally empty) escape allowance.

    ``allow`` names jaxpr primitives from :data:`HOST_ESCAPE_PRIMS`
    the target is sanctioned to contain — no shipped target declares
    any; the knob exists so a future, deliberately host-coupled
    program documents its exception in the registry instead of
    weakening the checker."""

    fn: Callable
    args: Sequence[Any]
    allow: Tuple[str, ...] = ()


@dataclasses.dataclass
class TransferTarget:
    name: str
    build: Callable[[], TransferSpec]

    checker = "transfer"


def _device_put_host_kinds(eqn) -> List[str]:
    """Host-memory destinations of a ``device_put`` eqn (TPU host
    offload: ``TransferToMemoryKind('pinned_host')`` and friends)."""
    kinds: List[str] = []
    for key in ("devices", "device", "srcs", "src"):
        v = eqn.params.get(key)
        items = v if isinstance(v, (tuple, list)) else [v]
        for item in items:
            kind = getattr(item, "memory_kind", None)
            if kind is not None and "host" in str(kind):
                kinds.append(str(kind))
    return kinds


def collect_escapes(fn: Callable, args: Sequence[Any]
                    ) -> Tuple[Dict[str, int], List[str], int]:
    """Trace ``fn`` and walk every (nested) eqn: returns the host-
    escape primitive counts, host-memory device_put kinds, and the
    total device_put count."""
    closed = trace(fn, *args)
    escapes: Dict[str, int] = {}
    host_puts: List[str] = []
    n_device_put = 0
    for eqn in iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name in HOST_ESCAPE_PRIMS:
            escapes[name] = escapes.get(name, 0) + 1
        elif name == "device_put":
            n_device_put += 1
            host_puts.extend(_device_put_host_kinds(eqn))
    return escapes, host_puts, n_device_put


def check_transfer(target: TransferTarget) -> Tuple[List[Finding], Dict]:
    """Prove the target's traced program contains no host escape."""
    try:
        spec = target.build()
    except Exception as e:  # noqa: BLE001
        return [Finding("transfer", target.name,
                        f"target build failed: {type(e).__name__}: {e}")], {}
    try:
        escapes, host_puts, n_device_put = collect_escapes(spec.fn,
                                                           spec.args)
    except Exception as e:  # noqa: BLE001
        return [Finding("transfer", target.name,
                        f"trace failed: {type(e).__name__}: {e}")], {}

    metrics = {"host_escapes": dict(sorted(escapes.items())),
               "device_puts": n_device_put}
    findings: List[Finding] = []
    for name, count in sorted(escapes.items()):
        if name in spec.allow:
            continue
        findings.append(Finding(
            "transfer", target.name,
            f"hot path contains {count}x {name} — "
            f"{HOST_ESCAPE_PRIMS[name]}; the only sanctioned readbacks "
            f"are the async probe trace and checkpoint boundary "
            f"copies, which live outside the compiled step", ERROR))
    for kind in host_puts:
        findings.append(Finding(
            "transfer", target.name,
            f"device_put onto host memory ({kind}) inside the step "
            f"program — a host round-trip per dispatch", ERROR))
    return findings, metrics
