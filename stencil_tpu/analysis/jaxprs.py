"""Shared jaxpr-walking utilities for the stencil-lint checkers.

All three checkers operate on the same substrate: trace a function to a
jaxpr WITHOUT executing it (``jax.make_jaxpr`` over
``ShapeDtypeStruct``s), then pattern-match primitives. Nothing here
moves a byte — tracing is pure Python, so the whole pass runs in
seconds on any backendless CI box.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional, Tuple

import jax
from jax import core as jax_core

Jaxpr = jax_core.Jaxpr
ClosedJaxpr = jax_core.ClosedJaxpr
Literal = jax_core.Literal
Var = jax_core.Var


def trace(fn: Callable, *args: Any) -> ClosedJaxpr:
    """Trace ``fn`` on abstract arguments (no FLOPs, no devices)."""
    return jax.make_jaxpr(fn)(*args)


def _param_jaxprs(params: dict) -> Iterator[Jaxpr]:
    """Every sub-jaxpr reachable through an eqn's params (pjit bodies,
    cond branches, scan/while bodies, pallas kernels, shard_map...)."""
    for v in params.values():
        if isinstance(v, ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for item in v:
                if isinstance(item, ClosedJaxpr):
                    yield item.jaxpr
                elif isinstance(item, Jaxpr):
                    yield item


def iter_eqns(jaxpr: Jaxpr) -> Iterator[jax_core.JaxprEqn]:
    """All eqns of ``jaxpr`` and (recursively) of every sub-jaxpr, in
    syntactic order."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _param_jaxprs(eqn.params):
            yield from iter_eqns(sub)


def find_pallas_kernels(jaxpr: Jaxpr) -> List[Tuple[str, Jaxpr]]:
    """(kernel_name, kernel_jaxpr) for every ``pallas_call`` reachable
    from ``jaxpr`` (through jit/shard_map/cond/... nesting)."""
    out: List[Tuple[str, Jaxpr]] = []
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name != "pallas_call":
            continue
        kj = eqn.params.get("jaxpr")
        if isinstance(kj, ClosedJaxpr):
            kj = kj.jaxpr
        if not isinstance(kj, Jaxpr):
            continue
        info = eqn.params.get("name_and_src_info")
        name = getattr(info, "name", None) or str(info) or "<kernel>"
        out.append((name, kj))
    return out


def leaf_aval(leaf: Any) -> Tuple[Tuple[int, ...], str, bool]:
    """(shape, dtype, weak_type) of an array-ish leaf."""
    import numpy as np

    shape = tuple(int(d) for d in getattr(leaf, "shape", ()))
    dtype = str(np.dtype(getattr(leaf, "dtype", np.float32)))
    weak = bool(getattr(leaf, "weak_type", False))
    aval = getattr(leaf, "aval", None)
    if aval is not None:
        weak = bool(getattr(aval, "weak_type", weak))
    return shape, dtype, weak


def flat_with_paths(tree: Any) -> List[Tuple[str, Any]]:
    """(path_string, leaf) pairs in canonical flatten order."""
    return [("".join(str(k) for k in path), leaf) for path, leaf in
            jax.tree_util.tree_flatten_with_path(tree)[0]]


def dtype_pairs(curr: Any, next_: Any
                ) -> Optional[List[Tuple[str,
                                         Tuple[Tuple[int, ...], str, bool],
                                         Tuple[Tuple[int, ...], str, bool]]]]:
    """The shared curr/next dtype-pair walk: flatten both trees and
    pair each leaf's (shape, dtype, weak_type) by position —
    ``(path, curr_aval, next_aval)`` per leaf, or ``None`` when the
    two trees disagree on leaf count (the pytree itself drifted).
    Both the recompile checker (carried-state fingerprint stability)
    and the precision checker (wire formats must not leak into the
    carried state) consume this one walker."""
    cf, nf = flat_with_paths(curr), flat_with_paths(next_)
    if len(cf) != len(nf):
        return None
    return [(cpath, leaf_aval(cleaf), leaf_aval(nleaf))
            for (cpath, cleaf), (_np, nleaf) in zip(cf, nf)]


def literal_int(x: Any) -> Optional[int]:
    """Static integer value of a jaxpr atom, or None when traced."""
    if isinstance(x, Literal):
        try:
            return int(x.val)
        except (TypeError, ValueError):
            return None
    if isinstance(x, (int,)):
        return int(x)
    return None


def is_semaphore_ref(atom: Any) -> bool:
    """True for operands typed as Pallas semaphore memory (the aval
    prints as ``MemRef<semaphore_mem>{dma_sem[...]}`` / barrier_sem)."""
    aval = getattr(atom, "aval", None)
    if aval is None:
        return False
    s = str(aval)
    return "sem" in s and ("semaphore" in s or "barrier" in s
                           or "dma_sem" in s)


def index_key(transforms: Any) -> Tuple:
    """Hashable static description of a ref's indexers (``.at[...]``)
    for identity purposes: literal ints stay ints, traced indices
    become the wildcard '?'. Two refs with equal (var, index_key) are
    treated as the same semaphore cell."""
    out: List[Any] = []

    def visit(o: Any) -> None:
        if isinstance(o, (tuple, list)):
            for i in o:
                visit(i)
            return
        n = literal_int(o)
        if n is not None:
            out.append(n)
        elif isinstance(o, Var):
            out.append("?")
        else:
            # NDIndexer / Slice carriers: recurse into their leaves
            indices = getattr(o, "indices", None)
            if indices is not None:
                visit(indices)
                return
            start = getattr(o, "start", None)
            size = getattr(o, "size", None)
            if start is not None or size is not None:
                visit([start, size])
    visit(transforms)
    return tuple(out)
