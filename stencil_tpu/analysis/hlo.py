"""Checker 4: HLO-level collective audit — what XLA actually lowers to.

The jaxpr checkers prove the *program we wrote* (ppermute bijections,
DMA discipline); this checker proves the *program XLA sees*. A halo
exchange must lower to ``stablehlo.collective_permute`` only — the
point-to-point neighbor shift that moves exactly the halo bytes. Any
``all_gather`` / ``all_reduce`` / ``all_to_all`` / ``reduce_scatter``
in a step function means the exchange fell off the fast path (an
accidental gather from a mis-specced shard_map, a psum smuggled into a
hot loop) and the wire cost jumps from O(halo) to O(domain) — the XLA
analog of TEMPI's silent fallback from the fast MPI data path
(PAPERS.md). Catching it here costs seconds on a backendless CPU box,
not a TPU-hour.

Method: ``jax.jit(fn).lower(*args)`` under the fake multi-device CPU
mesh — lowering only, nothing compiles or executes — then walk the
StableHLO module and collect every collective op with its operand
shape, element type, and per-shard byte count. The byte counts feed
the :mod:`.costmodel` cross-check against the analytic halo model.

Capability gates (recorded as metrics, never silent):

* Pallas kernels with ``interpret=False`` cannot lower off-TPU
  ("Only interpret mode is supported on CPU backend") — targets whose
  jaxpr contains a ``pallas_call`` are skipped off-TPU with a note;
  the dma/vmem checkers still cover them statically.
* images whose JAX cannot produce StableHLO for a shard_map program
  at all skip the checker with a note (probed once per process).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .jaxprs import iter_eqns, trace
from .report import ERROR, WARNING, Finding

# the wire collectives worth auditing, by StableHLO op name
WIRE_COLLECTIVES = ("collective_permute", "all_gather", "all_reduce",
                    "all_to_all", "reduce_scatter", "collective_broadcast")

# StableHLO element type -> bytes (the types the framework can emit)
_MLIR_ELEM_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "f8E4M3FN": 1, "f8E5M2": 1,
    "i64": 8, "i32": 4, "i16": 2, "i8": 1, "i1": 1,
    "ui64": 8, "ui32": 4, "ui16": 2, "ui8": 1,
    "c64": 8, "c128": 16,
}


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    """One wire collective in the lowered module."""

    kind: str                 # StableHLO op name, e.g. "collective_permute"
    shape: Tuple[int, ...]    # operand (per-shard) shape
    elem_type: str            # StableHLO element type, e.g. "f32"
    bytes_per_shard: int      # operand bytes each shard puts on the wire


@dataclasses.dataclass
class HloSpec:
    """A jittable program plus its allowed collective vocabulary.

    ``allow`` names the StableHLO collectives the program may lower to
    (default: collective-permute only — the halo-exchange contract).
    The all-gather *control* strategy registers itself with
    ``allow=("all_gather",)`` — deliberately O(domain), benchmarked as
    such. ``expect_collective`` guards against the checker passing
    vacuously on a refactor that traced away the exchange.
    ``exact_counts`` pins the op count of specific kinds — the health
    sentinel registers its probe with ``{"all_reduce": 1}`` to prove
    it adds exactly one small all-reduce and nothing else.
    """

    fn: Callable
    args: Sequence[Any]
    allow: Tuple[str, ...] = ("collective_permute",)
    expect_collective: bool = True
    exact_counts: Optional[Dict[str, int]] = None


@dataclasses.dataclass
class HloTarget:
    name: str
    build: Callable[[], HloSpec]

    checker = "hlo"


_lowering_supported: Optional[bool] = None


def lowering_supported() -> bool:
    """Probe (once) whether this JAX can lower a SHARD_MAP program to
    StableHLO on the current backend — the capability gate CI uses.
    The probe is a real (1-device) shard_map with a collective, so a
    compat-shimmed jax whose shard_map only traces fails the probe and
    the checkers record skips instead of erroring every target."""
    global _lowering_supported
    if _lowering_supported is None:
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P

        try:
            mesh = Mesh(jax.devices()[:1], ("_probe",))
            sm = jax.shard_map(
                lambda x: jax.lax.psum(x, "_probe"), mesh=mesh,
                in_specs=P(), out_specs=P(), check_vma=False)
            lowered = jax.jit(sm).lower(
                jax.ShapeDtypeStruct((2,), jnp.float32))
            lowered.compiler_ir(dialect="stablehlo")
            _lowering_supported = True
        except Exception:  # noqa: BLE001 - any failure means "cannot"
            _lowering_supported = False
    return _lowering_supported


def _elem_bytes(elem: str) -> int:
    return _MLIR_ELEM_BYTES.get(elem, 4)


def _walk_module(module) -> List[CollectiveOp]:
    """Collect wire collectives by walking the MLIR module's regions."""
    out: List[CollectiveOp] = []
    names = {f"stablehlo.{k}": k for k in WIRE_COLLECTIVES}

    def visit(op) -> None:
        for region in op.regions:
            for block in region.blocks:
                for o in block.operations:
                    kind = names.get(o.operation.name)
                    if kind is not None and len(o.operands):
                        t = o.operands[0].type
                        shape = tuple(int(d) for d in t.shape)
                        elem = str(t.element_type)
                        n = 1
                        for d in shape:
                            n *= d
                        out.append(CollectiveOp(
                            kind, shape, elem, n * _elem_bytes(elem)))
                    visit(o)

    visit(module.operation)
    return out


_TEXT_RE = None


def _walk_text(text: str) -> List[CollectiveOp]:
    """Regex fallback over ``lower(...).as_text()`` for images whose
    MLIR python bindings cannot walk the module. Collectives with a
    reduction region (all_reduce) keep their type signature on the
    op's closing line, so a line-oriented scan with a pending-kind
    state machine sees every op exactly once."""
    import re

    global _TEXT_RE
    if _TEXT_RE is None:
        _TEXT_RE = {
            "op": re.compile(r'stablehlo\.(%s)\b'
                             % "|".join(WIRE_COLLECTIVES)),
            "sig": re.compile(r':\s*\(tensor<([0-9x]*)([a-z][a-z0-9]*)>'),
        }
    out: List[CollectiveOp] = []
    pending: Optional[str] = None
    for line in text.splitlines():
        m = _TEXT_RE["op"].search(line)
        if m:
            pending = m.group(1)
        if pending is None:
            continue
        sig = _TEXT_RE["sig"].search(line)
        if sig is None:
            continue
        dims, elem = sig.group(1), sig.group(2)
        shape = tuple(int(d) for d in dims.split("x") if d)
        n = 1
        for d in shape:
            n *= d
        out.append(CollectiveOp(pending, shape, elem, n * _elem_bytes(elem)))
        pending = None
    return out


def collect_collectives(fn: Callable, args: Sequence[Any]
                        ) -> List[CollectiveOp]:
    """Lower ``fn`` (lowering only — nothing compiles or runs) and
    return every wire collective in the StableHLO module."""
    import jax

    lowered = jax.jit(fn).lower(*args)
    try:
        return _walk_module(lowered.compiler_ir(dialect="stablehlo"))
    except Exception:  # noqa: BLE001 - binding quirks -> text fallback
        return _walk_text(lowered.as_text())


def contains_pallas(fn: Callable, args: Sequence[Any],
                    closed=None) -> bool:
    """True when the traced program contains a ``pallas_call`` (which
    cannot lower off-TPU with ``interpret=False``). Pass an already-
    traced ``closed`` jaxpr to skip the (shard_map-dominated) re-trace."""
    if closed is None:
        closed = trace(fn, *args)
    return any(eqn.primitive.name == "pallas_call"
               for eqn in iter_eqns(closed.jaxpr))


_PALLAS_SKIP_NOTE = ("contains pallas_call; lowering needs a TPU "
                     "backend (dma/vmem checkers cover it statically)")


def pallas_unlowerable(fn: Callable, args: Sequence[Any],
                       closed=None) -> bool:
    """The shared capability gate for the lowering-based checkers:
    True when the program contains a ``pallas_call`` AND the backend
    is not a TPU (the only place Mosaic can lower it). On a TPU the
    gate opens and pallas targets lower like everything else."""
    import jax

    if jax.default_backend() == "tpu":
        return False
    return contains_pallas(fn, args, closed=closed)


def summarize(ops: Sequence[CollectiveOp]) -> Dict[str, Dict[str, int]]:
    """Per-kind {count, bytes_per_shard} — the report metric."""
    out: Dict[str, Dict[str, int]] = {}
    for op in ops:
        e = out.setdefault(op.kind, {"count": 0, "bytes_per_shard": 0})
        e["count"] += 1
        e["bytes_per_shard"] += op.bytes_per_shard
    return out


def check_hlo(target: HloTarget) -> Tuple[List[Finding], Dict]:
    """Prove the target lowers to its allowed collective vocabulary
    only; collect per-collective byte counts as metrics."""
    try:
        spec = target.build()
    except Exception as e:  # noqa: BLE001
        return [Finding("hlo", target.name,
                        f"target build failed: {type(e).__name__}: {e}")], {}
    if not lowering_supported():
        return [], {"skipped": "StableHLO lowering unavailable in this "
                               "JAX/backend"}
    try:
        if pallas_unlowerable(spec.fn, spec.args):
            return [], {"skipped": _PALLAS_SKIP_NOTE}
    except Exception as e:  # noqa: BLE001
        return [Finding("hlo", target.name,
                        f"trace failed: {type(e).__name__}: {e}")], {}
    try:
        ops = collect_collectives(spec.fn, spec.args)
    except Exception as e:  # noqa: BLE001
        return [Finding("hlo", target.name,
                        f"lowering failed: {type(e).__name__}: {e}")], {}

    metrics = {"collectives": summarize(ops)}
    findings: List[Finding] = []
    for kind, entry in sorted(metrics["collectives"].items()):
        if kind not in spec.allow:
            findings.append(Finding(
                "hlo", target.name,
                f"lowers to stablehlo.{kind} x{entry['count']} "
                f"({entry['bytes_per_shard']} B/shard) — a halo "
                f"exchange must be {'/'.join(spec.allow)} only; this "
                f"collective moves O(domain), not O(halo), bytes",
                ERROR))
    for kind, want in sorted((spec.exact_counts or {}).items()):
        got = metrics["collectives"].get(kind, {}).get("count", 0)
        if got != want:
            findings.append(Finding(
                "hlo", target.name,
                f"lowers to {got} stablehlo.{kind} ops, contract "
                f"requires exactly {want} — extra collectives mean "
                f"hidden communication smuggled into the step program",
                ERROR))
    if spec.expect_collective and not ops:
        findings.append(Finding(
            "hlo", target.name,
            "expected wire collectives but the lowered module has "
            "none — the checker would be vacuous here", WARNING))
    return findings, metrics
