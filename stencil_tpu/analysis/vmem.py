"""Checker 6: static Pallas VMEM / tiling audit.

A Pallas TPU kernel fails (or silently crawls) for memory reasons a
jaxpr-level checker never sees: its working set — the VMEM-resident
blocks plus scratch, doubled by the pipeline's double buffering — must
fit the ~16 MiB per-core VMEM, and its blocks should respect the
(8, 128) f32 register tiling (sublane x lane; 16/32 sublanes for 2/1
byte dtypes) or Mosaic pads every block on every grid step. This
checker reads those properties straight off every ``pallas_call``'s
``GridMapping`` at trace time — no TPU, no Mosaic, no execution:

* **VMEM footprint** — sum of VMEM-space block bytes (ANY/HBM and
  SMEM operands excluded) x2 when the grid pipelines (>1 step), plus
  VMEM scratch from the kernel jaxpr; ERROR over the budget
  (default 16 MiB, or the kernel's own ``vmem_limit_bytes`` when its
  compiler params raise it);
* **tile alignment** — for rank>=2 VMEM blocks, the lane (last) dim
  must be a multiple of 128 OR span the whole array dim (un-tiled is
  the only choice then); the sublane dim likewise against the dtype's
  sublane tile (8 f32 / 16 bf16 / 32 int8);
* **grid divisibility** — every VMEM block dim must divide the array
  dim it tiles: a ragged last tile means masked partial blocks on the
  hot path.

Semaphores are bytes-free here; SMEM has its own (unchecked, ~1 MiB)
budget and scalar-prefetch operands are tiny — excluded by design.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Sequence, Tuple

from .jaxprs import iter_eqns, trace
from .report import Finding, WARNING

VMEM_BUDGET_BYTES = 16 * 1024 * 1024  # per-core VMEM, v4/v5 ballpark

LANE = 128


def sublane_tile(itemsize: int) -> int:
    """Sublane tile rows for an element size: (8,128) holds 32-bit
    lanes; narrower dtypes pack 2/4 rows per register row."""
    return max(8, 8 * (4 // max(1, itemsize)))


@dataclasses.dataclass
class VmemSpec:
    """A traceable entry point containing >= 1 ``pallas_call``.

    Reuses the dma targets' builder convention (``fn(*args)`` traced
    abstractly); ``budget_bytes`` overrides the default VMEM budget
    (kernels that raise ``vmem_limit_bytes`` via compiler params get
    that limit automatically). ``expect_pallas`` guards against the
    audit passing vacuously after a refactor.
    """

    fn: Callable
    args: Sequence[Any]
    budget_bytes: int = VMEM_BUDGET_BYTES
    expect_pallas: bool = True


@dataclasses.dataclass
class VmemTarget:
    name: str
    build: Callable[[], VmemSpec]

    checker = "vmem"


def _space_name(aval: Any) -> str:
    """Memory space of a MemRef aval: 'vmem' (None/default), 'smem',
    'any' (HBM), 'semaphore', ..."""
    s = str(getattr(aval, "memory_space", None) or "")
    if "sem" in str(aval) and ("semaphore" in str(aval)
                               or "barrier" in str(aval)):
        return "semaphore"
    if not s or s == "None":
        return "vmem"
    return s.lower()


def _aval_bytes(shape: Sequence[int], dtype: Any) -> int:
    import numpy as np

    n = 1
    for d in shape:
        n *= int(d)
    try:
        return n * np.dtype(dtype).itemsize
    except TypeError:
        return 0  # semaphore or other unsized element types


def _grid_steps(grid: Sequence[Any]) -> int:
    steps = 1
    for g in grid:
        try:
            steps *= int(g)
        except (TypeError, ValueError):
            return 2  # traced grid dim: assume pipelined
    return steps


def _kernel_limit(params: dict, default: int) -> int:
    """The kernel's own vmem_limit_bytes (compiler params), else the
    default budget — a kernel that *declares* a raised limit is audited
    against what it asked for."""
    cp = params.get("compiler_params") or {}
    values = list(cp.values()) if isinstance(cp, dict) else [cp]
    for v in values:
        limit = getattr(v, "vmem_limit_bytes", None)
        if limit is None and isinstance(v, dict):
            limit = v.get("vmem_limit_bytes")
        if limit:
            return int(limit)
    return default


def _block_dim(b) -> int:
    """Concrete extent of one block dim: squeezed dims (``None`` in
    the BlockSpec, the ``Mapped`` sentinel in the GridMapping) occupy
    one array slice per grid step."""
    try:
        return int(b)
    except (TypeError, ValueError):
        return 1


def audit_pallas_call(eqn, budget: int, kname: str, target_name: str,
                      honor_kernel_limit: bool = True
                      ) -> Tuple[List[Finding], Dict]:
    """Audit one pallas_call eqn: footprint, alignment, divisibility.

    ``honor_kernel_limit=False`` audits against ``budget`` verbatim —
    the tiling checker's physical-VMEM mode, where a kernel's own
    raised ``vmem_limit_bytes`` is exactly the thing being distrusted
    (a raise defers the overflow from the Mosaic check to the
    allocator; see analysis/tiling.py)."""
    import numpy as np

    findings: List[Finding] = []
    gm = eqn.params.get("grid_mapping")
    if gm is None:
        return [Finding("vmem", target_name,
                        f"kernel '{kname}': pallas_call carries no "
                        f"grid_mapping on this JAX; VMEM audit "
                        f"unavailable", WARNING)], {}
    if honor_kernel_limit:
        budget = _kernel_limit(eqn.params, budget)
    steps = _grid_steps(tuple(gm.grid))
    block_bytes = 0
    n_vmem_blocks = 0

    def err(msg: str) -> None:
        findings.append(Finding("vmem", f"{target_name}:{kname}", msg))

    for bm in gm.block_mappings:
        aval = bm.block_aval
        space = _space_name(aval)
        if space in ("semaphore", "smem", "any"):
            continue
        arr = bm.array_shape_dtype
        block = tuple(_block_dim(b) for b in bm.block_shape)
        dtype = np.dtype(arr.dtype)
        block_bytes += _aval_bytes(block, dtype)
        n_vmem_blocks += 1
        label = (f"block {block} of {arr.dtype.name}"
                 f"[{','.join(str(d) for d in arr.shape)}]")
        if len(block) >= 1:
            lane_b, lane_a = block[-1], int(arr.shape[-1])
            if len(block) >= 2 and lane_b % LANE and lane_b != lane_a:
                err(f"{label}: lane (last) dim {lane_b} is neither a "
                    f"multiple of {LANE} nor the full array extent "
                    f"{lane_a} — every grid step pays a partial-lane "
                    f"tile")
            if len(block) >= 2:
                sub = sublane_tile(dtype.itemsize)
                sub_b, sub_a = block[-2], int(arr.shape[-2])
                if sub_b % sub and sub_b != sub_a:
                    err(f"{label}: sublane dim {sub_b} is neither a "
                        f"multiple of the ({sub}, {LANE}) "
                        f"{arr.dtype.name} tile nor the full array "
                        f"extent {sub_a}")
        for ax, (b, a) in enumerate(zip(block, arr.shape)):
            if b and int(a) % int(b):
                err(f"{label}: dim {ax} block {b} does not divide the "
                    f"array extent {a} — ragged last tile (masked "
                    f"partial blocks on the hot path)")

    # VMEM scratch: kernel-jaxpr invars past the block operands
    scratch_bytes = 0
    kj = eqn.params.get("jaxpr")
    kj = kj.jaxpr if hasattr(kj, "jaxpr") else kj
    n_lead = gm.num_index_operands + len(gm.block_mappings)
    for v in list(getattr(kj, "invars", []))[n_lead:]:
        aval = v.aval
        if _space_name(aval) != "vmem":
            continue
        shape = getattr(aval, "shape", None)
        dtype = getattr(aval, "dtype", None)
        if shape is not None and dtype is not None:
            scratch_bytes += _aval_bytes(shape, dtype)

    double = 2 if steps > 1 else 1
    total = block_bytes * double + scratch_bytes
    metrics = {
        "grid": [int(g) if not hasattr(g, "aval") else "?"
                 for g in gm.grid],
        "vmem_block_bytes": block_bytes,
        "vmem_scratch_bytes": scratch_bytes,
        "pipeline_buffers": double,
        "vmem_estimate_bytes": total,
        "budget_bytes": budget,
        "vmem_blocks": n_vmem_blocks,
    }
    if total > budget:
        err(f"estimated VMEM footprint {total} B ({n_vmem_blocks} "
            f"blocks x{double} pipeline buffers + {scratch_bytes} B "
            f"scratch) exceeds the {budget} B budget — the kernel "
            f"cannot stage its working set")
    return findings, metrics


def check_vmem(target: VmemTarget) -> Tuple[List[Finding], Dict]:
    try:
        spec = target.build()
    except Exception as e:  # noqa: BLE001
        return [Finding("vmem", target.name,
                        f"target build failed: {type(e).__name__}: {e}")], {}
    try:
        closed = trace(spec.fn, *spec.args)
    except Exception as e:  # noqa: BLE001
        return [Finding("vmem", target.name,
                        f"trace failed: {type(e).__name__}: {e}")], {}

    findings: List[Finding] = []
    metrics: Dict[str, Dict] = {"kernels": {}}
    n_seen: Dict[str, int] = {}
    for eqn in iter_eqns(closed.jaxpr):
        if eqn.primitive.name != "pallas_call":
            continue
        info = eqn.params.get("name_and_src_info")
        kname = getattr(info, "name", None) or str(info) or "<kernel>"
        n_seen[kname] = n_seen.get(kname, 0) + 1
        if n_seen[kname] > 1:
            kname = f"{kname}#{n_seen[kname]}"
        try:
            f, m = audit_pallas_call(eqn, spec.budget_bytes, kname,
                                     target.name)
        except Exception as e:  # noqa: BLE001 - unknown GridMapping
            # shapes must degrade to a finding, never kill the run
            f, m = [Finding(
                "vmem", f"{target.name}:{kname}",
                f"VMEM audit failed on this kernel's grid mapping: "
                f"{type(e).__name__}: {e}", WARNING)], {}
        if f and any(x.severity != WARNING for x in f):
            # prescriptive mode: every real finding carries the block-
            # shape planner's concrete fix (analysis/tiling.py),
            # planned against whatever budget THIS audit used (never
            # looser — a suggestion must satisfy the budget it was
            # flagged against)
            from .tiling import TILE_SELECT_BUDGET_BYTES, suggest_for_eqn

            audited = m.get("budget_bytes", spec.budget_bytes)
            sug = suggest_for_eqn(eqn, min(TILE_SELECT_BUDGET_BYTES,
                                           audited), kernel=kname)
            f = [dataclasses.replace(x, message=f"{x.message}; {sug}")
                 if x.severity != WARNING else x for x in f]
            m = dict(m)
            m["suggestion"] = sug
        findings.extend(f)
        metrics["kernels"][kname] = m
    if spec.expect_pallas and not metrics["kernels"]:
        findings.append(Finding(
            "vmem", target.name,
            "expected pallas_call kernels but none traced — the VMEM "
            "audit would be vacuous here", WARNING))
    return findings, metrics
