"""Checker 10: prescriptive VMEM tiling — the block-shape planner.

The VMEM audit (:mod:`.vmem`, checker 6) *flags* a Pallas kernel whose
blocks overflow VMEM or break the (sublane, 128) tile rules; this
module makes that model *prescriptive*: given a kernel's per-block-shape
byte model (either an analytic one the kernel module declares, or one
derived positionally from a traced ``pallas_call``'s ``GridMapping``),
it enumerates every legal candidate block shape —

* (sublane, 128)-tile-aligned: ``block_y`` a multiple of the dtype's
  sublane tile (``ops.pallas_stencil.sublane_tile_bytes``); the lane
  dim stays the full array extent in every shipped kernel, so lane
  alignment is the array's own;
* grid-divisible: ``block_z | Z`` and ``block_y | Y`` (no ragged tail
  tiles on the hot path);
* double-buffer footprint under budget: streamed blocks x2 pipeline
  buffers (+ held in-kernel windows where the kernel's model declares
  them) within the PHYSICAL per-core VMEM (a raised
  ``vmem_limit_bytes`` postpones the failure from the Mosaic check to
  the allocator — exactly the SNIPPETS.md 512^3 failure mode — so the
  planner never trusts it)

— prices each by modeled HBM traffic (read amplification: streamed
input bytes per main-stream output element, the ``1 + 2/block_z +
2/block_y`` family documented on ``ops/pallas_stencil.py``), and
returns a ranked :class:`TilingPlan`. The Pallas kernel modules route
their default block selection through :func:`plan_blocks` /
:func:`snap_blocks` (no more silent power-of-two halving), the VMEM
checker attaches each finding's concrete ``suggestion`` from
:func:`suggest_for_eqn`, and the registry's ``analysis.tiling.*``
targets audit every shipped kernel at 256^3- and 512^3-per-device
shapes — trace-only, so tier-1 on CPU proves the production-size story
the 8^3 bench trajectory never could (ROADMAP item 6).

Budget convention: SELECTION uses :data:`TILE_SELECT_BUDGET_BYTES`
(14 MiB — physical VMEM minus slack for semaphores/compiler
temporaries, the ``ops/pallas_halo.py`` precedent), AUDIT uses the full
physical :data:`vmem.VMEM_BUDGET_BYTES` (16 MiB). Selection being the
stricter of the two is what makes the plan -> audit round trip sound:
every planner-emitted shape passes ``check_vmem`` by construction.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .jaxprs import iter_eqns, trace
from .report import ERROR, WARNING, Finding
from .vmem import VMEM_BUDGET_BYTES, audit_pallas_call, sublane_tile

#: kernel-side block-selection budget: physical VMEM minus slack for
#: semaphores / compute temporaries the byte models do not count (the
#: ops/pallas_halo precedent, now the one shared constant)
TILE_SELECT_BUDGET_BYTES = 14 * 2**20

LANE = 128


class TilingInfeasibleError(ValueError):
    """No legal block shape exists for this kernel at this budget.

    ``reason`` names the binding constraint (alignment, divisibility,
    or the VMEM footprint of the minimal aligned block)."""

    def __init__(self, kernel: str, reason: str):
        super().__init__(f"{kernel}: no legal block shape — {reason}")
        self.kernel = kernel
        self.reason = reason


@dataclasses.dataclass(frozen=True)
class ShapeOption:
    """One legal candidate block shape, priced."""

    block_z: int
    block_y: int
    footprint_bytes: int
    #: modeled HBM read amplification: streamed input bytes per
    #: main-stream output element (1.0 = every input byte read once)
    amplification: float

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class TilingPlan:
    """The planner's output for one kernel at one array shape: every
    legal candidate, ranked cheapest-traffic first (ties prefer the
    fatter ``block_y``, then the fatter ``block_z`` — fatter lanes mean
    fewer, fatter edge DMAs; the judge-measured 512^3 fast point
    (8, 128) falls out of exactly this rule)."""

    kernel: str
    array_zyx: Tuple[int, int, int]
    itemsize: int
    budget_bytes: int
    options: List[ShapeOption]
    #: aligned+divisible candidates rejected by the budget alone
    over_budget: int = 0
    #: binding constraint when ``options`` is empty
    infeasible: Optional[str] = None

    @property
    def best(self) -> Optional[ShapeOption]:
        return self.options[0] if self.options else None

    def blocks(self) -> Tuple[int, int]:
        """The prescribed (block_z, block_y); raises
        :class:`TilingInfeasibleError` when nothing is legal."""
        if not self.options:
            raise TilingInfeasibleError(
                self.kernel, self.infeasible or "empty candidate space")
        return self.options[0].block_z, self.options[0].block_y

    def to_dict(self) -> Dict:
        return {
            "kernel": self.kernel,
            "array_zyx": list(self.array_zyx),
            "itemsize": self.itemsize,
            "budget_bytes": self.budget_bytes,
            "options": [o.to_dict() for o in self.options],
            "over_budget": self.over_budget,
            "infeasible": self.infeasible,
        }


def _divisors(n: int) -> List[int]:
    out = [d for d in range(1, n + 1) if n % d == 0]
    return out


def plan_blocks(kernel: str, Z: int, Y: int, X: int, itemsize: int,
                elems: Callable[[int, int], Tuple[int, int, int]], *,
                n_streams: int = 1,
                sublane_z: int = 1, sublane_y: Optional[int] = None,
                min_z: int = 1,
                cap_z: Optional[int] = None, cap_y: Optional[int] = None,
                budget: int = TILE_SELECT_BUDGET_BYTES,
                scratch_bytes: int = 0,
                max_options: int = 8) -> TilingPlan:
    """Synthesize the ranked legal block shapes for one kernel.

    ``elems(bz, by) -> (in_elems, out_elems, held_elems)`` is the
    kernel's byte model per lane column (x itemsize x X applied here):
    streamed input/output block elements (doubled for the pipeline's
    two buffers) and held in-kernel window elements (allocated once).
    It must count at least what the traced ``GridMapping`` will show,
    so legality here implies a clean ``check_vmem`` — the plan -> audit
    round-trip contract, property-tested in tests/test_tiling.py.

    ``n_streams`` is the number of main-block input streams (8 for the
    MHD kernels), normalizing ``amplification`` to 1.0 = perfect.
    ``cap_z``/``cap_y`` bound candidates above (the caller's requested
    ceiling); ``sublane_*``/``min_z`` bound them below. An empty legal
    set yields ``options=[]`` with the binding constraint named in
    ``infeasible`` (:meth:`TilingPlan.blocks` raises it).
    """
    esub = sublane_y if sublane_y is not None else sublane_tile(itemsize)
    # a ceiling below the alignment floor means "the smallest legal
    # shape" (bf16 doubles the sublane tile past the f32-sized caps)
    cz = min(max(cap_z, sublane_z, min_z), Z) if cap_z else Z
    cy = min(max(cap_y, esub), Y) if cap_y else Y
    bzs = [d for d in _divisors(Z)
           if d % max(sublane_z, 1) == 0 and min_z <= d <= cz]
    bys = [d for d in _divisors(Y) if d % max(esub, 1) == 0 and d <= cy]
    plan = TilingPlan(kernel=kernel, array_zyx=(Z, Y, X),
                      itemsize=itemsize, budget_bytes=int(budget),
                      options=[])
    if not bzs or not bys:
        which = []
        if not bzs:
            which.append(f"no block_z divides Z={Z} with "
                         f"{min_z} <= block_z <= {cz}"
                         + (f" as a multiple of {sublane_z}"
                            if sublane_z > 1 else ""))
        if not bys:
            which.append(f"no block_y divides Y={Y} as a multiple of "
                         f"the sublane tile {esub} with block_y <= {cy}")
        plan.infeasible = "; ".join(which) + " (alignment/divisibility)"
        return plan

    scored: List[ShapeOption] = []
    best_over = None  # (footprint, bz, by) of the cheapest illegal shape
    over = 0
    for bz in bzs:
        for by in bys:
            ein, eout, eheld = elems(bz, by)
            footprint = (itemsize * X * (2 * (int(ein) + int(eout))
                                         + int(eheld))
                         + int(scratch_bytes))
            if footprint > budget:
                over += 1
                if best_over is None or footprint < best_over[0]:
                    best_over = (footprint, bz, by)
                continue
            amp = float(ein) / float(max(n_streams, 1) * bz * by)
            scored.append(ShapeOption(bz, by, footprint, round(amp, 4)))
    plan.over_budget = over
    if not scored:
        fp, bz, by = best_over  # at least one aligned candidate existed
        plan.infeasible = (
            f"VMEM footprint is the binding constraint: even the "
            f"cheapest aligned block ({bz}, {by}) stages {fp} B against "
            f"the {budget} B budget at array ({Z}, {Y}, {X}) "
            f"x{itemsize} B")
        return plan
    scored.sort(key=lambda o: (o.amplification, -o.block_y, -o.block_z))
    plan.options = scored[:max(int(max_options), 1)]
    return plan


# ---------------------------------------------------------------------------
# explicit-request snapping + the once-per-fact replacement warning
# (the silent-degradation fix: a shrunk block shape now SAYS so)

_WARNED: set = set()


def _warn_once(key: Tuple, msg: str) -> None:
    if key in _WARNED:
        return
    _WARNED.add(key)
    from ..utils.logging import LOG_WARN

    LOG_WARN(msg)


def reset_warnings() -> None:
    """Test hook: forget which replacements were already warned."""
    _WARNED.clear()


def snap_blocks(kernel: str, Z: int, Y: int,
                requested_z: int, requested_y: int, *,
                sublane_z: int = 1, sublane_y: int = 1,
                min_z: int = 1) -> Tuple[int, int]:
    """Snap an EXPLICITLY requested (block_z, block_y) to the nearest
    legal-alignment shape at or below it (budget deliberately NOT
    applied: an operator sweeping block shapes asked to measure exactly
    that configuration, Mosaic errors included). When the request had
    to be replaced, ``LOG_WARN`` fires ONCE per (kernel, array, request)
    — the old halving loops shrank silently. Raises
    :class:`TilingInfeasibleError` when no aligned divisor exists."""
    bzs = [d for d in _divisors(Z)
           if d % max(sublane_z, 1) == 0
           and min_z <= d <= max(int(requested_z), min_z)]
    bys = [d for d in _divisors(Y)
           if d % max(sublane_y, 1) == 0 and d <= max(int(requested_y),
                                                      sublane_y)]
    if not bzs or not bys:
        raise TilingInfeasibleError(
            kernel, f"requested blocks ({requested_z}, {requested_y}) "
                    f"have no aligned divisor for array Z={Z}, Y={Y} "
                    f"(sublanes z%{sublane_z}, y%{sublane_y}, "
                    f"block_z >= {min_z})")
    bz, by = max(bzs), max(bys)
    if (bz, by) != (int(requested_z), int(requested_y)):
        _warn_once(
            (kernel, Z, Y, int(requested_z), int(requested_y)),
            f"{kernel}: requested block shape ({requested_z}, "
            f"{requested_y}) replaced by ({bz}, {by}) — the request "
            f"does not divide/align array (Z={Z}, Y={Y}); pass a "
            f"legal shape (python -m stencil_tpu.analysis "
            f"--plan-tiling) to silence")
    return bz, by


# ---------------------------------------------------------------------------
# the generic (trace-derived) model: a parametric footprint read
# straight off a pallas_call's GridMapping, for kernels the planner
# has no analytic model for — powers the VMEM checker's `suggestion`
# and the --plan-tiling report


def _block_dims(bm) -> Tuple[int, ...]:
    out = []
    for b in bm.block_shape:
        try:
            out.append(int(b))
        except (TypeError, ValueError):
            out.append(1)  # squeezed dim
    return tuple(out)


def plan_from_grid_mapping(eqn, budget: int = TILE_SELECT_BUDGET_BYTES,
                           kernel: str = "<kernel>"
                           ) -> Optional[TilingPlan]:
    """Derive a parametric block-shape model positionally from a traced
    ``pallas_call``: the first rank-3 VMEM *output* block's leading two
    dims are the (block_z, block_y) knobs; every other VMEM block's
    dims co-vary where they equal the reference's (dim 0 with block_z,
    dim 1 with block_y) and stay constant otherwise. Returns ``None``
    when no unambiguous parameterization exists (a squeezed/plane
    kernel whose reference dims are 1 — every single-row segment would
    alias the knob)."""
    import numpy as np

    gm = eqn.params.get("grid_mapping")
    if gm is None:
        return None
    try:
        n_out = int(gm.num_outputs)
    except (AttributeError, TypeError):
        n_out = 1
    from .vmem import _space_name

    blocks = []  # (dims, itemsize, is_output, array_shape)
    for i, bm in enumerate(gm.block_mappings):
        aval = bm.block_aval
        if _space_name(aval) in ("semaphore", "smem", "any"):
            continue
        arr = bm.array_shape_dtype
        try:
            isz = np.dtype(arr.dtype).itemsize
        except TypeError:
            continue
        is_out = i >= len(gm.block_mappings) - n_out
        blocks.append((_block_dims(bm), isz, is_out,
                       tuple(int(d) for d in arr.shape)))
    ref = next(((d, a) for d, _isz, is_out, a in blocks
                if is_out and len(d) == 3), None)
    if ref is None:
        return None
    (bz0, by0, _lx0), (Z, Y, X) = ref
    if bz0 <= 1 or by0 <= 1:
        return None  # ambiguous: constant-1 segments would alias the knob

    # VMEM scratch (constant in the block shape)
    kj = eqn.params.get("jaxpr")
    kj = kj.jaxpr if hasattr(kj, "jaxpr") else kj
    from .vmem import _aval_bytes

    scratch = 0
    n_lead = gm.num_index_operands + len(gm.block_mappings)
    for v in list(getattr(kj, "invars", []))[n_lead:]:
        aval = v.aval
        if _space_name(aval) != "vmem":
            continue
        shape = getattr(aval, "shape", None)
        dtype = getattr(aval, "dtype", None)
        if shape is not None and dtype is not None:
            scratch += _aval_bytes(shape, dtype)

    itemsize = max(isz for _d, isz, _o, _a in blocks)

    def scaled(dims, bz, by):
        n = 1
        for ax, d in enumerate(dims):
            if ax == 0 and d == bz0:
                d = bz
            elif ax == 1 and d == by0:
                d = by
            n *= d
        return n

    def elems(bz, by):
        ein = eout = 0
        for dims, isz, is_out, _a in blocks:
            # normalize foreign itemsizes into the plan's element unit
            n = scaled(dims, bz, by) * isz / itemsize / X
            if is_out:
                eout += n
            else:
                ein += n
        return ein, eout, 0

    n_streams = sum(1 for d, _isz, is_out, _a in blocks
                    if not is_out and len(d) == 3
                    and d[0] == bz0 and d[1] == by0)
    return plan_blocks(kernel, Z, Y, X, itemsize, elems,
                       n_streams=max(n_streams, 1),
                       sublane_y=sublane_tile(itemsize),
                       budget=budget)


def suggest_for_eqn(eqn, budget: int = TILE_SELECT_BUDGET_BYTES,
                    kernel: str = "<kernel>") -> str:
    """The concrete prescription attached to every VMEM finding: the
    best legal shape, or the named binding constraint, or the honest
    admission that no parametric model is derivable."""
    try:
        plan = plan_from_grid_mapping(eqn, budget, kernel)
    except Exception as e:  # noqa: BLE001 — suggestions never kill audits
        return (f"suggestion unavailable (planner failed: "
                f"{type(e).__name__}: {e})")
    if plan is None:
        return ("no parametric block-shape model derivable from this "
                "grid mapping (plane/squeezed kernel) — re-tile the "
                "kernel or shrink the per-device array")
    if plan.best is not None:
        o = plan.best
        return (f"suggestion: block shape ({o.block_z}, {o.block_y}) "
                f"fits {o.footprint_bytes} B <= {plan.budget_bytes} B "
                f"at amplification {o.amplification}")
    return f"infeasible at this budget — {plan.infeasible}"


# ---------------------------------------------------------------------------
# checker 10: the registry-facing tiling audit


@dataclasses.dataclass
class TilingSpec:
    """A traceable entry point audited at a production per-device
    shape against the PHYSICAL VMEM budget (declared
    ``vmem_limit_bytes`` raises are deliberately ignored — a raise
    defers the overflow from the Mosaic check to the allocator)."""

    fn: Callable
    args: Sequence[Any]
    budget_bytes: int = VMEM_BUDGET_BYTES
    expect_pallas: bool = True


@dataclasses.dataclass
class TilingTarget:
    """``expect`` is the registered verdict for this shape:

    * ``"legal"`` — the build must succeed and every contained
      ``pallas_call`` must pass the full audit (footprint, tile
      alignment, grid divisibility); any finding is an ERROR carrying
      the planner's concrete suggestion;
    * ``"infeasible"`` — the planner/kernel must REFUSE this size:
      either building/tracing raises :class:`TilingInfeasibleError`
      (the kernel-side planner declining — the silent-degradation fix
      proven at production size) or the audit flags the shape. A clean
      pass means the pinned expectation went stale and must be
      promoted to "legal" in review.
    """

    name: str
    build: Callable[[], TilingSpec]
    expect: str = "legal"

    checker = "tiling"


def _audit_shapes(spec: TilingSpec, target_name: str
                  ) -> Tuple[List[Finding], Dict]:
    """Trace and audit every pallas_call at the physical budget,
    suggestions attached; mirrors check_vmem's walk but never honors
    declared vmem_limit raises."""
    findings: List[Finding] = []
    metrics: Dict[str, Dict] = {"kernels": {}}
    closed = trace(spec.fn, *spec.args)
    n_seen: Dict[str, int] = {}
    for eqn in iter_eqns(closed.jaxpr):
        if eqn.primitive.name != "pallas_call":
            continue
        info = eqn.params.get("name_and_src_info")
        kname = getattr(info, "name", None) or str(info) or "<kernel>"
        n_seen[kname] = n_seen.get(kname, 0) + 1
        if n_seen[kname] > 1:
            kname = f"{kname}#{n_seen[kname]}"
        f, m = audit_pallas_call(eqn, spec.budget_bytes, kname,
                                 target_name, honor_kernel_limit=False)
        f = [dataclasses.replace(x, checker="tiling") for x in f]
        if f:
            sug = suggest_for_eqn(eqn, min(TILE_SELECT_BUDGET_BYTES,
                                           spec.budget_bytes), kname)
            f = [dataclasses.replace(x, message=f"{x.message}; {sug}")
                 for x in f]
            m["suggestion"] = sug
        plan = plan_from_grid_mapping(eqn, min(TILE_SELECT_BUDGET_BYTES,
                                               spec.budget_bytes), kname)
        if plan is not None:
            m["plan"] = plan.to_dict()
        findings.extend(f)
        metrics["kernels"][kname] = m
    if spec.expect_pallas and not metrics["kernels"]:
        findings.append(Finding(
            "tiling", target_name,
            "expected pallas_call kernels but none traced — the tiling "
            "audit would be vacuous here", WARNING))
    return findings, metrics


def check_tiling(target: TilingTarget) -> Tuple[List[Finding], Dict]:
    try:
        spec = target.build()
    except TilingInfeasibleError as e:
        if target.expect == "infeasible":
            # the kernel-side planner refused this size at build time
            return [], {"infeasible": str(e),
                        "verdict": "refused-at-build"}
        return [Finding(
            "tiling", target.name,
            f"planner refused a shape registered as legal: {e}")], {}
    except Exception as e:  # noqa: BLE001
        return [Finding("tiling", target.name,
                        f"target build failed: {type(e).__name__}: {e}")], {}

    if target.expect == "infeasible":
        # the build ran, so the planner did NOT refuse: the audit must
        # flag the shape, else the pinned expectation is stale
        try:
            findings, metrics = _audit_shapes(spec, target.name)
        except TilingInfeasibleError as e:
            return [], {"infeasible": str(e), "verdict": "refused-at-trace"}
        except Exception as e:  # noqa: BLE001
            return [Finding("tiling", target.name,
                            f"trace failed: {type(e).__name__}: {e}")], {}
        real = [f for f in findings if f.severity == ERROR]
        if not real:
            return [Finding(
                "tiling", target.name,
                "registered as infeasible at this per-device shape but "
                "the kernel now tiles legally — promote the registry "
                "expectation to \"legal\"")], metrics
        metrics["expected_findings"] = [str(f) for f in real]
        metrics["verdict"] = "flagged-as-expected"
        return [], metrics

    try:
        findings, metrics = _audit_shapes(spec, target.name)
    except TilingInfeasibleError as e:
        return [Finding(
            "tiling", target.name,
            f"planner refused a shape registered as legal: {e}")], {}
    except Exception as e:  # noqa: BLE001
        return [Finding("tiling", target.name,
                        f"trace failed: {type(e).__name__}: {e}")], {}
    metrics["verdict"] = "legal" if not findings else "flagged"
    return findings, metrics


# ---------------------------------------------------------------------------
# the --plan-tiling report (CLI): ranked plan tables per target


def plan_tiling_report(targets: Sequence[TilingTarget]) -> Dict[str, Dict]:
    """Per-target planner report for ``--plan-tiling``: each contained
    kernel's actual blocks, audit verdict at the physical budget, and
    the ranked legal candidates (or the named binding constraint)."""
    out: Dict[str, Dict] = {}
    for t in targets:
        entry: Dict[str, Any] = {}
        try:
            spec = t.build()
        except TilingInfeasibleError as e:
            out[t.name] = {"infeasible": str(e)}
            continue
        except Exception as e:  # noqa: BLE001
            out[t.name] = {"error": f"{type(e).__name__}: {e}"}
            continue
        try:
            findings, metrics = _audit_shapes(spec, t.name)
        except Exception as e:  # noqa: BLE001
            out[t.name] = {"error": f"{type(e).__name__}: {e}"}
            continue
        entry["expect"] = t.expect
        entry["findings"] = [str(f) for f in findings]
        entry["kernels"] = metrics.get("kernels", {})
        out[t.name] = entry
    return out


def render_plan_table(report: Dict[str, Dict]) -> str:
    """Human table over :func:`plan_tiling_report`'s dict."""
    lines: List[str] = []
    hdr = (f"  {'target':<58} {'kernel':<24} {'footprint':>12} "
           f"{'amp':>6}  verdict / best shape")
    lines.append(hdr)
    for name, entry in sorted(report.items()):
        if "infeasible" in entry:
            lines.append(f"  {name:<58} {'-':<24} {'-':>12} {'-':>6}  "
                         f"INFEASIBLE (planner refused): "
                         f"{entry['infeasible']}")
            continue
        if "error" in entry:
            lines.append(f"  {name:<58} {'-':<24} {'-':>12} {'-':>6}  "
                         f"ERROR: {entry['error']}")
            continue
        flagged = bool(entry.get("findings"))
        for kname, m in entry.get("kernels", {}).items():
            plan = m.get("plan") or {}
            best = (plan.get("options") or [None])[0]
            verdict = "FLAGGED" if flagged else "ok"
            if plan.get("infeasible"):
                tail = f"infeasible: {plan['infeasible']}"
            elif best:
                tail = (f"best ({best['block_z']}, {best['block_y']}) "
                        f"@ {best['footprint_bytes']} B")
            else:
                tail = "no parametric model"
            amp = best["amplification"] if best else "-"
            lines.append(
                f"  {name:<58} {kname:<24} "
                f"{m.get('vmem_estimate_bytes', '-'):>12} {amp!s:>6}  "
                f"{verdict}  {tail}")
    return "\n".join(lines)
