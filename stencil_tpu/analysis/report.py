"""Finding and report types for stencil-lint.

A checker emits :class:`Finding`s; a :class:`Report` aggregates them
across targets and serializes to the ``--json`` CI artifact. Severity
``error`` fails the run (nonzero exit); ``warning`` marks constructs
the checkers cannot statically verify (dynamic semaphore indices, data
flowing into loops) without claiming a bug.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Sequence

# v2: adds tool_version, per-checker wall time (checker_seconds), and
# per-target metrics (hlo collective byte counts, costmodel
# expected/observed bytes + flops/arithmetic intensity, vmem footprint
# estimates, capability-gate skip notes)
SCHEMA_VERSION = 2

TOOL_VERSION = "0.2.0"

ERROR = "error"
WARNING = "warning"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violated (or unverifiable) invariant.

    ``checker``  -- "footprint" | "dma" | "collectives" | "hlo" |
                    "costmodel" | "vmem" | "donation" | "transfer" |
                    "recompile"
    ``target``   -- registry name of the checked entity (or
                    "name:kernel" for per-kernel dma/vmem findings)
    ``message``  -- human-readable description of the violation
    ``severity`` -- ERROR (fails CI) or WARNING (reported only)
    """

    checker: str
    target: str
    message: str
    severity: str = ERROR

    def to_dict(self) -> Dict[str, str]:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return f"[{self.checker}] {self.target}: {self.message}"


@dataclasses.dataclass
class Report:
    """All findings of one stencil-lint run plus run metadata."""

    findings: List[Finding] = dataclasses.field(default_factory=list)
    targets_checked: List[str] = dataclasses.field(default_factory=list)
    # per-checker wall time (seconds), e.g. {"hlo": 1.2}
    checker_seconds: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    # per-target metrics keyed "<checker>:<target>" (byte counts, VMEM
    # estimates, capability-gate skip notes, ...)
    metrics: Dict[str, Dict] = dataclasses.field(default_factory=dict)

    def extend(self, findings: Sequence[Finding]) -> None:
        self.findings.extend(findings)

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == WARNING]

    @property
    def ok(self) -> bool:
        """True when no *error*-severity findings exist."""
        return not self.errors

    def to_dict(self) -> Dict:
        import jax

        by_checker: Dict[str, int] = {}
        for f in self.errors:
            by_checker[f.checker] = by_checker.get(f.checker, 0) + 1
        return {
            "schema_version": SCHEMA_VERSION,
            "tool": "stencil-lint",
            "tool_version": TOOL_VERSION,
            "jax_version": jax.__version__,
            "targets_checked": list(self.targets_checked),
            "counts": {
                "targets": len(self.targets_checked),
                "errors": len(self.errors),
                "warnings": len(self.warnings),
                "errors_by_checker": by_checker,
            },
            "checker_seconds": {k: round(v, 3)
                                for k, v in self.checker_seconds.items()},
            "metrics": self.metrics,
            "findings": [f.to_dict() for f in self.findings],
        }

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)
