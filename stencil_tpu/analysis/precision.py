"""Checker 13: dtype-flow certification of halo wire formats.

The twelve shipped checkers audit collectives, bytes, VMEM, dataflow,
tiling, and semaphore schedules — never *dtype flow*.  That gap is
what kept ROADMAP item 1 (low-precision wire formats) unshippable: a
bf16 halo path is only sound if the narrowing is confined to the wire,
and nothing could prove it.  This checker walks every registered entry
point's jaxpr building a dtype-provenance state per value — how many
times it has been quantized since the last collective hop, which
narrow dtype it still round-trips exactly through, and the widest
float dtype in its lineage — classifies every
``convert_element_type`` as **declared** (named by a wire/compute
declaration: ``make_exchange(wire_format=...)``,
``CarryContract.compute_dtype``/``wire_formats``) or **silent**
(ERROR), and proves three conditions:

* **(a) accumulation floor** — every additive reduction
  (``reduce_sum``/``psum``/``dot_general``/``cumsum``/
  ``scatter-add``/``add_any``) runs at >= the declared compute dtype
  (default f32) even when storage is narrower: the MHD
  storage/compute split becomes a proven invariant, not a convention.
  The check reads the reduction's OUTPUT dtype — that is the
  accumulator width (``preferred_element_type`` and all);
* **(b) declared wire dtype per link class** — each
  ``ppermute``/``all_gather``/``all_to_all`` operand carries exactly
  the wire dtype its axis declares, joined against ``linkmap``'s
  axis -> self/ici-hop<k>/dcn classification (the per-LINK story:
  bf16 on the far tier, f32 where the wire is free);
* **(c) at most one quantization per hop** — a value may be narrowed
  at most once between collective hops.  Widen-then-renarrow to the
  SAME dtype is an exact round-trip (the sequential axis sweeps
  re-narrow arrived halos without loss); narrowing twice with
  arithmetic in between is double quantization and is flagged.

Each target emits a :class:`PrecisionCertificate`
``{wire_dtypes, silent_converts, narrowest_accum,
max_rel_error_bound, safe, reasons[]}`` into the report metrics, and
the engines CONSUME it, schedule-certifier style
(``parallel/megastep.certificate_gate`` precedent):
``make_exchange(wire_format="bf16", ...)`` refuses to realize —
loudly, :class:`PrecisionGateError` — unless
:func:`certify_wire_format` proves the built program safe.  The
per-hop error bound is analytic: round-to-nearest narrowing to a
p-bit significand perturbs each halo element by a relative error of
at most ``2**-p`` (bf16: ``2**-8``; fp8 e4m3: ``2**-4``; fp8 e5m2:
``2**-3``), and ``wire_format="f32"`` is the bitwise identity path
(bound 0.0) — both pinned by the Jacobi fused-vs-stepwise tests.

Like every checker here the pass is trace-only (``jax.make_jaxpr``
over ``ShapeDtypeStruct``s): no FLOPs, no devices, seconds on a
backendless CI box.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .jaxprs import ClosedJaxpr, Jaxpr, Literal, dtype_pairs, trace
from .report import ERROR, Finding

#: additive reductions whose accumulator width condition (a) floors
#: (order-insensitive sums — max/min reductions carry no rounding
#: accumulation and are exempt)
REDUCTION_PRIMS = frozenset({
    "reduce_sum", "cumsum", "add_any", "dot_general", "scatter-add"})

#: primitives that move values verbatim — they propagate the
#: exact-round-trip state; everything else is arithmetic and clears it
VALUE_PRESERVING = frozenset({
    "slice", "dynamic_slice", "dynamic_update_slice", "concatenate",
    "reshape", "transpose", "broadcast_in_dim", "squeeze",
    "expand_dims", "rev", "copy", "gather", "select_n", "pad",
    "stop_gradient", "split"})

#: collectives that put bytes on the wire (condition (b)/(c) join
#: points); psum is a reduction, not a wire-format carrier
WIRE_PRIMS = frozenset({"ppermute", "all_gather", "all_to_all"})


class PrecisionGateError(RuntimeError):
    """A narrowing wire format failed certification at realize time."""


def _is_float(dt: Any) -> bool:
    try:
        import jax.numpy as jnp

        return bool(jnp.issubdtype(np.dtype(dt), jnp.floating))
    except TypeError:
        return False


def _nmant(dt: Any) -> int:
    import jax.numpy as jnp

    return int(jnp.finfo(np.dtype(dt)).nmant)


def _dtname(dt: Any) -> str:
    return str(np.dtype(dt))


def _wider(a: Optional[str], b: Optional[str]) -> Optional[str]:
    """The wider of two float dtype names (None = no float lineage)."""
    if a is None:
        return b
    if b is None:
        return a
    return a if _nmant(a) >= _nmant(b) else b


def rel_error_bound(wire_dtype_name: str) -> float:
    """Per-hop relative rounding bound of narrowing to this wire
    dtype: round-to-nearest to a (nmant+1)-bit significand perturbs
    each element by at most ``2**-(nmant+1)`` (bf16: 2**-8)."""
    return float(2.0 ** -(_nmant(wire_dtype_name) + 1))


# ---------------------------------------------------------------------------
# per-value provenance state


@dataclasses.dataclass
class _V:
    """Dtype provenance of one traced value.

    ``quant``    — lossy narrowings since the last collective hop;
    ``exact_in`` — narrow dtype the value still round-trips exactly
                   through (set by a narrowing, survives widening and
                   value-preserving movement, cleared by arithmetic);
    ``orig``     — widest float dtype in the lineage (the STORAGE
                   dtype condition (b) derives the expected wire
                   dtype from)."""

    quant: int = 0
    exact_in: Optional[str] = None
    orig: Optional[str] = None


def _fresh(aval: Any) -> _V:
    dt = getattr(aval, "dtype", None)
    return _V(orig=_dtname(dt) if dt is not None and _is_float(dt)
              else None)


@dataclasses.dataclass
class _Ctx:
    """One traversal's declarations and collectors."""

    wire: Optional[Dict[str, str]]          # axis -> declared format
    compute_nmant: int
    declared: frozenset                     # {(src, dst)} narrowings
    link_classes: Dict[str, str]
    silent: Dict[Tuple[str, str], int] = dataclasses.field(
        default_factory=dict)
    wire_dtypes: Dict[str, Dict[str, Any]] = dataclasses.field(
        default_factory=dict)
    accum_dtypes: List[str] = dataclasses.field(default_factory=list)
    reasons: List[str] = dataclasses.field(default_factory=list)
    max_bound: float = 0.0

    def fail(self, msg: str) -> None:
        if msg not in self.reasons:
            self.reasons.append(msg)


def declared_pairs_for(wire: Optional[Dict[str, str]],
                       compute_dtype: Optional[str] = "float32",
                       storage_dtype: Optional[str] = None,
                       extra: Sequence[Tuple[str, str]] = ()
                       ) -> frozenset:
    """The set of (src, dst) narrowing conversions the declarations
    name: each narrowing wire axis declares float32 -> its wire dtype
    (``parallel.exchange.WIRE_DTYPE_NAMES`` — bf16/e4m3/e5m2; the
    send boundary only, the widen back is lossless and needs no
    declaration), and a storage/compute split declares compute ->
    storage (the store-back of an MHD-style bf16-storage /
    f32-compute model)."""
    from ..parallel.exchange import WIRE_DTYPE_NAMES

    pairs = set(tuple(p) for p in extra)
    for fmt in (wire or {}).values():
        if fmt != "f32" and fmt in WIRE_DTYPE_NAMES:
            pairs.add(("float32", WIRE_DTYPE_NAMES[fmt]))
    if storage_dtype is not None and compute_dtype is not None \
            and _is_float(storage_dtype) and _is_float(compute_dtype) \
            and _nmant(storage_dtype) < _nmant(compute_dtype):
        pairs.add((_dtname(compute_dtype), _dtname(storage_dtype)))
    return frozenset(pairs)


def axis_link_classes(counts: Any,
                      devices: Optional[Sequence] = None,
                      dcn_axis: Optional[int] = None,
                      n_slices: int = 1) -> Dict[str, str]:
    """Each mesh axis's link class for a +1 neighbor shift —
    ``self`` (1-device axis: the periodic wrap is a local copy, no
    wire), else ``linkmap``'s classification of the representative
    shard-0 edge (``ici-hop<k>`` / ``dcn``).  Lazy import: linkmap
    reaches back into parallel/exchange."""
    from ..geometry import Dim3
    from ..observatory.linkmap import link_class_of, mesh_distance_matrix

    counts = Dim3.of(counts)
    dist = mesh_distance_matrix(counts, devices, dcn_axis, n_slices)
    step = {0: 1, 1: counts.x, 2: counts.x * counts.y}
    out: Dict[str, str] = {}
    for a, name in ((0, "x"), (1, "y"), (2, "z")):
        out[name] = ("self" if counts[a] == 1 else
                     link_class_of(0, step[a], dist, counts,
                                   dcn_axis, n_slices))
    return out


# ---------------------------------------------------------------------------
# the abstract interpreter


def _state_of(v: Any, env: Dict) -> _V:
    if isinstance(v, Literal):
        return _fresh(v.aval)
    s = env.get(v)
    if s is None:
        s = _fresh(v.aval)
        env[v] = s
    return s


def _join(states: Sequence[_V], preserve: bool,
          out_dtype: Optional[str]) -> _V:
    quant = max((s.quant for s in states), default=0)
    origs = [s.orig for s in states if s.orig is not None]
    orig = None
    for o in origs:
        orig = _wider(orig, o)
    if not preserve and out_dtype is not None and _is_float(out_dtype):
        orig = _wider(orig, _dtname(out_dtype))
    exact: Optional[str] = None
    if preserve:
        exacts = {s.exact_in for s in states if s.orig is not None}
        if len(exacts) == 1:
            exact = next(iter(exacts))
    return _V(quant=quant, exact_in=exact, orig=orig)


def _axis_of(params: Dict) -> Optional[str]:
    ax = params.get("axis_name")
    if isinstance(ax, (tuple, list)):
        ax = ax[0] if ax else None
    return str(ax) if ax is not None else None


def _sub_jaxpr(obj: Any) -> Optional[Jaxpr]:
    if isinstance(obj, ClosedJaxpr):
        return obj.jaxpr
    if isinstance(obj, Jaxpr):
        return obj
    return None


def _map_io(sub: Jaxpr, ins: Sequence[_V], env: Dict) -> Dict:
    sub_env: Dict = {}
    if len(sub.invars) == len(ins):
        for var, s in zip(sub.invars, ins):
            sub_env[var] = dataclasses.replace(s)
    return sub_env


def _walk(jaxpr: Jaxpr, env: Dict, ctx: _Ctx) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        ins = [_state_of(v, env) for v in eqn.invars]

        if name == "convert_element_type":
            src = _dtname(eqn.invars[0].aval.dtype)
            dst = _dtname(eqn.outvars[0].aval.dtype)
            s = ins[0]
            if _is_float(src) and _is_float(dst):
                if _nmant(dst) < _nmant(src):
                    if (src, dst) not in ctx.declared:
                        key = (src, dst)
                        ctx.silent[key] = ctx.silent.get(key, 0) + 1
                    if s.exact_in == dst:
                        out = dataclasses.replace(s)  # exact round-trip
                    else:
                        out = _V(quant=s.quant + 1, exact_in=dst,
                                 orig=s.orig)
                else:
                    out = _V(quant=s.quant, exact_in=s.exact_in,
                             orig=_wider(s.orig, dst))
            else:
                out = _V(orig=dst if _is_float(dst) else None)
            env[eqn.outvars[0]] = out
            continue

        if name in WIRE_PRIMS:
            axis = _axis_of(eqn.params)
            link = ctx.link_classes.get(axis or "", "ici-hop1")
            for i, v in enumerate(eqn.invars):
                dt = _dtname(v.aval.dtype)
                s = ins[i]
                if axis is not None:
                    rec = ctx.wire_dtypes.setdefault(
                        axis, {"dtypes": [], "link_class": link,
                               "declared": (ctx.wire or {}).get(axis)})
                    if dt not in rec["dtypes"]:
                        rec["dtypes"].append(dt)
                if not _is_float(dt) or s.orig is None:
                    continue
                if _nmant(dt) < _nmant(s.orig):
                    ctx.max_bound = max(ctx.max_bound,
                                        rel_error_bound(dt))
                if ctx.wire is not None and axis in (ctx.wire or {}):
                    from ..parallel.exchange import wire_dtype

                    expected = _dtname(
                        wire_dtype(np.dtype(s.orig), ctx.wire[axis]))
                    if dt != expected:
                        ctx.fail(
                            f"(b) wire dtype mismatch on axis {axis} "
                            f"({link}): {name} operand is {dt} but "
                            f"the declared wire format "
                            f"'{ctx.wire[axis]}' for {s.orig} storage "
                            f"expects {expected}")
                if s.quant > 1:
                    ctx.fail(
                        f"(c) double quantization: {name} operand on "
                        f"axis {axis} ({link}) was narrowed "
                        f"{s.quant} times since the previous hop — "
                        f"quantize at most once per hop")
            for ov, s in zip(eqn.outvars, ins):
                env[ov] = _V(quant=0, exact_in=s.exact_in, orig=s.orig)
            continue

        if name in REDUCTION_PRIMS or name.startswith("psum"):
            for ov in eqn.outvars:
                dt = getattr(ov.aval, "dtype", None)
                if dt is not None and _is_float(dt):
                    dtn = _dtname(dt)
                    if dtn not in ctx.accum_dtypes:
                        ctx.accum_dtypes.append(dtn)
                    if _nmant(dtn) < ctx.compute_nmant:
                        ctx.fail(
                            f"(a) accumulation below the compute "
                            f"floor: {name} accumulates at {dtn} "
                            f"(nmant {_nmant(dtn)}) — reductions must "
                            f"run at >= the declared compute dtype "
                            f"(nmant {ctx.compute_nmant}) even when "
                            f"storage is narrower")
            for ov in eqn.outvars:
                dt = getattr(ov.aval, "dtype", None)
                env[ov] = _join(ins, preserve=False,
                                out_dtype=_dtname(dt)
                                if dt is not None else None)
            continue

        if name == "scan":
            sub = _sub_jaxpr(eqn.params.get("jaxpr"))
            if sub is not None:
                sub_env = _map_io(sub, ins, env)
                _walk(sub, sub_env, ctx)
                outs = [sub_env.get(ov, _fresh(ov.aval))
                        if not isinstance(ov, Literal) else _fresh(ov.aval)
                        for ov in sub.outvars]
                for ov, s in zip(eqn.outvars,
                                 outs[-len(eqn.outvars):]):
                    env[ov] = dataclasses.replace(s)
            continue

        if name == "while":
            cn = eqn.params.get("cond_nconsts", 0)
            bn = eqn.params.get("body_nconsts", 0)
            carry = ins[cn + bn:]
            cond = _sub_jaxpr(eqn.params.get("cond_jaxpr"))
            body = _sub_jaxpr(eqn.params.get("body_jaxpr"))
            if cond is not None:
                _walk(cond, _map_io(cond, ins[:cn] + carry, env), ctx)
            if body is not None:
                body_env = _map_io(body, ins[cn:cn + bn] + carry, env)
                _walk(body, body_env, ctx)
                outs = [body_env.get(ov, _fresh(ov.aval))
                        if not isinstance(ov, Literal) else _fresh(ov.aval)
                        for ov in body.outvars]
                for ov, s in zip(eqn.outvars, outs):
                    env[ov] = dataclasses.replace(s)
            continue

        if name == "cond":
            branch_outs: List[List[_V]] = []
            for br in eqn.params.get("branches", ()):
                bj = _sub_jaxpr(br)
                if bj is None:
                    continue
                br_env = _map_io(bj, ins[1:], env)
                _walk(bj, br_env, ctx)
                branch_outs.append(
                    [br_env.get(ov, _fresh(ov.aval))
                     if not isinstance(ov, Literal) else _fresh(ov.aval)
                     for ov in bj.outvars])
            for i, ov in enumerate(eqn.outvars):
                states = [outs[i] for outs in branch_outs
                          if i < len(outs)]
                env[ov] = (_join(states, preserve=True, out_dtype=None)
                           if states else _fresh(ov.aval))
            continue

        if name == "pallas_call":
            kj = _sub_jaxpr(eqn.params.get("jaxpr"))
            if kj is not None:
                _walk(kj, {}, ctx)  # refs: fresh states, audit eqns
            for ov in eqn.outvars:
                env[ov] = _fresh(ov.aval)
            continue

        sub = _sub_jaxpr(eqn.params.get("jaxpr")
                         or eqn.params.get("call_jaxpr"))
        if sub is not None:
            sub_env = _map_io(sub, ins, env)
            _walk(sub, sub_env, ctx)
            outs = [sub_env.get(ov, _fresh(ov.aval))
                    if not isinstance(ov, Literal) else _fresh(ov.aval)
                    for ov in sub.outvars]
            if len(outs) == len(eqn.outvars):
                for ov, s in zip(eqn.outvars, outs):
                    env[ov] = dataclasses.replace(s)
            else:
                for ov in eqn.outvars:
                    env[ov] = _fresh(ov.aval)
            continue

        preserve = name in VALUE_PRESERVING
        for ov in eqn.outvars:
            dt = getattr(ov.aval, "dtype", None)
            env[ov] = _join(ins, preserve=preserve,
                            out_dtype=_dtname(dt)
                            if dt is not None else None)


# ---------------------------------------------------------------------------
# certificates


@dataclasses.dataclass
class PrecisionCertificate:
    """The dtype-flow verdict for one entry point: ``safe`` iff no
    silent converts and conditions (a)/(b)/(c) all hold; ``reasons``
    name every violated condition.  ``max_rel_error_bound`` is the
    analytic per-element, per-hop relative rounding bound of the
    narrowest wire dtype crossed (0.0 = bitwise identity wire)."""

    target: str
    wire_dtypes: Dict[str, Dict[str, Any]]
    silent_converts: List[Dict[str, Any]]
    narrowest_accum: Optional[str]
    max_rel_error_bound: float
    safe: bool
    reasons: List[str] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return {"target": self.target,
                "wire_dtypes": {k: dict(v) for k, v in
                                sorted(self.wire_dtypes.items())},
                "silent_converts": list(self.silent_converts),
                "narrowest_accum": self.narrowest_accum,
                "max_rel_error_bound": self.max_rel_error_bound,
                "safe": self.safe, "reasons": list(self.reasons)}


@dataclasses.dataclass
class PrecisionSpec:
    """A traceable entry point plus its dtype declarations.

    ``wire`` — per-axis declared wire formats (``{"x": "f32"|"bf16",
    ...}``); ``None`` = no declaration (observe-only: wire dtypes are
    recorded, condition (b) exact-match is not enforced — narrowing
    still needs a declaration or it is a silent convert).
    ``compute_min`` — the accumulation floor condition (a) proves.
    ``storage_dtype`` — declares a compute -> storage narrowing (the
    bf16-storage / f32-compute split).  ``counts``/``dcn_axis``/
    ``n_slices`` feed the linkmap join for per-link-class reporting.
    """

    fn: Callable
    args: Sequence[Any]
    wire: Optional[Dict[str, str]] = None
    compute_min: str = "float32"
    storage_dtype: Optional[str] = None
    declared_pairs: Tuple[Tuple[str, str], ...] = ()
    counts: Optional[Any] = None
    dcn_axis: Optional[int] = None
    n_slices: int = 1


@dataclasses.dataclass
class PrecisionTarget:
    name: str
    build: Callable[[], PrecisionSpec]

    checker = "precision"


def _certify(name: str, closed: ClosedJaxpr, spec: PrecisionSpec
             ) -> PrecisionCertificate:
    link_classes = (axis_link_classes(spec.counts, None, spec.dcn_axis,
                                      spec.n_slices)
                    if spec.counts is not None else {})
    ctx = _Ctx(wire=dict(spec.wire) if spec.wire is not None else None,
               compute_nmant=_nmant(spec.compute_min),
               declared=declared_pairs_for(spec.wire, spec.compute_min,
                                           spec.storage_dtype,
                                           spec.declared_pairs),
               link_classes=link_classes)
    env: Dict = {}
    for v in closed.jaxpr.invars:
        env[v] = _fresh(v.aval)
    _walk(closed.jaxpr, env, ctx)
    for (src, dst), n in sorted(ctx.silent.items()):
        ctx.fail(f"silent convert: {src} -> {dst} ({n}x) is a lossy "
                 f"narrowing named by no wire/compute declaration")
    if ctx.wire is not None:
        from ..parallel.exchange import WIRE_DTYPE_NAMES

        for ax, fmt in sorted(ctx.wire.items()):
            if fmt != "f32" and link_classes.get(ax) != "self":
                ctx.max_bound = max(
                    ctx.max_bound,
                    rel_error_bound(WIRE_DTYPE_NAMES.get(fmt, fmt)))
    narrowest = None
    for dtn in ctx.accum_dtypes:
        narrowest = (dtn if narrowest is None
                     or _nmant(dtn) < _nmant(narrowest) else narrowest)
    silent = [{"from": src, "to": dst, "count": n}
              for (src, dst), n in sorted(ctx.silent.items())]
    return PrecisionCertificate(
        target=name, wire_dtypes=ctx.wire_dtypes,
        silent_converts=silent, narrowest_accum=narrowest,
        max_rel_error_bound=ctx.max_bound, safe=not ctx.reasons,
        reasons=ctx.reasons)


def certify_wire_format(fn: Callable, args: Sequence[Any],
                        counts: Any = None,
                        wire_formats: Optional[Dict[str, str]] = None,
                        compute_min: str = "float32",
                        dcn_axis: Optional[int] = None,
                        n_slices: int = 1) -> PrecisionCertificate:
    """Runtime API for the realize-time gate
    (``make_exchange(wire_format=...)``): trace ``fn(*args)``, prove
    the dtype flow against the declared per-axis wire formats, and
    additionally prove the wire format does NOT leak into the carried
    state (every output leaf keeps its input dtype — the donated
    double-buffer contract).  Raises nothing — an untraceable program
    returns an unsafe certificate whose reasons say why, so callers
    refuse instead of crashing."""
    import jax

    spec = PrecisionSpec(fn=fn, args=args,
                         wire=dict(wire_formats or {}) or None,
                         compute_min=compute_min, counts=counts,
                         dcn_axis=dcn_axis, n_slices=n_slices)
    try:
        closed = trace(fn, *args)
    except Exception as e:  # noqa: BLE001
        return PrecisionCertificate(
            target="<untraceable>", wire_dtypes={}, silent_converts=[],
            narrowest_accum=None, max_rel_error_bound=0.0, safe=False,
            reasons=[f"precision trace failed: "
                     f"{type(e).__name__}: {e}"])
    cert = _certify("<wire-format-gate>", closed, spec)
    try:
        out = jax.eval_shape(fn, *args)
    except Exception:  # noqa: BLE001 - trace above already succeeded
        out = None
    if out is not None:
        pairs = dtype_pairs(args[0] if len(args) == 1 else list(args),
                            out)
        for path, (_is, idt, _iw), (_os, odt, _ow) in (pairs or []):
            if idt != odt:
                cert.reasons.append(
                    f"wire dtype leaked into the carried state at "
                    f"{path}: input {idt} -> output {odt} (the wire "
                    f"format must stay on the wire)")
                cert.safe = False
    return cert


def check_precision(target: PrecisionTarget
                    ) -> Tuple[List[Finding], dict]:
    """Certify the target's dtype flow; findings are the violated
    conditions and silent converts, metrics are the certificate
    (archived to the JSON report for the tuner/CI gate)."""
    try:
        spec = target.build()
    except Exception as e:  # noqa: BLE001
        return ([Finding("precision", target.name,
                         f"target build failed: {type(e).__name__}: "
                         f"{e}")], {})
    try:
        closed = trace(spec.fn, *spec.args)
    except Exception as e:  # noqa: BLE001
        return ([Finding("precision", target.name,
                         f"trace failed: {type(e).__name__}: {e}")], {})
    cert = _certify(target.name, closed, spec)
    findings = [Finding("precision", target.name, r, ERROR)
                for r in cert.reasons]
    return findings, cert.to_dict()
