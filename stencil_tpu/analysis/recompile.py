"""Checker 9: recompile-hazard audit — one compile per fingerprint.

A jitted entry point's compile is amortized over a campaign; a
fingerprint that drifts between dispatches re-traces and re-compiles
*every* dispatch, which at serving scale is the difference between an
engine-cache hit and a multi-second stall per request. The drifts are
always the same three, and all three are visible statically:

* **Python-scalar arguments** — a driver that passes a bare ``int``/
  ``float`` traces it as a *weak*-typed scalar; the same call made
  later with a device array (or by a different driver) is a different
  fingerprint, so the cache forks per call-site style. Entry points
  must take committed arrays (``jnp.asarray(n, jnp.int32)`` — exactly
  what the shipped run loops do).
* **weak-type promotion** — a carried output that picks up
  ``weak_type=True`` (a state leaf rebuilt from a Python scalar) feeds
  back a different aval than the strong array it replaces: retrace on
  the next dispatch, every dispatch.
* **dtype/shape drift between paired curr/next buffers** — the donated
  double-buffer contract requires the carried output aval to equal the
  input aval exactly; an ``astype`` (or a dropped field) makes every
  dispatch after the first a cache miss.

The checker needs only ``jax.eval_shape`` — no lowering, no compile —
and records each entry point's canonical abstract-signature
fingerprint as a metric, so the JSON artifact doubles as a
fingerprint manifest.

The static gate has a runtime twin: :func:`assert_single_compile` /
:class:`SingleCompileGuard` watch a jitted function's trace-cache size
across dispatches (``STENCIL_ASSERT_SINGLE_COMPILE=1`` arms the guard
inside ``resilience/driver.py`` and the ``CampaignService`` batch
loop), so a hazard that slips past the static model still fails
loudly instead of silently recompiling forever.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .jaxprs import dtype_pairs, flat_with_paths, leaf_aval
from .report import ERROR, Finding

#: arm the runtime trace-count guard in the drivers/service
ASSERT_SINGLE_COMPILE_ENV = "STENCIL_ASSERT_SINGLE_COMPILE"

#: carry pairing: (argnum, output index path) — None path means the
#: whole output IS the carried state
CarryPath = Tuple[int, Optional[Tuple[int, ...]]]


class RecompileGuardError(RuntimeError):
    """A guarded jitted function re-traced after its first dispatch."""


@dataclasses.dataclass
class RecompileSpec:
    """An entry point plus its carry contract.

    ``carry`` pairs each donated/carried argnum with the index path of
    the output subtree that feeds back into it on the next dispatch
    (``None`` = the whole output). The checker proves the two have
    identical flat avals — shape, dtype, AND weak_type."""

    fn: Callable
    args: Sequence[Any]
    carry: Tuple[CarryPath, ...] = ((0, None),)


@dataclasses.dataclass
class RecompileTarget:
    name: str
    build: Callable[[], RecompileSpec]

    checker = "recompile"


# the (shape, dtype, weak_type) leaf walk is shared with the precision
# checker — one dtype-pair extractor, analysis/jaxprs.py
_leaf_aval = leaf_aval
_flat_with_paths = flat_with_paths


def abstract_fingerprint(fn: Callable, args: Sequence[Any],
                         out: Any = None) -> str:
    """sha256 over the canonical abstract signature (flat input and
    output avals incl. weak_type) — the identity the jit cache keys
    on, minus static closure state. Pass an already-computed
    ``jax.eval_shape`` result as ``out`` to skip re-tracing (the
    unrolled megastep programs make a second abstract trace the
    checker's dominant cost)."""
    if out is None:
        import jax

        out = jax.eval_shape(fn, *args)
    sig = [("in", p, _leaf_aval(v)) for p, v in _flat_with_paths(args)]
    sig += [("out", p, _leaf_aval(v)) for p, v in _flat_with_paths(out)]
    return hashlib.sha256(repr(sig).encode()).hexdigest()


def _out_subtree(out: Any, path: Optional[Tuple[int, ...]]) -> Any:
    if path is None:
        return out
    for i in path:
        out = out[i]
    return out


def check_recompile(target: RecompileTarget
                    ) -> Tuple[List[Finding], Dict]:
    """Prove the target's abstract fingerprint is dispatch-stable."""
    import jax

    try:
        spec = target.build()
    except Exception as e:  # noqa: BLE001
        return [Finding("recompile", target.name,
                        f"target build failed: {type(e).__name__}: {e}")], {}

    findings: List[Finding] = []
    n_weak_args = 0
    for argnum, a in enumerate(spec.args):
        for path, leaf in _flat_with_paths(a):
            if isinstance(leaf, (bool,)):
                continue
            if isinstance(leaf, (int, float, complex)):
                n_weak_args += 1
                findings.append(Finding(
                    "recompile", target.name,
                    f"arg{argnum}{path} is a Python scalar "
                    f"({type(leaf).__name__}) — it traces weak-typed, "
                    f"so array-typed and scalar-typed call sites fork "
                    f"the jit cache; pass a committed "
                    f"jnp.asarray(..., dtype) instead", ERROR))
            elif _leaf_aval(leaf)[2]:
                n_weak_args += 1
                findings.append(Finding(
                    "recompile", target.name,
                    f"arg{argnum}{path} is weak-typed — its "
                    f"fingerprint differs from the strong-typed array "
                    f"the warm path feeds; commit it with an explicit "
                    f"dtype", ERROR))

    try:
        out = jax.eval_shape(spec.fn, *spec.args)
    except Exception as e:  # noqa: BLE001
        findings.append(Finding(
            "recompile", target.name,
            f"abstract evaluation failed: {type(e).__name__}: {e}"))
        return findings, {}

    carry_leaves = 0
    for argnum, path in spec.carry:
        try:
            out_sub = _out_subtree(out, path)
        except (IndexError, KeyError, TypeError):
            findings.append(Finding(
                "recompile", target.name,
                f"carry output path {path!r} does not exist in the "
                f"output tree — the carried state for arg{argnum} "
                f"cannot feed back", ERROR))
            continue
        pairs = dtype_pairs(spec.args[argnum], out_sub)
        if pairs is None:
            findings.append(Finding(
                "recompile", target.name,
                f"carry arg{argnum}: "
                f"{len(_flat_with_paths(spec.args[argnum]))} input "
                f"leaves vs {len(_flat_with_paths(out_sub))} output "
                f"leaves — the state pytree changes shape across a "
                f"dispatch (retrace every step)", ERROR))
            continue
        carry_leaves += len(pairs)
        for ipath, (ishape, idtype, iweak), \
                (oshape, odtype, oweak) in pairs:
            where = f"arg{argnum}{ipath}"
            if ishape != oshape:
                findings.append(Finding(
                    "recompile", target.name,
                    f"carry {where}: shape drift {ishape} -> {oshape} "
                    f"between paired curr/next buffers — every "
                    f"dispatch after the first re-traces", ERROR))
            elif idtype != odtype:
                findings.append(Finding(
                    "recompile", target.name,
                    f"carry {where}: dtype drift {idtype} -> {odtype} "
                    f"between paired curr/next buffers — every "
                    f"dispatch after the first re-traces (and the "
                    f"donation dies with it)", ERROR))
            elif oweak and not iweak:
                findings.append(Finding(
                    "recompile", target.name,
                    f"carry {where}: weak-type promotion — the output "
                    f"leaf is weak_type=True (rebuilt from a Python "
                    f"scalar?) while the input is strong; feeding it "
                    f"back re-traces every dispatch", ERROR))

    metrics = {"fingerprint": abstract_fingerprint(spec.fn, spec.args,
                                                   out=out),
               "carry_leaves": carry_leaves,
               "weak_args": n_weak_args}
    return findings, metrics


# ---------------------------------------------------------------------------
# the runtime twin: trace-count guards


def trace_cache_size(fn: Callable) -> Optional[int]:
    """The jit trace-cache entry count of ``fn``, or None when this
    JAX does not expose it (the guards then no-op)."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:  # noqa: BLE001 - introspection must never raise
        return None


@contextlib.contextmanager
def assert_single_compile(fn: Callable, label: str = ""):
    """Assert the jitted ``fn`` adds AT MOST ONE trace-cache entry
    inside the block — the 'one compile per fingerprint' contract a
    warm driver loop can wrap its steady state in."""
    before = trace_cache_size(fn)
    yield
    after = trace_cache_size(fn)
    # allow ONE cold compile; an already-warm fn (before >= 1) may not
    # add any entry — growth past max(before, 1) is a second
    # fingerprint either way
    if before is not None and after is not None \
            and after > max(before, 1):
        raise RecompileGuardError(
            f"{label or getattr(fn, '__name__', fn)}: jit cache grew "
            f"{before} -> {after} inside an assert_single_compile "
            f"block — the entry point re-traced (fingerprint drift)")


class SingleCompileGuard:
    """Cross-dispatch recompile watchdog: observe a jitted fn after
    each dispatch; any cache growth after the first observation means
    the steady-state fingerprint drifted."""

    def __init__(self) -> None:
        # keyed by id(fn) but HOLDING the fn: a freed fn's id can be
        # recycled by a new jit, which would inherit a stale baseline
        # and mask exactly the retrace this guard is armed to catch
        self._seen: Dict[int, Tuple[Callable, int]] = {}

    def observe(self, fn: Callable, label: str = "") -> None:
        size = trace_cache_size(fn)
        if size is None:
            return
        prev = self._seen.get(id(fn))
        if prev is not None and prev[0] is fn and size > prev[1]:
            raise RecompileGuardError(
                f"{label or getattr(fn, '__name__', fn)}: jit cache "
                f"grew {prev[1]} -> {size} between dispatches — the "
                f"hot loop is recompiling every step")
        self._seen[id(fn)] = (fn, size)
