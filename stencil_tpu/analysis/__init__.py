"""stencil-lint / stencil-audit: static invariant checking for the
stencil framework.

Thirteen checkers prove, WITHOUT executing anything (jaxpr tracing plus
lower-only StableHLO inspection and alias-map parsing of compiled —
never dispatched — programs; seconds on any CPU box, no TPU, no
interpreter), the invariants the whole framework hangs on:

* :mod:`.footprint`   — every registered stencil op's true access
  footprint is covered by its declared ``geometry.Radius`` in all 26
  directions (asymmetric radii included);
* :mod:`.dma`         — every Pallas kernel's remote DMA is barrier-
  ordered, started exactly once per semaphore arm, and waited on both
  ends (the static analog of the interpreter's race detector);
* :mod:`.collectives` — every ``lax.ppermute`` permutation is a full
  bijection of its mesh axis and all collective axis names resolve;
* :mod:`.hlo`         — every exchange method LOWERS to
  ``collective-permute`` only (no accidental all-gather/all-reduce/
  all-to-all), with per-collective byte counts extracted;
* :mod:`.costmodel`   — HLO-observed wire bytes match the analytic
  per-direction halo byte model from ``geometry``/``partition``
  (uneven remainders included), plus jaxpr FLOPs / arithmetic
  intensity metrics;
* :mod:`.vmem`        — every Pallas kernel's VMEM footprint fits the
  budget and its blocks respect (8, 128) tiling and grid divisibility;
* :mod:`.donation`    — every declared ``donate_argnums`` buffer of
  every jitted entry point actually appears in the compiled
  ``input_output_alias`` map (donated-but-copied is an ERROR);
* :mod:`.transfer`    — no host-callback/infeed/outfeed/host-memory
  escape inside any step or segment hot path (plus the runtime
  ``jax.transfer_guard("disallow")`` the drivers dispatch under);
* :mod:`.recompile`   — every entry point's abstract fingerprint is
  dispatch-stable: no Python-scalar args, no weak-type promotion, no
  dtype/shape drift between paired curr/next buffers (plus the
  runtime ``assert_single_compile`` trace-count guard);
* :mod:`.tiling`      — the prescriptive half of the VMEM audit: a
  block-shape planner derives the legal (sublane, 128)-aligned,
  grid-divisible, budget-fitting block shapes for every Pallas
  kernel, the kernels select their defaults through it, and registry
  targets gate every kernel at 256^3/512^3-per-device shapes against
  the PHYSICAL VMEM budget (raised ``vmem_limit_bytes`` deliberately
  distrusted — the SNIPPETS.md 512^3 Mosaic allocation failure,
  reproduced and closed);
* :mod:`.schedule`    — happens-before certification of every remote-
  DMA kernel's semaphore schedule under k-fold replay: send/recv slots
  drain before re-arm, the cross-shard rendezvous is deadlock-free,
  interior compute never reads an unwaited-inbound buffer — emitting
  the per-kernel ``ScheduleCertificate`` the megastep segment compiler
  consumes to fuse (or certificate-citingly decline) in-kernel RDMA
  paths;
* :mod:`.precision`   — dtype-flow certification: every
  ``convert_element_type`` is declared (wire/compute declarations on
  ``make_exchange``/``CarryContract``) or flagged silent, additive
  reductions accumulate at >= the declared compute dtype, every
  ``ppermute`` operand carries exactly its axis's declared wire dtype
  per ``linkmap`` link class, and narrowing happens at most once per
  hop — emitting the per-target ``PrecisionCertificate`` that gates
  low-precision halo wire formats (``wire_format="bf16"`` refuses to
  realize uncertified);
* ``linkmap`` (:mod:`stencil_tpu.observatory.linkmap`) — the link
  observatory's modeled per-(src, dst) traffic matrix sums EXACTLY to
  the HLO-extracted wire bytes for every registered exchange method
  (slab/packed at every plan depth, the all-gather control, particle
  migration, the PIC accumulate adjoint) — the matrix the placement
  QAP consumes and the wire bill the HLO proves are one object.

Run ``python -m stencil_tpu.analysis`` (exit nonzero on findings,
``--json`` for the CI artifact, ``--only``/``--list`` to select
checkers or glob target names), or use :func:`run_targets` /
:func:`stencil_tpu.analysis.registry.default_targets` from pytest.
"""

from __future__ import annotations

import time
from typing import Iterable, Optional, Sequence

from .collectives import (CollectiveSpec, CollectiveTarget,
                          check_collectives)
from .costmodel import CostModelSpec, CostModelTarget, check_costmodel
from .dma import PallasKernelSpec, PallasKernelTarget, check_pallas_kernels
from .donation import (DonationSpec, DonationTarget, alias_param_ids,
                       check_donation)
from .footprint import StencilOpSpec, StencilOpTarget, check_stencil_op
from .hlo import HloSpec, HloTarget, check_hlo
from .precision import (PrecisionCertificate, PrecisionGateError,
                        PrecisionSpec, PrecisionTarget,
                        axis_link_classes, certify_wire_format,
                        check_precision)
from .recompile import (RecompileGuardError, RecompileSpec,
                        RecompileTarget, SingleCompileGuard,
                        assert_single_compile, check_recompile)
from .report import ERROR, WARNING, Finding, Report
from .schedule import (ScheduleCertificate, ScheduleSpec,
                       ScheduleTarget, certify_traceable,
                       check_schedule)
from .transfer import (TransferSpec, TransferTarget, check_transfer,
                       hot_loop_transfer_guard)
from .tiling import (TilingInfeasibleError, TilingPlan, TilingSpec,
                     TilingTarget, check_tiling, plan_blocks,
                     snap_blocks)
from .vmem import VmemSpec, VmemTarget, check_vmem
# checker 11 lives with the link observatory it verifies (the modeled
# per-link traffic matrix, stencil_tpu/observatory/linkmap.py) — only
# the registration is here
from ..observatory.linkmap import (LinkmapSpec, LinkmapTarget,
                                   check_linkmap)

CHECKERS = ("footprint", "dma", "collectives", "hlo", "costmodel",
            "vmem", "donation", "transfer", "recompile", "tiling",
            "linkmap", "schedule", "precision")

CHECKER_DOC = {
    "footprint": "26-direction access footprint vs declared Radius",
    "dma": "Pallas remote-DMA barrier/start/wait discipline",
    "collectives": "ppermute bijections + collective axis names",
    "hlo": "collective-permute-only lowering (StableHLO audit)",
    "costmodel": "HLO bytes vs analytic halo model + FLOPs/AI",
    "vmem": "Pallas VMEM footprint, (8,128) tiling, grid divisibility",
    "donation": "donate_argnums buffers alias in the compiled HLO",
    "transfer": "no host-callback/infeed/outfeed escape in hot paths",
    "recompile": "dispatch-stable abstract fingerprints (no retrace)",
    "tiling": "prescriptive VMEM block-shape planner at 256^3/512^3",
    "linkmap": "per-link traffic matrix sums exactly to HLO bytes",
    "schedule": "RDMA semaphore schedules certified replay-safe "
                "(happens-before under k-fold replay)",
    "precision": "dtype-flow proofs: declared converts only, >= f32 "
                 "accumulation, exact per-link wire dtypes, one "
                 "quantization per hop",
}

__all__ = [
    "CHECKERS", "CHECKER_DOC", "ERROR", "WARNING", "Finding", "Report",
    "CollectiveSpec", "CollectiveTarget", "CostModelSpec",
    "CostModelTarget", "DonationSpec", "DonationTarget", "HloSpec",
    "HloTarget", "PallasKernelSpec", "PallasKernelTarget",
    "LinkmapSpec", "LinkmapTarget",
    "PrecisionCertificate", "PrecisionGateError", "PrecisionSpec",
    "PrecisionTarget",
    "RecompileGuardError", "RecompileSpec", "RecompileTarget",
    "ScheduleCertificate", "ScheduleSpec", "ScheduleTarget",
    "SingleCompileGuard", "StencilOpSpec", "StencilOpTarget",
    "TransferSpec", "TransferTarget", "VmemSpec", "VmemTarget",
    "alias_param_ids", "assert_single_compile", "axis_link_classes",
    "certify_traceable", "certify_wire_format", "check_collectives",
    "check_costmodel", "check_donation", "check_hlo",
    "check_linkmap", "check_pallas_kernels", "check_precision",
    "check_recompile", "check_schedule",
    "check_stencil_op", "check_tiling", "check_transfer", "check_vmem",
    "hot_loop_transfer_guard", "plan_blocks", "run_targets",
    "snap_blocks",
]

_DISPATCH = {
    "footprint": check_stencil_op,
    "dma": check_pallas_kernels,
    "collectives": check_collectives,
    "hlo": check_hlo,
    "costmodel": check_costmodel,
    "vmem": check_vmem,
    "donation": check_donation,
    "transfer": check_transfer,
    "recompile": check_recompile,
    "tiling": check_tiling,
    "linkmap": check_linkmap,
    "schedule": check_schedule,
    "precision": check_precision,
}


def run_targets(targets: Iterable,
                checkers: Optional[Sequence[str]] = None) -> Report:
    """Run each target through its checker; aggregate into a Report.

    A checker returns either ``findings`` or ``(findings, metrics)``;
    metrics land in ``report.metrics["<checker>:<target>"]`` and the
    JSON artifact. Per-checker wall time accumulates in
    ``report.checker_seconds``.
    """
    enabled = set(checkers) if checkers else set(CHECKERS)
    unknown = enabled - set(CHECKERS)
    if unknown:
        raise ValueError(f"unknown checkers {sorted(unknown)}; "
                         f"available: {list(CHECKERS)}")
    report = Report()
    for target in targets:
        kind = getattr(target, "checker", None)
        if kind not in _DISPATCH:
            report.findings.append(Finding(
                "runner", getattr(target, "name", repr(target)),
                f"unknown target kind {type(target).__name__}"))
            continue
        if kind not in enabled:
            continue
        report.targets_checked.append(target.name)
        t0 = time.perf_counter()
        result = _DISPATCH[kind](target)
        report.checker_seconds[kind] = (
            report.checker_seconds.get(kind, 0.0)
            + time.perf_counter() - t0)
        if isinstance(result, tuple):
            findings, metrics = result
            if metrics:
                report.metrics[f"{kind}:{target.name}"] = metrics
        else:
            findings = result
        report.extend(findings)
    return report
