"""stencil-lint: static invariant checking for the stencil framework.

Three checkers prove, WITHOUT executing anything (pure jaxpr tracing —
seconds on any CPU box, no TPU, no interpreter), the invariants the
whole framework hangs on:

* :mod:`.footprint`   — every registered stencil op's true access
  footprint is covered by its declared ``geometry.Radius`` in all 26
  directions (asymmetric radii included);
* :mod:`.dma`         — every Pallas kernel's remote DMA is barrier-
  ordered, started exactly once per semaphore arm, and waited on both
  ends (the static analog of the interpreter's race detector);
* :mod:`.collectives` — every ``lax.ppermute`` permutation is a full
  bijection of its mesh axis and all collective axis names resolve.

Run ``python -m stencil_tpu.analysis`` (exit nonzero on findings,
``--json`` for the CI artifact), or use :func:`run_targets` /
:func:`stencil_tpu.analysis.registry.default_targets` from pytest.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence

from .collectives import (CollectiveSpec, CollectiveTarget,
                          check_collectives)
from .dma import PallasKernelSpec, PallasKernelTarget, check_pallas_kernels
from .footprint import StencilOpSpec, StencilOpTarget, check_stencil_op
from .report import ERROR, WARNING, Finding, Report

CHECKERS = ("footprint", "dma", "collectives")

__all__ = [
    "CHECKERS", "ERROR", "WARNING", "Finding", "Report",
    "CollectiveSpec", "CollectiveTarget", "PallasKernelSpec",
    "PallasKernelTarget", "StencilOpSpec", "StencilOpTarget",
    "check_collectives", "check_pallas_kernels", "check_stencil_op",
    "run_targets",
]

_DISPATCH = {
    "footprint": check_stencil_op,
    "dma": check_pallas_kernels,
    "collectives": check_collectives,
}


def run_targets(targets: Iterable,
                checkers: Optional[Sequence[str]] = None) -> Report:
    """Run each target through its checker; aggregate into a Report."""
    enabled = set(checkers) if checkers else set(CHECKERS)
    unknown = enabled - set(CHECKERS)
    if unknown:
        raise ValueError(f"unknown checkers {sorted(unknown)}; "
                         f"available: {list(CHECKERS)}")
    report = Report()
    for target in targets:
        kind = getattr(target, "checker", None)
        if kind not in _DISPATCH:
            report.findings.append(Finding(
                "runner", getattr(target, "name", repr(target)),
                f"unknown target kind {type(target).__name__}"))
            continue
        if kind not in enabled:
            continue
        report.targets_checked.append(target.name)
        report.extend(_DISPATCH[kind](target))
    return report
