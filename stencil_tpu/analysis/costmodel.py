"""Checker 5: analytic cost model vs. HLO-observed communication.

The reference library's placement model prices every message from
geometry alone: face/edge/corner interface area x radius x element
size (reference: include/stencil/partition.hpp:167-208 split rule,
local_domain.cuh halo_bytes). This checker computes the same analytic
per-shard wire-byte model from ``geometry``/``partition`` (uneven +-1
remainders included — capacity-sized slabs ride the wire even for
short shards) and cross-checks it against what the *lowered HLO
actually moves* (:mod:`.hlo` byte extraction). A mismatch is a
lowering regression — an exchange shipping more than the halo, or
dropping part of it — caught statically, with no benchmark hardware.

It also derives per-op FLOP counts and arithmetic intensity from the
jaxpr (flops / top-level HBM bytes), reported as metrics in the JSON
artifact: the roofline inputs the bench harnesses otherwise measure on
hardware.

Byte-count convention: "observed bytes" is the sum of wire-collective
*operand* bytes per shard — what each shard contributes to every op.
For ``collective_permute`` that is exactly the wire traffic; for the
``all_gather`` control strategy it is the per-shard contribution (ring
wire cost is (n-1)x that), which keeps one convention across kinds.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Sequence, Tuple

from .hlo import (_PALLAS_SKIP_NOTE, collect_collectives,
                  lowering_supported, pallas_unlowerable, summarize)
from .jaxprs import iter_eqns, trace
from .report import ERROR, WARNING, Finding

# per-element FLOP weights for the jaxpr walk. Elementwise arithmetic
# counts 1; transcendentals use the conventional ~10-op estimate. This
# is a roofline-grade estimate, not a cycle count.
_FLOP_1 = frozenset({
    "add", "sub", "mul", "max", "min", "neg", "abs", "sign",
    "and", "or", "xor", "not", "select_n", "clamp", "square",
})
_FLOP_5 = frozenset({"div", "rem", "sqrt", "rsqrt", "cbrt",
                     "integer_pow", "pow"})
_FLOP_10 = frozenset({"exp", "expm1", "log", "log1p", "sin", "cos",
                      "tan", "tanh", "logistic", "atan2", "erf",
                      "erf_inv", "erfc"})


def jaxpr_flops(closed) -> int:
    """Estimated FLOPs of one evaluation: sum over arithmetic eqns of
    output element count x op weight (dot_general: 2 x out x K)."""
    flops = 0
    for eqn in iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        out = eqn.outvars[0] if eqn.outvars else None
        shape = getattr(getattr(out, "aval", None), "shape", None)
        if shape is None:
            continue
        n = 1
        for d in shape:
            n *= int(d)
        if name in _FLOP_1:
            flops += n
        elif name in _FLOP_5:
            flops += 5 * n
        elif name in _FLOP_10:
            flops += 10 * n
        elif name == "dot_general":
            dims = eqn.params.get("dimension_numbers")
            k = 1
            if dims:
                (lhs_c, _), _ = dims
                lhs_shape = getattr(eqn.invars[0].aval, "shape", ())
                for ax in lhs_c:
                    k *= int(lhs_shape[ax])
            flops += 2 * n * k
    return flops


def io_bytes(closed) -> int:
    """Top-level input + output bytes — the HBM-traffic floor the
    arithmetic-intensity estimate divides by."""
    import numpy as np

    total = 0
    for v in list(closed.jaxpr.invars) + list(closed.jaxpr.outvars):
        aval = getattr(v, "aval", None)
        shape = getattr(aval, "shape", None)
        dtype = getattr(aval, "dtype", None)
        if shape is None or dtype is None:
            continue
        n = 1
        for d in shape:
            n *= int(d)
        total += n * np.dtype(dtype).itemsize
    return total


def sweep_wire_bytes(shard_padded_zyx: Sequence[int], radius, counts,
                     elem_size: int,
                     axis_order: Tuple[int, ...] = (0, 1, 2),
                     wire_format=None, layout: str = "slab",
                     alloc_radius=None) -> Dict[str, int]:
    """Per-axis wire bytes one shard ships per exchange round, under
    either wire layout — the single byte-model entry the tuner, the
    runtime counters, and the registry cost targets share. "slab"
    delegates to ``parallel.exchange.exchanged_bytes_per_sweep``
    (full-allocation cross-sections); "irredundant" to
    ``parallel.packing.irredundant_bytes_per_sweep`` (each wire-halo
    cell priced exactly once)."""
    from ..parallel.exchange import exchanged_bytes_per_sweep
    from ..parallel.packing import (irredundant_bytes_per_sweep,
                                    normalize_wire_layout)

    if normalize_wire_layout(layout) == "irredundant":
        return irredundant_bytes_per_sweep(
            shard_padded_zyx, radius, counts, elem_size, axis_order,
            wire_format=wire_format, alloc_radius=alloc_radius)
    return exchanged_bytes_per_sweep(shard_padded_zyx, radius, counts,
                                     elem_size, axis_order,
                                     wire_format=wire_format)


def deep_exchange_bytes_per_shard(shard_interior_zyx: Sequence[int],
                                  radius, counts, elem_size: int,
                                  steps: int,
                                  wire_layout: str = "slab") -> int:
    """Wire bytes ONE shard puts on the ICI per ``steps``-deep exchange
    (temporal blocking): the deepened radius' rows over the DEEPENED
    padded cross-sections — the same ``sweep_wire_bytes`` source of
    truth the runtime counters and the HLO cross-check use, evaluated
    on the deep allocation under the selected wire layout."""
    deep = radius.deepened(steps)
    lo, hi = deep.pad_lo(), deep.pad_hi()
    z, y, x = shard_interior_zyx
    padded = (z + lo.z + hi.z, y + lo.y + hi.y, x + lo.x + hi.x)
    return sum(sweep_wire_bytes(padded, deep, counts, elem_size,
                                layout=wire_layout).values())


def amortized_step_wire_bytes(shard_interior_zyx: Sequence[int],
                              radius, counts, elem_size: int,
                              steps: int,
                              wire_layout: str = "slab") -> float:
    """Per-shard wire bytes charged to each STEP under ``steps``-deep
    blocking: the deep exchange's bytes spread over the ``steps`` steps
    it feeds. Rows amortize back to the base count but the slab
    cross-sections carry the ``2*steps*r`` allocation growth — bytes
    stay ~flat while exchange ROUNDS drop ``steps``x, which is the
    entire temporal-blocking trade (the irredundant layout claws the
    cross-section growth back, which is why its win scales with s)."""
    return deep_exchange_bytes_per_shard(shard_interior_zyx, radius,
                                         counts, elem_size, steps,
                                         wire_layout) / steps


def migration_record_rows(n_fields: int) -> int:
    """Rows of one particle-migration wire record: the SoA fields plus
    ``parallel.migrate.RECORD_EXTRA_ROWS`` packed control rows — the
    engine's one packing constant, re-exported here so the byte model
    cannot drift from the packer (whatever the record format packs the
    offsets and validity into, both sides count the same rows)."""
    from ..parallel.migrate import migration_record_rows as rows

    return rows(n_fields)


def migration_wire_bytes_per_shard(n_fields: int, budget: int, counts,
                                   elem_size: int) -> int:
    """Wire bytes ONE shard puts on the fabric per migration step:
    2 direction messages per mesh axis that crosses devices, each a
    fixed ``record_rows x budget`` buffer — the *static* price of the
    dynamic exchange (payload occupancy varies at runtime; wire bytes
    do not, which is what makes the HLO cross-check exact). 1-device
    axes degenerate to local copies and cost nothing."""
    from ..parallel.migrate import migration_messages

    return (migration_messages(counts) * migration_record_rows(n_fields)
            * int(budget) * int(elem_size))


def migration_step_seconds(n_fields: int, budget: int, counts,
                           elem_size: int,
                           coeffs: "LinkCoefficients | None" = None
                           ) -> float:
    """Alpha-beta migration cost per STEP: the ppermute launches plus
    the budget-sized buffers over the calibrated wire rate — what the
    tuner ranks capacity/budget candidates with
    (``tuning.plan.rank_migration_candidates``)."""
    from ..parallel.migrate import migration_messages

    c = coeffs if coeffs is not None else DEFAULT_ICI_COEFFS
    return c.seconds(migration_messages(counts),
                     migration_wire_bytes_per_shard(
                         n_fields, budget, counts, elem_size))


def temporal_step_exchange_seconds(shard_interior_zyx: Sequence[int],
                                   radius, counts, elem_size: int,
                                   steps: int, round_latency_s: float,
                                   wire_bytes_per_s: float) -> float:
    """Alpha-beta exchange cost per STEP at blocking depth ``steps``:
    ``latency / steps + amortized_bytes / bandwidth``. The latency term
    is per exchange ROUND (3 sequential axis sweeps of ppermutes plus
    launch overhead); the bandwidth term prices the deep slabs."""
    amort = amortized_step_wire_bytes(shard_interior_zyx, radius, counts,
                                      elem_size, steps)
    return round_latency_s / steps + amort / wire_bytes_per_s


def predict_exchange_every(shard_interior_zyx: Sequence[int], radius,
                           counts, elem_size: int,
                           round_latency_s: float,
                           wire_bytes_per_s: float,
                           candidates: Sequence[int] = (1, 2, 3, 4, 6, 8)
                           ) -> Tuple[int, Dict[int, float]]:
    """Predict the crossover: the ``exchange_every`` minimizing the
    alpha-beta per-step exchange time. Small shards / high round
    latency push the optimum up (round amortization wins); large shards
    / scarce bandwidth push it back toward 1 (deep-slab cross-section
    growth dominates). Depths the geometry cannot host (a shard must
    supply ``steps * r`` rows per side) are skipped. Returns
    ``(best_s, {s: seconds_per_step})``."""
    z, y, x = shard_interior_zyx
    interior_xyz = (x, y, z)
    costs: Dict[int, float] = {}
    for s in candidates:
        if any(s * max(radius.face(a, -1), radius.face(a, 1))
               > interior_xyz[a] for a in range(3)):
            continue
        costs[s] = temporal_step_exchange_seconds(
            shard_interior_zyx, radius, counts, elem_size, s,
            round_latency_s, wire_bytes_per_s)
    if not costs:
        raise ValueError(f"no candidate depth fits shards "
                         f"{shard_interior_zyx} with radius {radius}")
    return min(costs, key=costs.get), costs


@dataclasses.dataclass(frozen=True)
class LinkCoefficients:
    """Alpha-beta coefficients of one link class (ICI or DCN): the
    per-collective launch+hop latency and the sustained wire rate. The
    assumed defaults below are deliberately coarse; the exchange
    autotuner (:mod:`stencil_tpu.tuning`) replaces them with MEASURED
    values (pingpong fit) so :func:`predict_exchange_every`,
    :func:`temporal_step_exchange_seconds` and
    :func:`configured_step_seconds` price the actual machine."""

    alpha_s: float        # seconds of latency per collective message
    beta_bytes_per_s: float  # sustained bytes/s one shard can put on the wire

    def seconds(self, messages: int, wire_bytes: float) -> float:
        return messages * self.alpha_s + wire_bytes / self.beta_bytes_per_s


#: assumed (un-measured) constants — roughly a TPU ICI hop; the tuner
#: overwrites these with the pingpong fit before ranking anything
DEFAULT_ICI_COEFFS = LinkCoefficients(alpha_s=20e-6,
                                      beta_bytes_per_s=4.5e10)

#: assumed DCN (inter-slice) constants: ~10x the ICI launch+hop latency
#: and ~a quarter of its sustained rate — coarse on purpose, replaced
#: by the measured topology fingerprint's "dcn" link when available
DEFAULT_DCN_COEFFS = LinkCoefficients(alpha_s=200e-6,
                                      beta_bytes_per_s=1.25e10)

AXIS_NAMES = ("x", "y", "z")


def resolve_link_coeffs(coeffs, axis: "int | None" = None,
                        dcn: bool = False) -> LinkCoefficients:
    """The :class:`LinkCoefficients` pricing one mesh axis's exchange.

    ``coeffs`` may be None (assumed defaults: :data:`DEFAULT_DCN_COEFFS`
    for a DCN-blocked axis, :data:`DEFAULT_ICI_COEFFS` otherwise), one
    ``LinkCoefficients`` applied to every link, or a dict keyed by link
    name — per-axis ``"x"``/``"y"``/``"z"``, the ``"dcn"`` tier, and an
    ``"ici"`` catch-all (the shape ``observatory.linkmap.
    topology_coefficients`` produces from a measured fingerprint)."""
    if coeffs is None:
        return DEFAULT_DCN_COEFFS if dcn else DEFAULT_ICI_COEFFS
    if isinstance(coeffs, LinkCoefficients):
        return coeffs
    if dcn and "dcn" in coeffs:
        return coeffs["dcn"]
    if axis is not None and AXIS_NAMES[axis] in coeffs:
        return coeffs[AXIS_NAMES[axis]]
    if "ici" in coeffs:
        return coeffs["ici"]
    if dcn:
        return DEFAULT_DCN_COEFFS
    return next(iter(coeffs.values()), DEFAULT_ICI_COEFFS)


def exchange_round_model(method_name: str,
                         shard_interior_zyx: Sequence[int], radius,
                         counts, elem_sizes: Sequence[int],
                         steps: int = 1,
                         dtype_groups: "int | None" = None,
                         wire_format=None,
                         wire_layout: str = "slab") -> Tuple[int, int]:
    """Analytic (messages, wire_bytes) ONE shard contributes per deep
    exchange round under strategy ``method_name`` — the per-method
    refinement of :func:`deep_exchange_bytes_per_shard` the autotuner
    ranks candidate plans with:

    * ``PpermuteSlab`` / ``PallasDMA``: one message per active
      axis-direction per quantity; halo bytes.
    * ``PpermutePacked``: quantities concatenate per direction — one
      message per active axis-direction per DTYPE GROUP; same bytes
      (packing changes launches, not payload).
    * ``AllGather``: one collective per active axis-direction per
      quantity, but the ring moves ``(n_axis - 1)x`` the slab bytes
      (every shard's slab visits every device).

    ``elem_sizes``: one element size per quantity. ``steps`` > 1 prices
    the DEEPENED (temporal-blocking) round. ``dtype_groups``: the
    packed engine concatenates per DTYPE (f32 and i32 pack separately
    despite equal sizes — parallel/exchange.py groups by ``.dtype``);
    pass the distinct-dtype count when known, else it is approximated
    by the distinct element sizes. ``wire_format`` prices the halo
    payload at the on-wire width (a bf16 axis halves its 4-byte
    lanes) — only the ppermute engines carry narrow formats, and the
    certificate gate enforces that before any such plan realizes.
    ``wire_layout`` likewise prices the message shape ("slab" |
    "irredundant") for the ppermute engines only.
    """
    deep = radius.deepened(steps)
    lo, hi = deep.pad_lo(), deep.pad_hi()
    z, y, x = shard_interior_zyx
    padded = (z + lo.z + hi.z, y + lo.y + hi.y, x + lo.x + hi.x)

    directions = 0          # active axis-directions crossing devices
    gather_factor = {}      # axis name -> (n_axis - 1) ring multiplier
    for a, name in ((0, "x"), (1, "y"), (2, "z")):
        if counts[a] <= 1:
            continue
        for side in (-1, 1):
            if deep.face(a, side) > 0:
                directions += 1
        gather_factor[name] = counts[a] - 1

    if method_name == "PpermutePacked":
        groups = (int(dtype_groups) if dtype_groups
                  else len(set(elem_sizes)))
        messages = directions * groups
    else:
        messages = directions * len(elem_sizes)

    # only the slab/packed ppermute engines implement narrow wire
    # formats (parallel.methods.WIRE_CAPABLE); everything else ships
    # storage bytes
    wire_capable = method_name in ("PpermuteSlab", "PpermutePacked")
    wf = wire_format if wire_capable else None
    layout = wire_layout if wire_capable else "slab"
    nbytes = 0
    for esize in elem_sizes:
        per_axis = sweep_wire_bytes(padded, deep, counts, esize,
                                    wire_format=wf, layout=layout)
        for name, b in per_axis.items():
            if method_name == "AllGather":
                b *= gather_factor.get(name, 1)
            nbytes += b
    return messages, nbytes


def per_axis_round_model(method_name: str,
                         shard_interior_zyx: Sequence[int], radius,
                         counts, elem_sizes: Sequence[int],
                         steps=1,
                         dtype_groups: "int | None" = None,
                         wire_format=None,
                         wire_layout: str = "slab"
                         ) -> Dict[str, Tuple[int, int]]:
    """:func:`exchange_round_model` split per mesh axis: analytic
    ``{axis: (messages, wire_bytes)}`` ONE shard contributes per
    full-depth refresh of that axis. ``steps`` may be per-axis
    (``geometry.normalize_depths``): axis ``a``'s refresh ships
    ``s_a * r`` rows over the full deepened cross-sections — under
    asymmetric blocking the axis refreshes ``max(s) / s_a`` times per
    group, so its per-STEP price is this entry over ``s_a`` (see
    :func:`asymmetric_step_seconds`). Summing axes at uniform depth
    reproduces :func:`exchange_round_model` exactly."""
    from ..geometry import normalize_depths

    depths = normalize_depths(steps)
    deep = radius.deepened(depths)
    lo, hi = deep.pad_lo(), deep.pad_hi()
    z, y, x = shard_interior_zyx
    padded = (z + lo.z + hi.z, y + lo.y + hi.y, x + lo.x + hi.x)
    wire_capable = method_name in ("PpermuteSlab", "PpermutePacked")
    wf = wire_format if wire_capable else None
    layout = wire_layout if wire_capable else "slab"
    if method_name == "PpermutePacked":
        groups = (int(dtype_groups) if dtype_groups
                  else len(set(elem_sizes)))
    else:
        groups = len(elem_sizes)
    per_axis_bytes = [sweep_wire_bytes(padded, deep, counts, esize,
                                       wire_format=wf, layout=layout)
                      for esize in elem_sizes]
    out: Dict[str, Tuple[int, int]] = {}
    for a, name in ((0, "x"), (1, "y"), (2, "z")):
        directions = 0
        if counts[a] > 1:
            for side in (-1, 1):
                if deep.face(a, side) > 0:
                    directions += 1
        nbytes = 0
        for b in per_axis_bytes:
            v = b[name]
            if method_name == "AllGather":
                v *= max(counts[a] - 1, 1)
            nbytes += v
        out[name] = (directions * groups, nbytes)
    return out


def asymmetric_group_bytes_per_shard(shard_interior_zyx: Sequence[int],
                                     radius, counts, elem_size: int,
                                     depths,
                                     wire_layout: str = "slab") -> int:
    """Wire bytes ONE shard puts on the fabric per ``max(depths)``-step
    temporal group under per-axis depths: the sub-step-0 full exchange
    plus every mid-group refresh — axis ``a`` ships its deep slab
    ``max(s) / s_a`` times (``parallel.temporal.refresh_axes``). The
    HLO expectation for the asymmetric group registry targets; uniform
    depths collapse to :func:`deep_exchange_bytes_per_shard`."""
    from ..geometry import normalize_depths

    depths = normalize_depths(depths)
    s = max(depths)
    per_axis = per_axis_round_model(
        "PpermuteSlab", shard_interior_zyx, radius, counts, [elem_size],
        depths, wire_layout=wire_layout)
    return sum(per_axis[AXIS_NAMES[a]][1] * (s // depths[a])
               for a in range(3))


def asymmetric_step_seconds(method_name: str,
                            shard_interior_zyx: Sequence[int], radius,
                            counts, elem_sizes: Sequence[int],
                            depths, coeffs=None,
                            dcn_axis: "int | None" = None,
                            dtype_groups: "int | None" = None,
                            wire_format=None,
                            wire_layout: str = "slab") -> float:
    """Per-link alpha-beta exchange seconds per STEP under per-axis
    temporal depths: axis ``a`` pays its refresh price
    ``coeffs[link(a)].seconds(messages_a, bytes_a)`` once per ``s_a``
    steps — deep blocking across a DCN axis divides that axis's
    (expensive) launch count by ``s_a`` while the ICI axes keep their
    cheap per-step refreshes. ``coeffs``/``dcn_axis`` route through
    :func:`resolve_link_coeffs`."""
    from ..geometry import normalize_depths

    depths = normalize_depths(depths)
    per_axis = per_axis_round_model(
        method_name, shard_interior_zyx, radius, counts, elem_sizes,
        depths, dtype_groups, wire_format=wire_format,
        wire_layout=wire_layout)
    total = 0.0
    for a in range(3):
        m, b = per_axis[AXIS_NAMES[a]]
        c = resolve_link_coeffs(coeffs, axis=a, dcn=a == dcn_axis)
        total += c.seconds(m, b) / depths[a]
    return total


def predict_exchange_depths(shard_interior_zyx: Sequence[int], radius,
                            counts, elem_size: int, coeffs=None,
                            dcn_axis: "int | None" = None,
                            candidates: Sequence = (1, 2, 4, 8)
                            ) -> Tuple[Tuple[int, int, int],
                                       Dict[Tuple[int, int, int], float]]:
    """:func:`predict_exchange_every` generalized to per-axis depths
    priced per link: each candidate (an int or a per-axis spec) is
    scored with :func:`asymmetric_step_seconds`; geometry-infeasible
    depths are skipped. Returns ``(best, {depths_xyz: seconds})``."""
    from ..geometry import normalize_depths

    z, y, x = shard_interior_zyx
    interior_xyz = (x, y, z)
    costs: Dict[Tuple[int, int, int], float] = {}
    for cand in candidates:
        d = normalize_depths(cand)
        if any(d[a] * max(radius.face(a, -1), radius.face(a, 1))
               > interior_xyz[a] for a in range(3)):
            continue
        costs[tuple(d)] = asymmetric_step_seconds(
            "PpermuteSlab", shard_interior_zyx, radius, counts,
            [elem_size], d, coeffs=coeffs, dcn_axis=dcn_axis)
    if not costs:
        raise ValueError(f"no candidate depth fits shards "
                         f"{shard_interior_zyx} with radius {radius}")
    return min(costs, key=costs.get), costs


def configured_step_seconds(method_name: str,
                            shard_interior_zyx: Sequence[int], radius,
                            counts, elem_sizes: Sequence[int],
                            steps,
                            coeffs=DEFAULT_ICI_COEFFS,
                            dtype_groups: "int | None" = None,
                            wire_format=None,
                            wire_layout: str = "slab",
                            dcn_axis: "int | None" = None) -> float:
    """Alpha-beta exchange seconds per STEP of one (method,
    exchange_every) configuration: the deep round's cost spread over
    the ``steps`` steps it feeds — :func:`temporal_step_exchange_seconds`
    generalized across exchange strategies. The autotuner calls this
    with MEASURED coefficients to prune the sweep before timing.

    ``steps`` may be per-axis and ``coeffs`` a per-link dict (with
    ``dcn_axis`` naming the slice-blocked axis) — those route through
    :func:`asymmetric_step_seconds`; the uniform single-link case keeps
    the original one-term arithmetic exactly."""
    from ..geometry import normalize_depths

    depths = normalize_depths(steps)
    uniform = depths.x == depths.y == depths.z
    if uniform and isinstance(coeffs, LinkCoefficients) \
            and dcn_axis is None:
        messages, nbytes = exchange_round_model(
            method_name, shard_interior_zyx, radius, counts, elem_sizes,
            depths.x, dtype_groups, wire_format=wire_format,
            wire_layout=wire_layout)
        return coeffs.seconds(messages, nbytes) / depths.x
    return asymmetric_step_seconds(
        method_name, shard_interior_zyx, radius, counts, elem_sizes,
        depths, coeffs=coeffs, dcn_axis=dcn_axis,
        dtype_groups=dtype_groups, wire_format=wire_format,
        wire_layout=wire_layout)


@dataclasses.dataclass
class CostModelSpec:
    """A jittable exchange program plus its analytic byte expectation.

    ``expected_bytes_per_shard`` comes from the geometry/partition
    model (``parallel.exchange.exchanged_bytes_per_sweep`` /
    ``interior_slab_bytes`` — the one source of truth the runtime
    byte counters use). ``rel_tol`` absorbs representation noise only;
    the registered targets match exactly.
    """

    fn: Callable
    args: Sequence[Any]
    expected_bytes_per_shard: int
    rel_tol: float = 0.02
    count_kinds: Tuple[str, ...] = ("collective_permute", "all_gather")


@dataclasses.dataclass
class CostModelTarget:
    name: str
    build: Callable[[], CostModelSpec]

    checker = "costmodel"


def check_costmodel(target: CostModelTarget) -> Tuple[List[Finding], Dict]:
    try:
        spec = target.build()
    except Exception as e:  # noqa: BLE001
        return [Finding("costmodel", target.name,
                        f"target build failed: {type(e).__name__}: {e}")], {}

    metrics: Dict = {}
    try:
        closed = trace(spec.fn, *spec.args)
        flops = jaxpr_flops(closed)
        io = io_bytes(closed)
        metrics["flops"] = flops
        metrics["io_bytes"] = io
        metrics["arithmetic_intensity"] = (round(flops / io, 4) if io
                                           else None)
    except Exception as e:  # noqa: BLE001
        return [Finding("costmodel", target.name,
                        f"trace failed: {type(e).__name__}: {e}")], metrics

    if not lowering_supported():
        metrics["skipped"] = ("byte cross-check skipped: StableHLO "
                              "lowering unavailable in this JAX/backend")
        return [], metrics
    if pallas_unlowerable(spec.fn, spec.args, closed=closed):
        metrics["skipped"] = f"byte cross-check skipped: {_PALLAS_SKIP_NOTE}"
        return [], metrics
    try:
        ops = collect_collectives(spec.fn, spec.args)
    except Exception as e:  # noqa: BLE001
        return [Finding("costmodel", target.name,
                        f"lowering failed: {type(e).__name__}: {e}")], metrics

    observed = sum(op.bytes_per_shard for op in ops
                   if op.kind in spec.count_kinds)
    expected = int(spec.expected_bytes_per_shard)
    metrics["collectives"] = summarize(ops)
    metrics["observed_bytes_per_shard"] = observed
    metrics["expected_bytes_per_shard"] = expected

    findings: List[Finding] = []
    tol = max(1, int(spec.rel_tol * expected)) if expected else 0
    if abs(observed - expected) > tol:
        pct = (f"{100.0 * (observed - expected) / expected:+.1f}%"
               if expected else "n/a")
        findings.append(Finding(
            "costmodel", target.name,
            f"HLO moves {observed} B/shard but the analytic halo "
            f"model expects {expected} B/shard ({pct}) — the lowered "
            f"exchange no longer matches its geometry (lowering "
            f"regression or model drift)", ERROR))
    if expected and not ops:
        findings.append(Finding(
            "costmodel", target.name,
            "analytic model expects wire traffic but the lowered "
            "module has no collectives — exchange traced away?",
            WARNING))
    return findings, metrics
