"""Checker 7: buffer-donation audit — donated means *aliased*, proven.

Every hot-path entry point in this library jits with
``donate_argnums`` so the curr/next double-buffer swap costs no HBM
copy: the model step loops, the exchange orchestrator
(``make_exchange``), the fused megastep segments, and the ensemble
step/segment/lane programs. Donation is also the property that
silently disappears: a refactor that re-wraps a jitted function
without ``donate_argnums``, or an innocent ``astype`` that changes the
output's byte width, drops the alias and XLA quietly COPIES — the step
still computes the right answer, just with an extra O(domain) HBM
round-trip per dispatch. The only artifact that tells the truth is the
compiled program's ``input_output_alias`` map, so this checker compiles
each registered entry point (CPU backend, seconds — the alias map is a
lowering-level contract XLA:TPU consumes verbatim) and proves every
leaf of every declared-donated argument appears in it. A
donated-but-copied buffer is an ERROR.

:func:`alias_param_ids` is the single alias-map parser — promoted from
``tests/test_donation.py``, which (with ``tests/test_megastep.py``) now
asserts through it instead of duplicating the regex.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, List, Sequence, Set, Tuple

from .report import ERROR, Finding

# the HLO entry computation's alias map, e.g.
#   input_output_alias={ {0}: (0, {}, may-alias), {1}: (2, {}, ...) }
# the body nests braces ({0} output indices, {} param indices), so the
# block is extracted by brace counting, not a non-greedy regex (which
# would stop at the first '}' and see an empty map); each "(N," in the
# body names an aliased parameter
_ALIAS_ATTR = "input_output_alias={"
_ALIAS_PARAM_RE = re.compile(r"\((\d+),")


def _alias_block(compiled_text: str) -> str:
    """The brace-balanced body of the ``input_output_alias`` attribute,
    or '' when the program has no alias map."""
    start = compiled_text.find(_ALIAS_ATTR)
    if start < 0:
        return ""
    i = start + len(_ALIAS_ATTR)
    depth = 1
    for j in range(i, len(compiled_text)):
        c = compiled_text[j]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                return compiled_text[i:j]
    return compiled_text[i:]


def alias_param_ids(compiled_text: str) -> Set[int]:
    """Parameter numbers appearing in the compiled HLO's
    ``input_output_alias`` map. A program with no alias map at all
    returns the empty set (nothing aliases)."""
    return {int(p)
            for p in _ALIAS_PARAM_RE.findall(_alias_block(compiled_text))}


def compiled_alias_ids(fn: Callable, args: Sequence[Any]) -> Set[int]:
    """Compile the (already-jitted) entry point and parse its alias
    map. Nothing executes — ``lower().compile()`` only."""
    return alias_param_ids(fn.lower(*args).compile().as_text())


def _kept_param_order(compiled, n_leaves: int) -> List[int]:
    """Flat input-leaf indices actually KEPT as entry parameters, in
    parameter order: ``jit``'s default ``keep_unused=False`` drops
    unused inputs from the executable and renumbers the rest, so the
    alias map speaks post-drop numbering. Falls back to the identity
    when this JAX doesn't expose the kept set."""
    try:
        kept = compiled._executable._kept_var_idx
        return sorted(int(i) for i in kept)
    except Exception:  # noqa: BLE001 - private API; identity fallback
        return list(range(n_leaves))


@dataclasses.dataclass
class DonationSpec:
    """A jitted entry point plus its donation contract.

    ``fn`` must be the SHIPPED jitted callable (its ``donate_argnums``
    were declared where it was built — wrapping it in a fresh ``jit``
    here would erase exactly the property under audit). A plain
    callable is accepted for fixtures and is jitted WITHOUT donation
    (modelling the refactor that lost it). ``donate_argnums`` declares
    which positional args the contract says must fully alias.
    """

    fn: Callable
    args: Sequence[Any]
    donate_argnums: Tuple[int, ...] = (0,)


@dataclasses.dataclass
class DonationTarget:
    name: str
    build: Callable[[], DonationSpec]

    checker = "donation"


def _leaf_bytes(leaf: Any) -> int:
    import numpy as np

    shape = tuple(getattr(leaf, "shape", ()))
    dtype = getattr(leaf, "dtype", None)
    itemsize = np.dtype(dtype).itemsize if dtype is not None else 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * itemsize


def donated_param_map(args: Sequence[Any],
                      donate_argnums: Sequence[int]
                      ) -> Tuple[Dict[int, str], int]:
    """Map each donated flat parameter id to a human-readable leaf path
    (HLO entry parameters number the flattened positional args in
    order), plus the total donated bytes."""
    import jax

    donate = set(int(d) for d in donate_argnums)
    out: Dict[int, str] = {}
    donated_bytes = 0
    i = 0
    for argnum, a in enumerate(args):
        leaves_with_paths = jax.tree_util.tree_flatten_with_path(a)[0]
        for path, leaf in leaves_with_paths:
            if argnum in donate:
                keys = "".join(str(k) for k in path)
                out[i] = f"arg{argnum}{keys}"
                donated_bytes += _leaf_bytes(leaf)
            i += 1
    return out, donated_bytes


def check_donation(target: DonationTarget) -> Tuple[List[Finding], Dict]:
    """Prove every declared-donated buffer of the target actually
    aliases in the compiled program."""
    from .hlo import lowering_supported, pallas_unlowerable

    try:
        spec = target.build()
    except Exception as e:  # noqa: BLE001
        return [Finding("donation", target.name,
                        f"target build failed: {type(e).__name__}: {e}")], {}
    if not lowering_supported():
        return [], {"skipped": "StableHLO lowering unavailable in this "
                               "JAX/backend"}
    fn = spec.fn
    if not hasattr(fn, "lower"):
        import jax

        # fixture hook: a bare callable models the jit that LOST its
        # donate_argnums — audited as shipped, i.e. without donation
        fn = jax.jit(fn)
    try:
        if pallas_unlowerable(fn, spec.args):
            return [], {"skipped": "contains pallas_call; compiling "
                                   "needs a TPU backend"}
    except Exception as e:  # noqa: BLE001
        return [Finding("donation", target.name,
                        f"trace failed: {type(e).__name__}: {e}")], {}
    try:
        compiled = fn.lower(*spec.args).compile()
    except Exception as e:  # noqa: BLE001
        return [Finding("donation", target.name,
                        f"compile failed: {type(e).__name__}: {e}")], {}
    aliased = alias_param_ids(compiled.as_text())

    expected, donated_bytes = donated_param_map(spec.args,
                                                spec.donate_argnums)
    import jax

    n_leaves = len(jax.tree_util.tree_leaves(list(spec.args)))
    kept = _kept_param_order(compiled, n_leaves)
    metrics = {"donated_bytes": donated_bytes,
               "donated_leaves": len(expected),
               "aliased_params": sorted(aliased)}
    findings: List[Finding] = []
    for flat_id in sorted(expected):
        if flat_id not in kept:
            findings.append(Finding(
                "donation", target.name,
                f"declared-donated buffer {expected[flat_id]} is "
                f"UNUSED by the compiled program (jit dropped the "
                f"parameter) — the donation contract names a buffer "
                f"the entry point never consumes", ERROR))
            continue
        pid = kept.index(flat_id)
        if pid not in aliased:
            findings.append(Finding(
                "donation", target.name,
                f"declared-donated buffer {expected[flat_id]} (entry "
                f"parameter {pid}) is missing from the compiled "
                f"input_output_alias map — the donation is dead and "
                f"XLA copies this buffer every dispatch "
                f"(aliased params: {sorted(aliased) or 'none'})",
                ERROR))
    return findings, metrics
