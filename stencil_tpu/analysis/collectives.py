"""Checker 3: collective sanity — ppermute permutations and axis names.

``lax.ppermute`` is the exchange engine's transport: each halo shift is
a (source, dest) pair list over one mesh axis. XLA only validates the
permutation at compile time (and silently drops un-sourced
destinations — receiving shards keep ZEROS, the exact silent-stale-halo
failure mode). Statically, a shift is safe iff its permutation is a
full bijection of the axis:

* every pair index lies in ``[0, axis_size)``;
* no duplicated source and no duplicated destination;
* every device sends and receives exactly once (``len(perm) == n``) —
  a partial permutation leaves some shard's halo unfilled.

Additionally every collective's axis name (``ppermute``, ``all_gather``,
``axis_index``, ``psum``...) must resolve against the mesh axes built
by ``parallel/mesh.py`` — a typo'd axis name surfaces at runtime deep
inside shard_map; here it is a one-line finding.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Sequence, Tuple

from .jaxprs import iter_eqns, trace
from .report import ERROR, WARNING, Finding

# primitives that carry an axis_name param worth validating
_AXIS_PRIMS = ("ppermute", "all_gather", "axis_index", "psum",
               "all_to_all", "reduce_scatter")


@dataclasses.dataclass
class CollectiveSpec:
    """A traceable program (typically ``shard_map``-ped, possibly
    jitted) plus the mesh axis sizes its collectives must respect."""

    fn: Callable
    args: Sequence[Any]
    axis_sizes: Dict[str, int]
    expect_ppermute: bool = False


@dataclasses.dataclass
class CollectiveTarget:
    name: str
    build: Callable[[], CollectiveSpec]

    checker = "collectives"


def _axis_names(params: dict) -> Tuple[str, ...]:
    ax = params.get("axis_name", params.get("axes", ()))
    if isinstance(ax, (tuple, list)):
        return tuple(str(a) for a in ax)
    return (str(ax),)


def check_collectives(target: CollectiveTarget) -> List[Finding]:
    try:
        spec = target.build()
    except Exception as e:  # noqa: BLE001
        return [Finding("collectives", target.name,
                        f"target build failed: {type(e).__name__}: {e}")]
    try:
        closed = trace(spec.fn, *spec.args)
    except Exception as e:  # noqa: BLE001
        return [Finding("collectives", target.name,
                        f"trace failed: {type(e).__name__}: {e}")]

    findings: List[Finding] = []
    sizes = dict(spec.axis_sizes)
    n_ppermute = 0

    def err(msg: str, severity: str = ERROR) -> None:
        findings.append(Finding("collectives", target.name, msg, severity))

    for eqn in iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name not in _AXIS_PRIMS:
            continue
        axes = _axis_names(eqn.params)
        for ax in axes:
            if ax not in sizes:
                err(f"{name} over unknown mesh axis '{ax}' (mesh axes: "
                    f"{sorted(sizes)})")
        if name != "ppermute":
            continue
        n_ppermute += 1
        if len(axes) != 1 or axes[0] not in sizes:
            continue  # unknown axis already reported
        n = sizes[axes[0]]
        perm = [tuple(int(i) for i in pair)
                for pair in eqn.params.get("perm", ())]
        label = f"ppermute over '{axes[0]}' (size {n}) perm={perm}"
        srcs = [s for s, _ in perm]
        dsts = [d for _, d in perm]
        oob = [i for i in srcs + dsts if i < 0 or i >= n]
        if oob:
            err(f"{label}: indices {sorted(set(oob))} outside "
                f"[0, {n})")
            continue
        if len(set(srcs)) != len(srcs):
            dup = sorted({s for s in srcs if srcs.count(s) > 1})
            err(f"{label}: duplicated source(s) {dup} — a shard sends "
                f"twice, not a permutation")
        if len(set(dsts)) != len(dsts):
            dup = sorted({d for d in dsts if dsts.count(d) > 1})
            err(f"{label}: duplicated destination(s) {dup} — conflicting "
                f"writes to one shard's halo")
        if (len(set(srcs)) == len(srcs) and len(set(dsts)) == len(dsts)
                and (set(srcs) != set(range(n))
                     or set(dsts) != set(range(n)))):
            err(f"{label}: not a full bijection of the axis — "
                f"unpaired shards keep ZEROS in their halos (silent "
                f"stale data)")

    if spec.expect_ppermute and n_ppermute == 0:
        err("expected ppermute collectives but none traced — the "
            "checker would be vacuous here", WARNING)
    return findings
