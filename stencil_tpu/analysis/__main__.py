"""CLI entry: ``python -m stencil_tpu.analysis``.

Exit status: 0 when every checked invariant holds, 1 when any
error-severity finding exists, 2 on usage errors. ``--json PATH``
writes the machine-readable report (schema in ``report.py``) for CI
artifacts. ``--only NAME`` (or the legacy spelling ``--checker``)
restricts the run to one checker when NAME is a checker name, or to
the registry targets matching NAME as a glob pattern otherwise
(``--only 'telemetry.*'``); repeatable, and the two forms compose
(checker filter AND target filter). ``--list`` enumerates the
checkers plus the registry target counts per group and exits.
Positional arguments are fixture module paths (files defining
``TARGETS``) checked INSTEAD of the shipped registry — the
negative-control hook: the CLI must exit nonzero on every fixture
under ``tests/fixtures/lint/``.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from ..utils.naming import glob_match as _match


def _setup_backend() -> None:
    """Analysis is pure tracing/lowering: force a small virtual-CPU
    mesh so the shard_map targets resolve their axes without touching
    accelerators (mirrors tests/conftest.py; shared old-JAX fallback
    lives in apply_fake_cpu)."""
    try:
        from stencil_tpu.utils.config import apply_fake_cpu

        apply_fake_cpu(8)
    except RuntimeError:
        pass  # backend already initialized; use whatever exists


def main(argv: Optional[List[str]] = None) -> int:
    from . import CHECKER_DOC, CHECKERS

    parser = argparse.ArgumentParser(
        prog="python -m stencil_tpu.analysis",
        description="stencil-lint: static halo-radius / DMA-discipline "
                    "/ collective-permutation / HLO-lowering / "
                    "cost-model / VMEM / donation / host-transfer / "
                    "recompile / prescriptive-tiling / link-traffic / "
                    "RDMA-schedule-certification / "
                    "precision-certification checks (no execution)")
    parser.add_argument("fixtures", nargs="*",
                        help="fixture module paths (files defining "
                             "TARGETS) to check instead of the shipped "
                             "registry")
    parser.add_argument("--json", metavar="PATH",
                        help="write the JSON report here")
    parser.add_argument("--only", "--checker", action="append",
                        dest="only", metavar="CHECKER|GLOB",
                        help="run only this checker (exact checker "
                             "name) or only the targets matching this "
                             "glob pattern, e.g. 'telemetry.*' "
                             "(repeatable; forms compose)")
    parser.add_argument("--list", action="store_true", dest="list_",
                        help="list the available checkers and the "
                             "registry target counts per group, then "
                             "exit")
    parser.add_argument("--plan-tiling", metavar="GLOB",
                        dest="plan_tiling",
                        help="print the ranked VMEM block-shape plan "
                             "(shape, footprint bytes, amplification, "
                             "legality) for the analysis.tiling.* "
                             "targets matching GLOB; --json writes the "
                             "machine-readable plan report instead of "
                             "the findings artifact")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="suppress the per-target OK lines")
    args = parser.parse_args(argv)

    if args.list_:
        for name in CHECKERS:
            print(f"  {name:<12} {CHECKER_DOC[name]}")
        from .registry import default_targets

        targets = default_targets()
        groups: dict = {}
        for t in targets:
            g = t.name.split(".", 1)[0]
            groups.setdefault(g, {})
            groups[g][t.checker] = groups[g].get(t.checker, 0) + 1
        print(f"\n  {len(targets)} registry targets by group:")
        for g in sorted(groups):
            per = " ".join(f"{c}={n}"
                           for c, n in sorted(groups[g].items()))
            print(f"    {g:<12} {sum(groups[g].values()):>3}  ({per})")
        return 0

    checkers = [v for v in (args.only or []) if v in CHECKERS]
    patterns = [v for v in (args.only or []) if v not in CHECKERS]

    _setup_backend()

    if args.plan_tiling:
        import json as _json

        from .registry import default_targets
        from .tiling import plan_tiling_report, render_plan_table

        tiling = [t for t in default_targets() if t.checker == "tiling"]
        chosen = [t for t in tiling
                  if _match(t.name, args.plan_tiling)
                  or _match(t.name.replace("analysis.tiling.", "", 1),
                            args.plan_tiling)]
        if not chosen:
            print(f"stencil-lint: no tiling targets match "
                  f"{args.plan_tiling!r} ({len(tiling)} registered "
                  f"under analysis.tiling.*)", file=sys.stderr)
            return 2
        report = plan_tiling_report(chosen)
        print(render_plan_table(report))
        if args.json:
            with open(args.json, "w", encoding="utf-8") as fh:
                _json.dump({"tool": "stencil-lint", "mode": "plan-tiling",
                            "plans": report}, fh, indent=2)
            print(f"stencil-lint: tiling plan report written to "
                  f"{args.json}")
        return 0

    from . import run_targets
    from .registry import default_targets, load_targets

    try:
        if args.fixtures:
            targets = []
            for path in args.fixtures:
                targets.extend(load_targets(path))
        else:
            targets = default_targets()
    except (ImportError, ValueError, OSError) as e:
        print(f"stencil-lint: cannot load targets: {e}", file=sys.stderr)
        return 2

    if patterns:
        # EVERY pattern must match something: a typo'd glob among
        # several must fail the run, not silently drop its coverage
        unmatched = [p for p in patterns
                     if not any(_match(t.name, p) for t in targets)]
        if unmatched:
            print(f"stencil-lint: no targets match {unmatched} "
                  f"(values that are not checker names filter target "
                  f"names by glob)", file=sys.stderr)
            return 2
        targets = [t for t in targets
                   if any(_match(t.name, p) for p in patterns)]
    if checkers and not any(t.checker in checkers for t in targets):
        # a checker filter + glob that intersect to nothing would be a
        # vacuously green run — the same silent coverage drop the
        # unmatched-glob guard above refuses
        print(f"stencil-lint: the --only filters select no targets "
              f"(checkers {checkers} x {len(targets)} matched "
              f"target(s))", file=sys.stderr)
        return 2

    report = run_targets(targets, checkers=checkers or None)

    if not args.quiet:
        flagged = {f.target.split(":", 1)[0] for f in report.findings}
        for name in report.targets_checked:
            if name not in flagged:
                print(f"  OK   {name}")
    for f in report.findings:
        tag = "ERROR" if f.severity == "error" else "warn "
        print(f"  {tag} {f}")
    n_err, n_warn = len(report.errors), len(report.warnings)
    timing = " ".join(f"{k}={v:.2f}s"
                      for k, v in sorted(report.checker_seconds.items()))
    print(f"stencil-lint: {len(report.targets_checked)} targets, "
          f"{n_err} error(s), {n_warn} warning(s)"
          + (f" [{timing}]" if timing else ""))

    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            fh.write(report.to_json())
        print(f"stencil-lint: JSON report written to {args.json}")

    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
