"""CLI entry: ``python -m stencil_tpu.analysis``.

Exit status: 0 when every checked invariant holds, 1 when any
error-severity finding exists, 2 on usage errors. ``--json PATH``
writes the machine-readable report (schema in ``report.py``) for CI
artifacts. ``--only NAME`` (or the legacy spelling ``--checker``)
restricts the run to one checker (repeatable); ``--list`` enumerates
the checkers and exits. Positional arguments are fixture module paths
(files defining ``TARGETS``) checked INSTEAD of the shipped registry —
the negative-control hook: the CLI must exit nonzero on every fixture
under ``tests/fixtures/lint/``.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def _setup_backend() -> None:
    """Analysis is pure tracing/lowering: force a small virtual-CPU
    mesh so the shard_map targets resolve their axes without touching
    accelerators (mirrors tests/conftest.py; shared old-JAX fallback
    lives in apply_fake_cpu)."""
    try:
        from stencil_tpu.utils.config import apply_fake_cpu

        apply_fake_cpu(8)
    except RuntimeError:
        pass  # backend already initialized; use whatever exists


def main(argv: Optional[List[str]] = None) -> int:
    from . import CHECKER_DOC, CHECKERS

    parser = argparse.ArgumentParser(
        prog="python -m stencil_tpu.analysis",
        description="stencil-lint: static halo-radius / DMA-discipline "
                    "/ collective-permutation / HLO-lowering / "
                    "cost-model / VMEM checks (no execution)")
    parser.add_argument("fixtures", nargs="*",
                        help="fixture module paths (files defining "
                             "TARGETS) to check instead of the shipped "
                             "registry")
    parser.add_argument("--json", metavar="PATH",
                        help="write the JSON report here")
    parser.add_argument("--only", "--checker", action="append",
                        dest="checkers", choices=CHECKERS,
                        help="run only this checker (repeatable)")
    parser.add_argument("--list", action="store_true", dest="list_",
                        help="list the available checkers and exit")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="suppress the per-target OK lines")
    args = parser.parse_args(argv)

    if args.list_:
        for name in CHECKERS:
            print(f"  {name:<12} {CHECKER_DOC[name]}")
        return 0

    _setup_backend()

    from . import run_targets
    from .registry import default_targets, load_targets

    try:
        if args.fixtures:
            targets = []
            for path in args.fixtures:
                targets.extend(load_targets(path))
        else:
            targets = default_targets()
    except (ImportError, ValueError, OSError) as e:
        print(f"stencil-lint: cannot load targets: {e}", file=sys.stderr)
        return 2

    report = run_targets(targets, checkers=args.checkers)

    if not args.quiet:
        flagged = {f.target.split(":", 1)[0] for f in report.findings}
        for name in report.targets_checked:
            if name not in flagged:
                print(f"  OK   {name}")
    for f in report.findings:
        tag = "ERROR" if f.severity == "error" else "warn "
        print(f"  {tag} {f}")
    n_err, n_warn = len(report.errors), len(report.warnings)
    timing = " ".join(f"{k}={v:.2f}s"
                      for k, v in sorted(report.checker_seconds.items()))
    print(f"stencil-lint: {len(report.targets_checked)} targets, "
          f"{n_err} error(s), {n_warn} warning(s)"
          + (f" [{timing}]" if timing else ""))

    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            fh.write(report.to_json())
        print(f"stencil-lint: JSON report written to {args.json}")

    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
