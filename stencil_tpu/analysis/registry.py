"""The shipped-code target registry for stencil-lint.

Every stencil op, Pallas kernel, and collective exchange path the
framework ships is registered here with its declared contract; the
checkers in this package prove the contracts against the traced IR
(footprint/dma/collectives/vmem) or the lowered StableHLO
(hlo/costmodel). Negative-control fixtures under
``tests/fixtures/lint/`` define the same target types with
deliberately broken kernels (loaded via :func:`load_targets`) — each
checker must flag them, proving the pass is not vacuously green.

Coverage is drift-guarded: ``stencil_tpu.ops.PUBLIC_OPS`` and
``stencil_tpu.parallel.EXCHANGE_METHOD_TARGETS`` list every public op
and exchange method with the target name (prefix) here that covers it,
and ``tests/test_lint.py`` cross-checks both manifests against
:func:`default_targets` — new code cannot silently escape the gate.
"""

from __future__ import annotations

import functools
import importlib.util
from pathlib import Path
from typing import List, Union

from .collectives import CollectiveSpec, CollectiveTarget
from .costmodel import CostModelSpec, CostModelTarget
from .dma import PallasKernelSpec, PallasKernelTarget
from .donation import DonationSpec, DonationTarget
from .footprint import StencilOpSpec, StencilOpTarget
from .hlo import HloSpec, HloTarget
from .precision import PrecisionSpec, PrecisionTarget
from .recompile import RecompileSpec, RecompileTarget
from .schedule import ScheduleSpec, ScheduleTarget
from .transfer import TransferSpec, TransferTarget
from .vmem import VmemSpec, VmemTarget
from ..observatory.linkmap import LinkmapSpec, LinkmapTarget

Target = Union[StencilOpTarget, PallasKernelTarget, CollectiveTarget,
               HloTarget, CostModelTarget, VmemTarget, DonationTarget,
               TransferTarget, RecompileTarget, LinkmapTarget,
               ScheduleTarget, PrecisionTarget]


def _f32(shape):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(shape, jnp.float32)


def _mesh(shape):
    import jax

    from ..parallel.mesh import make_mesh

    n = shape[0] * shape[1] * shape[2]
    return make_mesh(shape, jax.devices()[:n])


# ---------------------------------------------------------------------------
# footprint targets: registered stencil ops vs. their declared Radius


def _jacobi7_spec() -> StencilOpSpec:
    from ..geometry import Dim3, Radius
    from ..ops.stencil_kernels import jacobi7

    radius = Radius.constant(1)
    interior = Dim3(8, 8, 8)
    shape = tuple(interior[2 - i] + radius.pad_lo()[2 - i]
                  + radius.pad_hi()[2 - i] for i in range(3))
    return StencilOpSpec(fn=lambda p: jacobi7(p, radius, interior),
                         args=(_f32(shape),), radius=radius,
                         interior=interior)


def _laplacian27_spec() -> StencilOpSpec:
    from ..geometry import Dim3, Radius
    from ..ops.stencil_kernels import laplacian27

    radius = Radius.constant(1)
    interior = Dim3(8, 8, 8)
    return StencilOpSpec(fn=lambda p: laplacian27(p, radius, interior),
                         args=(_f32((10, 10, 10)),), radius=radius,
                         interior=interior)


def _fd6_spec(kind: str, axes) -> StencilOpSpec:
    from ..geometry import Dim3, Radius
    from ..ops import fd6

    radius = Radius.constant(fd6.RADIUS)
    interior = Dim3(8, 8, 8)
    pad_lo = radius.pad_lo()
    shape = (14, 14, 14)  # 8 + 2 * RADIUS per dim

    if kind == "der1":
        fn = lambda p: fd6.der1(p, axes, 1.0, pad_lo, interior)  # noqa: E731
    elif kind == "der2":
        fn = lambda p: fd6.der2(p, axes, 1.0, pad_lo, interior)  # noqa: E731
    else:
        a, b = axes
        fn = lambda p: fd6.der_cross(p, a, b, 1.0, 1.0, pad_lo,  # noqa: E731
                                     interior)
    return StencilOpSpec(fn=fn, args=(_f32(shape),), radius=radius,
                         interior=interior)


def _mhd_rates_spec() -> StencilOpSpec:
    from ..geometry import Dim3, Radius
    from ..models.astaroth import FIELDS, MhdParams, mhd_rates
    from ..ops.fd6 import RADIUS, FieldData

    import jax.numpy as jnp

    radius = Radius.constant(RADIUS)
    interior = Dim3(8, 8, 8)
    pad_lo = radius.pad_lo()
    prm = MhdParams()
    inv_ds = (1.0 / prm.dsx, 1.0 / prm.dsy, 1.0 / prm.dsz)

    def fn(*padded):
        data = {q: FieldData(p, inv_ds, pad_lo, interior)
                for q, p in zip(FIELDS, padded)}
        rates = mhd_rates(data, prm, jnp.float32)
        return tuple(rates[q] for q in FIELDS)

    nf = len(FIELDS)
    return StencilOpSpec(fn=fn, args=tuple(_f32((14, 14, 14))
                                           for _ in range(nf)),
                         radius=radius, interior=interior,
                         padded_argnums=tuple(range(nf)))


# ---------------------------------------------------------------------------
# DMA-discipline targets: every Pallas kernel issuing (remote) DMA


def _rdma_exchange_spec(side: int = 8) -> PallasKernelSpec:
    import jax
    from jax.sharding import PartitionSpec as P

    from ..geometry import Radius
    from ..parallel.mesh import mesh_dim
    from ..parallel.pallas_exchange import exchange_shard_pallas

    mesh = _mesh((2, 2, 2))
    counts = mesh_dim(mesh)
    radius = Radius.constant(1)

    def shard(p):
        return exchange_shard_pallas(p, radius, counts, interpret=False)

    sm = jax.shard_map(shard, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    g = 2 * side
    return PallasKernelSpec(fn=sm, args=(_f32((g, g, g)),),
                            axis_names=("x", "y", "z"),
                            expect_remote_dma=True)


def _jacobi_overlap_spec(side: int = 8) -> PallasKernelSpec:
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..geometry import Dim3
    from ..ops.pallas_overlap import jacobi7_overlap_pallas

    mesh = _mesh((1, 2, 2))
    counts = Dim3(1, 2, 2)
    bz = 4 if side <= 8 else 8

    def shard(q):
        iz = jax.lax.axis_index("z")
        iy = jax.lax.axis_index("y")
        org = jnp.stack([iz * side, iy * side,
                         jnp.int32(0)]).astype(jnp.int32)
        return jacobi7_overlap_pallas(
            q, org, (side // 4, side // 2, side // 2),
            (5 * side // 8, side // 2, side // 2), 1, counts,
            block_z=bz, interpret=False)

    sm = jax.shard_map(shard, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    return PallasKernelSpec(fn=sm, args=(_f32((2 * side, 2 * side, side)),),
                            axis_names=("x", "y", "z"),
                            expect_remote_dma=True)


def _mhd_overlap_spec(pair: bool, side: int = 8) -> PallasKernelSpec:
    import jax
    from jax.sharding import PartitionSpec as P

    from ..geometry import Dim3
    from ..models.astaroth import FIELDS, MhdParams
    from ..ops.pallas_mhd_overlap import mhd_substep_overlap

    mesh = _mesh((1, 2, 2))
    counts = Dim3(1, 2, 2)
    prm = MhdParams()

    def shard(fields, w):
        f, wk = mhd_substep_overlap(fields, None if pair else w, 0, prm,
                                    prm.dt, counts, pair=pair,
                                    interpret=False)
        return f, (wk if wk is not None else f)

    spec = P("z", "y", "x")
    fspec = {q: spec for q in FIELDS}
    sm = jax.shard_map(shard, mesh=mesh, in_specs=(fspec, fspec),
                       out_specs=(fspec, fspec), check_vma=False)
    fields = {q: _f32((2 * side, 2 * side, side)) for q in FIELDS}
    w = {q: _f32((2 * side, 2 * side, side)) for q in FIELDS}
    return PallasKernelSpec(fn=sm, args=(fields, w),
                            axis_names=("x", "y", "z"),
                            expect_remote_dma=True)


def _jacobi_halo_kernel_spec(side: int = 8) -> PallasKernelSpec:
    """The fused halo kernel: no DMA at all — the checker proves its
    discipline vacuously and (more importantly) that it never gained a
    stray semaphore/DMA without review."""
    import jax.numpy as jnp

    from ..ops.pallas_halo import jacobi7_halo_pallas

    Z = Y = X = side
    slabs = {"zlo": _f32((1, Y, X)), "zhi": _f32((1, Y, X)),
             "ylo": _f32((Z, 8, X)), "yhi": _f32((Z, 8, X))}

    def fn(interior, zlo, zhi, ylo, yhi, org):
        return jacobi7_halo_pallas(
            interior, {"zlo": zlo, "zhi": zhi, "ylo": ylo, "yhi": yhi},
            org, (2, 4, 4), (5, 4, 4), 1, interpret=False)

    import jax
    org = jax.ShapeDtypeStruct((3,), jnp.int32)
    return PallasKernelSpec(
        fn=fn, args=(_f32((Z, Y, X)), slabs["zlo"], slabs["zhi"],
                     slabs["ylo"], slabs["yhi"], org),
        axis_names=(), expect_remote_dma=False)


# ---------------------------------------------------------------------------
# schedule-certification targets: checker 12 — the same remote-DMA
# kernels, their semaphore schedules certified sound under k-fold
# replay (the proof megastep's certificate-gated fusion consumes)

_SCHED_K = 4


def _schedule_from_kernel(build, expect_max_in_flight=None,
                          fused_by_megastep: bool = False
                          ) -> ScheduleSpec:
    """Lift a dma-checker kernel spec into a schedule spec: the same
    traceable fn, certified under ``_SCHED_K``-fold replay."""
    ps = build()
    return ScheduleSpec(
        fn=ps.fn, args=ps.args, axis_names=ps.axis_names,
        replay=_SCHED_K, expect_remote_dma=ps.expect_remote_dma,
        expect_max_in_flight=expect_max_in_flight,
        fused_by_megastep=fused_by_megastep)


def _overlap_schedule_spec() -> ScheduleSpec:
    from ..ops.pallas_overlap import SCHEDULE_EXPECT

    return _schedule_from_kernel(
        _jacobi_overlap_spec,
        expect_max_in_flight=SCHEDULE_EXPECT["max_in_flight"],
        fused_by_megastep=True)


def _mhd_overlap_schedule_spec(pair: bool) -> ScheduleSpec:
    from ..ops.pallas_mhd_overlap import SCHEDULE_EXPECT

    return _schedule_from_kernel(
        lambda: _mhd_overlap_spec(pair=pair),
        expect_max_in_flight=SCHEDULE_EXPECT["max_in_flight"])


def _halo_schedule_spec() -> ScheduleSpec:
    from ..ops.pallas_halo import SCHEDULE_EXPECT

    return _schedule_from_kernel(
        _jacobi_halo_kernel_spec,
        expect_max_in_flight=SCHEDULE_EXPECT["max_in_flight"])


def _overlap_segment_schedule_spec(side: int = 8) -> ScheduleSpec:
    """The fused overlap SEGMENT pinned as a registry target:
    ``_SCHED_K`` sequential ``jacobi7_overlap_pallas`` launches inside
    ONE traced program — the exact multi-launch shape megastep's
    chunk-of-1 unroll dispatches once the per-launch certificate
    licenses fusion (models/jacobi.py:_build_overlap_step). Every
    constituent launch must certify replay-safe; CI stage 1 asserts
    it."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..geometry import Dim3
    from ..ops.pallas_overlap import SCHEDULE_EXPECT, \
        jacobi7_overlap_pallas

    mesh = _mesh((1, 2, 2))
    counts = Dim3(1, 2, 2)
    bz = 4 if side <= 8 else 8

    def shard(q):
        iz = jax.lax.axis_index("z")
        iy = jax.lax.axis_index("y")
        org = jnp.stack([iz * side, iy * side,
                         jnp.int32(0)]).astype(jnp.int32)
        for _ in range(_SCHED_K):
            q = jacobi7_overlap_pallas(
                q, org, (side // 4, side // 2, side // 2),
                (5 * side // 8, side // 2, side // 2), 1, counts,
                block_z=bz, interpret=False)
        return q

    sm = jax.shard_map(shard, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    return ScheduleSpec(
        fn=sm, args=(_f32((2 * side, 2 * side, side)),),
        axis_names=("x", "y", "z"), replay=_SCHED_K,
        expect_remote_dma=True,
        expect_max_in_flight=SCHEDULE_EXPECT["max_in_flight"],
        fused_by_megastep=True)


def _schedule_targets() -> List[Target]:
    k = _SCHED_K
    return [
        ScheduleTarget(
            f"analysis.schedule.parallel.pallas_exchange."
            f"exchange_shard_pallas[k={k}]",
            lambda: _schedule_from_kernel(_rdma_exchange_spec)),
        ScheduleTarget(
            f"analysis.schedule.ops.pallas_overlap."
            f"jacobi7_overlap_pallas[k={k}]",
            _overlap_schedule_spec),
        ScheduleTarget(
            f"analysis.schedule.ops.pallas_mhd_overlap."
            f"mhd_substep_overlap[k={k}]",
            lambda: _mhd_overlap_schedule_spec(pair=False)),
        ScheduleTarget(
            f"analysis.schedule.ops.pallas_mhd_overlap."
            f"mhd_substep_overlap[pair,k={k}]",
            lambda: _mhd_overlap_schedule_spec(pair=True)),
        ScheduleTarget(
            f"analysis.schedule.ops.pallas_halo."
            f"jacobi7_halo_pallas[k={k}]",
            _halo_schedule_spec),
        ScheduleTarget(
            f"analysis.schedule.parallel.megastep."
            f"segment[overlap,k={k}]",
            _overlap_segment_schedule_spec),
    ]


# ---------------------------------------------------------------------------
# collective targets: ppermute bijections + axis-name hygiene


# the exchange_shard targets' geometry, shared by the collective spec
# builder AND the cost-model expectation so the two cannot drift: a
# (28,28,28) padded global over the 2x2x2 mesh -> (14,14,14) shards
_EXCHANGE_GLOBAL = (28, 28, 28)
_EXCHANGE_MESH = (2, 2, 2)


def _exchange_shard_shape():
    return tuple(g // m for g, m in zip(_EXCHANGE_GLOBAL,
                                        _EXCHANGE_MESH))


def _exchange_radius(radius_kind: str):
    from ..geometry import Radius

    if radius_kind == "r1":
        return Radius.constant(1)
    if radius_kind == "r3":
        return Radius.constant(3)
    if radius_kind == "asym":  # asymmetric, zero on some sides
        radius = Radius.constant(0)
        radius.set_dir((1, 0, 0), 2)
        radius.set_dir((-1, 0, 0), 1)
        radius.set_dir((0, 1, 0), 1)
        return radius
    raise ValueError(f"unknown exchange radius kind {radius_kind!r}")


def _exchange_spec(radius_kind: str) -> CollectiveSpec:
    import jax
    from jax.sharding import PartitionSpec as P

    from ..parallel.exchange import exchange_shard
    from ..parallel.mesh import mesh_dim

    mesh = _mesh(_EXCHANGE_MESH)
    counts = mesh_dim(mesh)
    radius = _exchange_radius(radius_kind)

    def shard(p):
        return exchange_shard(p, radius, counts)

    sm = jax.shard_map(shard, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    return CollectiveSpec(fn=sm, args=(_f32(_EXCHANGE_GLOBAL),),
                          axis_sizes=dict(mesh.shape),
                          expect_ppermute=True)


def _exchange_packed_uneven_spec() -> CollectiveSpec:
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..geometry import Dim3, Radius
    from ..parallel.exchange import exchange_shard_packed
    from ..parallel.mesh import mesh_dim

    mesh = _mesh((2, 2, 2))
    counts = mesh_dim(mesh)
    radius = Radius.constant(1)
    rem = Dim3(1, 1, 1)

    def shard(fields):
        return exchange_shard_packed(fields, radius, counts, rem=rem)

    spec = {"a": P("z", "y", "x"), "b": P("z", "y", "x")}
    sm = jax.shard_map(shard, mesh=mesh, in_specs=(spec,),
                       out_specs=spec, check_vma=False)
    fields = {"a": _f32((20, 20, 20)),
              "b": jax.ShapeDtypeStruct((20, 20, 20), jnp.bfloat16)}
    return CollectiveSpec(fn=sm, args=(fields,),
                          axis_sizes=dict(mesh.shape),
                          expect_ppermute=True)


def _exchange_allgather_spec() -> CollectiveSpec:
    import jax
    from jax.sharding import PartitionSpec as P

    from ..geometry import Radius
    from ..parallel.exchange import exchange_shard_allgather
    from ..parallel.mesh import mesh_dim

    mesh = _mesh((2, 2, 2))
    counts = mesh_dim(mesh)
    radius = Radius.constant(1)

    def shard(p):
        return exchange_shard_allgather(p, radius, counts)

    sm = jax.shard_map(shard, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    return CollectiveSpec(fn=sm, args=(_f32((16, 16, 16)),),
                          axis_sizes=dict(mesh.shape))


def _interior_slabs_spec(yzext: bool) -> CollectiveSpec:
    import jax
    from jax.sharding import PartitionSpec as P

    from ..geometry import Dim3
    from ..parallel.exchange import exchange_interior_slabs

    mesh = _mesh((1, 2, 2))
    counts = Dim3(1, 2, 2)

    def shard(p):
        s = exchange_interior_slabs(p, counts, rz=8, ry=8, radius_rows=3,
                                    y_z_extended=yzext)
        return (s["zlo"], s["zhi"], s["ylo"], s["yhi"])

    spec = P("z", "y", "x")
    sm = jax.shard_map(shard, mesh=mesh, in_specs=spec,
                       out_specs=(spec,) * 4, check_vma=False)
    return CollectiveSpec(fn=sm, args=(_f32((16, 16, 8)),),
                          axis_sizes=dict(mesh.shape),
                          expect_ppermute=True)


def _temporal_group_spec(s: int = 2) -> CollectiveSpec:
    """The temporal-blocking fused group (parallel/temporal.py): one
    depth-s exchange + s jacobi sub-steps on shrinking windows. Audited
    like any exchange method — ppermute bijections, collective-permute-
    only lowering, and the deep-slab byte model must match the HLO."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..geometry import Radius
    from ..ops.stencil_kernels import jacobi7
    from ..parallel.mesh import mesh_dim
    from ..parallel.methods import Method
    from ..parallel.temporal import temporal_shard_steps

    mesh = _mesh(_EXCHANGE_MESH)
    counts = mesh_dim(mesh)
    radius = Radius.constant(1)

    def upd(blocks, dims, off, k):
        return {"q": jacobi7(blocks["q"], radius, dims)}

    def shard(p):
        return temporal_shard_steps({"q": p}, radius, counts,
                                    Method.PpermuteSlab, upd, s)["q"]

    sm = jax.shard_map(shard, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    side = (8 + 2 * s)  # 8^3 interiors + deep pads, per shard
    g = tuple(side * m for m in _EXCHANGE_MESH)
    return CollectiveSpec(fn=sm, args=(_f32(g),),
                          axis_sizes=dict(mesh.shape),
                          expect_ppermute=True)


def _temporal_group_cost(s: int = 2) -> CostModelSpec:
    from ..geometry import Dim3, Radius
    from .costmodel import deep_exchange_bytes_per_shard

    cs = _temporal_group_spec(s)
    expected = deep_exchange_bytes_per_shard(
        (8, 8, 8), Radius.constant(1), Dim3(*_EXCHANGE_MESH), 4, s)
    return CostModelSpec(fn=cs.fn, args=cs.args,
                         expected_bytes_per_shard=expected)


#: the per-axis depth vector the asymmetric-group targets pin:
#: deep z (the DCN-friendly axis), shallow x/y — Dim3(1, 1, 2)
_ASYM_DEPTHS = ((1, 1, 2),)


def _temporal_group_asym_spec(depths=None) -> CollectiveSpec:
    """The PER-AXIS (asymmetric) temporal group: one exchange shipping
    each axis at its own depth, then ``max(depths)`` sub-steps with
    mid-group refreshes of the shallow axes (``refresh_axes``). Audited
    like the uniform group — ppermute bijections, collective-permute-
    only lowering, and the asymmetric byte model matching the HLO."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..geometry import Radius, normalize_depths
    from ..ops.stencil_kernels import jacobi7
    from ..parallel.mesh import mesh_dim
    from ..parallel.methods import Method
    from ..parallel.temporal import temporal_shard_steps

    d = normalize_depths(depths if depths is not None
                         else _ASYM_DEPTHS[0])
    mesh = _mesh(_EXCHANGE_MESH)
    counts = mesh_dim(mesh)
    radius = Radius.constant(1)

    def upd(blocks, dims, off, k):
        return {"q": jacobi7(blocks["q"], radius, dims)}

    def shard(p):
        return temporal_shard_steps({"q": p}, radius, counts,
                                    Method.PpermuteSlab, upd, d)["q"]

    sm = jax.shard_map(shard, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    # 8^3 interiors + per-axis deep pads, (z, y, x) layout
    sides = (8 + 2 * d.z, 8 + 2 * d.y, 8 + 2 * d.x)
    g = tuple(side * m for side, m in zip(sides, _EXCHANGE_MESH))
    return CollectiveSpec(fn=sm, args=(_f32(g),),
                          axis_sizes=dict(mesh.shape),
                          expect_ppermute=True)


def _temporal_group_asym_cost(depths=None) -> CostModelSpec:
    from ..geometry import Dim3, Radius
    from .costmodel import asymmetric_group_bytes_per_shard

    d = depths if depths is not None else _ASYM_DEPTHS[0]
    cs = _temporal_group_asym_spec(d)
    expected = asymmetric_group_bytes_per_shard(
        (8, 8, 8), Radius.constant(1), Dim3(*_EXCHANGE_MESH), 4, d)
    return CostModelSpec(fn=cs.fn, args=cs.args,
                         expected_bytes_per_shard=expected)


def _deep_tail_exchange_spec() -> CollectiveSpec:
    """The partial-depth exchange on a deep-carry allocation (the tail
    steps of a blocked loop): wire depth r on s*r pads."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..geometry import Radius
    from ..parallel.exchange import exchange_shard
    from ..parallel.mesh import mesh_dim

    mesh = _mesh(_EXCHANGE_MESH)
    counts = mesh_dim(mesh)
    radius = Radius.constant(1)

    def shard(p):
        return exchange_shard(p, radius, counts,
                              alloc_radius=radius.deepened(2))

    sm = jax.shard_map(shard, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    g = tuple(12 * m for m in _EXCHANGE_MESH)  # 8^3 interiors, pads 2
    return CollectiveSpec(fn=sm, args=(_f32(g),),
                          axis_sizes=dict(mesh.shape),
                          expect_ppermute=True)


def _deep_tail_exchange_cost() -> CostModelSpec:
    from ..geometry import Dim3, Radius

    cs = _deep_tail_exchange_spec()
    # base-radius rows ride on the DEEP allocation's cross-sections
    expected = _sweep_bytes((12, 12, 12), Radius.constant(1),
                            Dim3(*_EXCHANGE_MESH), 4)
    return CostModelSpec(fn=cs.fn, args=cs.args,
                         expected_bytes_per_shard=expected)


def _make_exchange_jit_spec() -> CollectiveSpec:
    from ..geometry import Radius
    from ..parallel.exchange import make_exchange
    from ..parallel.methods import Method

    mesh = _mesh((2, 2, 2))
    radius = Radius.constant(1)
    ex = make_exchange(mesh, radius, Method.PpermutePacked)
    return CollectiveSpec(fn=ex, args=({"q": _f32((20, 20, 20))},),
                          axis_sizes=dict(mesh.shape),
                          expect_ppermute=True)


# ---------------------------------------------------------------------------
# HLO / cost-model targets: every exchange METHOD, audited at the
# StableHLO level (collective-permute-only lowering) and cross-checked
# against the analytic halo byte model. The builders reuse the
# collective specs above and attach the geometry-derived expectation
# from parallel.exchange's byte counters — the same source of truth
# the runtime observability (utils/profiling.exchange_stats_report)
# prints.


def _sweep_bytes(shard_padded_zyx, radius, counts, elem_size) -> int:
    from ..parallel.exchange import exchanged_bytes_per_sweep

    return sum(exchanged_bytes_per_sweep(shard_padded_zyx, radius,
                                         counts, elem_size).values())


def _exchange_cost(radius_kind: str) -> CostModelSpec:
    from ..geometry import Dim3

    cs = _exchange_spec(radius_kind)
    expected = _sweep_bytes(_exchange_shard_shape(),
                            _exchange_radius(radius_kind),
                            Dim3(*_EXCHANGE_MESH), 4)
    return CostModelSpec(fn=cs.fn, args=cs.args,
                         expected_bytes_per_shard=expected)


def _packed_uneven_cost() -> CostModelSpec:
    from ..geometry import Dim3, Radius

    cs = _exchange_packed_uneven_spec()
    r = Radius.constant(1)
    counts = Dim3(2, 2, 2)
    # capacity shard (10,10,10) per field; bf16 packs in its own group
    expected = (_sweep_bytes((10, 10, 10), r, counts, 4)
                + _sweep_bytes((10, 10, 10), r, counts, 2))
    return CostModelSpec(fn=cs.fn, args=cs.args,
                         expected_bytes_per_shard=expected)


def _allgather_cost() -> CostModelSpec:
    from ..geometry import Dim3, Radius

    cs = _exchange_allgather_spec()
    expected = _sweep_bytes((8, 8, 8), Radius.constant(1),
                            Dim3(2, 2, 2), 4)
    return CostModelSpec(fn=cs.fn, args=cs.args,
                         expected_bytes_per_shard=expected)


def _interior_slabs_cost(yzext: bool) -> CostModelSpec:
    from ..geometry import Dim3
    from ..parallel.exchange import interior_slab_bytes

    cs = _interior_slabs_spec(yzext)
    expected = interior_slab_bytes((8, 8, 8), Dim3(1, 2, 2), 3, 4,
                                   y_z_extended=yzext)
    return CostModelSpec(fn=cs.fn, args=cs.args,
                         expected_bytes_per_shard=expected)


def _make_exchange_jit_cost() -> CostModelSpec:
    from ..geometry import Dim3, Radius

    cs = _make_exchange_jit_spec()
    expected = _sweep_bytes((10, 10, 10), Radius.constant(1),
                            Dim3(2, 2, 2), 4)
    return CostModelSpec(fn=cs.fn, args=cs.args,
                         expected_bytes_per_shard=expected)


def _hlo_from_collective(build, allow=("collective_permute",)) -> HloSpec:
    cs = build()
    return HloSpec(fn=cs.fn, args=cs.args, allow=tuple(allow))


# ---------------------------------------------------------------------------
# irredundant wire-layout targets: the packed layout (parallel/
# packing.py) keeps the slab engine's collective bill — 2 ppermutes
# per active radius direction — but each sweep ships only the rows no
# earlier sweep already delivered, so every halo cell crosses the wire
# exactly once. Each registered slab config gets an irredundant twin
# under the same three gates (ppermute bijection, collective-permute-
# only lowering, analytic-vs-HLO byte equality), with the byte
# expectation additionally pinned STRICTLY below the slab bill for
# every config carrying a diagonal (edge/corner) ride-along.
# tests/fixtures/lint/bad_packing.py (a fat slab program sold under
# the irredundant byte model) is the negative control.


def _irr_bytes(shard_padded_zyx, radius, counts, elem_size,
               wire_format=None, alloc_radius=None) -> int:
    from .costmodel import sweep_wire_bytes

    return sum(sweep_wire_bytes(shard_padded_zyx, radius, counts,
                                elem_size, wire_format=wire_format,
                                layout="irredundant",
                                alloc_radius=alloc_radius).values())


def _exchange_irr_spec(radius_kind: str) -> CollectiveSpec:
    import jax
    from jax.sharding import PartitionSpec as P

    from ..parallel.exchange import exchange_shard
    from ..parallel.mesh import mesh_dim

    mesh = _mesh(_EXCHANGE_MESH)
    counts = mesh_dim(mesh)
    radius = _exchange_radius(radius_kind)

    def shard(p):
        return exchange_shard(p, radius, counts,
                              wire_layout="irredundant")

    sm = jax.shard_map(shard, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    return CollectiveSpec(fn=sm, args=(_f32(_EXCHANGE_GLOBAL),),
                          axis_sizes=dict(mesh.shape),
                          expect_ppermute=True)


def _exchange_irr_hlo(radius_kind: str) -> HloSpec:
    cs = _exchange_irr_spec(radius_kind)
    # the layout shrinks messages, never their count: same ppermute
    # bill as the slab engine (one per nonzero radius direction)
    n = {"r1": 6, "r3": 6, "asym": 3}[radius_kind]
    return HloSpec(fn=cs.fn, args=cs.args,
                   allow=("collective_permute",),
                   exact_counts={"collective_permute": n})


def _exchange_irr_cost(radius_kind: str) -> CostModelSpec:
    from ..geometry import Dim3

    cs = _exchange_irr_spec(radius_kind)
    counts = Dim3(*_EXCHANGE_MESH)
    radius = _exchange_radius(radius_kind)
    expected = _irr_bytes(_exchange_shard_shape(), radius, counts, 4)
    # the layout's contract, pinned: strictly below the slab bill
    # (every registered config has a diagonal carry to shed)
    assert expected < _sweep_bytes(_exchange_shard_shape(), radius,
                                   counts, 4)
    return CostModelSpec(fn=cs.fn, args=cs.args,
                         expected_bytes_per_shard=expected)


def _exchange_packed_irr_uneven_spec() -> CollectiveSpec:
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..geometry import Dim3, Radius
    from ..parallel.exchange import exchange_shard_packed
    from ..parallel.mesh import mesh_dim

    mesh = _mesh((2, 2, 2))
    counts = mesh_dim(mesh)
    radius = Radius.constant(1)
    rem = Dim3(1, 1, 1)

    def shard(fields):
        return exchange_shard_packed(fields, radius, counts, rem=rem,
                                     wire_layout="irredundant")

    spec = {"a": P("z", "y", "x"), "b": P("z", "y", "x")}
    sm = jax.shard_map(shard, mesh=mesh, in_specs=(spec,),
                       out_specs=spec, check_vma=False)
    fields = {"a": _f32((20, 20, 20)),
              "b": jax.ShapeDtypeStruct((20, 20, 20), jnp.bfloat16)}
    return CollectiveSpec(fn=sm, args=(fields,),
                          axis_sizes=dict(mesh.shape),
                          expect_ppermute=True)


def _packed_irr_uneven_cost() -> CostModelSpec:
    from ..geometry import Dim3, Radius

    cs = _exchange_packed_irr_uneven_spec()
    r = Radius.constant(1)
    counts = Dim3(2, 2, 2)
    # capacity shard (10,10,10); static irredundant boxes — a short
    # shard's overhang rows are dead slack or halo rows a later sweep
    # rewrites, so uneven remainders change nothing on the wire
    expected = (_irr_bytes((10, 10, 10), r, counts, 4)
                + _irr_bytes((10, 10, 10), r, counts, 2))
    assert expected < (_sweep_bytes((10, 10, 10), r, counts, 4)
                       + _sweep_bytes((10, 10, 10), r, counts, 2))
    return CostModelSpec(fn=cs.fn, args=cs.args,
                         expected_bytes_per_shard=expected)


def _temporal_irr_spec(s: int = 2) -> CollectiveSpec:
    """The temporal-blocking fused group on irredundant wire boxes —
    where the layout's win is largest: the deep slab's diagonal carry
    grows with s^2 while the irredundant boxes grow only with s."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..geometry import Radius
    from ..ops.stencil_kernels import jacobi7
    from ..parallel.mesh import mesh_dim
    from ..parallel.methods import Method
    from ..parallel.temporal import temporal_shard_steps

    mesh = _mesh(_EXCHANGE_MESH)
    counts = mesh_dim(mesh)
    radius = Radius.constant(1)

    def upd(blocks, dims, off, k):
        return {"q": jacobi7(blocks["q"], radius, dims)}

    def shard(p):
        return temporal_shard_steps({"q": p}, radius, counts,
                                    Method.PpermuteSlab, upd, s,
                                    wire_layout="irredundant")["q"]

    sm = jax.shard_map(shard, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    side = (8 + 2 * s)
    g = tuple(side * m for m in _EXCHANGE_MESH)
    return CollectiveSpec(fn=sm, args=(_f32(g),),
                          axis_sizes=dict(mesh.shape),
                          expect_ppermute=True)


def _temporal_irr_cost(s: int = 2) -> CostModelSpec:
    from ..geometry import Dim3, Radius
    from .costmodel import deep_exchange_bytes_per_shard

    cs = _temporal_irr_spec(s)
    expected = deep_exchange_bytes_per_shard(
        (8, 8, 8), Radius.constant(1), Dim3(*_EXCHANGE_MESH), 4, s,
        wire_layout="irredundant")
    assert expected < deep_exchange_bytes_per_shard(
        (8, 8, 8), Radius.constant(1), Dim3(*_EXCHANGE_MESH), 4, s)
    return CostModelSpec(fn=cs.fn, args=cs.args,
                         expected_bytes_per_shard=expected)


def _deep_tail_irr_spec() -> CollectiveSpec:
    """The partial-depth tail exchange, irredundant: wire-radius boxes
    on the DEEP allocation — extension spans sized by the wire radius,
    so the tail sheds the deep slab's fat cross-sections entirely."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..geometry import Radius
    from ..parallel.exchange import exchange_shard
    from ..parallel.mesh import mesh_dim

    mesh = _mesh(_EXCHANGE_MESH)
    counts = mesh_dim(mesh)
    radius = Radius.constant(1)

    def shard(p):
        return exchange_shard(p, radius, counts,
                              alloc_radius=radius.deepened(2),
                              wire_layout="irredundant")

    sm = jax.shard_map(shard, mesh=mesh, in_specs=P("z", "y", "x"),
                       out_specs=P("z", "y", "x"), check_vma=False)
    g = tuple(12 * m for m in _EXCHANGE_MESH)
    return CollectiveSpec(fn=sm, args=(_f32(g),),
                          axis_sizes=dict(mesh.shape),
                          expect_ppermute=True)


def _deep_tail_irr_cost() -> CostModelSpec:
    from ..geometry import Dim3, Radius

    cs = _deep_tail_irr_spec()
    r = Radius.constant(1)
    expected = _irr_bytes((12, 12, 12), r, Dim3(*_EXCHANGE_MESH), 4,
                          alloc_radius=r.deepened(2))
    assert expected < _sweep_bytes((12, 12, 12), r,
                                   Dim3(*_EXCHANGE_MESH), 4)
    return CostModelSpec(fn=cs.fn, args=cs.args,
                         expected_bytes_per_shard=expected)


def _rdma_hlo_spec() -> HloSpec:
    """The PallasDMA exchange method: off-TPU the checker records a
    capability-gate skip (pallas_call cannot lower there); on a TPU
    backend it proves the kernel adds no XLA-level collectives around
    its explicit RDMA."""
    cs = _rdma_exchange_spec()
    return HloSpec(fn=cs.fn, args=cs.args, allow=(),
                   expect_collective=False)


# ---------------------------------------------------------------------------
# tuning-plan targets: every exchange configuration the autotuner
# (stencil_tpu/tuning) can EMIT — Method x exchange_every over the
# plan's depth set — built exactly the way plan application deploys
# them (make_exchange on the deepened radius). Whatever plan the tuner
# picks, its data path is already under the HLO ppermute-only gate and
# the analytic byte cross-check; a tuned win can never smuggle in an
# unaudited lowering. (PallasDMA plans exist only where the RDMA
# engine is runnable; its path is audited by the
# parallel.pallas_exchange targets above, aliased below so the
# coverage manifest's Auto entry maps to live targets.)

_PLAN_INTERIOR = 8


def _plan_depths():
    from ..tuning.plan import DEFAULT_DEPTHS

    return DEFAULT_DEPTHS


def _plan_exchange_spec(method_name: str, s: int,
                        layout: str = "slab") -> CollectiveSpec:
    from ..geometry import Radius
    from ..parallel.exchange import make_exchange
    from ..parallel.methods import Method

    mesh = _mesh(_EXCHANGE_MESH)
    deep = Radius.constant(1).deepened(s)
    ex = make_exchange(mesh, deep, Method[method_name],
                       wire_layout=layout)
    side = _PLAN_INTERIOR + 2 * s
    g = tuple(side * m for m in _EXCHANGE_MESH)
    return CollectiveSpec(fn=ex, args=({"q": _f32(g)},),
                          axis_sizes=dict(mesh.shape),
                          expect_ppermute=(method_name != "AllGather"))


def _plan_exchange_hlo(method_name: str, s: int,
                       layout: str = "slab") -> HloSpec:
    allow = (("all_gather",) if method_name == "AllGather"
             else ("collective_permute",))
    return _hlo_from_collective(
        lambda: _plan_exchange_spec(method_name, s, layout),
        allow=allow)


def _plan_exchange_cost(method_name: str, s: int,
                        layout: str = "slab") -> CostModelSpec:
    from ..geometry import Dim3, Radius
    from .costmodel import sweep_wire_bytes

    cs = _plan_exchange_spec(method_name, s, layout)
    side = _PLAN_INTERIOR + 2 * s
    expected = sum(sweep_wire_bytes(
        (side, side, side), Radius.constant(1).deepened(s),
        Dim3(*_EXCHANGE_MESH), 4, layout=layout).values())
    return CostModelSpec(fn=cs.fn, args=cs.args,
                         expected_bytes_per_shard=expected)


def _plan_targets() -> List[Target]:
    targets: List[Target] = []
    emittable = ([("PpermuteSlab", s) for s in _plan_depths()]
                 + [("PpermutePacked", s) for s in _plan_depths()]
                 + [("AllGather", 1)])
    for method, s in emittable:
        targets.append(HloTarget(
            f"tuning.plan[{method},s={s},hlo]",
            lambda m=method, d=s: _plan_exchange_hlo(m, d)))
        targets.append(CostModelTarget(
            f"tuning.plan[{method},s={s},cost]",
            lambda m=method, d=s: _plan_exchange_cost(m, d)))
    # the tuner's wire-layout axis (candidate keys
    # ``...,layout=irredundant``): one audited irredundant plan per
    # ppermute method, at a representative depth each
    for method, s in (("PpermuteSlab", 2), ("PpermutePacked", 4)):
        targets.append(HloTarget(
            f"tuning.plan[{method},s={s},layout=irredundant,hlo]",
            lambda m=method, d=s: _plan_exchange_hlo(
                m, d, "irredundant")))
        targets.append(CostModelTarget(
            f"tuning.plan[{method},s={s},layout=irredundant,cost]",
            lambda m=method, d=s: _plan_exchange_cost(
                m, d, "irredundant")))
    # the RDMA plan path (emittable on TPU only) — same audited spec
    # as parallel.pallas_exchange.exchange_shard_pallas[hlo]
    targets.append(HloTarget("tuning.plan[PallasDMA,s=1,hlo]",
                             _rdma_hlo_spec))
    return targets


# ---------------------------------------------------------------------------
# ensemble-serving targets: the batched member axis must be a free
# ride on the wire — the vmapped exchange lowers to the SAME
# collective-permutes as one member, each carrying the batch, so wire
# bytes are EXACTLY n_members x the single-member analytic model, and
# the batched production step smuggles in no extra collectives.

_ENSEMBLE_N = 4


def _ensemble_exchange_spec() -> CollectiveSpec:
    import jax
    from jax.sharding import PartitionSpec as P

    from ..parallel.exchange import exchange_shard
    from ..parallel.mesh import mesh_dim

    mesh = _mesh(_EXCHANGE_MESH)
    counts = mesh_dim(mesh)
    radius = _exchange_radius("r1")

    def shard(batched):
        return jax.vmap(
            lambda p: exchange_shard(p, radius, counts))(batched)

    spec = P(None, "z", "y", "x")
    sm = jax.shard_map(shard, mesh=mesh, in_specs=spec, out_specs=spec,
                       check_vma=False)
    return CollectiveSpec(fn=sm,
                          args=(_f32((_ENSEMBLE_N,) + _EXCHANGE_GLOBAL),),
                          axis_sizes=dict(mesh.shape),
                          expect_ppermute=True)


def _ensemble_exchange_cost() -> CostModelSpec:
    from ..geometry import Dim3

    cs = _ensemble_exchange_spec()
    # bytes scale EXACTLY xN over the single-member sweep model — the
    # serving contract: batching multiplies payload, never rounds
    expected = _ENSEMBLE_N * _sweep_bytes(_exchange_shard_shape(),
                                          _exchange_radius("r1"),
                                          Dim3(*_EXCHANGE_MESH), 4)
    return CostModelSpec(fn=cs.fn, args=cs.args,
                         expected_bytes_per_shard=expected)


def _ensemble_step_spec() -> HloSpec:
    """The production batched Jacobi step (serving/ensemble.py): the
    same 6 collective-permutes as the single-member step — pinned
    exactly, so a vmap batching regression that unrolled the member
    axis into per-member collectives fails the gate."""
    from ..serving.ensemble import EnsembleJacobi

    eng = EnsembleJacobi(_ENSEMBLE_N, 24, 24, 24,
                         mesh_shape=_EXCHANGE_MESH)
    hot, cold = eng._param_args()
    import jax.numpy as jnp
    args = (eng.state["temp"], hot, cold, jnp.asarray(1, jnp.int32))
    return HloSpec(fn=eng._step_n, args=args,
                   allow=("collective_permute",),
                   exact_counts={"collective_permute": 6})


def _ensemble_probe_spec() -> HloSpec:
    """The per-member health probe: (N, 2, nq) stats via still exactly
    ONE small all-reduce (the vmapped pmax batches, it does not
    multiply)."""
    from ..serving.ensemble import make_ensemble_probe

    mesh = _mesh((2, 2, 2))
    fn = make_ensemble_probe(mesh, ["a", "b"])
    fields = {"a": _f32((_ENSEMBLE_N, 16, 16, 16)),
              "b": _f32((_ENSEMBLE_N, 16, 16, 16))}
    return HloSpec(fn=fn, args=(fields,), allow=("all_reduce",),
                   exact_counts={"all_reduce": 1})


# ---------------------------------------------------------------------------
# resilience targets: the health sentinel's in-graph probe. The probe
# rides the production step loop, so its communication contract is the
# whole point: exactly ONE small all-reduce (the stacked-stats pmax)
# and nothing else — a sentinel that smuggled extra collectives into
# every check_every-th step would tax the fabric it is guarding.


def _health_probe_spec() -> HloSpec:
    import jax
    from jax.sharding import PartitionSpec as P

    from ..resilience.health import probe_shard

    mesh = _mesh((2, 2, 2))
    spec = P("z", "y", "x")

    def shard(a, b):
        return probe_shard({"a": a, "b": b})

    sm = jax.shard_map(shard, mesh=mesh, in_specs=(spec, spec),
                       out_specs=P(), check_vma=False)
    return HloSpec(fn=sm, args=(_f32((16, 16, 16)), _f32((16, 16, 16))),
                   allow=("all_reduce",),
                   exact_counts={"all_reduce": 1})


def _health_step_probe_spec() -> HloSpec:
    """The probe fused INTO the production jacobi step: the step's own
    collective-permutes plus exactly one all-reduce — the lowering the
    resilient run loop actually dispatches on probe steps."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..geometry import Dim3, Radius
    from ..models.jacobi import jacobi_shard_step
    from ..parallel.exchange import shard_origin
    from ..parallel.mesh import mesh_dim
    from ..parallel.methods import Method
    from ..resilience.health import probe_shard

    mesh = _mesh(_EXCHANGE_MESH)
    counts = mesh_dim(mesh)
    radius = Radius.constant(1)
    local = Dim3(12, 12, 12)
    gsize = Dim3(24, 24, 24)

    def shard(p):
        origin = shard_origin(local, Dim3(0, 0, 0))
        stepped = jacobi_shard_step(p, radius, counts, local, gsize,
                                    origin, Method.PpermuteSlab)
        return stepped, probe_shard({"temp": stepped})

    spec = P("z", "y", "x")
    sm = jax.shard_map(shard, mesh=mesh, in_specs=spec,
                       out_specs=(spec, P()), check_vma=False)
    # 6 ppermutes = the slab exchange's own 2-per-axis contract; a
    # probe-fusion regression that re-triggers the exchange would
    # double them and must fail the gate, not just the all_reduce pin
    return HloSpec(fn=sm, args=(_f32(_EXCHANGE_GLOBAL),),
                   allow=("collective_permute", "all_reduce"),
                   exact_counts={"all_reduce": 1,
                                 "collective_permute": 6})


# ---------------------------------------------------------------------------
# telemetry targets: the in-graph step-metrics instrumentation
# (stencil_tpu/telemetry/probe.py). Its license to ride the production
# loop is the acceptance contract verbatim: metric columns piggyback
# on the health probe's ONE existing all-reduce, so the instrumented
# production step lowers to the SAME collectives as the bare step —
# 6 collective-permutes + exactly 1 all-reduce, with the exchange's
# byte cross-check still exact (telemetry adds zero wire bytes).


def _telemetry_probe_spec() -> HloSpec:
    """The metrics-carrying probe alone: still exactly ONE small
    all-reduce — the extra columns ride the stacked-stats pmax."""
    import jax
    import jax.numpy as jnp

    from ..resilience.health import make_probe
    from ..telemetry.probe import STEP_METRIC_NAMES

    mesh = _mesh((2, 2, 2))
    fn = make_probe(mesh, ["a", "b"], extra_names=STEP_METRIC_NAMES)
    fields = {"a": _f32((16, 16, 16)), "b": _f32((16, 16, 16))}
    vec = jax.ShapeDtypeStruct((len(STEP_METRIC_NAMES),), jnp.float32)
    return HloSpec(fn=fn, args=(fields, vec), allow=("all_reduce",),
                   exact_counts={"all_reduce": 1})


def _telemetry_step_probe_fn():
    """The INSTRUMENTED production jacobi step: step + metrics-carrying
    probe fused, exactly as the resilient run loop dispatches it on
    probe steps when telemetry is on. Shared by the hlo gate and the
    byte cross-check so the two audit one program."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..geometry import Dim3
    from ..models.jacobi import jacobi_shard_step
    from ..parallel.exchange import shard_origin
    from ..parallel.mesh import mesh_dim
    from ..parallel.methods import Method
    from ..resilience.health import probe_shard
    from ..telemetry.probe import STEP_METRIC_NAMES

    mesh = _mesh(_EXCHANGE_MESH)
    counts = mesh_dim(mesh)
    radius = _exchange_radius("r1")
    local = Dim3(12, 12, 12)
    gsize = Dim3(24, 24, 24)

    def shard(p, vec):
        origin = shard_origin(local, Dim3(0, 0, 0))
        stepped = jacobi_shard_step(p, radius, counts, local, gsize,
                                    origin, Method.PpermuteSlab)
        extra = {m: vec[i] for i, m in enumerate(STEP_METRIC_NAMES)}
        return stepped, probe_shard({"temp": stepped}, extra=extra)

    spec = P("z", "y", "x")
    sm = jax.shard_map(shard, mesh=mesh, in_specs=(spec, P()),
                       out_specs=(spec, P()), check_vma=False)
    import jax.numpy as jnp
    vec = jax.ShapeDtypeStruct((len(STEP_METRIC_NAMES),), jnp.float32)
    return sm, (_f32(_EXCHANGE_GLOBAL), vec)


def _telemetry_step_probe_spec() -> HloSpec:
    fn, args = _telemetry_step_probe_fn()
    # identical pins to resilience.health.step+probe[hlo]: telemetry
    # must not change the production step's collective bill at all
    return HloSpec(fn=fn, args=args,
                   allow=("collective_permute", "all_reduce"),
                   exact_counts={"all_reduce": 1,
                                 "collective_permute": 6})


def _telemetry_step_probe_cost() -> CostModelSpec:
    """Zero extra wire bytes: the instrumented step's exchange still
    moves exactly the analytic halo bytes (the all-reduce is outside
    ``count_kinds`` by the package's byte convention — its count is
    pinned by the hlo target above)."""
    from ..geometry import Dim3

    fn, args = _telemetry_step_probe_fn()
    expected = _sweep_bytes(_exchange_shard_shape(),
                            _exchange_radius("r1"),
                            Dim3(*_EXCHANGE_MESH), 4)
    return CostModelSpec(fn=fn, args=args,
                         expected_bytes_per_shard=expected,
                         count_kinds=("collective_permute",))


# ---------------------------------------------------------------------------
# megastep targets: the whole-campaign fused segment
# (parallel/megastep.py). A check_every=k segment must compile to ONE
# program whose collective bill is exactly k x the per-step
# collective_permute count plus ONE small all-reduce per probe row and
# NOTHING else, with the exchange bytes exactly k x the per-step
# analytic model — the fusion can neither smuggle in hidden
# communication nor re-reduce the probe per sub-step
# (tests/fixtures/lint/bad_megastep.py is that negative control).

_MEGASTEP_K = 4
_MEGASTEP_PROBE_EVERY = 2


def _megastep_segment_fn(probe_every: int = _MEGASTEP_PROBE_EVERY):
    """The production fused segment over the jacobi shard step: k
    steps + the metric-carrying probe every ``probe_every`` sub-steps,
    built with the same ``fused_segment_shard`` machinery the model
    and driver deploy. Shared by the hlo gate and the byte cross-check
    so both audit one program."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..geometry import Dim3
    from ..models.jacobi import jacobi_shard_step
    from ..parallel.exchange import shard_origin
    from ..parallel.megastep import (fused_segment_shard, health_probe,
                                     segment_chunks)
    from ..parallel.mesh import mesh_dim
    from ..parallel.methods import Method
    from ..telemetry.probe import STEP_METRIC_NAMES

    mesh = _mesh(_EXCHANGE_MESH)
    counts = mesh_dim(mesh)
    radius = _exchange_radius("r1")
    local = Dim3(12, 12, 12)
    gsize = Dim3(24, 24, 24)

    def shard(p, vec):
        origin = shard_origin(local, Dim3(0, 0, 0))

        def advance(q, c, i):
            return jacobi_shard_step(q, radius, counts, local, gsize,
                                     origin, Method.PpermuteSlab)

        probe = health_probe(lambda q: {"temp": q}, base_vec=vec,
                             metric_names=STEP_METRIC_NAMES,
                             bytes_per_step=1.0)
        return fused_segment_shard(p, advance, probe,
                                   segment_chunks(_MEGASTEP_K),
                                   probe_every)

    spec = P("z", "y", "x")
    sm = jax.shard_map(shard, mesh=mesh, in_specs=(spec, P()),
                       out_specs=(spec, P()), check_vma=False)
    vec = jax.ShapeDtypeStruct((2,), jnp.float32)
    return sm, (_f32(_EXCHANGE_GLOBAL), vec)


def _megastep_segment_hlo() -> HloSpec:
    fn, args = _megastep_segment_fn()
    n_probes = -(-_MEGASTEP_K // _MEGASTEP_PROBE_EVERY)
    # k x the per-step slab sweep's 6 collective-permutes + exactly one
    # all-reduce per probe row — the whole fused bill, nothing hidden
    return HloSpec(fn=fn, args=args,
                   allow=("collective_permute", "all_reduce"),
                   exact_counts={"collective_permute": 6 * _MEGASTEP_K,
                                 "all_reduce": n_probes})


def _megastep_segment_cost() -> CostModelSpec:
    """Exact-byte cross-check: the fused segment's exchanges move
    exactly k x the per-step analytic halo bytes (probe all-reduces
    are outside ``count_kinds``; their count is pinned above)."""
    from ..geometry import Dim3

    fn, args = _megastep_segment_fn()
    expected = _MEGASTEP_K * _sweep_bytes(_exchange_shard_shape(),
                                          _exchange_radius("r1"),
                                          Dim3(*_EXCHANGE_MESH), 4)
    return CostModelSpec(fn=fn, args=args,
                         expected_bytes_per_shard=expected,
                         count_kinds=("collective_permute",))


# ---------------------------------------------------------------------------
# performance-observatory attribution targets: model-vs-measured
# attribution (observatory/attribution.py) is a HOST wall clock around
# the dispatch — the dispatched program must be byte-identical to the
# uninstrumented one. These targets lower exactly what
# PerfAttributor.attributed() hands the dispatcher and pin it to the
# SAME exact collective counts, the SAME analytic byte bill, and the
# SAME dispatch-stable compile fingerprint as the bare megastep/PIC
# entries above — attribution adds zero collectives, zero wire bytes,
# zero retraces. tests/fixtures/lint/bad_attribution.py (a timer that
# sneaks a host callback into the step) is the negative control.


def _attributed(spec):
    """The bare spec with its fn routed through
    ``PerfAttributor.attributed`` — everything else (exact counts,
    byte expectations, allowed vocabulary) stays the BARE target's by
    construction, so the two registrations cannot drift apart: any
    future attribution scheme that edits the program fails the bare
    target's own pins under the attribution name."""
    import dataclasses

    from ..observatory.attribution import PerfAttributor

    return dataclasses.replace(spec,
                               fn=PerfAttributor.attributed(spec.fn))


def _attribution_segment_hlo() -> HloSpec:
    return _attributed(_megastep_segment_hlo())


def _attribution_segment_cost() -> CostModelSpec:
    return _attributed(_megastep_segment_cost())


def _attributed_segment_entry():
    from ..observatory.attribution import PerfAttributor

    fn, args = _megastep_segment_entry()
    return PerfAttributor.attributed(fn), args


def _attributed_pic_entry():
    from ..observatory.attribution import PerfAttributor

    fn, args = _pic_step_entry()
    return PerfAttributor.attributed(fn), args


def _attribution_pic_hlo() -> HloSpec:
    return _attributed(_pic_step_hlo())


# ---------------------------------------------------------------------------
# link-observatory targets: the modeled per-(src, dst) traffic matrix
# (observatory/linkmap.py) must sum EXACTLY to the HLO-extracted wire
# bytes for every registered exchange method — slab/packed at every
# plan depth, the all-gather control, particle migration, and the full
# fused PIC step (whose bill includes the halo-accumulate adjoint).
# Each builder pairs a collective spec already under the hlo/cost
# gates with the linkmap twin of its byte expectation, so the matrix
# the placement QAP consumes and the wire bill the HLO proves are one
# object. tests/fixtures/lint/bad_linkmap.py (a matrix that drops the
# corner bytes riding the fat axis slabs — the classic 6-neighbor-only
# bug) is the negative control.


def _linkmap_exchange_spec(radius_kind: str) -> LinkmapSpec:
    from ..geometry import Dim3
    from ..observatory.linkmap import sweep_traffic

    cs = _exchange_spec(radius_kind)
    traffic = sweep_traffic(_exchange_shard_shape(),
                            _exchange_radius(radius_kind),
                            Dim3(*_EXCHANGE_MESH), (4,))
    return LinkmapSpec(fn=cs.fn, args=cs.args, traffic=traffic)


def _linkmap_exchange_irr_spec(radius_kind: str) -> LinkmapSpec:
    from ..geometry import Dim3
    from ..observatory.linkmap import sweep_traffic

    cs = _exchange_irr_spec(radius_kind)
    traffic = sweep_traffic(_exchange_shard_shape(),
                            _exchange_radius(radius_kind),
                            Dim3(*_EXCHANGE_MESH), (4,),
                            layout="irredundant")
    return LinkmapSpec(fn=cs.fn, args=cs.args, traffic=traffic)


def _linkmap_packed_uneven_spec() -> LinkmapSpec:
    from ..geometry import Dim3, Radius
    from ..observatory.linkmap import sweep_traffic

    cs = _exchange_packed_uneven_spec()
    # capacity shard (10,10,10); f32 + bf16 pack in separate groups —
    # launches differ, payload does not (same convention as the cost
    # target)
    traffic = sweep_traffic((10, 10, 10), Radius.constant(1),
                            Dim3(2, 2, 2), (4, 2))
    return LinkmapSpec(fn=cs.fn, args=cs.args, traffic=traffic)


def _linkmap_plan_spec(method_name: str, s: int) -> LinkmapSpec:
    from ..geometry import Dim3, Radius
    from ..observatory.linkmap import method_traffic

    cs = _plan_exchange_spec(method_name, s)
    traffic = method_traffic(
        method_name, (_PLAN_INTERIOR,) * 3, Radius.constant(1),
        Dim3(*_EXCHANGE_MESH), (4,), steps=s)
    return LinkmapSpec(fn=cs.fn, args=cs.args, traffic=traffic)


def _linkmap_temporal_asym_spec(depths=None) -> LinkmapSpec:
    """The asymmetric temporal group's traffic matrix: the group
    matrix carries axis ``a``'s deep slab ``max(s) / s_a`` times (the
    mid-group refreshes), and its per-shard row sum must equal the
    group program's HLO wire bytes exactly."""
    from ..geometry import Dim3, Radius, normalize_depths
    from ..observatory.linkmap import method_traffic

    d = depths if depths is not None else _ASYM_DEPTHS[0]
    cs = _temporal_group_asym_spec(d)
    traffic = method_traffic("PpermuteSlab", (8, 8, 8),
                             Radius.constant(1), Dim3(*_EXCHANGE_MESH),
                             (4,), steps=normalize_depths(d))
    return LinkmapSpec(fn=cs.fn, args=cs.args, traffic=traffic)


@functools.lru_cache(maxsize=None)
def _hier_dcn_domain():
    """The hierarchical partition planner's actual deployment on a
    DCN-blocked fake mesh: 2 fake slices of 4 devices each, mesh shape
    and slice axis chosen by ``_plan_dcn_partition`` (per-link priced),
    placement by the ``auto`` default."""
    import jax
    import numpy as np

    from ..distributed import DistributedDomain

    devs = jax.devices()[:8]
    dd = DistributedDomain(32, 16, 16, devices=devs)
    dd.set_radius(1)
    dd.add_data("q", np.float32)
    dd.set_dcn_axis(groups=[devs[:4], devs[4:]])
    dd.realize()
    return dd


def _linkmap_hier_dcn_spec() -> LinkmapSpec:
    """The hierarchical partition's per-link byte split, HLO-exact on
    the DCN-blocked mesh — plus the deployed placement payload: the
    assignment realize() shipped must cost no more than trivial device
    order under the NodeAware objective on the two-tier fabric."""
    from ..observatory.linkmap import sweep_traffic
    from ..parallel.mesh import mesh_dim

    dd = _hier_dcn_domain()
    local = dd.local_size
    lo, hi = dd.alloc_radius.pad_lo(), dd.alloc_radius.pad_hi()
    counts = mesh_dim(dd.mesh)
    traffic = sweep_traffic((local.z + lo.z + hi.z,
                             local.y + lo.y + hi.y,
                             local.x + lo.x + hi.x), dd.radius,
                            counts, (4,), alloc_radius=dd.alloc_radius)
    placement = {
        "counts": tuple(counts),
        "grid": tuple(dd.size),
        "assignment": list(dd.placement.assignment),
        "radius": dd.radius,
        "dcn_axis": dd.dcn_axis,
        "n_slices": dd.n_slices,
    }
    return LinkmapSpec(fn=dd._exchange_fn, args=(dict(dd.curr),),
                       traffic=traffic, placement=placement)


def _linkmap_allgather_spec() -> LinkmapSpec:
    from ..geometry import Dim3, Radius
    from ..observatory.linkmap import allgather_traffic

    cs = _exchange_allgather_spec()
    traffic = allgather_traffic((8, 8, 8), Radius.constant(1),
                                Dim3(2, 2, 2), (4,))
    return LinkmapSpec(fn=cs.fn, args=cs.args, traffic=traffic)


def _linkmap_migrate_spec() -> LinkmapSpec:
    from ..geometry import Dim3
    from ..observatory.linkmap import migration_traffic

    cs = _migrate_spec()
    traffic = migration_traffic(Dim3(*_MIGRATE_MESH),
                                len(_MIGRATE_FIELDS), _MIGRATE_BUDGET,
                                4)
    return LinkmapSpec(fn=cs.fn, args=cs.args, traffic=traffic,
                       count_kinds=("collective_permute",))


def _linkmap_pic_spec() -> LinkmapSpec:
    from ..geometry import Dim3, Radius
    from ..models.pic import PARTICLE_FIELDS, RADIUS
    from ..observatory.linkmap import pic_traffic

    eng = _pic_engine()
    fn, args = _pic_step_entry()
    local = eng.dd.local_size
    traffic = pic_traffic((local.z, local.y, local.x),
                          Radius.constant(RADIUS),
                          Dim3(*_EXCHANGE_MESH), 4,
                          len(PARTICLE_FIELDS), _PIC_BUDGET)
    return LinkmapSpec(fn=fn, args=args, traffic=traffic,
                       count_kinds=("collective_permute",))


# ---------------------------------------------------------------------------
# particle-migration / PIC targets: the DYNAMIC communication pattern.
# The fixed-capacity migration ring must lower to collective-permute
# only with its static budget x record-rows wire bill matching the
# analytic model EXACTLY (payload occupancy is runtime-dynamic; wire
# bytes are not — that is the whole design), and the full fused PIC
# step (deposit + reverse accumulate + exchange + gather + push +
# migrate) must bill exactly 2 ppermutes per active axis per engine
# and nothing else. tests/fixtures/lint/bad_migration.py (a migration
# that all-gathers every shard's outbox) is the negative control.

_MIGRATE_MESH = (2, 2, 2)
_MIGRATE_FIELDS = ("q", "x", "y")
_MIGRATE_CAPACITY = 16
_MIGRATE_BUDGET = 4

_PIC_N = 64
_PIC_CAPACITY = 32
_PIC_BUDGET = 8


def _migrate_spec() -> CollectiveSpec:
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import mesh_dim
    from ..parallel.migrate import migrate_shard

    mesh = _mesh(_MIGRATE_MESH)
    counts = mesh_dim(mesh)
    cap = _MIGRATE_CAPACITY

    def shard(fields, valid, ox, oy, oz):
        f, v, ovf = migrate_shard(fields, valid, (ox, oy, oz), counts,
                                  _MIGRATE_BUDGET)
        return f, v, ovf.reshape(1)

    spec = P(("z", "y", "x"))
    fspec = {q: spec for q in _MIGRATE_FIELDS}
    sm = jax.shard_map(shard, mesh=mesh,
                       in_specs=(fspec, spec, spec, spec, spec),
                       out_specs=(fspec, spec, spec), check_vma=False)
    n = 8 * cap
    fields = {q: _f32((n,)) for q in _MIGRATE_FIELDS}
    valid = jax.ShapeDtypeStruct((n,), jnp.bool_)
    off = jax.ShapeDtypeStruct((n,), jnp.int32)
    return CollectiveSpec(fn=sm, args=(fields, valid, off, off, off),
                          axis_sizes=dict(mesh.shape),
                          expect_ppermute=True)


def _migrate_hlo() -> HloSpec:
    cs = _migrate_spec()
    # 2 directions x 3 active axes, one packed record buffer each —
    # the dynamic exchange's whole collective bill
    return HloSpec(fn=cs.fn, args=cs.args, allow=("collective_permute",),
                   exact_counts={"collective_permute": 6})


def _migrate_cost() -> CostModelSpec:
    from ..geometry import Dim3
    from .costmodel import migration_wire_bytes_per_shard

    cs = _migrate_spec()
    expected = migration_wire_bytes_per_shard(
        len(_MIGRATE_FIELDS), _MIGRATE_BUDGET, Dim3(*_MIGRATE_MESH), 4)
    return CostModelSpec(fn=cs.fn, args=cs.args,
                         expected_bytes_per_shard=expected,
                         count_kinds=("collective_permute",))


@functools.lru_cache(maxsize=None)
def _pic_engine():
    import numpy as np

    from ..models.pic import Pic

    return Pic(16, 16, 16, _PIC_N, mesh_shape=_EXCHANGE_MESH,
               dtype=np.float32, capacity=_PIC_CAPACITY,
               budget=_PIC_BUDGET)


@functools.lru_cache(maxsize=None)
def _pic_step_entry():
    eng = _pic_engine()
    return eng._step, (dict(eng.state),)


def _pic_step_bytes() -> int:
    """The fused PIC step's exact wire bill: reverse accumulate +
    forward exchange (each one radius-2 sweep on the padded shard) +
    the migration ring."""
    from ..geometry import Dim3, Radius
    from ..models.pic import PARTICLE_FIELDS, RADIUS
    from .costmodel import migration_wire_bytes_per_shard

    eng = _pic_engine()
    local = eng.dd.local_size
    pad = 2 * RADIUS
    padded = (local.z + pad, local.y + pad, local.x + pad)
    sweep = _sweep_bytes(padded, Radius.constant(RADIUS),
                         Dim3(*_EXCHANGE_MESH), 4)
    return 2 * sweep + migration_wire_bytes_per_shard(
        len(PARTICLE_FIELDS), _PIC_BUDGET, Dim3(*_EXCHANGE_MESH), 4)


def _pic_step_hlo() -> HloSpec:
    fn, args = _pic_step_entry()
    # 6 ppermutes each for accumulate, exchange, and migration — the
    # dynamic pattern pays the same ring discipline as the static one
    return HloSpec(fn=fn, args=args, allow=("collective_permute",),
                   exact_counts={"collective_permute": 18})


def _pic_step_cost() -> CostModelSpec:
    fn, args = _pic_step_entry()
    return CostModelSpec(fn=fn, args=args,
                         expected_bytes_per_shard=_pic_step_bytes(),
                         count_kinds=("collective_permute",))


def _pic_probe_hlo() -> HloSpec:
    """The PIC sentinel probe: rho + every particle SoA lane + the
    IN-GRAPH migration-overflow column, still exactly ONE small
    all-reduce — the overflow counter rides the existing reduction."""
    eng = _pic_engine()
    return HloSpec(fn=eng._probe_fn, args=(dict(eng.state),),
                   allow=("all_reduce",),
                   exact_counts={"all_reduce": 1})


# ---------------------------------------------------------------------------
# PIC megastep targets: the segment compiler's carry-contract proof.
# A check_every=k fused PIC segment must lower to exactly k x the
# step's 18 collective-permutes plus ONE probe all-reduce per declared
# trace row and NOTHING else, with the exchange+migration bytes
# exactly k x the per-step analytic model AND the probe rows carrying
# the full contract column set (rho + 7 particle lanes + the overflow
# column — tests/fixtures/lint/bad_segment_carry.py, a contract that
# drops the overflow column, is the negative control).

_PIC_SEG_ROWS = -(-_MEGASTEP_K // _MEGASTEP_PROBE_EVERY)
#: probe-vector columns of the shipped PIC carry contract: rho + the
#: 7 particle SoA lanes + the migration-overflow extra column
_PIC_SEG_COLS = 9


@functools.lru_cache(maxsize=None)
def _pic_segment_entry():
    from ..parallel.megastep import metric_base_vec

    eng = _pic_engine()
    seg = eng.make_segment(_MEGASTEP_K,
                           probe_every=_MEGASTEP_PROBE_EVERY)
    return seg.fn, (dict(eng.state),
                    metric_base_vec(None, 0, mesh=eng.dd.mesh))


def _pic_segment_hlo() -> HloSpec:
    fn, args = _pic_segment_entry()
    return HloSpec(fn=fn, args=args,
                   allow=("collective_permute", "all_reduce"),
                   exact_counts={
                       "collective_permute": 18 * _MEGASTEP_K,
                       "all_reduce": _PIC_SEG_ROWS})


def _pic_segment_cost() -> CostModelSpec:
    fn, args = _pic_segment_entry()
    return CostModelSpec(fn=fn, args=args,
                         expected_bytes_per_shard=(
                             _MEGASTEP_K * _pic_step_bytes()),
                         count_kinds=("collective_permute",))


def _pic_segment_probe_cost() -> CostModelSpec:
    """The probe side of the carry contract, byte-exact: every trace
    row's single all-reduce moves the full (2, 9) f32 column set —
    rho + 7 particle lanes + the overflow column. A contract that
    drops a column (the bad_segment_carry fixture) shrinks the
    all-reduce operand and fails this pin."""
    fn, args = _pic_segment_entry()
    return CostModelSpec(fn=fn, args=args,
                         expected_bytes_per_shard=(
                             _PIC_SEG_ROWS * 2 * _PIC_SEG_COLS * 4),
                         count_kinds=("all_reduce",))


# ---------------------------------------------------------------------------
# Astaroth temporal megastep targets: the fused segment over
# lcm(3, s)-period temporal groups must pay exactly the grouped deep
# exchanges (w riding only where a group starts at alpha != 0) — the
# segment's wire bill is k x the amortized deep-exchange model,
# HLO-exact, with one probe all-reduce per declared trace row.

_AST_SEG_S = 2
_AST_SEG_K = 4


@functools.lru_cache(maxsize=None)
def _astaroth_temporal_engine():
    import jax
    import numpy as np

    from ..models.astaroth import Astaroth
    from ..parallel.methods import Method

    a = Astaroth(8, 8, 16, mesh_shape=(1, 1, 2),
                 devices=jax.devices()[:2], dtype=np.float32,
                 kernel="xla", methods=Method.PpermuteSlab,
                 exchange_every=_AST_SEG_S)
    a._ensure_w()
    return a


@functools.lru_cache(maxsize=None)
def _astaroth_segment_entry():
    from ..parallel.megastep import metric_base_vec

    a = _astaroth_temporal_engine()
    seg = a.make_segment(_AST_SEG_K,
                         probe_every=_MEGASTEP_PROBE_EVERY)
    return seg.fn, ((dict(a.dd.curr), dict(a._w)),
                    metric_base_vec(None, 0, mesh=a.dd.mesh))


def _astaroth_segment_counts():
    """(ppermutes, probe rows, expected bytes/shard) of the registered
    temporal segment: per lcm(3, s)-period chunk the groups start at
    RK substeps (g*s) % 3 — a group starting at alpha != 0 ships the
    8 w accumulators in the SAME deep exchange (2x quantities, same
    launches per quantity)."""
    import math

    from ..models.astaroth import FIELDS, RK3_ALPHA
    from ..parallel.mesh import mesh_dim
    from .costmodel import deep_exchange_bytes_per_shard

    a = _astaroth_temporal_engine()
    s = _AST_SEG_S
    period = math.lcm(3, s)
    counts = mesh_dim(a.dd.mesh)
    local = a.dd.local_size
    # one f32 quantity's depth-s deep exchange, per shard
    deep1 = deep_exchange_bytes_per_shard(
        (local.z, local.y, local.x), a.dd.radius, counts, 4, s)
    # ppermutes per quantity per deep exchange: 2 per active mesh axis
    active = sum(1 for ax in range(3) if counts[ax] > 1)
    starts = [(g * s) % 3 for g in range(period // s)]
    qs = [len(FIELDS) * (2 if RK3_ALPHA[st] != 0.0 else 1)
          for st in starts]
    n_chunks = _AST_SEG_K // (period // 3)
    cp = n_chunks * sum(qs) * 2 * active
    from ..parallel.megastep import probe_rel_steps
    rows = len(probe_rel_steps([period // 3] * n_chunks,
                               _MEGASTEP_PROBE_EVERY))
    return cp, rows, n_chunks * sum(qs) * deep1


def _astaroth_segment_hlo() -> HloSpec:
    fn, args = _astaroth_segment_entry()
    cp, rows, _ = _astaroth_segment_counts()
    return HloSpec(fn=fn, args=args,
                   allow=("collective_permute", "all_reduce"),
                   exact_counts={"collective_permute": cp,
                                 "all_reduce": rows})


def _astaroth_segment_cost() -> CostModelSpec:
    fn, args = _astaroth_segment_entry()
    _, _, expected = _astaroth_segment_counts()
    return CostModelSpec(fn=fn, args=args,
                         expected_bytes_per_shard=expected,
                         count_kinds=("collective_permute",))


def _central_diff_spec(axis: int) -> StencilOpSpec:
    from ..geometry import Dim3, Radius
    from ..ops.stencil_kernels import central_diff

    radius = Radius.constant(1)
    interior = Dim3(8, 8, 8)
    return StencilOpSpec(
        fn=lambda p: central_diff(p, axis, radius, interior),
        args=(_f32((10, 10, 10)),), radius=radius, interior=interior)


# ---------------------------------------------------------------------------
# dataflow targets: donation / transfer / recompile for every compiled
# entry point the drivers dispatch — the model step loops, the
# temporal path, make_exchange, the fused megastep segments, and the
# ensemble step/segment/lane programs. Each entry builder returns
# (jitted_fn, args) exactly the way the production caller invokes it,
# so the donation checker audits the SHIPPED jit (its declared
# donate_argnums), the transfer checker walks the same traced program,
# and the recompile checker fingerprints the same abstract signature.
# Builders are memoized: the three checkers audit ONE engine instead
# of realizing the same domain per target (nothing here dispatches —
# lower/trace/eval_shape only — so sharing the jitted fn is safe).


@functools.lru_cache(maxsize=None)
def _jacobi_step_entry(exchange_every: int = 1):
    import jax.numpy as jnp
    import numpy as np

    from ..models.jacobi import Jacobi3D

    j = Jacobi3D(16, 16, 16, mesh_shape=_EXCHANGE_MESH,
                 dtype=np.float32, kernel="xla",
                 exchange_every=exchange_every)
    return j._step_n, (j.dd.curr["temp"], jnp.asarray(2, jnp.int32))


@functools.lru_cache(maxsize=None)
def _astaroth_iter_entry():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models.astaroth import Astaroth
    from ..parallel.methods import Method

    a = Astaroth(8, 8, 8, mesh_shape=(1, 1, 2),
                 devices=jax.devices()[:2], dtype=np.float32,
                 kernel="xla", methods=Method.PpermuteSlab)
    a._ensure_w()
    return a._iter_n, (a.dd.curr, a._w, jnp.asarray(1, jnp.int32))


@functools.lru_cache(maxsize=None)
def _make_exchange_entry(method_name: str):
    from ..geometry import Radius
    from ..parallel.exchange import make_exchange
    from ..parallel.methods import Method

    mesh = _mesh(_EXCHANGE_MESH)
    ex = make_exchange(mesh, Radius.constant(1), Method[method_name])
    return ex, ({"q": _f32((20, 20, 20))},)


@functools.lru_cache(maxsize=None)
def _make_exchange_wire_entry(method_name: str, fmt: str = "bf16"):
    """The certified low-precision wire path: building this entry IS
    the gate — make_exchange refuses (PrecisionGateError) unless the
    precision checker certifies the narrowing program safe."""
    from ..geometry import Radius
    from ..parallel.exchange import make_exchange
    from ..parallel.methods import Method

    mesh = _mesh(_EXCHANGE_MESH)
    fs = {"q": _f32((20, 20, 20))}
    ex = make_exchange(mesh, Radius.constant(1), Method[method_name],
                       wire_format=fmt, fields_spec=fs)
    return ex, (dict(fs),)


def _wire_exchange_hlo(method_name: str) -> HloSpec:
    fn, args = _make_exchange_wire_entry(method_name)
    return HloSpec(fn=fn, args=args, allow=("collective_permute",))


def _wire_exchange_cost(method_name: str) -> CostModelSpec:
    from ..geometry import Dim3, Radius
    from ..parallel.exchange import exchanged_bytes_per_sweep

    fn, args = _make_exchange_wire_entry(method_name)
    expected = sum(exchanged_bytes_per_sweep(
        (10, 10, 10), Radius.constant(1), Dim3(*_EXCHANGE_MESH), 4,
        wire_format="bf16").values())
    # the whole point of the format, pinned: bf16 wire bytes are
    # EXACTLY half the f32 bill (the HLO cross-check then proves the
    # lowered program pays this figure)
    full = _sweep_bytes((10, 10, 10), Radius.constant(1),
                        Dim3(*_EXCHANGE_MESH), 4)
    assert expected * 2 == full
    return CostModelSpec(fn=fn, args=args,
                         expected_bytes_per_shard=expected)


@functools.lru_cache(maxsize=None)
def _make_exchange_layout_entry(method_name: str):
    """The jitted orchestrator under the irredundant wire layout —
    the exact engine ``DistributedDomain.realize`` deploys when
    ``wire_layout="irredundant"`` is set or a tuned plan carries it."""
    from ..geometry import Radius
    from ..parallel.exchange import make_exchange
    from ..parallel.methods import Method

    mesh = _mesh(_EXCHANGE_MESH)
    ex = make_exchange(mesh, Radius.constant(1), Method[method_name],
                       wire_layout="irredundant")
    return ex, ({"q": _f32((20, 20, 20))},)


@functools.lru_cache(maxsize=None)
def _make_exchange_fp8_entry(method_name: str = "PpermuteSlab",
                             layout: str = "slab"):
    """The certified fp8 (e4m3) wire path, optionally composed with
    the irredundant layout: building this entry IS the gate — exactly
    as for bf16, make_exchange refuses unless the precision checker
    certifies the narrowing safe."""
    from ..geometry import Radius
    from ..parallel.exchange import make_exchange
    from ..parallel.methods import Method

    mesh = _mesh(_EXCHANGE_MESH)
    fs = {"q": _f32((20, 20, 20))}
    ex = make_exchange(mesh, Radius.constant(1), Method[method_name],
                       wire_format="e4m3", fields_spec=fs,
                       wire_layout=layout)
    return ex, (dict(fs),)


def _layout_exchange_hlo(method_name: str) -> HloSpec:
    fn, args = _make_exchange_layout_entry(method_name)
    return HloSpec(fn=fn, args=args, allow=("collective_permute",))


def _layout_exchange_cost(method_name: str) -> CostModelSpec:
    from ..geometry import Dim3, Radius

    fn, args = _make_exchange_layout_entry(method_name)
    counts = Dim3(*_EXCHANGE_MESH)
    expected = _irr_bytes((10, 10, 10), Radius.constant(1), counts, 4)
    assert expected < _sweep_bytes((10, 10, 10), Radius.constant(1),
                                   counts, 4)
    return CostModelSpec(fn=fn, args=args,
                         expected_bytes_per_shard=expected)


def _fp8_exchange_hlo(method_name: str = "PpermuteSlab",
                      layout: str = "slab") -> HloSpec:
    fn, args = _make_exchange_fp8_entry(method_name, layout)
    return HloSpec(fn=fn, args=args, allow=("collective_permute",))


def _fp8_exchange_cost(method_name: str = "PpermuteSlab",
                       layout: str = "slab") -> CostModelSpec:
    from ..geometry import Dim3, Radius
    from .costmodel import sweep_wire_bytes

    fn, args = _make_exchange_fp8_entry(method_name, layout)
    counts = Dim3(*_EXCHANGE_MESH)
    r = Radius.constant(1)
    expected = sum(sweep_wire_bytes(
        (10, 10, 10), r, counts, 4, wire_format="e4m3",
        layout=layout).values())
    # the fp8 headline, pinned: wire bytes exactly ONE QUARTER of the
    # f32 bill under the same layout (the HLO cross-check then proves
    # the lowered program pays this figure)
    full = sum(sweep_wire_bytes((10, 10, 10), r, counts, 4,
                                layout=layout).values())
    assert expected * 4 == full
    return CostModelSpec(fn=fn, args=args,
                         expected_bytes_per_shard=expected)


@functools.lru_cache(maxsize=None)
def _megastep_segment_entry():
    import numpy as np

    from ..models.jacobi import Jacobi3D
    from ..parallel.megastep import metric_base_vec

    j = Jacobi3D(16, 16, 16, mesh_shape=_EXCHANGE_MESH,
                 dtype=np.float32, kernel="xla")
    seg = j.make_segment(_MEGASTEP_K, probe_every=_MEGASTEP_PROBE_EVERY)
    return seg.fn, (j.dd.curr["temp"],
                    metric_base_vec(None, 0, mesh=j.dd.mesh))


@functools.lru_cache(maxsize=None)
def _domain_segment_entry():
    import numpy as np

    from ..distributed import DistributedDomain
    from ..geometry import Radius
    from ..parallel.exchange import exchange_shard
    from ..parallel.megastep import metric_base_vec
    from ..parallel.mesh import mesh_dim

    dd = DistributedDomain(16, 16, 16)
    dd.set_mesh_shape(_EXCHANGE_MESH)
    dd.set_radius(1)
    dd.add_data("a", np.float32)
    dd.add_data("b", np.float32)
    dd.realize()
    counts = mesh_dim(dd.mesh)
    radius = Radius.constant(1)

    def shard_step(fields):
        return {q: exchange_shard(p, radius, counts)
                for q, p in fields.items()}

    seg = dd.make_segment(shard_step, check_every=2)
    return seg.fn, (dict(dd.curr),
                    metric_base_vec(None, 0, mesh=dd.mesh))


@functools.lru_cache(maxsize=None)
def _ensemble_engine():
    from ..serving.ensemble import EnsembleJacobi

    return EnsembleJacobi(_ENSEMBLE_N, 24, 24, 24,
                          mesh_shape=_EXCHANGE_MESH)


@functools.lru_cache(maxsize=None)
def _ensemble_step_entry():
    import jax.numpy as jnp

    eng = _ensemble_engine()
    hot, cold = eng._param_args()
    return eng._step_n, (eng.state["temp"], hot, cold,
                         jnp.asarray(1, jnp.int32))


@functools.lru_cache(maxsize=None)
def _ensemble_segment_entry():
    eng = _ensemble_engine()
    fn = eng._segments.get((2, 1))
    if fn is None:
        fn = eng._segment_fn(2, 1)
    hot, cold = eng._param_args()
    return fn, (eng.state["temp"], hot, cold)


def _ensemble_set_lane_entry():
    import jax.numpy as jnp

    eng = _ensemble_engine()
    lane = {q: eng.state[q][0] for q in eng.state}
    return eng._set_lane, (dict(eng.state), lane, jnp.int32(0))


@functools.lru_cache(maxsize=None)
def _fleet_bucket_requests():
    """A padded admission (user grid strictly inside the bucket) and
    the native bucket-shape request — the pair the fleet bucketing
    targets compare."""
    from ..serving.queue import CampaignRequest
    from ..serving.slo import GridBucketer

    bucketer = GridBucketer(((24, 24, 24),))
    padded, was_padded = bucketer.apply(CampaignRequest(
        tenant="lint", campaign="pad", grid=(18, 21, 13),
        mesh_shape=_EXCHANGE_MESH))
    native = CampaignRequest(tenant="lint", campaign="native",
                             grid=(24, 24, 24),
                             mesh_shape=_EXCHANGE_MESH)
    return padded, native, was_padded


@functools.lru_cache(maxsize=None)
def _fleet_bucket_entry():
    """The fleet admission path's compiled step: bucketing replaces
    the user grid with its bucket BEFORE fingerprinting, so a padded
    request must share the native bucket request's fingerprint (ONE
    engine-cache slot — the bounded-cache contract). Raises when
    bucketing leaks the pre-pad grid into the admission key; returns
    the bucket-shaped ensemble step entry the padded request reuses."""
    from ..serving.queue import request_fingerprint

    padded, native, was_padded = _fleet_bucket_requests()
    if not was_padded or tuple(padded.grid) != (24, 24, 24):
        raise AssertionError(
            f"grid bucketing failed: (18, 21, 13) admitted at "
            f"{tuple(padded.grid)}, want the (24, 24, 24) bucket")
    fp_pad = request_fingerprint(padded)
    fp_nat = request_fingerprint(native)
    if fp_pad != fp_nat:
        raise AssertionError(
            f"padded admission does not share the native bucket "
            f"fingerprint ({fp_pad} != {fp_nat}) — the pre-pad grid "
            f"leaked into the admission key, so the per-replica "
            f"engine cache is unbounded again")
    return _ensemble_step_entry()


def _fleet_bucket_step_spec() -> HloSpec:
    """Bucketed-admission HLO identity: the step an engine built from
    the PADDED request lowers to StableHLO text byte-identical to the
    native bucket-shape step (bucketing must not leak the pre-pad
    grid into the compiled program), with the same pinned collective
    contract as ``serving.ensemble.step``."""
    from .hlo import lowering_supported

    padded, _, _ = _fleet_bucket_requests()
    fn, args = _fleet_bucket_entry()
    if lowering_supported():
        import jax
        import jax.numpy as jnp

        from ..serving.ensemble import EnsembleJacobi
        eng_pad = EnsembleJacobi(_ENSEMBLE_N, *padded.grid,
                                 mesh_shape=_EXCHANGE_MESH)
        hot, cold = eng_pad._param_args()
        pad_args = (eng_pad.state["temp"], hot, cold,
                    jnp.asarray(1, jnp.int32))
        pad_text = jax.jit(eng_pad._step_n).lower(*pad_args).as_text()
        nat_text = jax.jit(fn).lower(*args).as_text()
        if pad_text != nat_text:
            raise AssertionError(
                "padded-bucket step does not lower to HLO identical "
                "to the native bucket-shape step — bucketed admission "
                "compiled a different program than the bucket it "
                "claims to reuse")
    return HloSpec(fn=fn, args=args, allow=("collective_permute",),
                   exact_counts={"collective_permute": 6})


def _donation_spec(entry, donate=(0,)):
    fn, args = entry()
    return DonationSpec(fn=fn, args=args, donate_argnums=tuple(donate))


def _transfer_spec(entry):
    fn, args = entry()
    return TransferSpec(fn=fn, args=args)


def _health_step_probe_transfer() -> TransferSpec:
    hs = _health_step_probe_spec()
    return TransferSpec(fn=hs.fn, args=hs.args)


def _recompile_spec(entry, carry=((0, None),)):
    fn, args = entry()
    return RecompileSpec(fn=fn, args=args, carry=tuple(carry))


def _dataflow_targets() -> List[Target]:
    """The donation/transfer/recompile registry block (one audit per
    production entry point per applicable checker)."""
    targets: List[Target] = []
    # donation: every declared donate_argnums buffer must alias
    donation = [
        ("models.jacobi.step_n[xla,donation]",
         _jacobi_step_entry, (0,)),
        ("models.jacobi.step_n[xla-temporal[s=2],donation]",
         lambda: _jacobi_step_entry(2), (0,)),
        ("models.astaroth.iter_n[donation]",
         _astaroth_iter_entry, (0, 1)),
        ("parallel.exchange.make_exchange[PpermuteSlab,donation]",
         lambda: _make_exchange_entry("PpermuteSlab"), (0,)),
        ("parallel.exchange.make_exchange[PpermutePacked,donation]",
         lambda: _make_exchange_entry("PpermutePacked"), (0,)),
        ("parallel.exchange.make_exchange[AllGather,donation]",
         lambda: _make_exchange_entry("AllGather"), (0,)),
        (f"parallel.megastep.segment[k={_MEGASTEP_K},donation]",
         _megastep_segment_entry, (0,)),
        ("distributed.make_segment[donation]",
         _domain_segment_entry, (0,)),
        (f"serving.ensemble.step[N={_ENSEMBLE_N},donation]",
         _ensemble_step_entry, (0,)),
        (f"serving.ensemble.segment[N={_ENSEMBLE_N},k=2,donation]",
         _ensemble_segment_entry, (0,)),
        (f"serving.ensemble.set_lane[N={_ENSEMBLE_N},donation]",
         _ensemble_set_lane_entry, (0,)),
        ("models.pic.step[donation]", _pic_step_entry, (0,)),
        (f"models.pic.segment[k={_MEGASTEP_K},donation]",
         _pic_segment_entry, (0,)),
    ]
    for name, entry, donate in donation:
        targets.append(DonationTarget(
            name, lambda e=entry, d=donate: _donation_spec(e, d)))
    # transfer: no host escape inside the compiled hot path
    transfer = [
        ("models.jacobi.step_n[xla,transfer]", _jacobi_step_entry),
        ("models.astaroth.iter_n[transfer]", _astaroth_iter_entry),
        ("parallel.exchange.make_exchange[PpermutePacked,transfer]",
         lambda: _make_exchange_entry("PpermutePacked")),
        (f"parallel.megastep.segment[k={_MEGASTEP_K},transfer]",
         _megastep_segment_entry),
        (f"serving.ensemble.step[N={_ENSEMBLE_N},transfer]",
         _ensemble_step_entry),
        (f"serving.ensemble.segment[N={_ENSEMBLE_N},k=2,transfer]",
         _ensemble_segment_entry),
        ("serving.fleet.admission[transfer]", _fleet_bucket_entry),
        ("models.pic.step[transfer]", _pic_step_entry),
    ]
    for name, entry in transfer:
        targets.append(TransferTarget(
            name, lambda e=entry: _transfer_spec(e)))
    targets.append(TransferTarget("resilience.health.step+probe[transfer]",
                                  _health_step_probe_transfer))
    # recompile: dispatch-stable abstract fingerprints; carry pairs
    # the donated state with the output subtree that feeds back
    recompile = [
        ("models.jacobi.step_n[xla,recompile]",
         _jacobi_step_entry, ((0, None),)),
        ("models.astaroth.iter_n[recompile]",
         _astaroth_iter_entry, ((0, (0,)), (1, (1,)))),
        ("parallel.exchange.make_exchange[PpermutePacked,recompile]",
         lambda: _make_exchange_entry("PpermutePacked"), ((0, None),)),
        (f"parallel.megastep.segment[k={_MEGASTEP_K},recompile]",
         _megastep_segment_entry, ((0, (0,)),)),
        (f"serving.ensemble.step[N={_ENSEMBLE_N},recompile]",
         _ensemble_step_entry, ((0, None),)),
        (f"serving.ensemble.segment[N={_ENSEMBLE_N},k=2,recompile]",
         _ensemble_segment_entry, ((0, (0,)),)),
        ("serving.fleet.admission[recompile]",
         _fleet_bucket_entry, ((0, None),)),
        ("models.pic.step[recompile]", _pic_step_entry, ((0, None),)),
    ]
    for name, entry, carry in recompile:
        targets.append(RecompileTarget(
            name, lambda e=entry, c=carry: _recompile_spec(e, c)))
    return targets


# ---------------------------------------------------------------------------
# VMEM targets: every shipped Pallas kernel's static memory/tiling
# audit. The overlap/RDMA builders are shared with the dma targets;
# the single-chip wrap/halo fast-path kernels (previously outside the
# registry) enter here.


def _vmem_from_kernel(build) -> VmemSpec:
    ks = build()
    return VmemSpec(fn=ks.fn, args=ks.args)


def _jacobi7_plane_vmem_spec(side: int = 8) -> VmemSpec:
    from ..geometry import Dim3, Radius
    from ..ops.pallas_stencil import jacobi7_pallas

    radius = Radius.constant(1)
    interior = Dim3(side, side, side)
    g = side + 2

    def fn(p):
        return jacobi7_pallas(p, radius, interior, interpret=False)

    return VmemSpec(fn=fn, args=(_f32((g, g, g)),))


def _laplace6_vmem_spec(side: int = 8) -> VmemSpec:
    from ..geometry import Dim3, Radius
    from ..ops.pallas_stencil import laplace6_pallas

    radius = Radius.constant(3)
    interior = Dim3(side, side, side)
    g = side + 6

    def fn(p):
        return laplace6_pallas(p, radius, interior, interpret=False)

    return VmemSpec(fn=fn, args=(_f32((g, g, g)),))


def _jacobi_wrap_vmem_spec(steps: int, side: int = 16) -> VmemSpec:
    from ..ops.pallas_stencil import (jacobi7_wrap_pallas,
                                      jacobi7_wrapn_pallas)

    hot = (side // 4, side // 2, side // 2)
    cold = (3 * side // 4, side // 2, side // 2)
    r = side // 8

    def fn(q):
        if steps == 1:
            return jacobi7_wrap_pallas(q, hot, cold, r, interpret=False)
        return jacobi7_wrapn_pallas(q, hot, cold, r, steps=steps,
                                    interpret=False)

    return VmemSpec(fn=fn, args=(_f32((side, side, side)),))


def _mhd_wrap_vmem_spec(pair: bool, side: int = 16) -> VmemSpec:
    from ..models.astaroth import FIELDS, MhdParams
    from ..ops.pallas_mhd import (mhd_substep01_wrap_pallas,
                                  mhd_substep_wrap_pallas)

    prm = MhdParams()

    def fn(*fs):
        fields = dict(zip(FIELDS, fs))
        if pair:
            f, w = mhd_substep01_wrap_pallas(fields, prm, prm.dt,
                                             interpret=False)
        else:
            f, w = mhd_substep_wrap_pallas(fields, None, 0, prm, prm.dt,
                                           interpret=False)
        return tuple(f[q] for q in FIELDS) + tuple(w[q] for q in FIELDS)

    return VmemSpec(fn=fn, args=tuple(_f32((side, side, side))
                                      for _ in FIELDS))


def _jacobi_halon_vmem_spec() -> VmemSpec:
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..geometry import Dim3
    from ..ops.pallas_halo import jacobi7_halon_pallas
    from ..parallel.exchange import exchange_interior_slabs, shard_origin

    mesh = _mesh((1, 2, 2))
    counts = Dim3(1, 2, 2)
    local = Dim3(16, 8, 8)
    bz, steps = 4, 2

    def shard(p):
        ox, oy, oz = shard_origin(local, Dim3(0, 0, 0))
        org = jnp.stack([oz, oy, ox]).astype(jnp.int32)
        slabs = exchange_interior_slabs(p, counts, rz=bz, ry=8,
                                        radius_rows=steps,
                                        y_z_extended=True)
        return jacobi7_halon_pallas(p, slabs, org, (16, 16, 16),
                                    (5, 8, 8), (11, 8, 8), 1,
                                    steps=steps, block_z=bz, block_y=8,
                                    interpret=False)

    spec = P("z", "y", "x")
    sm = jax.shard_map(shard, mesh=mesh, in_specs=spec, out_specs=spec,
                       check_vma=False)
    return VmemSpec(fn=sm, args=(_f32((16, 16, 16)),))


def _mhd_halo_vmem_spec(pair: bool) -> VmemSpec:
    import jax
    from jax.sharding import PartitionSpec as P

    from ..geometry import Dim3
    from ..models.astaroth import FIELDS, MhdParams
    from ..ops.pallas_halo import (mhd_halo_blocks,
                                   mhd_substep01_halo_pallas,
                                   mhd_substep_halo_pallas)
    from ..parallel.exchange import exchange_interior_slabs

    mesh = _mesh((1, 2, 2))
    counts = Dim3(1, 2, 2)
    prm = MhdParams()
    Z = Y = X = 8
    bz, _by = mhd_halo_blocks(Z, Y)
    rr = 6 if pair else 3

    def shard(fields):
        slabs = {q: exchange_interior_slabs(fields[q], counts, rz=bz,
                                            ry=8, radius_rows=rr,
                                            y_z_extended=True)
                 for q in FIELDS}
        if pair:
            f, w = mhd_substep01_halo_pallas(fields, slabs, prm, prm.dt,
                                             interpret=False)
        else:
            f, w = mhd_substep_halo_pallas(fields, None, slabs, 0, prm,
                                           prm.dt, interpret=False)
        return f, w

    spec = P("z", "y", "x")
    fspec = {q: spec for q in FIELDS}
    sm = jax.shard_map(shard, mesh=mesh, in_specs=(fspec,),
                       out_specs=(fspec, fspec), check_vma=False)
    fields = {q: _f32((2 * Z, 2 * Y, X)) for q in FIELDS}
    return VmemSpec(fn=sm, args=(fields,))


# ---------------------------------------------------------------------------
# prescriptive-tiling targets (checker 10): every shipped Pallas
# compute/exchange kernel audited at 256^3- and 512^3-PER-DEVICE
# shapes against the PHYSICAL VMEM budget — trace-only, so tier-1 on
# CPU proves the production-size story the 8^3 bench trajectory never
# could. Expectations are part of the registered contract:
#
# * "legal"      — the kernel's planner-derived default block shape
#                  passes the full VMEM audit at this size (the
#                  SNIPPETS.md 512^3 Mosaic failure, closed: the old
#                  (16, 128) Jacobi halo default is the bad_tiling
#                  fixture, proven flagged);
# * "infeasible" — the planner must REFUSE this size (build raises
#                  TilingInfeasibleError) or the audit must flag it:
#                  the full-lane (X-wide) MHD halo corner segments and
#                  the 7-plane laplace window genuinely cannot stage
#                  under 16 MiB at these shapes — re-tiling the lane
#                  dim is the named ROADMAP follow-up, and until then
#                  the gate proves the model paths decline loudly
#                  instead of dying in Mosaic's allocator.

from .tiling import TilingSpec, TilingTarget  # noqa: E402


def _tiling_from_vmem(build) -> TilingSpec:
    ks = build()
    return TilingSpec(fn=ks.fn, args=ks.args)


def _jacobi_halon_tiling_spec(side: int) -> TilingSpec:
    """The N=2 halo pair kernel called directly at a production
    per-device shape; slab shapes derive from the SAME planner fit the
    model deploys (fit_pair_halo_blocks raises when infeasible — the
    refused-at-build verdict)."""
    import jax
    import jax.numpy as jnp

    from ..ops.pallas_halo import (fit_pair_halo_blocks,
                                   jacobi7_halon_pallas)

    S = side
    bz, by = fit_pair_halo_blocks(S, S, S, 4, 2)
    slabs = {"zlo": _f32((bz, S, S)), "zhi": _f32((bz, S, S)),
             "ylo": _f32((S + 2 * bz, 8, S)),
             "yhi": _f32((S + 2 * bz, 8, S))}
    org = jax.ShapeDtypeStruct((3,), jnp.int32)

    def fn(interior, zlo, zhi, ylo, yhi, o):
        return jacobi7_halon_pallas(
            interior, {"zlo": zlo, "zhi": zhi, "ylo": ylo, "yhi": yhi},
            o, (S, S, S), (S // 4, S // 2, S // 2),
            (3 * S // 4, S // 2, S // 2), S // 8, steps=2,
            block_z=bz, block_y=by, interpret=False)

    return TilingSpec(fn=fn, args=(_f32((S, S, S)), slabs["zlo"],
                                   slabs["zhi"], slabs["ylo"],
                                   slabs["yhi"], org))


def _mhd_halo_tiling_spec(pair: bool, side: int) -> TilingSpec:
    """The MHD halo kernels at a production per-device shape, direct
    call. ``mhd_halo_blocks`` (the same fit the model and the slab
    exchange share) raises at these sizes — the full-lane corner
    segments bind — so the registered expectation is the refusal."""
    from ..models.astaroth import FIELDS, MhdParams
    from ..ops.pallas_halo import (mhd_halo_blocks,
                                   mhd_substep01_halo_pallas,
                                   mhd_substep_halo_pallas)

    S = side
    bz, _by = mhd_halo_blocks(S, S, 8, 32, 8, X=S, itemsize=4)
    prm = MhdParams()
    fields = {q: _f32((S, S, S)) for q in FIELDS}
    slabs = {q: {"zlo": _f32((bz, S, S)), "zhi": _f32((bz, S, S)),
                 "ylo": _f32((S + 2 * bz, 8, S)),
                 "yhi": _f32((S + 2 * bz, 8, S))} for q in FIELDS}

    def fn(fields, slabs):
        if pair:
            return mhd_substep01_halo_pallas(fields, slabs, prm, prm.dt,
                                             interpret=False)
        return mhd_substep_halo_pallas(fields, None, slabs, 0, prm,
                                       prm.dt, interpret=False)

    return TilingSpec(fn=fn, args=(fields, slabs))


def _tiling_targets() -> List[Target]:
    targets: List[Target] = []

    def vmem_backed(prefix: str, build_for_side):
        for side in _TILING_SIDES:
            targets.append(TilingTarget(
                f"analysis.tiling.{prefix}[{side}]",
                lambda b=build_for_side, s=side:
                    _tiling_from_vmem(lambda: b(s)),
                expect=_TILING_EXPECT[prefix][side]))

    vmem_backed("ops.pallas_stencil.jacobi7_pallas",
                _jacobi7_plane_vmem_spec)
    vmem_backed("ops.pallas_stencil.laplace6_pallas",
                _laplace6_vmem_spec)
    vmem_backed("ops.pallas_stencil.jacobi7_wrap_pallas",
                lambda s: _jacobi_wrap_vmem_spec(1, s))
    vmem_backed("ops.pallas_stencil.jacobi7_wrapn_pallas[n=2]",
                lambda s: _jacobi_wrap_vmem_spec(2, s))
    vmem_backed("ops.pallas_stencil.jacobi7_wrapn_pallas[n=4]",
                lambda s: _jacobi_wrap_vmem_spec(4, s))
    vmem_backed("ops.pallas_halo.jacobi7_halo_pallas",
                _jacobi_halo_kernel_spec)
    vmem_backed("ops.pallas_mhd.mhd_substep_wrap_pallas",
                lambda s: _mhd_wrap_vmem_spec(False, s))
    vmem_backed("ops.pallas_mhd.mhd_substep01_wrap_pallas",
                lambda s: _mhd_wrap_vmem_spec(True, s))
    vmem_backed("ops.pallas_overlap.jacobi7_overlap_pallas",
                lambda s: _jacobi_overlap_spec(s))
    vmem_backed("ops.pallas_mhd_overlap.mhd_substep_overlap",
                lambda s: _mhd_overlap_spec(False, s))
    vmem_backed("parallel.pallas_exchange.exchange_shard_pallas",
                lambda s: _rdma_exchange_spec(s))
    for side in _TILING_SIDES:
        targets.append(TilingTarget(
            f"analysis.tiling.ops.pallas_halo."
            f"jacobi7_halon_pallas[n=2][{side}]",
            lambda s=side: _jacobi_halon_tiling_spec(s),
            expect=_TILING_EXPECT[
                "ops.pallas_halo.jacobi7_halon_pallas[n=2]"][side]))
        for pair, key in ((False, "ops.pallas_halo.mhd_substep_halo_pallas"),
                          (True,
                           "ops.pallas_halo.mhd_substep01_halo_pallas")):
            targets.append(TilingTarget(
                f"analysis.tiling.{key}[{side}]",
                lambda p=pair, s=side: _mhd_halo_tiling_spec(p, s),
                expect=_TILING_EXPECT[key][side]))
    return targets


_TILING_SIDES = (256, 512)

#: the registered per-size verdicts (see the block comment above);
#: probed on this image and pinned — a kernel whose story changes must
#: change this table in review
_TILING_EXPECT = {
    "ops.pallas_stencil.jacobi7_pallas": {256: "legal", 512: "legal"},
    "ops.pallas_stencil.laplace6_pallas": {256: "legal",
                                           512: "infeasible"},
    "ops.pallas_stencil.jacobi7_wrap_pallas": {256: "legal",
                                               512: "legal"},
    "ops.pallas_stencil.jacobi7_wrapn_pallas[n=2]": {256: "legal",
                                                     512: "legal"},
    "ops.pallas_stencil.jacobi7_wrapn_pallas[n=4]": {256: "legal",
                                                     512: "legal"},
    "ops.pallas_halo.jacobi7_halo_pallas": {256: "legal", 512: "legal"},
    "ops.pallas_halo.jacobi7_halon_pallas[n=2]": {256: "legal",
                                                  512: "legal"},
    "ops.pallas_mhd.mhd_substep_wrap_pallas": {256: "legal",
                                               512: "infeasible"},
    "ops.pallas_mhd.mhd_substep01_wrap_pallas": {256: "legal",
                                                 512: "infeasible"},
    "ops.pallas_halo.mhd_substep_halo_pallas": {256: "infeasible",
                                                512: "infeasible"},
    "ops.pallas_halo.mhd_substep01_halo_pallas": {256: "infeasible",
                                                  512: "infeasible"},
    # the RDMA overlap kernel stages its slab exchange buffers as
    # block-independent VMEM scratch: ~42 MB at 512^3/device — no
    # block shape can fix that; lane re-tiling is the named follow-up
    "ops.pallas_overlap.jacobi7_overlap_pallas": {256: "legal",
                                                  512: "infeasible"},
    "ops.pallas_mhd_overlap.mhd_substep_overlap": {256: "infeasible",
                                                   512: "infeasible"},
    "parallel.pallas_exchange.exchange_shard_pallas": {256: "legal",
                                                       512: "legal"},
}


# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# precision targets: dtype-flow certification of every exchange/step/
# segment entry point (checker 13), plus the certified bf16-wire
# customer's HLO/byte cross-checks


def _precision_spec(entry, wire=None, counts=None):
    from ..geometry import Dim3

    fn, args = entry()
    return PrecisionSpec(fn=fn, args=args,
                         wire=dict(wire) if wire else None,
                         counts=Dim3(*(counts or _EXCHANGE_MESH)))


def _wire_format_targets() -> List[Target]:
    """The narrow-wire / packed-layout lowering contracts:
    collective-permute-only, with HLO-observed wire bytes exactly half
    (bf16) or exactly a quarter (fp8 e4m3) of the f32 bill, and the
    irredundant layout's bytes strictly below slab — separately and
    composed."""
    out: List[Target] = []
    for m in ("PpermuteSlab", "PpermutePacked"):
        out.append(HloTarget(
            f"parallel.exchange.make_exchange[{m},wire=bf16,hlo]",
            lambda m=m: _wire_exchange_hlo(m)))
        out.append(CostModelTarget(
            f"parallel.exchange.make_exchange[{m},wire=bf16,bytes]",
            lambda m=m: _wire_exchange_cost(m)))
        out.append(HloTarget(
            f"parallel.exchange.make_exchange"
            f"[{m},layout=irredundant,hlo]",
            lambda m=m: _layout_exchange_hlo(m)))
        out.append(CostModelTarget(
            f"parallel.exchange.make_exchange"
            f"[{m},layout=irredundant,bytes]",
            lambda m=m: _layout_exchange_cost(m)))
    out += [
        HloTarget(
            "parallel.exchange.make_exchange"
            "[PpermuteSlab,wire=e4m3,hlo]",
            lambda: _fp8_exchange_hlo("PpermuteSlab")),
        CostModelTarget(
            "parallel.exchange.make_exchange"
            "[PpermuteSlab,wire=e4m3,bytes]",
            lambda: _fp8_exchange_cost("PpermuteSlab")),
        HloTarget(
            "parallel.exchange.make_exchange"
            "[PpermuteSlab,wire=e4m3,layout=irredundant,hlo]",
            lambda: _fp8_exchange_hlo("PpermuteSlab", "irredundant")),
        CostModelTarget(
            "parallel.exchange.make_exchange"
            "[PpermuteSlab,wire=e4m3,layout=irredundant,bytes]",
            lambda: _fp8_exchange_cost("PpermuteSlab", "irredundant")),
    ]
    return out


def _precision_targets() -> List[Target]:
    w32 = {"x": "f32", "y": "f32", "z": "f32"}
    wbf = {"x": "bf16", "y": "bf16", "z": "bf16"}
    wf8 = {"x": "e4m3", "y": "e4m3", "z": "e4m3"}
    targets: List[Target] = []
    for m in ("PpermuteSlab", "PpermutePacked"):
        targets.append(PrecisionTarget(
            f"analysis.precision.parallel.exchange.make_exchange[{m}]",
            lambda m=m: _precision_spec(
                lambda: _make_exchange_entry(m), wire=w32)))
        targets.append(PrecisionTarget(
            f"analysis.precision.parallel.exchange."
            f"make_exchange[{m},wire=bf16]",
            lambda m=m: _precision_spec(
                lambda: _make_exchange_wire_entry(m), wire=wbf)))
        # the irredundant layout's pack/unpack must not perturb the
        # dtype flow: full-precision certificate on the packed boxes
        targets.append(PrecisionTarget(
            f"analysis.precision.parallel.exchange."
            f"make_exchange[{m},layout=irredundant]",
            lambda m=m: _precision_spec(
                lambda: _make_exchange_layout_entry(m), wire=w32)))
    # the fp8 wire certificates — slab and composed with the
    # irredundant layout (the certified-safe customer the quarter-
    # bytes HLO targets ride on)
    targets.append(PrecisionTarget(
        "analysis.precision.parallel.exchange."
        "make_exchange[PpermuteSlab,wire=e4m3]",
        lambda: _precision_spec(
            lambda: _make_exchange_fp8_entry("PpermuteSlab"),
            wire=wf8)))
    targets.append(PrecisionTarget(
        "analysis.precision.parallel.exchange."
        "make_exchange[PpermuteSlab,wire=e4m3,layout=irredundant]",
        lambda: _precision_spec(
            lambda: _make_exchange_fp8_entry(
                "PpermuteSlab", "irredundant"), wire=wf8)))
    targets += [
        PrecisionTarget("analysis.precision.models.jacobi.step_n",
                        lambda: _precision_spec(_jacobi_step_entry)),
        PrecisionTarget("analysis.precision.models.astaroth.iter_n",
                        lambda: _precision_spec(_astaroth_iter_entry,
                                                counts=(1, 1, 2))),
        PrecisionTarget("analysis.precision.models.astaroth.segment",
                        lambda: _precision_spec(
                            _astaroth_segment_entry, counts=(1, 1, 2))),
        PrecisionTarget("analysis.precision.parallel.megastep.segment",
                        lambda: _precision_spec(_megastep_segment_entry)),
        PrecisionTarget("analysis.precision.distributed.segment",
                        lambda: _precision_spec(_domain_segment_entry)),
        PrecisionTarget("analysis.precision.models.pic.step",
                        lambda: _precision_spec(_pic_step_entry)),
        PrecisionTarget("analysis.precision.models.pic.segment",
                        lambda: _precision_spec(_pic_segment_entry)),
        PrecisionTarget("analysis.precision.serving.ensemble.step_n",
                        lambda: _precision_spec(_ensemble_step_entry)),
        PrecisionTarget("analysis.precision.serving.ensemble.segment",
                        lambda: _precision_spec(_ensemble_segment_entry)),
    ]
    return targets


def default_targets() -> List[Target]:
    """Every shipped contract stencil-lint proves on each run."""
    targets: List[Target] = [
        StencilOpTarget("ops.stencil_kernels.jacobi7", _jacobi7_spec),
        StencilOpTarget("ops.stencil_kernels.laplacian27",
                        _laplacian27_spec),
        StencilOpTarget("models.astaroth.mhd_rates", _mhd_rates_spec),
    ]
    for axis, ax_name in enumerate("xyz"):
        targets.append(StencilOpTarget(
            f"ops.fd6.der1[{ax_name}]",
            lambda a=axis: _fd6_spec("der1", a)))
        targets.append(StencilOpTarget(
            f"ops.fd6.der2[{ax_name}]",
            lambda a=axis: _fd6_spec("der2", a)))
    for a, b in ((0, 1), (0, 2), (1, 2)):
        targets.append(StencilOpTarget(
            f"ops.fd6.der_cross[{'xyz'[a]}{'xyz'[b]}]",
            lambda p=(a, b): _fd6_spec("cross", p)))
    targets += [
        PallasKernelTarget("parallel.pallas_exchange.exchange_shard_pallas",
                           _rdma_exchange_spec),
        PallasKernelTarget("ops.pallas_overlap.jacobi7_overlap_pallas",
                           _jacobi_overlap_spec),
        PallasKernelTarget("ops.pallas_mhd_overlap.mhd_substep_overlap",
                           lambda: _mhd_overlap_spec(pair=False)),
        PallasKernelTarget("ops.pallas_mhd_overlap.mhd_substep_overlap[pair]",
                           lambda: _mhd_overlap_spec(pair=True)),
        PallasKernelTarget("ops.pallas_halo.jacobi7_halo_pallas",
                           _jacobi_halo_kernel_spec),
        CollectiveTarget("parallel.exchange.exchange_shard[r1]",
                         lambda: _exchange_spec("r1")),
        CollectiveTarget("parallel.exchange.exchange_shard[r3]",
                         lambda: _exchange_spec("r3")),
        CollectiveTarget("parallel.exchange.exchange_shard[asym]",
                         lambda: _exchange_spec("asym")),
        CollectiveTarget("parallel.exchange.exchange_shard_packed[uneven]",
                         _exchange_packed_uneven_spec),
        CollectiveTarget("parallel.exchange.exchange_shard_allgather",
                         _exchange_allgather_spec),
        CollectiveTarget("parallel.exchange.exchange_interior_slabs[yzext]",
                         lambda: _interior_slabs_spec(True)),
        CollectiveTarget("parallel.exchange.exchange_interior_slabs",
                         lambda: _interior_slabs_spec(False)),
        CollectiveTarget("parallel.exchange.make_exchange[jit,packed]",
                         _make_exchange_jit_spec),
        # temporal blocking: the fused s-step group and the partial-
        # depth tail exchange on a deep-carry allocation
        CollectiveTarget("parallel.temporal.temporal_shard_steps[s=2]",
                         lambda: _temporal_group_spec(2)),
        CollectiveTarget("parallel.temporal.temporal_shard_steps[s=4]",
                         lambda: _temporal_group_spec(4)),
        CollectiveTarget("parallel.temporal.temporal_shard_steps[s=1.1.2]",
                         _temporal_group_asym_spec),
        CollectiveTarget("parallel.exchange.exchange_shard[deep-tail]",
                         _deep_tail_exchange_spec),
    ]
    # HLO-lowering audit: one target per exchange METHOD (+ the jitted
    # orchestrator), collective-permute-only unless the method is the
    # deliberate all-gather control
    targets += [
        HloTarget("parallel.exchange.exchange_shard[r1,hlo]",
                  lambda: _hlo_from_collective(
                      lambda: _exchange_spec("r1"))),
        HloTarget("parallel.exchange.exchange_shard[asym,hlo]",
                  lambda: _hlo_from_collective(
                      lambda: _exchange_spec("asym"))),
        HloTarget("parallel.exchange.exchange_shard_packed[uneven,hlo]",
                  lambda: _hlo_from_collective(
                      _exchange_packed_uneven_spec)),
        HloTarget("parallel.exchange.exchange_shard_allgather[hlo]",
                  lambda: _hlo_from_collective(
                      _exchange_allgather_spec, allow=("all_gather",))),
        HloTarget("parallel.exchange.exchange_interior_slabs[yzext,hlo]",
                  lambda: _hlo_from_collective(
                      lambda: _interior_slabs_spec(True))),
        HloTarget("parallel.exchange.make_exchange[jit,packed,hlo]",
                  lambda: _hlo_from_collective(_make_exchange_jit_spec)),
        HloTarget("parallel.pallas_exchange.exchange_shard_pallas[hlo]",
                  _rdma_hlo_spec),
        HloTarget("parallel.temporal.temporal_shard_steps[s=2,hlo]",
                  lambda: _hlo_from_collective(
                      lambda: _temporal_group_spec(2))),
        HloTarget("parallel.temporal.temporal_shard_steps[s=1.1.2,hlo]",
                  lambda: _hlo_from_collective(
                      _temporal_group_asym_spec)),
        HloTarget("parallel.exchange.exchange_shard[deep-tail,hlo]",
                  lambda: _hlo_from_collective(_deep_tail_exchange_spec)),
    ]
    # analytic-vs-HLO byte cross-check for the same methods
    targets += [
        CostModelTarget("parallel.exchange.exchange_shard[r1,cost]",
                        lambda: _exchange_cost("r1")),
        CostModelTarget("parallel.exchange.exchange_shard[r3,cost]",
                        lambda: _exchange_cost("r3")),
        CostModelTarget("parallel.exchange.exchange_shard[asym,cost]",
                        lambda: _exchange_cost("asym")),
        CostModelTarget(
            "parallel.exchange.exchange_shard_packed[uneven,cost]",
            _packed_uneven_cost),
        CostModelTarget("parallel.exchange.exchange_shard_allgather[cost]",
                        _allgather_cost),
        CostModelTarget(
            "parallel.exchange.exchange_interior_slabs[yzext,cost]",
            lambda: _interior_slabs_cost(True)),
        CostModelTarget("parallel.exchange.exchange_interior_slabs[cost]",
                        lambda: _interior_slabs_cost(False)),
        CostModelTarget("parallel.exchange.make_exchange[jit,packed,cost]",
                        _make_exchange_jit_cost),
        # the amortized temporal-blocking byte model: one deep exchange
        # per fused group, priced on the deepened allocation — the HLO
        # must move exactly these bytes, at both registered depths
        CostModelTarget("parallel.temporal.temporal_shard_steps[s=2,cost]",
                        lambda: _temporal_group_cost(2)),
        CostModelTarget("parallel.temporal.temporal_shard_steps[s=4,cost]",
                        lambda: _temporal_group_cost(4)),
        CostModelTarget(
            "parallel.temporal.temporal_shard_steps[s=1.1.2,cost]",
            _temporal_group_asym_cost),
        CostModelTarget("parallel.exchange.exchange_shard[deep-tail,cost]",
                        _deep_tail_exchange_cost),
    ]
    # irredundant wire-layout twins of the registered exchange
    # configs: same ppermute ring, packed boxes — collective bijection,
    # ppermute-only lowering (count pinned UNCHANGED vs slab), and
    # HLO-exact bytes strictly below the slab bill (see the block
    # comment at the builders)
    targets += [
        CollectiveTarget("parallel.exchange.exchange_shard[r1,irr]",
                         lambda: _exchange_irr_spec("r1")),
        CollectiveTarget("parallel.exchange.exchange_shard[r3,irr]",
                         lambda: _exchange_irr_spec("r3")),
        CollectiveTarget("parallel.exchange.exchange_shard[asym,irr]",
                         lambda: _exchange_irr_spec("asym")),
        CollectiveTarget(
            "parallel.exchange.exchange_shard_packed[uneven,irr]",
            _exchange_packed_irr_uneven_spec),
        CollectiveTarget(
            "parallel.temporal.temporal_shard_steps[s=2,irr]",
            lambda: _temporal_irr_spec(2)),
        CollectiveTarget(
            "parallel.exchange.exchange_shard[deep-tail,irr]",
            _deep_tail_irr_spec),
        HloTarget("parallel.exchange.exchange_shard[r1,irr,hlo]",
                  lambda: _exchange_irr_hlo("r1")),
        HloTarget("parallel.exchange.exchange_shard[asym,irr,hlo]",
                  lambda: _exchange_irr_hlo("asym")),
        HloTarget(
            "parallel.exchange.exchange_shard_packed[uneven,irr,hlo]",
            lambda: _hlo_from_collective(
                _exchange_packed_irr_uneven_spec)),
        HloTarget("parallel.temporal.temporal_shard_steps[s=2,irr,hlo]",
                  lambda: _hlo_from_collective(
                      lambda: _temporal_irr_spec(2))),
        HloTarget("parallel.exchange.exchange_shard[deep-tail,irr,hlo]",
                  lambda: _hlo_from_collective(_deep_tail_irr_spec)),
        CostModelTarget("parallel.exchange.exchange_shard[r1,irr,cost]",
                        lambda: _exchange_irr_cost("r1")),
        CostModelTarget("parallel.exchange.exchange_shard[r3,irr,cost]",
                        lambda: _exchange_irr_cost("r3")),
        CostModelTarget(
            "parallel.exchange.exchange_shard[asym,irr,cost]",
            lambda: _exchange_irr_cost("asym")),
        CostModelTarget(
            "parallel.exchange.exchange_shard_packed[uneven,irr,cost]",
            _packed_irr_uneven_cost),
        CostModelTarget(
            "parallel.temporal.temporal_shard_steps[s=2,irr,cost]",
            lambda: _temporal_irr_cost(2)),
        CostModelTarget(
            "parallel.exchange.exchange_shard[deep-tail,irr,cost]",
            _deep_tail_irr_cost),
    ]
    # every exchange configuration the autotuner can emit (Method.Auto)
    targets += _plan_targets()
    # ensemble serving: the batched member axis rides existing
    # collectives (same op count, bytes exactly xN)
    targets += [
        CollectiveTarget("serving.ensemble.exchange[N=4]",
                         _ensemble_exchange_spec),
        HloTarget("serving.ensemble.exchange[N=4,hlo]",
                  lambda: _hlo_from_collective(_ensemble_exchange_spec)),
        CostModelTarget("serving.ensemble.exchange[N=4,cost]",
                        _ensemble_exchange_cost),
        HloTarget("serving.ensemble.step[N=4,hlo]",
                  _ensemble_step_spec),
        HloTarget("serving.ensemble.probe[N=4,hlo]",
                  _ensemble_probe_spec),
        HloTarget("serving.fleet.bucket_step[hlo]",
                  _fleet_bucket_step_spec),
    ]
    # the health sentinel's probe: exactly one small all-reduce, alone
    # and fused into the production step (see resilience/health.py)
    targets += [
        HloTarget("resilience.health.probe[hlo]", _health_probe_spec),
        HloTarget("resilience.health.step+probe[hlo]",
                  _health_step_probe_spec),
    ]
    # the telemetry step-metrics instrumentation: metric columns ride
    # the probe's one all-reduce — the instrumented production step
    # keeps the bare step's exact collective counts and exact exchange
    # bytes (see stencil_tpu/telemetry/probe.py)
    targets += [
        HloTarget("telemetry.probe+metrics[hlo]",
                  _telemetry_probe_spec),
        HloTarget("telemetry.step+probe+metrics[hlo]",
                  _telemetry_step_probe_spec),
        CostModelTarget("telemetry.step+probe+metrics[cost]",
                        _telemetry_step_probe_cost),
    ]
    # the megastep: a check_every=k fused segment is ONE program with
    # exactly k x the per-step collective_permutes + one all-reduce per
    # probe row, bytes exactly k x the per-step model
    targets += [
        HloTarget(f"parallel.megastep.segment[k={_MEGASTEP_K},hlo]",
                  _megastep_segment_hlo),
        CostModelTarget(
            f"parallel.megastep.segment[k={_MEGASTEP_K},cost]",
            _megastep_segment_cost),
    ]
    # performance observatory: the ATTRIBUTED entry points (what
    # PerfAttributor.attributed hands the dispatcher) lower to the
    # IDENTICAL program as the bare ones — same exact collective
    # counts, same analytic byte bill, no host escapes, unchanged
    # compile fingerprints under the recompile checker. Attribution
    # is host-side by contract; these targets make the contract a gate
    targets += [
        HloTarget("observatory.attribution.segment[hlo]",
                  _attribution_segment_hlo),
        CostModelTarget("observatory.attribution.segment[cost]",
                        _attribution_segment_cost),
        TransferTarget("observatory.attribution.segment[transfer]",
                       lambda: _transfer_spec(_attributed_segment_entry)),
        RecompileTarget("observatory.attribution.segment[recompile]",
                        lambda: _recompile_spec(_attributed_segment_entry,
                                                ((0, (0,)),))),
        HloTarget("observatory.attribution.pic_step[hlo]",
                  _attribution_pic_hlo),
        TransferTarget("observatory.attribution.pic_step[transfer]",
                       lambda: _transfer_spec(_attributed_pic_entry)),
    ]
    # link observatory: the modeled per-link traffic matrix sums
    # EXACTLY to the HLO-extracted wire bytes for every registered
    # method — slab/packed x s, the all-gather control, migration, and
    # the PIC step's accumulate adjoint (see the block comment above)
    targets += [
        LinkmapTarget("observatory.linkmap.exchange[r1]",
                      lambda: _linkmap_exchange_spec("r1")),
        LinkmapTarget("observatory.linkmap.exchange[r3]",
                      lambda: _linkmap_exchange_spec("r3")),
        LinkmapTarget("observatory.linkmap.exchange[asym]",
                      lambda: _linkmap_exchange_spec("asym")),
        LinkmapTarget("observatory.linkmap.exchange[r1,irr]",
                      lambda: _linkmap_exchange_irr_spec("r1")),
        LinkmapTarget("observatory.linkmap.exchange[r3,irr]",
                      lambda: _linkmap_exchange_irr_spec("r3")),
        LinkmapTarget("observatory.linkmap.packed[uneven]",
                      _linkmap_packed_uneven_spec),
        LinkmapTarget("observatory.linkmap.plan[PpermuteSlab,s=2]",
                      lambda: _linkmap_plan_spec("PpermuteSlab", 2)),
        LinkmapTarget("observatory.linkmap.plan[PpermutePacked,s=4]",
                      lambda: _linkmap_plan_spec("PpermutePacked", 4)),
        LinkmapTarget("observatory.linkmap.plan[PpermuteSlab,s=1.1.2]",
                      _linkmap_temporal_asym_spec),
        LinkmapTarget("observatory.linkmap.hierarchical[dcn]",
                      _linkmap_hier_dcn_spec),
        LinkmapTarget("observatory.linkmap.allgather",
                      _linkmap_allgather_spec),
        LinkmapTarget("observatory.linkmap.migrate",
                      _linkmap_migrate_spec),
        LinkmapTarget("observatory.linkmap.pic_step",
                      _linkmap_pic_spec),
    ]
    # the particle-migration ring and the fused PIC step: the dynamic
    # communication pattern under the same gates as the static sweep —
    # ppermute-only lowering with the static budget x record-rows wire
    # bill matching the model exactly, and the overflow column riding
    # the probe's one all-reduce
    targets += [
        CollectiveTarget("parallel.migrate.migrate_shard",
                         _migrate_spec),
        HloTarget("parallel.migrate.migrate_shard[hlo]", _migrate_hlo),
        CostModelTarget("parallel.migrate.migrate_shard[cost]",
                        _migrate_cost),
        HloTarget("models.pic.step[hlo]", _pic_step_hlo),
        CostModelTarget("models.pic.step[cost]", _pic_step_cost),
        HloTarget("models.pic.probe[hlo]", _pic_probe_hlo),
    ]
    # the segment compiler's per-model carry contracts: a fused PIC
    # segment bills exactly k x 18 collective-permutes + one probe
    # all-reduce per trace row with HLO-exact bytes AND the full
    # contract probe columns (overflow included, byte-pinned); the
    # astaroth temporal segment pays exactly its lcm(3,s)-period
    # grouped deep exchanges — k x the amortized deep-exchange model —
    # with w riding only where a group starts at alpha != 0
    targets += [
        HloTarget(f"models.pic.segment[k={_MEGASTEP_K},hlo]",
                  _pic_segment_hlo),
        CostModelTarget(f"models.pic.segment[k={_MEGASTEP_K},cost]",
                        _pic_segment_cost),
        CostModelTarget(f"models.pic.segment[k={_MEGASTEP_K},probe]",
                        _pic_segment_probe_cost),
        HloTarget(f"models.astaroth.segment[temporal,s={_AST_SEG_S},"
                  f"k={_AST_SEG_K},hlo]", _astaroth_segment_hlo),
        CostModelTarget(
            f"models.astaroth.segment[temporal,s={_AST_SEG_S},"
            f"k={_AST_SEG_K},cost]", _astaroth_segment_cost),
    ]
    for axis, ax_name in enumerate("xyz"):
        targets.append(StencilOpTarget(
            f"ops.stencil_kernels.central_diff[{ax_name}]",
            lambda a=axis: _central_diff_spec(a)))
    # the dataflow block: donation / transfer / recompile audits for
    # every compiled entry point the drivers dispatch
    targets += _dataflow_targets()
    # static VMEM/tiling audit: every shipped Pallas kernel
    targets += [
        VmemTarget("parallel.pallas_exchange.exchange_shard_pallas[vmem]",
                   lambda: _vmem_from_kernel(_rdma_exchange_spec)),
        VmemTarget("ops.pallas_overlap.jacobi7_overlap_pallas[vmem]",
                   lambda: _vmem_from_kernel(_jacobi_overlap_spec)),
        VmemTarget("ops.pallas_mhd_overlap.mhd_substep_overlap[vmem]",
                   lambda: _vmem_from_kernel(
                       lambda: _mhd_overlap_spec(pair=False))),
        VmemTarget("ops.pallas_halo.jacobi7_halo_pallas[vmem]",
                   lambda: _vmem_from_kernel(_jacobi_halo_kernel_spec)),
        VmemTarget("ops.pallas_stencil.jacobi7_pallas",
                   _jacobi7_plane_vmem_spec),
        VmemTarget("ops.pallas_stencil.laplace6_pallas",
                   _laplace6_vmem_spec),
        VmemTarget("ops.pallas_stencil.jacobi7_wrap_pallas",
                   lambda: _jacobi_wrap_vmem_spec(1)),
        VmemTarget("ops.pallas_stencil.jacobi7_wrapn_pallas[n=2]",
                   lambda: _jacobi_wrap_vmem_spec(2)),
        VmemTarget("ops.pallas_stencil.jacobi7_wrapn_pallas[n=4]",
                   lambda: _jacobi_wrap_vmem_spec(4)),
        VmemTarget("ops.pallas_mhd.mhd_substep_wrap_pallas",
                   lambda: _mhd_wrap_vmem_spec(pair=False)),
        VmemTarget("ops.pallas_mhd.mhd_substep01_wrap_pallas",
                   lambda: _mhd_wrap_vmem_spec(pair=True)),
        VmemTarget("ops.pallas_halo.jacobi7_halon_pallas[n=2]",
                   _jacobi_halon_vmem_spec),
        VmemTarget("ops.pallas_halo.mhd_substep_halo_pallas",
                   lambda: _mhd_halo_vmem_spec(pair=False)),
        VmemTarget("ops.pallas_halo.mhd_substep01_halo_pallas",
                   lambda: _mhd_halo_vmem_spec(pair=True)),
    ]
    # prescriptive tiling: every shipped Pallas kernel gated at
    # 256^3/512^3-per-device shapes (checker 10)
    targets += _tiling_targets()
    # replay-soundness certification of every remote-DMA kernel's
    # semaphore schedule (checker 12)
    targets += _schedule_targets()
    # the certified bf16 wire customer: HLO/byte proofs that wire
    # bytes exactly halve
    targets += _wire_format_targets()
    # dtype-flow certification of every exchange/step/segment entry
    # point (checker 13)
    targets += _precision_targets()
    return targets


def load_targets(path: Union[str, Path]) -> List[Target]:
    """Load a fixture module (a .py file defining ``TARGETS``) and
    return its targets — the negative-control entry point."""
    path = Path(path)
    spec = importlib.util.spec_from_file_location(
        f"stencil_lint_fixture_{path.stem}", path)
    if spec is None or spec.loader is None:
        raise ImportError(f"cannot load fixture module {path}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    targets = getattr(mod, "TARGETS", None)
    if not targets:
        raise ValueError(f"fixture {path} defines no TARGETS")
    return list(targets)
