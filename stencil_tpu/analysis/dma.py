"""Checker 2: Pallas remote-DMA / semaphore discipline.

The static analog of the distributed TPU interpreter's vector-clock
race detector (tests/test_sanitizer.py): instead of executing the
kernels, trace them to jaxprs and verify the choreography invariants
every remote write depends on. Analyzed per ``pallas_call`` kernel:

* **start/wait pairing** — every ``make_async_remote_copy`` start puts
  its send AND recv semaphores in flight; both must be waited
  (``dma_wait``) before the kernel ends — "waited on both ends" (the
  SPMD kernel body is the program of *every* device, so the local
  send-wait and recv-wait cover both endpoints of each transfer);
* **no reuse in flight** — a semaphore cell may not be re-armed by a
  second start before its wait (the interpreter reports this as a
  data race; statically it is a double-arm);
* **barrier ordering** — a kernel issuing remote writes must rendezvous
  first: ``get_barrier_semaphore`` + neighbor signals + a wait whose
  value matches the number of signals, all BEFORE the first remote DMA
  start (destination buffers quiescent — the "you may write" handshake
  of tx_ipc.cpp:20-105);
* **mesh axis hygiene** — every ``device_id`` axis in remote copies and
  barrier signals must name a real mesh axis.

Scope and approximations (deliberate, documented):

* only REMOTE DMAs (a ``device_id``) are tracked — local double-buffer
  pipelines (``make_async_copy`` in ``fori_loop``) arm semaphores
  across iterations by design and are the interpreter's job to check;
* ``cond`` branches (``pl.when`` grid phases) are inlined in order —
  all phases execute on some grid step, so their starts/waits form one
  program order;
* remote in-flight state must be loop-invariant across ``scan`` /
  ``while`` bodies: a remote start whose wait lives in a later
  iteration cannot be proven single-armed and is flagged;
* dynamic semaphore indices on remote DMAs are flagged as warnings
  (identity cannot be established statically).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax.tree_util as jtu

from .jaxprs import (ClosedJaxpr, Jaxpr, Var, find_pallas_kernels,
                     index_key, is_semaphore_ref, literal_int, trace)
from .report import ERROR, WARNING, Finding


@dataclasses.dataclass
class PallasKernelSpec:
    """A traceable entry point containing >= 1 ``pallas_call``.

    ``fn(*args)`` is traced abstractly (typically a ``shard_map``-ped
    wrapper over a concrete mesh so ``lax.axis_index`` resolves);
    ``axis_names`` are the mesh axes remote ``device_id``s may target.
    ``expect_remote_dma`` asserts at least one remote copy is found —
    guarding the checker against vacuously passing a refactored kernel
    that no longer traces any DMA.
    """

    fn: Callable
    args: Sequence[Any]
    axis_names: Tuple[str, ...] = ()
    expect_remote_dma: bool = False


@dataclasses.dataclass
class PallasKernelTarget:
    name: str
    build: Callable[[], PallasKernelSpec]

    checker = "dma"


# ---------------------------------------------------------------------------
# event extraction

_START = "start"
_WAIT = "wait"
_BSIG = "barrier_signal"
_BWAIT = "barrier_wait"
_LOOP_BEGIN = "loop_begin"
_LOOP_END = "loop_end"


def _sem_key(var: Any, transforms: Any) -> Tuple:
    return (id(var), index_key(transforms))


def _device_axes(device_id: Any) -> Tuple[str, ...]:
    if isinstance(device_id, dict):
        return tuple(str(k) for k in device_id.keys())
    return ()


def _unflatten(eqn, tree_param: str, env: Optional[dict] = None):
    tree = eqn.params.get(tree_param)
    if tree is None:
        return None
    invars = list(eqn.invars)
    if env:
        invars = [env.get(v, v) if isinstance(v, Var) else v
                  for v in invars]
    try:
        return jtu.tree_unflatten(tree, invars)
    except Exception:  # noqa: BLE001 - layout drift on other jax versions
        return None


def _sub_env(sub_invars, outer_invars, env: dict) -> dict:
    """Map a sub-jaxpr's invars to the CANONICAL (outermost) atoms of
    the operands feeding them, so a scratch semaphore ref keeps one
    identity across cond branches / loop bodies / nested calls."""
    new = {}
    for iv, ov in zip(sub_invars, outer_invars):
        if isinstance(ov, Var):
            new[iv] = env.get(ov, ov)
        else:
            new[iv] = ov
    return new


def _collect_events(jaxpr: Jaxpr, events: List[Tuple],
                    notes: List[str], env: Optional[dict] = None) -> None:
    env = env or {}
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dma_start":
            un = _unflatten(eqn, "tree", env)
            if un is None or len(un) != 9:
                notes.append("unrecognized dma_start operand layout; "
                             "DMA not analyzed")
                continue
            _src, _st, _dst, _dt, ssem, sst, rsem, rst, device_id = un
            remote = isinstance(device_id, dict) and bool(device_id)
            keys = []
            for sem, tr in ((ssem, sst), (rsem, rst)):
                if sem is not None and is_semaphore_ref(sem):
                    keys.append(_sem_key(sem, tr))
            events.append((_START, tuple(keys), remote,
                           _device_axes(device_id)))
        elif name == "dma_wait":
            un = _unflatten(eqn, "tree", env)
            if un is None or len(un) != 9:
                notes.append("unrecognized dma_wait operand layout; "
                             "wait not analyzed")
                continue
            # dma_wait waits on the dst_sem slot (wait_send swaps
            # src/dst so the same slot holds the send semaphore)
            _src, _st, _dst, _dt, _ssem, _sst, rsem, rst, _dev = un
            if rsem is not None and is_semaphore_ref(rsem):
                events.append((_WAIT, _sem_key(rsem, rst)))
        elif name == "get_barrier_semaphore":
            for ov in eqn.outvars:
                events.append(("barrier_def", id(ov)))
        elif name == "semaphore_signal":
            un = _unflatten(eqn, "args_tree", env)
            if un is None or len(un) < 4:
                continue
            sem, _tr, inc, device_id = un[0], un[1], un[2], un[3]
            events.append((_BSIG, id(sem), literal_int(inc),
                           _device_axes(device_id)))
        elif name == "semaphore_wait":
            un = _unflatten(eqn, "args_tree", env)
            if un is None or len(un) < 3:
                continue
            sem, _tr, value = un[0], un[1], un[2]
            events.append((_BWAIT, id(sem), literal_int(value)))
        elif name == "cond":
            # pl.when phases: all branches execute on some grid step —
            # inline them in syntactic order (operands after the
            # predicate feed every branch's invars)
            for br in eqn.params.get("branches", ()):
                bj = br.jaxpr if isinstance(br, ClosedJaxpr) else br
                _collect_events(bj, events, notes,
                                _sub_env(bj.invars, eqn.invars[1:], env))
        elif name == "scan":
            events.append((_LOOP_BEGIN,))
            sub = eqn.params.get("jaxpr")
            sj = sub.jaxpr if isinstance(sub, ClosedJaxpr) else sub
            if isinstance(sj, Jaxpr):
                # invars = consts + carry + xs, positionally aligned
                # with the body's consts + carry + x-elements
                _collect_events(sj, events, notes,
                                _sub_env(sj.invars, eqn.invars, env))
            events.append((_LOOP_END,))
        elif name == "while":
            events.append((_LOOP_BEGIN,))
            cn = eqn.params.get("cond_nconsts", 0)
            bn = eqn.params.get("body_nconsts", 0)
            # eqn.invars = cond_consts + body_consts + carry; the cond
            # jaxpr sees cond_consts + carry, the body body_consts +
            # carry — slice the matching operand groups for each
            carry = list(eqn.invars[cn + bn:])
            for key, operands in (
                    ("cond_jaxpr", list(eqn.invars[:cn]) + carry),
                    ("body_jaxpr", list(eqn.invars[cn:cn + bn]) + carry)):
                sub = eqn.params.get(key)
                if sub is None:
                    continue
                sj = sub.jaxpr if isinstance(sub, ClosedJaxpr) else sub
                if isinstance(sj, Jaxpr):
                    _collect_events(sj, events, notes,
                                    _sub_env(sj.invars, operands, env))
            events.append((_LOOP_END,))
        else:
            sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
            if sub is not None:
                sj = sub.jaxpr if isinstance(sub, ClosedJaxpr) else sub
                if isinstance(sj, Jaxpr):
                    _collect_events(sj, events, notes,
                                    _sub_env(sj.invars, eqn.invars, env))


# ---------------------------------------------------------------------------
# discipline simulation


def _fmt_key(key: Tuple) -> str:
    _var, idx = key
    return f"sem@{_var % 10000}[{','.join(map(str, idx))}]"


def _simulate(kernel: str, events: List[Tuple],
              axis_names: Tuple[str, ...]) -> Tuple[List[Finding], bool]:
    """Run the discipline state machine over one kernel's events.
    Returns (findings, saw_remote_dma)."""
    findings: List[Finding] = []

    def err(msg: str, severity: str = ERROR) -> None:
        findings.append(Finding("dma", kernel, msg, severity))

    # pass 1: which semaphore cells ever back a REMOTE transfer?
    tracked: set = set()
    saw_remote = False
    for ev in events:
        if ev[0] == _START and ev[2]:
            saw_remote = True
            tracked.update(ev[1])

    # pass 2: ordering / pairing
    inflight: Dict[Tuple, int] = {}
    barrier_sems: set = set()
    signals_before: Dict[int, int] = {}
    barrier_passed: set = set()
    remote_started = False
    loop_stack: List[Dict[Tuple, int]] = []

    for ev in events:
        kind = ev[0]
        if kind == "barrier_def":
            barrier_sems.add(ev[1])
        elif kind == _BSIG:
            _k, sem, inc, axes = ev
            for ax in axes:
                if axis_names and ax not in axis_names:
                    err(f"barrier signal targets unknown mesh axis "
                        f"'{ax}' (mesh axes: {sorted(axis_names)})")
            if sem in barrier_sems:
                signals_before[sem] = (signals_before.get(sem, 0)
                                       + (inc if inc is not None else 0))
        elif kind == _BWAIT:
            _k, sem, value = ev
            if sem in barrier_sems:
                sent = signals_before.get(sem, 0)
                if value is not None and sent != value:
                    err(f"barrier wait value {value} != {sent} signals "
                        f"issued — the rendezvous can deadlock or pass "
                        f"early")
                barrier_passed.add(sem)
        elif kind == _START:
            _k, keys, remote, axes = ev
            if not remote:
                continue
            for ax in axes:
                if axis_names and ax not in axis_names:
                    err(f"remote DMA targets unknown mesh axis '{ax}' "
                        f"(mesh axes: {sorted(axis_names)})")
            if not remote_started:
                remote_started = True
                if not barrier_passed:
                    err("remote DMA started before any neighbor "
                        "barrier wait — destination buffers are not "
                        "known quiescent (unordered remote write)")
            if not keys:
                err("remote DMA start without identifiable "
                    "send/recv semaphores", WARNING)
            for key in keys:
                if any(i == "?" for i in key[1]):
                    err(f"remote DMA semaphore {_fmt_key(key)} has a "
                        f"dynamic index; discipline not statically "
                        f"checkable", WARNING)
                    continue
                if inflight.get(key, 0) > 0:
                    err(f"semaphore {_fmt_key(key)} re-armed while its "
                        f"previous DMA is still in flight")
                inflight[key] = inflight.get(key, 0) + 1
        elif kind == _WAIT:
            key = ev[1]
            if key not in tracked or any(i == "?" for i in key[1]):
                continue
            if inflight.get(key, 0) <= 0:
                err(f"dma_wait on {_fmt_key(key)} with no DMA in "
                    f"flight")
            else:
                inflight[key] -= 1
        elif kind == _LOOP_BEGIN:
            loop_stack.append(dict(inflight))
        elif kind == _LOOP_END:
            before = loop_stack.pop() if loop_stack else {}
            if {k: v for k, v in inflight.items() if v} != \
                    {k: v for k, v in before.items() if v}:
                err("remote DMA in-flight state changes across a loop "
                    "body — start/wait pairing cannot be proven "
                    "(possible cross-iteration semaphore reuse)")
                inflight = dict(before)

    for key, n in sorted(inflight.items()):
        if n > 0:
            err(f"remote DMA on {_fmt_key(key)} started but never "
                f"awaited ({n} outstanding at kernel end)")
    return findings, saw_remote


def check_pallas_kernels(target: PallasKernelTarget) -> List[Finding]:
    """Verify DMA/semaphore discipline of every kernel the target
    traces to."""
    try:
        spec = target.build()
    except Exception as e:  # noqa: BLE001
        return [Finding("dma", target.name,
                        f"target build failed: {type(e).__name__}: {e}")]
    try:
        closed = trace(spec.fn, *spec.args)
    except Exception as e:  # noqa: BLE001
        return [Finding("dma", target.name,
                        f"trace failed: {type(e).__name__}: {e}")]
    kernels = find_pallas_kernels(closed.jaxpr)
    if not kernels:
        return [Finding("dma", target.name,
                        "no pallas_call found in the traced program",
                        WARNING)]
    findings: List[Finding] = []
    any_remote = False
    for kname, kjaxpr in kernels:
        events: List[Tuple] = []
        notes: List[str] = []
        _collect_events(kjaxpr, events, notes)
        for n in sorted(set(notes)):
            findings.append(Finding("dma", f"{target.name}:{kname}", n,
                                    WARNING))
        fs, saw_remote = _simulate(f"{target.name}:{kname}", events,
                                   tuple(spec.axis_names))
        # namespace the kernel into the target for the report
        findings.extend(Finding("dma", f.target, f.message, f.severity)
                        for f in fs)
        any_remote = any_remote or saw_remote
    if spec.expect_remote_dma and not any_remote:
        findings.append(Finding(
            "dma", target.name,
            "expected remote DMA but none traced — the checker would "
            "be vacuous here (did the kernel's transport change?)",
            WARNING))
    return findings
