"""Checker 12: happens-before certification of in-kernel RDMA
semaphore schedules under k-fold replay.

The ``dma`` checker (checker 2) proves one LAUNCH of a Pallas kernel
pairs every remote-DMA start with its waits.  That is not enough to
fuse a kernel into a multi-step megastep segment: a fused segment
replays the kernel body k times inside ONE compiled program, so the
schedule must additionally be sound under concatenation — every
launch must hand the next launch a quiescent semaphore file.  This
checker extracts a **semaphore schedule graph** from each kernel's
jaxpr — nodes are ``make_async_remote_copy`` starts, ``dma_wait``s,
barrier signals/waits, and interior-compute reads, with the mesh axes
each semaphore edge crosses — and simulates the *k-times-replayed*
event order, proving three conditions:

* **(a) no in-flight aliasing across sub-steps** — every send/recv
  semaphore slot armed by replay ``i`` is drained before replay
  ``i+1`` re-arms it (a slot re-armed while its previous copy flies is
  the data race the distributed interpreter reports dynamically);
* **(b) deadlock freedom of the cross-shard rendezvous** — under SPMD
  symmetry every shard runs the same program, so a barrier wait for
  ``v`` with fewer than ``v`` signals issued program-before is a
  circular cross-shard wait (each shard blocks on signals its
  neighbors would only send after passing the same wait: a deadlock
  cycle), and signals left un-consumed at a sub-step boundary would
  let replay ``i+1``'s rendezvous pass before the neighbors arrive
  (stale-signal replay unsoundness);
* **(c) no unwaited-inbound reads** — a buffer that is the target of
  a remote copy is dirty until the copy's recv semaphore is waited;
  interior compute reading a dirty buffer is the race that makes
  replay unsound even when the semaphore file itself balances.

The proof is emitted as a per-kernel
:class:`ScheduleCertificate` ``{max_in_flight, replay_safe,
reasons[]}`` in the JSON report's metrics, and
``parallel/megastep.py`` CONSUMES it: a kernel whose certificate says
``replay_safe`` is fused into multi-step in-kernel segments (the
Jacobi RDMA-overlap path), while unsafe schedules decline with the
certificate's own reasons — converting the segment compiler's
name-matched policy declines into proofs.

Scope mirrors the ``dma`` checker: only REMOTE copies are tracked
(local double-buffer pipelines arm semaphores across grid steps by
design), ``cond`` phases inline in syntactic order, loop bodies must
leave the remote in-flight state invariant, and dynamic remote
semaphore indices defeat static certification (flagged, never
``replay_safe``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .dma import (_collect_events, _fmt_key, _BSIG, _BWAIT, _LOOP_BEGIN,
                  _LOOP_END, _START, _WAIT)
from .jaxprs import find_pallas_kernels, trace
from .report import ERROR, WARNING, Finding

#: replay depth certified by default: enough to expose cross-replay
#: aliasing (needs 2), boundary staleness (needs 2), and pairing
#: drift that only accumulates (caught by 3+), while staying cheap
DEFAULT_REPLAY = 4


@dataclasses.dataclass
class ScheduleCertificate:
    """The happens-before verdict for one kernel replayed ``replay``
    times: ``replay_safe`` iff conditions (a)/(b)/(c) all hold;
    ``reasons`` name every violated condition (empty when safe);
    ``max_in_flight`` is the peak number of outstanding remote copies
    (the semaphore-file pressure a fused segment sustains)."""

    kernel: str
    replay: int
    max_in_flight: int
    replay_safe: bool
    reasons: List[str] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return {"kernel": self.kernel, "replay": self.replay,
                "max_in_flight": self.max_in_flight,
                "replay_safe": self.replay_safe,
                "reasons": list(self.reasons)}


@dataclasses.dataclass
class ScheduleSpec:
    """A traceable entry point whose Pallas kernels get schedule
    certificates.  ``fn(*args)`` is traced abstractly (typically a
    ``shard_map``-ped wrapper so ``lax.axis_index`` resolves);
    ``replay`` is the certified fusion depth; ``expect_remote_dma``
    guards against vacuous passes; ``expect_max_in_flight`` pins the
    kernel's declared semaphore pressure (the op module's
    ``SCHEDULE_EXPECT`` hint) so kernel refactors that change the
    schedule shape fail the checker instead of silently re-certifying;
    ``fused_by_megastep`` marks targets whose certificate the segment
    compiler actually consumes — CI asserts those are ``replay_safe``.
    """

    fn: Callable
    args: Sequence[Any]
    axis_names: Tuple[str, ...] = ()
    replay: int = DEFAULT_REPLAY
    expect_remote_dma: bool = False
    expect_max_in_flight: Optional[int] = None
    fused_by_megastep: bool = False


@dataclasses.dataclass
class ScheduleTarget:
    name: str
    build: Callable[[], ScheduleSpec]

    checker = "schedule"


# ---------------------------------------------------------------------------
# replayed-schedule simulation


def _is_ref(v: Any) -> bool:
    aval = getattr(v, "aval", None)
    if aval is None:
        return False
    s = str(aval)
    return ("Ref" in type(aval).__name__ or s.startswith("Ref")
            or s.startswith("MemRef"))


def _certify_events(kernel: str, events: List[Tuple], replay: int
                    ) -> Tuple[ScheduleCertificate, List[str], bool]:
    """Simulate ``events`` concatenated ``replay`` times.  Returns
    ``(certificate, warning_reasons, saw_remote)`` — warning_reasons
    are the subset of the certificate's reasons reported at WARNING
    severity (static certification defeated, not a proven bug)."""
    reasons: List[str] = []
    warn_reasons: List[str] = []

    def fail(msg: str, warn: bool = False) -> None:
        if msg not in reasons:
            reasons.append(msg)
            if warn:
                warn_reasons.append(msg)

    # pass 1: which semaphore cells ever back a REMOTE transfer?
    tracked: set = set()
    saw_remote = False
    for ev in events:
        if ev[0] == _START and ev[2]:
            saw_remote = True
            tracked.update(ev[1])

    # pass 2: the replayed happens-before simulation
    armed: Dict[Tuple, List[int]] = {}     # sem key -> replay tags
    barrier_sems: set = set()
    value: Dict[int, int] = {}             # barrier sem -> pending signals
    inbound: Dict[Tuple, List[int]] = {}   # recv key -> dirty dst ids
    dirty: Dict[int, int] = {}             # dst id -> unwaited inbound
    in_flight = 0
    max_in_flight = 0
    loop_stack: List[Dict[Tuple, Tuple[int, ...]]] = []

    for r in range(replay):
        for ev in events:
            kind = ev[0]
            if kind == "barrier_def":
                barrier_sems.add(ev[1])
            elif kind == _BSIG:
                _k, sem, inc, _axes = ev
                if sem in barrier_sems:
                    value[sem] = value.get(sem, 0) + (inc or 0)
            elif kind == _BWAIT:
                _k, sem, v = ev
                if sem not in barrier_sems or v is None:
                    continue
                have = value.get(sem, 0)
                if have < v:
                    fail(f"barrier wait for {v} with only {have} "
                         f"signal(s) issued program-before — every "
                         f"shard blocks on signals its neighbors send "
                         f"only after the same wait: circular "
                         f"cross-shard wait (deadlock cycle)")
                    value[sem] = 0
                else:
                    value[sem] = have - v
            elif kind == _START:
                _k, keys, remote, _axes, dst_id, recv_key = ev
                if not remote:
                    continue
                for key in keys:
                    if any(i == "?" for i in key[1]):
                        fail(f"remote DMA semaphore {_fmt_key(key)} "
                             f"has a dynamic index — the schedule is "
                             f"not statically certifiable", warn=True)
                        continue
                    tags = armed.setdefault(key, [])
                    if tags:
                        r0 = tags[0]
                        if r0 != r:
                            fail(f"semaphore slot {_fmt_key(key)} "
                                 f"re-armed in replay {r} while its "
                                 f"replay-{r0} copy is still in "
                                 f"flight — in-flight aliasing "
                                 f"across sub-steps")
                        else:
                            fail(f"semaphore slot {_fmt_key(key)} "
                                 f"re-armed while its previous copy "
                                 f"is still in flight — in-flight "
                                 f"aliasing")
                    tags.append(r)
                in_flight += 1
                max_in_flight = max(max_in_flight, in_flight)
                if dst_id is not None and recv_key is not None:
                    inbound.setdefault(recv_key, []).append(dst_id)
                    dirty[dst_id] = dirty.get(dst_id, 0) + 1
            elif kind == _WAIT:
                key = ev[1]
                if inbound.get(key):
                    dst = inbound[key].pop(0)
                    dirty[dst] -= 1
                    in_flight -= 1
                if key not in tracked or any(i == "?" for i in key[1]):
                    continue
                tags = armed.get(key)
                if tags:
                    tags.pop(0)
                else:
                    fail(f"wait on {_fmt_key(key)} with no copy in "
                         f"flight — start/wait pairing cannot be "
                         f"established under replay")
            elif kind == "read":
                rid = ev[1]
                if dirty.get(rid, 0) > 0:
                    fail(f"interior compute reads buffer ref@"
                         f"{rid % 10000} while an inbound remote copy "
                         f"targeting it is unwaited — the race that "
                         f"makes replay unsound")
            elif kind == _LOOP_BEGIN:
                loop_stack.append({k: tuple(v) for k, v in armed.items()
                                   if v})
            elif kind == _LOOP_END:
                before = loop_stack.pop() if loop_stack else {}
                now = {k: tuple(v) for k, v in armed.items() if v}
                if now != before:
                    fail("remote in-flight state changes across a "
                         "loop body — the replayed schedule cannot "
                         "be certified (possible cross-iteration "
                         "semaphore reuse)")
                    armed = {k: list(v) for k, v in before.items()}
        # sub-step boundary: replay r hands the semaphore file to r+1
        stale = {s: v for s, v in value.items() if v}
        for _sem, v in sorted(stale.items()):
            fail(f"barrier semaphore holds {v} stale signal(s) at a "
                 f"sub-step boundary — the next replay's rendezvous "
                 f"can pass before its neighbors arrive (stale-signal "
                 f"replay unsoundness)")

    for key, tags in sorted(armed.items(), key=lambda kv: kv[0][0]):
        if tags:
            fail(f"remote copy on {_fmt_key(key)} started but never "
                 f"awaited ({len(tags)} outstanding at kernel end)")

    cert = ScheduleCertificate(kernel=kernel, replay=replay,
                               max_in_flight=max_in_flight,
                               replay_safe=not reasons, reasons=reasons)
    return cert, warn_reasons, saw_remote


# ---------------------------------------------------------------------------
# event extraction: the dma checker's walk, with dst-buffer identity
# on remote starts and compute-read events for condition (c)

def _schedule_events(kjaxpr, notes: List[str]) -> List[Tuple]:
    """Collect the dma checker's event stream, enriched: every remote
    ``dma_start`` carries ``(dst_buffer_id, recv_sem_key)`` and every
    non-DMA, non-control equation touching a (non-semaphore) Ref emits
    a ``("read", ref_id)`` node — the interior-compute reads of
    condition (c).  Vars canonicalize through the same ``_sub_env``
    substitution as the dma walk, so identities line up across
    ``cond`` branches / loop bodies / nested calls."""
    from .jaxprs import ClosedJaxpr, Jaxpr, Var, is_semaphore_ref
    from .dma import _sem_key, _sub_env, _unflatten

    events: List[Tuple] = []

    def walk(jaxpr, env):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name == "dma_start":
                un = _unflatten(eqn, "tree", env)
                if un is None or len(un) != 9:
                    notes.append("unrecognized dma_start operand "
                                 "layout; DMA not analyzed")
                    continue
                (_src, _st, dst, _dt, ssem, sst, rsem, rst,
                 device_id) = un
                remote = isinstance(device_id, dict) and bool(device_id)
                keys = []
                for sem, tr in ((ssem, sst), (rsem, rst)):
                    if sem is not None and is_semaphore_ref(sem):
                        keys.append(_sem_key(sem, tr))
                axes = (tuple(str(k) for k in device_id.keys())
                        if isinstance(device_id, dict) else ())
                recv_key = (_sem_key(rsem, rst)
                            if rsem is not None and is_semaphore_ref(rsem)
                            else None)
                dst_id = id(dst) if dst is not None else None
                events.append((_START, tuple(keys), remote, axes,
                               dst_id, recv_key))
            elif name == "dma_wait":
                un = _unflatten(eqn, "tree", env)
                if un is None or len(un) != 9:
                    notes.append("unrecognized dma_wait operand "
                                 "layout; wait not analyzed")
                    continue
                # dma_wait waits on the dst_sem slot (wait_send swaps
                # src/dst so the same slot holds the send semaphore)
                _src, _st, _dst, _dt, _ss, _sst, rsem, rst, _dev = un
                if rsem is not None and is_semaphore_ref(rsem):
                    events.append((_WAIT, _sem_key(rsem, rst)))
            elif name in ("get_barrier_semaphore", "semaphore_signal",
                          "semaphore_wait"):
                # barrier choreography: the dma checker's extraction,
                # verbatim, on this one equation
                _collect_events(_OneEqn(eqn), events, notes, env)
            elif name == "cond":
                for br in eqn.params.get("branches", ()):
                    bj = br.jaxpr if isinstance(br, ClosedJaxpr) else br
                    walk(bj, _sub_env(bj.invars, eqn.invars[1:], env))
            elif name == "scan":
                events.append((_LOOP_BEGIN,))
                sub = eqn.params.get("jaxpr")
                sj = sub.jaxpr if isinstance(sub, ClosedJaxpr) else sub
                if isinstance(sj, Jaxpr):
                    walk(sj, _sub_env(sj.invars, eqn.invars, env))
                events.append((_LOOP_END,))
            elif name == "while":
                events.append((_LOOP_BEGIN,))
                cn = eqn.params.get("cond_nconsts", 0)
                bn = eqn.params.get("body_nconsts", 0)
                carry = list(eqn.invars[cn + bn:])
                for key, operands in (
                        ("cond_jaxpr", list(eqn.invars[:cn]) + carry),
                        ("body_jaxpr",
                         list(eqn.invars[cn:cn + bn]) + carry)):
                    sub = eqn.params.get(key)
                    if sub is None:
                        continue
                    sj = sub.jaxpr if isinstance(sub, ClosedJaxpr) else sub
                    if isinstance(sj, Jaxpr):
                        walk(sj, _sub_env(sj.invars, operands, env))
                events.append((_LOOP_END,))
            else:
                sub = eqn.params.get("jaxpr") or \
                    eqn.params.get("call_jaxpr")
                if sub is not None:
                    sj = sub.jaxpr if isinstance(sub, ClosedJaxpr) else sub
                    if isinstance(sj, Jaxpr):
                        walk(sj, _sub_env(sj.invars, eqn.invars, env))
                    continue
                seen = set()
                for v in eqn.invars:
                    if not isinstance(v, Var):
                        continue
                    cv = env.get(v, v)
                    if _is_ref(cv) and not is_semaphore_ref(cv):
                        rid = id(cv)
                        if rid not in seen:
                            seen.add(rid)
                            events.append(("read", rid))

    walk(kjaxpr, {})
    return events


class _OneEqn:
    """A single-equation pseudo-jaxpr so one equation can be pushed
    through the dma checker's jaxpr-shaped walk."""

    def __init__(self, eqn):
        self.eqns = [eqn]


# ---------------------------------------------------------------------------
# checker entry points


def certify_kernel(kname: str, kjaxpr, replay: int = DEFAULT_REPLAY
                   ) -> Tuple[ScheduleCertificate, List[str], bool,
                              List[str]]:
    """Certificate for one kernel jaxpr.  Returns ``(certificate,
    warning_reasons, saw_remote, notes)``."""
    notes: List[str] = []
    events = _schedule_events(kjaxpr, notes)
    cert, warn_reasons, saw_remote = _certify_events(kname, events,
                                                     replay)
    return cert, warn_reasons, saw_remote, sorted(set(notes))


def certify_traceable(fn: Callable, args: Sequence[Any],
                      axis_names: Tuple[str, ...] = (),
                      replay: int = DEFAULT_REPLAY
                      ) -> ScheduleCertificate:
    """Runtime API for the segment compiler: trace ``fn(*args)``,
    certify every Pallas kernel inside, and merge into one
    certificate (safe iff every kernel is safe).  Raises nothing —
    an untraceable program returns an unsafe certificate whose
    reasons say why, so callers decline instead of crashing."""
    del axis_names  # identity comes from the traced device_id dicts
    try:
        closed = trace(fn, *args)
    except Exception as e:  # noqa: BLE001
        return ScheduleCertificate(
            kernel="<untraceable>", replay=replay, max_in_flight=0,
            replay_safe=False,
            reasons=[f"schedule trace failed: {type(e).__name__}: {e}"])
    kernels = find_pallas_kernels(closed.jaxpr)
    if not kernels:
        return ScheduleCertificate(
            kernel="<none>", replay=replay, max_in_flight=0,
            replay_safe=False,
            reasons=["no pallas_call traced — nothing to certify"])
    certs = []
    for kname, kjaxpr in kernels:
        cert, _w, _remote, _notes = certify_kernel(kname, kjaxpr, replay)
        certs.append(cert)
    return ScheduleCertificate(
        kernel=",".join(c.kernel for c in certs), replay=replay,
        max_in_flight=max(c.max_in_flight for c in certs),
        replay_safe=all(c.replay_safe for c in certs),
        reasons=[f"{c.kernel}: {r}" for c in certs for r in c.reasons])


def check_schedule(target: ScheduleTarget
                   ) -> Tuple[List[Finding], dict]:
    """Certify every kernel the target traces to; findings are the
    violated replay-soundness conditions, metrics are the
    certificates (archived to the JSON report for megastep/CI)."""
    try:
        spec = target.build()
    except Exception as e:  # noqa: BLE001
        return ([Finding("schedule", target.name,
                         f"target build failed: {type(e).__name__}: "
                         f"{e}")], {})
    try:
        closed = trace(spec.fn, *spec.args)
    except Exception as e:  # noqa: BLE001
        return ([Finding("schedule", target.name,
                         f"trace failed: {type(e).__name__}: {e}")], {})
    kernels = find_pallas_kernels(closed.jaxpr)
    if not kernels:
        return ([Finding("schedule", target.name,
                         "no pallas_call found in the traced program",
                         WARNING)], {})
    findings: List[Finding] = []
    kernel_metrics: Dict[str, dict] = {}
    any_remote = False
    all_safe = True
    peak = 0
    seen_names: Dict[str, int] = {}
    for kname, kjaxpr in kernels:
        # a fused segment traces the SAME kernel once per launch —
        # number the repeats so each launch keeps its certificate
        n = seen_names.get(kname, 0)
        seen_names[kname] = n + 1
        if n:
            kname = f"{kname}#{n}"
        cert, warn_reasons, saw_remote, notes = certify_kernel(
            kname, kjaxpr, int(spec.replay))
        for n in notes:
            findings.append(Finding("schedule",
                                    f"{target.name}:{kname}", n,
                                    WARNING))
        for reason in cert.reasons:
            sev = WARNING if reason in warn_reasons else ERROR
            findings.append(Finding("schedule",
                                    f"{target.name}:{kname}", reason,
                                    sev))
        kernel_metrics[kname] = cert.to_dict()
        any_remote = any_remote or saw_remote
        all_safe = all_safe and cert.replay_safe
        peak = max(peak, cert.max_in_flight)
    if spec.expect_remote_dma and not any_remote:
        findings.append(Finding(
            "schedule", target.name,
            "expected remote DMA but none traced — the certificate "
            "would be vacuous here (did the kernel's transport "
            "change?)", WARNING))
    if spec.expect_max_in_flight is not None and \
            peak != int(spec.expect_max_in_flight):
        findings.append(Finding(
            "schedule", target.name,
            f"schedule hint drift: traced max_in_flight {peak} != "
            f"declared {int(spec.expect_max_in_flight)} (the op "
            f"module's SCHEDULE_EXPECT hint) — re-review the kernel's "
            f"semaphore schedule and update the hint"))
    metrics = {"replay": int(spec.replay), "replay_safe": all_safe,
               "max_in_flight": peak,
               "fused_by_megastep": bool(spec.fused_by_megastep),
               "kernels": kernel_metrics}
    return findings, metrics
