// Native quadratic-assignment solvers for topology-aware placement.
//
// TPU-native re-implementation of the reference's qap namespace
// (reference: include/stencil/qap.hpp:51-180): an exact brute-force
// search over permutations with a wall-clock timeout, and a greedy
// pairwise-swap hill climb with incremental cost updates. Exposed as a
// C ABI consumed from Python via ctypes (stencil_tpu/qap.py).
//
// Cost model: cost(f) = sum_{a,b} w[a][b] * d[f[a]][f[b]], with the
// convention that 0 * inf == 0 (cost_product, qap.hpp:16-21).

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <vector>

namespace {

inline double cost_product(double we, double de) {
  if (0 == we || 0 == de) return 0;
  return we * de;
}

inline double cost(int64_t n, const double *w, const double *d,
                   const std::vector<int64_t> &f) {
  double ret = 0;
  for (int64_t a = 0; a < n; ++a)
    for (int64_t b = 0; b < n; ++b)
      ret += cost_product(w[a * n + b], d[f[a] * n + f[b]]);
  return ret;
}

}  // namespace

extern "C" {

// Exact search: all permutations, best kept; stops after timeout_s
// seconds of wall clock (reference qap::solve uses a fixed 10 s cap).
// Returns the best cost found; writes the permutation into out_f.
double qap_solve_exact(int64_t n, const double *w, const double *d,
                       int64_t *out_f, double timeout_s) {
  using Clock = std::chrono::steady_clock;
  const auto stop = Clock::now() + std::chrono::duration_cast<Clock::duration>(
                                       std::chrono::duration<double>(timeout_s));
  std::vector<int64_t> f(n);
  for (int64_t i = 0; i < n; ++i) f[i] = i;
  std::vector<int64_t> best = f;
  double best_cost = cost(n, w, d, f);
  uint64_t iter = 0;
  while (std::next_permutation(f.begin(), f.end())) {
    if ((++iter & 0x3FF) == 0 && Clock::now() > stop) break;
    const double c = cost(n, w, d, f);
    if (c < best_cost) {
      best_cost = c;
      best = f;
    }
  }
  for (int64_t i = 0; i < n; ++i) out_f[i] = best[i];
  return best_cost;
}

// Greedy pairwise-swap hill climb with incremental cost update
// (reference qap::solve_catch, qap.hpp:87-180).
double qap_solve_catch(int64_t n, const double *w, const double *d,
                       int64_t *out_f) {
  std::vector<int64_t> bestF(n);
  for (int64_t i = 0; i < n; ++i) bestF[i] = i;
  double bestCost = cost(n, w, d, bestF);

  bool improved;
  do {
    improved = false;
    std::vector<int64_t> imprF = bestF;
    double imprCost = bestCost;
    for (int64_t i = 0; i < n; ++i) {
      for (int64_t j = i + 1; j < n; ++j) {
        std::vector<int64_t> f = bestF;
        double c = bestCost;
        for (int64_t k = 0; k < n; ++k) {
          c -= cost_product(w[i * n + k], d[f[i] * n + f[k]]);
          c -= cost_product(w[j * n + k], d[f[j] * n + f[k]]);
          if (k != i && k != j) {
            c -= cost_product(w[k * n + i], d[f[k] * n + f[i]]);
            c -= cost_product(w[k * n + j], d[f[k] * n + f[j]]);
          }
        }
        std::swap(f[i], f[j]);
        for (int64_t k = 0; k < n; ++k) {
          c += cost_product(w[i * n + k], d[f[i] * n + f[k]]);
          c += cost_product(w[j * n + k], d[f[j] * n + f[k]]);
          if (k != i && k != j) {
            c += cost_product(w[k * n + i], d[f[k] * n + f[i]]);
            c += cost_product(w[k * n + j], d[f[k] * n + f[j]]);
          }
        }
        // the incremental update is invalid when inf terms are involved
        // (inf - inf = NaN); fall back to a full recompute
        if (!std::isfinite(c)) c = cost(n, w, d, f);
        if (c < imprCost) {
          imprF = f;
          imprCost = c;
          improved = true;
        }
      }
    }
    if (improved) {
      bestF = imprF;
      bestCost = imprCost;
    }
  } while (improved);

  for (int64_t i = 0; i < n; ++i) out_f[i] = bestF[i];
  return bestCost;
}

double qap_cost(int64_t n, const double *w, const double *d,
                const int64_t *f) {
  std::vector<int64_t> fv(f, f + n);
  return cost(n, w, d, fv);
}

}  // extern "C"
